"""Environment-capability probe (not a pytest module).

Run as ``python mp_probe.py <port> <pid> <nprocs>``.  Joins a minimal
``jax.distributed`` job over localhost and attempts ONE cross-process
collective (``sync_global_devices``) — the exact operation every real
multi-process test needs first.  Prints ``MP_PROBE_OK <pid>`` on
success.

Some jaxlib builds cannot run collectives across processes on the CPU
backend at all (``XlaRuntimeError: Multiprocess computations aren't
implemented on the CPU backend``) — an environment limit, not a repo
bug.  ``tests/test_multiprocess.py`` runs this probe once per session
and skips the multi-process suite with an explicit reason when it
fails, instead of failing tier-1 on an impossible prerequisite.
"""

import os
import sys

port, pid, nprocs = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=nprocs, process_id=pid)
from jax.experimental import multihost_utils  # noqa: E402

multihost_utils.sync_global_devices("mvtpu_mp_probe")
print("MP_PROBE_OK", pid, flush=True)
