"""Tail-plane fleet holder for the QoS/hedge/deadline tests (not a
pytest module; docs/serving.md "tail").

Run as ``python tail_worker.py <machine_file> <rank> [extra flags...]``:
joins a 2-rank native epoll fleet, registers ArrayTable 0 (64 ones),
MatrixTable 1 (32x4, row ``i`` filled with ``i + 1`` — distinct values
so a hedged read's answer is checkable), and KVTable 2, rendezvouses,
prints ``SERVE_READY`` — then serves stdin COMMANDS until ``done``:

- ``fault <kind> <n>``       arm a deterministic fault budget
- ``fault_rate <kind> <r>``  arm a probabilistic fault
- ``clear``                  clear every fault
- ``add <value>``            one acked ArrayTable add of ``value`` ones
- ``mon <name>``             print ``MON <name>=<count>``

Every command is acknowledged with an ``OK <cmd>`` line so the pytest
side can sequence without sleeps.  On ``done`` it prints the fan-in
counters, rendezvouses, and exits with ``SERVE_WORKER_OK <rank>``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from multiverso_tpu import native as nat  # noqa: E402

SIZE = 64
MROWS = 32
MCOLS = 4


def main() -> int:
    mf, rank = sys.argv[1], int(sys.argv[2])
    extra = sys.argv[3:]
    rt = nat.NativeRuntime(args=[f"-machine_file={mf}", f"-rank={rank}",
                                 "-log_level=error",
                                 "-rpc_timeout_ms=30000",
                                 "-barrier_timeout_ms=60000", *extra])
    assert rt.net_engine() == "epoll", rt.net_engine()
    h = rt.new_array_table(SIZE)
    hm = rt.new_matrix_table(MROWS, MCOLS)
    hk = rt.new_kv_table()
    assert (h, hm, hk) == (0, 1, 2), (h, hm, hk)
    rt.barrier()
    if rank == 0:
        rt.set_fault_seed(1234)
        rt.array_add(h, np.ones(SIZE, np.float32))
        rows = np.repeat(np.arange(1, MROWS + 1, dtype=np.float32),
                         MCOLS).reshape(MROWS, MCOLS)
        rt.matrix_add_rows(hm, list(range(MROWS)), rows)
    rt.barrier()
    print("SERVE_READY", flush=True)
    for line in sys.stdin:
        parts = line.split()
        if not parts or parts[0] == "done":
            break
        cmd = parts[0]
        if cmd == "fault":
            rt.set_fault_n(parts[1], int(parts[2]))
        elif cmd == "fault_rate":
            rt.set_fault(parts[1], float(parts[2]))
        elif cmd == "clear":
            rt.clear_faults()
        elif cmd == "add":
            rt.array_add(h, float(parts[1]) * np.ones(SIZE, np.float32))
        elif cmd == "mon":
            print(f"MON {parts[1]}={rt.query_monitor(parts[1])}",
                  flush=True)
        print(f"OK {cmd}", flush=True)
    st = rt.fanin_stats()
    print(f"FANIN accepted={st['accepted_total']} "
          f"active={st['active_clients']} shed={st['client_shed']}",
          flush=True)
    rt.barrier()
    rt.shutdown()
    print(f"SERVE_WORKER_OK {rank}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
