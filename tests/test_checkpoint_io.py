"""Checkpoint/resume + Stream IO tests (reference Test/main.cpp checkpoint
scenario + io/ streams; SURVEY.md §4, §5)."""

import numpy as np
import pytest


def test_local_stream_roundtrip(tmp_path, mv):
    from multiverso_tpu.io import LocalStream, StreamFactory

    p = str(tmp_path / "sub" / "x.bin")  # parent dir auto-created
    with StreamFactory.open(p, "wb") as s:
        s.write(b"hello multiverso")
    with StreamFactory.open("file://" + p, "rb") as s:
        assert s.read() == b"hello multiverso"


def test_stream_undriven_scheme_raises(mv):
    """Unregistered schemes fall back to fsspec; a scheme with no
    installed driver (s3 needs s3fs) raises the integration contract."""
    from multiverso_tpu.io import StreamFactory

    with pytest.raises(NotImplementedError, match="fsspec"):
        StreamFactory.open("s3://bucket/key")


def test_hdfs_without_hadoop_client_raises(mv):
    from multiverso_tpu.io import StreamFactory

    with pytest.raises(NotImplementedError, match="hadoop"):
        StreamFactory.open("hdfs://nn/path", "rb")


def test_memory_scheme_roundtrip(mv):
    """Remote-scheme coverage without a network: fsspec's memory FS."""
    from multiverso_tpu.io import StreamFactory

    with StreamFactory.open("memory://ckpt/x.bin", "wb") as s:
        s.write(b"remote bytes")
    with StreamFactory.open("memory://ckpt/x.bin", "rb") as s:
        assert s.read() == b"remote bytes"


def test_local_stream_atomic_write(tmp_path, mv):
    import os

    from multiverso_tpu.io import LocalStream

    p = str(tmp_path / "atomic.bin")
    s = LocalStream(p, "wb", atomic=True)
    s.write(b"half")
    assert not os.path.exists(p)          # nothing at final path mid-write
    s.close()
    with open(p, "rb") as f:
        assert f.read() == b"half"
    assert not [x for x in os.listdir(tmp_path) if ".tmp." in x]


def test_checkpoint_over_memory_scheme(mv):
    """Checkpoint save/restore through a non-local stream backend."""
    import numpy as np

    from multiverso_tpu import checkpoint

    mv.init()
    t = mv.ArrayTable(8, name="memck")
    t.add(np.arange(8, dtype=np.float32))
    checkpoint.save("memory://ck/snap.mv", extra={"step": 3})
    t.add(np.ones(8, np.float32))
    extra = checkpoint.restore("memory://ck/snap.mv")
    assert extra == {"step": 3}
    np.testing.assert_allclose(t.get(), np.arange(8, dtype=np.float32))


def test_checkpoint_roundtrip_all_table_kinds(tmp_path, mv):
    mv.init(updater_type="adagrad")
    a = mv.ArrayTable(32, name="a")
    m = mv.MatrixTable(16, 4, name="m")
    s = mv.SparseMatrixTable(16, 4, name="s")
    k = mv.KVTable(value_shape=(2,), name="k")

    a.add(np.ones(32, np.float32))
    m.add_rows([1, 5], np.ones((2, 4), np.float32))
    s.add_rows([2, 3], np.full((2, 4), 2.0, np.float32))
    k.add({"x": [1.0, 2.0]})
    want_a, want_m, want_s = a.get(), m.get(), s.get()
    want_k = k.get(["x"])["x"]

    path = str(tmp_path / "ck.bin")
    mv.checkpoint.save(path, extra={"step": 7})

    # trash the state, then restore
    a.add(np.ones(32, np.float32))
    m.add(np.ones((16, 4), np.float32))
    extra = mv.checkpoint.restore(path)
    assert extra == {"step": 7}
    np.testing.assert_allclose(a.get(), want_a)
    np.testing.assert_allclose(m.get(), want_m)
    np.testing.assert_allclose(s.get(), want_s)
    np.testing.assert_allclose(k.get(["x"])["x"], want_k)


def test_checkpoint_restores_updater_state(tmp_path, mv):
    """AdaGrad accumulator must survive the round trip — resumed training
    continues the same trajectory (reference Store/Load dumps state too)."""
    mv.init(updater_type="adagrad")
    t = mv.ArrayTable(8, name="t")
    opt = mv.AddOption(learning_rate=0.1)
    t.add(np.ones(8, np.float32), option=opt)
    path = str(tmp_path / "ck.bin")
    mv.checkpoint.save(path)

    t.add(np.ones(8, np.float32), option=opt)
    after_two = t.get().copy()

    mv.checkpoint.restore(path)
    t.add(np.ones(8, np.float32), option=opt)
    np.testing.assert_allclose(t.get(), after_two, rtol=1e-6)


def test_checkpoint_strict_mismatch(tmp_path, mv):
    mv.init()
    mv.ArrayTable(8, name="t")
    path = str(tmp_path / "ck.bin")
    mv.checkpoint.save(path)
    mv.ArrayTable(8, name="extra")
    with pytest.raises(ValueError, match="mismatch"):
        mv.checkpoint.restore(path)
    # non-strict loads the intersection
    mv.checkpoint.restore(path, strict=False)


def test_checkpoint_bad_magic(tmp_path, mv):
    mv.init()
    path = str(tmp_path / "junk.bin")
    with open(path, "wb") as f:
        f.write(b"not a checkpoint")
    with pytest.raises(ValueError, match="not a multiverso_tpu checkpoint"):
        mv.checkpoint.restore(path)


def test_checkpoint_does_not_flush_pending_bsp(tmp_path, mv):
    """Saving mid-clock must not apply sync-mode buffered adds."""
    mv.init(sync=True)
    t = mv.ArrayTable(4, name="t", updater_type="default")
    t.add(np.ones(4, np.float32))
    path = str(tmp_path / "ck.bin")
    mv.checkpoint.save(path)
    np.testing.assert_allclose(t.get(), 0.0)   # still buffered
    mv.barrier()
    np.testing.assert_allclose(t.get(), 1.0)


def test_duplicate_table_name_rejected(mv):
    mv.init()
    mv.ArrayTable(4, name="dup")
    with pytest.raises(ValueError, match="duplicate table name"):
        mv.MatrixTable(2, 2, name="dup")
    # failed constructor must not leave a half-built table behind
    mv.barrier()


def test_restore_discards_pending_bsp_adds(tmp_path, mv):
    """Deltas buffered before a restore belong to the abandoned timeline."""
    mv.init(sync=True)
    t = mv.ArrayTable(4, name="t", updater_type="default")
    path = str(tmp_path / "ck.bin")
    mv.checkpoint.save(path)
    t.add(np.ones(4, np.float32))       # buffered, then abandoned
    mv.checkpoint.restore(path)
    mv.barrier()
    np.testing.assert_allclose(t.get(), 0.0)


def test_atomic_write_aborts_on_exception(tmp_path, mv):
    """A body that raises must not replace a previous good file."""
    from multiverso_tpu.io import StreamFactory

    p = str(tmp_path / "good.bin")
    with StreamFactory.open(p, "wb") as s:
        s.write(b"good data")
    with pytest.raises(OSError, match="disk full"):
        with StreamFactory.open(p, "wb", atomic=True) as s:
            s.write(b"PART")
            raise OSError("disk full")
    with open(p, "rb") as f:
        assert f.read() == b"good data"
    import os
    assert not [x for x in os.listdir(tmp_path) if ".tmp." in x]


def test_fsspec_missing_file_raises_file_not_found(mv):
    """Path errors surface as themselves, not as driver complaints."""
    from multiverso_tpu.io import StreamFactory

    with pytest.raises(FileNotFoundError):
        StreamFactory.open("memory://no/such/file.bin", "rb")


def test_memory_scheme_atomic_roundtrip(mv):
    from multiverso_tpu.io import StreamFactory

    with StreamFactory.open("memory://at/x.bin", "wb", atomic=True) as s:
        s.write(b"atomic remote")
    with StreamFactory.open("memory://at/x.bin", "rb") as s:
        assert s.read() == b"atomic remote"


def test_custom_scheme_old_contract_still_works(tmp_path, mv):
    """Schemes registered with the documented (path, mode) ctor must keep
    working even when the opener requests atomic."""
    from multiverso_tpu.io import LocalStream, StreamFactory

    class TwoArg(LocalStream):
        def __init__(self, path, mode="rb"):
            super().__init__(str(tmp_path / path), mode)

    StreamFactory.register("twoarg", TwoArg)
    try:
        with StreamFactory.open("twoarg://y.bin", "wb", atomic=True) as s:
            s.write(b"ok")
        with StreamFactory.open("twoarg://y.bin", "rb") as s:
            assert s.read() == b"ok"
    finally:
        StreamFactory._schemes.pop("twoarg", None)


def test_restore_pytree_validates_shapes(mv, tmp_path):
    """A checkpoint from one config must refuse to load into another,
    naming the offending leaf — not corrupt silently."""
    import jax
    import jax.numpy as jnp
    import pytest as _pytest

    from multiverso_tpu import checkpoint

    mv.init()
    path = str(tmp_path / "tree.ckpt")
    tree = {"w": jnp.ones((4, 4)), "step": 3, "run": "exp1"}
    checkpoint.save_pytree(path, tree)

    # non-array leaves round-trip with their own types
    back = checkpoint.restore_pytree(path)
    assert back["step"] == 3 and back["run"].startswith("exp")

    like_bad = {"w": jnp.ones((8, 8)), "step": 0, "run": ""}
    with _pytest.raises(ValueError, match="expects"):
        checkpoint.restore_pytree(path, like=like_bad)

    like_wrong_tree = {"w": jnp.ones((4, 4)), "extra_key": jnp.ones(2),
                      "step": 0, "run": ""}
    with _pytest.raises(ValueError, match="structure"):
        checkpoint.restore_pytree(path, like=like_wrong_tree)


def test_save_pytree_async_roundtrip(tmp_path, mv):
    """Async pytree save: D2H at call point, write off-thread; after
    result() the file restores exactly, and mutating the live tree after
    the call does not corrupt the snapshot (host copy taken eagerly)."""
    import jax.numpy as jnp

    from multiverso_tpu import checkpoint

    mv.init()
    tree = {"w": jnp.arange(16, dtype=jnp.float32),
            "step": 7, "name": "flagship"}
    uri = str(tmp_path / "async_ck.bin")
    handle = checkpoint.save_pytree_async(uri, tree)
    tree["w"] = tree["w"] + 100.0  # post-call mutation must not leak in
    handle.result(timeout=60)
    assert handle.done()
    back = checkpoint.restore_pytree(uri)
    np.testing.assert_allclose(np.asarray(back["w"]), np.arange(16))
    assert back["step"] == 7 and back["name"] == "flagship"


def test_save_pytree_async_error_surfaces_in_result(tmp_path, mv):
    """An IO failure on the writer thread re-raises at result(), not
    silently (the handle is the only place a caller can learn of it).
    The target's 'parent dir' is a regular file, so the stream's
    makedirs genuinely fails (a bare nonexistent dir would be created)."""
    import jax.numpy as jnp
    import pytest

    from multiverso_tpu import checkpoint

    mv.init()
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("in the way")
    handle = checkpoint.save_pytree_async(
        str(blocker / "ck.bin"), {"w": jnp.zeros(4)})
    with pytest.raises(Exception):
        handle.result(timeout=60)
