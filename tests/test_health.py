"""Tier-1 gate for the closed-loop health plane
(docs/observability.md "health plane"): the pure error-budget math in
multiverso_tpu/slo.py against hand-computed values, the alert state
machine's lifecycle edges (hysteresis, no-data discipline, burn-rate
multiwindow, critical profiler boost), the fleet merge behind
``mvtop --alerts`` / ``mvdoctor``, the arm()/disarm() flush wiring, the
Prometheus label-escaping round trip, the ``-metrics_history`` ring
cap, the native stall watchdog via the C API, and the meta-contract
that every OpsQuery kind has an mvtop view and a docs section.
"""

import json
import os
import shutil
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")


@pytest.fixture()
def registry():
    from multiverso_tpu import health, metrics

    health.disarm()
    metrics.reset()
    yield metrics
    health.disarm()
    metrics.reset()


# ---------------------------------------------------------------- slo math

def test_budget_and_validation():
    from multiverso_tpu import slo

    assert slo.budget(0.999) == pytest.approx(0.001)
    assert slo.budget(0.99) == pytest.approx(0.01)
    for bad in (0.0, 1.0, 1.5, -0.1):
        with pytest.raises(ValueError):
            slo.budget(bad)


def test_window_delta_hand_computed():
    from multiverso_tpu import slo

    pts = [(0.0, 5.0), (5.0, 9.0), (20.0, 10.0)]
    assert slo.window_delta(pts, 60.0) == pytest.approx(5.0)
    # Window ending at the last point only holds one sample: no delta.
    assert slo.window_delta(pts, 10.0) is None
    assert slo.window_delta([], 60.0) is None
    assert slo.window_delta([(0.0, 1.0)], 60.0) is None
    # A counter reset reads as zero events, never negative.
    assert slo.window_delta([(0.0, 10.0), (5.0, 2.0)], 60.0) == 0.0


def test_window_rate_hand_computed():
    from multiverso_tpu import slo

    assert slo.window_rate([(0.0, 0.0), (10.0, 30.0)],
                           60.0) == pytest.approx(3.0)
    assert slo.window_rate([(3.0, 1.0)], 60.0) is None
    # Zero elapsed time cannot produce a rate.
    assert slo.window_rate([(5.0, 1.0), (5.0, 9.0)], 60.0) is None


def test_error_fraction_and_burn_rate_hand_computed():
    from multiverso_tpu import slo

    bad = [(0.0, 0.0), (10.0, 10.0)]
    total = [(0.0, 0.0), (10.0, 1000.0)]
    assert slo.error_fraction(bad, total, 60.0) == pytest.approx(0.01)
    # 10 bad of 1000 against a 0.999 objective = 10x the error budget.
    assert slo.burn_rate(bad, total, 0.999, 60.0) == pytest.approx(10.0)
    # Zero traffic is "no data", not "perfect availability".
    flat = [(0.0, 7.0), (10.0, 7.0)]
    assert slo.error_fraction(bad, flat, 60.0) is None
    assert slo.burn_rate(bad, flat, 0.999, 60.0) is None
    # More bad than total clamps to fraction 1.0, not beyond.
    worse = [(0.0, 0.0), (10.0, 5000.0)]
    assert slo.error_fraction(worse, total, 60.0) == pytest.approx(1.0)


def test_multiwindow_burn_requires_both_windows():
    from multiverso_tpu import slo

    # The long window burned hot historically, but the short window has
    # no fresh points: significant but not still-happening -> no fire.
    bad = [(0.0, 0.0), (10.0, 10.0)]
    total = [(0.0, 0.0), (10.0, 1000.0)]
    long_b, short_b, firing = slo.multiwindow_burn(
        bad, total, 0.999, 5.0, long_s=60.0, short_s=5.0)
    assert long_b == pytest.approx(10.0)
    assert short_b is None and not firing
    # A fresh breaching point lights the short window too.
    bad += [(12.0, 12.0)]
    total += [(12.0, 1200.0)]
    long_b, short_b, firing = slo.multiwindow_burn(
        bad, total, 0.999, 5.0, long_s=60.0, short_s=5.0)
    assert long_b == pytest.approx(10.0)
    assert short_b == pytest.approx(10.0)
    assert firing
    # short_s = 0 degenerates to single-window.
    long_b, short_b, firing = slo.multiwindow_burn(
        bad[:2], total[:2], 0.999, 5.0, long_s=60.0, short_s=0.0)
    assert firing and short_b == long_b


# ---------------------------------------------------------------- rules

def test_rule_validation():
    from multiverso_tpu import health

    with pytest.raises(ValueError):
        health.Rule(name="r", metric="m", op="gt")
    with pytest.raises(ValueError):
        health.Rule(name="r", metric="m", op="rate_gt", severity="fatal")
    with pytest.raises(ValueError):
        health.Rule(name="r", metric="m", op="burn_rate_gt")


def test_default_rules_are_valid_and_cover_the_planes():
    from multiverso_tpu import health

    rules = health.default_rules()
    names = {r.name for r in rules}
    assert {"lat-p99", "lat-slo-burn", "audit-gap",
            "rss-growth", "hb-missed"} <= names
    for r in rules:
        assert r.op in health.RULE_OPS
        assert r.severity in health.SEVERITIES


# ------------------------------------------------------- alert lifecycle

def _feed_counter(reg, counter, samples):
    """Drive a counter through ``[(ts, cumulative_value)]`` history."""
    prev = counter._value
    for ts, v in samples:
        counter.inc(v - prev)
        prev = v
        reg.record_history(now=ts)


def test_counter_delta_rule_fires_and_resolves(registry):
    from multiverso_tpu import health, metrics

    reg = metrics.Registry()
    c = reg.counter("t.err")
    rule = health.Rule(name="r", metric="t.err", op="counter_delta_gt",
                       threshold=5.0, window_s=60.0)
    ev = health.HealthEvaluator([rule], registry=reg)
    _feed_counter(reg, c, [(0.0, 0.0), (10.0, 20.0)])
    trans = ev.evaluate(now=10.0)
    assert trans == [{"rule": "r", "to": "firing",
                      "severity": "warning", "value": 20.0}]
    (a,) = ev.snapshot()
    assert a["state"] == "firing" and a["fired"] == 1
    # Firing state is scrapeable like any other series.
    assert metrics.gauge("health.alerts.firing",
                         {"severity": "warning"}).value == 1.0
    # The counter goes flat -> the window delta drops to 0 -> resolve.
    _feed_counter(reg, c, [(70.0, 20.0), (80.0, 20.0)])
    trans = ev.evaluate(now=80.0)
    assert trans == [{"rule": "r", "to": "resolved",
                      "severity": "warning", "value": 0.0}]
    (a,) = ev.snapshot()
    assert a["state"] == "ok" and a["resolved"] == 1


def test_for_s_hysteresis_flapping_shows_pending_churn_only(registry):
    from multiverso_tpu import health, metrics

    reg = metrics.Registry()
    rule = health.Rule(name="up", metric="t.up", op="absent",
                       for_s=30.0)
    ev = health.HealthEvaluator([rule], registry=reg)
    # Flap: missing -> present -> missing, never 30 s sustained.
    ev.evaluate(now=0.0)
    assert ev.snapshot()[0]["state"] == "pending"
    reg.gauge("t.up").set(1.0)
    ev.evaluate(now=10.0)
    assert ev.snapshot()[0]["state"] == "ok"
    reg.remove("t.up")
    ev.evaluate(now=20.0)
    ev.evaluate(now=45.0)               # 25 s pending: still < for_s
    a = ev.snapshot()[0]
    assert a["state"] == "pending" and a["fired"] == 0
    ev.evaluate(now=51.0)               # 31 s sustained -> fires
    a = ev.snapshot()[0]
    assert a["state"] == "firing" and a["fired"] == 1


def test_no_data_keeps_firing_but_resets_pending(registry):
    from multiverso_tpu import health, metrics

    reg = metrics.Registry()
    c = reg.counter("t.err")
    firing = health.Rule(name="f", metric="t.err",
                         op="counter_delta_gt", threshold=5.0,
                         window_s=60.0)
    pending = health.Rule(name="p", metric="t.err",
                          op="counter_delta_gt", threshold=5.0,
                          for_s=100.0, window_s=60.0)
    ev = health.HealthEvaluator([firing, pending], registry=reg)
    _feed_counter(reg, c, [(0.0, 0.0), (10.0, 20.0)])
    ev.evaluate(now=10.0)
    by = {a["rule"]: a for a in ev.snapshot()}
    assert by["f"]["state"] == "firing"
    assert by["p"]["state"] == "pending"
    # The series vanishes (rank restart, ring reset): silence is not
    # proof of recovery -- firing holds; pending loses its evidence.
    reg.reset()
    trans = ev.evaluate(now=20.0)
    assert trans == []
    by = {a["rule"]: a for a in ev.snapshot()}
    assert by["f"]["state"] == "firing" and by["f"]["resolved"] == 0
    assert by["f"]["value"] is None
    assert by["p"]["state"] == "ok"


def test_burn_rate_rule_matches_hand_computed_math(registry):
    from multiverso_tpu import health, metrics, slo

    reg = metrics.Registry()
    bad, total = reg.counter("t.breach"), reg.counter("t.total")
    for ts, b, t in [(0.0, 0.0, 0.0), (10.0, 10.0, 1000.0),
                     (12.0, 12.0, 1200.0)]:
        bad.inc(b - bad._value)
        total.inc(t - total._value)
        reg.record_history(now=ts)
    rule = health.Rule(name="burn", metric="t.breach",
                       op="burn_rate_gt", total_metric="t.total",
                       objective=0.999, threshold=5.0,
                       window_s=60.0, short_window_s=5.0)
    ev = health.HealthEvaluator([rule], registry=reg)
    trans = ev.evaluate(now=12.0)
    assert [t["to"] for t in trans] == ["firing"]
    a = ev.snapshot()[0]
    expect = slo.burn_rate(reg.history("t.breach"),
                           reg.history("t.total"), 0.999, 60.0)
    assert a["value"] == pytest.approx(expect) == pytest.approx(10.0)


def test_critical_alert_boosts_profiler_and_restores(registry):
    from multiverso_tpu import health, metrics
    from multiverso_tpu import profiler as pyprof

    reg = metrics.Registry()
    rule = health.Rule(name="crit", metric="t.up", op="absent",
                       severity="critical")
    ev = health.HealthEvaluator([rule], registry=reg)
    try:
        ev.evaluate(now=0.0)
        assert ev.snapshot()[0]["state"] == "firing"
        prof = pyprof.active()
        assert prof is not None and prof.hz == health.BOOST_HZ
        # Resolving the last critical restores the previous rate (none
        # was armed before, so the sampler stops outright).
        reg.gauge("t.up").set(1.0)
        ev.evaluate(now=1.0)
        assert ev.snapshot()[0]["state"] == "ok"
        assert pyprof.active() is None
    finally:
        pyprof.stop(to_trace=False)


# ------------------------------------------------------- fleet merge

def test_fleet_alert_rows_silent_and_watchdog(registry):
    from multiverso_tpu import health

    doc = {"scope": "fleet", "kind": "alerts", "silent": [2],
           "ranks": {
               "0": {"rank": 0,
                     "host": {"armed": True, "alerts": [
                         {"rule": "lat-slo-burn", "severity": "critical",
                          "state": "firing", "value": 12.5,
                          "age_s": 3.0}]},
                     "watchdog": [
                         {"loop": "reactor.0", "stalled": True,
                          "queued": 7, "stalled_s": 1.5},
                         {"loop": "hb.scan", "stalled": False}]},
               "1": {"rank": 1, "host": None, "watchdog": []},
           }}
    rows = health.fleet_alert_rows(doc)
    by = {(r["rank"], r["rule"]): r for r in rows}
    assert by[("0", "lat-slo-burn")]["state"] == "firing"
    wd = by[("0", "watchdog:reactor.0")]
    assert wd["severity"] == "critical" and wd["value"] == 7.0
    assert ("0", "watchdog:hb.scan") not in by  # healthy loop: no row
    # A silent rank is UNKNOWN, never resolved.
    assert by[("2", "-")]["state"] == "unknown"
    assert by[("2", "-")]["value"] is None
    # A local (non-fleet) report flattens too.
    local = {"rank": 3, "host": {"alerts": [
        {"rule": "r", "severity": "info", "state": "ok"}]}}
    assert health.fleet_alert_rows(local)[0]["rank"] == "3"


# ------------------------------------------------------- arm / disarm

def test_arm_wires_the_flush_loop_and_disarm_unwires(registry):
    from multiverso_tpu import health, metrics

    assert health.alerts_doc()["armed"] is False
    ev = health.arm(rules=[health.Rule(name="up", metric="t.up",
                                       op="absent")])
    try:
        assert health.evaluator() is ev
        # Re-arming replaces, not stacks, the flush hook.
        ev2 = health.arm(rules=[health.Rule(name="up", metric="t.up",
                                            op="absent")])
        assert health.evaluator() is ev2
        with metrics._HOOK_LOCK:
            assert len(metrics._FLUSH_HOOKS) == 1
        metrics.start_flush(20)
        deadline = time.time() + 5
        doc = health.alerts_doc()
        while time.time() < deadline:
            doc = health.alerts_doc()
            if doc["firing"]:
                break
            time.sleep(0.02)
        assert doc["armed"] and doc["rules"] == 1
        assert doc["firing"] == 1, doc
        assert doc["alerts"][0]["rule"] == "up"
    finally:
        health.disarm()
    assert health.alerts_doc() == {"armed": False, "rules": 0,
                                   "firing": 0, "alerts": []}
    with metrics._HOOK_LOCK:
        assert len(metrics._FLUSH_HOOKS) == 0


# ------------------------------------------------- registry satellites

def test_prometheus_label_escaping_round_trip(registry):
    from multiverso_tpu.ops.introspect import parse_prometheus

    hostile = 'a"b\\c\nd}e'
    registry.gauge("t.esc", {"path": hostile}).set(7.0)
    text = registry.render_prometheus()
    # The reserved characters ship escaped on the wire...
    assert '\\"' in text and "\\\\" in text and "\\n" in text
    values, _ = parse_prometheus(text)
    keys = [k for k in values if k.startswith("t_esc{")]
    # ...and the quote-aware parser still keys the series despite the
    # literal `}` inside the label value.
    assert len(keys) == 1, values
    assert 'd}e"' in keys[0]
    assert values[keys[0]] == 7.0


def test_history_ring_capped_and_recapped(registry):
    c = registry.counter("t.n")
    registry.set_history_depth(4)
    for i in range(10):
        c.inc()
        registry.record_history(now=float(i))
    pts = registry.history("t.n")
    assert pts == [(6.0, 7.0), (7.0, 8.0), (8.0, 9.0), (9.0, 10.0)]
    # Shrinking re-caps existing rings, keeping the newest points.
    registry.set_history_depth(2)
    assert registry.history("t.n") == [(8.0, 9.0), (9.0, 10.0)]
    # Below 2 points rate()/delta() could never answer: clamped.
    registry.set_history_depth(1)
    assert registry.REGISTRY.history_depth == 2


# ------------------------------------------------- ops-kind meta-test

def test_every_ops_kind_has_an_mvtop_view_and_a_docs_section():
    """The wire catalogue, the mvtop view map, and the operator docs
    must name every kind: a plane you cannot render or read about is
    not shipped (mvcontract separately diffs the catalogue against the
    native dispatch strings)."""
    import mvtop

    from multiverso_tpu.serve import wire

    assert set(mvtop.KIND_VIEWS) == set(wire.OPS_KINDS)
    with open(os.path.join(REPO, "docs", "observability.md")) as fh:
        doc = fh.read()
    for kind in wire.OPS_KINDS:
        assert f'`"{kind}"`' in doc, f"docs/observability.md: {kind}"


def test_mvtop_alert_view_rows_and_firing_counts():
    import mvtop

    doc = {"silent": [1], "ranks": {"0": {
        "rank": 0,
        "host": {"alerts": [
            {"rule": "b", "severity": "warning", "state": "ok",
             "value": None, "age_s": 4.0},
            {"rule": "a", "severity": "critical", "state": "firing",
             "value": 12.25, "age_s": 2.0}]},
        "watchdog": []}}}
    rows = mvtop.alert_view_rows(doc)
    # Firing sorts above ok, unknown between them.
    assert [(r["rank"], r["rule"], r["state"]) for r in rows] == [
        ("0", "a", "firing"), ("1", "-", "unknown"), ("0", "b", "ok")]
    assert rows[0]["value"] == "12.25" and rows[0]["age_s"] == "2"
    assert rows[2]["value"] == "-"
    assert mvtop.firing_counts(doc) == {"0": 1, "1": "?"}
    stale = mvtop.render_stale("r1\nr2", OSError("down"))
    assert "showing last good scrape" in stale
    assert stale.count("stale") == 2


def test_mvdoctor_diagnose_correlates_planes():
    import mvdoctor

    planes = {
        "alerts": {"ranks": {"1": {"rank": 1, "host": {"alerts": [
            {"rule": "lat-slo-burn", "severity": "critical",
             "state": "firing", "value": 40.0, "age_s": 3.0}]},
            "watchdog": []}}},
        "latency": {"ranks": {"1": {"rank": 1, "stages": {
            "apply": {"p99_ms": 25.0}, "net": {"p99_ms": 0.2}},
            "total": {"p99_ms": 25.4}}}},
        "hotkeys": {"ranks": {"1": [
            {"id": 0, "gets": 1000, "skew_ratio": 9.0,
             "hotkeys": {"topk": [{"key": 3, "count": 100}]}}]}},
        "audit": {}, "capacity": {},
    }
    findings = mvdoctor.diagnose(planes)
    assert findings, "no findings"
    top = findings[0]
    assert top["severity"] == "critical" and top["rank"] == "1"
    assert "latency SLO burn" in top["title"]
    assert "'apply'" in top["title"]
    text = mvdoctor.render(findings)
    assert "[critical] rank 1" in text


# ---------------------------------------------------- native watchdog

@needs_gxx
def test_native_watchdog_and_alerts_report(tmp_path):
    from multiverso_tpu import native as nat

    nat.ensure_built()
    rt = nat.NativeRuntime(args=["-log_level=error", "-trace=true",
                                 f"-trace_dir={tmp_path}"])
    try:
        rt.set_watchdog(100)
        # Queued work, zero progress: the checker must flag a stall.
        rt.watchdog_busy("t.loop", 5)

        def loop_row():
            return {d["loop"]: d for d in rt.watchdog_stats()
                    }.get("t.loop")

        deadline = time.time() + 5
        row = None
        while time.time() < deadline:
            row = loop_row()
            if row and row["stalled"]:
                break
            time.sleep(0.05)
        assert row and row["stalled"], row
        assert row["queued"] == 5 and row["stalls"] >= 1
        # Progress clears the stall without disarming.
        deadline = time.time() + 5
        while time.time() < deadline:
            rt.watchdog_bump("t.loop")
            row = loop_row()
            if not row["stalled"]:
                break
            time.sleep(0.05)
        assert not row["stalled"], row
        # Host alert state round-trips through the in-band report,
        # beside the watchdog table.
        doc = {"armed": True, "rules": 1, "firing": 0, "alerts": []}
        rt.set_ops_host_alerts(json.dumps(doc))
        rep = json.loads(rt.ops_report("alerts"))
        assert rep["rank"] == 0
        assert rep["host"] == doc
        assert "t.loop" in {d["loop"] for d in rep["watchdog"]}
        rt.set_ops_host_alerts("")
        rep = json.loads(rt.ops_report("alerts"))
        assert rep["host"] is None
        # An idle loop cannot stall even with the watchdog armed.
        rt.watchdog_busy("t.loop", 0)
        time.sleep(0.3)
        assert not loop_row()["stalled"]
        rt.set_watchdog(0)
    finally:
        rt.shutdown()


# ------------------------------------------------- closed-loop chaos

@pytest.mark.slow
@needs_gxx
def test_doctor_demo_end_to_end():
    """The full acceptance smoke (``make doctor-demo``): quiet fleet ->
    seeded apply-delay fault pages fleet-wide -> mvdoctor names the
    rank and the stage -> clearing resolves (tier-2; minutes)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "doctor_demo.py")],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-2000:]
    assert "DOCTOR_DEMO_OK" in r.stdout
