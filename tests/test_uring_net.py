"""io_uring transport tests (docs/transport.md "io_uring data plane").

The completion engine (`-net_engine=uring`) must be a drop-in twin of
the epoll reactor: same anonymous serve protocol, same frame caps, same
admission gates — only the readiness model changed (registered-buffer
zero-copy receive, SQE-submitted sends, multishot accept).  This suite
re-runs the epoll suite's hostile-wire scenarios against a uring fleet:

- partial-frame reassembly (1-byte dribble across RECV completions);
- mid-frame peer disconnect (the partial dies, the server stays up);
- hostile frame lengths (dropped at the prefix, no allocation);
- write-queue backpressure against a slow reader (completion-driven
  drain, no deadlock, no lost replies);
- per-client admission shed (reactor-answered ReplyBusy);
- a 1k-connection fan-in smoke (`-m slow`) — far above `-uring_depth`,
  proving the SQ is a submission window, not a connection cap;
- the capability-probe seam: the whole module skips on kernels that
  cannot run io_uring, and the forced-probe-failure regression proves
  the uring->epoll fallback end to end (effective engine, health
  fields, service still up).

Helpers (fleet holder, machine files, frame codec) are shared with
tests/test_epoll_net.py — the suites must stay structurally identical
so an engine-semantics drift shows up as a diff here.
"""

import os
import shutil
import socket
import struct
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from multiverso_tpu.serve.wire import (AnonServeClient, FrameDecoder,  # noqa: E402
                                       MSG, pack_frame, unpack_frame)

from tests.test_epoll_net import (Fleet, _assert_clean_exit,  # noqa: E402
                                  _binary, _machine_file)


def _uring_supported() -> bool:
    if shutil.which("g++") is None:
        return False
    from multiverso_tpu import native as nat

    return bool(nat.load().MV_UringSupported())


pytestmark = pytest.mark.skipif(
    not _uring_supported(),
    reason="kernel cannot run the io_uring engine (MV_UringSupported=0)")

URING = ("-net_engine=uring",)


@pytest.fixture
def fleet(tmp_path):
    f = Fleet(tmp_path, extra=URING)
    try:
        yield f
    finally:
        f.kill()


# ------------------------------------------------------- anonymous protocol

def test_anonymous_client_on_uring(fleet):
    """A raw-socket client probes the version and pulls a shard over the
    completion engine; the fan-in stats count it like epoll would."""
    with AnonServeClient(fleet.endpoints[0]) as c:
        assert c.table_version(0) == 1
        shard = c.get_shard(0)
        assert shard.shape == (32,)
        np.testing.assert_allclose(shard, 1.0)
        for _ in range(5):
            assert c.table_version(0) == 1
    outs = fleet.release()
    _assert_clean_exit(outs, fleet.procs)
    assert "FANIN accepted=1" in outs[0], outs[0]


def test_partial_frame_dribble_on_uring(fleet):
    """One byte per send: the engine reassembles the frame across RECV
    completions (length prefix and body each arrive in shards)."""
    host, port = fleet.endpoints[0].rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=30)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    frame = pack_frame(MSG["RequestGet"], 0, 7)
    for i in range(len(frame)):
        s.sendall(frame[i:i + 1])
        if i < 16:
            time.sleep(0.002)
    dec = FrameDecoder()
    reply = None
    while reply is None:
        chunk = s.recv(65536)
        assert chunk, "server closed on a dribbled frame"
        dec.feed(chunk)
        body = dec.next_frame()
        if body is not None:
            reply = unpack_frame(body)
    assert reply["type_name"] == "ReplyGet" and reply["msg_id"] == 7
    np.testing.assert_allclose(
        np.frombuffer(reply["blobs"][0], np.float32), 1.0)
    s.close()
    _assert_clean_exit(fleet.release(), fleet.procs)


def test_midframe_disconnect_on_uring(fleet):
    """A client dying mid-frame discards the partial (the in-flight
    recv completes with reset/EOF); the NEXT client gets full service."""
    host, port = fleet.endpoints[0].rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=30)
    frame = pack_frame(MSG["RequestGet"], 0, 9)
    s.sendall(frame[:len(frame) // 2])
    time.sleep(0.05)
    s.close()
    with AnonServeClient(fleet.endpoints[0]) as c:
        np.testing.assert_allclose(c.get_shard(0), 1.0)
    _assert_clean_exit(fleet.release(), fleet.procs)


def test_hostile_frame_length_on_uring(fleet):
    """An anonymous connection claiming a larger-than-allowed frame is
    dropped at the length prefix — no slab, no READ_FIXED, no parse."""
    host, port = fleet.endpoints[0].rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=30)
    s.sendall(struct.pack("<q", 1 << 40))
    s.settimeout(10)
    assert s.recv(16) == b""
    s.close()
    with AnonServeClient(fleet.endpoints[0]) as c:
        assert c.table_version(0) == 1
    _assert_clean_exit(fleet.release(), fleet.procs)


def test_write_backpressure_slow_reader_on_uring(tmp_path):
    """A slow reader fills the bounded write queue; the engine holds
    frames (send completions pace resubmission) and every reply arrives
    once the reader catches up — no deadlock, no loss."""
    f = Fleet(tmp_path, extra=URING + ("-net_writeq_bytes=4096",))
    try:
        host, port = f.endpoints[0].rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=60)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        k = 24
        for i in range(k):
            s.sendall(pack_frame(MSG["RequestGet"], 0, 100 + i))
        time.sleep(1.0)
        dec = FrameDecoder()
        got = []
        s.settimeout(60)
        while len(got) < k:
            chunk = s.recv(4096)
            assert chunk, f"connection died after {len(got)}/{k} replies"
            dec.feed(chunk)
            while True:
                body = dec.next_frame()
                if body is None:
                    break
                got.append(unpack_frame(body))
            time.sleep(0.01)
        assert [g["msg_id"] for g in got] == list(range(100, 100 + k))
        for g in got:
            assert g["type_name"] == "ReplyGet"
        s.close()
        _assert_clean_exit(f.release(), f.procs)
    finally:
        f.kill()


def test_per_client_admission_sheds_busy_on_uring(tmp_path):
    """`-client_inflight_max=1`: the uring reactor answers the excess
    of a back-to-back burst with ReplyBusy, without touching actors."""
    f = Fleet(tmp_path, extra=URING + ("-client_inflight_max=1",))
    try:
        host, port = f.endpoints[0].rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=60)
        k = 8
        burst = b"".join(pack_frame(MSG["RequestGet"], 0, 200 + i)
                         for i in range(k))
        s.sendall(burst)
        dec = FrameDecoder()
        replies = []
        s.settimeout(60)
        while len(replies) < k:
            chunk = s.recv(65536)
            assert chunk
            dec.feed(chunk)
            while True:
                body = dec.next_frame()
                if body is None:
                    break
                replies.append(unpack_frame(body))
        kinds = {r["type_name"] for r in replies}
        assert "ReplyBusy" in kinds, kinds
        assert "ReplyGet" in kinds, kinds
        s.close()
        outs = f.release()
        _assert_clean_exit(outs, f.procs)
        assert "shed=0" not in outs[0].split("FANIN", 1)[1].split()[-1], \
            outs[0]
    finally:
        f.kill()


# ----------------------------------------------------- probe + fallback seam

def test_forced_probe_failure_falls_back_to_epoll(tmp_path):
    """MVTPU_URING_FORCE_UNSUPPORTED=1 + `-net_engine=uring`: the fleet
    comes up ON EPOLL (logged fallback), serves anonymous clients, and
    the health report records requested vs effective engine."""
    from multiverso_tpu import native as nat

    nat.ensure_built()
    mf, eps = _machine_file(tmp_path, 2)
    code = (
        "import json, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from multiverso_tpu import native as nat\n"
        f"rt = nat.NativeRuntime(args=['-machine_file={mf}', "
        "'-rank=' + sys.argv[1], '-log_level=error', "
        "'-net_engine=uring', '-barrier_timeout_ms=60000'])\n"
        "assert rt.net_engine() == 'epoll', rt.net_engine()\n"
        "h = json.loads(rt.ops_report('health'))\n"
        "assert h['engine'] == 'epoll', h\n"
        "assert h['engine_requested'] == 'uring', h\n"
        "assert h['engine_fallback'] is True, h\n"
        "t = rt.new_array_table(64)\n"
        "rt.barrier()\n"
        "print('READY', flush=True)\n"
        "sys.stdin.readline()\n"
        "rt.barrier(); rt.shutdown(); print('FALLBACK_OK', flush=True)\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env["MVTPU_URING_FORCE_UNSUPPORTED"] = "1"
    procs = [subprocess.Popen([sys.executable, "-c", code, str(r)],
                              stdin=subprocess.PIPE,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for r in range(2)]
    try:
        for p in procs:
            assert "READY" in p.stdout.readline()
        # The fallback fleet is a REAL epoll fleet: anonymous service up.
        with AnonServeClient(eps[0]) as c:
            assert c.table_version(0) >= 0
        for p in procs:
            p.stdin.write("done\n")
            p.stdin.flush()
        outs = [p.communicate(timeout=120)[0] for p in procs]
        for r, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0 and "FALLBACK_OK" in out, out[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_probe_env_override_visible_from_python():
    """The probe itself honors the forced-unsupported env hook (what
    this module's skipif and CI gates rely on)."""
    from multiverso_tpu import native as nat

    lib = nat.load()
    assert lib.MV_UringSupported() == 1
    os.environ["MVTPU_URING_FORCE_UNSUPPORTED"] = "1"
    try:
        assert lib.MV_UringSupported() == 0
    finally:
        del os.environ["MVTPU_URING_FORCE_UNSUPPORTED"]
    assert lib.MV_UringSupported() == 1


# --------------------------------------------------------- native scenarios

def test_net_child_scenario_on_uring(tmp_path):
    """The full sharded-table scenario (adds, barriers, SSP cache, KV)
    on the completion engine — `-net_engine` switches the readiness
    model without changing semantics."""
    mf, _ = _machine_file(tmp_path, 2)
    b = _binary()
    procs = [subprocess.Popen([b, "net_child", mf, str(r), "uring"],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(2)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=180)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} (uring):\n{out[-3000:]}"
        assert f"NET_CHILD_OK {r}" in out


def test_chaos_retry_on_uring_engine(tmp_path):
    """The PR 2 fault seam on the completion path: injected send
    failures consume retry attempts, the payload still lands."""
    mf, _ = _machine_file(tmp_path, 2)
    b = _binary()
    procs = [subprocess.Popen([b, "chaos_retry", mf, str(r), "uring"],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(2)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=180)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"CHAOS_RETRY_OK {r}" in out


# ------------------------------------------------------------- 1k fan-in

@pytest.mark.slow
def test_1k_connection_smoke_on_uring(tmp_path):
    """1000 concurrent anonymous sockets against one uring server rank
    — ~60x the default `-uring_depth`: the SQ is a submission window
    the engine flushes through, not a cap on concurrent connections.
    Every probe is answered and the fan-in counter records them all."""
    import resource
    import selectors

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if hard < 2200:
        pytest.skip(f"fd hard limit {hard} too low for 1k sockets")
    resource.setrlimit(resource.RLIMIT_NOFILE,
                       (min(hard, 16384), hard))

    f = Fleet(tmp_path, extra=URING + ("-net_arena_bytes=8192",))
    try:
        host, port = f.endpoints[0].rsplit(":", 1)
        n = 1000
        sel = selectors.DefaultSelector()
        socks = []
        for i in range(n):
            s = socket.socket()
            s.connect((host, int(port)))
            s.setblocking(False)
            sel.register(s, selectors.EVENT_READ,
                         {"dec": FrameDecoder(), "id": i})
            socks.append(s)
            s.send(pack_frame(MSG["RequestVersion"], 0, i))
        answered = set()
        deadline = time.time() + 120
        while len(answered) < n and time.time() < deadline:
            for key, _ in sel.select(timeout=1.0):
                data = key.data
                try:
                    chunk = key.fileobj.recv(65536)
                except BlockingIOError:
                    continue
                assert chunk, f"conn {data['id']} closed unanswered"
                data["dec"].feed(chunk)
                body = data["dec"].next_frame()
                if body is not None:
                    reply = unpack_frame(body)
                    assert reply["type_name"] in ("ReplyVersion",
                                                  "ReplyBusy")
                    answered.add(data["id"])
        assert len(answered) == n, f"only {len(answered)}/{n} answered"
        for s in socks:
            sel.unregister(s)
            s.close()
        outs = f.release()
        _assert_clean_exit(outs, f.procs)
        assert f"FANIN accepted={n}" in outs[0], outs[0][-500:]
    finally:
        f.kill()
