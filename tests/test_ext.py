"""Extension-binding tests (reference binding/python tests + theano_ext /
lasagne_ext / torch usage; SURVEY.md §2.30–2.34)."""

import numpy as np
import pytest


def test_mv_shared_delta_sync(mv):
    mv.init()
    from multiverso_tpu.ext import mv_shared

    v = mv_shared(np.zeros((2, 3), np.float32), average=False)
    local = v.get_value()
    local += 1.0
    v.set_value(local)
    merged = v.mv_sync()
    np.testing.assert_allclose(merged, 1.0)
    # a second sync with no local change pushes zero delta
    np.testing.assert_allclose(v.mv_sync(), 1.0)


def test_mv_shared_two_workers_average(mv):
    """Two simulated workers each add 1.0 with average=True → merged 1.0
    (each contributes delta/workers; workers_num()==1 here so scale=1, use
    two vars on one table-per-var to emulate the merge arithmetic)."""
    mv.init()
    from multiverso_tpu.ext import mv_shared

    v = mv_shared(np.zeros(4, np.float32), average=False)
    # worker A and worker B both push +1 deltas before either pulls
    v.table.add(np.ones(4, np.float32))
    v.set_value(v.get_value() + 1.0)
    merged = v.mv_sync()
    np.testing.assert_allclose(merged, 2.0)  # both contributions merged


def test_sync_all_mv_shared_vars(mv):
    mv.init()
    from multiverso_tpu.ext import mv_shared
    from multiverso_tpu.ext.jax_ext import sync_all_mv_shared_vars

    a = mv_shared(np.zeros(2, np.float32), average=False)
    b = mv_shared(np.ones(2, np.float32), average=False)
    a.set_value(np.full(2, 3.0))
    sync_all_mv_shared_vars()
    np.testing.assert_allclose(a.get_value(), 3.0)
    np.testing.assert_allclose(b.get_value(), 1.0)


def test_shared_param_manager_pytree(mv):
    mv.init()
    import jax.numpy as jnp

    from multiverso_tpu.ext import SharedParamManager

    params = {"w": jnp.ones((3, 2)), "b": jnp.zeros(2)}
    mgr = SharedParamManager(params, average=False)
    params = {"w": params["w"] + 2.0, "b": params["b"] - 1.0}
    merged = mgr.sync(params)
    np.testing.assert_allclose(np.asarray(merged["w"]), 3.0)
    np.testing.assert_allclose(np.asarray(merged["b"]), -1.0)
    assert merged["w"].shape == (3, 2)


def test_torch_param_manager_sync(mv):
    torch = pytest.importorskip("torch")
    mv.init()
    from multiverso_tpu.ext.torch_ext import TorchParamManager

    net = torch.nn.Sequential(torch.nn.Linear(4, 3), torch.nn.ReLU(),
                              torch.nn.Linear(3, 2))
    mgr = TorchParamManager(net, average=False)
    with torch.no_grad():
        for p in net.parameters():
            p.add_(1.0)
    want = [p.detach().numpy().copy() for p in net.parameters()]
    mgr.sync_all_param()
    got = [p.detach().numpy() for p in net.parameters()]
    for w, g in zip(want, got):
        np.testing.assert_allclose(g, w, rtol=1e-6)


def test_torch_data_parallel_training_converges(mv):
    """Mini ResNet-style data-parallel run: 2 simulated torch workers train
    on disjoint shards, syncing through one table each step (the reference's
    ResNet-20/CIFAR-10 pattern at toy scale)."""
    torch = pytest.importorskip("torch")
    mv.init()
    from multiverso_tpu.ext.torch_ext import TorchParamManager

    torch.manual_seed(0)
    rng = np.random.RandomState(0)
    x = rng.randn(256, 8).astype(np.float32)
    true_w = rng.randn(8, 2).astype(np.float32)
    y = (x @ true_w).argmax(1)

    def make_net():
        torch.manual_seed(1)
        return torch.nn.Sequential(torch.nn.Linear(8, 16), torch.nn.ReLU(),
                                   torch.nn.Linear(16, 2))

    nets = [make_net(), make_net()]
    mgrs = [TorchParamManager(n, name=f"net{i}", average=True)
            for i, n in enumerate(nets)]
    # both managers must sync through the SAME table for a real merge;
    # emulate by pointing worker B's manager at worker A's table
    mgrs[1].table = mgrs[0].table

    loss_fn = torch.nn.CrossEntropyLoss()
    opts = [torch.optim.SGD(n.parameters(), lr=0.1) for n in nets]
    xt = torch.from_numpy(x)
    yt = torch.from_numpy(y)
    first = None
    for step in range(40):
        for wid in (0, 1):
            xs, ys = xt[wid::2], yt[wid::2]
            opts[wid].zero_grad()
            loss = loss_fn(nets[wid](xs), ys)
            loss.backward()
            opts[wid].step()
            if first is None:
                first = float(loss)
        for m in mgrs:
            m.sync_all_param()
    # one extra zero-delta round so every worker pulls the final merge
    for m in mgrs:
        m.sync_all_param()
    last = float(loss_fn(nets[0](xt), yt))
    assert last < first * 0.6, (first, last)
    # after sync, both workers hold identical parameters
    for pa, pb in zip(nets[0].parameters(), nets[1].parameters()):
        np.testing.assert_allclose(pa.detach().numpy(), pb.detach().numpy(),
                                   rtol=1e-5)


def test_delta_sync_pins_asp_under_bsp_runtime(mv):
    """mv_shared/param managers must work under a sync=True runtime — their
    protocol is ASP and their tables pin sync=False."""
    mv.init(sync=True)
    from multiverso_tpu.ext import mv_shared

    v = mv_shared(np.zeros(4, np.float32), average=False)
    v.set_value(np.full(4, 2.0, np.float32))
    np.testing.assert_allclose(v.mv_sync(), 2.0)  # visible pre-barrier


def test_resnet20_data_parallel_trains(mv):
    """ResNet-20/CIFAR-shaped data-parallel run (BASELINE config #4) at toy
    scale: 2 workers, shared table, accuracy above chance after 2 epochs."""
    torch = pytest.importorskip("torch")
    mv.init()
    from multiverso_tpu.apps.resnet import (ResNet20DataParallel,
                                            synthetic_cifar)

    x, y = synthetic_cifar(256, num_classes=4, seed=0)
    app = ResNet20DataParallel(num_workers=2, lr=0.05, num_classes=4)
    for _ in range(2):
        app.train_epoch(x, y, batch_size=64)
    acc = app.accuracy(x[:128], y[:128])
    assert acc > 0.4, acc   # chance = 0.25


def test_torch_param_manager_shared_table_shape_check(mv):
    torch = pytest.importorskip("torch")
    mv.init()
    from multiverso_tpu.ext.torch_ext import TorchParamManager

    a = TorchParamManager(torch.nn.Linear(4, 2), name="shape_a")
    with pytest.raises(ValueError, match="shared table"):
        TorchParamManager(torch.nn.Linear(8, 2), table=a.table)


def test_mv_shared_compressed_sync_converges(mv):
    """Repeated drift + compressed delta-sync tracks the true value via
    error feedback (the wire-bound ext path riding the 1-bit codec)."""
    mv.init()
    from multiverso_tpu.ext.jax_ext import mv_shared

    sv = mv_shared(np.zeros(32, np.float32), name="ext_q")
    target = np.linspace(-1, 1, 32).astype(np.float32)
    v = np.zeros(32, np.float32)
    for _ in range(60):
        v = v + 0.2 * (target - v)          # local training drift
        sv.set_value(v)
        v = sv.mv_sync(compress="1bit")      # push 1-bit delta, pull
    np.testing.assert_allclose(v, target, atol=0.05)


def test_shared_param_manager_compressed_sync(mv):
    mv.init()
    from multiverso_tpu.ext.jax_ext import SharedParamManager

    params = {"w": np.ones((4, 4), np.float32),
              "b": np.zeros(4, np.float32)}
    mgr = SharedParamManager(params, name="ext_qm")
    params["w"] += 0.5
    params["b"] += 0.5
    merged = mgr.sync(params, compress="1bit")
    # single worker, UNIFORM delta (one bucket, exact mean): lossless
    np.testing.assert_allclose(np.asarray(merged["w"]), 1.5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(merged["b"]), 0.5, atol=1e-5)
