"""Fleet holder for the ops failure tests (not a pytest module).

Run as ``python ops_fleet_worker.py <machine_file> <rank> <trace_dir>``:
joins a 2-rank native epoll fleet with tracing, a FAST heartbeat lease
(100 ms interval, 400 ms timeout) and fail-fast wire flags, does
cross-rank table traffic (so spans + monitors exist), exports this
rank's Chrome trace to ``<trace_dir>/trace_rank<r>.json``, prints
``OPS_FLEET_READY`` — then HOLDS until a line arrives on stdin.

The pytest side (tests/test_ops.py) SIGKILLs rank 1 while the fleet is
held: rank 0's lease loop must mark the peer dead and the dead-peer
flight-recorder trigger must dump ``blackbox_rank0.json`` — the test
polls the file and scrapes rank 0's fleet view over an anonymous
socket.  On release the worker exits via ``os._exit`` (a clean shutdown
with a dead peer would just grind through every wire deadline — the
state under test is already on disk).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from multiverso_tpu import native as nat  # noqa: E402
from multiverso_tpu import tracing  # noqa: E402

SIZE = 64


def main() -> int:
    mf, rank, trace_dir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    rt = nat.NativeRuntime(args=[
        f"-machine_file={mf}", f"-rank={rank}", "-log_level=error",
        "-trace=true", f"-trace_dir={trace_dir}",
        "-heartbeat_ms=100", "-heartbeat_timeout_ms=400",
        "-rpc_timeout_ms=5000", "-barrier_timeout_ms=10000",
        "-connect_retry_ms=500", "-send_retries=0",
        "-ops_fleet_timeout_ms=1000"])
    assert rt.net_engine() == "epoll", rt.net_engine()
    h = rt.new_array_table(SIZE)
    rt.barrier()
    for _ in range(3):
        rt.array_add(h, np.ones(SIZE, np.float32))
        rt.array_get(h, SIZE)
    rt.barrier()
    # Export the span ring NOW: the surviving rank's trace must exist
    # before the chaos (a dead rank exports nothing — that is the point).
    tracing.enable(rank=rank)
    tracing.add_native_spans(rt)
    tracing.save(os.path.join(trace_dir, f"trace_rank{rank}.json"))
    print("OPS_FLEET_READY", flush=True)
    sys.stdin.readline()          # held; the test may kill our sibling
    print(f"OPS_FLEET_OK {rank}", flush=True)
    sys.stdout.flush()
    # Skip the native teardown: with a SIGKILLed peer, Zoo::Stop's
    # barrier/flush legs would only burn their full deadlines.
    os._exit(0)


if __name__ == "__main__":
    sys.exit(main())
