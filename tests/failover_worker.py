"""Replication/failover fleet driver (not a pytest module;
docs/replication.md).

Run as ``python failover_worker.py <machine_file> <rank> [extra
flags...]``: joins an N-rank native fleet with ``-replication_factor=1
-repl_sync=true`` and a fast symmetric heartbeat lease, registers
ArrayTable 0 (12 elements — one 4-element shard per rank at N=3) and
MatrixTable 1 (12x4), does one acked warm add per rank, verifies
convergence, prints ``FAILOVER_READY`` — then serves stdin COMMANDS
until ``done`` (each acked with ``OK <cmd>`` so the pytest side
sequences without sleeps):

- ``sums``            print ``SUMS <json>`` — this rank's audit
                      checksums: own shard beacons, backup-shard
                      beacons, and which shard it backs
- ``repl``            print ``REPL <json>`` — routing epoch, shard
                      owners, replication stats
- ``waitdead <n>``    poll until >= n peers are lease-dead (15 s cap)
- ``waitowner <s> <r>``  poll until shard s routes to rank r
- ``add <v>``         acked add of ``v`` ones to BOTH tables, retried
                      through promotion races (bounded)
- ``get``             print ``VALUES <json>`` — array values + per-row
                      matrix sums
- ``barrier``         print ``BARRIER ok|failed`` (dead-leased ranks
                      are excused from the quorum with replication on)
- ``audit_fleet`` / ``repl_fleet``  print the fleet-scope report JSON
- ``mon <name>``      print ``MON <name>=<count>``
- ``fault <k> <n>`` / ``fault_rate <k> <r>`` / ``clear``  chaos knobs
- ``join <shard>``    MV_ReplJoin: become shard's backup live
- ``exit_hard``       ``os._exit(0)`` (rank-0-kill mode: no barrier
                      authority is left to shut down through)

The pytest side (tests/test_failover.py) SIGKILLs a rank mid-hold and
drives the survivors through detection, promotion, re-routed traffic,
and the mvaudit zero-lost-acked-adds diff.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from multiverso_tpu import native as nat  # noqa: E402

SIZE = 12
MROWS = 12
MCOLS = 4


def main() -> int:
    mf, rank = sys.argv[1], int(sys.argv[2])
    extra = sys.argv[3:]
    nranks = len(open(mf).read().split())
    rt = nat.NativeRuntime(args=[
        f"-machine_file={mf}", f"-rank={rank}", "-log_level=error",
        "-rpc_timeout_ms=3000", "-barrier_timeout_ms=20000",
        "-heartbeat_ms=100", "-heartbeat_timeout_ms=400",
        "-replication_factor=1", "-repl_sync=true", "-promote_auto=true",
        "-send_retries=2", "-send_backoff_ms=20",
        "-connect_retry_ms=500", "-ops_fleet_timeout_ms=1500", *extra])
    h = rt.new_array_table(SIZE)
    hm = rt.new_matrix_table(MROWS, MCOLS)
    rt.barrier()

    ones = np.ones(SIZE, np.float32)
    mones = np.ones((MROWS, MCOLS), np.float32)
    all_rows = list(range(MROWS))
    rt.array_add(h, ones)
    rt.matrix_add_rows(hm, all_rows, mones)
    rt.barrier()
    out = rt.array_get(h, SIZE)
    assert np.allclose(out, float(nranks)), out
    print("FAILOVER_READY", flush=True)

    def checked_add(v: float) -> None:
        # Blocking adds retried through the promotion window: a
        # dead-shard add fails fast (rc -3) until the epoch flip
        # re-routes it.  Whole-table adds are only exactness-safe once
        # every shard routes to a live rank, so callers sequence this
        # AFTER waitowner.
        for table_add in (
                lambda: rt.array_add(h, v * ones),
                lambda: rt.matrix_add_rows(hm, all_rows, v * mones)):
            for attempt in range(40):
                try:
                    table_add()
                    break
                except RuntimeError:
                    time.sleep(0.1)
            else:
                raise RuntimeError("add never succeeded post-failover")

    for line in sys.stdin:
        parts = line.split()
        if not parts or parts[0] == "done":
            break
        cmd = parts[0]
        if cmd == "sums":
            doc = rt.audit_report()
            t0 = doc["tables"][0]
            print("SUMS " + json.dumps({
                "backup_shard": doc.get("backup_shard", -1),
                "server": t0.get("checksums"),
                "backup": t0.get("backup_checksums"),
            }), flush=True)
        elif cmd == "repl":
            print("REPL " + json.dumps({
                "epoch": rt.routing_epoch(),
                "owners": [rt.shard_owner(s) for s in range(nranks)],
                "backup_shard": rt.backup_shard(),
                "stats": rt.replication_stats(),
            }), flush=True)
        elif cmd == "waitdead":
            want = int(parts[1])
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if rt.dead_peer_count() >= want:
                    break
                time.sleep(0.05)
            print(f"DEAD {rt.dead_peer_count()}", flush=True)
        elif cmd == "waitowner":
            shard, want = int(parts[1]), int(parts[2])
            deadline = time.monotonic() + 15
            owner = -1
            while time.monotonic() < deadline:
                owner = rt.shard_owner(shard)
                if owner == want:
                    break
                time.sleep(0.05)
            print(f"OWNER {shard}={owner}", flush=True)
        elif cmd == "add":
            checked_add(float(parts[1]))
        elif cmd == "get":
            vals = rt.array_get(h, SIZE)
            rows = rt.matrix_get_rows(hm, all_rows, MCOLS)
            print("VALUES " + json.dumps({
                "array": [float(v) for v in vals],
                "row_sums": [float(s) for s in rows.sum(axis=1)],
            }), flush=True)
        elif cmd == "barrier":
            try:
                rt.barrier()
                print("BARRIER ok", flush=True)
            except RuntimeError:
                print("BARRIER failed", flush=True)
        elif cmd == "audit_fleet":
            print("AUDIT_FLEET " + rt.ops_fleet_report("audit"),
                  flush=True)
        elif cmd == "repl_fleet":
            print("REPL_FLEET " + rt.ops_fleet_report("replication"),
                  flush=True)
        elif cmd == "mon":
            print(f"MON {parts[1]}={rt.query_monitor(parts[1])}",
                  flush=True)
        elif cmd == "fault":
            rt.set_fault_seed(1234)
            rt.set_fault_n(parts[1], int(parts[2]))
        elif cmd == "fault_rate":
            rt.set_fault_seed(1234)
            rt.set_fault(parts[1], float(parts[2]))
        elif cmd == "clear":
            rt.clear_faults()
        elif cmd == "join":
            rt.repl_join(int(parts[1]))
        elif cmd == "exit_hard":
            sys.stdout.flush()
            os._exit(0)
        print(f"OK {cmd}", flush=True)
    rt.shutdown()
    print(f"FAILOVER_WORKER_OK {rank}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
