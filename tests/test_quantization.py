"""1-bit gradient compression (SURVEY.md §5 quantization lineage):
quantizer properties, error-feedback convergence, and the table-level
compress='1bit' add path.
"""

import numpy as np
import pytest

from multiverso_tpu.util.quantization import (OneBitCompressor,
                                              dequantize_1bit,
                                              quantize_1bit)


def test_roundtrip_shapes_and_scales():
    rng = np.random.RandomState(0)
    d = rng.randn(1000).astype(np.float32)
    packed, p, m, res = quantize_1bit(d)
    assert packed.dtype == np.uint8 and packed.size == 125  # 1000/8
    assert p >= 0 >= m
    recon = dequantize_1bit(packed, p, m, 1000)
    # signs preserved exactly; magnitudes replaced by bucket means
    np.testing.assert_array_equal(recon >= 0, d >= 0)
    np.testing.assert_allclose(recon + res, d, rtol=1e-5, atol=1e-6)


def test_error_feedback_telescopes():
    """Sum of reconstructions == sum of true deltas minus the FINAL
    residual — the telescoping identity that makes 1-bit SGD converge."""
    rng = np.random.RandomState(1)
    comp = OneBitCompressor()
    total_true = np.zeros(64, np.float32)
    total_recon = np.zeros(64, np.float32)
    for _ in range(50):
        d = rng.randn(64).astype(np.float32)
        total_true += d
        packed, p, m = comp.compress(d)
        total_recon += comp.decompress(packed, p, m, (64,))
    drift = total_true - total_recon
    np.testing.assert_allclose(drift, comp._residual, rtol=1e-4, atol=1e-4)
    # residual stays bounded (it does NOT accumulate across steps)
    assert np.abs(comp._residual).max() < 10 * np.abs(total_true).max() / 50


def test_wire_bytes_are_32x_smaller():
    n = 1 << 20
    packed, _, _, _ = quantize_1bit(np.ones(n, np.float32))
    assert packed.nbytes == n // 8          # 1/32 of n*4 f32 bytes


def test_array_table_compressed_add_converges(mv):
    """Gradient descent through compress='1bit' adds reaches the optimum
    of a quadratic — the error feedback does its job end-to-end."""
    mv.init(updater_type="sgd")
    import multiverso_tpu as m

    target = np.linspace(-1, 1, 32).astype(np.float32)
    t = m.ArrayTable(32, name="q_lr")
    opt = m.AddOption(learning_rate=0.3)
    for _ in range(80):
        w = t.get()
        t.add(w - target, option=opt, compress="1bit")   # grad of 0.5|w-t|^2
    np.testing.assert_allclose(t.get(), target, atol=0.05)


def test_matrix_table_compressed_add(mv):
    mv.init()
    import multiverso_tpu as m

    t = m.MatrixTable(8, 4, name="q_m")
    d = np.random.RandomState(2).randn(8, 4).astype(np.float32)
    for _ in range(20):
        t.add(d, compress="1bit")
    # Error feedback keeps the residual BOUNDED (a few |d| on outlier
    # elements), so the per-step average converges to d as steps grow.
    np.testing.assert_allclose(t.get() / 20, d, atol=0.5)


def test_compress_rejects_bsp_and_unknown(mv):
    mv.init()
    import multiverso_tpu as m

    t = m.ArrayTable(8, name="q_err")
    with pytest.raises(ValueError, match="unknown compress"):
        t.add(np.ones(8, np.float32), compress="2bit")
    ts = m.ArrayTable(8, name="q_bsp", sync=True)
    with pytest.raises(ValueError, match="BSP"):
        ts.add(np.ones(8, np.float32), compress="1bit")


def test_compressor_residual_resets_on_restore(mv):
    mv.init()
    import multiverso_tpu as m
    from multiverso_tpu import checkpoint

    t = m.ArrayTable(8, name="q_ck")
    t.add(np.full(8, 0.7, np.float32), compress="1bit")
    assert t._compressor is not None and t._compressor._residual is not None
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "q.ckpt")
        checkpoint.save(path)
        checkpoint.restore(path)
    assert t._compressor._residual is None


def test_compress_rejects_int_tables(mv):
    import jax.numpy as jnp

    mv.init()
    import multiverso_tpu as m

    t = m.ArrayTable(8, dtype=jnp.int32, name="q_int")
    with pytest.raises(ValueError, match="floating"):
        t.add(np.ones(8, np.int32), compress="1bit")
