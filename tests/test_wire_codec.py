"""Compressed, copy-light wire data plane (docs/wire_compression.md).

Four layers of coverage:

1. Python 1-bit quantization property tests — empty payloads, NaN/Inf
   sanitization, odd lengths, all-negative buckets, and the
   error-feedback residual draining (not accumulating) over repeated
   compress/apply cycles.
2. The native codec unit suite (``mvtpu_test codec``): sparse/1-bit
   round trips, malformed-payload rejection, header stamps, reply
   accept-list negotiation.
3. Multi-process wire scenarios: ``codec_wire`` (1bit ships >= 3x fewer
   payload bytes than raw for the same dense adds, measured via the
   ``net.bytes`` counters, with served values inside tolerance) and
   ``agg_child`` (>= 4 consecutive small adds collapse into ONE wire
   message; Get/Clock/Barrier/explicit-flush all drain the buffer, so
   BSP/SSP visibility holds).
4. The binding/bridge surface: MV_SetTableCodec / MV_FlushAdds /
   MV_WireStats through ctypes, the ``net.bytes{dir=...}`` metrics
   bridge, the ``codec.encode`` / ``agg.flush`` fault seams, and a
   2-proc raw-vs-1bit LR convergence check (final loss within 5% at
   equal steps — the acceptance bar bench_lr_native8 reports).
"""

import os
import re
import shutil
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(REPO, "multiverso_tpu", "native")

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")


# ---------------------------------------------------------------------------
# 1. Python quantization property tests (no runtime needed)
# ---------------------------------------------------------------------------

def test_quantize_empty_payload():
    from multiverso_tpu.util.quantization import (dequantize_1bit,
                                                  quantize_1bit)

    packed, p, m, res = quantize_1bit(np.zeros(0, np.float32))
    assert packed.size == 0 and res.size == 0
    assert p == 0.0 and m == 0.0
    assert dequantize_1bit(packed, p, m, 0).size == 0


@pytest.mark.parametrize("n", [1, 5, 7, 9, 31, 33])
def test_quantize_odd_lengths_roundtrip(n):
    from multiverso_tpu.util.quantization import (dequantize_1bit,
                                                  quantize_1bit)

    rng = np.random.RandomState(n)
    d = rng.randn(n).astype(np.float32)
    packed, p, m, res = quantize_1bit(d)
    out = dequantize_1bit(packed, p, m, n)
    assert out.shape == (n,)
    # Reconstruction + residual telescopes back to the input exactly.
    np.testing.assert_allclose(out + res, d, atol=1e-5)


def test_quantize_all_negative():
    from multiverso_tpu.util.quantization import (dequantize_1bit,
                                                  quantize_1bit)

    d = np.asarray([-1.0, -2.0, -3.0], np.float32)
    packed, p, m, _ = quantize_1bit(d)
    assert p == 0.0 and m == pytest.approx(-2.0)
    np.testing.assert_allclose(dequantize_1bit(packed, p, m, 3), -2.0)


def test_quantize_sanitizes_nonfinite():
    """NaN/Inf inputs must not poison the scales or ride the feedback
    loop: they quantize as 0 and their residual resets to 0 (matches the
    native codec)."""
    from multiverso_tpu.util.quantization import quantize_1bit

    d = np.asarray([np.nan, np.inf, -np.inf, 2.0, -2.0], np.float32)
    packed, p, m, res = quantize_1bit(d)
    assert np.isfinite(p) and np.isfinite(m)
    assert np.isfinite(res).all()
    assert res[0] == 0.0 and res[1] == 0.0 and res[2] == 0.0
    assert packed.size == 1


def test_error_feedback_residual_drains():
    """Repeated compress/apply cycles with fluctuating deltas: the
    applied sum tracks the true sum (relative error -> ~0) and the
    carried residual stays bounded — the error DRAINS into later
    messages instead of accumulating."""
    from multiverso_tpu.util.quantization import OneBitCompressor

    comp = OneBitCompressor()
    rng = np.random.RandomState(0)
    n, steps = 64, 80
    applied = np.zeros(n, np.float32)
    true_sum = np.zeros(n, np.float64)
    for _ in range(steps):
        d = rng.randn(n).astype(np.float32)
        true_sum += d
        packed, p, m = comp.compress(d)
        applied += comp.decompress(packed, p, m, (n,))
    # |applied - true| == |final residual|; with ~N(0,1) deltas the
    # residual stays O(1) while the sums walk O(sqrt(steps)).
    err = np.abs(applied - true_sum)
    assert float(err.max()) < 4.0
    assert np.abs(comp._residual).max() < 4.0
    rel = float(err.mean()) / max(1.0, float(np.abs(true_sum).mean()))
    assert rel < 0.5


# ---------------------------------------------------------------------------
# 2 + 3. Native codec unit suite and multi-process wire scenarios
# ---------------------------------------------------------------------------

def _binary():
    b = os.path.join(NATIVE_DIR, "build", "mvtpu_test")
    subprocess.run(["make", "-C", NATIVE_DIR, "-j4", "build/mvtpu_test"],
                   check=True, capture_output=True, timeout=600)
    return b


def _machine_file(tmp_path, n=2):
    import socket

    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    eps = [f"127.0.0.1:{s.getsockname()[1]}" for s in socks]
    for s in socks:
        s.close()
    mf = tmp_path / "machines.txt"
    mf.write_text("".join(e + "\n" for e in eps))
    return str(mf)


def _run_ranks(binary, scenario, mf, n, extra=()):
    procs = [subprocess.Popen([binary, scenario, mf, str(r), *extra],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(n)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=180)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs, procs


@needs_gxx
def test_native_codec_unit_suite():
    out = subprocess.run([_binary(), "codec"], capture_output=True,
                         text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "codec        OK" in out.stdout


@needs_gxx
def test_codec_wire_1bit_ships_3x_fewer_bytes(tmp_path):
    """Acceptance: the 2-proc wire bench's 1bit phase ships >= 3x fewer
    payload bytes than raw for dense adds (net.bytes counters), with
    served values inside tolerance (asserted inside the scenario)."""
    mf = _machine_file(tmp_path, 2)
    outs, procs = _run_ranks(_binary(), "codec_wire", mf, 2)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"CODEC_WIRE_OK {r}" in out, out[-2000:]
    m = re.search(r"CODEC_RATIO ([0-9.]+)", outs[0])
    assert m, outs[0][-2000:]
    assert float(m.group(1)) >= 3.0, outs[0][-2000:]
    # Both phases reported bytes/msgs for the bench keys.
    assert re.search(r"CODEC raw bytes=\d+ msgs=\d+", outs[0])
    assert re.search(r"CODEC 1bit bytes=\d+ msgs=\d+", outs[0])


@needs_gxx
def test_add_aggregation_collapses_and_flushes(tmp_path):
    """Acceptance: >= 4 consecutive small async adds collapse into ONE
    wire message, and Get/Clock/Barrier/MV_FlushAdds all flush the
    buffer with no semantic change (values asserted in the scenario)."""
    mf = _machine_file(tmp_path, 2)
    outs, procs = _run_ranks(_binary(), "agg_child", mf, 2)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"AGG_OK {r}" in out, out[-2000:]


# ---------------------------------------------------------------------------
# 4. Binding surface, metrics bridge, fault seams, LR convergence
# ---------------------------------------------------------------------------

@needs_gxx
def test_binding_codec_surface_and_single_proc_1bit():
    """MV_SetTableCodec / MV_FlushAdds / MV_WireStats through ctypes in
    a fresh subprocess (its own runtime singleton): a 1bit table's adds
    decode correctly even in-process, aggregation honors the explicit
    flush, the agg.flush fault seam fires, and wire_stats stays zero
    without a wire."""
    code = """
import numpy as np
from multiverso_tpu import fault, native as nat

rt = nat.NativeRuntime(args=["-updater_type=default", "-log_level=error",
                             "-add_agg_bytes=1048576"])
h = rt.new_array_table(32)
rt.set_table_codec(h, "1bit")
delta = (1.0 + 0.25 * (np.arange(32) % 4)).astype(np.float32)
for a in range(4):
    rt.array_add(h, np.roll(delta, a), sync=True)
out = rt.array_get(h, 32)
want = 4 * 1.375
assert abs(out.mean() - want) / want < 0.02, out.mean()
assert np.abs(out - want).max() < 1.5, out

# Unknown codec name -> rc -1.
try:
    rt.set_table_codec(h, "zstd")
    raise SystemExit("expected failure")
except RuntimeError:
    pass

# Aggregation: async adds absorb until the explicit flush.
h2 = rt.new_array_table(8)
for _ in range(5):
    rt.array_add(h2, np.ones(8, np.float32), sync=False)
assert rt.query_monitor("agg.flush") == 0
rt.flush_adds(h2)
assert rt.query_monitor("agg.flush") == 1
np.testing.assert_allclose(rt.array_get(h2, 8), 5.0)

# agg.flush fault seam (docs/fault_tolerance.md).
fault.configure(seed=1, sites={"agg.flush": 1.0})
try:
    rt.flush_adds(h2)
    raise SystemExit("expected injected fault")
except fault.FaultError:
    pass
fault.reset()

# Single process: no transport, so the wire ledger stays empty.
ws = rt.wire_stats()
assert ws == {"sent_bytes": 0, "recv_bytes": 0,
              "sent_msgs": 0, "recv_msgs": 0}, ws
rt.shutdown()
print("CODEC_BINDING_OK")
"""
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, env={**os.environ, "JAX_PLATFORMS": "cpu",
                          "PYTHONPATH": REPO})
    assert "CODEC_BINDING_OK" in out.stdout, out.stdout + out.stderr


def test_bridge_maps_net_bytes_counters():
    """bridge_native turns the native net.bytes.{sent,recv} ledgers
    (count = frames, total = bytes) into the labelled net.bytes/net.msgs
    counters — wire observability parity with the Python io.bytes."""
    from multiverso_tpu import metrics

    metrics.reset()

    class StubRuntime:
        def dump_monitors(self):
            buckets = tuple([0] * 28)
            return {"net.bytes.sent": (7, 4096.0, 1024.0, buckets),
                    "net.bytes.recv": (3, 512.0, 256.0, buckets),
                    "Net::Send": (7, 0.004, 0.001, buckets)}

    n = metrics.bridge_native(StubRuntime())
    assert n == 3
    assert metrics.counter("net.bytes", {"dir": "sent"}).value == 4096.0
    assert metrics.counter("net.bytes", {"dir": "recv"}).value == 512.0
    assert metrics.counter("net.msgs", {"dir": "sent"}).value == 7
    assert metrics.counter("net.msgs", {"dir": "recv"}).value == 3
    # Re-bridging refreshes absolute state instead of double-counting.
    metrics.bridge_native(StubRuntime())
    assert metrics.counter("net.bytes", {"dir": "sent"}).value == 4096.0
    metrics.reset()


def test_codec_encode_fault_seam(mv):
    """The codec.encode chaos seam fires inside the JAX-plane compress
    path, where a real encode failure would surface."""
    from multiverso_tpu import fault

    mv.init(updater_type="sgd")
    import multiverso_tpu as m

    t = m.ArrayTable(16)
    fault.configure(seed=7, sites={"codec.encode": 1.0})
    try:
        with pytest.raises(fault.FaultError):
            t.add(np.ones(16, np.float32), compress="1bit")
        assert fault.count("fault.codec.encode") == 1
    finally:
        fault.reset()
    # Disarmed: the compressed add goes through (sgd, lr=0.1 -> -0.1).
    t.add(np.ones(16, np.float32), compress="1bit")
    np.testing.assert_allclose(t.get(), -0.1, atol=1e-5)


def test_wire_codec_flag_defaults_compress(mv):
    """-wire_codec=1bit makes 1-bit the default for host dense adds on
    float ASP tables (explicit compress= still wins; BSP tables are
    exempt — the residual is per wire message)."""
    mv.init(updater_type="sgd")
    import multiverso_tpu as m

    m.config.set_flag("wire_codec", "1bit")
    try:
        t = m.ArrayTable(8, name="wc_default")
        t.add(np.full(8, 2.0, np.float32))  # all-equal: 1bit is exact
        np.testing.assert_allclose(t.get(), -0.2, atol=1e-5)  # sgd lr=.1
        assert t._compressor is not None  # the 1bit path actually ran
        tb = m.ArrayTable(8, name="wc_bsp", sync=True)
        tb.add(np.ones(8, np.float32))    # BSP: buffered, not compressed
        assert tb._compressor is None
    finally:
        m.config.set_flag("wire_codec", "raw")


@needs_gxx
def test_lr_native_1bit_loss_within_5pct(tmp_path):
    """Acceptance: equal-steps LR over the native wire, raw vs 1bit +
    error feedback — final loss within 5%."""
    worker = os.path.join(REPO, "multiverso_tpu", "apps",
                          "lr_native_worker.py")
    from multiverso_tpu import native as nat

    nat.ensure_built()

    def run(codec):
        mf = _machine_file(tmp_path, 2)
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        procs = [subprocess.Popen(
            [sys.executable, worker, mf, str(r), "40", "256", codec],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env) for r in range(2)]
        outs = []
        try:
            for p in procs:
                outs.append(p.communicate(timeout=300)[0])
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        losses = []
        for r, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0 and "NATIVE_LR_OK" in out, \
                f"rank {r} ({codec}):\n{out[-2000:]}"
            losses.append(float(re.search(r"loss=([0-9.]+)", out).group(1)))
        return float(np.mean(losses))

    loss_raw = run("raw")
    loss_1bit = run("1bit")
    assert abs(loss_1bit - loss_raw) / loss_raw < 0.05, \
        (loss_raw, loss_1bit)
