"""tests/test_contract.py — mvcontract, the cross-language contract
checker (tools/mvcontract.py, docs/static_analysis.md).

Three layers:

- extractor unit tests: each of the five surface extractors, run over
  the REAL tree, must see the facts we know are true (MsgType values,
  struct sizeofs, ctypes arities including the getattr-loop and
  list-arithmetic forms, Lua cdef prototypes, flag defaults, docs
  flag-table rows);
- the clean-tree gate: the real tree diffs clean — this is what keeps
  `make contract` (inside `make lint`) green in tier-1;
- the seeded-drift matrix: every drift category the checker exists for
  is seeded into a doctored copy of one surface and must produce a
  finding that names the file and the surface pair, and `--strict`
  must exit 1 on it.

Everything here is static: no native build, no subprocess, no import
of the checked modules.
"""

import os
import shutil
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import mvcontract  # noqa: E402


def _p(rel):
    return os.path.join(REPO, rel)


MESSAGE_H = _p("multiverso_tpu/native/include/mvtpu/message.h")
C_API_H = _p("multiverso_tpu/native/include/mvtpu/c_api.h")
WIRE_PY = _p("multiverso_tpu/serve/wire.py")
BINDING_PY = _p("multiverso_tpu/native/__init__.py")
LUA = _p("multiverso_tpu/binding/lua/multiverso.lua")
CONFIGURE_CC = _p("multiverso_tpu/native/src/configure.cc")
CONFIG_PY = _p("multiverso_tpu/config.py")
OPS_CC = _p("multiverso_tpu/native/src/ops.cc")


def _seed(tmp_path, src, name, old, new):
    """Copy `src` to tmp with `old` replaced by `new` (must occur)."""
    text = open(src, "r", encoding="utf-8").read()
    assert old in text, f"seed anchor missing from {src}: {old!r}"
    out = tmp_path / name
    out.write_text(text.replace(old, new))
    return str(out)


# ------------------------------------------------------ extractor: (a1)

def test_message_header_extractor_msgtypes():
    m = mvcontract.extract_message_header(MESSAGE_H)
    types = {k: v[0] for k, v in m["msgtypes"].items()}
    # Spot-check the span of the enum: serve protocol, control plane,
    # replication, and the sentinel.
    assert types["RequestGet"] == 1
    assert types["RequestCancel"] == 13
    assert types["ControlRegister"] == 16
    assert types["ReplForward"] == 25
    assert types["Exit"] == 64
    assert m["codecs"] == {
        k: m["codecs"][k] for k in ("kRaw", "kOneBit", "kSparse")}
    assert {k: v[0] for k, v in m["codecs"].items()} == {
        "kRaw": 0, "kOneBit": 1, "kSparse": 2}


def test_message_header_extractor_msgflags():
    m = mvcontract.extract_message_header(MESSAGE_H)
    flags = {k: v[0] for k, v in m["msgflags"].items()}
    assert flags["kAcceptRaw"] == 1
    assert flags["kHasTiming"] == 8
    assert flags["kHasAudit"] == 16
    assert flags["kHasQos"] == 32


def test_message_header_extractor_struct_layouts():
    m = mvcontract.extract_message_header(MESSAGE_H)
    s = m["structs"]
    # WireHeader: 4xint32, 3xint64, 4xint32 = 56 bytes, no padding.
    assert "".join(s["WireHeader"]["prims"]) == "iiiiqqqiiii"
    assert s["WireHeader"]["sizeof"] == 56
    # TimingTrail: int64_t t[kStamps] with kStamps resolved from the
    # member enum — the brace initializer must not add fields.
    assert "".join(s["TimingTrail"]["prims"]) == "qqqqqq"
    assert s["TimingTrail"]["sizeof"] == 48
    assert s["AuditStamp"]["sizeof"] == 16
    assert "".join(s["QosStamp"]["prims"]) == "iiq"
    assert s["QosStamp"]["sizeof"] == 16


def test_c_sizeof_applies_alignment_rules():
    # int32 followed by int64: the int64 is 8-aligned, so the struct
    # carries a 4-byte hole and 8-byte tail alignment.
    assert mvcontract._c_sizeof(["i", "q"]) == 16
    assert mvcontract._c_sizeof(["i", "i", "q"]) == 16
    assert mvcontract._c_sizeof(["q", "i"]) == 16  # tail padding
    assert mvcontract._c_sizeof(["i"]) == 4


# ------------------------------------------------------ extractor: (a2)

def test_c_api_extractor_prototypes_and_rc():
    capi = mvcontract.extract_c_api(C_API_H)
    fns = capi["functions"]
    assert len(fns) > 80  # the full C API, not a lucky subset
    arity, ret, line = fns["MV_Init"]
    assert (arity, ret) == (2, "int") and line > 0
    # (void) parameter lists are arity 0; long long and char* returns
    # normalize; multi-line prototypes parse.
    assert fns["MV_RoutingEpoch"][:2] == (0, "longlong")
    assert fns["MV_DashboardReport"][1] == "charp"
    assert fns["MV_FreeString"][:2] == (1, "void")
    # The documented rc map: 0 plus -1..-7.
    assert capi["rc_codes"] == {-1, -2, -3, -4, -5, -6, -7}


# ------------------------------------------------------- extractor: (b)

def test_wire_extractor():
    w = mvcontract.extract_wire(WIRE_PY)
    assert w["structs"]["HEADER"]["fmt"] == "<4i3q4i"
    assert w["structs"]["HEADER"]["size"] == 56
    assert "".join(w["structs"]["TIMING"]["prims"]) == "qqqqqq"
    assert {k: v[0] for k, v in w["flags"].items()} == {
        "FLAG_TIMING": 8, "FLAG_AUDIT": 16, "FLAG_QOS": 32,
        "_ACCEPT_RAW": 1}
    msg = {k: v[0] for k, v in w["msg"].items()}
    assert msg["RequestGet"] == 1
    assert msg["OpsReply"] == 24
    assert len(msg) >= 11
    # The ops report-kind catalogue (health plane rode in last).
    assert "alerts" in w["ops_kinds"]
    assert "metrics" in w["ops_kinds"]


def test_ops_kinds_cc_extractor():
    cc = mvcontract.extract_ops_kinds_cc(OPS_CC)
    # Every catalogued kind has a native dispatch, alerts included.
    for kind in ("metrics", "health", "tables", "hotkeys", "latency",
                 "audit", "replication", "capacity", "alerts"):
        assert kind in cc["kinds"], kind


# ------------------------------------------------------- extractor: (c)

def test_ctypes_extractor_direct_and_loop_forms():
    b = mvcontract.extract_ctypes_binding(BINDING_PY)
    fns = b["functions"]
    # Every bound symbol carries both an arity and a restype — the
    # extractor handled every assignment form the binding uses.
    assert len(fns) > 80
    assert all(e["arity"] is not None and e["ret"] is not None
               for e in fns.values())
    # List-multiplication arity: [POINTER(c_longlong)] * 7.
    assert fns["MV_ArenaStats"]["arity"] == 7
    # Concat + continuation: [c_int32] + [...] * n.
    assert fns["MV_ReplicationStats"]["arity"] == 8
    # getattr-in-for-loop binding form.
    assert fns["MV_TableVersion"]["arity"] == 2
    # restype kinds.
    assert fns["MV_FreeString"]["ret"] == "void"
    assert fns["MV_DashboardReport"]["ret"] == "charp"
    assert fns["MV_RoutingEpoch"]["ret"] == "longlong"


def test_ctypes_extractor_rc_map():
    b = mvcontract.extract_ctypes_binding(BINDING_PY)
    # _check special-cases the shed and arena rc codes.
    assert set(b["rc_handled"]) == {-6, -7}


# ------------------------------------------------------- extractor: (d)

def test_lua_extractor():
    lua = mvcontract.extract_lua_cdef(LUA)
    fns = lua["functions"]
    assert len(fns) > 80
    assert fns["MV_Init"][:2] == (2, "int")
    assert fns["MV_RoutingEpoch"][:2] == (0, "longlong")
    assert fns["MV_FreeString"][:2] == (1, "void")


# ------------------------------------------------------- extractor: (e)

def test_flag_extractors():
    native = mvcontract.extract_native_flags(CONFIGURE_CC)
    config = mvcontract.extract_config_flags(CONFIG_PY)
    assert native["sync"][0] == "bool" and native["sync"][1] is False
    # Quoted default containing commas must not split the match.
    assert native["qos_classes"][1] == "bulk:1,gold:8"
    assert config["serve_timeout_ms"][1] == 30000.0
    # Dynamic default (os.environ.get) is extracted as unknown.
    assert config["log_level"][1] is None
    assert len(native) > 50 and len(config) > 40


def test_docs_flag_table_extractor(tmp_path):
    md = tmp_path / "x.md"
    md.write_text(
        "Prose.\n\n"
        "| flag | plane | default | effect |\n"
        "|------|-------|---------|--------|\n"
        "| `-alpha` | both | 1 | a |\n"
        "| `-beta=7` | Python | 7 | b |\n\n"
        "| engine | readiness |\n|---|---|\n| epoll | level |\n")
    rows = mvcontract.extract_docs_flags([str(md)])
    assert [(r[2], r[3]) for r in rows] == [
        ("alpha", "both"), ("beta", "python")]
    real = mvcontract.extract_docs_flags(
        [_p("docs/serving.md"), _p("docs/observability.md")])
    assert any(name == "qos_classes" for _, _, name, _ in real)


# ------------------------------------------------------ clean-tree gate

def test_contract_repo_clean():
    """The real tree diffs clean — the tier-1 mirror of
    `make contract`."""
    findings = mvcontract.diff_contract(mvcontract.build_contract(REPO))
    assert findings == [], "\n" + "\n".join(str(f) for f in findings)


def test_checker_is_pure_static():
    """The acceptance bar: no subprocess, no native library load."""
    src = open(mvcontract.__file__, "r", encoding="utf-8").read()
    assert "subprocess" not in src
    assert "CDLL" not in src and "cdll" not in src


def test_main_strict_clean_exit():
    assert mvcontract.main(["--root", REPO]) == 0
    assert mvcontract.main(["--strict", "--root", REPO]) == 0
    assert mvcontract.main(["--no-such-flag"]) == 2


# -------------------------------------------------- seeded-drift matrix

def _findings(**overrides):
    return mvcontract.diff_contract(
        mvcontract.build_contract(REPO, **overrides))


def test_drift_renamed_msgtype(tmp_path):
    p = _seed(tmp_path, MESSAGE_H, "m.h",
              "RequestReplica = 11", "RequestReplicaPull = 11")
    f = _findings(message_h=p)
    assert len(f) == 1
    assert f[0].pair == "message.h<->serve/wire.py"
    assert "RequestReplica" in f[0].msg and f[0].path.endswith("wire.py")


def test_drift_msgtype_value_mismatch(tmp_path):
    p = _seed(tmp_path, MESSAGE_H, "m.h",
              "RequestCancel = 13", "RequestCancel = 14")
    f = _findings(message_h=p)
    assert any("RequestCancel" in x.msg and "13" in x.msg for x in f)


def test_drift_duplicate_msgtype_value(tmp_path):
    p = _seed(tmp_path, MESSAGE_H, "m.h",
              "Heartbeat = 21", "Heartbeat = 20")
    f = _findings(message_h=p)
    assert any("reuses wire value 20" in x.msg for x in f)


def test_drift_wrong_struct_size(tmp_path):
    p = _seed(tmp_path, WIRE_PY, "w.py", '"<6q"', '"<5q"')
    f = _findings(wire_py=p)
    assert any("sizeof(TimingTrail)" in x.msg and "48" in x.msg
               for x in f)
    assert all(x.pair == "message.h<->serve/wire.py" for x in f)


def test_drift_struct_padding_hole(tmp_path):
    # int32 pad -> int64 pad in QosStamp: layout AND sizeof drift (the
    # C side would also misalign, which _c_sizeof models).
    p = _seed(tmp_path, MESSAGE_H, "m.h",
              "int32_t pad = 0;", "int64_t pad = 0;")
    f = _findings(message_h=p)
    assert any("QOS" in x.msg and "QosStamp" in x.msg for x in f)


def test_drift_flag_constant(tmp_path):
    p = _seed(tmp_path, WIRE_PY, "w.py",
              "FLAG_QOS = 1 << 5", "FLAG_QOS = 1 << 6")
    f = _findings(wire_py=p)
    assert any("FLAG_QOS" in x.msg and "kHasQos" in x.msg for x in f)


def test_drift_ctypes_arity(tmp_path):
    p = _seed(tmp_path, BINDING_PY, "b.py",
              "lib.MV_WaitGet.argtypes = [ctypes.c_int32]",
              "lib.MV_WaitGet.argtypes = [ctypes.c_int32, "
              "ctypes.c_int32]")
    f = _findings(binding_py=p)
    assert len(f) == 1
    assert f[0].pair == "c_api.h<->ctypes-binding"
    assert "MV_WaitGet" in f[0].msg and "arity 2" in f[0].msg


def test_drift_unbound_c_api_function(tmp_path):
    # A new C entry point with no Python side: the binding is the
    # primary surface, so the header copy grows a function.
    p = _seed(tmp_path, C_API_H, "c.h",
              "int MV_ShutDown();",
              "int MV_ShutDown();\nint MV_NewEntryPoint(int x);")
    f = _findings(c_api_h=p)
    assert any("MV_NewEntryPoint" in x.msg and "never bound" in x.msg
               for x in f)


def test_drift_ctypes_restype(tmp_path):
    p = _seed(tmp_path, BINDING_PY, "b.py",
              "lib.MV_FreeString.restype = None",
              "lib.MV_FreeString.restype = ctypes.c_int")
    f = _findings(binding_py=p)
    assert any("MV_FreeString" in x.msg and "restype" in x.msg
               for x in f)


def test_drift_binding_rc_not_documented(tmp_path):
    p = _seed(tmp_path, BINDING_PY, "b.py",
              "rc == -6", "rc == -9")
    f = _findings(binding_py=p)
    assert any(x.pair == "c_api.h<->binding-rc-map" and "-9" in x.msg
               for x in f)


def test_drift_lua_arity(tmp_path):
    p = _seed(tmp_path, LUA, "l.lua",
              "int MV_WaitGet(int32_t wait_handle);",
              "int MV_WaitGet(int32_t wait_handle, int32_t x);")
    f = _findings(lua=p)
    assert len(f) == 1
    assert f[0].pair == "c_api.h<->lua-cdef"
    assert "MV_WaitGet" in f[0].msg


def test_drift_flag_missing_from_config(tmp_path):
    # A flag the docs declare plane=both vanishes from config.py:
    # present in C++, missing from Python.
    p = _seed(tmp_path, CONFIG_PY, "c.py",
              'define_bool("wire_timing"', 'define_bool("wire_timing_x"')
    f = _findings(config_py=p)
    assert any("wire_timing" in x.msg and "does not define it" in x.msg
               and x.path.endswith(".md") for x in f)


def test_drift_flag_default_mismatch(tmp_path):
    p = _seed(tmp_path, CONFIG_PY, "c.py",
              'define_bool("sync", False', 'define_bool("sync", True')
    f = _findings(config_py=p)
    assert any(x.pair == "configure.cc<->config.py"
               and "-sync" in x.msg for x in f)


def test_drift_docs_dead_flag(tmp_path):
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "stale.md").write_text(
        "| flag | plane | default | effect |\n|---|---|---|---|\n"
        "| `-retired_flag` | both | 0 | long gone |\n")
    f = _findings(docs=str(docs))
    assert len(f) == 1
    assert "dead flag" in f[0].msg and f[0].path.endswith("stale.md")
    assert f[0].line == 3


def test_drift_ops_kind_missing_native_dispatch(tmp_path):
    # OPS_KINDS names a kind ops.cc stopped dispatching.
    p = _seed(tmp_path, OPS_CC, "ops.cc",
              'kind == "alerts"', 'kind == "alertz"')
    f = _findings(ops_cc=p)
    assert any(x.pair == "serve/wire.py<->ops.cc"
               and "'alerts'" in x.msg
               and "unknown-kind error" in x.msg for x in f)


def test_drift_ops_kind_missing_from_catalogue(tmp_path):
    # ops.cc dispatches a kind the wire catalogue does not list.
    p = _seed(tmp_path, WIRE_PY, "w.py", '"audit", "replication"',
              '"replication"')
    f = _findings(wire_py=p)
    assert any(x.pair == "serve/wire.py<->ops.cc"
               and '"audit"' in x.msg
               and "OPS_KINDS does not list it" in x.msg for x in f)


def test_strict_exit_on_seeded_drift(tmp_path, capsys):
    p = _seed(tmp_path, WIRE_PY, "w.py", '"<6q"', '"<5q"')
    rc = mvcontract.main(
        ["--strict", "--root", REPO, "--wire-py", p])
    assert rc == 1
    out = capsys.readouterr()
    assert "TIMING" in out.out
    # Without --strict the findings print but the exit stays 0 (report
    # mode for triage).
    assert mvcontract.main(["--root", REPO, "--wire-py", p]) == 0
