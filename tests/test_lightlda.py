"""LightLDA app tests: count conservation + topic recovery on planted data
(SURVEY.md §2.36 — the sparse-table async-Add flagship)."""

import numpy as np
import pytest


def _counts_consistent(lda, docs, doc_topic):
    """Global invariants: word-topic totals == topic sums == token count."""
    wt = lda.word_topic.get()
    ts = lda.topic_sum.get()
    n_tokens = int((docs != -1).sum())
    assert abs(wt.sum() - n_tokens) < 1e-3
    np.testing.assert_allclose(wt.sum(axis=0), ts, atol=1e-3)
    np.testing.assert_allclose(doc_topic.sum(), n_tokens, atol=1e-3)


def test_lda_init_counts_consistent(mv):
    mv.init()
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    docs, _ = synthetic_documents(20, 50, 5, doc_len=30, seed=0)
    lda = LightLDA(50, 5)
    dt = lda.initialize_counts(docs, seed=0)
    _counts_consistent(lda, docs, dt)


def test_lda_parity_pass_preserves_counts(mv):
    mv.init()
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    docs, _ = synthetic_documents(10, 30, 3, doc_len=20, seed=1)
    lda = LightLDA(30, 3)
    dt = lda.initialize_counts(docs, seed=1)
    dt = lda.sample_pass(docs, dt, seed=1)
    _counts_consistent(lda, docs, dt)


def test_lda_fused_pass_preserves_counts(mv):
    mv.init()
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    docs, _ = synthetic_documents(16, 40, 4, doc_len=32, seed=2)
    lda = LightLDA(40, 4)
    dt = lda.initialize_counts(docs, seed=2)
    for _ in range(3):
        dt = lda.run_fused_pass(docs, dt)
    _counts_consistent(lda, docs, dt)


def test_lda_fused_recovers_planted_topics(mv):
    """Blocked-Gibbs sweeps on well-separated synthetic topics must beat
    random assignment by a wide margin."""
    mv.init()
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    K = 4
    docs, true_topics = synthetic_documents(60, 80, K, doc_len=48, seed=3,
                                            concentration=0.05)
    lda = LightLDA(80, K, alpha=0.5, beta=0.1, seed=3)
    dt = lda.initialize_counts(docs, seed=3)
    for _ in range(15):
        dt = lda.run_fused_pass(docs, dt)
    purity = lda.topic_purity(docs, true_topics, dt)
    assert purity > 0.6, purity   # random ≈ 1/K = 0.25


def test_lda_works_under_bsp_runtime(mv):
    """LDA pins async adds; a sync=True runtime must not starve its counts."""
    mv.init(sync=True)
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    docs, _ = synthetic_documents(10, 30, 3, doc_len=20, seed=4)
    lda = LightLDA(30, 3)
    dt = lda.initialize_counts(docs, seed=4)
    _counts_consistent(lda, docs, dt)
    dt = lda.run_fused_pass(docs, dt)
    _counts_consistent(lda, docs, dt)
