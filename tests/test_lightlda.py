"""LightLDA app tests: count conservation + topic recovery on planted data
(SURVEY.md §2.36 — the sparse-table async-Add flagship)."""

import numpy as np
import pytest


def _counts_consistent(lda, docs, doc_topic):
    """Global invariants: word-topic totals == topic sums == token count."""
    wt = lda.word_topic.get()
    ts = lda.topic_sum.get()
    n_tokens = int((docs != -1).sum())
    assert abs(wt.sum() - n_tokens) < 1e-3
    np.testing.assert_allclose(wt.sum(axis=0), ts, atol=1e-3)
    np.testing.assert_allclose(doc_topic.sum(), n_tokens, atol=1e-3)


def test_lda_init_counts_consistent(mv):
    mv.init()
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    docs, _ = synthetic_documents(20, 50, 5, doc_len=30, seed=0)
    lda = LightLDA(50, 5)
    dt = lda.initialize_counts(docs, seed=0)
    _counts_consistent(lda, docs, dt)


def test_lda_parity_pass_preserves_counts(mv):
    mv.init()
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    docs, _ = synthetic_documents(10, 30, 3, doc_len=20, seed=1)
    lda = LightLDA(30, 3)
    dt = lda.initialize_counts(docs, seed=1)
    dt = lda.sample_pass(docs, dt, seed=1)
    _counts_consistent(lda, docs, dt)


def test_lda_fused_pass_preserves_counts(mv):
    mv.init()
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    docs, _ = synthetic_documents(16, 40, 4, doc_len=32, seed=2)
    lda = LightLDA(40, 4)
    dt = lda.initialize_counts(docs, seed=2)
    for _ in range(3):
        dt = lda.run_fused_pass(docs, dt)
    _counts_consistent(lda, docs, dt)


def test_lda_fused_recovers_planted_topics(mv):
    """Blocked-Gibbs sweeps on well-separated synthetic topics must beat
    random assignment by a wide margin."""
    mv.init()
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    K = 4
    docs, true_topics = synthetic_documents(60, 80, K, doc_len=48, seed=3,
                                            concentration=0.05)
    lda = LightLDA(80, K, alpha=0.5, beta=0.1, seed=3)
    dt = lda.initialize_counts(docs, seed=3)
    for _ in range(15):
        dt = lda.run_fused_pass(docs, dt)
    purity = lda.topic_purity(docs, true_topics, dt)
    assert purity > 0.6, purity   # random ≈ 1/K = 0.25


def test_lda_mh_pass_preserves_counts(mv):
    mv.init()
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    docs, _ = synthetic_documents(16, 40, 4, doc_len=32, seed=5)
    lda = LightLDA(40, 4)
    dt = lda.initialize_counts(docs, seed=5)
    for _ in range(3):
        dt = lda.run_mh_pass(docs, dt)
    _counts_consistent(lda, docs, dt)


def test_lda_mh_pass_preserves_counts_with_padding(mv):
    mv.init()
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    docs, _ = synthetic_documents(12, 30, 3, doc_len=24, seed=6)
    docs[::3, 17:] = -1          # ragged docs: PAD tails
    docs[5, :] = -1              # one fully-empty doc
    lda = LightLDA(30, 3)
    dt = lda.initialize_counts(docs, seed=6)
    for _ in range(3):
        dt = lda.run_mh_pass(docs, dt)
    _counts_consistent(lda, docs, dt)


def test_lda_mh_recovers_planted_topics(mv):
    """The MH sampler must converge like the dense-Gibbs kernel does."""
    mv.init()
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    K = 4
    docs, true_topics = synthetic_documents(60, 80, K, doc_len=48, seed=7,
                                            concentration=0.05)
    lda = LightLDA(80, K, alpha=0.5, beta=0.1, seed=7)
    dt = lda.initialize_counts(docs, seed=7)
    for _ in range(25):
        dt = lda.run_mh_pass(docs, dt, mh_steps=4)
    purity = lda.topic_purity(docs, true_topics, dt)
    assert purity > 0.6, purity   # random ≈ 1/K = 0.25


def test_lda_mh_handles_large_K(mv):
    """K=1024 correctness smoke: the MH pass must preserve the count
    invariants at a K far beyond the dense kernel's comfort zone.  (At
    this tiny D·L the avoided [D, L, K] tensor is only megabytes — the
    *memory/throughput* regime is exercised by bench_lightlda_mh at
    K=8192 on real hardware; this test guards the math.)"""
    mv.init()
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    K = 1024
    docs, _ = synthetic_documents(32, 512, 16, doc_len=20, seed=8)
    lda = LightLDA(512, K)
    dt = lda.initialize_counts(docs, seed=8)
    dt = lda.run_mh_pass(docs, dt)
    _counts_consistent(lda, docs, dt)


def test_table_close_releases_name_and_registry(mv):
    """close() unregisters (the name becomes reusable) and drops buffers."""
    mv.init()
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    docs, _ = synthetic_documents(4, 20, 2, doc_len=8, seed=9)
    lda = LightLDA(20, 2, name="closable")
    lda.initialize_counts(docs, seed=9)
    lda.close()
    lda2 = LightLDA(20, 2, name="closable")   # same name must not collide
    dt2 = lda2.initialize_counts(docs, seed=9)
    _counts_consistent(lda2, docs, dt2)


def test_lda_works_under_bsp_runtime(mv):
    """LDA pins async adds; a sync=True runtime must not starve its counts."""
    mv.init(sync=True)
    from multiverso_tpu.apps import LightLDA, synthetic_documents

    docs, _ = synthetic_documents(10, 30, 3, doc_len=20, seed=4)
    lda = LightLDA(30, 3)
    dt = lda.initialize_counts(docs, seed=4)
    _counts_consistent(lda, docs, dt)
    dt = lda.run_fused_pass(docs, dt)
    _counts_consistent(lda, docs, dt)
