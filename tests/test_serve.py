"""Serve-layer tests (docs/serving.md): versioned client cache, request
coalescer, ServeClient over the native wire, and the busy-shed/retry
protocol.

Three tiers:

1. pure-unit (cache + coalescer mechanics — no runtime at all);
2. JAX-plane tables with the serve cache armed (the ``mv`` fixture);
3. the native ``ServeClient`` (g++-gated) — version protocol, probe
   economics, chaos seams (``serve.busy`` / ``serve.stale``).

The 2-process wire acceptance (8 concurrent gets in <= 2 round trips,
zero-wire cache hits, busy-shed convergence under chaos) lives in
``tools/serve_demo.py`` and runs here g++-gated.
"""

import os
import shutil
import subprocess
import sys
import threading

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- cache unit

def _fresh_metrics():
    from multiverso_tpu import metrics

    metrics.reset()
    return metrics


def test_cache_version_gating_and_lru_bound():
    from multiverso_tpu.serve import VersionedLRUCache

    _fresh_metrics()
    c = VersionedLRUCache(max_entries=2)
    c.store(("t", 1), np.ones(3), version=5)
    assert c.lookup(("t", 1), min_version=5)[1] == 5
    assert c.lookup(("t", 1), min_version=6) is None      # too stale
    assert c.lookup(("t", 1), min_version=4)[1] == 5      # within bound
    # A racing slow fetch may not roll a fresher entry back.
    c.store(("t", 1), np.zeros(3), version=3)
    assert c.lookup(("t", 1), min_version=None)[1] == 5
    # Hard LRU bound: the eldest entry falls out.
    c.store(("t", 2), np.ones(1), version=1)
    c.store(("t", 3), np.ones(1), version=1)
    assert len(c) == 2
    assert c.lookup(("t", 1), min_version=None) is None   # evicted (LRU)
    assert c.stats()["evictions"] == 1


def test_cache_prefix_invalidation():
    from multiverso_tpu.serve import VersionedLRUCache

    c = VersionedLRUCache(max_entries=8)
    c.store((7, "array", 16), 1, version=1)
    c.store((7, "rows", (1, 2)), 2, version=1)
    c.store((8, "array", 16), 3, version=1)
    assert c.invalidate(7) == 2            # handle 7's entries only
    assert c.lookup((8, "array", 16), min_version=None) is not None
    assert c.invalidate() == 1             # full clear
    assert len(c) == 0


def test_cache_rejects_nonpositive_bound():
    from multiverso_tpu.serve import VersionedLRUCache

    with pytest.raises(ValueError):
        VersionedLRUCache(max_entries=0)


# ------------------------------------------------------------ coalescer unit

def test_coalescer_merges_concurrent_submits():
    from multiverso_tpu.serve import Coalescer

    co = Coalescer(window_s=0.05, max_batch=64)
    calls = []
    done = threading.Barrier(8)

    def execute(items):
        calls.append(list(items))
        return [i * 10 for i in items]

    out = [None] * 8

    def go(i):
        done.wait()                      # release all 8 together
        out[i] = co.submit("k", i, execute)

    ts = [threading.Thread(target=go, args=(i,)) for i in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert out == [i * 10 for i in range(8)]     # each got ITS result
    assert len(calls) <= 2                        # merged, not 8 fetches
    assert sum(len(c) for c in calls) == 8


def test_coalescer_size_cap_seals_early():
    from multiverso_tpu.serve import Coalescer

    co = Coalescer(window_s=5.0, max_batch=2)    # window too long to wait
    calls = []

    def execute(items):
        calls.append(list(items))
        return items

    out = [None] * 2

    def go(i):
        out[i] = co.submit("k", i, execute)

    ts = [threading.Thread(target=go, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=3.0)              # full batch must NOT wait 5 s
    assert out[0] is not None and out[1] is not None
    assert len(calls) == 1 and len(calls[0]) == 2


def test_coalescer_failure_fans_out_and_result_count_checked():
    from multiverso_tpu.serve import Coalescer

    co = Coalescer(window_s=0.0, max_batch=4)

    def boom(items):
        raise RuntimeError("wire died")

    with pytest.raises(RuntimeError, match="wire died"):
        co.submit("k", 0, boom)

    with pytest.raises(RuntimeError, match="results"):
        co.submit("k", 0, lambda items: [])   # wrong result arity


# ---------------------------------------------------- client unit (stub rt)

class _StubRT:
    """Duck-typed NativeRuntime: enough surface for ServeClient reads."""

    def __init__(self):
        self.version = 1
        self.gets = 0

    def last_version(self, handle):
        return self.version

    def table_version(self, handle):
        return self.version

    def array_get(self, handle, size):
        self.gets += 1
        return np.full(size, 7.0, np.float32)


def test_client_coalesced_waiters_get_private_copies():
    """Regression: every coalesced waiter of one wire fetch used to get
    the SAME ndarray — one caller mutating its result corrupted every
    sibling's.  Each waiter must own a private copy (like the hit path).
    """
    from multiverso_tpu.serve import ServeClient

    _fresh_metrics()
    c = ServeClient(_StubRT(), cache_entries=8, max_staleness=0,
                    window_us=20000, lease_ms=60000)
    out = [None] * 4
    start = threading.Barrier(4)

    def go(i):
        start.wait()
        a = c.array_get(1, 8)
        a[:] = float(i)              # caller-owned: must not leak out
        out[i] = a

    ts = [threading.Thread(target=go, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for i in range(4):
        np.testing.assert_allclose(out[i], float(i))
    np.testing.assert_allclose(c.array_get(1, 8), 7.0)  # cache unpoisoned


def test_client_disabled_cache_counts_no_misses():
    """serve_cache_entries=0: no cache exists, so stats must not accrue
    a growing miss count (and no version probes fire either)."""
    from multiverso_tpu import metrics
    from multiverso_tpu.serve import ServeClient

    _fresh_metrics()
    rt = _StubRT()
    c = ServeClient(rt, cache_entries=0, max_staleness=0)
    np.testing.assert_allclose(c.array_get(1, 4), 7.0)
    np.testing.assert_allclose(c.array_get(1, 4), 7.0)
    assert rt.gets == 2                       # every read pays the wire
    assert metrics.counter("serve.cache.miss").value == 0
    assert metrics.counter("serve.probe").value == 0


# ------------------------------------------------- JAX-plane table caching

def test_table_cache_hit_and_write_through_invalidation(mv):
    from multiverso_tpu import metrics

    mv.init()
    metrics.reset()
    t = mv.ArrayTable(16, name="srv_a", serve_cache=16, max_staleness=0)
    t.add(np.ones(16, np.float32))
    np.testing.assert_allclose(t.get(), 1.0)        # miss -> cached
    h0 = metrics.counter("serve.cache.hit").value
    got = t.get()                                    # repeat read: hit
    np.testing.assert_allclose(got, 1.0)
    assert metrics.counter("serve.cache.hit").value == h0 + 1
    # The hit hands back a COPY: caller mutation can't poison the cache.
    got[:] = 99.0
    np.testing.assert_allclose(t.get(), 1.0)    # second hit (h0 + 2)
    # Local add bumps the version -> stale entry misses (never stale
    # at max_staleness=0), fresh value lands and re-caches.
    t.add(np.ones(16, np.float32))
    np.testing.assert_allclose(t.get(), 2.0)
    hits_after = metrics.counter("serve.cache.hit").value
    assert hits_after == h0 + 2                 # the fresh read was a miss
    np.testing.assert_allclose(t.get(), 2.0)
    assert metrics.counter("serve.cache.hit").value == hits_after + 1


def test_table_max_staleness_window(mv):
    mv.init()
    t = mv.ArrayTable(8, name="srv_b", serve_cache=16, max_staleness=1)
    t.add(np.ones(8, np.float32))
    np.testing.assert_allclose(t.get(), 1.0)        # cached at v1
    t.add(np.ones(8, np.float32))                    # v2: within bound
    np.testing.assert_allclose(t.get(), 1.0)        # documented stale HIT
    t.add(np.ones(8, np.float32))                    # v3: bound exceeded
    np.testing.assert_allclose(t.get(), 3.0)        # fresh


def test_table_serve_disabled_by_default(mv):
    from multiverso_tpu import metrics

    mv.init()
    metrics.reset()
    t = mv.ArrayTable(8, name="srv_off")
    t.add(np.ones(8, np.float32))
    np.testing.assert_allclose(t.get(), 1.0)
    np.testing.assert_allclose(t.get(), 1.0)
    assert metrics.counter("serve.cache.hit").value == 0


def test_matrix_bucket_granularity(mv):
    from multiverso_tpu import metrics

    mv.init()
    metrics.reset()
    m = mv.MatrixTable(256, 4, name="srv_m", serve_cache=32)
    m.add_rows(np.array([1]), np.ones((1, 4), np.float32))
    np.testing.assert_allclose(m.get_rows(np.array([1]))[0], 1.0)
    h0 = metrics.counter("serve.cache.hit").value
    m.get_rows(np.array([1]))                        # hit
    assert metrics.counter("serve.cache.hit").value == h0 + 1
    # Row 70 lives in bucket 6; row 1's entry (bucket 1) must survive.
    m.add_rows(np.array([70]), np.ones((1, 4), np.float32))
    m.get_rows(np.array([1]))                        # still a hit
    assert metrics.counter("serve.cache.hit").value == h0 + 2
    # Row 65 shares bucket 1 -> invalidates row 1's entry.
    m.add_rows(np.array([65]), np.ones((1, 4), np.float32))
    m.get_rows(np.array([1]))                        # miss
    assert metrics.counter("serve.cache.hit").value == h0 + 2


def test_lazy_buckets_inherit_whole_table_version(mv):
    """Regression: the bucket array is created lazily on the FIRST
    bucket-granular bump.  Whole-table bumps (dense adds) that ran while
    it was None must stay visible — seeding the new array with zeros
    instead of the pre-bump version would let entries cached BEFORE
    those dense adds hit forever (a stale serve at max_staleness=0)."""
    mv.init()
    m = mv.MatrixTable(256, 4, name="srv_lz", serve_cache=32,
                       max_staleness=0)
    m.add(np.ones((256, 4), np.float32))             # whole-table bump
    np.testing.assert_allclose(m.get_rows(np.array([1]))[0], 1.0)  # cached
    m.add(np.ones((256, 4), np.float32))             # bump w/ buckets None
    # First bucket-granular bump (row 70, bucket 6) materializes the
    # bucket array; bucket 1 must inherit the dense-add version.
    m.add_rows(np.array([70]), np.ones((1, 4), np.float32))
    np.testing.assert_allclose(m.get_rows(np.array([1]))[0], 2.0)


def test_kv_bucket_granularity_and_copy_safety(mv):
    from multiverso_tpu import metrics
    from multiverso_tpu.tables.base import Table

    mv.init()
    metrics.reset()
    kv = mv.KVTable(value_shape=(2,), name="srv_kv", serve_cache=32)
    kv.add({"a": np.ones(2)})
    g = kv.get(["a"])
    np.testing.assert_allclose(g["a"], 1.0)
    h0 = metrics.counter("serve.cache.hit").value
    g2 = kv.get(["a"])                               # hit
    assert metrics.counter("serve.cache.hit").value == h0 + 1
    g2["a"][:] = 99.0                                # mutate the copy
    np.testing.assert_allclose(kv.get(["a"])["a"], 1.0)
    # raw() contract survives the serve cache: a HIT skips fetch(), but
    # the mirror must still hold every key the app Get()s.
    kv.raw.clear()
    kv.get(["a"])                                    # hit — no fetch
    np.testing.assert_allclose(kv.raw["a"], 1.0)
    # A key in a DIFFERENT bucket leaves "a"'s entry valid.
    other = next(k for k in (f"k{i}" for i in range(200))
                 if Table.serve_key_bucket(k) != Table.serve_key_bucket("a"))
    kv.add({other: np.ones(2)})
    kv.get(["a"])                                    # still a hit
    assert metrics.counter("serve.cache.hit").value >= h0 + 2
    kv.add({"a": np.ones(2)})                        # same bucket: miss
    np.testing.assert_allclose(kv.get(["a"])["a"], 2.0)


def test_concurrent_gets_coalesce_to_one_fetch(mv):
    from multiverso_tpu import metrics

    mv.init()
    metrics.reset()
    t = mv.ArrayTable(1024, name="srv_c", serve_cache=16)
    t.add(np.ones(1024, np.float32))
    res = [None] * 8
    start = threading.Barrier(8)

    def go(i):
        start.wait()
        res[i] = t.get()

    ts = [threading.Thread(target=go, args=(i,)) for i in range(8)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    assert all(r[0] == 1.0 for r in res)
    # 8 logical gets -> very few actual fetches: misses count fetch
    # attempts, the coalesce histogram shows the batching.
    h = metrics.histogram("serve.coalesce.batch")
    assert h.count >= 1
    assert h.count + int(metrics.counter("serve.cache.hit").value) <= 8
    assert h.sum >= 8 - int(metrics.counter("serve.cache.hit").value)


def test_serve_stale_chaos_seam_forces_miss(mv):
    from multiverso_tpu import fault, metrics

    mv.init()
    metrics.reset()
    t = mv.ArrayTable(8, name="srv_f", serve_cache=16)
    t.add(np.ones(8, np.float32))
    t.get()                                          # cached
    fault.configure(sites={"serve.stale": {"times": 1}})
    try:
        m0 = metrics.counter("serve.cache.miss").value
        np.testing.assert_allclose(t.get(), 1.0)     # forced miss
        assert metrics.counter("serve.cache.miss").value == m0 + 1
        assert fault.count("fault.serve.stale") == 1
        h0 = metrics.counter("serve.cache.hit").value
        t.get()                                      # seam disarmed: hit
        assert metrics.counter("serve.cache.hit").value == h0 + 1
    finally:
        fault.reset()


# ------------------------------------------------------- native ServeClient

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")


@pytest.fixture(scope="module")
def srt():
    from multiverso_tpu import native as nat

    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    nat.ensure_built()
    rt = nat.NativeRuntime(args=["-updater_type=default",
                                 "-log_level=error"])
    yield rt
    rt.shutdown()


@needs_gxx
def test_native_version_protocol(srt):
    h = srt.new_array_table(16)
    assert srt.table_version(h) == 0
    srt.array_add(h, np.ones(16, np.float32))
    assert srt.table_version(h) == 1
    assert srt.last_version(h) == 1          # blocking-add ack stamped it
    srt.array_add(h, np.ones(16, np.float32))
    srt.array_get(h, 16)
    assert srt.last_version(h) == 2
    assert srt.serve_queue_depth() >= 0
    hits, misses = srt.cache_stats()
    assert hits >= 0 and misses >= 0


@needs_gxx
def test_serve_client_cache_skips_wire(srt):
    from multiverso_tpu import metrics
    from multiverso_tpu.serve import ServeClient

    metrics.reset()
    c = ServeClient(srt, cache_entries=32, max_staleness=0, lease_ms=60000)
    h = srt.new_array_table(32)
    srt.array_add(h, np.ones(32, np.float32))
    np.testing.assert_allclose(c.array_get(h, 32), 1.0)   # miss -> cached
    wire0 = srt.query_monitor("ArrayWorker::Get")
    probes0 = metrics.counter("serve.probe").value
    for _ in range(5):
        np.testing.assert_allclose(c.array_get(h, 32), 1.0)
    assert srt.query_monitor("ArrayWorker::Get") == wire0  # ZERO wire gets
    assert metrics.counter("serve.probe").value == probes0  # lease held
    assert metrics.counter("serve.cache.hit").value >= 5
    # Write-through: the client's own add invalidates + re-learns.
    c.array_add(h, np.ones(32, np.float32))
    np.testing.assert_allclose(c.array_get(h, 32), 2.0)


@needs_gxx
def test_serve_client_probe_instead_of_fetch(srt):
    """lease_ms=0 + max_staleness=0: every cached read pays one cheap
    version probe and NEVER serves stale — the full fetch only reruns
    when the version really moved."""
    from multiverso_tpu import metrics
    from multiverso_tpu.serve import ServeClient

    metrics.reset()
    c = ServeClient(srt, cache_entries=32, max_staleness=0, lease_ms=0)
    h = srt.new_array_table(8)
    srt.array_add(h, np.ones(8, np.float32))
    np.testing.assert_allclose(c.array_get(h, 8), 1.0)
    wire0 = srt.query_monitor("ArrayWorker::Get")
    np.testing.assert_allclose(c.array_get(h, 8), 1.0)    # probe + hit
    assert srt.query_monitor("ArrayWorker::Get") == wire0
    assert metrics.counter("serve.probe").value >= 2
    # An out-of-band add (not via the client) MUST be seen: the probe
    # reveals the bump, the stale entry misses, the fetch reruns.
    srt.array_add(h, np.ones(8, np.float32))
    np.testing.assert_allclose(c.array_get(h, 8), 2.0)
    assert srt.query_monitor("ArrayWorker::Get") == wire0 + 1


@needs_gxx
def test_serve_client_busy_retry(srt):
    """Scripted shed storm: serve.busy raises BusyError twice; the
    client's RetryPolicy backs off and converges."""
    from multiverso_tpu import fault, metrics
    from multiverso_tpu.native import BusyError
    from multiverso_tpu.serve import ServeClient

    metrics.reset()
    c = ServeClient(srt, cache_entries=32)
    h = srt.new_array_table(8)
    srt.array_add(h, np.ones(8, np.float32))
    fault.configure(sites={"serve.busy": {"times": 2, "error": BusyError}})
    try:
        np.testing.assert_allclose(c.array_get(h, 8), 1.0)
        assert fault.count("retry.attempts") >= 2
    finally:
        fault.reset()


@needs_gxx
def test_serve_client_rows_union(srt):
    from multiverso_tpu.serve import ServeClient

    c = ServeClient(srt, cache_entries=32, window_us=20000)
    hm = srt.new_matrix_table(64, 4)
    srt.matrix_add_rows(hm, [1, 2, 3], np.ones((3, 4), np.float32))
    wire0 = srt.query_monitor("MatrixWorker::GetRows")
    res = [None] * 8
    start = threading.Barrier(8)

    def go(i):
        start.wait()
        res[i] = c.matrix_get_rows(hm, [i % 4], 4)

    ts = [threading.Thread(target=go, args=(i,)) for i in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for i in range(8):
        want = 1.0 if i % 4 in (1, 2, 3) else 0.0
        np.testing.assert_allclose(res[i][0], want)
    # 8 concurrent row reads -> at most 2 wire round trips.
    assert srt.query_monitor("MatrixWorker::GetRows") - wire0 <= 2


# ----------------------------------------------------- 2-process acceptance

@needs_gxx
def test_serve_demo_two_process():
    """The acceptance demo (make serve-demo): coalescing <= 2 round
    trips for 8 concurrent gets, zero-wire cache hits, and busy-shed
    retry convergence with no lost adds under -server_inflight_max=1 +
    chaos."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_demo.py")],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=REPO)
    assert out.returncode == 0, out.stdout[-4000:] + out.stderr[-2000:]
    assert "SERVE_DEMO_OK" in out.stdout, out.stdout[-2000:]
