"""Tail-at-scale serve-tier tests (docs/serving.md "tail").

Covers the three tentpole layers end to end on a live 2-rank epoll
fleet plus the pure-Python mirrors:

- QoS wire stamp: pack/unpack round trip, composition with the timing
  trail + audit stamp, and version tolerance (an unstamped frame is
  byte-identical to the pre-13 layout);
- per-tenant weighted admission: a bulk herd at its class budget is
  shed with ReplyBusy at the reactor while gold reads keep flowing
  (per-class counters prove which gate fired);
- deadline propagation: a 1 ns-budget get is dropped (no reply, no
  apply slot) and counted serve.deadline.shed;
- hedged reads: under a seeded ``apply_delay`` straggler the replica
  hedge wins at the reactor, the loser's cancel token drops it at
  dequeue, values are exact, and the PR 12 audit plane confirms zero
  lost or duplicated acked adds — plus the disarmed-hedge control;
- the RLIMIT_NOFILE degrade satellite, the -serve_timeout_ms satellite,
  and the mvtop --qos / latdoctor deadline-note surfaces.
"""

import json
import os
import shutil
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from multiverso_tpu.serve.hedge import HedgedReader, LatencyTracker  # noqa: E402
from multiverso_tpu.serve.wire import (AnonServeClient,  # noqa: E402
                                       FLAG_QOS, HEADER, MSG,
                                       pack_frame, qos_id, unpack_frame)

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


# ------------------------------------------------------------- pure mirrors

def test_qos_stamp_roundtrip():
    frame = pack_frame(MSG["RequestGet"], 3, 17, qos=(1, 250_000_000))
    body = frame[8:]
    reply = unpack_frame(body)
    assert reply["flags"] & FLAG_QOS
    assert reply["qos"] == (1, 250_000_000)
    assert reply["table_id"] == 3 and reply["msg_id"] == 17


def test_qos_composes_with_timing_and_audit():
    frame = pack_frame(MSG["RequestGet"], 0, 5, timing=True,
                       audit=(7, 12), qos=(0, 999), blobs=[b"abcd"])
    reply = unpack_frame(frame[8:])
    assert reply["timing"] is not None
    assert reply["audit"] == (7, 12)
    assert reply["qos"] == (0, 999)
    assert reply["blobs"] == [b"abcd"]


def test_unstamped_frame_is_pre13_byte_identical():
    """Version tolerance: no qos kwarg -> the exact pre-13 layout."""
    frame = pack_frame(MSG["RequestVersion"], 2, 9)
    expected = HEADER.pack(-1, -1, MSG["RequestVersion"], 2, 9, 0, -1,
                           0, 1, 0, 0)
    assert frame[8:] == expected
    assert unpack_frame(frame[8:])["qos"] is None


def test_qos_id_mapping():
    assert qos_id("bulk") == 0
    assert qos_id("gold") == 1
    assert qos_id(3) == 3
    with pytest.raises(ValueError):
        qos_id("platinum")


def test_latency_tracker_hedge_delay():
    t = LatencyTracker()
    assert t.hedge_delay(0.002) == 0.002      # no samples: the floor
    for ms in range(1, 101):
        t.observe(ms * 1e-3)
    assert 0.090 <= t.hedge_delay(0.002) <= 0.101   # ~p95
    assert t.hedge_delay(0.5) == 0.5          # floor still wins


def test_serve_timeout_flag_drives_default(monkeypatch):
    """Satellite: AnonServeClient's default timeout is the
    -serve_timeout_ms flag, not a hard-coded 30 s."""
    from multiverso_tpu import config

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    def accept_one():
        try:
            srv.accept()
        except OSError:
            pass  # listener closed at teardown before/while accepting

    t = threading.Thread(target=accept_one, daemon=True)
    t.start()
    old = config.get("serve_timeout_ms")
    try:
        config.set_flag("serve_timeout_ms", 5000)
        c = AnonServeClient(f"127.0.0.1:{port}")
        assert c.sock.gettimeout() == pytest.approx(5.0)
        assert c.timeout == pytest.approx(5.0)
        c.close()
    finally:
        config.set_flag("serve_timeout_ms", old)
        srv.close()


def test_fd_budget_degrades_with_reason(monkeypatch, capsys):
    """Satellite: a low-ulimit host degrades the herd (10k -> what
    fits) with a logged reason instead of dying with EMFILE."""
    import resource

    from multiverso_tpu.apps import fanin_bench_worker as fw

    monkeypatch.setattr(resource, "getrlimit", lambda _r: (1024, 1024))

    def deny(_r, _lim):
        raise ValueError("hard limit exceeded")

    monkeypatch.setattr(resource, "setrlimit", deny)
    got = fw._fd_budget(10000)
    assert got == 1024 - 256
    out = capsys.readouterr().out
    assert "degrading herd" in out and "10000" in out
    # A limit that already covers the ask passes through untouched.
    monkeypatch.setattr(resource, "getrlimit", lambda _r: (65536, 65536))
    assert fw._fd_budget(10000) == 10000


# ------------------------------------------------------------ fleet harness

def _machine_file(tmp_path, n=2):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    eps = [f"127.0.0.1:{s.getsockname()[1]}" for s in socks]
    for s in socks:
        s.close()
    mf = tmp_path / "machines.txt"
    mf.write_text("".join(e + "\n" for e in eps))
    return str(mf), eps


class TailFleet:
    """Two epoll ranks running tests/tail_worker.py: table 0 = 64 ones,
    table 1 = a 32x4 matrix with row i == i+1, stdin command channel."""

    def __init__(self, tmp_path, extra=(), env_extra=None):
        from multiverso_tpu import native as nat

        nat.ensure_built()
        self.mf, self.endpoints = _machine_file(tmp_path, 2)
        worker = os.path.join(REPO, "tests", "tail_worker.py")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        env.update(env_extra or {})
        self.procs = [
            subprocess.Popen(
                [sys.executable, worker, self.mf, str(r), *extra],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True, env=env)
            for r in range(2)
        ]
        for p in self.procs:
            line = p.stdout.readline()
            assert "SERVE_READY" in line, line

    def cmd(self, text, rank=0) -> str:
        """Send one command to a rank; returns the lines before its OK
        ack (e.g. the MON answer)."""
        p = self.procs[rank]
        p.stdin.write(text + "\n")
        p.stdin.flush()
        out = []
        while True:
            line = p.stdout.readline()
            assert line, "worker died"
            if line.startswith("OK "):
                return "".join(out)
            out.append(line)

    def monitor(self, name, rank=0) -> int:
        ans = self.cmd(f"mon {name}", rank=rank)
        return int(ans.split("=", 1)[1])

    def release(self):
        outs = []
        for p in self.procs:
            try:
                p.stdin.write("done\n")
                p.stdin.flush()
            except OSError:
                pass
        for p in self.procs:
            outs.append(p.communicate(timeout=120)[0])
        for r, (p, out) in enumerate(zip(self.procs, outs)):
            assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
            assert f"SERVE_WORKER_OK {r}" in out, out[-2000:]
        return outs

    def kill(self):
        for p in self.procs:
            if p.poll() is None:
                p.kill()


# --------------------------------------------------- QoS weighted admission

def test_qos_admission_sheds_bulk_keeps_gold(tmp_path):
    """The tentpole acceptance shape: with -qos_inflight_max=8 and a
    sleeping apply (every get naps), a bulk client pushing past its
    1-slot class budget is answered ReplyBusy AT THE REACTOR while a
    gold client's reads are all admitted and served."""
    fleet = TailFleet(tmp_path,
                      extra=("-qos_classes=bulk:1,gold:8",
                             "-qos_inflight_max=8"),
                      env_extra={"MVTPU_FAULT_DELAY_MS": "60"})
    try:
        ep = fleet.endpoints[0]
        # Every get naps 60 ms at apply: admitted reads pile up inflight
        # so the class budgets actually bind.
        fleet.cmd("fault_rate apply_delay 1.0")
        bulk = AnonServeClient(ep, timeout=30.0, qos_class="bulk")
        gold = AnonServeClient(ep, timeout=30.0, qos_class="gold")
        # 6 concurrent bulk gets: 1 guaranteed slot + deficit borrowing
        # (weight 1 of quantum 8) cannot cover them.
        for i in range(6):
            bulk.send_raw(pack_frame(MSG["RequestGet"], 0, 100 + i,
                                     qos=bulk._qos()))
        # 4 concurrent gold gets: inside gold's 7-slot guaranteed share.
        for i in range(4):
            gold.send_raw(pack_frame(MSG["RequestGet"], 0, 200 + i,
                                     qos=gold._qos()))
        counts = {"bulk": {}, "gold": {}}
        for name, client, want in (("bulk", bulk, 6), ("gold", gold, 4)):
            for _ in range(want):
                reply = client.recv_reply()
                counts[name][reply["type_name"]] = \
                    counts[name].get(reply["type_name"], 0) + 1
        fleet.cmd("clear")
        # Gold never shed; bulk shed at the reactor.
        assert counts["gold"] == {"ReplyGet": 4}, counts
        assert counts["bulk"].get("ReplyBusy", 0) >= 1, counts
        assert fleet.monitor("serve.qos.shed.bulk") >= 1
        assert fleet.monitor("serve.qos.admit.gold") >= 4
        assert fleet.monitor("serve.qos.shed.gold") == 0
        bulk.close()
        gold.close()
        fleet.release()
    finally:
        fleet.kill()


# ------------------------------------------------------ deadline propagation

def test_deadline_expired_get_sheds_at_dequeue(tmp_path):
    """A get whose propagated budget is 1 ns is dropped — no reply, no
    apply slot — and counted serve.deadline.shed; an unstamped get on
    the same connection (the pre-13 frame) still round-trips."""
    fleet = TailFleet(tmp_path)
    try:
        ep = fleet.endpoints[0]
        with AnonServeClient(ep, timeout=15.0) as c:
            for i in range(5):
                c.send_raw(pack_frame(MSG["RequestGet"], 0, 300 + i,
                                      qos=(0, 1)))
            # The healthy, unstamped control round-trips normally...
            mid = c._next_id()
            c.send_raw(pack_frame(MSG["RequestGet"], 0, mid))
            reply = c.recv_reply()
            assert reply["type_name"] == "ReplyGet"
            assert reply["msg_id"] == mid  # ...and the 5 shed gets
            # produced no replies at all (FIFO: theirs would have come
            # first).
            assert c._decoder.next_frame() is None
        deadline = time.time() + 20
        while time.time() < deadline:
            if fleet.monitor("serve.deadline.shed") >= 5:
                break
            time.sleep(0.05)
        assert fleet.monitor("serve.deadline.shed") >= 5
        # The in-band latency scrape names them per class too.
        with AnonServeClient(ep, timeout=15.0) as c:
            rep = json.loads(c.ops_report("latency"))
        assert rep["qos"]["deadline_shed"] >= 5
        assert any(k["deadline_sheds"] >= 5
                   for k in rep["qos"]["classes"]), rep["qos"]
        fleet.release()
    finally:
        fleet.kill()


# ------------------------------------------------------------- hedged reads

HOT = [0, 1, 2, 3]
EXPECT = np.repeat(np.arange(1.0, 5.0, dtype=np.float32), 4).reshape(4, 4)


def _warm(reader, n=60):
    for _ in range(n):
        got = reader.get_rows(HOT)
        np.testing.assert_allclose(got, EXPECT)


def test_hedge_cancel_on_first_win_zero_dup_adds(tmp_path):
    """The satellite chaos acceptance: a seeded apply_delay straggler
    on the primary read is WON by the replica hedge (answered at the
    reactor while the primary sits behind the sleeping apply), the
    loser's cancel token drops it at dequeue, the answer is exact, and
    the PR 12 audit plane proves zero lost or duplicated acked adds."""
    fleet = TailFleet(tmp_path,
                      env_extra={"MVTPU_FAULT_DELAY_MS": "400"})
    try:
        ep = fleet.endpoints[0]
        fleet.cmd("add 1.0")          # acked adds bracketing the chaos
        reader = HedgedReader(ep, 1, 4, qos_class="gold",
                              hedge_min_us=5000, timeout=20.0)
        _warm(reader)                 # SpaceSaving top-K now holds HOT
        assert reader.stats()["issued"] == 0  # healthy: no hedges fired
        # ONE get eats the 400 ms nap: a decoy occupies the server
        # actor, so the hedged read's primary parks in the mailbox.
        fleet.cmd("fault apply_delay 1")
        decoy = AnonServeClient(ep, timeout=15.0)
        decoy.send_raw(pack_frame(MSG["RequestGet"], 0, 7777))
        time.sleep(0.05)              # decoy reaches the nap first
        t0 = time.monotonic()
        got = reader.get_rows(HOT)
        hedged_s = time.monotonic() - t0
        np.testing.assert_allclose(got, EXPECT)
        st = reader.stats()
        assert st["issued"] == 1 and st["won"] == 1, st
        assert hedged_s < 0.35, hedged_s   # beat the 400 ms straggler
        # The decoy (and nothing else) comes back on its socket.
        assert decoy.recv_reply()["msg_id"] == 7777
        decoy.close()
        # The cancelled loser was dropped at dequeue, counted.
        deadline = time.time() + 10
        while time.time() < deadline:
            if fleet.monitor("serve.hedge.cancelled") >= 1:
                break
            time.sleep(0.05)
        assert fleet.monitor("serve.hedge.cancelled") >= 1
        assert fleet.monitor("serve.hedge.cancel_noted") >= 1
        fleet.cmd("clear")
        fleet.cmd("add 1.0")
        reader.close()

        # Audit plane: zero lost, zero duplicated acked adds.
        from multiverso_tpu.ops.audit import diff_fleet

        with AnonServeClient(ep, timeout=15.0) as c:
            doc = json.loads(c.ops_report("audit", scope=1))
        problems = [f for f in diff_fleet(doc)
                    if f["kind"] in ("lost", "dup")]
        assert problems == [], problems

        # Disarmed-hedge control: same straggler shape, no hedge — the
        # caller waits out the full nap and the counters stay zero.
        control = HedgedReader(ep, 1, 4, qos_class="gold",
                               hedge_min_us=5000, enabled=False,
                               timeout=20.0)
        _warm(control, n=5)
        fleet.cmd("fault apply_delay 1")
        t0 = time.monotonic()
        got = control.get_rows(HOT)   # this primary IS the straggler
        waited = time.monotonic() - t0
        np.testing.assert_allclose(got, EXPECT)
        st = control.stats()
        assert st["issued"] == 0 and st["won"] == 0, st
        assert waited >= 0.3, waited  # ate the nap: no hedge to save it
        fleet.cmd("clear")
        control.close()
        fleet.release()
    finally:
        fleet.kill()


# --------------------------------------------------------------- tool views

def _canned_qos_report(rank="0"):
    return {rank: {"armed": True, "stages": {}, "qos": {
        "inflight_max": 32,
        "classes": [
            {"name": "bulk", "weight": 1, "budget": 3, "inflight": 2,
             "admits": 900, "sheds": 400, "deadline_sheds": 60},
            {"name": "gold", "weight": 8, "budget": 29, "inflight": 1,
             "admits": 5000, "sheds": 0, "deadline_sheds": 0},
        ],
        "deadline_shed": 60, "cancels_noted": 9, "cancelled": 7}}}


def test_mvtop_qos_rows_and_rate_discipline():
    import mvtop

    rows = mvtop.qos_rows(_canned_qos_report())
    by_class = {r["class"]: r for r in rows}
    assert by_class["bulk"]["sheds"] == 400
    assert by_class["gold"]["admits"] == 5000
    # Watch mode: '-' before two scrapes exist, real rates after.
    tracker = mvtop.RateTracker()
    rows = mvtop.qos_rows(_canned_qos_report(), tracker=tracker, now=10.0)
    assert rows[0]["admit/s"] == "-"
    second = _canned_qos_report()
    second["0"]["qos"]["classes"][0]["admits"] = 1000   # +100 in 2 s
    rows = mvtop.qos_rows(second, tracker=tracker, now=12.0)
    bulk = [r for r in rows if r["class"] == "bulk"][0]
    assert bulk["admit/s"] == "50.0"


def test_latdoctor_deadline_note():
    import latdoctor

    report = _canned_qos_report()["0"]
    note = latdoctor.deadline_note(report)
    assert note is not None and "bulk" in note
    healthy = _canned_qos_report()["0"]
    for k in healthy["qos"]["classes"]:
        k["deadline_sheds"] = 0
    healthy["qos"]["deadline_shed"] = 0
    assert latdoctor.deadline_note(healthy) is None
