"""Tier-1 gate for the workload observability plane
(docs/observability.md, "workload plane"): the hot-key sketches
(property-tested: planted heavy hitters always surface, count-min never
underestimates and stays inside its eps bound, per-rank merges fold),
the JAX-plane table mirror, the metrics time-series ring
(rate()/delta()), the label-cardinality-overflow flight-recorder hook,
mvtop's two-scrape rate columns, and the native plane end to end —
including the ``"hotkeys"`` OpsQuery round trip on both wire engines
and the NaN update-health blackbox trigger.
"""

import json
import os
import shutil
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")


# ---------------------------------------------------------- sketch properties

def test_key_hash_is_fnv1a_and_stable():
    from multiverso_tpu.sketch import key_hash

    # FNV-1a 64 reference values (the native KeyHash/KVHash function):
    # hash("") is the offset basis; str/bytes agree; ints hash their
    # little-endian int64 form.
    assert key_hash(b"") == 1469598103934665603
    assert key_hash("abc") == key_hash(b"abc")
    assert key_hash(3) == key_hash((3).to_bytes(8, "little", signed=True))
    assert key_hash("a") != key_hash("b")


def test_space_saving_planted_heavy_hitters_always_surface():
    """Any key with frequency > total/K is guaranteed monitored — the
    space-saving invariant, checked over a zipf-ish stream with noise
    keys churning the tail."""
    from multiverso_tpu.sketch import SpaceSavingSketch

    rng = np.random.RandomState(0)
    ss = SpaceSavingSketch(k=16)
    true = {}
    for i in range(4000):
        if i % 2 == 0:
            key = f"hot{(i // 2) % 8}"       # 8 planted hitters, 6.25% each
        else:
            key = f"noise{rng.randint(100000)}"
        ss.offer(key)
        true[key] = true.get(key, 0) + 1
    top = {label: (count, err) for label, count, err in ss.topk()}
    for h in range(8):
        key = f"hot{h}"
        assert key in top, (key, sorted(top))
        count, err = top[key]
        assert count >= true[key]            # upper bound
        assert count - err <= true[key]      # honest lower bound


def test_count_min_never_underestimates_and_bounds_error():
    from multiverso_tpu.sketch import CountMinSketch

    cm = CountMinSketch(width=512, depth=4)
    for i in range(3000):
        cm.add(i % 30)                       # 30 keys, 100 each
    eps_slack = 2 * cm.total * cm.depth // cm.width   # generous eps*N
    for i in range(30):
        est = cm.estimate(i)
        assert est >= 100
        assert est <= 100 + eps_slack
    assert cm.estimate("never-seen") <= eps_slack


def test_sketches_merge_across_ranks():
    """The fleet-scope fold: merging per-rank sketches must preserve
    the heavy hitters and sum counts/grids."""
    from multiverso_tpu.sketch import (CountMinSketch, SpaceSavingSketch,
                                       WorkloadTracker)

    a, b = SpaceSavingSketch(8), SpaceSavingSketch(8)
    for _ in range(40):
        a.offer("shared")
    for _ in range(25):
        b.offer("shared")
    b.offer("b-only")
    a.merge(b)
    top = dict((label, count) for label, count, _ in a.topk())
    assert top["shared"] == 65
    assert a.total == 66

    ca, cb = CountMinSketch(64, 2), CountMinSketch(64, 2)
    ca.add("x", 10)
    cb.add("x", 5)
    ca.merge(cb)
    assert ca.estimate("x") >= 15
    assert ca.total == 15
    with pytest.raises(ValueError):
        ca.merge(CountMinSketch(32, 2))

    ta, tb = WorkloadTracker(topk=8), WorkloadTracker(topk=8)
    ta.note_get([1, 1, 2])                   # ONE get touching 3 keys
    tb.note_get([1])
    tb.note_add([3])
    ta.merge(tb)
    rep = ta.report()
    assert rep["gets"] == 2 and rep["adds"] == 1
    assert rep["hotkeys"]["topk"][0]["key"] == "1"
    assert rep["hotkeys"]["topk"][0]["count"] == 3


def test_workload_tracker_report_shape_and_skew():
    from multiverso_tpu.sketch import WorkloadTracker

    t = WorkloadTracker(topk=8, buckets=64)
    for _ in range(64):
        t.note_get([7])                      # one hot bucket
    t.note_add()                             # whole-table op: totals only
    rep = t.report()
    assert rep["gets"] == 64 and rep["adds"] == 1
    assert rep["bucket_load_max"] == 64
    assert rep["skew_ratio"] == 64.0         # all load in bucket 7
    top = rep["hotkeys"]["topk"][0]
    assert top["key"] == "7" and top["count"] == 64
    assert top["estimate"] >= 64             # count-min never under


# ------------------------------------------------------ JAX-plane table mirror

def test_table_workload_report_mirrors_native_shape(mv):
    mv.init()
    t = mv.MatrixTable(32, 4)
    hot = np.ones((1, 4), np.float32)
    for _ in range(10):
        t.add_rows([3], hot)
        t.get_rows([3, 7])
    rep = t.workload_report()
    assert rep["armed"] and rep["id"] == t.table_id
    assert rep["gets"] == 10 and rep["adds"] == 10
    top = [e["key"] for e in rep["hotkeys"]["topk"]]
    assert top[0] == "3"                     # the hot row leads
    assert rep["skew_ratio"] > 1.0


def test_table_workload_disarmed_by_flag(mv):
    from multiverso_tpu import config

    config.set_flag("hotkey_enabled", False)
    try:
        mv.init()
        t = mv.ArrayTable(8)
        t.get()
        assert t.workload_report() == {"id": t.table_id, "armed": False}
    finally:
        config.set_flag("hotkey_enabled", True)


# ----------------------------------------------------- metrics time-series

@pytest.fixture()
def registry():
    from multiverso_tpu import metrics

    metrics.reset()
    yield metrics
    metrics.reset()


def test_metrics_history_rate_and_delta(registry):
    c = registry.counter("req.count")
    g = registry.gauge("q.depth")
    c.inc(100)
    g.set(5)
    registry.record_history(now=10.0)
    c.inc(50)
    g.set(9)
    registry.record_history(now=20.0)
    assert registry.rate("req.count") == pytest.approx(5.0)   # 50 in 10s
    assert registry.delta("req.count") == pytest.approx(50.0)
    assert registry.rate("q.depth") == pytest.approx(0.4)
    assert len(registry.history("req.count")) == 2
    # Window narrows the baseline sample.
    c.inc(10)
    registry.record_history(now=30.0)
    assert registry.rate("req.count", window_s=11.0) == pytest.approx(1.0)
    assert registry.rate("req.count") == pytest.approx(3.0)   # full ring


def test_metrics_history_histogram_and_bounds(registry):
    h = registry.histogram("op.lat", bounds=[1.0, 10.0])
    h.observe(0.5)
    registry.record_history(now=1.0)
    h.observe(0.5)
    h.observe(2.0)
    registry.record_history(now=2.0)
    assert registry.rate("op.lat_count") == pytest.approx(2.0)
    assert registry.delta("op.lat_sum") == pytest.approx(2.5)
    # Ring is bounded: HISTORY_SNAPSHOTS points max.
    for i in range(registry.HISTORY_SNAPSHOTS + 10):
        registry.record_history(now=10.0 + i)
    assert len(registry.history("op.lat_count")) == \
        registry.HISTORY_SNAPSHOTS
    # Fewer than two points / unknown series: None, never a crash and
    # NEVER a zero — a fresh scrape must not read as "zero traffic"
    # (renderers print '-'); delta keeps its 0.0 contract.
    assert registry.rate("nope") is None
    assert registry.delta("nope") == 0.0
    h2 = registry.histogram("fresh.lat", bounds=[1.0])
    h2.observe(0.5)
    registry.record_history(now=100.0)
    assert registry.rate("fresh.lat_count") is None  # one flush so far
    registry.record_history(now=110.0)
    assert registry.rate("fresh.lat_count") == pytest.approx(0.0)


def test_metrics_flush_records_history(registry, tmp_path):
    c = registry.counter("flush.count")
    c.inc(3)
    registry.start_flush(10, str(tmp_path / "m.prom"))
    try:
        import time

        deadline = time.time() + 5
        while not registry.history("flush.count") and \
                time.time() < deadline:
            time.sleep(0.01)
        assert registry.history("flush.count"), \
            "flush thread never recorded a history point"
    finally:
        registry.stop_flush()


def test_label_overflow_lands_in_flight_recorder(registry):
    """The cardinality-overflow series is snapshot-only; the EVENT must
    also land in the flight-recorder ring so a post-mortem sees the
    explosion (satellite fix + regression test)."""
    from multiverso_tpu.ops.flight_recorder import recorder

    recorder.reset()
    for i in range(registry.MAX_SERIES_PER_NAME + 3):
        registry.counter("burst", labels={"v": str(i)})
    events = [e for e in recorder.events()
              if e["kind"] == "metric_overflow"]
    assert len(events) == 3, [e["kind"] for e in recorder.events()]
    assert events[0]["detail"] == "burst"
    assert "v=" in events[0]["dropped_labels"]
    recorder.reset()


# ----------------------------------------------------------- mvtop rates

def test_mvtop_compute_rates_and_sparkline():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import mvtop

    prev = {"vmax": 100.0, "gets": 50.0, "adds": 20.0, "shed": 0.0}
    cur = {"vmax": 160.0, "gets": 250.0, "adds": 30.0, "shed": 4.0}
    rates = mvtop.compute_rates(prev, cur, dt=2.0)
    assert rates == {"vmax": 30.0, "gets": 100.0, "adds": 5.0,
                     "shed": 2.0}
    # A restarted rank's counter reset clamps to 0, not negative.
    assert mvtop.compute_rates({"vmax": 500.0}, {"vmax": 10.0},
                               1.0)["vmax"] == 0.0
    # Uncomputable rates (no baseline sample, zero elapsed, or a None
    # from a pre-second-flush metrics.rate()) are ABSENT — the renderer
    # prints '-', never a fake 0.0 a fresh scrape would misread as
    # "zero traffic".
    assert mvtop.compute_rates({}, {"vmax": 10.0}, 0.0) == {}
    assert mvtop.compute_rates({}, {"vmax": 10.0}, 1.0) == {}
    assert mvtop.compute_rates({"vmax": None}, {"vmax": 10.0}, 1.0) == {}
    assert mvtop.compute_rates({"vmax": 1.0}, {"vmax": None}, 1.0) == {}

    assert mvtop.sparkline([]) == "-"
    assert mvtop.sparkline([0, 0]) == "▁▁"
    line = mvtop.sparkline([0, 5, 10])
    assert len(line) == 3 and line[-1] == "█"


def test_mvtop_watch_rates_from_two_canned_scrapes():
    """The --watch refresh loop's rate columns, fed two canned scrape
    samples: the second refresh must show the computed per-second
    rates and a sparkline; the first shows placeholders."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import mvtop

    health = {"healthy": True, "engine": "epoll", "serve_queue_depth": 0,
              "server_inflight_max": 8, "clients": 3, "client_shed": 0,
              "dead_peers": [], "blackbox_triggers": 0}
    t0 = [{"id": 0, "version": 100, "gets": 50, "adds": 20,
           "agg_pending": 0}]
    t1 = [{"id": 0, "version": 160, "gets": 250, "adds": 30,
           "agg_pending": 0}]
    row0 = mvtop._row_from_health("0", health, t0)
    row1 = mvtop._row_from_health("0", dict(health, client_shed=4), t1)

    tracker = mvtop.RateTracker()
    first = tracker.update("0", row0["_counters"], now=100.0)
    assert first["v/s"] == "-"               # no baseline yet
    second = tracker.update("0", row1["_counters"], now=102.0)
    assert second["v/s"] == "30.0"
    assert second["get/s"] == "100.0"
    assert second["add/s"] == "5.0"
    assert second["shed/s"] == "2.0"
    assert second["trend"] != "-" and len(second["trend"]) >= 1
    # The rendered watch table carries the rate columns.
    row1.update(second)
    table = mvtop.render([row1], mvtop._COLS + mvtop._RATE_COLS)
    assert "v/s" in table and "30.0" in table


def test_mvtop_hotkey_rows_rank_by_skew():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import mvtop

    entry = {"id": 0, "gets": 100, "adds": 50, "skew_ratio": 7.5,
             "staleness_mean": 1.2, "nan_count": 0, "inf_count": 0,
             "hotkeys": {"total": 150, "topk": [
                 {"key": "42", "count": 90, "error": 0, "estimate": 91}]}}
    assert mvtop._fmt_topk(entry) == "42:90"
    assert mvtop._fmt_topk({"hotkeys": {"topk": []}}) == "-"
    table = mvtop.render(
        [{"rank": "0", "table": 0, "gets": 100, "adds": 50,
          "skew": "7.50", "stale~": "1.2", "nan": 0, "inf": 0,
          "top keys": "42:90"}], mvtop._HOTKEY_COLS)
    assert "42:90" in table and "7.50" in table


# ------------------------------------------------------------- native plane

@pytest.fixture()
def native_rt(tmp_path):
    from multiverso_tpu import native as nat

    nat.ensure_built()
    rt = nat.NativeRuntime(args=["-log_level=error",
                                 f"-trace_dir={tmp_path}"])
    yield rt
    rt.set_hotkey_tracking(True)
    rt.shutdown()


@needs_gxx
def test_native_hotkeys_report_and_load_stats(native_rt):
    h = native_rt.new_matrix_table(128, 4)
    hot = np.ones((1, 4), np.float32)
    for i in range(16):
        native_rt.matrix_add_rows(h, [9], hot)
        native_rt.matrix_get_rows(h, [9, 20 + i], 4)
    report = native_rt.hot_keys()
    entry = report[h]
    assert entry["id"] == h and entry["armed"]
    assert entry["gets"] == 16 and entry["adds"] == 16
    assert entry["skew_ratio"] > 1.0
    top = entry["hotkeys"]["topk"]
    assert top[0]["key"] == "9"
    assert top[0]["estimate"] >= top[0]["count"] - top[0]["error"]
    # Observed staleness: worker gets stamp last_version, so the
    # histogram has samples and the mean sits near 0 (read-your-writes).
    assert entry["staleness_count"] >= 1
    stats = native_rt.table_load_stats(h)
    assert stats["gets"] == 16 and stats["adds"] == 16
    assert stats["add_l2"] == pytest.approx(8.0)   # sqrt(16*4*1)
    assert stats["add_linf"] == 1.0
    # One-table restriction of MV_HotKeys.
    only = native_rt.hot_keys(h)
    assert len(only) == 1 and only[0]["id"] == h
    # The ops plane serves the same payload as the "hotkeys" kind.
    via_ops = json.loads(native_rt.ops_report("hotkeys"))
    assert via_ops[h]["gets"] == 16
    # The "tables" report carries the new workload fields too.
    tables = json.loads(native_rt.ops_report("tables"))
    assert tables[h]["gets"] == 16 and tables[h]["nan_count"] == 0


@needs_gxx
def test_native_hotkey_disarm_stops_accounting(native_rt):
    h = native_rt.new_matrix_table(32, 2)
    native_rt.matrix_get_rows(h, [1], 2)
    before = native_rt.table_load_stats(h)["gets"]
    native_rt.set_hotkey_tracking(False)
    native_rt.matrix_get_rows(h, [1], 2)
    assert native_rt.table_load_stats(h)["gets"] == before
    native_rt.set_hotkey_tracking(True)
    native_rt.matrix_get_rows(h, [1], 2)
    assert native_rt.table_load_stats(h)["gets"] == before + 1


@needs_gxx
def test_native_nan_add_dumps_blackbox_naming_table(native_rt, tmp_path):
    """The update-health sentinel acceptance path: the FIRST NaN-
    poisoned add dumps blackbox_rank0.json naming the table; repeats
    count but do not re-trigger."""
    h = native_rt.new_array_table(8)
    poison = np.ones(8, np.float32)
    poison[2] = np.nan
    poison[6] = np.inf
    native_rt.array_add(h, poison)
    stats = native_rt.table_load_stats(h)
    assert stats["nan_count"] == 1 and stats["inf_count"] == 1
    box = tmp_path / "blackbox_rank0.json"
    assert box.exists(), "NaN add did not dump the black box"
    doc = json.load(open(box))
    assert doc["reason"].startswith(f"nan_update: table {h}"), \
        doc["reason"]
    # The hotkeys report carries the sentinel counters too.
    entry = native_rt.hot_keys(h)[0]
    assert entry["nan_count"] == 1 and entry["inf_count"] == 1


# -------------------------------------------------------------- wire plane

def _spawn_fleet(script, tmp_path, nranks=2, extra=()):
    socks = [socket.socket() for _ in range(nranks)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    eps = [f"127.0.0.1:{s.getsockname()[1]}" for s in socks]
    for s in socks:
        s.close()
    mf = os.path.join(str(tmp_path), "machines")
    with open(mf, "w") as f:
        f.write("\n".join(eps) + "\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", script), mf,
             str(r), *map(str, extra)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env)
        for r in range(nranks)
    ]
    return eps, procs


@needs_gxx
def test_hotkeys_roundtrip_epoll_anonymous_scrape(tmp_path):
    """The ``"hotkeys"`` kind over the anonymous serve wire (epoll
    engine): local scope answers the table list, fleet scope wraps it
    in the ranks{} merge with every rank present."""
    from multiverso_tpu import native as nat

    nat.ensure_built()
    from multiverso_tpu.ops.introspect import OpsClient
    from multiverso_tpu.serve.wire import AnonServeClient

    eps, procs = _spawn_fleet("epoll_serve_worker.py", tmp_path)
    try:
        for p in procs:
            assert "SERVE_READY" in p.stdout.readline()
        # Drive some shard reads so the accounting has data.
        with AnonServeClient(eps[0], timeout=15) as ac:
            for _ in range(5):
                ac.get_shard(0)
        with OpsClient(eps[0], timeout=15) as c:
            local = c.hotkeys()
            assert local[0]["id"] == 0 and local[0]["armed"]
            assert local[0]["gets"] >= 5
            fleet = c.hotkeys(fleet=True)
            assert fleet["kind"] == "hotkeys"
            assert fleet["silent"] == []
            assert set(fleet["ranks"]) == {"0", "1"}
            assert fleet["ranks"]["0"][0]["gets"] >= 5
            assert fleet["ranks"]["1"][0]["armed"] is True
    finally:
        outs = []
        for p in procs:
            if p.poll() is None:
                try:
                    p.stdin.write("\n")
                    p.stdin.flush()
                except (BrokenPipeError, OSError):
                    pass
        for p in procs:
            try:
                outs.append(p.communicate(timeout=120)[0])
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate()[0])
    for out in outs:
        assert "SERVE_WORKER_OK" in out, out[-2000:]


@needs_gxx
def test_hotkeys_roundtrip_tcp_fleet_report(tmp_path):
    """The blocking tcp engine refuses anonymous scrapers, so the rank
    assembles the fleet view itself (MV_OpsFleetReport) — the
    ``"hotkeys"`` kind must round-trip over the rank wire with both
    ranks' hot keys present."""
    from multiverso_tpu import native as nat

    nat.ensure_built()
    eps, procs = _spawn_fleet("tcp_ops_worker.py", tmp_path)
    outs = []
    for p in procs:
        try:
            outs.append(p.communicate(timeout=120)[0])
        except subprocess.TimeoutExpired:
            p.kill()
            outs.append(p.communicate()[0])
    for p, out in zip(procs, outs):
        assert p.returncode == 0 and "TCP_OPS_OK" in out, out[-2000:]
    line = next(ln for ln in outs[0].splitlines()
                if ln.startswith("FLEET_HOTKEYS "))
    fleet = json.loads(line[len("FLEET_HOTKEYS "):])
    assert fleet["scope"] == "fleet" and fleet["kind"] == "hotkeys"
    assert fleet["silent"] == []
    # Rank 0's shard saw hot row 5; rank 1's shard hot row 45.
    r0 = {e["key"]: e for e in
          fleet["ranks"]["0"][0]["hotkeys"]["topk"]}
    r1 = {e["key"]: e for e in
          fleet["ranks"]["1"][0]["hotkeys"]["topk"]}
    assert "5" in r0 and r0["5"]["count"] >= 20    # both ranks' traffic
    assert "45" in r1 and r1["45"]["count"] >= 20
