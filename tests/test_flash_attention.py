"""Pallas flash-attention kernel tests (interpret mode on the CPU mesh;
the same kernel compiles for real on TPU — see ops/flash_attention.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import dense_attention_ref

from multiverso_tpu.ops import flash_attention


@pytest.mark.parametrize("B,H,T,D,bq,bk", [
    (2, 2, 256, 64, 128, 128),
    (1, 4, 128, 32, 64, 32),
    (2, 1, 64, 64, 64, 64),
    (1, 2, 256, 128, 256, 64),
])
@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dense(B, H, T, D, bq, bk, causal):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    want = dense_attention_ref(q, k, v, causal)
    got = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def test_flash_rejects_misaligned():
    q = jnp.zeros((1, 1, 100, 32))
    with pytest.raises(ValueError, match="must divide"):
        flash_attention(q, q, q, block_q=64, block_k=64, interpret=True)


def test_local_attention_cpu_fallback_is_jnp():
    """On the CPU backend the dispatcher must not take the Pallas path."""
    from multiverso_tpu.parallel.ring_attention import blockwise_attention_local

    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 128, 32).astype(np.float32))
    got = blockwise_attention_local(q, q, q, 32 ** -0.5)
    want = dense_attention_ref(q, q, q)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
