"""Pallas flash-attention kernel tests (interpret mode on the CPU mesh;
the same kernel compiles for real on TPU — see ops/flash_attention.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import dense_attention_ref

from multiverso_tpu.ops import flash_attention


@pytest.mark.parametrize("B,H,T,D,bq,bk", [
    (2, 2, 256, 64, 128, 128),
    (1, 4, 128, 32, 64, 32),
    (2, 1, 64, 64, 64, 64),
    (1, 2, 256, 128, 256, 64),
])
@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dense(B, H, T, D, bq, bk, causal):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    want = dense_attention_ref(q, k, v, causal)
    got = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def test_flash_rejects_misaligned():
    # No power-of-two block >= 8 divides 100: unusable, so it raises.
    q = jnp.zeros((1, 1, 100, 32))
    with pytest.raises(ValueError, match="no usable block"):
        flash_attention(q, q, q, block_q=64, block_k=64, interpret=True)


def test_flash_block_fallback_fits_odd_lengths():
    """Requested blocks shrink to the largest dividing power of two —
    T=192 runs under the 512/1024 defaults (as 64-blocks) instead of
    raising like rounds 1-3 did."""
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(1, 2, 192, 32).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(1, 2, 192, 32).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(1, 2, 192, 32).astype(np.float32)) * 0.3
    got = flash_attention(q, k, v, causal=True, interpret=True)
    want = dense_attention_ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def test_local_attention_cpu_fallback_is_jnp():
    """On the CPU backend the dispatcher must not take the Pallas path."""
    from multiverso_tpu.parallel.ring_attention import blockwise_attention_local

    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 128, 32).astype(np.float32))
    got = blockwise_attention_local(q, q, q, 32 ** -0.5)
    want = dense_attention_ref(q, q, q)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


# ---------------------------------------------------------------------------
# Gradient coverage (round-1 verdict: the missing tests that would have
# caught the non-differentiable kernel voiding the TPU bench).
# ---------------------------------------------------------------------------

def _dense_loss(q, k, v, causal):
    return jnp.sum(jnp.square(dense_attention_ref(q, k, v, causal)))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("T,bq,bk", [(128, 64, 64), (256, 128, 128)])
def test_flash_grad_matches_dense(causal, T, bq, bk):
    rng = np.random.RandomState(2)
    B, H, D = 1, 2, 32
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.3

    def flash_loss(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk,
                            interpret=True)
        return jnp.sum(jnp.square(o))

    gq, gk, gv = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    wq, wk, wv = jax.grad(_dense_loss, argnums=(0, 1, 2))(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(wq), atol=2e-4)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(wk), atol=2e-4)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(wv), atol=2e-4)


def test_flash_lse_matches_dense():
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(1, 2, 128, 32).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 2, 128, 32).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 2, 128, 32).astype(np.float32))
    scale = 32 ** -0.5
    _, lse = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                             interpret=True, return_lse=True)
    s = jnp.einsum("bhtd,bhsd->bhts", q, k) * scale
    mask = jnp.tril(jnp.ones((128, 128), bool))
    s = jnp.where(mask[None, None], s, -jnp.inf)
    want = jax.nn.logsumexp(s, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(want), atol=2e-5)


def test_flash_lse_combination_rule():
    """Two normalized partials combined via lse == attention over the
    concatenated keys — the identity the ring schedule relies on — and
    its gradient flows through the lse output's custom_vjp path."""
    rng = np.random.RandomState(4)
    B, H, T, D = 1, 1, 128, 32
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32)) * 0.5
    k = jnp.asarray(rng.randn(B, H, 2 * T, D).astype(np.float32)) * 0.5
    v = jnp.asarray(rng.randn(B, H, 2 * T, D).astype(np.float32)) * 0.5

    def combined_loss(q, k, v):
        o1, l1 = flash_attention(q, k[:, :, :T], v[:, :, :T], causal=False,
                                 block_q=64, block_k=64, interpret=True,
                                 return_lse=True)
        o2, l2 = flash_attention(q, k[:, :, T:], v[:, :, T:], causal=False,
                                 block_q=64, block_k=64, interpret=True,
                                 return_lse=True)
        lse = jnp.logaddexp(l1, l2)
        o = (o1 * jnp.exp(l1 - lse)[..., None]
             + o2 * jnp.exp(l2 - lse)[..., None])
        return jnp.sum(jnp.square(o))

    def dense_loss(q, k, v):
        return jnp.sum(jnp.square(dense_attention_ref(q, k, v, causal=False)))

    got = combined_loss(q, k, v)
    want = dense_loss(q, k, v)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    gq, gk, gv = jax.grad(combined_loss, argnums=(0, 1, 2))(q, k, v)
    wq, wk, wv = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(wq), atol=3e-4)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(wk), atol=3e-4)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(wv), atol=3e-4)


def test_flash_grad_bf16():
    """bf16 inputs differentiate without error and track the f32 grads."""
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(1, 2, 128, 64).astype(np.float32)) * 0.3

    def loss(x, interp_dtype):
        x = x.astype(interp_dtype)
        o = flash_attention(x, x, x, causal=True, block_q=64, block_k=64,
                            interpret=True)
        return jnp.sum(jnp.square(o.astype(jnp.float32)))

    g16 = jax.grad(lambda x: loss(x, jnp.bfloat16))(q)
    g32 = jax.grad(lambda x: loss(x, jnp.float32))(q)
    np.testing.assert_allclose(np.asarray(g16), np.asarray(g32),
                               atol=0.15, rtol=0.1)


def test_forced_flash_dispatch_under_value_and_grad(monkeypatch):
    """CI coverage of the exact line that killed round-1's bench: the
    dispatcher sends the transformer's attention to the Pallas kernel and
    value_and_grad must work through it."""
    from multiverso_tpu.parallel.ring_attention import (
        blockwise_attention_local)

    monkeypatch.setenv("MVTPU_FORCE_FLASH", "interpret")
    rng = np.random.RandomState(6)
    q = jnp.asarray(rng.randn(1, 2, 128, 32).astype(np.float32)) * 0.4

    def loss(x):
        o = blockwise_attention_local(x, x, x, 32 ** -0.5, causal=True)
        return jnp.sum(jnp.square(o))

    val, grad = jax.value_and_grad(loss)(q)

    def dense(x):
        return jnp.sum(jnp.square(dense_attention_ref(x, x, x, True)))

    wval, wgrad = jax.value_and_grad(dense)(q)
    np.testing.assert_allclose(float(val), float(wval), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(wgrad),
                               atol=2e-4)


def test_forced_flash_transformer_train_step(monkeypatch):
    """Full train_step with the flash kernel force-dispatched (interpret):
    the end-to-end path the TPU bench runs."""
    monkeypatch.setenv("MVTPU_FORCE_FLASH", "interpret")
    from jax.sharding import Mesh
    from multiverso_tpu.models.transformer import (
        TransformerConfig, TransformerTrainer)

    devs = np.array(jax.devices()[:1]).reshape(1, 1, 1)
    mesh = Mesh(devs, ("dp", "sp", "tp"))
    cfg = TransformerConfig(vocab_size=64, dim=64, n_layers=1, n_heads=2,
                            hidden=128, max_seq=128,
                            compute_dtype=jnp.float32)
    tr = TransformerTrainer(cfg, mesh, updater_type="sgd")
    toks = np.random.RandomState(7).randint(0, 64, (2, 128), dtype=np.int64)
    l0 = tr.train_step(toks)
    l1 = tr.train_step(toks)
    assert np.isfinite(l0) and np.isfinite(l1)
    assert l1 < l0
