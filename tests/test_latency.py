"""Tier-1 gate for the latency-attribution plane
(docs/observability.md "latency plane"): the wire timing trail (pack /
unpack / version tolerance), the stage-duration + NTP clock-offset
math, the sampling profiler (Python sampler thread + folded-stack
plumbing), the ``merge_dir`` truncated-file tolerance satellite, and —
over a live 2-rank fleet — stage monotonicity after offset correction,
old-header round trips, and latdoctor naming a seeded apply-path delay
as the dominant stage (never the wire).
"""

import json
import os
import shutil
import socket
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")

from multiverso_tpu.serve import wire  # noqa: E402
from multiverso_tpu import latency  # noqa: E402


# ------------------------------------------------------------ frame format

def test_pack_frame_timing_trail_round_trips():
    frame = wire.pack_frame(wire.MSG["RequestVersion"], 3, 7,
                            timing=True)
    body = frame[wire._LEN.size:]
    got = wire.unpack_frame(body)
    assert got["flags"] & wire.FLAG_TIMING
    assert got["timing"] is not None and len(got["timing"]) == 6
    t0, t1 = got["timing"][0], got["timing"][1]
    assert t0 > 0 and t1 >= t0
    assert got["timing"][2:] == (0, 0, 0, 0)


def test_pack_frame_old_header_unchanged():
    """Version tolerance: a trail-less frame is byte-identical to the
    PR 3 layout and parses with ``timing=None``."""
    frame = wire.pack_frame(wire.MSG["RequestGet"], 1, 2)
    body = frame[wire._LEN.size:]
    assert len(body) == wire.HEADER.size          # header only, no trail
    got = wire.unpack_frame(body)
    assert got["timing"] is None
    assert not (got["flags"] & wire.FLAG_TIMING)
    # ...and a timed frame costs exactly one TimingTrail more.
    timed = wire.pack_frame(wire.MSG["RequestGet"], 1, 2, timing=True)
    assert len(timed) == len(frame) + wire.TIMING.size


# ----------------------------------------------------- stage / offset math

def _trail(t0, t1, t2, t3, t4, t5):
    return (t0, t1, t2, t3, t4, t5)


def test_stage_durations_telescope_to_total():
    ms = 1_000_000
    # Server clock 5 ms AHEAD: its stamps carry +5ms.
    shift = 5 * ms
    trail = _trail(10 * ms, 11 * ms,
                   13 * ms + shift, 14 * ms + shift, 17 * ms + shift,
                   18 * ms + shift)
    now = 20 * ms
    stages = wire.stage_durations(trail, now, offset_ns=shift)
    assert stages["queue"] == pytest.approx(1e-3)
    assert stages["wire_out"] == pytest.approx(2e-3)
    assert stages["mailbox"] == pytest.approx(1e-3)
    assert stages["apply"] == pytest.approx(3e-3)
    assert stages["reactor"] == pytest.approx(1e-3)
    assert stages["wire_back"] == pytest.approx(2e-3)
    assert stages["total"] == pytest.approx(10e-3)
    # Offset-corrected stages telescope back to the end-to-end total.
    ssum = sum(v for k, v in stages.items() if k != "total")
    assert ssum == pytest.approx(stages["total"], rel=1e-9)


def test_ntp_sample_recovers_seeded_offset():
    ms = 1_000_000
    shift = 7 * ms
    # Symmetric 1 ms wire each way, 2 ms server hold.
    trail = _trail(0, 10 * ms, 11 * ms + shift, 0, 0, 13 * ms + shift)
    now = 14 * ms
    off, rtt = wire.ntp_sample(trail, now)
    assert off == shift
    assert rtt == 2 * ms
    # Local trail (never crossed the wire): no sample.
    assert wire.ntp_sample(_trail(1, 2, 0, 3, 4, 5), 6) is None


def test_offset_estimator_min_rtt_wins():
    est = wire.OffsetEstimator(window=4)
    est.update(100, 50)
    est.update(999, 400)      # congested sample: must not win
    est.update(105, 60)
    assert est.offset_ns == 100
    assert est.rtt_ns == 50
    assert est.samples == 3
    for _ in range(4):        # window slides the min-rtt sample out
        est.update(200, 80)
    assert est.offset_ns == 200


def test_record_stages_and_dominant_stage(monkeypatch):
    from multiverso_tpu import metrics

    metrics.reset()
    latency.record_stages({"queue": 1e-4, "apply": 5e-3, "total": 6e-3})
    snap = metrics.snapshot()
    assert snap["lat.stage.apply"]["count"] == 1
    assert snap["lat.total"]["count"] == 1
    metrics.reset()

    report = {"stages": {"apply": {"p99_ms": 25.0, "p50_ms": 20.0},
                         "wire_out": {"p99_ms": 1.0, "p50_ms": 0.5}},
              "total": {"p99_ms": 26.5, "p50_ms": 21.0, "p95_ms": 25.0,
                        "count": 9}}
    assert latency.dominant_stage(report) == "apply"
    assert latency.dominant_stage(report, "p50_ms") == "apply"
    assert latency.dominant_stage({"stages": {}}) is None
    summary = latency.stage_summary(report)
    assert summary["total"]["p99_ms"] == 26.5
    assert set(summary) == {"apply", "wire_out", "total"}


# ------------------------------------------------------------- profiler

def test_python_sampling_profiler_catches_a_busy_stack():
    from multiverso_tpu import profiler

    stop = threading.Event()

    def _burn():
        while not stop.is_set():
            sum(i * i for i in range(200))

    t = threading.Thread(target=_burn, daemon=True)
    t.start()
    p = profiler.SamplingProfiler(hz=200).start()
    try:
        deadline = time.time() + 10.0
        while p.samples < 10 and time.time() < deadline:
            time.sleep(0.02)
    finally:
        p.stop()
        stop.set()
        t.join(timeout=5)
    assert p.samples >= 10
    folded = p.folded()
    assert folded
    assert any("_burn" in stack for stack in folded)
    # Folded keys are outermost-first: the leaf is the innermost frame.
    burn_stack = next(s for s in folded if "_burn" in s)
    assert ";" in burn_stack


def test_parse_folded_and_profile_to_spans():
    from multiverso_tpu import profiler, tracing

    folded = profiler.parse_folded(
        "main;serve;apply 30\nmain;idle 5\n\nnot_a_count x\n")
    assert folded == {"main;serve;apply": 30, "main;idle": 5}
    tracing.clear()
    tracing.enable(rank=0)
    try:
        n = profiler.profile_to_spans(folded, period_s=0.01)
        assert n == 2
        evs = [e for e in tracing.events()
               if e.name.startswith("profile:")]
        assert {e.name for e in evs} == {"profile:apply", "profile:idle"}
        hot = next(e for e in evs if e.name == "profile:apply")
        assert hot.dur_us == 300_000           # 30 samples x 10 ms
        assert hot.args["stack"] == "main;serve;apply"
        assert hot.args["plane"] == "profiler/python"
    finally:
        tracing.disable()
        tracing.clear()


def test_profile_to_spans_noop_when_tracing_off():
    from multiverso_tpu import profiler, tracing

    tracing.disable()
    assert profiler.profile_to_spans({"a;b": 3}, 0.01) == 0


# ------------------------------------------- merge_dir tolerance satellite

def test_merge_dir_skips_truncated_rank_file(tmp_path):
    from multiverso_tpu import tracing

    good = {"traceEvents": [{"name": "x", "ph": "X", "ts": 5, "dur": 1,
                             "pid": 0, "tid": 0, "args": {}}]}
    (tmp_path / "trace_rank0.json").write_text(json.dumps(good))
    # A rank SIGKILLed mid-write leaves a truncated JSON document.
    (tmp_path / "trace_rank1.json").write_text(
        json.dumps(good)[: len(json.dumps(good)) // 2])
    (tmp_path / "trace_rank2.json").write_text('{"traceEvents": 42}')
    out = tracing.merge_dir(str(tmp_path))
    doc = json.load(open(out))
    names = [e["name"] for e in doc["traceEvents"]]
    assert "x" in names
    skipped = [e for e in doc["traceEvents"]
               if e["name"] == "trace_merge_skipped"]
    assert {e["args"]["file"] for e in skipped} == {
        "trace_rank1.json", "trace_rank2.json"}


# ------------------------------------------------------------- wire plane

def _spawn_fleet(tmp_path, nranks=2):
    socks = [socket.socket() for _ in range(nranks)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    eps = [f"127.0.0.1:{s.getsockname()[1]}" for s in socks]
    for s in socks:
        s.close()
    mf = os.path.join(str(tmp_path), "machines")
    with open(mf, "w") as f:
        f.write("\n".join(eps) + "\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = [
        subprocess.Popen(
            [sys.executable,
             os.path.join(REPO, "tests", "latency_worker.py"), mf,
             str(r)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env)
        for r in range(nranks)
    ]
    for p in procs:
        line = p.stdout.readline()
        assert "LAT_READY" in line, line
    return eps, procs


def _cmd(proc, cmd, marker, timeout=60):
    proc.stdin.write(cmd + "\n")
    proc.stdin.flush()
    deadline = time.time() + timeout
    lines = []
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line.rstrip("\n"))
        if marker in line:
            return lines
    raise AssertionError(f"no {marker} after {cmd!r}: {lines}")


def _quit(procs):
    outs = []
    for p in procs:
        if p.poll() is None:
            try:
                p.stdin.write("quit\n")
                p.stdin.flush()
            except (BrokenPipeError, OSError):
                pass
    for p in procs:
        try:
            outs.append(p.communicate(timeout=120)[0])
        except subprocess.TimeoutExpired:
            p.kill()
            outs.append(p.communicate()[0])
    return outs


@needs_gxx
def test_fleet_stage_attribution_offsets_and_old_header(tmp_path):
    """A live 2-rank fleet: cross-rank traffic leaves per-stage
    histograms (wire_out/apply populated) and a clock-offset estimate
    on both ranks; an anonymous TIMED probe's corrected stamps are
    monotonic; an OLD-HEADER (trail-less) client round-trips cleanly."""
    from multiverso_tpu import native as nat

    nat.ensure_built()
    eps, procs = _spawn_fleet(tmp_path)
    try:
        reports = {}
        offsets = {}
        for r, p in enumerate(procs):
            lines = _cmd(p, "report", "LAT_OFFSET")
            rep = next(ln for ln in lines if ln.startswith("LAT_REPORT "))
            off = next(ln for ln in lines if ln.startswith("LAT_OFFSET "))
            reports[r] = json.loads(rep[len("LAT_REPORT "):])
            offsets[r] = json.loads(off[len("LAT_OFFSET "):])
        for r in (0, 1):
            stages = reports[r]["stages"]
            assert reports[r]["armed"] is True
            for name in ("queue", "wire_out", "mailbox", "apply",
                         "reactor", "wire_back"):
                assert stages.get(name, {}).get("count", 0) > 0, \
                    (r, name, sorted(stages))
            assert reports[r]["total"]["count"] > 0
            # Every timed cross-rank reply (and the heartbeat echo)
            # feeds the peer-offset estimator.
            assert offsets[r] is not None, offsets
            assert offsets[r]["rtt_ns"] >= 0
            assert reports[r]["offsets"], reports[r]["offsets"]

        # ---- anonymous timed probe: corrected stamps are monotonic ----
        c = wire.AnonServeClient(eps[0], timeout=15, timing=True)
        try:
            trail = None
            for i in range(8):
                mid = c._next_id()
                c.send_raw(wire.pack_frame(wire.MSG["RequestVersion"],
                                           0, mid, timing=True))
                reply = c.recv_reply()
                assert reply["type_name"] == "ReplyVersion"
                trail = reply["timing"]
                now = time.monotonic_ns()
            assert trail is not None and all(t > 0 for t in trail)
            off = c.offset.offset_ns
            corrected = [trail[0], trail[1], trail[2] - off,
                         trail[3] - off, trail[4] - off, trail[5] - off,
                         now]
            slack = max(c.offset.rtt_ns or 0, 1_000_000)
            for a, b in zip(corrected, corrected[1:]):
                assert b >= a - slack, (corrected, off, slack)
            assert c.last_stages and c.last_stages["total"] > 0
            ssum = sum(v for k, v in c.last_stages.items()
                       if k != "total")
            assert ssum == pytest.approx(c.last_stages["total"],
                                         rel=0.25, abs=2e-3)
        finally:
            c.close()

        # ---- old-header peer: no trail, identical behavior ------------
        old = wire.AnonServeClient(eps[0], timeout=15, timing=False)
        try:
            v = old.table_version(0)
            assert v > 0
            assert old.last_stages is None
            assert old.offset.samples == 0
        finally:
            old.close()
    finally:
        outs = _quit(procs)
    for r, out in enumerate(outs):
        assert f"LAT_OK {r}" in out, out[-2000:]


@needs_gxx
def test_latdoctor_names_seeded_apply_delay(tmp_path):
    """The acceptance scenario: a 100% 25 ms ``apply_delay`` fault on
    rank 0's server apply path must make ``apply`` (never the wire) the
    dominant p99 stage of rank 1's breakdown — asserted through the
    fleet-scope "latency" report AND latdoctor's rendered verdict."""
    from multiverso_tpu import native as nat

    nat.ensure_built()
    eps, procs = _spawn_fleet(tmp_path)
    try:
        _cmd(procs[0], "fault", "LAT_FAULT_ARMED")
        _cmd(procs[1], "traffic", "LAT_TRAFFIC_DONE", timeout=120)

        from multiverso_tpu.ops.introspect import OpsClient

        with OpsClient(eps[0], timeout=15) as c:
            fleet = c.latency(fleet=True)
        rank1 = fleet["ranks"]["1"]
        assert latency.dominant_stage(rank1, "p99_ms") == "apply"
        apply_p99 = rank1["stages"]["apply"]["p99_ms"]
        wire_p99 = max(rank1["stages"].get("wire_out",
                                           {}).get("p99_ms", 0.0),
                       rank1["stages"].get("wire_back",
                                           {}).get("p99_ms", 0.0))
        assert apply_p99 > 10.0, apply_p99       # the 25 ms delay shows
        assert apply_p99 > wire_p99 * 2, (apply_p99, wire_p99)

        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "latdoctor.py"),
             eps[0], "--fleet"],
            capture_output=True, text=True, timeout=60,
            env=dict(os.environ, PYTHONPATH=REPO))
        assert out.returncode == 0, out.stderr
        assert "dominant p99 stage = apply" in out.stdout, out.stdout
    finally:
        outs = _quit(procs)
    for r, out in enumerate(outs):
        assert f"LAT_OK {r}" in out, out[-2000:]
