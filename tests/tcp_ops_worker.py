"""TCP-engine ops-fleet holder (not a pytest module).

Run as ``python tcp_ops_worker.py <machine_file> <rank>``: joins a
2-rank fleet on the BLOCKING tcp engine (which refuses anonymous
scraper connections — the engine the in-band wire scrape can't reach),
does a little skewed table traffic, and has rank 0 assemble the
fleet-scope ``"hotkeys"`` report ITSELF over the rank wire
(``MV_OpsFleetReport``) — proving the workload plane is reachable on
every engine.  Rank 0 prints ``FLEET_HOTKEYS <json>``; both ranks print
``TCP_OPS_OK <rank>``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from multiverso_tpu import native as nat  # noqa: E402

ROWS = 64
COLS = 4


def main() -> int:
    mf, rank = sys.argv[1], int(sys.argv[2])
    rt = nat.NativeRuntime(args=[f"-machine_file={mf}", f"-rank={rank}",
                                 "-net_engine=tcp", "-log_level=error",
                                 "-rpc_timeout_ms=30000",
                                 "-barrier_timeout_ms=60000"])
    assert rt.net_engine() == "tcp", rt.net_engine()
    h = rt.new_matrix_table(ROWS, COLS)
    rt.barrier()
    # Skewed traffic from BOTH ranks: row 5 (rank 0's shard) and row 45
    # (rank 1's shard) are everyone's hot keys.
    delta = np.ones((2, COLS), np.float32)
    for i in range(10):
        rt.matrix_add_rows(h, [5, 45], delta)
        rt.matrix_get_rows(h, [5, 45, 10 + i], COLS)
    rt.barrier()
    if rank == 0:
        print("FLEET_HOTKEYS " + rt.ops_fleet_report("hotkeys"),
              flush=True)
        # Capacity plane (docs/observability.md "capacity plane"): the
        # same engine-agnostic path must carry the "capacity" kind.
        print("FLEET_CAPACITY " + rt.ops_fleet_report("capacity"),
              flush=True)
    rt.barrier()
    rt.shutdown()
    print(f"TCP_OPS_OK {rank}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
