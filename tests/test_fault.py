"""Tier-1 chaos suite (docs/fault_tolerance.md): every injected failure
mode driven end-to-end, with a fixed seed so CI is deterministic.

Python plane: RetryPolicy schedules, the FaultInjector seams (streams /
table ops / barrier), CRC-framed checkpoint corruption + the
CheckpointManager fallback.  Native plane (g++-gated): the scripted-wire
scenarios in test_main.cc — send retry-then-succeed, drop/duplicate,
barrier timeout naming the missing rank, dropped-peer heartbeat report,
and the quiet control run proving injection-off changes nothing.

``make chaos`` runs exactly this file with MVTPU_FAULT_SEED pinned.
"""

import os
import shutil
import socket
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(REPO, "multiverso_tpu", "native")
SEED = int(os.environ.get("MVTPU_FAULT_SEED", "1234"))


@pytest.fixture()
def chaos(mv):
    """mv runtime + a disarmed injector and a zeroed counter ledger on
    both sides of the test (monitors are process-global)."""
    from multiverso_tpu import dashboard, fault

    fault.reset()
    dashboard.reset()
    yield mv
    fault.reset()
    dashboard.reset()


# ---------------------------------------------------------------- RetryPolicy

def test_retry_policy_recovers_from_transient_failures(chaos):
    from multiverso_tpu.fault import RetryPolicy

    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert RetryPolicy(attempts=3, backoff_s=0.001,
                       seed=SEED).run(flaky) == "ok"
    assert len(calls) == 3


def test_retry_policy_exhausts_and_reraises(chaos):
    from multiverso_tpu.fault import RetryPolicy

    with pytest.raises(OSError, match="always"):
        RetryPolicy(attempts=3, backoff_s=0.001, seed=SEED).run(
            lambda: (_ for _ in ()).throw(OSError("always")))


def test_retry_policy_schedule_is_deterministic_and_exponential(chaos):
    from multiverso_tpu.fault import RetryPolicy

    p = RetryPolicy(attempts=4, backoff_s=0.1, multiplier=2.0,
                    jitter=0.1, seed=SEED)
    a, b = list(p.delays()), list(p.delays())
    assert a == b                       # same seed, same schedule
    assert len(a) == 3
    for i, d in enumerate(a):           # exponential within jitter bounds
        base = 0.1 * 2.0 ** i
        assert base * 0.9 <= d <= base * 1.1


def test_retry_policy_deadline_stops_early(chaos):
    import time

    from multiverso_tpu.fault import RetryPolicy

    t0 = time.monotonic()
    with pytest.raises(OSError):
        # 10 attempts of 0.5 s backoff would take ~4.5 s; the 0.2 s
        # deadline must cut the schedule off almost immediately.
        RetryPolicy(attempts=10, backoff_s=0.5, jitter=0.0,
                    deadline_s=0.2).run(
            lambda: (_ for _ in ()).throw(OSError("down")))
    assert time.monotonic() - t0 < 2.0


def test_retry_policy_does_not_catch_unlisted_errors(chaos):
    from multiverso_tpu.fault import RetryPolicy

    calls = []

    def bug():
        calls.append(1)
        raise ValueError("a real bug, not a transient")

    with pytest.raises(ValueError):
        RetryPolicy(attempts=5, backoff_s=0.001).run(bug)
    assert len(calls) == 1              # no retry on non-transients


# ------------------------------------------------------------- FaultInjector

def test_injector_disabled_is_a_noop_with_zero_counters(chaos):
    from multiverso_tpu import dashboard, fault

    fault.inject("io.write")            # disarmed: must not raise
    fault.inject("table.Add")
    assert not fault.is_enabled()
    monitors = dashboard.report(log=False)
    assert not any(name.startswith("fault.") for name in monitors)


def test_injector_times_budget_fires_exactly_n(chaos):
    from multiverso_tpu import fault

    fault.configure(seed=SEED, sites={"io.write": {"times": 2}})
    for _ in range(2):
        with pytest.raises(fault.FaultError, match="io.write"):
            fault.inject("io.write")
    fault.inject("io.write")            # budget spent: clean
    assert fault.count("fault.io.write") == 2


def test_injector_rate_is_deterministic_under_seed(chaos):
    from multiverso_tpu import fault

    def pattern():
        fault.reset()
        fault.configure(seed=SEED, sites={"op": 0.5})
        hits = []
        for _ in range(64):
            try:
                fault.inject("op")
                hits.append(0)
            except fault.FaultError:
                hits.append(1)
        return hits

    a, b = pattern(), pattern()
    assert a == b                       # same seed → same failure script
    assert 0 < sum(a) < 64              # and it actually fires sometimes


# ----------------------------------------------------------- injected seams

def test_stream_write_faults_are_absorbed_by_checkpoint_retry(
        tmp_path, chaos):
    """Two injected write failures < the retry budget: save() succeeds
    anyway and the ledger shows the retries."""
    from multiverso_tpu import checkpoint, fault

    chaos.init()
    t = chaos.ArrayTable(8, name="t")
    t.add(np.arange(8, dtype=np.float32))
    fault.configure(seed=SEED,
                    sites={"io.write": {"times": 2, "error": OSError}})
    path = str(tmp_path / "ck.bin")
    checkpoint.save(path, extra={"step": 1})
    assert fault.count("fault.io.write") == 2
    assert fault.count("retry.attempts") >= 2
    fault.reset()
    assert checkpoint.restore(path) == {"step": 1}
    np.testing.assert_allclose(t.get(), np.arange(8))


def test_stream_write_faults_beyond_budget_surface(tmp_path, chaos):
    from multiverso_tpu import checkpoint, fault

    chaos.init()
    chaos.ArrayTable(8, name="t")
    fault.configure(seed=SEED,
                    sites={"io.write": {"times": 99, "error": OSError}})
    with pytest.raises(OSError):
        checkpoint.save(str(tmp_path / "ck.bin"))


def test_table_op_fault_seam(chaos):
    from multiverso_tpu import fault

    chaos.init()
    t = chaos.ArrayTable(4, name="t")
    fault.configure(seed=SEED, sites={"table.Add": {"times": 1}})
    with pytest.raises(fault.FaultError, match="table.Add"):
        t.add(np.ones(4, np.float32))
    t.add(np.ones(4, np.float32))       # budget spent: lands
    np.testing.assert_allclose(t.get(), 1.0)
    assert fault.count("fault.table.Add") == 1


def test_barrier_timeout_names_the_sync_point(chaos):
    """An injected straggler (the barrier seam sleeps past the deadline)
    turns into BarrierTimeout naming the rendezvous — never a hang."""
    from multiverso_tpu import fault
    from multiverso_tpu.core.context import BarrierTimeout

    chaos.init()
    fault.configure(seed=SEED,
                    sites={"barrier": {"delay_s": 3.0, "times": 1}})
    with pytest.raises(BarrierTimeout, match="mvtpu_barrier"):
        chaos.barrier(timeout_s=0.2)
    fault.reset()
    chaos.barrier(timeout_s=5.0)        # healthy rendezvous still works


def test_barrier_timeout_flag_parity(chaos):
    """The barrier_timeout_ms flag is the kwarg's default — native-flag
    parity on the SPMD plane."""
    from multiverso_tpu import config, fault
    from multiverso_tpu.core.context import BarrierTimeout

    chaos.init()
    config.set_flag("barrier_timeout_ms", 200)
    fault.configure(seed=SEED,
                    sites={"barrier": {"delay_s": 3.0, "times": 1}})
    with pytest.raises(BarrierTimeout):
        chaos.barrier()


# ------------------------------------------------- checkpoint corruption

def test_truncated_checkpoint_raises_checkpoint_corrupt(tmp_path, chaos):
    from multiverso_tpu import checkpoint

    chaos.init()
    t = chaos.ArrayTable(16, name="t")
    t.add(np.ones(16, np.float32))
    path = str(tmp_path / "ck.bin")
    checkpoint.save(path)
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:len(raw) // 2])       # killed mid-write
    with pytest.raises(checkpoint.CheckpointCorrupt, match="truncated"):
        checkpoint.restore(path)


def test_bitflipped_checkpoint_raises_checkpoint_corrupt(tmp_path, chaos):
    from multiverso_tpu import checkpoint

    chaos.init()
    t = chaos.ArrayTable(16, name="t")
    t.add(np.ones(16, np.float32))
    path = str(tmp_path / "ck.bin")
    checkpoint.save(path)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) - 8] ^= 0xFF                          # storage bit rot
    open(path, "wb").write(bytes(raw))
    with pytest.raises(checkpoint.CheckpointCorrupt, match="CRC"):
        checkpoint.restore(path)


def test_legacy_v1_checkpoint_still_restores(tmp_path, chaos):
    """Pre-CRC files (magic v1 + bare pickle) keep working — only
    without the integrity check."""
    import pickle

    from multiverso_tpu import checkpoint

    chaos.init()
    t = chaos.ArrayTable(4, name="t")
    t.add(np.full(4, 5.0, np.float32))
    snap = {"clock": 0, "extra": {"legacy": True},
            "tables": {"t": t.store_state()}}
    path = str(tmp_path / "v1.bin")
    with open(path, "wb") as f:
        f.write(b"MVTPUCKPT1")
        f.write(pickle.dumps(snap, protocol=4))
    t.add(np.ones(4, np.float32))
    assert checkpoint.restore(path) == {"legacy": True}
    np.testing.assert_allclose(t.get(), 5.0)


def test_checkpoint_manager_keeps_n_and_falls_back(tmp_path, chaos):
    """keep=N rotation + restore_latest falling past a corrupt newest
    snapshot to the previous good one."""
    from multiverso_tpu import checkpoint

    chaos.init()
    t = chaos.ArrayTable(4, name="t")
    mgr = checkpoint.CheckpointManager(str(tmp_path / "ckpts"), keep=3)
    for step in range(1, 5):
        t.add(np.ones(4, np.float32))   # value == step
        mgr.save_step(step, extra={"value": float(step)})
    assert mgr.steps() == [2, 3, 4]     # step 1 pruned
    files = sorted(os.listdir(tmp_path / "ckpts"))
    assert len([f for f in files if f.endswith(".ckpt")]) == 3

    # Corrupt the newest snapshot: resume lands on step 3.
    newest = str(tmp_path / "ckpts" / "step_0000000004.ckpt")
    raw = bytearray(open(newest, "rb").read())
    raw[-4] ^= 0xFF
    open(newest, "wb").write(bytes(raw))
    step, extra = mgr.restore_latest()
    assert step == 3 and extra == {"value": 3.0}
    np.testing.assert_allclose(t.get(), 3.0)


def test_checkpoint_manager_all_corrupt_raises(tmp_path, chaos):
    from multiverso_tpu import checkpoint

    chaos.init()
    chaos.ArrayTable(4, name="t")
    mgr = checkpoint.CheckpointManager(str(tmp_path / "ckpts"), keep=2)
    mgr.save_step(1)
    for name in os.listdir(tmp_path / "ckpts"):
        if name.endswith(".ckpt"):
            p = str(tmp_path / "ckpts" / name)
            open(p, "wb").write(b"garbage")
    with pytest.raises(checkpoint.CheckpointCorrupt, match="no restorable"):
        mgr.restore_latest()


def test_checkpoint_manager_rebuilds_lost_manifest(tmp_path, chaos):
    """The manifest is an index, not the source of truth: deleting it
    must not orphan the snapshots."""
    from multiverso_tpu import checkpoint

    chaos.init()
    t = chaos.ArrayTable(4, name="t")
    mgr = checkpoint.CheckpointManager(str(tmp_path / "ckpts"), keep=3)
    t.add(np.ones(4, np.float32))
    mgr.save_step(7, extra={"value": 1.0})
    os.unlink(str(tmp_path / "ckpts" / checkpoint.CheckpointManager.MANIFEST))
    step, extra = mgr.restore_latest()
    assert step == 7 and extra == {"value": 1.0}


# ------------------------------------------------------- native chaos tier

pytestmark_native = pytest.mark.skipif(shutil.which("g++") is None,
                                       reason="no C++ toolchain")


def _machine_file(tmp_path, n=2):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    eps = [f"127.0.0.1:{s.getsockname()[1]}" for s in socks]
    for s in socks:
        s.close()
    mf = tmp_path / "machines.txt"
    mf.write_text("".join(f"{e}\n" for e in eps))
    return str(mf)


def _binary():
    b = os.path.join(NATIVE_DIR, "build", "mvtpu_test")
    subprocess.run(["make", "-C", NATIVE_DIR, "-j4", "build/mvtpu_test"],
                   check=True, capture_output=True, timeout=600)
    return b


def _run_ranks(scenario, mf, n):
    procs = [subprocess.Popen([_binary(), scenario, mf, str(r)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(n)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=120)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


@pytestmark_native
def test_native_chaos_send_retry_then_succeed(tmp_path):
    """Two injected write failures, bounded backoff, payload lands;
    net.retries/fault.fail_send counters asserted inside the scenario."""
    procs, outs = _run_ranks("chaos_retry", _machine_file(tmp_path), 2)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"CHAOS_RETRY_OK {r}" in out, out[-2000:]


@pytestmark_native
def test_native_chaos_drop_and_duplicate(tmp_path):
    """A lossy then duplicating wire, one message each — shard values
    and net.dropped/net.duplicated counters asserted in the scenario."""
    procs, outs = _run_ranks("chaos_dropdup", _machine_file(tmp_path), 2)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"CHAOS_DROPDUP_OK {r}" in out, out[-2000:]


@pytestmark_native
def test_native_chaos_barrier_timeout_names_missing_rank(tmp_path):
    """Zoo::Barrier with a deadline: rank 1 never arrives; rank 0 gets
    rc=-3 within the deadline and the error NAMES rank 1."""
    procs, outs = _run_ranks("chaos_barrier", _machine_file(tmp_path), 2)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"CHAOS_BARRIER_OK {r}" in out, out[-2000:]
    assert "waiting for rank(s) 1" in outs[0], outs[0][-2000:]


@pytestmark_native
def test_native_chaos_heartbeat_reports_dead_peer(tmp_path):
    """Leases on, rank 1 crashes: rank 0 reports the dead peer via
    MV_DeadPeerCount + hb.missed without any call having to hang."""
    procs, outs = _run_ranks("chaos_heartbeat", _machine_file(tmp_path), 2)
    assert procs[0].returncode == 0, outs[0][-3000:]
    assert "CHAOS_HB_OK 0" in outs[0], outs[0][-2000:]
    assert "lease expired" in outs[0], outs[0][-2000:]
    assert procs[1].returncode == 0, outs[1][-3000:]  # _exit(0) crash sim


@pytestmark_native
def test_native_chaos_disabled_counters_zero(tmp_path):
    """Control run: no injection, identical workload — every injected-
    path counter is exactly zero (asserted inside the scenario)."""
    procs, outs = _run_ranks("chaos_quiet", _machine_file(tmp_path), 2)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"CHAOS_QUIET_OK {r}" in out, out[-2000:]
