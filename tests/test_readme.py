"""The README's code blocks must actually run — documentation drift is a
bug.  Each fenced python block is extracted verbatim and executed in one
shared namespace (later blocks may use names from earlier ones, exactly
as a reader following along would)."""

import os
import re

import numpy as np

_README = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "README.md")


def _python_blocks():
    text = open(_README).read()
    return re.findall(r"```python\n(.*?)```", text, re.DOTALL)


def test_readme_python_blocks_execute(mv):
    blocks = _python_blocks()
    assert len(blocks) >= 3, "README lost its quickstart blocks?"
    ns = {}
    # Blocks reference free variables a reader supplies (their own data);
    # provide the obvious ones documented around the blocks.
    import jax
    import jax.numpy as jnp

    ns["jax"] = jax
    ns["jnp"] = jnp
    ns["np"] = np
    ns["grad"] = np.ones(1000, np.float32)
    from multiverso_tpu.apps import synthetic_classification

    ns["x"], ns["y"] = synthetic_classification(64, 784, 10, seed=0)
    import multiverso_tpu as _mv

    import shutil

    for i, block in enumerate(blocks):
        code = compile(block, f"README.md#python-block-{i}", "exec")
        if "TransformerTrainer" in block:
            # Flagship fragments build dim-2048 models — minutes of CPU
            # compile for a doc test.  Syntax-checked above; execution
            # parity lives in tests/test_transformer.py.
            continue
        if "NativeRuntime" in block and shutil.which("g++") is None:
            continue  # same toolchain gate as tests/test_native.py
        # Blocks after the quickstart are session fragments (the reader
        # is mid-session); give them a live session and a live table.
        if "mv.init" not in block:
            _mv.init(args=["-updater_type=sgd"])
            if re.search(r"\bt\.", block):
                ns["t"] = _mv.ArrayTable(1000)
        try:
            exec(code, ns)
        except Exception as exc:
            raise AssertionError(
                f"README python block {i} failed: {exc}\n---\n{block}"
            ) from exc
        if "NativeRuntime" in block and "rt" in ns:
            # The C runtime is process-global state: left started with
            # this block's flags, a later NativeRuntime(args=...) would
            # silently reuse it (Zoo::Start no-ops when started) and
            # other tests' updater expectations would break.
            ns["rt"].shutdown()
    # The quickstart's shutdown ran; re-init so later blocks that touch
    # tables keep working is handled inside the loop order — final state
    # sanity: the fused LR step produced a finite loss.
    assert "loss" in ns and np.isfinite(float(ns["loss"]))
