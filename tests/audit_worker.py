"""Fleet driver for the delivery-audit tests (not a pytest module).

Run as ``python audit_worker.py <machine_file> <rank> <mode>
[trace_dir] [extra flags...]``: a 2-rank native fleet where rank 1
drives stamped adds through injected chaos and rank 0 prints the
fleet-scope ``"audit"`` report (``AUDIT_FLEET <json>`` — assembled via
MV_OpsFleetReport, so the same path covers the epoll AND the blocking
tcp engine, which refuses anonymous scrapers).  Modes:

- ``chaos`` — blocking adds eating injected ``fail_send`` (retry
  absorbs), exactly two injected ``dup`` sends, an async burst, then a
  final blocking add whose ack (per-connection FIFO) covers the whole
  tail.  The auditor must name exactly the two dups and ZERO lost
  acked adds.
- ``agg`` — ``-add_agg_bytes`` armed: an async burst collapses into
  ONE wire message per shard whose stamp covers the whole window (the
  seq-range accounting), then a blocking add acks everything.
- ``loss`` — rank 0 arms a one-shot ``discard_apply`` fault (a SILENT
  server-side discard: delivered, never applied, never booked).  Rank
  1's async stream leaves a hole in the shard-0 seq stream; past
  ``-audit_grace_ms`` the ``audit_gap`` blackbox fires on rank 0 and
  the fleet diff names the missing seq.  The tail is async — never
  acked — so the verdict must be gap + unacked, NOT a lost acked add.
- ``checksum`` — identical bit-exact ``assign`` stores from both ranks'
  views; rank 0 prints each rank's bucket checksums for the stability
  assertion.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from multiverso_tpu import native as nat  # noqa: E402

SIZE = 64
ASYNC_BURST = 6


def main() -> int:
    mf, rank, mode = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    trace_dir = sys.argv[4] if len(sys.argv) > 4 else ""
    extra = sys.argv[5:]
    args = [f"-machine_file={mf}", f"-rank={rank}", "-log_level=error",
            "-rpc_timeout_ms=20000", "-barrier_timeout_ms=60000",
            "-send_retries=3", "-send_backoff_ms=20",
            "-audit_grace_ms=250", *extra]
    if trace_dir:
        args.append(f"-trace_dir={trace_dir}")
    rt = nat.NativeRuntime(args=args)
    h = rt.new_array_table(SIZE)
    rt.barrier()

    delta = np.ones(SIZE, np.float32)
    if rank == 0 and mode == "loss":
        # One-shot SILENT server-side discard: the next RequestAdd that
        # reaches THIS rank's server actor vanishes pre-apply.
        rt.set_fault_seed(11)
        rt.set_fault_n("discard_apply", 1)
    rt.barrier()

    if rank == 1:
        rt.set_fault_seed(7)
        if mode == "chaos":
            for _ in range(3):
                rt.set_fault_n("fail_send", 1)   # retry absorbs
                rt.array_add(h, delta)
            rt.clear_faults()
            rt.set_fault_n("dup", 2)             # exactly two dups
            rt.array_add(h, delta)
            rt.array_add(h, delta)
            rt.clear_faults()
            for _ in range(ASYNC_BURST):
                rt.array_add(h, delta, sync=False)
            # The final blocking ack covers the async tail (FIFO).
            rt.array_add(h, delta)
        elif mode == "agg":
            for _ in range(ASYNC_BURST):
                rt.array_add(h, delta, sync=False)
            rt.array_add(h, delta)               # flush + ack everything
        elif mode == "loss":
            # Async stream: the first add to shard 0 is discarded there
            # (seq 1 never applied), the rest arrive ahead of the hole.
            for _ in range(4):
                rt.array_add(h, delta, sync=False)
            rt.array_get(h, SIZE)                # drain the pipeline
            # Let the grace window expire, then force the sweep server-
            # side via the audit scrape (rank 0 prints it below).
            time.sleep(0.6)
        elif mode == "checksum":
            rt.array_add(h, delta)
        ledger = rt.audit_report()["tables"][0]["worker"]
        print(f"LEDGER {json.dumps(ledger)}", flush=True)
    rt.barrier()

    if rank == 0:
        fleet = rt.ops_fleet_report("audit")
        print(f"AUDIT_FLEET {fleet}", flush=True)
        if mode == "checksum":
            # A second identical store must leave checksums unchanged
            # (assign is bit-exact): capture, re-store, re-capture.
            before = rt.audit_report()["tables"][0]["checksums"]
            print(f"CHECKSUM_BEFORE {json.dumps(before)}", flush=True)
    rt.barrier()
    if mode == "checksum":
        if rank == 1:
            rt.array_add(h, delta)               # second store, same bits
        rt.barrier()
        if rank == 0:
            after = rt.audit_report()["tables"][0]["checksums"]
            print(f"CHECKSUM_AFTER {json.dumps(after)}", flush=True)
        rt.barrier()
    rt.barrier()
    rt.shutdown()
    print(f"AUDIT_WORKER_OK {rank}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
