"""Tier-1 gate for shard replication + lease-triggered failover +
elastic membership (docs/replication.md): the shard-hint wire mirror,
the routing-epoch cache discipline (ServeClient + JAX-plane Table),
mvtop's replication view from a canned scrape, the native chaos
scenarios on BOTH wire engines (SIGKILL a server under load → backup
promoted inside the lease window, exact convergence, dup-idempotent
replays) and the live elastic join, the Python fleet acceptance
(SIGKILL + mvaudit zero lost acked adds + CRC beacon convergence on
the promoted shard), the symmetric-lease regression (rank 0 is the
corpse, a survivor detects and promotes), and the true-backup hedge
under a seeded apply_delay straggler."""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(REPO, "multiverso_tpu", "native")

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")


# ---------------------------------------------------------- wire mirror

def test_shard_hint_frame_roundtrip():
    """The shard hint rides the old pad slot biased by one: stamped
    frames round-trip it, unstamped frames stay byte-identical to the
    pre-replication wire and parse as hint -1."""
    from multiverso_tpu.serve.wire import MSG, pack_frame, unpack_frame

    msg = unpack_frame(pack_frame(MSG["RequestGet"], 0, 7, shard=3)[8:])
    assert msg["shard"] == 3
    old = unpack_frame(pack_frame(MSG["RequestGet"], 0, 7)[8:])
    assert old["shard"] == -1
    # The unhinted frame is bit-identical to the pre-replication one.
    assert pack_frame(MSG["RequestGet"], 0, 7, shard=-1) == \
        pack_frame(MSG["RequestGet"], 0, 7)


def test_shard_hint_composes_with_stamps():
    from multiverso_tpu.serve.wire import MSG, pack_frame, unpack_frame

    msg = unpack_frame(pack_frame(MSG["RequestGet"], 1, 2,
                                  blobs=[b"payload8"], timing=True,
                                  audit=(5, 5), qos=(1, 10), shard=2)[8:])
    assert msg["shard"] == 2 and msg["audit"] == (5, 5)
    assert msg["qos"] == (1, 10) and msg["blobs"] == [b"payload8"]


# ------------------------------------------- routing-epoch cache rules

class _StubRt:
    """Minimal runtime for ServeClient: versioned array serving with a
    mutable routing epoch."""

    def __init__(self):
        self.value = np.arange(4, dtype=np.float32)
        self.version = 1
        self.epoch = 0
        self.fetches = 0

    def routing_epoch(self):
        return self.epoch

    def last_version(self, handle):
        return self.version

    def table_version(self, handle):
        return self.version

    def array_get(self, handle, size):
        self.fetches += 1
        return self.value.copy()


def test_serve_client_drops_cache_on_epoch_flip():
    """A routing-epoch flip voids the serve cache and version leases:
    cached entries were stamped under the previous shard owner's
    version timeline (docs/replication.md)."""
    from multiverso_tpu.serve.client import ServeClient

    rt = _StubRt()
    c = ServeClient(rt, cache_entries=8, max_staleness=10,
                    window_us=0.0, lease_ms=60000.0)
    a = c.array_get(0, 4)
    b = c.array_get(0, 4)
    assert rt.fetches == 1 and np.allclose(a, b)  # second read: cache hit
    rt.epoch = 1                                  # promotion happened
    rt.value = rt.value + 100.0                   # new owner's bytes
    got = c.array_get(0, 4)
    assert rt.fetches == 2, "epoch flip must force a re-fetch"
    assert np.allclose(got, rt.value)
    # Stable epoch: caching resumes.
    c.array_get(0, 4)
    assert rt.fetches == 2


def test_table_note_routing_epoch_is_monotonic_and_invalidating():
    from multiverso_tpu.tables.base import Table

    t = Table.__new__(Table)
    import threading

    t._serve_version = 0
    t._serve_buckets = None
    t._serve_ver_lock = threading.Lock()
    t._routing_epoch = 0
    t._workload = None
    t._serve_cache = {}  # truthy: _serve_bump must bump the version
    v0 = t._serve_version
    t.note_routing_epoch(5)
    assert t.routing_epoch == 5
    assert t._serve_version > v0   # flip voided every cached entry
    v1 = t._serve_version
    t.note_routing_epoch(3)        # stale observation: ignored
    assert t.routing_epoch == 5 and t._serve_version == v1


# ------------------------------------------------- mvtop canned scrape

def test_mvtop_replication_rows_from_canned_scrape():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import mvtop

    doc = {"ranks": {
        "0": {"rank": 0, "armed": True, "sync": True, "epoch": 1026,
              "backup_shard": 2, "owners": [0, 2, 2], "backups": [-1, -1, 0],
              "promoted": [], "outstanding": 1,
              "stats": {"forwards": 9, "acks": 8, "applied": 4,
                        "promotions": 0, "epoch_flips": 1,
                        "dup_skips": 0, "catchups": 0}},
        "2": {"rank": 2, "armed": True, "sync": True, "epoch": 1026,
              "backup_shard": 1, "owners": [0, 2, 2], "backups": [-1, -1, 0],
              "promoted": [1], "outstanding": 0,
              "stats": {"forwards": 3, "acks": 3, "applied": 9,
                        "promotions": 1, "epoch_flips": 0,
                        "dup_skips": 2, "catchups": 0}},
    }, "silent": [1]}
    rows = mvtop.repl_rows(doc)
    by_rank = {r["rank"]: r for r in rows}
    assert by_rank["0"]["epoch"] == 1026 and by_rank["0"]["fwd"] == 9
    assert by_rank["2"]["promoted"] == "1"
    assert by_rank["2"]["dup_skip"] == 2
    assert by_rank[1]["armed"] == "SILENT"


# ------------------------------------------------ native chaos (tier-1)

def _binary():
    subprocess.run(["make", "-C", NATIVE_DIR, "-j4", "all"], check=True,
                   capture_output=True)
    return os.path.join(NATIVE_DIR, "build", "mvtpu_test")


def _machine_file(tmp_path, n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    eps = [f"127.0.0.1:{s.getsockname()[1]}" for s in socks]
    for s in socks:
        s.close()
    mf = os.path.join(str(tmp_path), "machines")
    with open(mf, "w") as f:
        f.write("\n".join(eps) + "\n")
    return mf, eps


@needs_gxx
@pytest.mark.parametrize("engine", ["epoll", "tcp"])
def test_native_failover_scenario(tmp_path, engine):
    """The chaos acceptance on BOTH wire engines: a 3-rank replicated
    fleet crashes rank 1 mid-run — the survivors detect the expired
    lease symmetrically, rank 2 promotes shard 1 and broadcasts the
    epoch flip, re-routed adds land, the dup-idempotence gate keeps a
    re-delivered stamped frame from double-applying, and the fleet
    converges to EXACT values including the promoted shard."""
    b = _binary()
    mf, _ = _machine_file(tmp_path, 3)
    procs = [subprocess.Popen([b, "failover_child", mf, str(r), engine],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(3)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=180)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r in (0, 2):
        assert procs[r].returncode == 0, f"rank {r}:\n{outs[r][-4000:]}"
        assert f"FAILOVER_OK {r}" in outs[r], outs[r][-2000:]


@needs_gxx
def test_native_elastic_join_scenario(tmp_path):
    """Elastic membership: a worker-only rank joins the replication
    set live (announce → whole-shard catch-up snapshot → forwarded
    deltas), re-runs the catch-up idempotently (the kill-mid-catch-up
    recovery path), then takes the shard over via an operator-driven
    promotion — traffic re-routes with exact values, no restart."""
    b = _binary()
    _, eps = _machine_file(tmp_path, 3)
    ctrl = eps[0]
    ports = [ep.rsplit(":", 1)[1] for ep in eps]
    specs = [("all", ports[0], "true"), ("server", ports[1], "false"),
             ("worker", ports[2], "false")]
    procs = []
    for i, (role, port, is_ctrl) in enumerate(specs):
        procs.append(subprocess.Popen(
            [b, "join_child", ctrl, port, role, "3", is_ctrl],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
        if i == 0:
            time.sleep(0.3)  # the controller must be listening first
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=180)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for (role, _, _), p, out in zip(specs, procs, outs):
        assert p.returncode == 0, f"{role}:\n{out[-4000:]}"
        assert f"JOIN_OK {role}" in out, out[-2000:]


# --------------------------------------------- Python fleet acceptance

def _spawn_fleet(tmp_path, nranks=3, extra=()):
    from multiverso_tpu import native as nat

    nat.ensure_built()
    mf, eps = _machine_file(tmp_path, nranks)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = [
        subprocess.Popen(
            [sys.executable,
             os.path.join(REPO, "tests", "failover_worker.py"),
             mf, str(r), *map(str, extra)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env)
        for r in range(nranks)
    ]
    for p in procs:
        line = p.stdout.readline()
        assert "FAILOVER_READY" in line, line
    return eps, procs


def _send(p, cmd):
    p.stdin.write(cmd + "\n")
    p.stdin.flush()


def _collect(p, cmd, reply_prefix=None):
    reply = None
    while True:
        line = p.stdout.readline()
        assert line, f"worker died mid-command {cmd!r}"
        if reply_prefix and line.startswith(reply_prefix):
            reply = line[len(reply_prefix):].strip()
        if line.startswith("OK "):
            return reply


def _cmd(p, cmd, reply_prefix=None):
    """Send one command; collect lines until its OK ack, returning the
    reply line with ``reply_prefix`` (if any)."""
    _send(p, cmd)
    return _collect(p, cmd, reply_prefix)


def _cmd_all(procs, cmd, reply_prefix=None):
    """Issue one command to SEVERAL workers concurrently (collective
    ops like barrier rendezvous across them — sequencing would
    deadlock the quorum), then collect each reply."""
    for p in procs:
        _send(p, cmd)
    return [_collect(p, cmd, reply_prefix) for p in procs]


def _finish(procs, timeout=60):
    outs = []
    for p in procs:
        if p.poll() is None:
            try:
                p.stdin.write("done\n")
                p.stdin.flush()
            except (BrokenPipeError, OSError):
                pass
    for p in procs:
        try:
            outs.append(p.communicate(timeout=timeout)[0])
        except subprocess.TimeoutExpired:
            p.kill()
            outs.append(p.communicate()[0])
    return outs


@needs_gxx
def test_failover_fleet_zero_lost_acked_adds(tmp_path):
    """The full acceptance: SIGKILL server rank 1 under a replicated
    3-rank fleet — the backup promotes within the lease window, the
    promoted shard's CRC beacons match the pre-kill primary's last
    audited state, survivors' re-routed adds converge to exact values,
    and ``ops.audit.diff_fleet`` over the survivor-assembled fleet
    report proves ZERO lost acked adds and zero aged gaps."""
    from multiverso_tpu.ops.audit import diff_fleet

    eps, procs = _spawn_fleet(tmp_path)
    try:
        # The victim's last audited shard state (its OWN shard = 1).
        pre = json.loads(_cmd(procs[1], "sums", "SUMS "))
        assert pre["server"], pre

        procs[1].send_signal(signal.SIGKILL)
        procs[1].wait(timeout=30)

        # Symmetric detection + promotion within the lease window,
        # observed on BOTH survivors.
        assert int(_cmd(procs[2], "waitdead 1", "DEAD ")) >= 1
        assert _cmd(procs[2], "waitowner 1 2", "OWNER ") == "1=2"
        assert _cmd(procs[0], "waitowner 1 2", "OWNER ") == "1=2"

        # CRC beacons: the promoted (backup) shard instance on rank 2
        # holds EXACTLY the dead primary's last audited bytes — sync
        # replication made every acked add present on both replicas.
        post = json.loads(_cmd(procs[2], "sums", "SUMS "))
        assert post["backup_shard"] == 1
        assert post["backup"] == pre["server"], (pre, post)

        # Re-routed traffic: two more acked rounds from each survivor.
        for p in (procs[0], procs[2]):
            _cmd(p, "add 1")
            _cmd(p, "add 1")
        # Survivor rendezvous (concurrent — it is a collective): the
        # dead-leased rank is excused from the quorum.
        assert _cmd_all([procs[0], procs[2]], "barrier",
                        "BARRIER ") == ["ok", "ok"]
        vals = json.loads(_cmd(procs[0], "get", "VALUES "))
        assert all(v == 7.0 for v in vals["array"]), vals  # 3 + 2*2
        assert all(s == 7.0 * 4 for s in vals["row_sums"]), vals

        # The auditor's verdict, assembled BY a survivor over the rank
        # wire: zero lost acked adds, zero aged gaps (the dead rank is
        # silent, not lossy — its books died with it).
        fleet = json.loads(_cmd(procs[0], "audit_fleet", "AUDIT_FLEET "))
        findings = diff_fleet(fleet)
        lost = [f for f in findings if f["kind"] == "lost"]
        aged = [f for f in findings
                if f["kind"] == "gap" and f.get("aged")]
        assert lost == [] and aged == [], findings

        repl = json.loads(_cmd(procs[0], "repl_fleet", "REPL_FLEET "))
        r2 = repl["ranks"]["2"]
        assert r2["promoted"] == [1] and r2["epoch"] > 0, r2
        assert r2["stats"]["promotions"] >= 1
    finally:
        outs = _finish(procs)
    for r in (0, 2):
        assert f"FAILOVER_WORKER_OK {r}" in outs[r], outs[r][-3000:]


@needs_gxx
def test_rank0_kill_detected_and_promoted_by_survivor(tmp_path):
    """Symmetric lease watching (the satellite bugfix): rank 0 — the
    old, only lease authority — is the corpse; a SURVIVOR detects the
    expiry on its own (hb.missed counts there now), and shard 0's
    backup (server 1 in the chain) promotes without rank 0's help."""
    eps, procs = _spawn_fleet(tmp_path)
    try:
        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait(timeout=30)

        assert int(_cmd(procs[1], "waitdead 1", "DEAD ")) >= 1
        assert int(_cmd(procs[2], "waitdead 1", "DEAD ")) >= 1
        missed = _cmd(procs[1], "mon hb.missed", "MON ")
        assert int(missed.split("=")[1]) >= 1, missed
        # Chained assignment: shard 0's backup is server 1 — it
        # self-triggers promotion with the lease authority dead.
        assert _cmd(procs[1], "waitowner 0 1", "OWNER ") == "0=1"
        repl = json.loads(_cmd(procs[1], "repl", "REPL "))
        assert repl["stats"]["promotions"] >= 1, repl
    finally:
        # No barrier authority is left: hard exit, state already proven.
        for p in procs[1:]:
            if p.poll() is None:
                try:
                    p.stdin.write("exit_hard\n")
                    p.stdin.flush()
                except (BrokenPipeError, OSError):
                    pass
        for p in procs:
            try:
                p.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
                p.communicate()


@needs_gxx
def test_hedge_wins_against_true_backup_under_straggler(tmp_path):
    """Satellite: serve/hedge.py hedges against the TRUE backup shard
    when replication is armed — a seeded apply_delay straggler on the
    primary naps every apply, the hedge re-issues at the backup rank
    (shard hint routes it into the backed instance), values are exact,
    and serve.hedge.backup wins are counted."""
    from multiverso_tpu.serve.hedge import HedgedReader

    eps, procs = _spawn_fleet(tmp_path, nranks=2)
    try:
        # Straggle rank 0 (primary of shard 0, rows 0..5): every apply
        # naps 400 ms; the hedge should win LONG before that.
        _cmd(procs[0], "fault_rate delay_ms 400")
        _cmd(procs[0], "fault_rate apply_delay 1.0")

        with HedgedReader(eps[0], table_id=1, cols=4, hedge_min_us=2000,
                          backup_endpoint=eps[1], backup_shard=0,
                          timeout=20.0) as reader:
            t0 = time.monotonic()
            rows = reader.get_rows([0, 1, 2, 3])
            elapsed = time.monotonic() - t0
            # Warm adds were 2 ranks x ones → every element exactly 2.
            assert np.allclose(rows, 2.0), rows
            st = reader.stats()
            assert st["issued"] >= 1 and st["won"] >= 1, st
            assert st["backup_wins"] >= 1, st
            assert elapsed < 0.35, f"hedge should beat the 400ms nap " \
                                   f"(took {elapsed:.3f}s)"
        _cmd(procs[0], "clear")
    finally:
        outs = _finish(procs)
    for r in range(2):
        assert f"FAILOVER_WORKER_OK {r}" in outs[r], outs[r][-3000:]
