"""Tier-1 gate for the capacity plane (docs/observability.md,
"capacity plane"): per-table resident-byte accounting on all three
table kinds (within 10% of a ground-truth walk — exact in practice),
the disarm/re-arm-resync contract, the bounded load-history ring, the
``"capacity"`` OpsQuery round trip on BOTH wire engines (anonymous
epoll scrape local + fleet; tcp via ``MV_OpsFleetReport``), the
replica double-count regression, /proc stats in the health report, the
Python gauge registry + serve-cache gauges, mvtop's ``--capacity``
canned-scrape view, and the ``tools/mvplan.py`` placement advisor
(spread <= 2x on a seeded zipf fleet; ``--strict`` alarm semantics).
"""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import mvplan  # noqa: E402

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")

KV_OVERHEAD = 64  # native capacity::kKVEntryOverhead


# ------------------------------------------------------------- native plane

@pytest.fixture()
def native_rt(tmp_path):
    from multiverso_tpu import native as nat

    nat.ensure_built()
    rt = nat.NativeRuntime(args=["-log_level=error",
                                 "-capacity_history_ms=0",
                                 f"-trace_dir={tmp_path}"])
    yield rt
    rt.set_capacity_tracking(True)
    rt.set_hotkey_replica(False)
    rt.shutdown()


@needs_gxx
def test_native_byte_accounting_matrix_kv_array(native_rt):
    """Resident bytes track the ground-truth walk on every table kind
    (acceptance: within 10%; the books are exact by construction)."""
    rt = native_rt
    h_m = rt.new_matrix_table(96, 8)
    h_a = rt.new_array_table(256)
    h_k = rt.new_kv_table()
    keys = [f"k{i:03d}" for i in range(32)]
    rt.kv_add(h_k, keys, np.ones(len(keys), np.float32))

    rep = rt.capacity_report()
    assert rep["armed"] is True
    tables = {t["id"]: t for t in rep["tables"]}
    assert tables[h_m]["shard"]["resident_bytes"] == 96 * 8 * 4
    assert tables[h_m]["shard"]["rows"] == 96
    assert tables[h_a]["shard"]["resident_bytes"] == 256 * 4
    assert tables[h_a]["shard"]["rows"] == 256
    truth = sum(len(k) + 4 + KV_OVERHEAD for k in keys)
    got = tables[h_k]["shard"]["resident_bytes"]
    assert abs(got - truth) <= 0.1 * truth
    assert tables[h_k]["shard"]["rows"] == len(keys)
    # Per-bucket byte attribution sums back to the shard totals.
    assert sum(tables[h_m]["shard"]["bucket_bytes"]) == 96 * 8 * 4
    assert sum(tables[h_k]["shard"]["bucket_bytes"]) == got
    # Duplicate keys never double-book.
    rt.kv_add(h_k, keys[:4], np.ones(4, np.float32))
    rep2 = rt.capacity_report()
    t2 = {t["id"]: t for t in rep2["tables"]}[h_k]
    assert t2["shard"]["rows"] == len(keys)
    assert t2["shard"]["resident_bytes"] == got


@needs_gxx
def test_native_store_load_rebuilds_books(native_rt, tmp_path):
    """A snapshot Load recomputes the byte books exactly (the
    catch-up/restore path must not inherit a blank ledger)."""
    rt = native_rt
    h = rt.new_kv_table()
    keys = [f"persist-{i}" for i in range(16)]
    rt.kv_add(h, keys, np.ones(len(keys), np.float32))
    before = {t["id"]: t for t in rt.capacity_report()["tables"]}
    path = str(tmp_path / "kv.snap")
    rt.store_table(h, path)
    # Poison the books by loading over them: Load must rebuild.
    rt.load_table(h, path)
    after = {t["id"]: t for t in rt.capacity_report()["tables"]}
    assert after[h]["shard"]["resident_bytes"] == \
        before[h]["shard"]["resident_bytes"]
    assert after[h]["shard"]["rows"] == 16


@needs_gxx
def test_native_disarm_freezes_and_rearm_resyncs(native_rt):
    """Disarmed, the hot-path growth hooks are one relaxed load (no
    counter movement); re-arming RESYNCS with an exact walk, so the
    books are accurate whenever tracking is on."""
    rt = native_rt
    h = rt.new_kv_table()
    rt.kv_add(h, ["seed"], np.ones(1, np.float32))
    rows0 = {t["id"]: t for t in
             rt.capacity_report()["tables"]}[h]["shard"]["rows"]
    assert rows0 == 1
    rt.set_capacity_tracking(False)
    rt.kv_add(h, ["dark-1", "dark-2"], np.ones(2, np.float32))
    rep = rt.capacity_report()
    assert rep["armed"] is False
    assert {t["id"]: t for t in rep["tables"]}[h]["shard"]["rows"] == 1
    rt.set_capacity_tracking(True)
    rep2 = rt.capacity_report()
    entry = {t["id"]: t for t in rep2["tables"]}[h]["shard"]
    assert entry["rows"] == 3
    truth = sum(len(k) + 4 + KV_OVERHEAD
                for k in ("seed", "dark-1", "dark-2"))
    assert entry["resident_bytes"] == truth


@needs_gxx
def test_native_history_ring_bounded(native_rt):
    """The per-table load-history ring records once per scrape at
    -capacity_history_ms=0 and stays bounded at 64 windows."""
    rt = native_rt
    h = rt.new_matrix_table(32, 4)
    for i in range(70):
        if i % 10 == 0:
            rt.matrix_get_rows(h, [1], 4)
        rt.capacity_report()
    hist = {t["id"]: t for t in
            rt.capacity_report()["tables"]}[h]["history"]
    assert 2 <= hist["windows"] <= 64
    assert len(hist["curve"]) == hist["windows"]
    assert "bucket_rate" in hist and len(hist["bucket_rate"]) == 64
    assert hist["get_rate"] >= 0.0


@needs_gxx
def test_native_health_carries_proc_stats(native_rt):
    """RSS / peak RSS / open fds / uptime ride the health scrape."""
    health = json.loads(native_rt.ops_report("health"))
    assert health["rss_bytes"] > 0
    assert health["vm_hwm_bytes"] >= health["rss_bytes"] // 2
    assert health["open_fds"] > 0
    assert health["uptime_s"] >= 0.0
    # The capacity report carries the same proc object + gauges.
    rep = native_rt.capacity_report()
    assert rep["proc"]["rss_bytes"] > 0
    assert "host_arena.bytes" in rep["gauges"]
    assert "net.writeq_bytes" in rep["gauges"]


@needs_gxx
def test_tables_report_keeps_replica_rows_separate(native_rt):
    """The PR 10 replica double-count regression: after an armed
    replica install, the ``"tables"`` report's ``rows`` is the SHARD
    count alone and replica entries are their own field — capacity
    math cannot count a row twice."""
    rt = native_rt
    h = rt.new_matrix_table(64, 4)
    ones = np.ones((2, 4), np.float32)
    rt.matrix_add_rows(h, [1, 2], ones)
    for _ in range(8):
        rt.matrix_get_rows(h, [1, 2], 4)
    rt.set_hotkey_replica(True)
    rt.replica_refresh(h)
    assert rt.replica_stats(h)["rows"] >= 2   # replica is populated
    tables = {t["id"]: t for t in
              json.loads(rt.ops_report("tables"))}
    assert tables[h]["rows"] == 64            # shard rows ONLY
    assert tables[h]["replica_rows"] >= 2     # its own field
    assert tables[h]["resident_bytes"] == 64 * 4 * 4
    cap = {t["id"]: t for t in rt.capacity_report()["tables"]}
    assert cap[h]["shard"]["resident_bytes"] == 64 * 4 * 4
    assert cap[h]["worker"]["replica_bytes"] > 0
    assert cap[h]["worker"]["replica_rows"] >= 2


# -------------------------------------------------------------- wire plane

def _spawn_fleet(script, tmp_path, nranks=2, extra=()):
    import socket

    socks = [socket.socket() for _ in range(nranks)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    eps = [f"127.0.0.1:{s.getsockname()[1]}" for s in socks]
    for s in socks:
        s.close()
    mf = os.path.join(str(tmp_path), "machines")
    with open(mf, "w") as f:
        f.write("\n".join(eps) + "\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", script), mf,
             str(r), *map(str, extra)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env)
        for r in range(nranks)
    ]
    return eps, procs


@needs_gxx
def test_capacity_roundtrip_epoll_anonymous_scrape(tmp_path):
    """The ``"capacity"`` kind over the anonymous serve wire (epoll):
    local scope answers this rank's report, fleet scope wraps every
    rank in the ranks{} merge — and the shard byte books describe the
    held fleet's 64-element array table."""
    from multiverso_tpu import native as nat

    nat.ensure_built()
    from multiverso_tpu.ops.introspect import OpsClient

    eps, procs = _spawn_fleet("epoll_serve_worker.py", tmp_path)
    try:
        for p in procs:
            assert "SERVE_READY" in p.stdout.readline()
        with OpsClient(eps[0], timeout=15) as c:
            local = c.capacity()
            assert local["rank"] == 0 and local["armed"] is True
            assert local["proc"]["rss_bytes"] > 0
            shard = local["tables"][0]["shard"]
            assert shard["resident_bytes"] == 32 * 4  # 64 elems / 2
            fleet = c.capacity(fleet=True)
            assert fleet["kind"] == "capacity"
            assert fleet["silent"] == []
            assert set(fleet["ranks"]) == {"0", "1"}
            total = sum(
                r["tables"][0]["shard"]["resident_bytes"]
                for r in fleet["ranks"].values())
            assert total == 64 * 4  # the whole array, across shards
            # The advisor consumes the fleet doc directly; an array
            # table is whole-shard (no per-bucket bytes), so there is
            # nothing bucket-migratable to plan — documented behavior,
            # not an error.
            proposal = mvplan.propose(fleet)
            assert proposal["tables"] == {}
    finally:
        outs = []
        for p in procs:
            if p.poll() is None:
                try:
                    p.stdin.write("\n")
                    p.stdin.flush()
                except (BrokenPipeError, OSError):
                    pass
        for p in procs:
            try:
                outs.append(p.communicate(timeout=120)[0])
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate()[0])
    for out in outs:
        assert "SERVE_WORKER_OK" in out, out[-2000:]


@needs_gxx
def test_capacity_roundtrip_tcp_fleet_report(tmp_path):
    """The blocking tcp engine refuses anonymous scrapers, so the rank
    assembles the fleet capacity view itself (MV_OpsFleetReport) —
    both ranks' shard byte books must be present."""
    from multiverso_tpu import native as nat

    nat.ensure_built()
    eps, procs = _spawn_fleet("tcp_ops_worker.py", tmp_path)
    outs = []
    for p in procs:
        try:
            outs.append(p.communicate(timeout=120)[0])
        except subprocess.TimeoutExpired:
            p.kill()
            outs.append(p.communicate()[0])
    for p, out in zip(procs, outs):
        assert p.returncode == 0 and "TCP_OPS_OK" in out, out[-2000:]
    line = next(ln for ln in outs[0].splitlines()
                if ln.startswith("FLEET_CAPACITY "))
    fleet = json.loads(line[len("FLEET_CAPACITY "):])
    assert fleet["scope"] == "fleet" and fleet["kind"] == "capacity"
    assert fleet["silent"] == []
    # 64x4 matrix row-sharded over 2 ranks: 32 rows x 4 cols x 4 B each.
    for rank in ("0", "1"):
        shard = fleet["ranks"][rank]["tables"][0]["shard"]
        assert shard["resident_bytes"] == 32 * 4 * 4
        assert shard["rows"] == 32


# ------------------------------------------------------------ Python plane

def test_python_gauge_registry_and_container_bytes():
    from multiverso_tpu import capacity, metrics

    metrics.reset()
    capacity.register_gauge("test.holder", lambda: 1234)
    capacity.register_gauge("test.broken", lambda: 1 / 0)
    snap = capacity.snapshot()
    try:
        assert snap["test.holder"] == 1234
        assert snap["test.broken"] == -1     # a dead gauge reports -1
        # Exported as capacity.<name> series.
        assert metrics.gauge("capacity.test.holder").value == 1234
    finally:
        capacity.unregister_gauge("test.holder")
        capacity.unregister_gauge("test.broken")
        metrics.reset()
    arr = np.zeros(100, np.float32)
    d = {"a": (arr, 3), "b": b"xyz"}
    assert capacity.container_bytes(d) == arr.nbytes + 3 + 2 * 64


def test_serve_cache_registers_byte_gauge():
    """Every VersionedLRUCache registers a capacity gauge (MV018's
    contract) whose value tracks the cached ndarray bytes."""
    from multiverso_tpu import capacity
    from multiverso_tpu.serve.cache import VersionedLRUCache

    c = VersionedLRUCache(8, name="gaugetest")
    c.store(("t", 1), np.zeros(64, np.float32), 1)
    snap = capacity.snapshot(export=False)
    mine = [v for k, v in snap.items() if k.startswith("gaugetest.cache")]
    assert mine and mine[0] == 64 * 4 + 64, snap
    name = c._gauge_name
    del c
    # The weak binding self-prunes at the next snapshot.
    snap2 = capacity.snapshot(export=False)
    assert snap2.get(name, 0) == 0
    assert name not in capacity.snapshot(export=False)


# ----------------------------------------------------------------- mvtop

_CANNED_RANK = {
    "rank": 0, "armed": True, "server_id": 0, "servers": 2,
    "proc": {"rss_bytes": 50_000_000, "vm_hwm_bytes": 60_000_000,
             "open_fds": 33, "uptime_s": 4.2},
    "arena": {"buffers": 2, "free_buffers": 1, "bytes": 1 << 20,
              "in_flight": 0, "deferred": 3},
    "net": {"engine": "epoll", "writeq_bytes": 4096},
    "gauges": {"host_arena.bytes": 1 << 20},
    "tables": [{"id": 0,
                "shard": {"resident_bytes": 8192, "rows": 64,
                          "gets": 100, "adds": 50,
                          "bucket_bytes": [128] * 64,
                          "bucket_gets": [1] * 64,
                          "bucket_adds": [1] * 64},
                "history": {"windows": 0, "curve": []},
                "worker": {"agg_bytes": 256, "replica_rows": 5,
                           "replica_bytes": 1000}}]}


def test_mvtop_capacity_rows_and_rate_discipline():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import mvtop

    rows = mvtop.capacity_rows({"0": _CANNED_RANK, "1": None})
    assert len(rows) == 2
    row = rows[0]
    assert row["res_bytes"] == 8192 and row["rows"] == 64
    assert row["repl_rows"] == 5 and row["agg_B"] == 256
    assert row["wq_B"] == 4096 and row["arena_def"] == 3
    assert row["rss_MB"] == "50.0"
    assert rows[1]["res_bytes"] == "-"        # dead rank: placeholders
    table = mvtop.render(rows, mvtop._CAP_COLS)
    assert "8192" in table and "50.0" in table

    # Two-scrape growth columns: '-' before the first baseline, rates
    # after (the PR 11 discipline — never a fake zero).
    tracker = mvtop.RateTracker()
    first = mvtop.capacity_rows({"0": _CANNED_RANK}, tracker=tracker,
                                now=100.0)
    assert first[0]["b/s"] == "-" and first[0]["rss/s"] == "-"
    grown = json.loads(json.dumps(_CANNED_RANK))
    grown["tables"][0]["shard"]["resident_bytes"] = 8192 + 2000
    grown["proc"]["rss_bytes"] = 50_000_000 + 10_000_000
    second = mvtop.capacity_rows({"0": grown}, tracker=tracker,
                                 now=102.0)
    assert second[0]["b/s"] == "1000.0"
    assert second[0]["rss/s"] == "5000000.0"
    table = mvtop.render(second,
                         mvtop._CAP_COLS + mvtop._CAP_RATE_COLS)
    assert "b/s" in table and "1000.0" in table


# ----------------------------------------------------------------- mvplan

def _seeded_zipf_fleet(nshards=2, seed=7):
    """A synthetic fleet capacity doc: uniform bucket bytes + zipf
    bucket load over nshards ranks (the herd shape bench_capacity
    measures for real)."""
    rng = np.random.RandomState(seed)
    p = 1.0 / np.arange(1, 65)
    p /= p.sum()
    load = (rng.multinomial(20000, p)).astype(int)
    ranks = {}
    for sid in range(nshards):
        gets = [int(load[b]) if b % nshards == sid else 0
                for b in range(64)]
        bb = [512 if b % nshards == sid else 0 for b in range(64)]
        ranks[str(sid)] = {
            "rank": sid, "armed": True, "server_id": sid,
            "servers": nshards,
            "proc": {"rss_bytes": 1, "vm_hwm_bytes": 1, "open_fds": 1,
                     "uptime_s": 1.0},
            "arena": {}, "net": {}, "gauges": {},
            "tables": [{"id": 0,
                        "shard": {"resident_bytes": sum(bb),
                                  "rows": 64 // nshards,
                                  "gets": sum(gets), "adds": 0,
                                  "bucket_bytes": bb,
                                  "bucket_gets": gets,
                                  "bucket_adds": [0] * 64},
                        "history": {"windows": 0, "curve": []}}]}
    return {"kind": "capacity", "scope": "fleet", "ranks": ranks,
            "silent": []}


def test_mvplan_spread_under_two_on_seeded_zipf_fleet():
    doc = _seeded_zipf_fleet()
    proposal = mvplan.propose(doc)
    plan = proposal["tables"]["0"]
    assert plan["shards"] == 2
    # The zipf head makes the CURRENT weight spread imbalanced; LPT
    # packs the 64 buckets to <= 2x (in practice ~1.0).
    assert plan["spread_before"]["weight"] > plan["spread_after"]["weight"]
    assert plan["spread_after"]["weight"] <= 2.0
    assert plan["spread_after"]["bytes"] <= 2.0
    assert plan["moves"], "zipf imbalance must propose bucket moves"
    for m in plan["moves"]:
        assert m["from"] != m["to"]
        assert plan["current_map"][m["bucket"]] == m["from"]
        assert plan["map"][m["bucket"]] == m["to"]
    assert proposal["proposal_version"] == 1


def test_mvplan_uses_history_rates_when_recorded():
    doc = _seeded_zipf_fleet()
    t = doc["ranks"]["0"]["tables"][0]
    t["history"] = {"windows": 2, "span_ms": 1000,
                    "bucket_rate": [100.0] + [0.0] * 63,
                    "curve": []}
    agg = mvplan.aggregate_fleet(doc)[0]
    assert agg["rate"] is not None and agg["rate"][0] == 100.0
    weights = mvplan.bucket_weights(agg)
    assert weights[0] == max(weights)     # the rated bucket dominates


def test_mvplan_cli_strict_and_proposal_file(tmp_path):
    doc = _seeded_zipf_fleet()
    scrape = tmp_path / "fleet.json"
    scrape.write_text(json.dumps(doc))
    out_file = tmp_path / "proposal.json"
    rc = mvplan.main(["--scrape", str(scrape), "--out", str(out_file)])
    assert rc == 0
    proposal = json.loads(out_file.read_text())
    assert proposal["tables"]["0"]["spread_after"]["weight"] <= 2.0
    # Strict mode alarms on the observed zipf imbalance...
    rc = mvplan.main(["--scrape", str(scrape), "--strict",
                      "--max-spread", "1.1",
                      "--out", str(tmp_path / "p2.json")])
    assert rc == 1
    # ...and stays quiet under a generous bound.
    rc = mvplan.main(["--scrape", str(scrape), "--strict",
                      "--max-spread", "50.0",
                      "--out", str(tmp_path / "p3.json")])
    assert rc == 0
    # Unusable input is exit 2, not a stack trace.
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert mvplan.main(["--scrape", str(bad)]) == 2
