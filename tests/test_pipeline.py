"""Pipeline parallelism (GPipe over a mesh axis): exactness against the
sequential stack, gradient parity, training through the pipeline, and
the (dp, pp) combined layout."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from multiverso_tpu.parallel.pipeline import gpipe, stage_pspec


def _mlp_stage(params, h):
    """One stage = Lp dense+tanh layers, scanned."""
    def body(h, w):
        return jnp.tanh(h @ w), None

    h, _ = jax.lax.scan(body, h, params)
    return h


def _make(pp, layers_per_stage, d, seed=0):
    rng = np.random.RandomState(seed)
    w = (rng.randn(pp, layers_per_stage, d, d) / np.sqrt(d)).astype(
        np.float32)
    return jnp.asarray(w)


def _sequential(w, x_flat):
    h = x_flat
    for s in range(w.shape[0]):
        h = _mlp_stage(w[s], h)
    return h


@pytest.mark.parametrize("pp,micro", [(4, 4), (8, 3), (2, 6)])
def test_gpipe_matches_sequential(pp, micro):
    d = 16
    mesh = Mesh(np.asarray(jax.devices()[:pp]), ("pp",))
    w = _make(pp, 2, d)
    x = jnp.asarray(np.random.RandomState(1)
                    .randn(micro, 4, d).astype(np.float32))
    got = gpipe(_mlp_stage, w, x, mesh, batch_axis=None)
    want = jnp.stack([_sequential(w, x[m]) for m in range(micro)])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)


def test_gpipe_grads_match_sequential():
    pp, micro, d = 4, 3, 8
    mesh = Mesh(np.asarray(jax.devices()[:pp]), ("pp",))
    w = _make(pp, 2, d, seed=2)
    x = jnp.asarray(np.random.RandomState(3)
                    .randn(micro, 4, d).astype(np.float32))
    tgt = jnp.asarray(np.random.RandomState(4)
                      .randn(micro, 4, d).astype(np.float32))

    def loss_pipe(w):
        return jnp.mean(jnp.square(gpipe(_mlp_stage, w, x, mesh,
                                         batch_axis=None) - tgt))

    def loss_seq(w):
        out = jnp.stack([_sequential(w, x[m]) for m in range(micro)])
        return jnp.mean(jnp.square(out - tgt))

    g_pipe = jax.grad(loss_pipe)(w)
    g_seq = jax.grad(loss_seq)(w)
    np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq),
                               atol=1e-5)

    # remat_stages: the 1F1B-memory-profile knob (stage activations
    # recompute in the backward sweep) must be gradient-exact.  Jitted:
    # jax.checkpoint inside shard_map has no eager path.
    def loss_remat(w):
        return jnp.mean(jnp.square(
            gpipe(_mlp_stage, w, x, mesh, batch_axis=None,
                  remat_stages=True) - tgt))

    g_remat = jax.jit(jax.grad(loss_remat))(w)
    np.testing.assert_allclose(np.asarray(g_remat), np.asarray(g_seq),
                               atol=1e-5)


def test_gpipe_trains_on_dp_pp_mesh():
    """Combined layout: microbatch batch dim sharded over dp, stages over
    pp — the full jitted train step updates sharded stage weights and the
    loss falls."""
    dp, pp, d, micro = 2, 4, 8, 4
    mesh = Mesh(np.asarray(jax.devices()).reshape(dp, pp), ("dp", "pp"))
    w = jax.device_put(_make(pp, 2, d, seed=5),
                       NamedSharding(mesh, stage_pspec(4)))
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(micro, 4, d).astype(np.float32))
    tgt = jnp.tanh(jnp.asarray(rng.randn(micro, 4, d).astype(np.float32)))

    def loss(w):
        out = gpipe(_mlp_stage, w, x, mesh)
        return jnp.mean(jnp.square(out - tgt))

    @jax.jit
    def train(w):
        def body(w, _):
            l, g = jax.value_and_grad(loss)(w)
            return w - 0.5 * g, l

        return jax.lax.scan(body, w, None, length=300)

    w, losses = train(w)
    first, last = float(losses[0]), float(losses[-1])
    assert last < first * 0.5, (first, last)
    assert w.sharding.spec == stage_pspec(4)


# ------------------------------------------------ transformer over pp

def test_transformer_pipeline_matches_local(mv):
    """The flagship transformer's layers pipelined over pp reproduce the
    single-device forward exactly, and the trainer drives the loss down
    on a (dp, pp) mesh with stage-sharded stacked layers."""
    from dataclasses import replace

    from multiverso_tpu.models import (TransformerConfig,
                                       TransformerTrainer, init_params)
    from multiverso_tpu.models.transformer import transformer_forward

    mv.init()
    cfg = TransformerConfig(vocab_size=128, dim=32, n_layers=4, n_heads=4,
                            hidden=64, max_seq=32,
                            compute_dtype=jnp.float32, scan_layers=True,
                            pipeline_microbatches=2)
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "pp"))
    params = jax.tree_util.tree_map(jnp.asarray, init_params(cfg, seed=0))
    toks = jnp.asarray(np.random.RandomState(0).randint(
        128, size=(4, 16)).astype(np.int32))

    local_cfg = replace(cfg, pipeline_microbatches=0)
    want = transformer_forward(params, toks, local_cfg, mesh=None)
    got = transformer_forward(params, toks, cfg, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4)

    tr = TransformerTrainer(cfg, mesh, updater_type="sgd")
    assert tr.params["layers"]["wq"].sharding.spec[0] == "pp"
    toks_np = np.random.RandomState(1).randint(
        128, size=(4, 16)).astype(np.int32)
    first = tr.train_step(toks_np)
    for _ in range(15):
        last = tr.train_step(toks_np)
    assert last < first * 0.8, (first, last)


def test_transformer_pipeline_rejects_bad_configs(mv):
    from multiverso_tpu.models import TransformerConfig, init_params
    from multiverso_tpu.models.transformer import transformer_forward

    mv.init()
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("sp", "pp"))
    cfg = TransformerConfig(vocab_size=64, dim=32, n_layers=4, n_heads=4,
                            hidden=64, max_seq=32, scan_layers=True,
                            pipeline_microbatches=2)
    params = jax.tree_util.tree_map(jnp.asarray, init_params(cfg, seed=1))
    toks = jnp.zeros((4, 16), jnp.int32)
    with pytest.raises(ValueError, match="sp"):
        transformer_forward(params, toks, cfg, mesh=mesh)

    from dataclasses import replace

    mesh2 = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "pp"))
    cfg_loop = replace(cfg, scan_layers=False)
    params_loop = jax.tree_util.tree_map(
        jnp.asarray, init_params(cfg_loop, seed=1))
    with pytest.raises(ValueError, match="scan_layers"):
        transformer_forward(params_loop, toks, cfg_loop, mesh=mesh2)

    # batch 4 with M=2 microbatches over dp=4: Bm=2 not divisible
    mesh_dp4 = Mesh(np.asarray(jax.devices()).reshape(4, 2), ("dp", "pp"))
    with pytest.raises(ValueError, match="microbatches"):
        transformer_forward(params, toks, cfg, mesh=mesh_dp4)

    # pp x tp needs head/hidden/dim divisibility by tp
    from dataclasses import replace as _replace
    mesh_tp = Mesh(np.asarray(jax.devices()).reshape(2, 2, 2),
                   ("dp", "tp", "pp"))
    cfg_odd = _replace(cfg, n_heads=3, dim=48, hidden=66)
    params_odd = jax.tree_util.tree_map(
        jnp.asarray, init_params(cfg_odd, seed=2))
    with pytest.raises(ValueError, match="divisible by tp"):
        transformer_forward(params_odd, jnp.zeros((4, 16), jnp.int32),
                            cfg_odd, mesh=mesh_tp)


def test_transformer_pipeline_tp_matches_local(mv):
    """pp x tp composition (VERDICT r3 item 4): the manual-collective
    stage body (psum after row-parallel wo/w2) on a (dp, tp, pp) mesh
    reproduces the single-device forward, and the trainer's loss falls
    with stage weights sharded over BOTH pp and tp."""
    from dataclasses import replace

    from multiverso_tpu.models import (TransformerConfig,
                                       TransformerTrainer, init_params)
    from multiverso_tpu.models.transformer import transformer_forward

    mv.init()
    cfg = TransformerConfig(vocab_size=128, dim=32, n_layers=4, n_heads=4,
                            hidden=64, max_seq=32,
                            compute_dtype=jnp.float32, scan_layers=True,
                            pipeline_microbatches=2)
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 2, 2),
                ("dp", "tp", "pp"))
    params = jax.tree_util.tree_map(jnp.asarray, init_params(cfg, seed=0))
    toks = jnp.asarray(np.random.RandomState(0).randint(
        128, size=(4, 16)).astype(np.int32))

    local_cfg = replace(cfg, pipeline_microbatches=0)
    want = transformer_forward(params, toks, local_cfg, mesh=None)
    got = transformer_forward(params, toks, cfg, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4)

    tr = TransformerTrainer(cfg, mesh, updater_type="sgd")
    spec = tr.params["layers"]["wq"].sharding.spec
    assert spec[0] == "pp" and spec[-1] == "tp", spec
    toks_np = np.random.RandomState(1).randint(
        128, size=(4, 16)).astype(np.int32)
    first = tr.train_step(toks_np)
    for _ in range(15):
        last = tr.train_step(toks_np)
    assert last < first * 0.8, (first, last)
