"""Tier-1 gate for the sparse-embedding fast path (docs/embedding.md):
the row-granular serve cache (per-row versioned entries, miss-only
subset fetches, staleness-0 correctness under churn, the armed-gate
miss accounting the PR 4 review fix requires), the KV key-granular
twin, the ServeClient row cache over the native wire, the sparse table
workload wiring, the DLRM recommender app, and the native hot-key
replica — including the 2-process cross-worker invalidation bar (a
server-side add is observed within one replica lease).
"""

import os
import shutil
import subprocess
import sys
import threading

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")


# ------------------------------------------------- row-granular serve cache

def test_row_cache_hits_across_id_sets(mv):
    """A hot row caches INDIVIDUALLY: a different id set sharing it
    still hits, and the miss fetch pulls only the missing rows."""
    from multiverso_tpu import metrics

    mv.init()
    metrics.reset()
    t = mv.MatrixTable(32, 4, name="rowc", serve_cache=64)
    t.add_rows([1, 2], np.ones((2, 4), np.float32))

    fetched_sets = []
    orig = t._gather_host

    def spy(rows):
        fetched_sets.append(sorted(int(r) for r in rows))
        return orig(rows)

    t._gather_host = spy
    a = t.get_rows([1, 2, 3])
    np.testing.assert_allclose(a[0], 1.0)
    b = t.get_rows([2, 3, 4])        # rows 2, 3 cached — fetch only 4
    np.testing.assert_allclose(b[0], 1.0)
    np.testing.assert_allclose(b[2], 0.0)
    assert fetched_sets == [[1, 2, 3], [4]], fetched_sets
    assert metrics.counter("serve.cache.hit").value >= 2
    # Caller mutation cannot corrupt the cache (read-only stored rows,
    # fresh assembly per caller).
    c = t.get_rows([2])
    c[:] = 99.0
    np.testing.assert_allclose(t.get_rows([2])[0], 1.0)


def test_row_cache_staleness0_never_serves_pre_add(mv):
    """max_staleness=0: a read after an add ALWAYS reflects it — under
    sequential churn and under a concurrent writer thread."""
    mv.init()
    t = mv.MatrixTable(16, 2, name="churn", serve_cache=64,
                       max_staleness=0)
    for i in range(5):
        t.get_rows([3])
        t.add_rows([3], np.ones((1, 2), np.float32))
        np.testing.assert_allclose(t.get_rows([3])[0], float(i + 1))

    errs = []
    stop = threading.Event()

    def writer():
        try:
            for _ in range(50):
                t.add_rows([7], np.ones((1, 2), np.float32))
        except Exception as exc:  # surface in the main thread
            errs.append(exc)
        finally:
            stop.set()

    seen = [0.0]

    def reader():
        try:
            while not stop.is_set():
                v = float(t.get_rows([7])[0, 0])
                assert v >= seen[0], (v, seen[0])  # monotone: no rollback
                seen[0] = v
        except Exception as exc:
            errs.append(exc)

    th_w = threading.Thread(target=writer)
    th_r = threading.Thread(target=reader)
    th_r.start()
    th_w.start()
    th_w.join()
    th_r.join()
    assert not errs, errs
    # Every add acked: the final read must see all 50 (staleness 0).
    np.testing.assert_allclose(t.get_rows([7])[0, 0], 50.0)


def test_row_cache_whole_table_bump_not_lost(mv):
    """The PR 4 staleness-gate bug shape against the NEW path: rows
    cached while the bucket array is still lazy must not keep hitting
    across a WHOLE-TABLE bump (dense add / load_state)."""
    mv.init()
    t = mv.MatrixTable(8, 2, name="bump", serve_cache=64,
                       max_staleness=0)
    np.testing.assert_allclose(t.get_rows([1])[0], 0.0)  # cached @ v0
    t.add(np.ones((8, 2), np.float32))   # whole-table bump
    np.testing.assert_allclose(t.get_rows([1])[0], 1.0)  # MUST refetch
    # And bucket-granular bumps after the whole-table one keep working.
    t.add_rows([1], np.ones((1, 2), np.float32))
    np.testing.assert_allclose(t.get_rows([1])[0], 2.0)


def test_row_cache_miss_counts_only_when_armed(mv):
    """Satellite regression (the PR 4 review-fix discipline): a chaos-
    forced stale read must NOT accrue serve.cache.miss when the row
    cache is disarmed — flags off means no cache stats, period."""
    from multiverso_tpu import fault, metrics

    mv.init()
    metrics.reset()
    # Disarmed: serve cache off entirely.
    t0 = mv.MatrixTable(8, 2, name="gate0", serve_cache=0)
    fault.configure(sites={"serve.stale": {"times": 1}})
    try:
        m0 = metrics.counter("serve.cache.miss").value
        t0.get_rows([1])
        assert metrics.counter("serve.cache.miss").value == m0
    finally:
        fault.reset()
    # Disarmed via -serve_row_cache=false with the id-set path armed:
    # the chaos miss counts ONCE (the old path's armed accounting).
    mv.config.set_flag("serve_row_cache", False)
    t1 = mv.MatrixTable(8, 2, name="gate1", serve_cache=16)
    assert not t1._serve_row_cache
    fault.configure(sites={"serve.stale": {"times": 1}})
    try:
        m0 = metrics.counter("serve.cache.miss").value
        t1.get_rows([1])
        assert metrics.counter("serve.cache.miss").value > m0
    finally:
        fault.reset()
        mv.config.set_flag("serve_row_cache", True)
    # Armed row path: the forced miss counts too.
    t2 = mv.MatrixTable(8, 2, name="gate2", serve_cache=16)
    fault.configure(sites={"serve.stale": {"times": 1}})
    try:
        m0 = metrics.counter("serve.cache.miss").value
        t2.get_rows([1])
        assert metrics.counter("serve.cache.miss").value > m0
    finally:
        fault.reset()


def test_row_cache_disabled_flag_falls_back(mv):
    """-serve_row_cache=false reverts to the PR 4 id-set entries: the
    values stay correct, and a repeated identical id set still hits."""
    from multiverso_tpu import metrics

    mv.config.set_flag("serve_row_cache", False)
    try:
        mv.init()
        metrics.reset()
        t = mv.MatrixTable(16, 2, name="fallback", serve_cache=32)
        t.add_rows([5], np.ones((1, 2), np.float32))
        a = t.get_rows([5, 6])
        h0 = metrics.counter("serve.cache.hit").value
        b = t.get_rows([5, 6])               # identical set: hits
        np.testing.assert_allclose(a, b)
        assert metrics.counter("serve.cache.hit").value > h0
        t.add_rows([5], np.ones((1, 2), np.float32))
        np.testing.assert_allclose(t.get_rows([5, 6])[0], 2.0)
    finally:
        mv.config.set_flag("serve_row_cache", True)


def test_kv_key_granular_cache(mv):
    from multiverso_tpu import metrics

    mv.init()
    metrics.reset()
    t = mv.KVTable(name="kvrow", serve_cache=64, max_staleness=0)
    t.add({"a": 1.0, "b": 2.0})
    r1 = t.get(["a", "b"])
    h0 = metrics.counter("serve.cache.hit").value
    r2 = t.get(["b", "c"])               # b cached, c fresh-missing
    assert metrics.counter("serve.cache.hit").value > h0
    assert float(r1["b"]) == 2.0 and float(r2["b"]) == 2.0
    assert float(r2["c"]) == 0.0
    t.add({"b": 1.0})
    assert float(t.get(["b"])["b"]) == 3.0   # staleness 0: fresh
    # raw() mirror still tracks every Get()'d key (reference contract).
    assert set(t.raw) >= {"a", "b", "c"}


def test_sparse_table_workload_notes_hot_traffic(mv):
    """Satellite: the sparse table's mirror hits feed the hot-key
    sketch — without the wiring, exactly the HOT rows (served from the
    mirror, never reaching the base keys= hook) would be invisible."""
    mv.config.set_flag("hotkey_enabled", True)
    mv.init()
    t = mv.SparseMatrixTable(32, 2, name="spw")
    for _ in range(5):
        t.get_rows([3, 4])               # first call misses, rest hit
    rep = t.workload_report()
    assert rep["armed"], rep
    # Without the wiring only the FIRST call (the mirror miss) would be
    # visible: gets would read 1 and every bucket load 1.  With it, all
    # five calls count and the touched buckets carry one note per call.
    assert rep["gets"] == 5, rep
    assert rep["bucket_load_max"] == 5, rep
    top = [e["key"] for e in rep["hotkeys"]["topk"]]
    assert "3" in top and "4" in top, top


# ------------------------------------------------------------- DLRM app

def test_dlrm_trains_and_serves(mv):
    mv.init()
    from multiverso_tpu.apps import DLRMRecommender

    m = DLRMRecommender(num_users=128, num_items=64, dim=8,
                        learning_rate=0.3, serve_cache=256)
    losses = m.train_epoch(batches=30, batch=128, seed=3)
    first = float(np.mean(losses[:5]))
    last = float(np.mean(losses[-5:]))
    assert last < first, (first, last)   # zipf head memorized
    s = m.scores(0, [0, 1, 2, 3])
    assert s.shape == (4,) and np.isfinite(s).all()
    rep = m.hot_report()
    assert rep["armed"] and rep["gets"] > 0
    m.close()


# --------------------------------------------------- native replica plane

@pytest.fixture()
def native_rt():
    from multiverso_tpu import native as nat

    nat.ensure_built()
    rt = nat.NativeRuntime(args=["-log_level=error", "-hotkey_topk=16"])
    yield rt
    rt.set_hotkey_replica(False)
    rt.shutdown()


@needs_gxx
def test_native_replica_serves_and_invalidates(native_rt):
    """Single-process replica protocol: pushed top-K rows serve hits;
    an acked add stales the ledger at replica_max_staleness=0 so the
    next read returns the NEW value (red on a replica path without
    invalidation)."""
    rt = native_rt
    h = rt.new_matrix_table(64, 4)
    rt.matrix_add_rows(h, [1, 2], np.ones((2, 4), np.float32))
    for _ in range(8):
        rt.matrix_get_rows(h, [1, 2], 4)
    rt.set_hotkey_replica(True)
    rt.replica_refresh(h)
    stats0 = rt.replica_stats(h)
    assert stats0["rows"] >= 2 and stats0["pushes"] >= 1, stats0
    got = rt.matrix_get_rows(h, [1, 2], 4)
    np.testing.assert_allclose(got, 1.0)
    stats1 = rt.replica_stats(h)
    assert stats1["hits"] > stats0["hits"], (stats0, stats1)
    # Staleness-0 freshness after an acked add.
    rt.matrix_add_rows(h, [1], np.full((1, 4), 5.0, np.float32))
    np.testing.assert_allclose(rt.matrix_get_rows(h, [1], 4)[0], 6.0)
    # The "hotkeys" ops report carries the replica ledger.
    rep = rt.hot_keys(h)
    assert rep and "replica" in rep[0], rep
    assert rep[0]["replica"]["pushes"] >= 1, rep


@needs_gxx
def test_native_replica_disarmed_is_inert(native_rt):
    rt = native_rt
    h = rt.new_matrix_table(16, 2)
    rt.matrix_add_rows(h, [1], np.ones((1, 2), np.float32))
    for _ in range(4):
        rt.matrix_get_rows(h, [1], 2)
    stats = rt.replica_stats(h)
    assert stats["hits"] == 0 and stats["refreshes"] == 0, stats


@needs_gxx
def test_anon_client_replica_pull(tmp_path):
    """Anonymous serve clients participate (docs/embedding.md): a raw
    RequestReplica frame pulls the shard's hot rows + versions."""
    import socket

    from multiverso_tpu import native as nat
    from multiverso_tpu.serve.wire import AnonServeClient

    nat.ensure_built()
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    mf = tmp_path / "machines"
    mf.write_text(f"127.0.0.1:{port}\n127.0.0.1:1\n")
    # A 2-line machine file with only rank 0 alive still serves
    # anonymous clients on rank 0's listen port (epoll engine).
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    code = (
        "import sys, time; import numpy as np\n"
        "sys.path.insert(0, %r)\n"
        "from multiverso_tpu import native as nat\n"
        "rt = nat.NativeRuntime(args=['-machine_file=%s', '-rank=0',"
        " '-log_level=error', '-hotkey_topk=8',"
        " '-barrier_timeout_ms=1000'])\n"
        "h = rt.new_matrix_table(8, 2)\n"
        "rt.matrix_add_rows(h, [1], np.ones((1, 2), np.float32))\n"
        "for _ in range(6): rt.matrix_get_rows(h, [1], 2)\n"
        "print('SERVING', flush=True)\n"
        "time.sleep(8)\n" % (REPO, str(mf).replace('\\', '/')))
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    try:
        assert "SERVING" in proc.stdout.readline()
        with AnonServeClient(f"127.0.0.1:{port}", timeout=10) as c:
            rep = c.get_replica(0)
        assert 1 in rep, sorted(rep)
        version, row = rep[1]
        assert version >= 1
        np.testing.assert_allclose(row, 1.0)
    finally:
        proc.kill()
        proc.communicate(timeout=10)


@needs_gxx
def test_replica_cross_worker_invalidation_2proc(tmp_path):
    """Acceptance bar: a hot row updated ON THE SERVER (by the other
    worker — no ack ever reaches this rank's version ledger) is
    observed fresh within one replica lease; no torn or rolled-back
    value is ever served."""
    import socket

    from multiverso_tpu import native as nat

    nat.ensure_built()
    socks = [socket.socket() for _ in range(2)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    eps = [f"127.0.0.1:{s.getsockname()[1]}" for s in socks]
    for s in socks:
        s.close()
    mf = tmp_path / "machines"
    mf.write_text("\n".join(eps) + "\n")
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    worker = os.path.join(REPO, "tests", "embedding_replica_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(mf), str(r)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for r in range(2)]
    outs = [p.communicate(timeout=120)[0] for p in procs]
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"REPLICA_WORKER_OK {r}" in out, out[-2000:]
    assert "REPLICA_FRESH_MS" in outs[1]


# -------------------------------------------------- ServeClient row cache

@needs_gxx
def test_serveclient_row_granular_over_native(native_rt):
    from multiverso_tpu import metrics
    from multiverso_tpu.serve.client import ServeClient

    metrics.reset()
    rt = native_rt
    h = rt.new_matrix_table(64, 4)
    rt.matrix_add_rows(h, [1, 2], np.ones((2, 4), np.float32))
    sc = ServeClient(rt, cache_entries=64, max_staleness=0,
                     lease_ms=500.0, window_us=0.0)
    a = sc.matrix_get_rows(h, [1, 2, 3], 4)
    h0 = metrics.counter("serve.cache.hit").value
    b = sc.matrix_get_rows(h, [2, 3, 4], 4)   # 2, 3 hit; 4 fetches
    assert metrics.counter("serve.cache.hit").value >= h0 + 2
    np.testing.assert_allclose(a[1], b[0])
    # Write-through + staleness 0: the add invalidates, the next read
    # reflects it.
    sc.matrix_add_rows(h, [2], np.ones((1, 4), np.float32))
    np.testing.assert_allclose(sc.matrix_get_rows(h, [2], 4)[0], 2.0)
    # Duplicate ids in one request assemble correctly.
    d = sc.matrix_get_rows(h, [1, 1, 2], 4)
    np.testing.assert_allclose(d[0], d[1])
    # KV key-granular twin.
    hk = rt.new_kv_table()
    rt.kv_add(hk, ["x", "y"], [1.0, 2.0])
    v1 = sc.kv_get(hk, ["x", "y"])
    v2 = sc.kv_get(hk, ["y", "z"])
    assert v1[1] == 2.0 and v2[0] == 2.0 and v2[1] == 0.0
    assert sc.replica_stats(h)["hits"] >= 0  # surface exists
