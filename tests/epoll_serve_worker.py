"""Serve-fleet holder for the epoll transport tests (not a pytest module).

Run as ``python epoll_serve_worker.py <machine_file> <rank> [extra flags
...]``: joins a native fleet on the epoll engine, registers one
64-element ArrayTable (id 0), rank 0 blocking-adds ones so every shard
holds 1.0, rendezvouses, prints ``SERVE_READY`` — and then HOLDS the
fleet up for anonymous wire clients until a line arrives on stdin.  On
release it prints the fan-in counters (``FANIN accepted=N active=N
shed=N``), rendezvouses again, and exits with ``SERVE_WORKER_OK <rank>``.

The pytest side (tests/test_epoll_net.py) talks to rank 0's listen port
with raw sockets while the fleet is held.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from multiverso_tpu import native as nat  # noqa: E402

SIZE = 64


def main() -> int:
    mf, rank = sys.argv[1], int(sys.argv[2])
    extra = sys.argv[3:]
    rt = nat.NativeRuntime(args=[f"-machine_file={mf}", f"-rank={rank}",
                                 "-log_level=error",
                                 "-rpc_timeout_ms=30000",
                                 "-barrier_timeout_ms=60000", *extra])
    # Engine-aware: an explicit -net_engine in the extra flags (the
    # uring suite passes one) must have taken effect; default is epoll.
    want = "epoll"
    for flag in extra:
        if flag.startswith("-net_engine="):
            want = flag.split("=", 1)[1]
    assert rt.net_engine() == want, rt.net_engine()
    h = rt.new_array_table(SIZE)
    assert h == 0, h
    rt.barrier()
    if rank == 0:
        rt.array_add(h, np.ones(SIZE, np.float32))
    rt.barrier()
    print("SERVE_READY", flush=True)
    sys.stdin.readline()          # held until the test releases us
    st = rt.fanin_stats()
    print(f"FANIN accepted={st['accepted_total']} "
          f"active={st['active_clients']} shed={st['client_shed']}",
          flush=True)
    rt.barrier()
    rt.shutdown()
    print(f"SERVE_WORKER_OK {rank}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
