"""Tier-1 gate for the live introspection plane (docs/observability.md):
exemplar capture/parsing, the flight recorder (Python + native), the
in-band OpsQuery wire protocol (local + fleet scope), and the
fleet-scrape-under-failure contract — a SIGKILLed server rank must show
up dead in the fleet snapshot within the lease window, the dead-peer
trigger must dump a black box, and that dump's spans must correlate by
trace id with the surviving rank's exported trace.
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")


# ------------------------------------------------------- prometheus parsing

def test_parse_prometheus_values_and_exemplars():
    from multiverso_tpu.ops.introspect import parse_prometheus

    text = (
        "# TYPE lat histogram\n"
        'lat_bucket{le="0.001"} 3 # {trace_id="0x1002a"} 0.0009\n'
        'lat_bucket{le="+Inf"} 4\n'
        "lat_sum 0.005\n"
        "lat_count 4\n"
        'up{rank="1"} 1\n')
    values, exemplars = parse_prometheus(text)
    assert values['lat_bucket{le="0.001"}'] == 3.0
    assert values['up{rank="1"}'] == 1.0
    assert values["lat_count"] == 4.0
    assert exemplars['lat_bucket{le="0.001"}']["trace_id"] == "0x1002a"
    assert 'lat_bucket{le="+Inf"}' not in exemplars


# ------------------------------------------------------- python exemplars

@pytest.fixture()
def registry():
    from multiverso_tpu import metrics, tracing

    metrics.reset()
    tracing.disable()
    tracing.clear()
    yield metrics
    metrics.reset()
    tracing.disable()
    tracing.clear()


def test_histogram_exemplar_capture_and_quantile_link(registry):
    """An observation inside a span stamps its trace id as the bucket's
    exemplar; exemplar(q) returns the id of the quantile's bucket."""
    from multiverso_tpu import tracing

    tracing.enable(rank=0)
    h = registry.histogram("t.lat", bounds=[1.0, 10.0, 100.0])
    for _ in range(50):
        h.observe(0.5)                       # no active span: no id
    with tracing.span("slow.op") as tid:
        h.observe(50.0)                      # the p99 bucket
    assert tid != 0
    assert h.exemplar(0.99) == tid
    assert h.exemplar(0.50) == 0             # bulk bucket: never spanned
    assert h.to_dict()["exemplar_p99"] == f"{tid:#x}"
    # Explicit trace_id overrides the thread-local.
    h.observe(500.0, trace_id=0xABC)
    assert h.exemplar(1.0) == 0xABC


def test_render_prometheus_exemplars_opt_in(registry):
    """Exemplars render only on request (OpenMetrics suffix breaks
    plain-Prometheus parsers, so the flush file stays vanilla)."""
    h = registry.histogram("t.ex", bounds=[1.0])
    h.observe(0.5, trace_id=0x77)
    plain = registry.render_prometheus()
    assert "trace_id" not in plain
    rich = registry.render_prometheus(exemplars=True)
    assert '# {trace_id="0x77"} 1.0' in rich
    # Round-trips through the scrape parser.
    from multiverso_tpu.ops.introspect import parse_prometheus

    _, exemplars = parse_prometheus(rich)
    assert exemplars['t_ex_bucket{le="1.0"}']["trace_id"] == "0x77"


def test_parse_native_dump_exemplar_field(registry):
    """The 5th tab field (per-bucket exemplars) is parsed when present
    and optional when absent (pre-exemplar dumps)."""
    buckets = ",".join(["1"] + ["0"] * 27)
    exemplars = ",".join(["4242"] + ["0"] * 27)
    new = f"op\t1\t0.5\t0.5\t{buckets}\t{exemplars}\n"
    old = f"op\t1\t0.5\t0.5\t{buckets}\n"
    got_new = registry.parse_native_dump(new)["op"]
    got_old = registry.parse_native_dump(old)["op"]
    assert len(got_new) == 5 and got_new[4][0] == 4242
    assert len(got_old) == 4

    class Stub:
        def dump_monitors(self):
            return registry.parse_native_dump(new)

    registry.bridge_native(Stub())
    h = registry.REGISTRY.histogram("native.op",
                                    bounds=registry.NATIVE_TIME_BUCKETS)
    assert h.exemplar(0.5) == 4242


# ------------------------------------------------------- flight recorder

def test_flight_recorder_dump_and_trace_correlation(registry, tmp_path):
    from multiverso_tpu import config, tracing
    from multiverso_tpu.ops.flight_recorder import FlightRecorder

    tracing.enable(rank=3)
    with tracing.span("doomed.op") as tid:
        pass
    config.set_flag("trace_dir", str(tmp_path))
    try:
        rec = FlightRecorder(max_events=4)
        rec.attach(rank=3)
        for i in range(10):                  # ring is bounded: newest win
            rec.record("step", f"s{i}")
        path = rec.trigger("unit_test_failure")
        assert path == str(tmp_path / "blackbox_rank3.json")
        doc = json.load(open(path))
        assert doc["reason"] == "unit_test_failure"
        assert doc["rank"] == 3
        kinds = [e["kind"] for e in doc["events"]]
        assert kinds[-1] == "trigger" and len(doc["events"]) == 4
        assert any(s["trace_id"] == f"{tid:#x}" for s in doc["spans"])
        assert rec.triggers == 1
    finally:
        config.set_flag("trace_dir", "")


def test_flight_recorder_no_trace_dir_records_only(registry):
    from multiverso_tpu.ops.flight_recorder import FlightRecorder

    rec = FlightRecorder()
    assert rec.trigger("nowhere-to-dump") is None
    assert rec.events()[-1]["detail"] == "nowhere-to-dump"


def test_checkpoint_corrupt_triggers_flight_recorder(registry, tmp_path):
    """CheckpointCorrupt is a flight-recorder trigger: constructing one
    (even on the tolerated restore-fallback path) dumps the box."""
    from multiverso_tpu import config
    from multiverso_tpu.checkpoint import CheckpointCorrupt
    from multiverso_tpu.ops.flight_recorder import recorder

    config.set_flag("trace_dir", str(tmp_path))
    recorder.reset()
    recorder.attach(rank=0)
    try:
        CheckpointCorrupt("ckpt.bin: CRC mismatch")
        box = tmp_path / "blackbox_rank0.json"
        assert box.exists()
        doc = json.load(open(box))
        assert doc["reason"].startswith("checkpoint_corrupt")
    finally:
        config.set_flag("trace_dir", "")
        recorder.reset()


# ------------------------------------------------------------ native plane

@pytest.fixture()
def native_rt(tmp_path):
    from multiverso_tpu import native as nat

    nat.ensure_built()
    rt = nat.NativeRuntime(args=["-log_level=error", "-trace=true",
                                 f"-trace_dir={tmp_path}"])
    yield rt
    rt.shutdown()


@needs_gxx
def test_native_ops_report_kinds(native_rt):
    import numpy as np

    h = native_rt.new_array_table(16)
    native_rt.array_add(h, np.ones(16, np.float32))
    native_rt.array_get(h, 16)

    health = json.loads(native_rt.ops_report("health"))
    assert health["started"] and health["ready"] and health["healthy"]
    assert health["engine"] == "local" and health["size"] == 1

    tables = json.loads(native_rt.ops_report("tables"))
    assert tables[0]["version"] >= 1
    assert tables[0]["codec"] == "raw"
    assert tables[0]["bucket_version_max"] >= \
        tables[0]["bucket_version_min"]

    metrics_text = native_rt.ops_report("metrics")
    assert "ArrayServer::ProcessGet_bucket" in metrics_text
    assert "trace_id=" in metrics_text      # exemplars (tracing armed)

    err = json.loads(native_rt.ops_report("nonsense"))
    assert "unknown ops kind" in err["error"]


@needs_gxx
def test_native_ops_host_metrics_push_wins(native_rt):
    native_rt.set_ops_host_metrics("# TYPE pushed counter\npushed 7.0\n")
    assert native_rt.ops_report("metrics").startswith("# TYPE pushed")
    native_rt.set_ops_host_metrics("")
    assert "pushed 7.0" not in native_rt.ops_report("metrics")


@needs_gxx
def test_native_blackbox_event_and_trigger(native_rt, tmp_path):
    import numpy as np

    h = native_rt.new_array_table(8)
    native_rt.array_get(h, 8)
    native_rt.blackbox_event("test", "before-the-crash")
    native_rt.blackbox_trigger("unit-trigger")
    doc = json.load(open(tmp_path / "blackbox_rank0.json"))
    assert doc["reason"] == "unit-trigger"
    assert any(e["kind"] == "test" and e["detail"] == "before-the-crash"
               for e in doc["events"])
    assert any(e["kind"] == "lifecycle" for e in doc["events"])
    assert doc["spans"] and all("trace_id" in s for s in doc["spans"])
    assert "ArrayWorker::Get" in doc["monitors"]


# ------------------------------------------------------------- wire plane

def _spawn_fleet(script, tmp_path, nranks=2, extra=()):
    socks = [socket.socket() for _ in range(nranks)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    eps = [f"127.0.0.1:{s.getsockname()[1]}" for s in socks]
    for s in socks:
        s.close()
    mf = os.path.join(str(tmp_path), "machines")
    with open(mf, "w") as f:
        f.write("\n".join(eps) + "\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", script), mf,
             str(r), *map(str, extra)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env)
        for r in range(nranks)
    ]
    return eps, procs


def _release(procs, marker, timeout=120):
    outs = []
    for p in procs:
        if p.poll() is None:
            try:
                p.stdin.write("\n")
                p.stdin.flush()
            except (BrokenPipeError, OSError):
                pass
    for p in procs:
        try:
            outs.append(p.communicate(timeout=timeout)[0])
        except subprocess.TimeoutExpired:
            p.kill()
            outs.append(p.communicate()[0])
    return outs


@needs_gxx
def test_wire_scrape_local_and_fleet(tmp_path):
    """An anonymous socket scrapes one rank (local scope) and the whole
    fleet (fleet scope: per-rank labels + explicit up markers)."""
    from multiverso_tpu import native as nat

    nat.ensure_built()
    from multiverso_tpu.ops.introspect import OpsClient

    eps, procs = _spawn_fleet("epoll_serve_worker.py", tmp_path,
                              extra=("-trace=true",))
    try:
        for p in procs:
            assert "SERVE_READY" in p.stdout.readline()
        with OpsClient(eps[0], timeout=15) as c:
            health = c.health()
            assert health["rank"] == 0 and health["size"] == 2
            assert health["engine"] == "epoll"
            values, _ = c.metrics(fleet=True)
            assert values['mv_ops_rank_up{rank="0"}'] == 1.0
            assert values['mv_ops_rank_up{rank="1"}'] == 1.0
            assert any('rank="1"' in k and "_bucket" in k
                       for k in values)
            fleet = c.health(fleet=True)
            assert fleet["silent"] == [] and fleet["dead"] == []
            assert fleet["ranks"]["1"]["rank"] == 1
            ft = c.fleet_tables()
            assert ft["ranks"]["0"][0]["id"] == 0
    finally:
        outs = _release(procs, "SERVE_WORKER_OK")
    for out in outs:
        assert "SERVE_WORKER_OK" in out, out[-2000:]


@needs_gxx
def test_fleet_scrape_marks_killed_rank_dead_and_dumps_blackbox(tmp_path):
    """The acceptance chaos path: SIGKILL a server rank mid-run — the
    fleet snapshot marks it dead within the lease window, the dead-peer
    trigger dumps blackbox_rank0.json, and the dump's spans correlate
    by trace id with the surviving rank's exported trace."""
    from multiverso_tpu import native as nat

    nat.ensure_built()
    from multiverso_tpu.ops.introspect import OpsClient

    eps, procs = _spawn_fleet("ops_fleet_worker.py", tmp_path,
                              extra=(str(tmp_path),))
    try:
        for p in procs:
            assert "OPS_FLEET_READY" in p.stdout.readline()

        procs[1].send_signal(signal.SIGKILL)
        procs[1].wait(timeout=30)

        # Dead-peer trigger: the black box must land within the lease
        # window (400 ms timeout + scan cadence; 15 s is generous).
        box_path = os.path.join(str(tmp_path), "blackbox_rank0.json")
        deadline = time.time() + 15
        doc = None
        while time.time() < deadline:
            if os.path.exists(box_path):
                try:
                    doc = json.load(open(box_path))
                    break
                except ValueError:
                    pass                      # mid-rename: retry
            time.sleep(0.1)
        assert doc is not None, "blackbox_rank0.json never appeared"
        assert doc["reason"].startswith("dead_peer: rank 1"), doc["reason"]

        # Fleet snapshot from the SURVIVOR: rank 1 dead + silent.
        with OpsClient(eps[0], timeout=15) as c:
            fleet = c.health(fleet=True)
            assert fleet["dead"] == [1], fleet
            assert fleet["silent"] == [1], fleet
            assert fleet["ranks"]["1"] is None
            assert fleet["ranks"]["0"]["healthy"] is False  # dead peer
            values, _ = c.metrics(fleet=True)
            assert values['mv_ops_rank_up{rank="1"}'] == 0.0
            assert values['mv_ops_rank_dead{rank="1"}'] == 1.0

        # mvtop's fleet table renders the corpse as an explicit row.
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import mvtop

        rows = mvtop.collect([eps[0]], fleet=True, timeout=15)
        by_rank = {r["rank"]: r for r in rows}
        assert by_rank["1"]["up"] == "NO"
        assert by_rank["0"]["up"] == "yes"

        # Blackbox spans correlate with the surviving rank's trace.
        trace = json.load(
            open(os.path.join(str(tmp_path), "trace_rank0.json")))
        trace_ids = {e["args"].get("trace_id")
                     for e in trace["traceEvents"]} - {None}
        box_ids = {s["trace_id"] for s in doc["spans"]} - {"0x0"}
        assert box_ids & trace_ids, (sorted(box_ids)[:4],
                                     sorted(trace_ids)[:4])
    finally:
        outs = _release(procs, "OPS_FLEET_OK")
    assert any("OPS_FLEET_OK 0" in out for out in outs), outs[0][-2000:]
