"""Tier-1 gate for the delivery-audit plane (docs/observability.md
"audit plane"): the wire-framing mirror (version tolerance), the fleet
diff logic (lost vs unacked vs dup vs gap), seq/agg-range accounting
through the native books, checksum stability across bit-exact assign
stores, the 2-proc chaos acceptance on BOTH wire engines (injected
dups named exactly, zero lost acked adds), the seeded silent-loss →
``audit_gap`` blackbox path, and the flight-recorder dump rotation
regression (two triggers leave two readable dumps)."""

import json
import os
import shutil
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")


# ------------------------------------------------------------- wire mirror

def test_audit_stamp_frame_roundtrip():
    from multiverso_tpu.serve.wire import (AUDIT, FLAG_AUDIT, MSG,
                                           pack_frame, unpack_frame)

    frame = pack_frame(MSG["RequestGet"], 0, 7, audit=(3, 9))
    body = frame[8:]
    msg = unpack_frame(body)
    assert msg["flags"] & FLAG_AUDIT
    assert msg["audit"] == (3, 9)
    assert AUDIT.size == 16


def test_audit_and_timing_compose_in_serialize_order():
    """Trail first, stamp second — the native Serialize order; both
    optional blocks in one frame must round-trip with blobs intact."""
    from multiverso_tpu.serve.wire import MSG, pack_frame, unpack_frame

    frame = pack_frame(MSG["RequestGet"], 1, 2, blobs=[b"payload8"],
                       timing=True, audit=(5, 5))
    msg = unpack_frame(frame[8:])
    assert msg["timing"] is not None and msg["timing"][0] > 0
    assert msg["audit"] == (5, 5)
    assert msg["blobs"] == [b"payload8"]


def test_unflagged_frame_parses_exactly_as_before():
    """Version tolerance: a pre-audit frame (no flag bits) must parse
    with audit=None and timing=None — the old layout unchanged."""
    from multiverso_tpu.serve.wire import MSG, pack_frame, unpack_frame

    msg = unpack_frame(pack_frame(MSG["RequestVersion"], 0, 1)[8:])
    assert msg["audit"] is None and msg["timing"] is None


# --------------------------------------------------------------- fleet diff

def _fleet(ranks, silent=()):
    return {"ranks": ranks, "silent": list(silent)}


def _rank_doc(rank, tables):
    return {"rank": rank, "armed": True, "tables": tables}


def _server(origins, anomalies=()):
    return {"origins": origins, "anomalies": list(anomalies),
            "anomaly_total": len(anomalies)}


def _origin(origin, watermark, **kw):
    base = {"origin": origin, "watermark": watermark, "applied": 0,
            "covered": 0, "dups": 0, "reorders": 0,
            "pending_dropped": 0, "pending": [], "gap_fired": False}
    base.update(kw)
    return base


def test_diff_fleet_clean_when_acked_covered():
    from multiverso_tpu.ops.audit import diff_fleet

    fleet = _fleet({
        "0": _rank_doc(0, [{"id": 0,
                            "worker": {"shards": [
                                {"shard": 0, "sent": 5, "acked": 5}]},
                            "server": _server([_origin(1, 7),
                                               _origin(0, 5)])}]),
        "1": _rank_doc(1, [{"id": 0,
                            "worker": {"shards": [
                                {"shard": 0, "sent": 7, "acked": 7}]},
                            "server": _server([])}]),
    })
    assert diff_fleet(fleet) == []


def test_diff_fleet_names_lost_acked_adds():
    """acked > watermark on the owning shard = the contract violation,
    named with its seq range."""
    from multiverso_tpu.ops.audit import diff_fleet

    fleet = _fleet({
        "0": _rank_doc(0, [{"id": 0, "server": _server([_origin(1, 4)])}]),
        "1": _rank_doc(1, [{"id": 0,
                            "worker": {"shards": [
                                {"shard": 0, "sent": 9, "acked": 9}]},
                            "server": _server([])}]),
    })
    findings = diff_fleet(fleet)
    lost = [f for f in findings if f["kind"] == "lost"]
    assert len(lost) == 1
    assert lost[0]["origin"] == 1 and lost[0]["shard"] == 0
    assert (lost[0]["seq_lo"], lost[0]["seq_hi"]) == (5, 9)
    # Severity order: the loss leads the list.
    assert findings[0]["kind"] == "lost"


def test_diff_fleet_unacked_tail_is_not_lost():
    """sent > acked with the watermark covering acked = a SIGKILLed
    worker's async tail: reported as never-acked, not lost."""
    from multiverso_tpu.ops.audit import diff_fleet

    fleet = _fleet({
        "0": _rank_doc(0, [{"id": 0, "server": _server([_origin(1, 3)])}]),
        "1": _rank_doc(1, [{"id": 0,
                            "worker": {"shards": [
                                {"shard": 0, "sent": 8, "acked": 3}]},
                            "server": _server([])}]),
    })
    findings = diff_fleet(fleet)
    kinds = [f["kind"] for f in findings]
    assert "unacked" in kinds and "lost" not in kinds
    tail = next(f for f in findings if f["kind"] == "unacked")
    assert (tail["seq_lo"], tail["seq_hi"]) == (4, 8)


def test_diff_fleet_names_dups_gaps_and_silent_ranks():
    from multiverso_tpu.ops.audit import diff_fleet

    anomalies = [{"kind": "dup", "origin": 1, "seq_lo": 4, "seq_hi": 4,
                  "ts_ms": 1}]
    fleet = _fleet({
        "0": _rank_doc(0, [{"id": 0, "server": _server(
            [_origin(1, 3, dups=1, reorders=2, pending=[[6, 7]],
                     gap_fired=True)], anomalies)}]),
    }, silent=[2])
    findings = diff_fleet(fleet)
    kinds = [f["kind"] for f in findings]
    assert "dup" in kinds and "gap" in kinds and "silent" in kinds
    dup = next(f for f in findings if f["kind"] == "dup")
    assert dup["count"] == 1 and dup["seqs"] == [(4, 4)]
    gap = next(f for f in findings if f["kind"] == "gap")
    assert (gap["seq_lo"], gap["seq_hi"]) == (4, 5)  # missing 4..5


def test_confirm_lost_drops_transient_race():
    """A 'lost' verdict from a non-atomic scrape is believed only when
    the refreshed snapshot still shows it for the same stream."""
    from multiverso_tpu.ops.audit import confirm_lost

    first = [{"kind": "lost", "table": 0, "origin": 1, "shard": 0,
              "seq_lo": 5, "seq_hi": 9}]
    refreshed_clean = [{"kind": "dup", "table": 0, "origin": 1,
                        "shard": 0, "count": 1}]
    out = confirm_lost(first, refreshed_clean)
    assert [f["kind"] for f in out] == ["dup"]
    refreshed_still = refreshed_clean + [
        {"kind": "lost", "table": 0, "origin": 1, "shard": 0,
         "seq_lo": 5, "seq_hi": 9}]
    out = confirm_lost(first, refreshed_still)
    assert [f["kind"] for f in out] == ["lost", "dup"]


def test_checksum_divergence_primitive():
    from multiverso_tpu.ops.audit import checksum_divergence

    assert checksum_divergence([1, 2, 3], [1, 2, 3]) == []
    assert checksum_divergence([1, 2, 3], [1, 9, 3]) == [1]
    assert checksum_divergence([1], [1, 2]) == [0, 1]


def test_audit_rows_lag_and_missing_origin_ledger():
    from multiverso_tpu.ops.audit import audit_rows

    fleet = _fleet({
        "0": _rank_doc(0, [{"id": 0,
                            "server": _server([_origin(1, 4),
                                               _origin(9, 2)])}]),
        "1": _rank_doc(1, [{"id": 0,
                            "worker": {"shards": [
                                {"shard": 0, "sent": 6, "acked": 6}]},
                            "server": _server([])}]),
    })
    rows = audit_rows(fleet)
    by_origin = {r["origin"]: r for r in rows}
    assert by_origin[1]["acked"] == 6 and by_origin[1]["lag"] == 2
    # Origin 9 has no reachable ledger: '-' semantics (None), never 0.
    assert by_origin[9]["acked"] is None and by_origin[9]["lag"] is None


def test_mvtop_audit_rate_discipline_dash_before_first_scrape():
    """The --audit watch column obeys the PR 11 rate discipline: '-'
    until two scrapes exist, then a real dup/s figure."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import mvtop

    tracker = mvtop.RateTracker()
    first = tracker.update("0/0/1", {"dups": 10}, now=100.0)
    assert first.get("dup/s", "-") == "-"
    second = tracker.update("0/0/1", {"dups": 30}, now=110.0)
    assert second["dup/s"] == "2.0"


# ------------------------------------------------- flight-recorder rotation

def test_blackbox_rotation_keeps_both_dumps(tmp_path):
    """Satellite regression: two distinct triggers on one rank must
    leave TWO readable dumps (timestamped archives + manifest); the
    canonical blackbox_rank<r>.json stays the latest."""
    from multiverso_tpu import config
    from multiverso_tpu.ops.flight_recorder import FlightRecorder

    config.set_flag("trace_dir", str(tmp_path))
    try:
        rec = FlightRecorder()
        rec.attach(rank=0)
        rec.record("phase", "one")
        assert rec.trigger("first failure")
        rec.record("phase", "two")
        assert rec.trigger("second failure")

        manifest = json.load(
            open(tmp_path / "blackbox_rank0.manifest.json"))
        assert len(manifest["dumps"]) == 2
        assert manifest["total_triggers"] == 2
        docs = [json.load(open(tmp_path / name))
                for name in manifest["dumps"]]
        assert docs[0]["reason"] == "first failure"
        assert docs[1]["reason"] == "second failure"
        # Canonical latest-name contract: existing readers keep working.
        latest = json.load(open(tmp_path / "blackbox_rank0.json"))
        assert latest["reason"] == "second failure"
    finally:
        config.set_flag("trace_dir", "")


def test_blackbox_rotation_prunes_to_keep(tmp_path):
    from multiverso_tpu import config
    from multiverso_tpu.ops.flight_recorder import FlightRecorder

    config.set_flag("trace_dir", str(tmp_path))
    config.set_flag("blackbox_keep", 2)
    try:
        rec = FlightRecorder()
        rec.attach(rank=3)
        for i in range(5):
            rec.trigger(f"failure {i}")
        manifest = json.load(
            open(tmp_path / "blackbox_rank3.manifest.json"))
        assert len(manifest["dumps"]) == 2
        assert manifest["total_triggers"] == 5
        archives = [p for p in os.listdir(tmp_path)
                    if p.startswith("blackbox_rank3.")
                    and p.endswith(".json")
                    and "manifest" not in p
                    and p != "blackbox_rank3.json"]
        assert sorted(archives) == sorted(manifest["dumps"])
        reasons = {json.load(open(tmp_path / n))["reason"]
                   for n in manifest["dumps"]}
        assert reasons == {"failure 3", "failure 4"}
    finally:
        config.set_flag("trace_dir", "")
        config.set_flag("blackbox_keep", 4)


# --------------------------------------------------------- native 2-proc

def _run_fleet(tmp_path, mode, extra=(), nranks=2):
    from multiverso_tpu import native as nat

    nat.ensure_built()
    socks = [socket.socket() for _ in range(nranks)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    eps = [f"127.0.0.1:{s.getsockname()[1]}" for s in socks]
    for s in socks:
        s.close()
    mf = os.path.join(str(tmp_path), "machines")
    with open(mf, "w") as f:
        f.write("\n".join(eps) + "\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests",
                                          "audit_worker.py"),
             mf, str(r), mode, str(tmp_path), *map(str, extra)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
        for r in range(nranks)
    ]
    outs = []
    for p in procs:
        try:
            outs.append(p.communicate(timeout=180)[0])
        except subprocess.TimeoutExpired:
            p.kill()
            outs.append(p.communicate()[0])
    for p, out in zip(procs, outs):
        assert p.returncode == 0 and "AUDIT_WORKER_OK" in out, out[-3000:]
    return outs


def _fleet_doc(out0: str) -> dict:
    line = next(ln for ln in out0.splitlines()
                if ln.startswith("AUDIT_FLEET "))
    return json.loads(line[len("AUDIT_FLEET "):])


@needs_gxx
@pytest.mark.parametrize("engine", ["epoll", "tcp"])
def test_chaos_dups_named_zero_lost_acked(tmp_path, engine):
    """The acceptance chaos (both wire engines): injected fail_send is
    absorbed by retry, the two injected duplicate sends are named
    EXACTLY (count and seq), and the diff shows zero lost acked adds
    with every stream fully acked (the final blocking ack covers the
    async tail by per-connection FIFO)."""
    from multiverso_tpu.ops.audit import diff_fleet

    outs = _run_fleet(tmp_path, "chaos",
                      extra=(f"-net_engine={engine}",))
    fleet = _fleet_doc(outs[0])
    assert fleet["silent"] == []
    findings = diff_fleet(fleet)
    kinds = [f["kind"] for f in findings]
    assert "lost" not in kinds and "gap" not in kinds, findings
    assert "unacked" not in kinds, findings  # final ack covered the tail
    # Exactly the injected dups: 2 dup'd sends, each to ONE remote
    # shard (rank 1's local deliveries never traverse Net::Send).
    dup_total = sum(f["count"] for f in findings if f["kind"] == "dup")
    assert dup_total == 2, findings
    for f in findings:
        if f["kind"] == "dup":
            assert f["origin"] == 1 and f["seqs"], f


@needs_gxx
def test_agg_window_range_accounting(tmp_path):
    """A collapsed aggregation window ships ONE message per shard whose
    stamp covers every absorbed add: applied counts messages, covered
    counts logical adds, and the watermark lands on the window's end."""
    outs = _run_fleet(tmp_path, "agg", extra=("-add_agg_bytes=1000000",))
    fleet = _fleet_doc(outs[0])
    for rank_doc in fleet["ranks"].values():
        server = rank_doc["tables"][0]["server"]
        origins = {o["origin"]: o for o in server["origins"]}
        if 1 not in origins:
            continue  # rank 1's own shard books local deliveries too
        book = origins[1]
        # 6 async adds collapsed into one flush message + 1 blocking
        # add: 2 messages, 7 logical adds, watermark 7, fully in order.
        assert book["applied"] == 2, server
        assert book["covered"] == 7, server
        assert book["watermark"] == 7, server
        assert book["reorders"] == 0 and book["dups"] == 0, server
    # The origin's ledger agrees: everything sent is acked.
    ledger_line = next(ln for ln in outs[1].splitlines()
                       if ln.startswith("LEDGER "))
    ledger = json.loads(ledger_line[len("LEDGER "):])
    for sh in ledger["shards"]:
        assert sh["sent"] == 7 and sh["acked"] == 7, ledger


@needs_gxx
def test_seeded_silent_loss_fires_audit_gap(tmp_path):
    """A silent server-side discard (the seeded real loss retry cannot
    absorb) must leave a hole the books catch: the fleet diff names the
    gap's seq range, the audit_gap blackbox fires on the discarding
    rank, and — because the tail was async — the verdict is gap +
    unacked, NOT a lost acked add."""
    from multiverso_tpu.ops.audit import diff_fleet

    outs = _run_fleet(tmp_path, "loss")
    fleet = _fleet_doc(outs[0])
    findings = diff_fleet(fleet)
    kinds = [f["kind"] for f in findings]
    assert "gap" in kinds, findings
    assert "lost" not in kinds, findings
    assert "unacked" in kinds, findings
    gap = next(f for f in findings if f["kind"] == "gap")
    assert gap["origin"] == 1 and gap["seq_lo"] == 1, findings
    # Detection-time evidence: the blackbox dumped on rank 0 names the
    # gap (canonical file or rotated archive — both must exist).
    box = json.load(open(os.path.join(str(tmp_path),
                                      "blackbox_rank0.json")))
    assert "audit_gap" in box["reason"], box["reason"]
    manifest = json.load(open(os.path.join(
        str(tmp_path), "blackbox_rank0.manifest.json")))
    assert manifest["dumps"], manifest


@needs_gxx
def test_checksums_stable_across_bit_exact_assign_stores(tmp_path):
    """Two identical assign stores leave bit-identical bucket
    checksums — the replica-divergence primitive's stability half."""
    from multiverso_tpu.ops.audit import checksum_divergence

    outs = _run_fleet(tmp_path, "checksum",
                      extra=("-updater_type=assign",))
    before = json.loads(next(
        ln for ln in outs[0].splitlines()
        if ln.startswith("CHECKSUM_BEFORE "))[len("CHECKSUM_BEFORE "):])
    after = json.loads(next(
        ln for ln in outs[0].splitlines()
        if ln.startswith("CHECKSUM_AFTER "))[len("CHECKSUM_AFTER "):])
    assert before, outs[0][-2000:]
    assert checksum_divergence(before, after) == []


# ------------------------------------------------------------ seq math

def test_ack_ledger_wraparound_safety_in_diff():
    """Streams living at the top of the int64 seq space must diff
    without overflow into phantom findings (the books compare, never
    add, beyond +1)."""
    from multiverso_tpu.ops.audit import diff_fleet

    top = 2**63 - 2
    fleet = _fleet({
        "0": _rank_doc(0, [{"id": 0, "server": _server(
            [_origin(1, top + 1)])}]),
        "1": _rank_doc(1, [{"id": 0,
                            "worker": {"shards": [
                                {"shard": 0, "sent": top + 1,
                                 "acked": top + 1}]},
                            "server": _server([])}]),
    })
    assert diff_fleet(fleet) == []
