"""Lua FFI binding surface (reference ``binding/lua/``, SURVEY.md §2.33).

No Lua runtime ships in this image, so the always-on test is a
sync-contract check: every C function the Lua module cdefs must exist in
``c_api.h`` with the identical declaration, and every binding-facing
``MV_*`` declaration must be cdef'd — the drift that would break the
module at ``ffi.load`` time.  When a ``luajit`` binary IS available the
smoke test runs the module for real.
"""

import os
import re
import shutil
import subprocess

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LUA = os.path.join(_ROOT, "multiverso_tpu", "binding", "lua",
                    "multiverso.lua")
_HDR = os.path.join(_ROOT, "multiverso_tpu", "native", "include", "mvtpu",
                    "c_api.h")


def _normalize(decl: str) -> str:
    return re.sub(r"\s+", " ", decl).strip()


def _decls(text: str):
    """{name: normalized declaration} for every ``int MV_*(...);``."""
    out = {}
    for m in re.finditer(r"int\s+(MV_\w+)\s*\(([^;]*?)\)\s*;", text,
                         re.DOTALL):
        out[m.group(1)] = _normalize(f"int {m.group(1)}({m.group(2)})")
    return out


def test_lua_cdef_matches_c_api_header():
    lua = open(_LUA).read()
    cdef = re.search(r"ffi\.cdef\[\[(.*?)\]\]", lua, re.DOTALL)
    assert cdef, "no ffi.cdef block in multiverso.lua"
    lua_decls = _decls(cdef.group(1))
    hdr_decls = _decls(open(_HDR).read())

    assert lua_decls, "cdef block parsed to zero declarations"
    missing = set(hdr_decls) - set(lua_decls)
    assert not missing, f"c_api.h functions absent from the Lua cdef: " \
                        f"{sorted(missing)}"
    for name, decl in lua_decls.items():
        assert name in hdr_decls, f"cdef declares unknown function {name}"
        assert decl == hdr_decls[name], (
            f"{name} signature drift:\n  lua: {decl}\n  hdr: "
            f"{hdr_decls[name]}")


def test_lua_module_wraps_every_cdef_function():
    """Each cdef'd C function is actually used by the wrapper (no dead
    surface), and the module exposes the reference handler API."""
    lua = open(_LUA).read()
    cdef = re.search(r"ffi\.cdef\[\[(.*?)\]\]", lua, re.DOTALL).group(1)
    body = lua.replace(cdef, "")
    for name in _decls(cdef):
        assert f"C.{name}" in body, f"{name} cdef'd but never called"
    for api in ("mv.init", "mv.shutdown", "mv.barrier",
                "mv.ArrayTableHandler", "mv.MatrixTableHandler"):
        assert api in body, f"missing reference API surface: {api}"


def test_lua_cdef_executes_via_cffi(tmp_path):
    """Execute the binding's EXACT FFI contract — no luajit required.

    LuaJIT's ``ffi.cdef``/``ffi.load`` and Python's ``cffi`` are design
    twins: both parse real C declarations and bind them to a dlopen'd
    library.  This test feeds the verbatim cdef block from
    ``multiverso.lua`` through cffi's C parser (strict — a bad type or
    missing typedef fails here where the regex contract test cannot see
    it), dlopens the same ``libmvtpu.so`` the Lua module loads, and
    replays ``test_lua_smoke``'s round trips (array add/get, matrix
    rows sync+async, async gets with wait/cancel ticket semantics, KV
    single+batch) through those declarations.  What
    it cannot cover is the Lua wrapper code itself (the handler classes
    in ``multiverso.lua``) — that remains gated on a luajit appearing on
    PATH (see ``test_lua_smoke``).
    """
    pytest.importorskip("cffi")
    from multiverso_tpu import native as nat

    lib = nat.ensure_built()
    lua = open(_LUA).read()
    cdef = re.search(r"ffi\.cdef\[\[(.*?)\]\]", lua, re.DOTALL).group(1)
    cdef_file = tmp_path / "cdef.txt"
    cdef_file.write_text(cdef)

    script = tmp_path / "cffi_smoke.py"
    script.write_text(f"""
import cffi

ffi = cffi.FFI()
ffi.cdef(open({str(cdef_file)!r}).read())   # the verbatim Lua cdef block
C = ffi.dlopen({lib!r})

argv = [ffi.new("char[]", s) for s in (b"-updater_type=default",
                                       b"-log_level=error")]
assert C.MV_Init(len(argv), ffi.new("const char*[]", argv)) == 0

h = ffi.new("int32_t[1]")
assert C.MV_NewArrayTable(8, h) == 0
ones = ffi.new("float[]", [1.0] * 8)
assert C.MV_AddArrayTable(h[0], ones, 8) == 0
out = ffi.new("float[8]")
assert C.MV_GetArrayTable(h[0], out, 8) == 0
assert abs(out[0] - 1.0) < 1e-6 and abs(out[7] - 1.0) < 1e-6

m = ffi.new("int32_t[1]")
assert C.MV_NewMatrixTable(6, 3, m) == 0
ids = ffi.new("int32_t[]", [1, 4])
delta = ffi.new("float[]", [1, 2, 3, 4, 5, 6])
assert C.MV_AddMatrixTableByRows(m[0], delta, ids, 2, 3) == 0
rows = ffi.new("float[6]")
back = ffi.new("int32_t[]", [4, 1])
assert C.MV_GetMatrixTableByRows(m[0], rows, back, 2, 3) == 0
assert abs(rows[0] - 4.0) < 1e-6 and abs(rows[3] - 1.0) < 1e-6
one = ffi.new("int32_t[]", [1])
ten = ffi.new("float[]", [10.0, 10.0, 10.0])
assert C.MV_AddAsyncMatrixTableByRows(m[0], ten, one, 1, 3) == 0
assert C.MV_Barrier() == 0
assert C.MV_GetMatrixTableByRows(m[0], rows, one, 1, 3) == 0
assert abs(rows[0] - 11.0) < 1e-6

# Async gets through the exact cdef: GetAsync -> WaitGet fills the
# buffer (ticket consumed: a second wait is -2), and CancelGet
# releases an un-waited ticket (waiting it afterwards is -2 too).
w = ffi.new("int32_t[1]")
arows = ffi.new("float[3]")
assert C.MV_GetAsyncMatrixTableByRows(m[0], arows, one, 1, 3, w) == 0
assert C.MV_WaitGet(w[0]) == 0
assert abs(arows[0] - 11.0) < 1e-6
assert C.MV_WaitGet(w[0]) == -2
aout = ffi.new("float[8]")
assert C.MV_GetAsyncArrayTable(h[0], aout, 8, w) == 0
assert C.MV_WaitGet(w[0]) == 0
assert abs(aout[0] - 1.0) < 1e-6
assert C.MV_GetAsyncArrayTable(h[0], aout, 8, w) == 0
assert C.MV_CancelGet(w[0]) == 0
assert C.MV_WaitGet(w[0]) == -2

kv = ffi.new("int32_t[1]")
assert C.MV_NewKVTable(kv) == 0
assert C.MV_AddKV(kv[0], b"alpha", 2.5) == 0
v = ffi.new("float[1]")
assert C.MV_GetKV(kv[0], b"alpha", v) == 0
assert abs(v[0] - 2.5) < 1e-6
lens = ffi.new("int32_t[]", [1, 2])
assert C.MV_AddKVBatch(kv[0], b"bcc", lens, 2,
                       ffi.new("float[]", [1.0, 2.0])) == 0
qlens = ffi.new("int32_t[]", [2, 1, 6])
vals = ffi.new("float[3]")
assert C.MV_GetKVBatch(kv[0], b"ccbabsent", qlens, 3, vals) == 0
assert abs(vals[0] - 2.0) < 1e-6 and abs(vals[1] - 1.0) < 1e-6
assert vals[2] == 0.0

assert C.MV_Barrier() == 0
assert C.MV_ShutDown() == 0
print("CFFI_SMOKE_OK")
""")
    out = subprocess.run(
        [__import__("sys").executable, str(script)], capture_output=True,
        text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "CFFI_SMOKE_OK" in out.stdout


@pytest.mark.skipif(shutil.which("luajit") is None, reason="no luajit")
def test_lua_smoke(tmp_path):
    """Live execution of the Lua module: array, matrix-rows, and KV round
    trips through the real FFI + libmvtpu.so.

    Environment status (rounds 1-4): this image ships NO Lua runtime —
    no luajit/lua binary, no liblua*.so, no lupa Python package — and
    the sandbox has zero egress, so none can be vendored or installed
    (`pip/apt install` are also disallowed).  The sync-contract tests
    above are the always-on insurance; this test runs automatically the
    moment a `luajit` appears on PATH (install one and re-run pytest —
    no further wiring needed).
    """
    from multiverso_tpu import native as nat

    nat.ensure_built()
    script = tmp_path / "smoke.lua"
    script.write_text("""
package.path = package.path .. ";%s/?.lua"
local mv = require("multiverso")
mv.init({"-updater_type=default", "-log_level=error"})
local t = mv.ArrayTableHandler:new(8)
t:add({1, 1, 1, 1, 1, 1, 1, 1})
local w = t:get()
assert(math.abs(w[0] - 1.0) < 1e-6)

local m = mv.MatrixTableHandler:new(6, 3)
m:add_rows({1, 4}, {1, 2, 3, 4, 5, 6})
local rows = m:get_rows({4, 1})
assert(math.abs(rows[0] - 4.0) < 1e-6)   -- row 4, col 0
assert(math.abs(rows[3] - 1.0) < 1e-6)   -- row 1, col 0
m:add_rows({1}, {10, 10, 10}, {async = true})
mv.barrier()
local again = m:get_rows({1})
assert(math.abs(again[0] - 11.0) < 1e-6)

local kv = mv.KVTableHandler:new()
kv:add("alpha", 2.5)
assert(math.abs(kv:get("alpha") - 2.5) < 1e-6)
kv:add_batch({"b", "cc"}, {1.0, 2.0})
local vals = kv:get_batch({"cc", "b", "absent"})
assert(math.abs(vals[0] - 2.0) < 1e-6)
assert(math.abs(vals[1] - 1.0) < 1e-6)
assert(vals[2] == 0.0)

mv.barrier()
mv.shutdown()
print("LUA_SMOKE_OK")
""" % os.path.dirname(_LUA))
    out = subprocess.run(["luajit", str(script)], capture_output=True,
                         text=True, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "LUA_SMOKE_OK" in out.stdout
