"""Lua FFI binding surface (reference ``binding/lua/``, SURVEY.md §2.33).

No Lua runtime ships in this image, so the always-on test is a
sync-contract check: every C function the Lua module cdefs must exist in
``c_api.h`` with the identical declaration, and every binding-facing
``MV_*`` declaration must be cdef'd — the drift that would break the
module at ``ffi.load`` time.  When a ``luajit`` binary IS available the
smoke test runs the module for real.
"""

import os
import re
import shutil
import subprocess

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LUA = os.path.join(_ROOT, "multiverso_tpu", "binding", "lua",
                    "multiverso.lua")
_HDR = os.path.join(_ROOT, "multiverso_tpu", "native", "include", "mvtpu",
                    "c_api.h")


def _normalize(decl: str) -> str:
    return re.sub(r"\s+", " ", decl).strip()


def _decls(text: str):
    """{name: normalized declaration} for every ``int MV_*(...);``."""
    out = {}
    for m in re.finditer(r"int\s+(MV_\w+)\s*\(([^;]*?)\)\s*;", text,
                         re.DOTALL):
        out[m.group(1)] = _normalize(f"int {m.group(1)}({m.group(2)})")
    return out


def test_lua_cdef_matches_c_api_header():
    lua = open(_LUA).read()
    cdef = re.search(r"ffi\.cdef\[\[(.*?)\]\]", lua, re.DOTALL)
    assert cdef, "no ffi.cdef block in multiverso.lua"
    lua_decls = _decls(cdef.group(1))
    hdr_decls = _decls(open(_HDR).read())

    assert lua_decls, "cdef block parsed to zero declarations"
    missing = set(hdr_decls) - set(lua_decls)
    assert not missing, f"c_api.h functions absent from the Lua cdef: " \
                        f"{sorted(missing)}"
    for name, decl in lua_decls.items():
        assert name in hdr_decls, f"cdef declares unknown function {name}"
        assert decl == hdr_decls[name], (
            f"{name} signature drift:\n  lua: {decl}\n  hdr: "
            f"{hdr_decls[name]}")


def test_lua_module_wraps_every_cdef_function():
    """Each cdef'd C function is actually used by the wrapper (no dead
    surface), and the module exposes the reference handler API."""
    lua = open(_LUA).read()
    cdef = re.search(r"ffi\.cdef\[\[(.*?)\]\]", lua, re.DOTALL).group(1)
    body = lua.replace(cdef, "")
    for name in _decls(cdef):
        assert f"C.{name}" in body, f"{name} cdef'd but never called"
    for api in ("mv.init", "mv.shutdown", "mv.barrier",
                "mv.ArrayTableHandler", "mv.MatrixTableHandler"):
        assert api in body, f"missing reference API surface: {api}"


@pytest.mark.skipif(shutil.which("luajit") is None, reason="no luajit")
def test_lua_smoke(tmp_path):
    """Live execution of the Lua module: array, matrix-rows, and KV round
    trips through the real FFI + libmvtpu.so.

    Environment status (rounds 1-4): this image ships NO Lua runtime —
    no luajit/lua binary, no liblua*.so, no lupa Python package — and
    the sandbox has zero egress, so none can be vendored or installed
    (`pip/apt install` are also disallowed).  The sync-contract tests
    above are the always-on insurance; this test runs automatically the
    moment a `luajit` appears on PATH (install one and re-run pytest —
    no further wiring needed).
    """
    from multiverso_tpu import native as nat

    nat.ensure_built()
    script = tmp_path / "smoke.lua"
    script.write_text("""
package.path = package.path .. ";%s/?.lua"
local mv = require("multiverso")
mv.init({"-updater_type=default", "-log_level=error"})
local t = mv.ArrayTableHandler:new(8)
t:add({1, 1, 1, 1, 1, 1, 1, 1})
local w = t:get()
assert(math.abs(w[0] - 1.0) < 1e-6)

local m = mv.MatrixTableHandler:new(6, 3)
m:add_rows({1, 4}, {1, 2, 3, 4, 5, 6})
local rows = m:get_rows({4, 1})
assert(math.abs(rows[0] - 4.0) < 1e-6)   -- row 4, col 0
assert(math.abs(rows[3] - 1.0) < 1e-6)   -- row 1, col 0
m:add_rows({1}, {10, 10, 10}, {async = true})
mv.barrier()
local again = m:get_rows({1})
assert(math.abs(again[0] - 11.0) < 1e-6)

local kv = mv.KVTableHandler:new()
kv:add("alpha", 2.5)
assert(math.abs(kv:get("alpha") - 2.5) < 1e-6)
kv:add_batch({"b", "cc"}, {1.0, 2.0})
local vals = kv:get_batch({"cc", "b", "absent"})
assert(math.abs(vals[0] - 2.0) < 1e-6)
assert(math.abs(vals[1] - 1.0) < 1e-6)
assert(vals[2] == 0.0)

mv.barrier()
mv.shutdown()
print("LUA_SMOKE_OK")
""" % os.path.dirname(_LUA))
    out = subprocess.run(["luajit", str(script)], capture_output=True,
                         text=True, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "LUA_SMOKE_OK" in out.stdout
