"""App-level tests: the reference's example-driven validation (SURVEY.md §4,
§7 minimum slice) — LR converging with loss decrease + parity between the
push-pull path and the fused SPMD path; word2vec training on both paths.
"""

import numpy as np
import pytest


# ---------------------------------------------------------------------- LR

def test_lr_parity_path_converges(mv):
    mv.init(updater_type="sgd")
    from multiverso_tpu.apps import LogisticRegression, synthetic_classification

    x, y = synthetic_classification(512, 16, 4, seed=0)
    lr = LogisticRegression(16, 4, learning_rate=0.5)
    first = lr.evaluate(x, y)[0]
    for _ in range(5):
        for i in range(0, 512, 64):
            lr.train_batch(x[i:i + 64], y[i:i + 64])
    last, acc = lr.evaluate(x, y)
    assert last < first * 0.5
    assert acc > 0.8


def test_lr_fused_path_converges(mv):
    mv.init(updater_type="sgd")
    from multiverso_tpu.apps import LogisticRegression, synthetic_classification

    x, y = synthetic_classification(1024, 16, 4, seed=1)
    lr = LogisticRegression(16, 4, learning_rate=0.5)
    first = lr.evaluate(x, y)[0]
    for _ in range(5):
        lr.train_epoch_fused(x, y, batch_size=128)
    last, acc = lr.evaluate(x, y)
    assert last < first * 0.5
    assert acc > 0.8


def test_lr_fused_matches_parity_single_step(mv):
    """The fused SPMD step computes the same math as the push-pull loop."""
    mv.init(updater_type="sgd")
    from multiverso_tpu.apps import LogisticRegression, synthetic_classification

    x, y = synthetic_classification(128, 8, 3, seed=2)
    a = LogisticRegression(8, 3, learning_rate=0.1, name="lr_a", seed=7)
    b = LogisticRegression(8, 3, learning_rate=0.1, name="lr_b", seed=7)
    np.testing.assert_allclose(a.table.get(), b.table.get())

    a.train_batch(x, y)

    step, place = b.make_fused_step()
    data, state = b.table.raw_value()
    data, state, _ = step(data, state, place(x), place(y))
    b.table.raw_assign(data, state)

    np.testing.assert_allclose(a.table.get(), b.table.get(),
                               rtol=1e-4, atol=1e-5)


def test_lr_workers_consistent_bsp(mv):
    """Sync mode: k workers' adds all apply at the barrier; every worker then
    pulls identical parameters (the §7 cross-worker consistency check)."""
    mv.init(sync=True, updater_type="sgd")
    from multiverso_tpu.apps import LogisticRegression, synthetic_classification

    x, y = synthetic_classification(256, 8, 3, seed=3)
    lr = LogisticRegression(8, 3, learning_rate=0.1)
    w0 = lr.table.get()
    for wid in range(4):  # 4 simulated workers, one batch each
        lr.train_batch(x[wid * 64:(wid + 1) * 64], y[wid * 64:(wid + 1) * 64])
    np.testing.assert_allclose(lr.table.get(), w0)  # clock still open
    mv.barrier()
    assert not np.allclose(lr.table.get(), w0)


# ----------------------------------------------------------------- word2vec

def test_w2v_parity_path_trains(mv):
    mv.init(updater_type="sgd")
    from multiverso_tpu.apps import SkipGram, synthetic_corpus

    sg = SkipGram(vocab_size=50, dim=8, window=2, negatives=3)
    corpus = synthetic_corpus(500, 50, seed=0)
    before = sg.table_in.get().copy()
    steps = sg.train_epoch(corpus, batch_size=64, prefetch=True)
    assert steps > 0
    assert not np.allclose(sg.table_in.get(), before)


def test_w2v_fused_loss_decreases(mv):
    mv.init(updater_type="sgd")
    from multiverso_tpu.apps import SkipGram, synthetic_corpus

    sg = SkipGram(vocab_size=64, dim=16, window=3, negatives=4,
                  learning_rate=0.1)
    corpus = synthetic_corpus(2000, 64, seed=1)
    _, first = sg.train_epoch_fused(corpus, batch_size=256, seed=1)
    for e in range(3):
        _, last = sg.train_epoch_fused(corpus, batch_size=256, seed=1)
    assert last < first


def test_w2v_fused_matches_parity_single_batch(mv):
    mv.init(updater_type="sgd")
    from multiverso_tpu.apps import SkipGram

    a = SkipGram(vocab_size=32, dim=4, negatives=2, seed=5, name="w2v_a")

    c = np.array([1, 2, 3, 1], np.int32)
    o = np.array([4, 5, 6, 7], np.int32)
    neg = np.array([[8, 9], [10, 11], [12, 13], [14, 15]], np.int32)
    a.train_batch(c, o, neg)
    got_in_a = a.table_in.get()
    got_out_a = a.table_out.get()

    import multiverso_tpu as mv2
    b = SkipGram(vocab_size=32, dim=4, negatives=2, seed=5, name="w2v_b")
    step, place = b.make_fused_step()
    din, sin = b.table_in.raw_value()
    dout, sout = b.table_out.raw_value()
    din, sin, dout, sout, _ = step(din, sin, dout, sout,
                                   place(c), place(o), place(neg))
    b.table_in.raw_assign(din, sin)
    b.table_out.raw_assign(dout, sout)

    np.testing.assert_allclose(got_in_a, b.table_in.get(),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(got_out_a, b.table_out.get(),
                               rtol=1e-4, atol=1e-6)


# ------------------------------------------------------------- AsyncBuffer

def test_async_buffer_order_and_overlap(mv):
    from multiverso_tpu.util import AsyncBuffer

    calls = []

    def fill():
        calls.append(len(calls))
        return len(calls) - 1

    with AsyncBuffer(fill) as buf:
        assert buf.get() == 0
        assert buf.get() == 1
        assert buf.get() == 2


def test_prefetch_to_device(mv):
    """prefetch_to_device: order preserved, values intact, arrays land
    as committed jax.Arrays (optionally pre-sharded), exhaustion clean."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from multiverso_tpu.util import prefetch_to_device

    batches = [{"x": np.full((4, 2), i, np.float32), "i": i}
               for i in range(5)]
    got = list(prefetch_to_device(iter(batches), size=2))
    assert [b["i"] for b in got] == list(range(5))
    for i, b in enumerate(got):
        assert isinstance(b["x"], jax.Array)
        np.testing.assert_allclose(np.asarray(b["x"]), i)

    # Pre-sharded landing: the batch dim arrives split over the mesh.
    mesh = jax.sharding.Mesh(np.asarray(jax.devices()[:2]), ("dp",))
    sh = NamedSharding(mesh, P("dp"))
    out = list(prefetch_to_device(iter(batches[:2]), size=2, sharding=sh))
    assert out[0]["x"].sharding == sh

    # A scalar-array leaf and a non-divisible partial batch replicate
    # instead of raising mid-epoch.
    ragged = [{"x": np.ones((3, 2), np.float32), "n": np.asarray(7)}]
    (rb,) = prefetch_to_device(iter(ragged), sharding=sh)
    assert np.asarray(rb["n"]) == 7
    np.testing.assert_allclose(np.asarray(rb["x"]), 1.0)
    assert rb["x"].sharding.is_fully_replicated
    assert rb["n"].sharding.is_fully_replicated

    # size validated at the call site, not at first next().
    with pytest.raises(ValueError):
        prefetch_to_device(iter(batches), size=0)

    # size > stream length: everything still arrives exactly once.
    assert [b["i"] for b in
            prefetch_to_device(iter(batches), size=10)] == list(range(5))


def test_timer():
    from multiverso_tpu.util import Timer

    t = Timer()
    assert t.elapsed >= 0.0
    t.stop()
    e = t.elapsed
    assert t.elapsed == e


def test_w2v_fused_matches_parity_stateful_duplicates(mv):
    """Momentum (stateful) updater: duplicate rows in a fused batch must be
    segment-summed before apply, matching the eager path exactly."""
    mv.init(updater_type="momentum")
    from multiverso_tpu.apps import SkipGram

    c = np.array([1, 1, 1, 2], np.int32)            # heavy duplication
    o = np.array([4, 4, 5, 4], np.int32)
    neg = np.array([[4, 5], [5, 4], [4, 4], [5, 5]], np.int32)

    a = SkipGram(32, 4, negatives=2, seed=9, updater_type="momentum", name="w2v_a")
    a.train_batch(c, o, neg)

    b = SkipGram(32, 4, negatives=2, seed=9, updater_type="momentum", name="w2v_b")
    step, place = b.make_fused_step()
    din, sin = b.table_in.raw_value()
    dout, sout = b.table_out.raw_value()
    din, sin, dout, sout, _ = step(din, sin, dout, sout,
                                   place(c), place(o), place(neg))
    b.table_in.raw_assign(din, sin)
    b.table_out.raw_assign(dout, sout)

    np.testing.assert_allclose(a.table_in.get(), b.table_in.get(),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(a.table_out.get(), b.table_out.get(),
                               rtol=1e-4, atol=1e-6)
    # momentum state must match too
    np.testing.assert_allclose(
        np.asarray(a.table_out.raw_value()[1][0]),
        np.asarray(b.table_out.raw_value()[1][0]), rtol=1e-4, atol=1e-6)


# ------------------------------------------------------- skipgram mixture

def test_sgmix_fused_senses_separate(mv):
    """The flagship multi-sense check: train on a synthetic homonym corpus
    (token 0 lives in two disjoint context worlds) and assert the two
    senses specialize — opposite posteriors under A-contexts vs
    B-contexts, and a roughly balanced prior."""
    mv.init(updater_type="sgd")
    from multiverso_tpu.apps import (SkipGramMixture,
                                     synthetic_homonym_corpus)

    corpus = synthetic_homonym_corpus(4000, vocab_size=21,
                                      groups=((1, 10), (11, 20)), seed=0)
    sg = SkipGramMixture(21, dim=16, senses=2, learning_rate=0.3,
                         negatives=3, window=3, seed=3)
    for epoch in range(12):
        _, loss = sg.train_epoch_fused(corpus, batch_size=256,
                                       seed=epoch)
    assert np.isfinite(loss)

    ctx_a = np.arange(1, 11)
    ctx_b = np.arange(11, 21)
    post_a = sg.sense_posterior(0, ctx_a)
    post_b = sg.sense_posterior(0, ctx_b)
    # each context world picks one dominant sense, and different ones
    assert post_a.max() > 0.8, post_a
    assert post_b.max() > 0.8, post_b
    assert post_a.argmax() != post_b.argmax(), (post_a, post_b)
    # the homonym saw both worlds, so neither sense starved
    prior = sg.sense_priors(0)
    assert prior.min() > 0.2, prior
    # a single-sense word collapses onto one sense
    sv_a = sg.sense_vector(0, int(post_a.argmax()))
    sv_b = sg.sense_vector(0, int(post_b.argmax()))
    cos = (sv_a @ sv_b) / (np.linalg.norm(sv_a) * np.linalg.norm(sv_b)
                           + 1e-12)
    assert cos < 0.9, cos            # senses are not the same vector


def test_sgmix_parity_matches_fused_single_batch(mv):
    """Push-pull EM batch == fused EM batch on all three tables."""
    import jax.numpy as jnp

    mv.init(updater_type="sgd")
    from multiverso_tpu.apps import SkipGramMixture

    rng = np.random.RandomState(0)
    B, K, C = 64, 3, 4
    c = rng.randint(21, size=B).astype(np.int32)
    bags = rng.randint(21, size=(B, C)).astype(np.int32)
    mask = rng.rand(B, C) < 0.8
    mask[:, 0] = True                      # every example has context
    neg = rng.randint(21, size=(B, K)).astype(np.int32)

    a = SkipGramMixture(21, dim=8, senses=2, window=2, name="sgm_a", seed=5)
    b = SkipGramMixture(21, dim=8, senses=2, window=2, name="sgm_b", seed=5)

    a.train_batch(c, bags, mask, neg)

    step, place = b.make_fused_step()
    ds, ss = b.table_sense.raw_value()
    do, so = b.table_out.raw_value()
    dp, sp_ = b.table_prior.raw_value()
    ds, ss, do, so, dp, sp_, _ = step(ds, ss, do, so, dp, sp_,
                                      place(c), place(bags),
                                      jnp.asarray(mask), place(neg))
    b.table_sense.raw_assign(ds, ss)
    b.table_out.raw_assign(do, so)
    b.table_prior.raw_assign(dp, sp_)

    np.testing.assert_allclose(a.table_sense.get(), b.table_sense.get(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(a.table_out.get(), b.table_out.get(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(a.table_prior.get(), b.table_prior.get(),
                               rtol=1e-4, atol=1e-5)


def test_sgmix_prior_counts_accumulate(mv):
    """Prior rows take plain-add responsibility counts (not sgd deltas):
    every batch adds exactly B responsibilities across touched rows."""
    mv.init(updater_type="sgd")
    from multiverso_tpu.apps import SkipGramMixture

    sg = SkipGramMixture(10, dim=4, senses=3, window=2, name="sgm_c",
                         seed=1)
    before = sg.table_prior.get().sum()
    rng = np.random.RandomState(2)
    B = 32
    sg.train_batch(rng.randint(10, size=B).astype(np.int32),
                   rng.randint(10, size=(B, 4)).astype(np.int32),
                   np.ones((B, 4), bool),
                   rng.randint(10, size=(B, 2)).astype(np.int32))
    after = sg.table_prior.get().sum()
    np.testing.assert_allclose(after - before, B, rtol=1e-4)


def test_sgmix_padded_slots_do_not_touch_word0(mv):
    """Padding bag slots carry a sentinel past the visible rows, so a
    non-linear updater (momentum decays state even on zero deltas) never
    perturbs real word 0 through padding."""
    mv.init(updater_type="momentum")
    from multiverso_tpu.apps import SkipGramMixture

    sg = SkipGramMixture(12, dim=4, senses=2, window=3, name="sgm_pad",
                         updater_type="momentum", seed=2)
    w0_before = sg.table_out.get()[0].copy()
    rng = np.random.RandomState(3)
    B, C = 16, 6
    c = rng.randint(1, 12, size=B).astype(np.int32)    # centers != 0
    bags = np.full((B, C), 12, np.int32)               # sentinel pad
    bags[:, 0] = rng.randint(1, 12, size=B)            # contexts != 0
    mask = np.zeros((B, C), bool)
    mask[:, 0] = True
    neg = rng.randint(1, 12, size=(B, 2)).astype(np.int32)
    for _ in range(3):
        sg.train_batch(c, bags, mask, neg)
    np.testing.assert_array_equal(sg.table_out.get()[0], w0_before)
