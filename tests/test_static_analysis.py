"""Tier-1 gate for the static-analysis layer (docs/static_analysis.md).

Three jobs:

1. ``make analyze`` — clang thread-safety analysis (plus -Wshadow /
   -Wconversion as errors) over every native TU.  Skips with a clear
   reason when clang++ is absent (the analysis is clang-only); the
   gcc path is still exercised because the annotations compile to
   no-ops in every other native test's build.
2. ``tools/mvlint.py`` over the whole repo must be clean — the lint IS
   tier-1 (fast, pure-AST, no toolchain dependency).
3. Each mvlint rule must demonstrably FIRE on a seeded violation (and
   stay quiet on the compliant twin), so a refactor of the lint cannot
   silently lobotomize a rule while the repo stays green.
"""

import os
import shutil
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(REPO, "multiverso_tpu", "native")

sys.path.insert(0, os.path.join(REPO, "tools"))
import mvlint  # noqa: E402


# ------------------------------------------------------------ make analyze

def test_make_analyze_thread_safety():
    """clang -Wthread-safety -Werror over every native TU: a Get/Add/
    registry path touching a GUARDED_BY member without its mutex is a
    build error.  Skip (not fail) without clang — the whole point of
    the target is that it runs wherever clang exists."""
    if shutil.which("clang++") is None:
        pytest.skip("clang++ not installed — `make analyze` needs clang's "
                    "thread-safety analysis (gcc compiles the annotations "
                    "as no-ops)")
    out = subprocess.run(["make", "-C", NATIVE_DIR, "analyze"],
                         capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, \
        f"make analyze failed:\n{(out.stdout + out.stderr)[-4000:]}"


# ------------------------------------------------------------ repo lint

def test_mvlint_repo_clean():
    """The repo's own Python layer holds every mvlint invariant (same
    run `make mvlint` / `make lint` wraps)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mvlint.py"), REPO],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, \
        f"mvlint findings:\n{out.stdout}\n{out.stderr}"


# ------------------------------------------------- per-rule seeded violations

def _lint_src(tmp_path, src, name="snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return [(f.rule, f.line) for f in mvlint.lint_file(str(p))]


def test_mv001_fires_on_ctypes_temporary(tmp_path):
    rules = _lint_src(tmp_path, """\
        import numpy as np
        out = np.zeros(4, np.float32)
        lib.MV_Get(h, _fp(np.zeros(4, np.float32)), 4)   # temporary: BAD
        lib.MV_Get(h, _fp(out), 4)                       # named: fine
        lib.MV_Put(h, (a + b).ctypes.data_as(P))         # temporary: BAD
        lib.MV_Put(h, out.ctypes.data_as(P))             # named: fine
        """)
    assert [r for r, _ in rules] == ["MV001", "MV001"], rules


def test_mv002_fires_on_dangling_async(tmp_path):
    rules = _lint_src(tmp_path, """\
        rt.matrix_get_rows_async(h, ids, 8)          # discarded: BAD
        handle = rt.matrix_get_rows_async(h, ids, 8) # bound: fine
        handle.wait()
        """)
    assert [r for r, _ in rules] == ["MV002"], rules


def test_mv002_exempts_pytest_raises(tmp_path):
    """Inside `with pytest.raises(...)` the call is SUPPOSED to throw
    before a handle exists — no finding."""
    rules = _lint_src(tmp_path, """\
        import pytest
        with pytest.raises(ValueError):
            rt.train_step_async(toks, accum=4)
        """)
    assert rules == [], rules


def test_mv003_fires_on_host_sync_in_jit(tmp_path):
    # MV003 only applies to the tables layer — build the path shape.
    d = tmp_path / "tables"
    d.mkdir()
    rules = _lint_src(d, """\
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return np.asarray(x) + 1          # host sync in trace: BAD

        def host_step(x):
            return np.asarray(x) + 1          # untraced: fine

        def inner(x):
            return x.block_until_ready()      # BAD once jitted below

        f = jax.jit(inner)
        """)
    assert [r for r, _ in rules] == ["MV003", "MV003"], rules


def test_mv004_fires_on_unbounded_subprocess(tmp_path):
    # MV004 only applies to bench* files — name the snippet accordingly.
    rules = _lint_src(tmp_path, """\
        import subprocess
        subprocess.run(["sleep", "9"])                  # unbounded: BAD
        subprocess.run(["sleep", "9"], timeout=60)      # bounded: fine
        p = subprocess.Popen(["sleep", "9"])
        p.communicate()                                 # unbounded: BAD
        p.communicate(timeout=60)                       # bounded: fine
        """, name="bench_snippet.py")
    assert [r for r, _ in rules] == ["MV004", "MV004"], rules


def test_mv005_fires_on_unbounded_retry(tmp_path):
    """Runtime code spinning `while True` around a swallow-all except
    with no exit is an unbounded retry loop; adding any exit (break on
    success, re-raise after a cap) or moving to tests/ silences it."""
    src = """\
        import time

        def keep_alive(conn):
            while True:
                try:
                    conn.send(b"ping")             # unbounded: BAD
                except Exception:
                    time.sleep(1)

        def bounded(conn):
            for attempt in range(5):
                try:
                    conn.send(b"ping")
                    break
                except Exception:
                    time.sleep(1)

        def drain(q):
            while True:                            # bounded by break: fine
                try:
                    item = q.get()
                except Exception:
                    break
                if item is None:
                    return
        """
    rules = _lint_src(tmp_path, src, name="runtime_snippet.py")
    assert [r for r, _ in rules] == ["MV005"], rules
    # The identical loop inside a test file is exempt (tests may
    # legitimately spin on a child process).
    assert _lint_src(tmp_path, src, name="test_snippet.py") == []


def test_mv006_fires_on_print_in_library(tmp_path):
    """Library code (the multiverso_tpu package, apps/ exempt) may not
    print() or mint ad-hoc loggers — output must route through
    multiverso_tpu.log.Log so -log_level/-log_file keep working."""
    d = tmp_path / "multiverso_tpu"
    d.mkdir()
    src = """\
        import logging
        from .log import Log

        def noisy(x):
            print("value:", x)                          # BAD
            log = logging.getLogger(__name__)           # BAD
            anon = logging.getLogger()                  # BAD
            named = logging.getLogger("multiverso_tpu") # explicit sink: fine
            Log.info("value: %s", x)                    # the house logger
        """
    rules = _lint_src(d, src)
    assert [r for r, _ in rules] == ["MV006", "MV006", "MV006"], rules
    # The same code inside apps/ (executable worker scripts whose stdout
    # IS their protocol) and tests/ is exempt.
    apps = d / "apps"
    apps.mkdir()
    assert _lint_src(apps, src) == []
    assert _lint_src(d, src, name="test_snippet.py") == []


def test_mv007_fires_on_unbounded_client_cache(tmp_path):
    """Library code may not grow a cache/queue without a size bound;
    bounding it (deque maxlen, an LRU with eviction) or moving out of
    library scope silences the rule."""
    d = tmp_path / "multiverso_tpu"
    d.mkdir()
    src = """\
        from collections import OrderedDict, deque

        class RowClient:
            def __init__(self):
                self._row_cache = {}                 # unbounded: BAD
                self._reply_queue = deque()          # unbounded: BAD
                self._pending = {}                   # not cache-named: fine

        class BoundedClient:
            def __init__(self, max_entries):
                self.max_entries = max_entries
                self._row_cache = OrderedDict()      # bounded below: fine
                self._reply_queue = deque(maxlen=64)

            def put(self, k, v):
                self._row_cache[k] = v
                while len(self._row_cache) > self.max_entries:
                    self._row_cache.popitem(last=False)
        """
    rules = _lint_src(d, src)
    assert [r for r, _ in rules] == ["MV007", "MV007"], rules
    # Outside library scope (tests, apps) the identical code is exempt.
    assert _lint_src(d, src, name="test_snippet.py") == []
    apps = d / "apps"
    apps.mkdir()
    assert _lint_src(apps, src) == []


def test_mv008_fires_on_noncontiguous_ctypes(tmp_path):
    """A strided view handed to a ctypes pointer fires; arrays with a
    provably C-contiguous producer (ascontiguousarray, fresh
    constructors, ravel, _f32) in the same function do not."""
    rules = _lint_src(tmp_path, """\
        import numpy as np

        def bad(lib, h, a):
            view = a[::2]                       # possibly strided: BAD
            lib.MV_Get(h, _fp(view), view.size)
            col = a.T                           # transpose view: BAD
            lib.MV_Put(h, col.ctypes.data_as(P))

        def good(lib, h, a):
            ids = np.ascontiguousarray(a, dtype=np.int32)
            lib.MV_Get(h, _ip(ids), ids.size)
            out = np.zeros(8, np.float32)
            lib.MV_Get(h, _fp(out), 8)
            flat = a.ravel()
            lib.MV_Put(h, _fp(flat), flat.size)
            lens = np.asarray([1, 2, 3], np.int32)  # fresh from literal
            lib.MV_Put(h, _ip(lens), 3)
        """)
    assert [r for r, _ in rules] == ["MV008", "MV008"], rules


def test_mv008_parameter_without_coercion_fires(tmp_path):
    """A bare parameter (unknown provenance) needs the coercion."""
    rules = _lint_src(tmp_path, """\
        def push(lib, h, delta):
            lib.MV_Add(h, _fp(delta), delta.size)
        """)
    assert [r for r, _ in rules] == ["MV008"], rules


def test_mv009_fires_on_blocking_socket_in_reactor(tmp_path):
    """A native file marked reactor-context may not issue blocking
    socket calls: recv/send without MSG_DONTWAIT fires; guarded calls,
    continuation-line flags, and unmarked files stay quiet."""
    src = """\
        // mvlint: reactor-context — event-loop source
        void Loop(int fd) {
          char buf[64];
          ::recv(fd, buf, sizeof(buf), 0);               // BAD
          ::send(fd, buf, sizeof(buf), MSG_NOSIGNAL);    // BAD
          ::recv(fd, buf, sizeof(buf), MSG_DONTWAIT);    // guarded: fine
          ::recv(fd, buf,
                 sizeof(buf), MSG_DONTWAIT);   // flags on next line: fine
          int c = ::accept4(fd, nullptr, nullptr, SOCK_NONBLOCK);  // fine
          SendAttempt(fd);    // method name containing 'send': fine
        }
        """
    rules = _lint_src(tmp_path, src, name="reactor.cc")
    assert [r for r, _ in rules] == ["MV009", "MV009"], rules
    # The identical calls WITHOUT the marker are out of scope — plain
    # blocking transports (net.cc) legitimately block their own threads.
    unmarked = src.replace("// mvlint: reactor-context", "// plain")
    assert _lint_src(tmp_path, unmarked, name="plain.cc") == []


def test_mv009_suppression_names_the_reason(tmp_path):
    rules = _lint_src(tmp_path, """\
        // mvlint: reactor-context
        void Connect(int fd, const sockaddr* a, socklen_t l) {
          ::connect(fd, a, l);  // mvlint: disable=MV009 (pre-reactor)
        }
        """, name="reactor2.cc")
    assert rules == [], rules


def test_mv009_repo_reactor_sources_are_marked():
    """The epoll engine source itself carries the marker (so the rule
    actually polices the real reactor, not just snippets)."""
    p = os.path.join(NATIVE_DIR, "src", "epoll_net.cc")
    with open(p) as fh:
        assert mvlint.REACTOR_MARKER in fh.read()
    assert mvlint.lint_file(p) == []


def test_mv019_fires_on_unbounded_cqe_drain(tmp_path):
    """An unbounded `while (true)` loop that consumes completion-queue
    entries fires; a batch-capped drain and an unbounded loop that never
    touches the CQ (an EINTR-retry around a syscall) stay quiet."""
    rules = _lint_src(tmp_path, """\
        void Drain(Ring* r) {
          while (true) {
            io_uring_cqe* cqe = Peek(r);       // BAD: no batch bound
            if (!cqe) break;
            Handle(cqe);
          }
        }
        """, name="drain.cc")
    assert [r for r, _ in rules] == ["MV019"], rules
    assert _lint_src(tmp_path, """\
        void Drain(Ring* r) {
          constexpr unsigned kCqeBatch = 256;
          for (unsigned n = 0; n < kCqeBatch; ++n) {
            io_uring_cqe* cqe = Peek(r);
            if (!cqe) break;
            Handle(cqe);
          }
        }
        void Retry(int fd) {
          while (true) {
            if (::syscall(fd) >= 0) break;     // no CQE in sight: fine
            if (errno != EINTR) break;
          }
        }
        """, name="bounded.cc") == []


def test_mv019_for_semicolon_loop_and_cq_head_fire(tmp_path):
    """`for (;;)` counts as unbounded, and head/tail pointer access is
    CQE consumption even without a variable literally named cqe."""
    rules = _lint_src(tmp_path, """\
        void Drain(Ring* r) {
          for (;;) {
            unsigned head = *r->cq_head;
            if (head == *r->cq_tail) break;
            Handle(r, head);
          }
        }
        """, name="forever.cc")
    assert [r for r, _ in rules] == ["MV019"], rules


def test_mv019_suppression_names_the_reason(tmp_path):
    rules = _lint_src(tmp_path, """\
        void Drain(Ring* r) {
          while (true) {  // mvlint: MV019-exempt(bounded by ring size)
            io_uring_cqe* cqe = Peek(r);
            if (!cqe) break;
            Handle(cqe);
          }
        }
        """, name="exempt.cc")
    assert rules == [], rules


def test_mv019_uring_source_is_marked_and_clean():
    """The io_uring engine source carries the reactor marker (MV009
    polices its socket calls) and is MV019-clean — its CQE drain is the
    batch-bounded shape the rule demands."""
    p = os.path.join(NATIVE_DIR, "src", "uring_net.cc")
    with open(p) as fh:
        assert mvlint.REACTOR_MARKER in fh.read()
    assert mvlint.lint_file(p) == []


def test_mv010_fires_on_registry_bypass(tmp_path):
    """Library code minting metric series outside the unified registry
    (direct Counter/Gauge/Histogram construction) fires; the registry
    accessors — and collections.Counter in unrelated code — do not."""
    d = tmp_path / "multiverso_tpu"
    d.mkdir()
    src = """\
        from collections import Counter
        from multiverso_tpu import metrics
        from multiverso_tpu.metrics import Histogram

        def bad():
            h = Histogram("rogue.latency")        # bypass: BAD
            c = metrics.Counter("rogue.count")    # bypass: BAD
            return h, c

        def good(tokens):
            h = metrics.histogram("app.latency")  # registry accessor
            c = metrics.counter("app.count")
            tally = Counter(tokens)               # collections.Counter
            return h, c, tally
        """
    rules = _lint_src(d, src)
    assert [r for r, _ in rules] == ["MV010", "MV010"], rules
    # Outside library scope (tests, apps) the identical code is exempt.
    assert _lint_src(d, src, name="test_snippet.py") == []
    apps = d / "apps"
    apps.mkdir()
    assert _lint_src(apps, src) == []


def test_mv010_fires_on_dropped_span_id(tmp_path):
    """A span id captured with `as` but never propagated is an
    observability bypass; using the id (native set_trace_id, a wire
    stamp) or dropping the `as` clause silences the rule."""
    d = tmp_path / "multiverso_tpu"
    d.mkdir()
    rules = _lint_src(d, """\
        from multiverso_tpu import tracing

        def bad(rt, h):
            with tracing.span("op") as tid:       # id dropped: BAD
                rt.get(h)

        def good(rt, h):
            with tracing.span("op") as tid:       # propagated: fine
                rt.set_trace_id(tid)
                rt.get(h)
            with tracing.span("op2"):             # no binding: fine
                rt.get(h)
        """)
    assert [r for r, _ in rules] == ["MV010"], rules


def test_mv010_registry_itself_is_exempt(tmp_path):
    """metrics.py constructs the classes it registers — exempt."""
    d = tmp_path / "multiverso_tpu"
    d.mkdir()
    rules = _lint_src(d, """\
        from multiverso_tpu.metrics import Histogram

        def mint(name):
            return Histogram(name)
        """, name="metrics.py")
    assert rules == [], rules


def test_mv011_fires_on_per_key_labels(tmp_path):
    """Registry labels derived from a table key / row id mint one
    series per key — unbounded cardinality; per-key accounting must go
    through a bounded sketch.  Bounded dimensions (table name, rank,
    dir) stay legal."""
    d = tmp_path / "multiverso_tpu"
    d.mkdir()
    rules = _lint_src(d, """\
        from multiverso_tpu import metrics

        def bad(key, row_id, hot_rows, i):
            metrics.counter("t.reads", labels={"key": str(key)})   # BAD
            metrics.counter("t.reads", labels={"r": f"{row_id}"})  # BAD
            metrics.gauge("t.load", labels={"x": hot_rows[i]})     # BAD

        def good(table_id, rank):
            metrics.counter("t.reads", labels={"table": str(table_id)})
            metrics.counter("t.reads", labels={"rank": str(rank)})
            metrics.counter("io.bytes", labels={"dir": "read"})
        """)
    assert [r for r, _ in rules] == ["MV011", "MV011", "MV011"], rules


def test_mv011_fires_on_keyish_label_name(tmp_path):
    """A label literally NAMED "key"/"row" with a non-constant value is
    per-key by its own admission, however the value is spelled."""
    d = tmp_path / "multiverso_tpu"
    d.mkdir()
    rules = _lint_src(d, """\
        from multiverso_tpu import metrics

        def bad(k):
            metrics.histogram("t.lat", labels={"row": str(k)})     # BAD

        def good():
            metrics.histogram("t.lat", labels={"row": "header"})   # const
        """)
    assert [r for r, _ in rules] == ["MV011"], rules


def test_mv011_out_of_scope_and_suppressible(tmp_path):
    """Tests/apps are exempt (same scope rule as MV010); an in-library
    finding silences with the usual suppression comment."""
    src = """\
        from multiverso_tpu import metrics

        def f(key):
            metrics.counter("t.x", labels={"key": str(key)})
        """
    d = tmp_path / "multiverso_tpu"
    d.mkdir()
    assert [r for r, _ in _lint_src(d, src)] == ["MV011"]
    assert _lint_src(d, src, name="test_snippet.py") == []
    suppressed = src.replace(
        "labels={\"key\": str(key)})",
        "labels={\"key\": str(key)})  # mvlint: disable=MV011")
    assert _lint_src(d, suppressed) == []


def test_mv013_fires_on_row_at_a_time_loop(tmp_path):
    """Row-at-a-time table fetch/add inside a for over ids (apps/ and
    models/ scope): each iteration pays a full monitor/serve/wire round
    trip the batched rows=/keys= call amortizes (docs/embedding.md)."""
    d = tmp_path / "multiverso_tpu" / "apps"
    d.mkdir(parents=True)
    rules = _lint_src(d, """\
        def bad(table, kv, ids, keys, deltas):
            for i in ids:
                table.get_rows([i])                    # BAD
            for i in ids:
                table.add_rows([i], deltas[i])         # BAD
            for k in keys:
                kv.get([k])                            # BAD
            for k in keys:
                kv.add({k: deltas[k]})                 # BAD

        def good(table, kv, ids, keys, deltas, cfg):
            table.get_rows(ids)                        # batched: fine
            table.add_rows(ids, deltas)
            kv.get(keys)
            for k in keys:
                cfg.get(k)                             # dict.get: fine
            for i in ids:
                table.get_rows([0, 1, 2])              # constant set: fine
        """)
    assert [r for r, _ in rules] == ["MV013"] * 4, rules


def test_mv013_out_of_scope_and_suppressible(tmp_path):
    """Library/tests are out of scope (the rule polices app/model
    training loops); an in-scope finding silences with the usual
    comment."""
    src = """\
        def f(table, ids):
            for i in ids:
                table.get_rows([i])
        """
    apps = tmp_path / "multiverso_tpu" / "apps"
    apps.mkdir(parents=True)
    assert [r for r, _ in _lint_src(apps, src)] == ["MV013"]
    lib = tmp_path / "multiverso_tpu" / "tables"
    lib.mkdir(parents=True)
    assert _lint_src(lib, src) == []           # library scope: exempt
    assert _lint_src(apps, src,
                     name="test_snippet.py") == []   # tests: exempt
    suppressed = src.replace(
        "table.get_rows([i])",
        "table.get_rows([i])  # mvlint: disable=MV013")
    assert _lint_src(apps, suppressed) == []


def test_mv012_fires_on_bridge_copy_churn(tmp_path):
    """astype/.copy()/ascontiguousarray minted INLINE on a native
    bridge add/get argument is a full-payload copy per call — the
    churn the arena/borrow protocol exists to kill
    (docs/host_bridge.md).  Named buffers and non-bridge calls stay
    legal."""
    rules = _lint_src(tmp_path, """\
        import numpy as np

        def bad(rt, h, grad, x):
            rt.array_add(h, grad.astype(np.float32))            # BAD
            rt.matrix_add_all(h, np.ascontiguousarray(grad))    # BAD
            rt.array_add(h, delta=x.copy())                     # BAD (kwarg)
            lib.MV_AddArrayTable(h, _fp(x.astype(np.float32)), 4)  # BAD

        def good(rt, h, grad, arena):
            buf = arena.alloc(grad.shape)
            np.copyto(buf, grad)
            rt.array_add(h, buf, borrowed=True)       # arena: fine
            d = grad.astype(np.float32)               # hoisted: fine
            rt.array_add(h, d)
            other = np.ascontiguousarray(grad)        # not a bridge call
            consume(other.copy())
        """)
    # The raw MV_* line draws BOTH rules: MV001 (ctypes temporary) and
    # MV012 (inline churn through the _fp wrapper).
    assert sorted(rules) == [("MV001", 7), ("MV012", 4), ("MV012", 5),
                             ("MV012", 6), ("MV012", 7)], rules


def test_mv012_out_of_scope_and_suppressible(tmp_path):
    """Tests are exempt (they build ad-hoc arrays); a genuinely
    required copy suppresses with its why."""
    src = """\
        import numpy as np

        def f(rt, h, x):
            rt.array_add(h, x.astype(np.float32))
        """
    assert [r for r, _ in _lint_src(tmp_path, src)] == ["MV012"]
    assert _lint_src(tmp_path, src, name="test_snippet.py") == []
    suppressed = src.replace(
        "rt.array_add(h, x.astype(np.float32))",
        "rt.array_add(h, x.astype(np.float32))  "
        "# mvlint: disable=MV012 — cold path, caller dtype unknown")
    assert _lint_src(tmp_path, suppressed) == []


def test_mv014_fires_on_wall_clock_interval(tmp_path):
    """An interval measured as time.time() minus time.time() (directly
    or through assigned names) steps with NTP/DST — the latency plane
    (docs/observability.md) requires monotonic clocks for durations."""
    lib = tmp_path / "multiverso_tpu"
    lib.mkdir()
    rules = _lint_src(lib, """\
        import time

        def bad_direct(t0):
            t0 = time.time()
            return time.time() - t0                     # BAD

        def bad_datetime():
            import datetime
            start = datetime.datetime.now()
            return datetime.datetime.now() - start      # BAD

        def fine_monotonic():
            t0 = time.monotonic()
            return time.monotonic() - t0                # monotonic: fine

        def fine_timestamp(dt):
            return (time.time() - dt) * 1e6             # ts math: fine
        """)
    assert [r for r, _ in rules] == ["MV014", "MV014"], rules


def test_mv014_out_of_scope_and_suppressible(tmp_path):
    src = """\
        import time

        def f():
            t0 = time.time()
            return time.time() - t0
        """
    lib = tmp_path / "multiverso_tpu"
    lib.mkdir()
    assert [r for r, _ in _lint_src(lib, src)] == ["MV014"]
    # apps/ and tests are out of scope (a test may step clocks on
    # purpose; apps' stdout protocols are not library hot paths).
    apps = lib / "apps"
    apps.mkdir()
    assert _lint_src(apps, src) == []
    assert _lint_src(lib, src, name="test_clock.py") == []
    suppressed = src.replace(
        "return time.time() - t0",
        "return time.time() - t0  # mvlint: disable=MV014")
    assert _lint_src(lib, suppressed) == []


def test_mv015_fires_on_swallowed_native_exception(tmp_path):
    """`except ...: pass` (and bare log-and-drop) around native-call/
    wire/table code hides exactly the delivery failures the audit
    plane exists to surface (docs/observability.md "audit plane")."""
    lib = tmp_path / "multiverso_tpu"
    lib.mkdir()
    rules = _lint_src(lib, """\
        def bad_pass(rt, h, delta):
            try:
                rt.array_add(h, delta)
            except Exception:
                pass                                    # BAD

        def bad_log_and_drop(sock, frame, Log):
            try:
                sock.sendall(frame)
            except OSError as exc:
                Log.error("send failed: %s", exc)       # BAD: dropped

        def bad_raw_capi(lib, h):
            try:
                lib.MV_FlushAdds(h)
            except Exception:
                pass                                    # BAD
        """)
    assert [r for r, _ in rules] == ["MV015"] * 3, rules


def test_mv015_handling_and_cleanup_are_legal(tmp_path):
    lib = tmp_path / "multiverso_tpu"
    lib.mkdir()
    rules = _lint_src(lib, """\
        def fine_reraise(rt, h, delta):
            try:
                rt.array_add(h, delta)
            except Exception:
                raise RuntimeError("add failed")

        def fine_fallback(sock, frame):
            try:
                sock.sendall(frame)
            except OSError:
                return False
            return True

        def fine_cleanup(sock):
            try:
                sock.close()
            except OSError:
                pass

        def fine_unrelated(d):
            try:
                return d["k"]
            except KeyError:
                pass
        """)
    assert rules == [], rules


def test_mv015_out_of_scope_and_suppressible(tmp_path):
    src = """\
        def f(rt, h, delta):
            try:
                rt.array_add(h, delta)
            except Exception:
                pass
        """
    lib = tmp_path / "multiverso_tpu"
    lib.mkdir()
    assert [r for r, _ in _lint_src(lib, src)] == ["MV015"]
    # apps/ and tests are out of scope (tests probe failure paths on
    # purpose; apps are worker scripts, not library delivery paths).
    apps = lib / "apps"
    apps.mkdir()
    assert _lint_src(apps, src) == []
    assert _lint_src(lib, src, name="test_swallow.py") == []
    suppressed = src.replace(
        "except Exception:",
        "except Exception:  # mvlint: disable=MV015 — deliberate drop")
    assert _lint_src(lib, suppressed) == []


def test_mv016_fires_on_serve_read_without_deadline(tmp_path):
    """A serve-protocol read minted without a qos= deadline stamp
    bypasses deadline propagation (docs/serving.md "tail") — the
    server cannot shed it once the caller gave up."""
    rules = _lint_src(tmp_path, """\
        from multiverso_tpu.serve.wire import MSG, pack_frame

        def bad_probe(sock):
            sock.sendall(pack_frame(MSG["RequestVersion"], 0, 1))  # BAD

        def bad_get(sock, ids):
            sock.sendall(pack_frame(MSG["RequestGet"], 0, 2,
                                    blobs=[ids]))                  # BAD

        def bad_replica(sock):
            sock.sendall(pack_frame(MSG["RequestReplica"], 1, 3))  # BAD
        """)
    assert [r for r, _ in rules] == ["MV016"] * 3, rules


def test_mv016_stamped_cancel_and_ops_are_legal(tmp_path):
    rules = _lint_src(tmp_path, """\
        from multiverso_tpu.serve.wire import MSG, pack_frame

        def fine_stamped(sock):
            sock.sendall(pack_frame(MSG["RequestGet"], 0, 1,
                                    qos=(1, 5_000_000_000)))

        def fine_cancel(sock):
            # Not a read: the cancel token never stamps a deadline.
            sock.sendall(pack_frame(MSG["RequestCancel"], 0, 1))

        def fine_ops(sock):
            # Scrapes are reactor-answered, not apply-slot reads.
            sock.sendall(pack_frame(MSG["OpsQuery"], -1, 2,
                                    blobs=[b"health"]))

        def fine_client_stamp(client, mid):
            client.send_raw(pack_frame(MSG["RequestVersion"], 0, mid,
                                       qos=client._qos()))
        """)
    assert rules == [], rules


def test_mv016_out_of_scope_and_suppressible(tmp_path):
    src = """\
        from multiverso_tpu.serve.wire import MSG, pack_frame

        def f(sock):
            sock.sendall(pack_frame(MSG["RequestGet"], 0, 1))
        """
    assert [r for r, _ in _lint_src(tmp_path, src)] == ["MV016"]
    # Tests are out of scope: version-tolerance suites legitimately
    # mint the unstamped pre-13 frame.
    assert _lint_src(tmp_path, src, name="test_pre13.py") == []
    suppressed = src.replace(
        "sock.sendall(pack_frame(MSG[\"RequestGet\"], 0, 1))",
        "sock.sendall(pack_frame(MSG[\"RequestGet\"], 0, 1))"
        "  # mvlint: disable=MV016 — pre-13 frame on purpose")
    assert _lint_src(tmp_path, suppressed) == []


def test_mv017_fires_on_cached_route_across_wire(tmp_path):
    """A shard routing decision (modulo math or placement lookup)
    carried across wire calls with no routing-epoch re-check: after a
    failover the map flips and the cached route points at a corpse
    (docs/replication.md)."""
    rules = _lint_src(tmp_path, """\
        def bad_modulo(client, table, ids, shards):
            owner = ids[0] % shards                        # BAD: cached
            for i in ids:
                client.get_rows(table, [i], 4)
            return owner

        def bad_lookup(rt, table, shard):
            rank = rt.shard_owner(shard)                   # BAD: cached
            rt.array_get(table, 8)
            return rank

        def bad_attr_shards(self, client, row):
            target = row % self.num_servers                # BAD: cached
            client.send_raw(b"frame")
            return target
        """)
    assert [r for r, _ in rules] == ["MV017"] * 3, rules


def test_mv017_epoch_check_and_no_wire_are_legal(tmp_path):
    rules = _lint_src(tmp_path, """\
        def fine_rechecked(rt, client, table, ids, shards):
            if rt.routing_epoch() != getattr(rt, "_seen", 0):
                rt._seen = rt.routing_epoch()
            owner = ids[0] % shards
            client.get_rows(table, ids, 4)
            return owner

        def fine_no_wire(ids, shards):
            # SPMD-plane shard math: no wire call, no staleness risk.
            return [i % shards for i in ids]

        def fine_route_after_wire(client, table, ids, shards):
            # The wire call precedes the routing decision — nothing is
            # carried across it.
            client.get_rows(table, ids, 4)
            return ids[0] % shards
        """)
    assert rules == [], rules


def test_mv017_out_of_scope_and_suppressible(tmp_path):
    src = """\
        def f(client, table, ids, shards):
            owner = ids[0] % shards
            client.get_rows(table, ids, 4)
            return owner
        """
    assert [r for r, _ in _lint_src(tmp_path, src)] == ["MV017"]
    # Tests are out of scope: a regression test may pin a route on
    # purpose (e.g. to prove the OLD route fails post-promotion).
    assert _lint_src(tmp_path, src, name="test_pinned_route.py") == []
    suppressed = src.replace(
        "owner = ids[0] % shards",
        "owner = ids[0] % shards"
        "  # mvlint: disable=MV017 — pre-replication fixture")
    assert _lint_src(tmp_path, suppressed) == []


def _lint_serve_src(tmp_path, src, name="snippet.py"):
    """Write src into a serve-plane path (MV018's Python scope)."""
    serve = tmp_path / "multiverso_tpu" / "serve"
    serve.mkdir(parents=True, exist_ok=True)
    p = serve / name
    p.write_text(textwrap.dedent(src))
    return [(f.rule, f.line) for f in mvlint.lint_file(str(p))]


def test_mv018_fires_on_untracked_serve_growth(tmp_path):
    """A serve-plane cache/queue with no registered capacity gauge is
    invisible to the fleet capacity scrape — the placement advisor
    plans over a fiction (docs/observability.md "capacity plane")."""
    rules = _lint_serve_src(tmp_path, """\
        from collections import OrderedDict, deque

        class RowCache:
            def __init__(self):
                self._entries = OrderedDict()           # BAD

        class Pipeline:
            def __init__(self):
                self.reply_queue = deque(maxlen=64)     # BAD: bounded
                                                        # but invisible
        """)
    assert [r for r, _ in rules] == ["MV018"] * 2, rules


def test_mv018_gauge_evidence_and_exemption_are_legal(tmp_path):
    rules = _lint_serve_src(tmp_path, """\
        from collections import OrderedDict, deque

        from .. import capacity

        class GaugedCache:
            def __init__(self):
                self._entries = OrderedDict()
                capacity.register_gauge("gauged.cache", self.bytes)

            def bytes(self):
                return 0

        class ExemptQueue:
            def __init__(self):
                self.q_ring = deque(  # mvlint: MV018-exempt(drained \
synchronously inside one reactor turn — never holds bytes across calls)
                    maxlen=8)
        """)
    assert rules == [], rules


def test_mv018_native_member_needs_capacity_note(tmp_path):
    """Native edition: a growth-named container member must name how
    its bytes reach the "capacity" report (or carry a reasoned
    exemption)."""
    bad = tmp_path / "state.h"
    bad.write_text(textwrap.dedent("""\
        struct WorkerState {
          std::deque<Frame> reply_queue_;
        };
        """))
    rules = [(f.rule, f.line) for f in mvlint.lint_file(str(bad))]
    assert [r for r, _ in rules] == ["MV018"], rules

    good = tmp_path / "state_ok.h"
    good.write_text(textwrap.dedent("""\
        struct WorkerState {
          // capacity: writeq_bytes gauge (the "capacity" report's
          // net.writeq_bytes field)
          std::deque<Frame> reply_queue_;
          // mvlint: MV018-exempt(one entry per in-flight call)
          std::unordered_map<int64_t, Pending> pending_;
        };
        """))
    assert mvlint.lint_file(str(good)) == []
    # An EMPTY exemption reason does not suppress — the why is the
    # point of the marker.
    empty = tmp_path / "state_empty.h"
    empty.write_text(textwrap.dedent("""\
        struct WorkerState {
          // mvlint: MV018-exempt()
          std::deque<Frame> reply_queue_;
        };
        """))
    rules = [(f.rule, f.line) for f in mvlint.lint_file(str(empty))]
    assert [r for r, _ in rules] == ["MV018"], rules


def test_mv018_out_of_scope_paths(tmp_path):
    """Python scope is the serve plane only; tests are exempt."""
    src = """\
        from collections import OrderedDict

        class SideCache:
            def __init__(self):
                self._entries = OrderedDict()
        """
    assert [r for r, _ in _lint_serve_src(tmp_path, src)] == ["MV018"]
    # Same class OUTSIDE the serve plane: MV018 stays quiet (MV007
    # still polices unbounded growth there).
    assert _lint_src(tmp_path, src) == []
    assert _lint_serve_src(tmp_path, src, name="test_cache.py") == []


def test_suppression_comment(tmp_path):
    rules = _lint_src(tmp_path, """\
        rt.flush_async(q)  # mvlint: disable=MV002 — fire-and-forget flush
        """)
    assert rules == [], rules


def test_unparseable_file_is_reported(tmp_path):
    rules = _lint_src(tmp_path, "def broken(:\n")
    assert [r for r, _ in rules] == ["MV000"], rules


# ------------------------------------------------- MV000 parse-failure

def test_mv000_parse_failure_names_the_error(tmp_path):
    """A file no rule could run over gets an EXPLICIT parse-failure
    diagnostic (never a silent skip), naming the exception."""
    bad = tmp_path / "broken.py"
    bad.write_text("def broken(:\n    pass\n")
    findings = mvlint.lint_file(str(bad))
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "MV000"
    assert "parse-failure" in f.msg and "SyntaxError" in f.msg
    assert f.line == 1  # anchored at the syntax error, not line 0


def test_mv000_parse_failure_on_undecodable_bytes(tmp_path):
    bad = tmp_path / "mojibake.py"
    bad.write_bytes(b"x = 1\n\xff\xfe garbage \xff\n")
    findings = mvlint.lint_file(str(bad))
    assert [f.rule for f in findings] == ["MV000"]
    assert "parse-failure" in findings[0].msg
    assert "UnicodeDecodeError" in findings[0].msg


def test_mv000_parse_failure_on_undecodable_native_file(tmp_path):
    """The native (C++) lint path reports unreadable files the same
    way."""
    bad = tmp_path / "broken.cc"
    bad.write_bytes(b"// mvlint: reactor-context\n\xff\xfe\n")
    findings = mvlint.lint_file(str(bad))
    assert [f.rule for f in findings] == ["MV000"]
    assert "parse-failure" in findings[0].msg


# ---------------------------------------------------- --changed mode

def _git(repo, *args):
    subprocess.run(
        ["git", "-C", str(repo), "-c", "user.email=t@t", "-c",
         "user.name=t", *args],
        check=True, capture_output=True, text=True, timeout=60)


def test_changed_mode_lints_only_the_diff(tmp_path, capsys):
    """--changed=REF lints exactly the files `git diff --name-only REF`
    reports: a pre-existing (committed) violation stays out of the run;
    the freshly-touched file is in it."""
    repo = tmp_path / "r"
    repo.mkdir()
    _git(repo, "init", "-q")
    (repo / "touched.py").write_text("x = 1\n")
    # Committed violation in a file this change does NOT touch.
    (repo / "untouched.py").write_text("rt.flush_async(q)\n")
    _git(repo, "add", ".")
    _git(repo, "commit", "-qm", "seed")
    (repo / "touched.py").write_text("rt.flush_async(q)\n")

    rc = mvlint.main(["--changed=HEAD", str(repo)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "touched.py" in out and "untouched.py" not in out

    # Default behavior unchanged: a full run still sees both.
    rc = mvlint.main([str(repo)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "touched.py" in out and "untouched.py" in out


def test_changed_mode_clean_diff(tmp_path, capsys):
    repo = tmp_path / "r"
    repo.mkdir()
    _git(repo, "init", "-q")
    (repo / "bad.py").write_text("rt.flush_async(q)\n")
    _git(repo, "add", ".")
    _git(repo, "commit", "-qm", "seed")
    # Nothing changed since HEAD: --changed lints nothing, exits 0 —
    # the committed violation is invisible to the pre-commit loop.
    assert mvlint.main(["--changed", str(repo)]) == 0
    capsys.readouterr()


# ------------------------------------- reasoned exemption (all rules)

def test_exempt_marker_suppresses_python_rules(tmp_path):
    """The MV018-style reasoned marker works uniformly on Python
    rules."""
    assert _lint_src(tmp_path, """\
        rt.flush_async(q)  # mvlint: MV002-exempt(fire-and-forget flush)
        """) == []
    assert _lint_src(tmp_path, """\
        ptr = _fp(np.zeros(4))  # mvlint: MV001-exempt(scratch freed after sync call)
        """) == []


def test_exempt_marker_requires_nonempty_reason(tmp_path):
    """An empty reason does not suppress — on any rule."""
    rules = _lint_src(tmp_path, """\
        rt.flush_async(q)  # mvlint: MV002-exempt()
        """)
    assert [r for r, _ in rules] == ["MV002"], rules
    rules = _lint_src(tmp_path, """\
        rt.flush_async(q)  # mvlint: MV002-exempt(   )
        """)
    assert [r for r, _ in rules] == ["MV002"], rules


def test_exempt_marker_suppresses_native_rules(tmp_path):
    """The same reasoned marker suppresses on the native (C++) lint
    path — and the empty-reason rejection holds there too."""
    src = """\
        // mvlint: reactor-context
        void Connect(int fd, const sockaddr* a, socklen_t l) {
          ::connect(fd, a, l);  // mvlint: MV009-exempt(pre-reactor)
        }
        """
    assert _lint_src(tmp_path, src, name="reactor.cc") == []
    empty = src.replace("MV009-exempt(pre-reactor)", "MV009-exempt()")
    rules = _lint_src(tmp_path, empty, name="reactor.cc")
    assert [r for r, _ in rules] == ["MV009"], rules


def test_no_bare_disable_markers_in_tree():
    """Satellite: every in-tree suppression carries the reasoned
    -exempt(reason) form.  The bare legacy disable= marker is reserved
    for tests and the linter's own documentation."""
    allowed = {os.path.join("tests", "test_static_analysis.py"),
               os.path.join("tools", "mvlint.py")}
    offenders = []
    for path in mvlint.iter_py_files([REPO]):
        rel = os.path.relpath(path, REPO)
        if rel in allowed:
            continue
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            for i, line in enumerate(fh, 1):
                if "mvlint: disable=" in line:
                    offenders.append(f"{rel}:{i}")
    assert offenders == [], offenders


# ----------------------------------------------------- rule registry

def test_rules_registry_is_complete():
    """Every MVxxx a check can emit is registered, and vice versa."""
    with open(mvlint.__file__, "r", encoding="utf-8") as fh:
        src = fh.read()
    import re as _re
    emitted = set(_re.findall(r'"(MV\d{3})"', src))
    assert emitted == set(mvlint.RULES), \
        sorted(emitted ^ set(mvlint.RULES))


def test_every_rule_has_a_seeded_violation_test():
    """Meta test: a new rule cannot land without a test here that
    names it — each registered rule id must appear inside at least one
    test function in this file."""
    import ast as _ast
    with open(__file__, "r", encoding="utf-8") as fh:
        src = fh.read()
    tree = _ast.parse(src)
    covered = set()
    for node in tree.body:
        if isinstance(node, _ast.FunctionDef) \
                and node.name.startswith("test_"):
            segment = _ast.get_source_segment(src, node) or ""
            for rule in mvlint.RULES:
                if rule in segment:
                    covered.add(rule)
    missing = sorted(set(mvlint.RULES) - covered)
    assert missing == [], \
        f"rules with no seeded-violation test in this file: {missing}"
