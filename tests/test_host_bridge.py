"""Host-bridge fast path (docs/host_bridge.md): pinned arena buffers,
zero-copy borrowed adds/gets, the borrow/out= table protocol, the
assign updater, and the double-buffered OffloadedState bridge — plus
the serve-layer copy-discipline satellites that rode the same PR.

The borrowed-buffer LIFETIME coverage (mutate/free mid-flight under
injected drop/dup/delay, ASan/TSan) lives in the native suite
(test_main.cc `arena`/`bridge` units + the `bridge_child` scenario in
tests/test_native.py's sanitizer sweeps); this file covers the Python
surface and the bit-exactness contract end to end.
"""

import shutil

import numpy as np
import pytest

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")


@pytest.fixture(scope="module")
def rt():
    from multiverso_tpu import native as nat

    nat.ensure_built()
    r = nat.NativeRuntime(args=["-updater_type=assign",
                                "-log_level=error"])
    yield r
    r.shutdown()


@pytest.fixture()
def arena(rt):
    return rt.arena()


# ---------------------------------------------------------------- arena

@needs_gxx
def test_arena_alloc_alignment_and_recycle(rt, arena):
    a = arena.alloc(1000)
    assert a.dtype == np.float32 and a.shape == (1000,)
    assert a.ctypes.data % 64 == 0          # MV008 holds by construction
    assert a.flags["C_CONTIGUOUS"]
    addr = a.ctypes.data
    assert arena.owns(a)
    arena.release(a)
    assert not arena.owns(a)
    b = arena.alloc(1000)                   # same capacity: recycled
    assert b.ctypes.data == addr
    arena.release(b)


@needs_gxx
def test_arena_release_errors(rt, arena):
    from multiverso_tpu.native import ArenaError

    a = arena.alloc(64)
    arena.release(a)
    with pytest.raises(ArenaError):
        arena.release(a)                    # double release
    with pytest.raises(ArenaError):
        arena.release(np.zeros(64, np.float32))  # not arena memory


@needs_gxx
def test_arena_stats_shape(rt, arena):
    st = arena.stats()
    for k in ("buffers", "free_buffers", "bytes", "in_flight",
              "deferred", "recycled", "pinned"):
        assert k in st and st[k] >= 0, st


# ------------------------------------------------------- borrowed paths

@needs_gxx
def test_borrowed_array_roundtrip_and_out(rt, arena):
    h = rt.new_array_table(512)
    buf = arena.alloc(512)
    buf[:] = np.arange(512, dtype=np.float32)
    rt.array_add(h, buf, sync=True, borrowed=True)
    out = arena.alloc(512)
    got = rt.array_get(h, 512, out=out)
    assert got is out                       # landed in the caller buffer
    assert np.array_equal(got, buf)
    # assign updater: a second borrowed push OVERWRITES (bit-exact
    # store semantics, not accumulation).
    buf[:] = -3.25
    rt.array_add(h, buf, sync=True, borrowed=True)
    assert np.all(rt.array_get(h, 512) == np.float32(-3.25))
    arena.release(buf)
    arena.release(out)


@needs_gxx
def test_borrowed_rejects_non_arena_and_bad_layout(rt, arena):
    from multiverso_tpu.native import ArenaError

    h = rt.new_array_table(64)
    with pytest.raises(ArenaError):
        rt.array_add(h, np.ones(64, np.float32), borrowed=True)
    buf = arena.alloc(64)
    with pytest.raises(ValueError):         # never converts
        rt.array_add(h, buf.astype(np.float64), borrowed=True)
    with pytest.raises(ValueError):         # never copies strided views
        rt.array_add(h, buf[::2], borrowed=True)
    with pytest.raises(ValueError):         # out= validates identically
        rt.array_get(h, 64, out=np.zeros(64, np.float64))
    arena.release(buf)


@needs_gxx
def test_async_borrowed_get_defers_release(rt, arena):
    """An early arena.release of an async get's destination must DEFER
    recycling until wait() consumes the ticket — the Python face of the
    native regression (test_main.cc `arena`, red on a naive arena)."""
    h = rt.new_array_table(4096)
    buf = arena.alloc(4096)
    buf[:] = 7.0
    rt.array_add(h, buf, sync=True, borrowed=True)
    out = arena.alloc(4096)
    before = arena.stats()["deferred"]
    ag = rt.array_get_async(h, 4096, out=out, arena=arena)
    arena.release(out)                      # mid-flight: must defer
    got = ag.wait()
    assert np.all(got == 7.0)
    assert arena.stats()["deferred"] - before >= 1
    arena.release(buf)


@needs_gxx
def test_borrowed_matrix_paths(rt, arena):
    h = rt.new_matrix_table(16, 8)
    md = arena.alloc((16, 8))
    md[:] = 1.0
    rt.matrix_add_all(h, md, borrowed=True)
    rows = arena.alloc((3, 8))
    rows[:] = 9.0
    rt.matrix_add_rows(h, [2, 5, 11], rows, borrowed=True)
    out = arena.alloc((3, 8))
    ag = rt.matrix_get_rows_async(h, [2, 5, 11], 8, out=out, arena=arena)
    assert np.all(ag.wait() == 9.0)         # assign overwrote those rows
    plain = rt.matrix_get_rows(h, [0, 1], 8)
    assert np.all(plain == 1.0)
    for b in (md, rows, out):
        arena.release(b)


# --------------------------------------------------- JAX-plane protocol

def test_table_get_out_and_add_borrow(mv):
    mv.init(args=["-log_level=error"])
    t = mv.ArrayTable(64, name="hb_arr")
    delta = np.arange(64, dtype=np.float32)
    t.add(delta, borrow=True)
    out = np.empty(64, np.float32)
    got = t.get(out=out)
    assert got is out and np.array_equal(out, delta)
    # borrow never converts/copies: wrong dtype raises.
    with pytest.raises(ValueError):
        t.add(np.ones(64, np.float64), borrow=True)
    with pytest.raises(TypeError):
        t.add([1.0] * 64, borrow=True)


def test_bsp_borrowed_buffer_not_mutated(mv):
    """A second BSP add to the same option must NOT += into the first
    (borrowed) caller array — the aliasing hazard the borrowed-pending
    set exists to prevent."""
    mv.init(args=["-log_level=error"], sync=True)
    t = mv.ArrayTable(8, name="hb_bsp")
    mine = np.ones(8, np.float32)
    t.add(mine, borrow=True)
    t.add(np.full(8, 2.0, np.float32))
    assert np.all(mine == 1.0), "table mutated a borrowed caller buffer"
    mv.barrier()
    assert np.allclose(t.get(), 3.0)


def test_matrix_get_rows_out_and_borrow(mv):
    mv.init(args=["-log_level=error"])
    t = mv.MatrixTable(8, 4, name="hb_mat")
    d = np.full((2, 4), 5.0, np.float32)
    t.add_rows([1, 3], d, borrow=True)
    out = np.empty((2, 4), np.float32)
    got = t.get_rows([1, 3], out=out)
    assert got is out and np.all(out == 5.0)


def test_kv_add_borrow_validates(mv):
    mv.init(args=["-log_level=error"])
    t = mv.KVTable(name="hb_kv")
    v = np.float32(2.5).reshape(())
    t.add({"a": np.asarray(v)}, borrow=True)
    assert float(t.get(["a"])["a"]) == 2.5
    with pytest.raises(ValueError):
        t.add({"a": 1.0}, borrow=True)      # not an ndarray of the dtype


# ------------------------------------------------------- assign updater

def test_assign_updater_jax_parity(mv):
    """Python/JAX assign parity with the native semantics: dense
    overwrite, rows last-write-wins, masked padding can't clobber."""
    mv.init(args=["-log_level=error"], updater_type="assign")
    t = mv.ArrayTable(16, name="hb_assign")
    t.add(np.full(16, 3.0, np.float32))
    t.add(np.full(16, 1.5, np.float32))
    assert np.all(t.get() == 1.5)           # overwrite, not 4.5
    m = mv.MatrixTable(6, 2, name="hb_assign_m", updater_type="assign")
    m.add_rows([1, 4], np.full((2, 2), 8.0, np.float32))
    got = m.get_rows([0, 1, 4])
    assert np.all(got[0] == 0.0) and np.all(got[1:] == 8.0)


# ------------------------------------------------------ offload bridge

@needs_gxx
def test_offloaded_state_bit_exact_native(rt):
    from multiverso_tpu.parallel.offload import OffloadedState

    off = OffloadedState(rt, 333)
    rng = np.random.RandomState(5)
    v = rng.randn(333).astype(np.float32)
    v[0] = np.float32(1e-38)                # subnormal-adjacent
    v[1] = np.float32(-0.0)
    off.init(v)
    ref = v.copy()
    for i in range(5):
        s = off.wait()
        new = (s * np.float32(0.99) + np.float32(i * 0.1)).astype(
            np.float32)
        off.push(new)
        off.prefetch()
        ref = (ref * np.float32(0.99) + np.float32(i * 0.1)).astype(
            np.float32)
    assert off.wait().tobytes() == ref.tobytes()
    off.close()


def test_offloaded_state_local_backend():
    from multiverso_tpu.parallel.offload import OffloadedState

    off = OffloadedState(None, 64, backend="local")
    v = np.arange(64, dtype=np.float32)
    off.init(v)
    assert off.wait().tobytes() == v.tobytes()
    off.push(v * 2)
    assert np.array_equal(off.wait(), v * 2)


@needs_gxx
def test_trainer_offload_bit_exact(rt, mv):
    """The acceptance contract: an offloaded TransformerTrainer's loss
    trajectory matches the in-memory baseline BIT FOR BIT at equal
    steps (the bridge is a store, not an approximation)."""
    from multiverso_tpu.core import context as core_context
    from multiverso_tpu.models.transformer import (TransformerConfig,
                                                   TransformerTrainer)
    from multiverso_tpu.parallel.offload import OffloadedState

    mv.init(args=["-log_level=error"])
    mesh = core_context.get_context().mesh
    cfg = TransformerConfig(vocab_size=64, dim=32, n_layers=2, n_heads=2,
                            hidden=64, max_seq=32)
    toks = np.random.RandomState(0).randint(
        64, size=(4, 16)).astype(np.int32)

    base = TransformerTrainer(cfg, mesh, updater_type="momentum", seed=1)
    mem = [float(base.train_step_async(toks)) for _ in range(3)]

    tr = TransformerTrainer(cfg, mesh, updater_type="momentum", seed=1)
    bridge = OffloadedState(rt, tr.offload_size())
    tr.offload_state(bridge)
    off = [float(tr.train_step_async(toks)) for _ in range(3)]
    assert [np.float32(x).tobytes() for x in mem] == \
           [np.float32(x).tobytes() for x in off]
    bridge.close()


def test_trainer_offload_rejects_stateless_updater(mv):
    from multiverso_tpu.core import context as core_context
    from multiverso_tpu.models.transformer import (TransformerConfig,
                                                   TransformerTrainer)
    from multiverso_tpu.parallel.offload import OffloadedState

    mv.init(args=["-log_level=error"])
    mesh = core_context.get_context().mesh
    cfg = TransformerConfig(vocab_size=32, dim=16, n_layers=1, n_heads=2,
                            hidden=32, max_seq=16)
    tr = TransformerTrainer(cfg, mesh, updater_type="sgd", seed=0)
    assert tr.offload_size() == 0
    with pytest.raises(ValueError):
        tr.offload_state(OffloadedState(None, 1, backend="local"))


# --------------------------------------------- serve copy satellites

def test_serve_read_single_copy_per_miss(mv):
    """Satellite: the table serve cache stores the fetched value itself
    and copies once on the way out — and caller mutation of the
    returned array must not corrupt later hits."""
    mv.init(args=["-log_level=error", "-serve_cache_entries=8"])
    t = mv.ArrayTable(16, name="hb_serve", serve_cache=8)
    t.add(np.ones(16, np.float32))
    first = t.get()
    first[:] = -99.0                        # caller scribbles its copy
    again = t.get()                         # hit: pristine
    assert np.all(again == 1.0), again


def test_anon_wire_get_shard_is_readonly_view():
    """Satellite: AnonServeClient.get_shard returns the frombuffer view
    (read-only flagged), not a copy."""
    from multiverso_tpu.serve.wire import pack_frame, unpack_frame

    payload = np.arange(6, dtype=np.float32).tobytes()
    frame = pack_frame(3, 0, 1, blobs=[payload])   # ReplyGet shape
    body = unpack_frame(frame[8:])
    arr = np.frombuffer(body["blobs"][0], dtype=np.float32)
    assert not arr.flags.writeable          # bytes-backed view
    assert np.array_equal(arr, np.arange(6, dtype=np.float32))


def test_serve_client_cache_is_mutation_proof(mv):
    """Satellite: ServeClient stores the wire value read-only and hands
    every caller a writable copy — scribbling on a result can never
    corrupt a later hit."""
    from multiverso_tpu.serve.client import ServeClient

    mv.init(args=["-log_level=error"])

    class StubRT:
        def __init__(self):
            self.fetches = 0

        def array_get(self, handle, size):
            self.fetches += 1
            return np.ones(size, np.float32)

        def last_version(self, handle):
            return 1

        def table_version(self, handle):
            return 1

    stub = StubRT()
    c = ServeClient(stub, cache_entries=8, max_staleness=0,
                    window_us=0.0, lease_ms=1e6)
    a = c.array_get(0, 8)
    assert a.flags.writeable                # caller copy is writable
    a[:] = -5.0
    b = c.array_get(0, 8)                   # cache hit
    assert np.all(b == 1.0)
    assert stub.fetches == 1                # really was a hit


def test_kv_allgather_payload_roundtrip(mv):
    """Satellite: the HIGHEST_PROTOCOL + buffer-protocol loads path
    still round-trips arbitrary payloads single-process."""
    mv.init(args=["-log_level=error"])
    t = mv.KVTable(name="hb_kv_pickle")
    payload = {"x": np.arange(5, dtype=np.float32), "y": ("s", 3)}
    out = t._allgather_payload(payload)
    assert len(out) == 1
    assert np.array_equal(out[0]["x"], payload["x"])
    assert out[0]["y"] == ("s", 3)
