"""Native (C++) runtime tests through the ctypes binding — the Python side
of the reference's C-API surface (SURVEY.md §2.19, §2.28) plus a math-parity
check against the JAX updaters.

The C++ unit tests themselves live in native/test/test_main.cc; the first
test here runs that binary.
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "multiverso_tpu", "native")

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


def _uring_ok():
    """Probe once whether the host kernel supports the io_uring engine
    (MV_UringSupported walks IORING_REGISTER_PROBE); sweeps append their
    uring arms only when it does, so old kernels skip — not fail."""
    try:
        from multiverso_tpu import native as nat
        nat.ensure_built()
        return bool(nat.load().MV_UringSupported())
    except Exception:
        return False


@pytest.fixture(scope="module")
def native():
    from multiverso_tpu import native as nat

    nat.ensure_built()
    rt = nat.NativeRuntime(args=["-updater_type=default",
                                 "-log_level=error"])
    yield rt
    rt.shutdown()


def test_cpp_unit_suite_passes(native):
    binary = os.path.join(NATIVE_DIR, "build", "mvtpu_test")
    subprocess.run(["make", "-C", NATIVE_DIR, "-j4", "build/mvtpu_test"],
                   check=True, capture_output=True)
    out = subprocess.run([binary], capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "ALL NATIVE TESTS PASSED" in out.stdout


def test_native_ids(native):
    assert native.workers_num() == 1
    assert native.worker_id() == 0
    assert native.server_id() == 0


def test_native_array_roundtrip(native):
    h = native.new_array_table(32)
    np.testing.assert_allclose(native.array_get(h, 32), 0.0)
    native.array_add(h, np.ones(32, np.float32))
    native.array_add(h, np.full(32, 2.0, np.float32), sync=False)
    native.barrier()  # flush the async add
    np.testing.assert_allclose(native.array_get(h, 32), 3.0)


def test_native_matrix_rows(native):
    h = native.new_matrix_table(8, 4)
    native.matrix_add_rows(h, [1, 3], np.ones((2, 4), np.float32))
    got = native.matrix_get_rows(h, [1, 2, 3], 4)
    np.testing.assert_allclose(got[0], 1.0)
    np.testing.assert_allclose(got[1], 0.0)
    np.testing.assert_allclose(got[2], 1.0)
    full = native.matrix_get_all(h, 8, 4)
    np.testing.assert_allclose(full.sum(), 8.0)


def test_native_async_get(native):
    """GetAsync/Wait through ctypes (reference WorkerTable::GetAsync,
    SURVEY.md §2.10): in-flight handles resolve to the same data as the
    blocking calls, wait() is idempotent, and a dropped un-waited handle
    cancels its ticket from __del__ (withdrawing the in-flight request
    BEFORE numpy frees the output buffer a late reply would write)."""
    hm = native.new_matrix_table(64, 8)
    native.matrix_add_rows(hm, [3, 7], np.ones((2, 8), np.float32))
    g = native.matrix_get_rows_async(hm, [3, 7, 50], 8)
    got = g.wait()
    np.testing.assert_allclose(got[:2], 1.0)
    np.testing.assert_allclose(got[2], 0.0)
    np.testing.assert_allclose(g.wait(), got)  # idempotent
    ha = native.new_array_table(16)
    native.array_add(ha, np.arange(16, dtype=np.float32))
    ag = native.array_get_async(ha, 16)
    np.testing.assert_allclose(ag.wait(), np.arange(16))
    g_drop = native.matrix_get_rows_async(hm, [1], 8)
    ticket = g_drop._ticket
    del g_drop                                 # __del__ cancels the ticket
    assert native.lib.MV_WaitGet(ticket) == -2  # gone from the registry


def test_native_async_get_overlap_across_processes(native, tmp_path):
    """2-process async-overlap scenario: an async GetRows' wire work
    proceeds while the caller computes, so Wait() after the compute
    returns in a fraction of the blocking GetRows time (bounds asserted
    inside the C++ scenario, with generous slack)."""
    mf = _machine_file(tmp_path, 2)
    b = _binary()
    outs, procs = _run_ranks(b, "async_overlap", mf, 2)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"ASYNC_OVERLAP_OK {r}" in out, out[-2000:]


def test_native_checkpoint(native, tmp_path):
    h = native.new_array_table(8)
    native.array_add(h, np.full(8, 7.0, np.float32))
    p = str(tmp_path / "t.bin")
    native.store_table(h, p)
    native.array_add(h, np.ones(8, np.float32))
    native.load_table(h, p)
    np.testing.assert_allclose(native.array_get(h, 8), 7.0)


def test_native_kv_roundtrip(native, tmp_path):
    """KV table through ctypes: singles, async, batch with duplicate keys,
    absent-key zeros, checkpoint (SURVEY.md §2.14)."""
    h = native.new_kv_table()
    assert native.kv_get(h, "nope") == 0.0
    native.kv_add(h, "alpha", 2.0)
    native.kv_add(h, "alpha", 0.5, sync=False)
    native.barrier()  # flush the async add
    assert native.kv_get(h, "alpha") == 2.5
    native.kv_add(h, ["b", "c", "b"], [1.0, 4.0, 2.0])
    np.testing.assert_allclose(native.kv_get(h, ["b", "c", "alpha"]),
                               [3.0, 4.0, 2.5])
    p = str(tmp_path / "kv.bin")
    native.store_table(h, p)
    native.kv_add(h, "alpha", 100.0)
    native.load_table(h, p)
    assert native.kv_get(h, "alpha") == 2.5


def test_native_bad_handle(native):
    with pytest.raises(RuntimeError, match="rc=-2"):
        native.array_get(999, 4)
    with pytest.raises(RuntimeError, match="rc=-2"):
        native.kv_get(999, "k")


def test_native_dashboard(native):
    report = native.dashboard_report()
    assert "Dashboard" in report
    assert "ArrayWorker::Get" in report


def test_native_fault_api_surface(native):
    """The fault/monitor C API through ctypes: counters read 0 when
    never fired, fault knobs validate their kinds, disarmed injection
    changes nothing (the single-process twin of the chaos scenarios in
    tests/test_fault.py)."""
    assert native.query_monitor("no.such.counter") == 0
    assert native.query_monitor("net.retries") == 0
    assert native.dead_peer_count() == 0
    native.set_fault_seed(1234)
    native.set_fault("drop", 0.5)       # armed...
    native.clear_faults()               # ...and disarmed again
    with pytest.raises(RuntimeError, match="rc=-1"):
        native.set_fault("no_such_kind", 0.5)
    with pytest.raises(RuntimeError, match="rc=-1"):
        native.set_fault("drop", 2.0)   # probability out of range
    # Single-process: no wire, so even an armed injector is inert.
    native.set_fault_n("drop", 5)
    h = native.new_array_table(8)
    native.array_add(h, np.ones(8, np.float32))
    np.testing.assert_allclose(native.array_get(h, 8), 1.0)
    native.clear_faults()
    assert native.query_monitor("net.dropped") == 0


def test_native_updater_math_matches_jax(mv):
    """SGD through the native server == SGD through the JAX table (float32).

    A separate process is needed because the module-scoped runtime above
    is pinned to the default updater; use a subprocess with -updater_type=sgd.
    """
    code = """
import numpy as np
from multiverso_tpu import native as nat
rt = nat.NativeRuntime(args=["-updater_type=sgd", "-log_level=error"])
rt.set_add_option(learning_rate=0.5)
h = rt.new_array_table(8)
rt.array_add(h, np.full(8, 2.0, np.float32))
out = rt.array_get(h, 8)
assert np.allclose(out, -1.0), out   # 0 - 0.5*2
rt.shutdown()
print("NATIVE_SGD_OK")
"""
    out = subprocess.run(
        ["python", "-c", code], capture_output=True, text=True,
        cwd=os.path.dirname(NATIVE_DIR.rstrip("/")).rsplit("/", 1)[0] or "/",
        env={**os.environ, "PYTHONPATH": os.path.dirname(
            os.path.dirname(NATIVE_DIR))})
    assert "NATIVE_SGD_OK" in out.stdout, out.stdout + out.stderr

    # identical math through the JAX table
    mv.init(updater_type="sgd")
    import multiverso_tpu as m
    t = m.ArrayTable(8)
    t.add(np.full(8, 2.0, np.float32),
          option=m.AddOption(learning_rate=0.5))
    np.testing.assert_allclose(t.get(), -1.0)


# ------------------------------------------------- multi-process scenarios

def _machine_file(tmp_path, n=2):
    import socket

    ports = []
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    mf = tmp_path / "machines.txt"
    mf.write_text("".join(f"127.0.0.1:{p}\n" for p in ports))
    return str(mf)


def _binary():
    b = os.path.join(NATIVE_DIR, "build", "mvtpu_test")
    subprocess.run(["make", "-C", NATIVE_DIR, "-j4", "build/mvtpu_test"],
                   check=True, capture_output=True)
    return b


def _run_ranks(binary, scenario, mf, n, extra=()):
    procs = [subprocess.Popen([binary, scenario, mf, str(r), *extra],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(n)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=180)[0])
    finally:
        # A hung rank must not leak its siblings (they hold listen ports
        # the rest of the pytest session would collide with).
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs, procs


@pytest.mark.parametrize("nprocs", [2, 4])
def test_native_multi_process_net(native, tmp_path, nprocs):
    """N OS processes, sharded tables over the TCP transport: Add/Get
    round trips cross the process boundary, barriers rendezvous through
    rank 0 (the reference's mpirun -n N scenario, SURVEY.md §4)."""
    mf = _machine_file(tmp_path, nprocs)
    b = _binary()
    outs, procs = _run_ranks(b, "net_child", mf, nprocs)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"NET_CHILD_OK {r}" in out, out[-2000:]


@pytest.mark.parametrize("engine", ["tcp", "epoll"])
def test_native_embed_chaos_scenario(native, tmp_path, engine):
    """Sparse-embedding data plane under chaos (docs/embedding.md): 2
    ranks, multi-shard borrowed AddRows run-iovecs and hot-key replica
    pushes with drop/dup/delay injected — a dropped run loses exactly
    the remote shard's rows, a dup doubles them, a delayed frame
    defers a mid-flight arena release, a dropped replica push fails
    bounded, and the version gate never serves a stale replica row."""
    mf = _machine_file(tmp_path, 2)
    b = _binary()
    outs, procs = _run_ranks(b, "embed_child", mf, 2, extra=(engine,))
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"EMBED_CHAOS_OK {r}" in out, out[-2000:]


@pytest.mark.parametrize("updater",
                         ["sgd", "adagrad", "momentum", "smooth_gradient"])
def test_native_stateful_updater_cross_rank(native, tmp_path, updater):
    """Stateful updaters across ranks: every rank's blocking add applies
    sequentially through the shard-resident slot state; all ranks read
    the same deterministic result (fills the round-2 gap where the net
    scenario pinned -updater_type=default)."""
    mf = _machine_file(tmp_path, 2)
    b = _binary()
    outs, procs = _run_ranks(b, "net_updater", mf, 2, extra=(updater,))
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"NET_UPDATER_OK {r}" in out, out[-2000:]


@pytest.mark.parametrize("staleness", ["0", "1"])
def test_native_ssp_bounded_staleness(native, tmp_path, staleness):
    """SSP (SURVEY.md §2.9-bis): with -staleness=1 the fast rank's first
    ahead-Get overlaps the straggler (no wait) and the NEXT clock's Get
    is held; with -staleness=0 every ahead-Get is held.  Released reads
    include the straggler's clock adds — the s=0 case is exactly the BSP
    read guarantee without a barrier."""
    mf = _machine_file(tmp_path, 2)
    b = _binary()
    outs, procs = _run_ranks(b, "ssp_child", mf, 2, extra=(staleness,))
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"SSP_OK {r}" in out, out[-2000:]


@pytest.mark.parametrize("engine", ["tcp", "epoll", "uring"])
def test_native_wire_bench_scenario(native, tmp_path, engine):
    """The direct transport microbench (bench.py wire_{tcp,epoll}_*
    keys) must produce a full 4-size sweep of positive rates from a
    real 2-process loopback run — ON BOTH ENGINES — and the loopback
    RTT must stay in the low single-digit milliseconds.  The RTT bound
    is the TCP_NODELAY regression guard: with Nagle + delayed ACK on
    the frame path the same probe reads ~40–200 ms (the r04
    `wire_rtt_ms ≈ 98` pathology), so a silent loss of the socket
    option cannot pass this sweep.  20 ms leaves room for a loaded CI
    host; the pathology is an order of magnitude above it."""
    if engine == "uring" and not _uring_ok():
        pytest.skip("kernel lacks io_uring op support")
    mf = _machine_file(tmp_path, 2)
    b = _binary()
    outs, procs = _run_ranks(b, "wire_bench", mf, 2, extra=(engine,))
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} ({engine}):\n{out[-3000:]}"
        assert f"WIRE_BENCH_OK {r}" in out, out[-2000:]
    lines = [l for l in outs[0].splitlines() if l.startswith("WIRE ")]
    assert len(lines) == 4, outs[0][-2000:]
    for line in lines:
        _, size, put, get, rtt = line.split()
        assert float(put) > 0 and float(get) > 0 and float(rtt) > 0, line
        assert float(rtt) < 20.0, \
            f"loopback RTT {rtt} ms on {engine} — Nagle/delayed-ACK " \
            f"shaped; TCP_NODELAY lost? ({line})"


def test_native_wire_bench_mpi_singleton(native):
    """The MPI wire-bench path without a launcher: a single process gets
    OpenMPI's isolated singleton (size 1) and must report itself skipped
    (WIRE_MPI_SINGLETON) — or MPI_UNAVAILABLE without libmpi — with
    rc 0 either way, so bench.py's mpirun-gated sweep degrades cleanly.
    (MPI mode ignores the machine-file argument.)"""
    b = _binary()
    out = subprocess.run([b, "wire_bench", "unused", "0", "mpi"],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert ("WIRE_MPI_SINGLETON" in out.stdout
            or "MPI_UNAVAILABLE" in out.stdout), \
        (out.stdout + out.stderr)[-1500:]


def test_native_tsan_scenarios(native, tmp_path):
    """ThreadSanitizer sweep over the native runtime (VERDICT r4 action
    5): the whole runtime rebuilt -fsanitize=thread, then the unit
    suite plus the lock-heaviest multi-process scenarios (sharded
    tables over the wire, SSP holds, backup-quorum release, async-get
    overlap) run under it.  Any data-race report fails the run —
    zoo.cc alone juggles five mutexes with documented ordering, and
    'threads OK' without a sanitizer was the round-4 weak spot."""
    subprocess.run(["make", "-C", NATIVE_DIR, "-j4", "tsan-build"],
                   check=True, capture_output=True)
    tsan_bin = os.path.join(NATIVE_DIR, "build", "tsan", "mvtpu_test")
    env = dict(os.environ, TSAN_OPTIONS="halt_on_error=1")

    out = subprocess.run([tsan_bin], capture_output=True, text=True,
                         env=env, timeout=600)
    report = out.stdout + out.stderr
    assert out.returncode == 0 and "ThreadSanitizer" not in report, \
        report[-4000:]

    scenarios = [("net_child", 2, ()),
                 ("backup_child", 3, ("0.34",)),
                 ("ssp_tput", 2, ("3",)),
                 ("async_overlap", 2, ()),
                 # Borrowed arena sends under
                 # drop/dup/delay (host_bridge.md).
                 ("bridge_child", 2, ("epoll",)),
                 ("embed_child", 2, ("epoll",)),
                 # Replication forward + promotion
                 # race (docs/replication.md): the
                 # new hot surface — rank 1 dies
                 # mid-fleet, rank 2 promotes.
                 ("failover_child", 3, ("epoll",))]
    if _uring_ok():
        # The io_uring reactor's hottest races: CQE drain vs writer
        # threads (net_child), injected-fault retries over zero-copy
        # sends (chaos_retry), and a SIGKILLed rank's in-flight SQEs
        # during promotion (failover_child).
        scenarios += [("net_child", 2, ("uring",)),
                      ("chaos_retry", 2, ("uring",)),
                      ("failover_child", 3, ("uring",))]
    for scenario, nprocs, extra in scenarios:
        mf = _machine_file(tmp_path, nprocs)  # rewritten per scenario
        procs = [subprocess.Popen([tsan_bin, scenario, mf, str(r), *extra],
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True,
                                  env=env)
                 for r in range(nprocs)]
        outs = []
        try:
            for p in procs:
                outs.append(p.communicate(timeout=600)[0])
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for r, (p, o) in enumerate(zip(procs, outs)):
            assert p.returncode == 0 and "ThreadSanitizer" not in o, \
                f"{scenario} rank {r}:\n{o[-4000:]}"


@pytest.mark.slow
def test_native_asan_scenarios(native, tmp_path):
    """ASan+UBSan sweep — the heap-lifetime half of the sanitizer
    matrix (docs/static_analysis.md): TSan schedules races, ASan
    catches what TSan structurally cannot — use-after-free on reply
    and send buffers (the MpiNet orphan-park class), overflows in the
    wire framing, UB in the arithmetic.  Unit suite plus the same
    multi-process scenarios as the TSan sweep, with the hold/admission
    SSP variant.  Marked slow: full-runtime rebuild + multi-process
    runs pay seconds, so tier-1 (`-m 'not slow'`) skips it; `make asan`
    covers the unit half interactively."""
    subprocess.run(["make", "-C", NATIVE_DIR, "-j4", "asan-build"],
                   check=True, capture_output=True, timeout=600)
    asan_bin = os.path.join(NATIVE_DIR, "build", "asan", "mvtpu_test")
    env = dict(os.environ, ASAN_OPTIONS="halt_on_error=1",
               UBSAN_OPTIONS="halt_on_error=1:print_stacktrace=1")

    out = subprocess.run([asan_bin], capture_output=True, text=True,
                         env=env, timeout=600)
    report = out.stdout + out.stderr
    assert out.returncode == 0 and "AddressSanitizer" not in report \
        and "runtime error" not in report, report[-4000:]

    scenarios = [("net_child", 2, ()),
                 ("backup_child", 3, ("0.34",)),
                 ("ssp_child", 2, ("1",)),
                 ("async_overlap", 2, ()),
                 # Borrowed arena sends under
                 # drop/dup/delay: the use-after-
                 # recycle class lives here.
                 ("bridge_child", 2, ("epoll",)),
                 ("embed_child", 2, ("epoll",)),
                 # Replication forward + promotion
                 # race: a SIGKILLed rank's frames
                 # die mid-wire while its backup
                 # installs as serving.
                 ("failover_child", 3, ("epoll",))]
    if _uring_ok():
        # The heap-lifetime half for uring: registered-slab borrows
        # outliving a retiring conn (net_child), zero-copy notif CQEs
        # landing after retry resubmission (chaos_retry), and mid-wire
        # frame death on a killed rank's ring (failover_child).
        scenarios += [("net_child", 2, ("uring",)),
                      ("chaos_retry", 2, ("uring",)),
                      ("failover_child", 3, ("uring",))]
    for scenario, nprocs, extra in scenarios:
        mf = _machine_file(tmp_path, nprocs)  # rewritten per scenario
        procs = [subprocess.Popen([asan_bin, scenario, mf, str(r), *extra],
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True,
                                  env=env)
                 for r in range(nprocs)]
        outs = []
        try:
            for p in procs:
                outs.append(p.communicate(timeout=600)[0])
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for r, (p, o) in enumerate(zip(procs, outs)):
            assert p.returncode == 0 and "AddressSanitizer" not in o \
                and "runtime error" not in o, \
                f"{scenario} rank {r}:\n{o[-4000:]}"


@pytest.mark.parametrize("ratio", ["0", "0.34"])
def test_native_backup_worker_ratio(native, tmp_path, ratio):
    """-backup_worker_ratio straggler slack (reference sync server,
    SURVEY §2.9; VERDICT r4 action 3): with ratio 0.34 over 3 workers,
    clock-1 reads release on the 2-worker quorum without waiting for
    the deliberate 1.5 s straggler; with ratio 0 (control) the same
    reads park until the straggler ticks.  Both modes end with every
    add applied (timing + consistency asserted inside the scenario)."""
    mf = _machine_file(tmp_path, 3)
    b = _binary()
    outs, procs = _run_ranks(b, "backup_child", mf, 3, extra=(ratio,))
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"BACKUP_OK {r}" in out, out[-2000:]


def test_native_ssp_beats_bsp_under_jitter(native, tmp_path):
    """SSP earning its keep (VERDICT r4 action 7): same jittery
    straggler (alternating 0/160 ms per clock, 80 ms average) against a
    steady 40 ms worker.  With staleness=0 the worker pays the
    straggler's worst-case path every clock; with staleness=3 the
    window absorbs the jitter and the worker runs near its own pace.
    Measured locally: ~1000 ms vs ~520 ms (1.9×); asserted at a
    CI-tolerant 1.33× floor."""
    b = _binary()

    def run(staleness):
        import re

        mf = _machine_file(tmp_path, 2)
        outs, procs = _run_ranks(b, "ssp_tput", mf, 2, extra=(staleness,))
        for r, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
            assert f"SSP_TPUT_OK {r}" in out, out[-2000:]
        return int(re.search(r"SSP_TPUT ms=(\d+)", outs[0]).group(1))

    bsp_ms = run("0")
    ssp_ms = run("3")
    assert ssp_ms < 0.75 * bsp_ms, (bsp_ms, ssp_ms)


def test_native_ssp_dead_straggler_fails_fast(native, tmp_path):
    """A straggler that crashes without ticking must not hang or leak the
    fast rank's held Gets: each attempt errors within -rpc_timeout_ms
    and purges the previously parked message (ReplyError fail-fast)."""
    mf = _machine_file(tmp_path, 2)
    b = _binary()
    procs = [subprocess.Popen([b, "ssp_dead", mf, str(r)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(2)]
    outs = [p.communicate(timeout=120)[0] for p in procs]
    assert procs[0].returncode == 0, outs[0][-3000:]
    assert "SSP_DEAD_OK" in outs[0]
    assert procs[1].returncode == 0, outs[1][-3000:]  # _exit(0) crash sim


@pytest.mark.parametrize("live_rank", ["0", "1"])
def test_native_dead_peer_fails_fast(native, tmp_path, live_rank):
    """Only one rank exists: blocking Get/Add/Barrier must all return
    rc=-3 within their deadlines instead of hanging (round-2's behavior
    was an infinite Waiter wait).  rank 0 = quorum-timeout path, rank 1 =
    unreachable-barrier-authority path."""
    import time

    mf = _machine_file(tmp_path)
    b = _binary()
    t0 = time.time()
    out = subprocess.run([b, "dead_peer", mf, live_rank],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "DEAD_PEER_OK" in out.stdout
    assert time.time() - t0 < 45


def test_native_dead_server_fails_fast(native, tmp_path):
    """Rank 1 crashes (exit without shutdown) after the rendezvous; rank
    0's next blocking Get errors within -rpc_timeout_ms."""
    mf = _machine_file(tmp_path)
    b = _binary()
    procs = [subprocess.Popen([b, "dead_server", mf, str(r)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(2)]
    outs = [p.communicate(timeout=120)[0] for p in procs]
    assert procs[0].returncode == 0, outs[0][-3000:]
    assert "DEAD_SERVER_OK" in outs[0]
    assert procs[1].returncode == 0, outs[1][-3000:]  # _exit(0) crash sim


def test_native_dynamic_registration(native, tmp_path):
    """Control_Register parity (SURVEY.md §2.7): no machine file, no
    -rank — two nodes register with the controller, which assigns ranks
    and broadcasts the node table with per-node ROLE bitmasks.  The
    worker-only and server-only processes prove tables shard across
    server-role ranks while only worker-role ranks push/pull."""
    import socket

    b = _binary()
    ports = []
    socks = []
    for _ in range(3):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    ctrl = f"127.0.0.1:{ports[0]}"
    spec = [(ports[0], "all", "true"), (ports[1], "worker", "false"),
            (ports[2], "server", "false")]
    procs = [subprocess.Popen(
        [b, "register", ctrl, str(port), role, "3", is_ctrl],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for port, role, is_ctrl in spec]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=180)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for (port, role, _), p, out in zip(spec, procs, outs):
        assert p.returncode == 0, f"{role}:\n{out[-3000:]}"
        assert f"REGISTER_OK {role}" in out, out[-2000:]


@pytest.mark.parametrize("scenario,marker", [
    ("mpi_self", "MPI_SELF_OK"),
    ("mpi_zoo", "MPI_ZOO_OK"),
])
def test_native_mpi_transport(native, scenario, marker):
    """Literal MPI wire backend (reference net/mpi_net.h, SURVEY.md
    §2.17), selected with ``-net_type=mpi``: libmpi is dlopen'd (no
    mpi.h in the image) and rank/size come from MPI itself.

    ``mpi_self`` drives MpiNet directly — a Message with float payload
    traverses MPI_Send → Iprobe/Recv → inbound callback (the Zoo's
    local-dst shortcut is not in the path).  ``mpi_zoo`` boots the full
    runtime over the MPI transport and round-trips a table.  Each runs
    in its own subprocess because MPI_Finalize is terminal per process.
    Without mpirun in the image both run as OpenMPI isolated singletons
    (rank 0 / size 1); the same code path serves ``mpirun -n N``
    launches, where rank/size arrive from the launcher environment.
    Skips only when no usable libmpi resolves at all.
    """
    out = subprocess.run([_binary(), scenario], capture_output=True,
                         text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    if "MPI_UNAVAILABLE" in out.stdout:
        pytest.skip("no dlopen-able libmpi in this image")
    assert marker in out.stdout, out.stdout + out.stderr


def test_native8_lr_baseline_section(native):
    """bench_lr_native8's machinery at CI scale (2 procs, 5 steps): the
    north-star denominator (BASELINE.md action 2) must produce a finite
    aggregate rate from real cross-process wire traffic."""
    import bench

    r = bench.bench_lr_native8(procs=2, steps=5, batch=64)
    assert r["lr_native8_samples_per_sec"] > 0
    assert r["lr_native8_procs"] == 2.0


def test_w2v_native_worker_grad_converges(native):
    """The w2v worker's SGNS row gradients, applied through the native
    sgd updater, reduce the true SGNS loss on a deterministic tiny
    problem — the denominator measures a real optimizer, not noise.
    Also pins the gradient's width-agnostic contract (D=8 here vs the
    worker's 128 — a hardcoded reshape once broke exactly this)."""
    from multiverso_tpu.apps.w2v_native_worker import (_sigmoid,
                                                      sgns_row_grads)

    V, D, B, lr = 50, 8, 64, 0.05
    h_in = native.new_matrix_table(V, D)
    h_out = native.new_matrix_table(V, D)
    rng = np.random.default_rng(0)
    init = rng.normal(scale=0.1, size=(V, D)).astype(np.float32)
    # Module fixture runs the plain `default` adder, so seeding is a
    # straight add; per-step sgd semantics come from AddOption math
    # applied worker-side here (delta = -lr * grad pushed through add).
    native.matrix_add_rows(h_in, np.arange(V), init)
    native.matrix_add_rows(h_out, np.arange(V), init.copy())
    c = rng.integers(V, size=B).astype(np.int32)
    o = ((c + 1) % V).astype(np.int32)
    neg = rng.integers(V, size=(B, 3)).astype(np.int32)

    def loss():
        w_in = native.matrix_get_rows(h_in, np.arange(V), D)
        w_out = native.matrix_get_rows(h_out, np.arange(V), D)
        s_pos = np.einsum("bd,bd->b", w_in[c], w_out[o])
        s_neg = np.einsum("bd,bkd->bk", w_in[c], w_out[neg])
        return float(-np.log(_sigmoid(s_pos)).mean()
                     - np.log(_sigmoid(-s_neg)).sum(1).mean())

    l0 = loss()
    rows_in, c_loc = np.unique(c, return_inverse=True)
    cat = np.concatenate([o, neg.ravel()])
    rows_out, inv = np.unique(cat, return_inverse=True)
    for _ in range(30):
        w_in = native.matrix_get_rows(h_in, rows_in, D)
        w_out = native.matrix_get_rows(h_out, rows_out, D)
        d_in, d_out = sgns_row_grads(
            w_in, w_out, c_loc.astype(np.int32), inv[:B].astype(np.int32),
            inv[B:].reshape(B, 3).astype(np.int32))
        native.matrix_add_rows(h_in, rows_in, -lr * d_in)
        native.matrix_add_rows(h_out, rows_out, -lr * d_out)
    l1 = loss()
    assert l1 < l0 * 0.6, (l0, l1)


def test_native8_w2v_baseline_section(native):
    """bench_w2v_native8's machinery at CI scale: the word2vec half of
    the north-star ledger (VERDICT r4 action 1) — touched-row pulls
    (async, double-buffered) + row-delta pushes over the wire must
    produce a finite aggregate pair rate in both prefetch modes."""
    import bench

    r = bench.bench_w2v_native8(procs=2, steps=3, batch=128)
    assert r["w2v_native8_pairs_per_sec"] > 0
    assert r["w2v_native8_procs"] == 2.0
    assert r["w2v_native8_prefetch_speedup"] > 0
