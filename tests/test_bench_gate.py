"""Tier-1 gate for the bench regression gate itself (ROADMAP item 5;
``make bench-gate`` / tools/bench_compare.py).

Three jobs: the committed BENCH_BASELINE.json must parse and run green
against the newest committed bench line; a seeded regression must fail
loudly (the gate demonstrably fires); and the line-extraction must
survive the messy real formats (driver wrappers, partial lines, the
r05-style unparseable file)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sys.path.insert(0, os.path.join(REPO, "tools"))
import bench_compare  # noqa: E402


def _gate(*args):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_compare.py"),
         *args],
        capture_output=True, text=True, timeout=60)
    return out.returncode, out.stdout + out.stderr


def test_baseline_parses_and_names_real_keys():
    with open(os.path.join(REPO, "BENCH_BASELINE.json")) as fh:
        baseline = json.load(fh)
    assert baseline["keys"], baseline
    for key, spec in baseline["keys"].items():
        assert spec.get("direction") in ("higher", "lower"), key
        assert "value" in spec, key
        assert "band_rel" in spec or "band_abs" in spec, key


def test_gate_green_against_committed_bench_line():
    """`make bench-gate` with no arguments: the newest parseable
    BENCH_r*.json must sit inside every band it measures (missing keys
    skip — sections are individually best-effort)."""
    rc, out = _gate()
    assert rc == 0, out
    assert "0 regression(s)" in out, out


def test_gate_fails_on_seeded_regression(tmp_path):
    """A line regressing a gated key out of band must exit nonzero and
    name the key — the 'fails on a seeded regression' acceptance bar."""
    line = {"metric": "x", "value": 1, "unit": "u",
            "extras": {"transformer_large_mfu_pct": 40.0,   # -17 points
                       "wire_tcp_rtt_ms": 95.0}}            # Nagle is back
    p = tmp_path / "seeded.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 1, out
    assert "transformer_large_mfu_pct" in out and "FAIL" in out, out
    assert "wire_tcp_rtt_ms" in out, out


def test_gate_passes_in_band_line(tmp_path):
    line = {"extras": {"transformer_large_mfu_pct": 57.0,
                       "wire_tcp_rtt_ms": 0.4,
                       "fanin_shed_rate": 0.8,
                       "fanin_accepted": 1000.0,
                       "ops_scrape_p99_ms": 2.5,
                       "ops_overhead_pct": 0.3}}
    p = tmp_path / "ok.json"
    p.write_text("some log noise\n" + json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 0, out


def test_gate_guards_ops_keys(tmp_path):
    """bench_ops acceptance bars (docs/observability.md): scrape p99
    past 5 ms or introspection overhead past 1% must fail the gate."""
    line = {"extras": {"ops_scrape_p99_ms": 9.0,     # > 5 ms bar
                       "ops_overhead_pct": 2.5}}     # > 1% bar
    p = tmp_path / "ops_regressed.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 1, out
    assert "ops_scrape_p99_ms" in out and "FAIL" in out, out
    assert "ops_overhead_pct" in out, out


def test_gate_guards_tail_keys(tmp_path):
    """bench_tail acceptance bars (docs/serving.md "tail"): gold
    residency p99 degrading into the broken-admission regime when the
    bulk herd arrives (QoS isolation lost — e.g. the lost-wakeup
    regression read 50x+), a zero hedge-win rate under the seeded
    straggler (hedge path dead), zero deadline sheds (propagation
    broken), or stamp overhead past its band must all fail the gate."""
    line = {"extras": {"tail_qos_isolation": 60.0,     # broken-gate regime
                       "tail_hedge_win_rate": 0.0,     # hedge never won
                       "tail_deadline_shed": 0.0,      # nothing shed
                       "tail_overhead_pct": 6.0}}      # way past band
    p = tmp_path / "tail_regressed.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 1, out
    assert "tail_qos_isolation" in out and "FAIL" in out, out
    assert "tail_hedge_win_rate" in out, out
    assert "tail_deadline_shed" in out, out
    assert "tail_overhead_pct" in out, out


def test_gate_passes_in_band_tail_line(tmp_path):
    line = {"extras": {"tail_qos_isolation": 20.0,
                       "tail_hedge_win_rate": 0.8,
                       "tail_deadline_shed": 20.0,
                       "tail_gold_p999_ms": 4.0,
                       "tail_bulk_p999_ms": 400.0,
                       "tail_overhead_pct": 1.5}}
    p = tmp_path / "tail_ok.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 0, out


def test_gate_guards_latency_keys(tmp_path):
    """bench_latency acceptance bars (docs/observability.md "latency
    plane"): profiler overhead past the always-on 1% bar, a stage sum
    that stopped telescoping to the end-to-end latency (lost stamps /
    bad clock offsets), or trail overhead past its band must all fail
    the gate."""
    line = {"extras": {"latency_profiler_overhead_pct": 3.0,   # > 1 bar
                       "latency_stage_sum_ratio": 0.5,         # lost stages
                       "latency_timing_overhead_pct": 8.0}}    # way past
    p = tmp_path / "latency_regressed.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 1, out
    assert "latency_profiler_overhead_pct" in out and "FAIL" in out, out
    assert "latency_stage_sum_ratio" in out, out
    assert "latency_timing_overhead_pct" in out, out


def test_gate_passes_in_band_latency_line(tmp_path):
    line = {"extras": {"latency_profiler_overhead_pct": 0.4,
                       "latency_timing_overhead_pct": 1.0,
                       "latency_stage_sum_ratio": 0.98,
                       "latency_e2e_p99_ms": 2.0}}
    p = tmp_path / "latency_ok.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 0, out


def test_gate_guards_skew_keys(tmp_path):
    """bench_skew acceptance bars (docs/observability.md, workload
    plane): a collapsed zipf skew ratio (the sketches stopped seeing the
    imbalance), a planted hot key missing from the top-K, or sketch
    overhead past the noise band must all fail the gate."""
    line = {"extras": {"skew_ratio_zipf": 2.0,          # < 3.5 floor
                       "skew_hot_recall": 0.6,          # missed hot keys
                       "hotkey_track_overhead_pct": 25.0}}  # way past band
    p = tmp_path / "skew_regressed.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 1, out
    assert "skew_ratio_zipf" in out and "FAIL" in out, out
    assert "skew_hot_recall" in out, out
    assert "hotkey_track_overhead_pct" in out, out


def test_gate_passes_in_band_skew_line(tmp_path):
    line = {"extras": {"skew_ratio_zipf": 8.5,
                       "skew_ratio_uniform": 1.3,
                       "skew_hot_recall": 1.0,
                       "hotkey_track_overhead_pct": 1.1}}
    p = tmp_path / "skew_ok.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 0, out


def test_gate_guards_bridge_keys(tmp_path):
    """bench_bridge acceptance bars (docs/host_bridge.md): the borrowed
    add/out= get bandwidth collapsing back toward the pre-arena rates,
    the borrow-vs-copy speedup evaporating, or double buffering hiding
    none of the round trip must all fail the gate."""
    line = {"extras": {"bridge_add_host_gbps": 0.2,    # ~the old 0.12
                       "bridge_get_host_gbps": 0.05,
                       "bridge_borrow_speedup": 1.0,   # borrow buys nothing
                       "offload_overlap_pct": 5.0}}    # overlap gone
    p = tmp_path / "bridge_regressed.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 1, out
    assert "bridge_add_host_gbps" in out and "FAIL" in out, out
    assert "bridge_get_host_gbps" in out, out
    assert "bridge_borrow_speedup" in out, out
    assert "offload_overlap_pct" in out, out


def test_gate_passes_in_band_bridge_line(tmp_path):
    line = {"extras": {"bridge_add_host_gbps": 2.8,
                       "bridge_get_host_gbps": 0.9,
                       "bridge_borrow_speedup": 3.1,
                       "offload_overlap_pct": 55.0}}
    p = tmp_path / "bridge_ok.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 0, out


def test_gate_guards_embedding_keys(tmp_path):
    """bench_embedding acceptance bars (docs/embedding.md, schema 14):
    the row-cache speedup collapsing under its 10x floor, the replica
    p50 falling behind the row-cached p50, the borrowed AddRows
    speedup evaporating (a later codec/staging change silently
    re-copying), the replica push no longer covering the hot head, or
    the sparse reply codec losing its byte saving must all fail."""
    line = {"extras": {"embedding_rowcache_vs_cold_p50": 6.0,   # < 10
                       "embedding_replica_vs_rowcache_p50": 0.7,
                       "embedding_addrows_borrow_speedup": 1.2,  # < 2
                       "embedding_replica_hit_rate": 0.2,
                       "embedding_sparse_bytes_ratio": 1.1}}
    p = tmp_path / "embedding_regressed.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 1, out
    assert "embedding_rowcache_vs_cold_p50" in out and "FAIL" in out, out
    assert "embedding_replica_vs_rowcache_p50" in out, out
    assert "embedding_addrows_borrow_speedup" in out, out
    assert "embedding_replica_hit_rate" in out, out
    assert "embedding_sparse_bytes_ratio" in out, out


def test_gate_passes_in_band_embedding_line(tmp_path):
    line = {"extras": {"embedding_rowcache_vs_cold_p50": 11.5,
                       "embedding_replica_vs_rowcache_p50": 1.3,
                       "embedding_addrows_borrow_speedup": 5.0,
                       "embedding_replica_hit_rate": 0.9,
                       "embedding_sparse_bytes_ratio": 5.5}}
    p = tmp_path / "embedding_ok.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 0, out


def test_gate_guards_audit_keys(tmp_path):
    """bench_audit acceptance bars (docs/observability.md "audit
    plane"): audit overhead past the always-on 1% bar, a detect
    latency past 50 ms (the books stopped seeing dups promptly), or
    the injected dup never surfacing at all must all fail the gate."""
    line = {"extras": {"audit_overhead_pct": 2.5,        # > 1% bar
                       "audit_add_overhead_pct": 9.0,    # way past band
                       "audit_detect_ms": 400.0,         # dup went dark
                       "audit_dup_named": 0.0}}          # never surfaced
    p = tmp_path / "audit_regressed.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 1, out
    assert "audit_overhead_pct" in out and "FAIL" in out, out
    assert "audit_add_overhead_pct" in out, out
    assert "audit_detect_ms" in out, out
    assert "audit_dup_named" in out, out


def test_gate_passes_in_band_audit_line(tmp_path):
    line = {"extras": {"audit_overhead_pct": 0.3,
                       "audit_add_overhead_pct": 1.5,
                       "audit_detect_ms": 0.5,
                       "audit_dup_named": 1.0}}
    p = tmp_path / "audit_ok.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 0, out


def test_gate_guards_capacity_keys(tmp_path):
    """bench_capacity acceptance bars (docs/observability.md "capacity
    plane"): accounting overhead past the always-on 1% bar, resident-
    byte books drifting under the ground truth (the advisor would plan
    over a fiction), or a placement proposal whose projected spread
    blows the 2x bar must all fail the gate."""
    line = {"extras": {"capacity_overhead_pct": 3.0,      # > 1% bar
                       "capacity_bytes_accuracy": 0.5,    # lost bytes
                       "capacity_kv_accuracy": 0.4,       # resync broke
                       "mvplan_spread_after": 4.0}}       # > 2x bar
    p = tmp_path / "capacity_regressed.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 1, out
    assert "capacity_overhead_pct" in out and "FAIL" in out, out
    assert "capacity_bytes_accuracy" in out, out
    assert "capacity_kv_accuracy" in out, out
    assert "mvplan_spread_after" in out, out


def test_gate_passes_in_band_capacity_line(tmp_path):
    line = {"extras": {"capacity_overhead_pct": 0.4,
                       "capacity_bytes_accuracy": 1.0,
                       "capacity_kv_accuracy": 0.98,
                       "mvplan_spread_after": 1.1}}
    p = tmp_path / "capacity_ok.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 0, out


def test_gate_guards_failover_keys(tmp_path):
    """bench_failover acceptance bars (docs/replication.md): detection
    or promotion drifting past seconds, a caller-visible blackout past
    the rpc-deadline+lease bound, ANY lost acked add (zero tolerance —
    the sync-replication contract), or replication read overhead past
    the 3% bar must all fail the gate."""
    line = {"extras": {"failover_detect_ms": 9000.0,      # lease blind
                       "failover_promote_ms": 12000.0,    # stuck epoch
                       "failover_p99_blip_ms": 30000.0,   # outage
                       "failover_lost_acked_adds": 1.0,   # THE violation
                       "repl_overhead_pct": 8.0}}         # > 3% bar
    p = tmp_path / "failover_regressed.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 1, out
    assert "failover_detect_ms" in out and "FAIL" in out, out
    assert "failover_promote_ms" in out, out
    assert "failover_p99_blip_ms" in out, out
    assert "failover_lost_acked_adds" in out, out
    assert "repl_overhead_pct" in out, out


def test_gate_passes_in_band_failover_line(tmp_path):
    line = {"extras": {"failover_detect_ms": 1600.0,
                       "failover_promote_ms": 1700.0,
                       "failover_p99_blip_ms": 1800.0,
                       "failover_lost_acked_adds": 0.0,
                       "repl_overhead_pct": 0.5}}
    p = tmp_path / "failover_ok.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 0, out


def test_gate_guards_health_keys(tmp_path):
    """bench_health acceptance bars (docs/observability.md "health
    plane", schema 20): the armed health plane costing the serve tier
    real QPS (the evaluation must stay on the flush thread), a seeded
    fault taking longer than 2 s to page through the flush loop, or
    the alert never firing at all must all fail the gate."""
    line = {"extras": {"health_overhead_pct": 8.0,       # > 1% bar
                       "health_alert_detect_ms": 9000.0,  # loop not closing
                       "health_alert_fired": 0.0}}        # never paged
    p = tmp_path / "health_regressed.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 1, out
    assert "health_overhead_pct" in out and "FAIL" in out, out
    assert "health_alert_detect_ms" in out, out
    assert "health_alert_fired" in out, out


def test_gate_passes_in_band_health_line(tmp_path):
    line = {"extras": {"health_overhead_pct": 0.5,
                       "health_probe_qps": 4000.0,
                       "health_alert_detect_ms": 700.0,
                       "health_alert_fired": 1.0}}
    p = tmp_path / "health_ok.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 0, out


def test_gate_guards_uring_keys(tmp_path):
    """io_uring engine bars (docs/transport.md): the uring RTT drifting
    into the Nagle-pathology regime, the 64 KiB put-burst rate
    collapsing under the committed floor, or the uring serve tier's
    probe p99 blowing past the herd band must all fail the gate."""
    line = {"extras": {"wire_uring_rtt_ms": 40.0,            # Nagle regime
                       "wire_uring_bytes_per_s": 5.0e7,      # < 0.1 GB/s floor
                       "fanin_uring_p99_ms": 90.0}}          # herd p99 blown
    p = tmp_path / "uring_regressed.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 1, out
    assert "wire_uring_rtt_ms" in out and "FAIL" in out, out
    assert "wire_uring_bytes_per_s" in out, out
    assert "fanin_uring_p99_ms" in out, out


def test_gate_passes_in_band_uring_line(tmp_path):
    line = {"extras": {"wire_uring_rtt_ms": 0.2,
                       "wire_uring_bytes_per_s": 1.1e9,
                       "fanin_uring_p99_ms": 2.0}}
    p = tmp_path / "uring_ok.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 0, out


def test_gate_skips_absent_uring_keys(tmp_path):
    """Hosts whose kernel fails the capability probe emit NO uring keys
    (bench.py gates the whole arm on MV_UringSupported) — the default
    gate must SKIP them, not fail, so non-uring CI stays green."""
    line = {"extras": {"fanin_accepted": 1000.0,
                       "wire_tcp_rtt_ms": 0.4}}
    p = tmp_path / "no_uring.json"
    p.write_text(json.dumps(line) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 0, out
    assert "wire_uring" not in [l.split()[1] for l in out.splitlines()
                                if l.startswith("FAIL")], out


def test_last_parseable_line_wins(tmp_path):
    """Schema-7 cumulative emission: the LAST line is the freshest
    cumulative state and must shadow earlier partials."""
    stale = {"extras": {"transformer_large_mfu_pct": 10.0}}
    fresh = {"extras": {"transformer_large_mfu_pct": 57.0}}
    p = tmp_path / "cumulative.json"
    p.write_text(json.dumps(stale) + "\n" + json.dumps(fresh) + "\n")
    rc, out = _gate("--line", str(p))
    assert rc == 0, out


def test_driver_wrapper_and_null_parse_forms(tmp_path):
    """BENCH_r*.json driver wrappers resolve through `parsed` (or the
    raw `tail`); a parsed=null rc=124 file yields nothing."""
    wrapped = {"n": 9, "rc": 0,
               "parsed": {"extras": {"fanin_accepted": 1000.0}}}
    p = tmp_path / "wrap.json"
    p.write_text(json.dumps(wrapped))
    assert bench_compare.load_line(str(p)) == {"fanin_accepted": 1000.0}
    dead = tmp_path / "dead.json"
    dead.write_text(json.dumps({"n": 5, "rc": 124, "parsed": None,
                                "tail": "WARNING: nothing\n"}))
    assert bench_compare.load_line(str(dead)) is None


def test_strict_mode_fails_on_missing_keys(tmp_path):
    p = tmp_path / "thin.json"
    p.write_text(json.dumps({"extras": {"fanin_accepted": 1000.0}}))
    rc, out = _gate("--line", str(p))
    assert rc == 0, out                      # default: skip
    rc, out = _gate("--line", str(p), "--strict")
    assert rc == 1, out                      # strict: miss = fail
