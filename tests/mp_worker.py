"""Multi-process test worker (not a pytest module).

Run as ``python mp_worker.py <port> <pid> <nprocs> <scratch_dir>``.
Each process joins a ``jax.distributed`` job over localhost (CPU backend,
2 local devices each → a 2*nprocs-device global mesh) and exercises every
``process_count() > 1`` code path: init/registration, barrier, collective
eager Add/Get on Array and Matrix tables, BSP pending flush, rank-0
checkpoint save + collective restore, and the jax_ext delta-sync
protocol.  Prints ``WORKER_OK <pid>`` on success; any assert kills the
process and fails the parent test.

This is the TPU-native analog of the reference's ``mpirun -n N
Test/main.cpp`` scenarios (SURVEY.md §4): real OS processes, real
cross-process collectives, one machine.
"""

import os
import sys

port, pid, nprocs = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
scratch = sys.argv[4]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import multiverso_tpu as mv  # noqa: E402
from multiverso_tpu import checkpoint  # noqa: E402

mv.init(distributed=True,
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs, process_id=pid)
assert jax.process_count() == nprocs, jax.process_count()
assert len(jax.devices()) == 2 * nprocs, jax.devices()
assert mv.workers_num() == nprocs and mv.worker_id() == pid
assert mv.is_master_worker() == (pid == 0)

# --- barrier: the multihost_utils.sync_global_devices path -----------------
mv.barrier()

# --- ArrayTable: global-mesh creation + collective per-rank adds -----------
t = mv.ArrayTable(10, name="mp_a")
t.add(np.full(10, float(pid + 1), np.float32))        # rank r pushes (r+1)s
total = sum(range(1, nprocs + 1))
np.testing.assert_allclose(t.get(), np.full(10, float(total)))

# --- MatrixTable rows: different rows per rank, union-applied --------------
m = mv.MatrixTable(8, 4, name="mp_m")
m.add_rows(np.array([pid, 4 + pid]),
           np.ones((2, 4), np.float32) * (pid + 1))
gm = m.get()
want = np.zeros((8, 4), np.float32)
for r in range(nprocs):
    want[r] = r + 1.0
    want[4 + r] = r + 1.0
np.testing.assert_allclose(gm, want)

# --- MatrixTable.get_rows: per-rank id sets union into one collective ------
got = m.get_rows(np.array([pid, 7 - pid]))
np.testing.assert_allclose(got, want[[pid, 7 - pid]])
# A rank with no rows still joins the collective (empty-id lockstep).
got = m.get_rows(np.array([], np.int64) if pid == 0 else np.array([3]))
if pid == 0:
    assert got.shape == (0, 4)
else:
    np.testing.assert_allclose(got, want[[3]])

# --- KVTable: per-rank dict adds allgather-merge into identical stores -----
kv = mv.KVTable(value_shape=(2,), name="mp_kv")
kv.add({f"k{pid}": np.full(2, float(pid + 1)), "shared": np.ones(2)})
g = kv.get([f"k{r}" for r in range(nprocs)] + ["shared"])
for r in range(nprocs):
    np.testing.assert_allclose(g[f"k{r}"], np.full(2, float(r + 1)))
np.testing.assert_allclose(g["shared"], np.full(2, float(nprocs)))

# --- SparseMatrixTable: cached get_rows stays collective-safe across ranks -
sp = mv.SparseMatrixTable(8, 4, name="mp_sp")
sp.add_rows(np.array([pid]), np.full((1, 4), float(pid + 1)))
got = sp.get_rows(np.arange(nprocs))          # miss → collective fill
want_sp = np.zeros((nprocs, 4), np.float32)
for r in range(nprocs):
    want_sp[r] = r + 1.0
np.testing.assert_allclose(got, want_sp)
# Second read: rank 0 all-hit, others ask an extra row — every rank must
# still join the miss collective or the job deadlocks.
got = sp.get_rows(np.arange(nprocs) if pid == 0
                  else np.array([pid, nprocs]))
if pid == 0:
    np.testing.assert_allclose(got, want_sp)
else:
    np.testing.assert_allclose(got[0], want_sp[pid])
    np.testing.assert_allclose(got[1], 0.0)

# --- 1-bit compressed eager add: 1/32-size allgather, identical merges -----
tq = mv.ArrayTable(64, name="mp_q")
dq = np.full(64, float(pid + 1), np.float32)
tq.add(dq, compress="1bit")                 # collective (packed bytes)
got_q = tq.get()
# every rank decoded the identical payload set -> identical stores; the
# per-rank constant deltas quantize exactly (one bucket, exact mean)
np.testing.assert_allclose(got_q, float(total), rtol=1e-5)

# --- BSP: pending until the clock boundary, then one merged apply ----------
ts = mv.ArrayTable(4, name="mp_sync", sync=True)
ts.add(np.ones(4, np.float32) * (pid + 1))
np.testing.assert_allclose(ts.get(), 0.0)             # invisible pre-barrier
mv.barrier()
np.testing.assert_allclose(ts.get(), float(total))

# --- SSP: staleness=1 defers the merged apply one clock, in lockstep -------
# (the SPMD mapping of bounded staleness: every rank defers identically,
# so the flush collective still runs at the same barrier on all ranks)
tssp = mv.ArrayTable(4, name="mp_ssp", sync=True, staleness=1)
tssp.add(np.ones(4, np.float32) * (pid + 1))
mv.barrier()                                   # s=1 overlap: still stale
np.testing.assert_allclose(tssp.get(), 0.0)
mv.barrier()                                   # matured: all ranks' adds
np.testing.assert_allclose(tssp.get(), float(total))
# s=0 table on the same clocks behaves exactly like BSP
tssp0 = mv.ArrayTable(4, name="mp_ssp0", sync=True, staleness=0)
tssp0.add(np.ones(4, np.float32) * (pid + 1))
mv.barrier()
np.testing.assert_allclose(tssp0.get(), float(total))

# --- KV coalesce: N eager adds -> ONE allgather at the barrier -------------
kvc = mv.KVTable(name="mp_kvc", coalesce=True)
_collectives = {"n": 0}
_orig_allgather = kvc._allgather_payload
def _counting_allgather(payload):
    _collectives["n"] += 1
    return _orig_allgather(payload)
kvc._allgather_payload = _counting_allgather
for i in range(5):                        # 5 eager adds, zero collectives
    kvc.add({f"c{pid}": 1.0, "tot": 1.0})
assert _collectives["n"] == 0, _collectives
mv.barrier()                              # ONE merged collective
assert _collectives["n"] == 1, _collectives
gc = kvc.get(["tot"] + [f"c{r}" for r in range(nprocs)])
np.testing.assert_allclose(gc["tot"], 5.0 * nprocs)
for r in range(nprocs):
    np.testing.assert_allclose(gc[f"c{r}"], 5.0)
# --- serve layer: version protocol across ranks (docs/serving.md) ----------
# On this plane every eager add is a lockstep collective apply, so "a
# remote rank's add" bumps the table version IDENTICALLY everywhere —
# the cache must then MISS at max_staleness=0 (never a stale read), HIT
# within a non-zero bound (the documented stale read), and hit/miss in
# lockstep so the fetch collective stays deadlock-free.
from multiverso_tpu import metrics as _metrics  # noqa: E402

tsrv = mv.ArrayTable(8, name="mp_serve", serve_cache=16, max_staleness=0)
tsrv.add(np.ones(8, np.float32))               # collective apply -> v1
g1 = tsrv.get()                                # miss -> cached at v1
np.testing.assert_allclose(g1, float(nprocs))
_h0 = _metrics.counter("serve.cache.hit").value
g2 = tsrv.get()                                # repeat read: cache hit
assert _metrics.counter("serve.cache.hit").value == _h0 + 1
np.testing.assert_allclose(g2, g1)
tsrv.add(np.ones(8, np.float32))               # remote+local adds -> v2
g3 = tsrv.get()                                # stale entry must MISS
assert _metrics.counter("serve.cache.hit").value == _h0 + 1
np.testing.assert_allclose(g3, 2.0 * nprocs)

tstale = mv.ArrayTable(8, name="mp_stale", serve_cache=16, max_staleness=1)
tstale.add(np.ones(8, np.float32))
s1 = tstale.get()                              # cached at v1
tstale.add(np.ones(8, np.float32))             # v2: within the bound
s2 = tstale.get()                              # stale HIT (documented)
np.testing.assert_allclose(s2, s1)
tstale.add(np.ones(8, np.float32))             # v3: bound exceeded
s3 = tstale.get()                              # fresh
np.testing.assert_allclose(s3, 3.0 * nprocs)
tsrv.close()
tstale.close()

# Scratch tables out of the registry (also keeps the checkpoint below
# restorable by the parent test, which re-creates only the core tables).
tssp.close()
tssp0.close()
kvc.close()

# --- checkpoint: collective store, rank-0 write, collective restore --------
path = os.path.join(scratch, "mp.ckpt")
checkpoint.save(path, extra={"step": 7})
t.add(np.ones(10, np.float32))                        # diverge post-snapshot
extra = checkpoint.restore(path)
assert extra == {"step": 7}
np.testing.assert_allclose(t.get(), np.full(10, float(total)))

# --- jax_ext delta-sync: the theano-ext protocol across processes ----------
from multiverso_tpu.ext.jax_ext import mv_shared  # noqa: E402

sv = mv_shared(np.zeros(4, np.float32), name="mp_shared")
sv.set_value(np.full(4, float(pid + 1), np.float32))  # local training drift
merged = sv.mv_sync()                                 # push delta/N, pull
np.testing.assert_allclose(
    merged, np.full(4, total / float(nprocs)), rtol=1e-6)

# --- flagship trainer: collective step + pytree checkpoint round trip ------
from jax.sharding import Mesh  # noqa: E402

from multiverso_tpu.models import (TransformerConfig,  # noqa: E402
                                   TransformerTrainer)

cfg_t = TransformerConfig(vocab_size=64, dim=16, n_layers=1, n_heads=2,
                          hidden=32, max_seq=16)
mesh_t = Mesh(np.asarray(jax.devices()), ("dp",))
tr = TransformerTrainer(cfg_t, mesh_t, updater_type="sgd")
toks = np.random.RandomState(0).randint(
    64, size=(2 * len(jax.devices()), 16)).astype(np.int32)
assert np.isfinite(tr.train_step(toks))
tpath = os.path.join(scratch, "trainer.ckpt")
tr.save(tpath)                                   # collective, rank-0 write
from multiverso_tpu.tables.base import host_fetch  # noqa: E402

want_head = host_fetch(tr.params["head"])        # collective materialize
tr.train_step(toks)                              # diverge
tr.restore(tpath)                                # collective restore
np.testing.assert_array_equal(host_fetch(tr.params["head"]), want_head)
assert np.isfinite(tr.train_step(toks))          # trains on from restore

mv.shutdown()
print("WORKER_OK", pid, flush=True)
