"""Epoll transport tests (docs/transport.md).

The reactor engine (`-net_engine=epoll`, the default for TCP fleets)
drives nonblocking sockets through per-connection read/write state
machines.  These tests cover what the blocking engine's suite cannot:

- the anonymous serve protocol (non-rank clients over raw sockets);
- partial-frame reassembly (1-byte dribble delivery);
- mid-frame peer disconnect (the partial dies, the server stays up);
- hostile frame lengths (connection dropped, no huge allocation);
- write-queue backpressure against a slow reader (EPOLLOUT drain, no
  deadlock, no lost replies);
- a 1k-connection fan-in smoke (`-m slow`).

The rank-fleet semantics themselves (barriers, shards, chaos seams) run
on the epoll engine everywhere else in the suite, since it is the
default — plus the explicit both-engine scenario below.
"""

import os
import shutil
import socket
import struct
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(REPO, "multiverso_tpu", "native")

sys.path.insert(0, REPO)

from multiverso_tpu.serve.wire import (AnonServeClient, FrameDecoder,  # noqa: E402
                                       MSG, ServeBusy, pack_frame,
                                       unpack_frame)

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


# ------------------------------------------------------------ fleet fixture

def _machine_file(tmp_path, n=2):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    eps = [f"127.0.0.1:{s.getsockname()[1]}" for s in socks]
    for s in socks:
        s.close()
    mf = tmp_path / "machines.txt"
    mf.write_text("".join(e + "\n" for e in eps))
    return str(mf), eps


class Fleet:
    """Two epoll-engine server ranks holding table 0 (= 64 ones) up for
    anonymous clients; release() tears them down and returns outputs."""

    def __init__(self, tmp_path, extra=()):
        from multiverso_tpu import native as nat

        nat.ensure_built()
        self.mf, self.endpoints = _machine_file(tmp_path, 2)
        worker = os.path.join(REPO, "tests", "epoll_serve_worker.py")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        self.procs = [
            subprocess.Popen(
                [sys.executable, worker, self.mf, str(r), *extra],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True, env=env)
            for r in range(2)
        ]
        for p in self.procs:
            line = p.stdout.readline()
            assert "SERVE_READY" in line, line

    def release(self):
        outs = []
        for p in self.procs:
            try:
                p.stdin.write("done\n")
                p.stdin.flush()
            except OSError:
                pass
        for p in self.procs:
            outs.append(p.communicate(timeout=120)[0])
        return outs

    def kill(self):
        for p in self.procs:
            if p.poll() is None:
                p.kill()


@pytest.fixture
def fleet(tmp_path):
    f = Fleet(tmp_path)
    try:
        yield f
    finally:
        f.kill()


def _assert_clean_exit(outs, procs):
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"SERVE_WORKER_OK {r}" in out, out[-2000:]


# ------------------------------------------------------- anonymous protocol

def test_anonymous_client_version_and_get(fleet):
    """A raw-socket client (no rank identity) probes the version and
    pulls rank 0's shard; the reactor counts it in the fan-in stats."""
    with AnonServeClient(fleet.endpoints[0]) as c:
        assert c.table_version(0) == 1      # rank 0's blocking add
        shard = c.get_shard(0)
        assert shard.shape == (32,)         # 64 split over 2 server ranks
        np.testing.assert_allclose(shard, 1.0)
        # Several round trips over ONE connection (the pseudo-rank route
        # back must survive reuse).
        for _ in range(5):
            assert c.table_version(0) == 1
    outs = fleet.release()
    _assert_clean_exit(outs, fleet.procs)
    assert "FANIN accepted=1" in outs[0], outs[0]


def test_partial_frame_dribble(fleet):
    """A peer may deliver one byte per readiness event: the reactor must
    reassemble the frame across reads, not assume atomic delivery."""
    host, port = fleet.endpoints[0].rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=30)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    frame = pack_frame(MSG["RequestGet"], 0, 7)
    for i in range(len(frame)):             # 1-byte dribble
        s.sendall(frame[i:i + 1])
        if i < 16:
            time.sleep(0.002)               # force separate wakeups early
    dec = FrameDecoder()
    reply = None
    while reply is None:
        chunk = s.recv(65536)
        assert chunk, "server closed on a dribbled frame"
        dec.feed(chunk)
        body = dec.next_frame()
        if body is not None:
            reply = unpack_frame(body)
    assert reply["type_name"] == "ReplyGet" and reply["msg_id"] == 7
    np.testing.assert_allclose(
        np.frombuffer(reply["blobs"][0], np.float32), 1.0)
    s.close()
    _assert_clean_exit(fleet.release(), fleet.procs)


def test_midframe_disconnect_leaves_server_healthy(fleet):
    """A client dying mid-frame discards the partial: nothing reaches
    the actors, and the NEXT client gets full service."""
    host, port = fleet.endpoints[0].rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=30)
    frame = pack_frame(MSG["RequestGet"], 0, 9)
    s.sendall(frame[:len(frame) // 2])      # half a frame...
    time.sleep(0.05)
    s.close()                               # ...then the peer vanishes
    with AnonServeClient(fleet.endpoints[0]) as c:
        np.testing.assert_allclose(c.get_shard(0), 1.0)
    _assert_clean_exit(fleet.release(), fleet.procs)


def test_hostile_frame_length_drops_connection(fleet):
    """An anonymous connection claiming a larger-than-allowed frame is
    dropped at the length prefix — no arena allocation, no parse."""
    host, port = fleet.endpoints[0].rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=30)
    s.sendall(struct.pack("<q", 1 << 40))   # over the client frame cap
    s.settimeout(10)
    assert s.recv(16) == b""                # server hung up on us
    s.close()
    with AnonServeClient(fleet.endpoints[0]) as c:  # server still fine
        assert c.table_version(0) == 1
    _assert_clean_exit(fleet.release(), fleet.procs)


def test_hostile_num_blobs_drops_connection(fleet):
    """A header-only frame claiming INT32_MAX blobs must fail the
    deserialize bound check (each blob costs >= 8 bytes of frame), not
    force a multi-GB vector reserve that would kill the reactor."""
    from multiverso_tpu.serve.wire import HEADER, _LEN
    host, port = fleet.endpoints[0].rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=30)
    body = HEADER.pack(-1, -1, MSG["RequestGet"], 0, 1, 0, -1, 0, 1,
                       2**31 - 1, 0)        # num_blobs = INT32_MAX
    s.sendall(_LEN.pack(len(body)) + body)
    s.settimeout(10)
    assert s.recv(16) == b""                # dropped as malformed
    s.close()
    with AnonServeClient(fleet.endpoints[0]) as c:  # server still fine
        assert c.table_version(0) == 1
    _assert_clean_exit(fleet.release(), fleet.procs)


def test_rank_src_forgery_stays_anonymous(fleet):
    """Rank identity needs the Hello handshake: an anonymous client
    forging a valid rank in src is still served as an anonymous client
    (the reply routes back over ITS socket — it neither impersonates a
    fleet member nor unlocks the rank frame bound)."""
    from multiverso_tpu.serve.wire import HEADER, _LEN
    host, port = fleet.endpoints[0].rsplit(":", 1)
    s = socket.create_connection((host, int(port)), timeout=30)
    body = HEADER.pack(1, -1, MSG["RequestVersion"], 0, 5, 0, -1, 0, 1,
                       0, 0)                # src = 1: a REAL rank
    s.sendall(_LEN.pack(len(body)) + body)
    dec = FrameDecoder()
    s.settimeout(30)
    reply = None
    while reply is None:
        chunk = s.recv(65536)
        assert chunk, "forged-src client was dropped instead of served"
        dec.feed(chunk)
        body = dec.next_frame()
        if body is not None:
            reply = unpack_frame(body)
    assert reply["type_name"] == "ReplyVersion" and reply["msg_id"] == 5
    s.close()
    outs = fleet.release()
    _assert_clean_exit(outs, fleet.procs)
    assert "FANIN accepted=1" in outs[0], outs[0]  # counted as a client


def test_frame_decoder_rejects_corrupt_length():
    """A desynced/garbled length prefix must raise, not buffer forever
    (a silent None would hang selectors herds on a dead stream)."""
    for bad in (struct.pack("<q", 0), struct.pack("<q", -7),
                struct.pack("<q", 1 << 50)):
        dec = FrameDecoder()
        dec.feed(bad + b"garbage")
        with pytest.raises(ConnectionError):
            dec.next_frame()


def test_write_backpressure_slow_reader(tmp_path):
    """A slow reader fills the bounded per-connection write queue; the
    reactor parks the frames and drains them under EPOLLOUT when the
    reader catches up — every reply arrives, nothing deadlocks."""
    f = Fleet(tmp_path, extra=("-net_writeq_bytes=4096",))
    try:
        host, port = f.endpoints[0].rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=60)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        k = 24                               # ~24 x 200B replies > cap
        for i in range(k):
            s.sendall(pack_frame(MSG["RequestGet"], 0, 100 + i))
        time.sleep(1.0)                      # let the queue actually fill
        dec = FrameDecoder()
        got = []
        s.settimeout(60)
        while len(got) < k:
            chunk = s.recv(4096)
            assert chunk, f"connection died after {len(got)}/{k} replies"
            dec.feed(chunk)
            while True:
                body = dec.next_frame()
                if body is None:
                    break
                got.append(unpack_frame(body))
            time.sleep(0.01)                 # stay slow: EPOLLOUT drains
        assert [g["msg_id"] for g in got] == list(range(100, 100 + k))
        for g in got:
            assert g["type_name"] == "ReplyGet"
        s.close()
        _assert_clean_exit(f.release(), f.procs)
    finally:
        f.kill()


def test_per_client_admission_sheds_busy(tmp_path):
    """`-client_inflight_max=1`: a client firing N gets back-to-back on
    one connection gets at most 1 admitted before replies return — the
    reactor answers the excess with ReplyBusy, never touching the actor
    mailbox."""
    f = Fleet(tmp_path, extra=("-client_inflight_max=1",))
    try:
        host, port = f.endpoints[0].rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=60)
        k = 8
        burst = b"".join(pack_frame(MSG["RequestGet"], 0, 200 + i)
                         for i in range(k))
        s.sendall(burst)
        dec = FrameDecoder()
        replies = []
        s.settimeout(60)
        while len(replies) < k:
            chunk = s.recv(65536)
            assert chunk
            dec.feed(chunk)
            while True:
                body = dec.next_frame()
                if body is None:
                    break
                replies.append(unpack_frame(body))
        kinds = {r["type_name"] for r in replies}
        assert "ReplyBusy" in kinds, kinds   # the gate fired
        assert "ReplyGet" in kinds, kinds    # but service continued
        s.close()
        outs = f.release()
        _assert_clean_exit(outs, f.procs)
        assert "shed=0" not in outs[0].split("FANIN", 1)[1].split()[-1], \
            outs[0]
    finally:
        f.kill()


def test_anon_client_blocked_on_tcp_engine(tmp_path):
    """Control: the blocking tcp engine has no reply route for non-rank
    connections — an anonymous probe must NOT be answered (the fleet
    itself stays healthy).  This is what makes epoll the serve tier."""
    from multiverso_tpu import native as nat

    nat.ensure_built()
    mf, eps = _machine_file(tmp_path, 2)
    code = (
        "import sys, numpy as np\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from multiverso_tpu import native as nat\n"
        f"rt = nat.NativeRuntime(args=['-machine_file={mf}', "
        "'-rank=' + sys.argv[1], '-log_level=error', "
        "'-net_engine=tcp', '-barrier_timeout_ms=60000'])\n"
        "assert rt.net_engine() == 'tcp'\n"
        "h = rt.new_array_table(64)\n"
        "rt.barrier()\n"
        "print('READY', flush=True)\n"
        "sys.stdin.readline()\n"
        "rt.barrier(); rt.shutdown(); print('TCP_OK', flush=True)\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    procs = [subprocess.Popen([sys.executable, "-c", code, str(r)],
                              stdin=subprocess.PIPE,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for r in range(2)]
    try:
        for p in procs:
            assert "READY" in p.stdout.readline()
        host, port = eps[0].rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=10)
        s.sendall(pack_frame(MSG["RequestVersion"], 0, 1))
        s.settimeout(2)
        with pytest.raises((socket.timeout, ConnectionError)):
            data = s.recv(16)
            if not data:
                raise ConnectionError("closed")
        s.close()
        for p in procs:
            p.stdin.write("done\n")
            p.stdin.flush()
        outs = [p.communicate(timeout=120)[0] for p in procs]
        for r, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0 and "TCP_OK" in out, out[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


# --------------------------------------------------------- both-engine fleet

def _binary():
    b = os.path.join(NATIVE_DIR, "build", "mvtpu_test")
    subprocess.run(["make", "-C", NATIVE_DIR, "-j4", "build/mvtpu_test"],
                   check=True, capture_output=True, timeout=600)
    return b


@pytest.mark.parametrize("engine", ["tcp", "epoll"])
def test_net_child_scenario_on_both_engines(tmp_path, engine):
    """The full sharded-table scenario (adds, barriers, SSP cache, KV)
    must hold on BOTH readiness models — `-net_engine` switches the
    transport without changing semantics."""
    mf, _ = _machine_file(tmp_path, 2)
    b = _binary()
    procs = [subprocess.Popen([b, "net_child", mf, str(r), engine],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(2)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=180)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} ({engine}):\n{out[-3000:]}"
        assert f"NET_CHILD_OK {r}" in out


def test_chaos_retry_on_epoll_engine(tmp_path):
    """PR 2 fault seam on the reactor path: two injected send failures
    consume retry attempts, the payload still lands (the epoll twin of
    the chaos suite's tcp scenario)."""
    mf, _ = _machine_file(tmp_path, 2)
    b = _binary()
    procs = [subprocess.Popen([b, "chaos_retry", mf, str(r), "epoll"],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(2)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=180)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r}:\n{out[-3000:]}"
        assert f"CHAOS_RETRY_OK {r}" in out


# ------------------------------------------------------------- 1k fan-in

@pytest.mark.slow
def test_1k_connection_smoke(tmp_path):
    """1000 concurrent anonymous sockets against one server rank: every
    connection gets its version probe answered and the fan-in counter
    records them all (`-net_arena_bytes=8192` bounds the per-connection
    arena so the smoke stays small)."""
    import resource
    import selectors

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if hard < 2200:
        pytest.skip(f"fd hard limit {hard} too low for 1k sockets")
    resource.setrlimit(resource.RLIMIT_NOFILE,
                       (min(hard, 16384), hard))

    f = Fleet(tmp_path, extra=("-net_arena_bytes=8192",))
    try:
        host, port = f.endpoints[0].rsplit(":", 1)
        n = 1000
        sel = selectors.DefaultSelector()
        socks = []
        for i in range(n):
            s = socket.socket()
            s.connect((host, int(port)))
            s.setblocking(False)
            sel.register(s, selectors.EVENT_READ,
                         {"dec": FrameDecoder(), "id": i})
            socks.append(s)
            s.send(pack_frame(MSG["RequestVersion"], 0, i))
        answered = set()
        deadline = time.time() + 120
        while len(answered) < n and time.time() < deadline:
            for key, _ in sel.select(timeout=1.0):
                data = key.data
                try:
                    chunk = key.fileobj.recv(65536)
                except BlockingIOError:
                    continue
                assert chunk, f"conn {data['id']} closed unanswered"
                data["dec"].feed(chunk)
                body = data["dec"].next_frame()
                if body is not None:
                    reply = unpack_frame(body)
                    assert reply["type_name"] in ("ReplyVersion",
                                                  "ReplyBusy")
                    answered.add(data["id"])
        assert len(answered) == n, f"only {len(answered)}/{n} answered"
        for s in socks:
            sel.unregister(s)
            s.close()
        outs = f.release()
        _assert_clean_exit(outs, f.procs)
        assert f"FANIN accepted={n}" in outs[0], outs[0][-500:]
    finally:
        f.kill()
