"""Two-rank hot-key replica invalidation holder (not a pytest module).

Run as ``python embedding_replica_worker.py <machine_file> <rank>``:
rank 1 warms rank 0's hot-key tracker, pulls the replica, and serves a
hot row locally; rank 0 then updates that row SERVER-SIDE (a blocking
add from the other worker — rank 1's own version ledger learns nothing
from it).  Rank 1 must observe the new value within one replica lease
(the snapshot re-pull is the cross-worker invalidation path;
docs/embedding.md).  Rank 1 prints ``REPLICA_FRESH_MS <ms>``;
both ranks print ``REPLICA_WORKER_OK``.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from multiverso_tpu import native as nat  # noqa: E402

ROWS = 64
COLS = 4
LEASE_MS = 100


def main() -> int:
    mf, rank = sys.argv[1], int(sys.argv[2])
    rt = nat.NativeRuntime(args=[
        f"-machine_file={mf}", f"-rank={rank}", "-log_level=error",
        "-rpc_timeout_ms=30000", "-barrier_timeout_ms=60000",
        "-hotkey_topk=16", f"-replica_lease_ms={LEASE_MS}",
        "-hotkey_replica=true"])
    h = rt.new_matrix_table(ROWS, COLS)
    h_kv = rt.new_kv_table()
    rt.barrier()

    if rank == 1:
        # Seed + warm the tracker on rank 0's shard (rows 1, 2).
        rt.matrix_add_rows(h, [1, 2], np.ones((2, COLS), np.float32))
        for _ in range(8):
            rt.matrix_get_rows(h, [1, 2], COLS)
        rt.replica_refresh(h)
        first = rt.matrix_get_rows(h, [1], COLS)
        assert first[0, 0] == 1.0, first
        stats = rt.replica_stats(h)
        assert stats["rows"] >= 1, stats
        rt.kv_add(h_kv, "ready", 1.0)
        deadline = time.time() + 30
        while rt.kv_get(h_kv, "updated") < 1.0:
            if time.time() > deadline:
                raise RuntimeError("rank 0 never updated")
            time.sleep(0.01)
        # The server-side add bumped row 1 to 11; rank 1's own version
        # ledger saw no ack for it, so only the lease re-pull can
        # surface it — within ~one lease, never a stale value forever.
        t0 = time.perf_counter()
        fresh_ms = -1.0
        while time.perf_counter() - t0 < 10.0:
            got = float(rt.matrix_get_rows(h, [1], COLS)[0, 0])
            assert got in (1.0, 11.0), got  # never a torn value
            if got == 11.0:
                fresh_ms = (time.perf_counter() - t0) * 1e3
                break
            time.sleep(0.01)
        assert fresh_ms >= 0, "stale past 10 s"
        assert fresh_ms <= 20 * LEASE_MS, fresh_ms  # within ~one lease
        print(f"REPLICA_FRESH_MS {fresh_ms:.1f}", flush=True)
        rt.kv_add(h_kv, "done", 1.0)
    else:
        deadline = time.time() + 60
        while rt.kv_get(h_kv, "ready") < 1.0:
            if time.time() > deadline:
                raise RuntimeError("rank 1 never readied")
            time.sleep(0.01)
        # SERVER-SIDE update from this rank: rank 1 gets no ack stamp.
        rt.matrix_add_rows(h, [1], np.full((1, COLS), 10.0, np.float32))
        rt.kv_add(h_kv, "updated", 1.0)
        while rt.kv_get(h_kv, "done") < 1.0:
            if time.time() > deadline:
                raise RuntimeError("rank 1 never finished")
            time.sleep(0.01)

    rt.barrier()
    rt.shutdown()
    print(f"REPLICA_WORKER_OK {rank}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
