"""Flagship transformer + ring attention tests: exactness of the
sequence-parallel path against the local path, sharded training
convergence, and updater-semantics integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import dense_attention_ref
from jax.sharding import Mesh

from multiverso_tpu.models import (TransformerConfig, TransformerTrainer,
                                   init_params)
from multiverso_tpu.models.transformer import lm_loss, transformer_forward
from multiverso_tpu.parallel.ring_attention import (
    blockwise_attention_local, ring_attention)


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(2, 4, 64, 16).astype(np.float32))
    return mk(), mk(), mk()


def test_blockwise_local_matches_dense(qkv):
    q, k, v = qkv
    want = dense_attention_ref(q, k, v)
    got = blockwise_attention_local(q, k, v, 16 ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)


@pytest.mark.parametrize("shape,names", [
    ((8,), ("sp",)),
    ((2, 4), ("dp", "sp")),
    ((2, 2, 2), ("dp", "sp", "tp")),
])
def test_ring_attention_exact(qkv, shape, names):
    q, k, v = qkv
    mesh = Mesh(np.asarray(jax.devices()).reshape(shape), names)
    want = dense_attention_ref(q, k, v)
    got = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)


def test_ring_attention_non_causal(qkv):
    q, k, v = qkv
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "sp"))
    want = dense_attention_ref(q, k, v, causal=False)
    got = ring_attention(q, k, v, mesh, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)


_CFG = TransformerConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                         hidden=128, max_seq=64, compute_dtype=jnp.float32)


def test_forward_ring_matches_local():
    params = jax.tree_util.tree_map(jnp.asarray, init_params(_CFG, seed=0))
    toks = jnp.asarray(np.random.RandomState(0).randint(
        128, size=(4, 32)).astype(np.int32))
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 2, 2),
                ("dp", "sp", "tp"))
    local = transformer_forward(params, toks, _CFG, mesh=None)
    ring = transformer_forward(params, toks, _CFG, mesh=mesh)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(local),
                               atol=1e-3)


def test_trainer_loss_decreases_sharded():
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 2, 2),
                ("dp", "sp", "tp"))
    tr = TransformerTrainer(_CFG, mesh, updater_type="sgd")
    toks = np.random.RandomState(1).randint(
        128, size=(4, 32)).astype(np.int32)
    first = tr.train_step(toks)
    for _ in range(15):
        last = tr.train_step(toks)
    assert last < first * 0.7, (first, last)


def test_trainer_momentum_state():
    """Stateful updater threads through the pytree step."""
    mesh = Mesh(np.asarray(jax.devices()), ("dp",))
    tr = TransformerTrainer(_CFG, mesh, updater_type="momentum")
    toks = np.random.RandomState(2).randint(
        128, size=(2, 16)).astype(np.int32)
    tr.train_step(toks)
    v = tr.state["head"][0]
    assert float(jnp.abs(v).max()) > 0.0   # velocity populated


def test_bf16_compute_path():
    cfg = TransformerConfig(vocab_size=64, dim=32, n_layers=1, n_heads=2,
                            hidden=64, max_seq=32,
                            compute_dtype=jnp.bfloat16)
    params = jax.tree_util.tree_map(jnp.asarray, init_params(cfg, seed=0))
    toks = jnp.asarray(np.random.RandomState(0).randint(
        64, size=(2, 16)).astype(np.int32))
    out = transformer_forward(params, toks, cfg, mesh=None)
    assert out.dtype == jnp.bfloat16
    loss = lm_loss(params, toks, cfg)
    assert np.isfinite(float(loss))


@pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
def test_ring_attention_layouts_exact(qkv, layout):
    q, k, v = qkv
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "sp"))
    want = dense_attention_ref(q, k, v)
    got = ring_attention(q, k, v, mesh, layout=layout)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)


def test_ring_zigzag_rejects_non_causal():
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "sp"))
    q = jnp.zeros((1, 1, 64, 16))
    with pytest.raises(ValueError, match="zigzag"):
        ring_attention(q, q, q, mesh, causal=False, layout="zigzag")


def _mesh2(names=("dp", "sp")):
    devs = np.array(jax.devices()[:2]).reshape(1, 2)
    return Mesh(devs, names)


@pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
def test_ring_uses_flash_kernel_exact(monkeypatch, layout):
    """sp=2 ring with the Pallas kernel force-dispatched per ring step
    (interpret mode): the sp>1 path must hit kernel speed on TPU, so CI
    must prove the kernel path is numerically exact inside the ring."""
    monkeypatch.setenv("MVTPU_FORCE_FLASH", "interpret")
    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(1, 2, 256, 32).astype(np.float32)) * 0.4
    k = jnp.asarray(rng.randn(1, 2, 256, 32).astype(np.float32)) * 0.4
    v = jnp.asarray(rng.randn(1, 2, 256, 32).astype(np.float32)) * 0.4
    mesh = _mesh2()
    got = ring_attention(q, k, v, mesh, axis_name="sp", causal=True,
                         batch_axis="dp", head_axis=None, layout=layout)
    want = dense_attention_ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_scan_remat_matches_loop():
    """scan_layers + remat is a pure re-scheduling: forward logits and
    gradients must match the loop format bit-for-bit-ish (f32 tolerance)."""
    from dataclasses import replace

    from multiverso_tpu.models.transformer import stack_layer_params

    cfg_scan = replace(_CFG, scan_layers=True, remat=True)
    loop_params = jax.tree_util.tree_map(jnp.asarray,
                                         init_params(_CFG, seed=3))
    scan_params = dict(loop_params,
                       layers=stack_layer_params(loop_params["layers"]))
    toks = jnp.asarray(np.random.RandomState(3).randint(
        128, size=(2, 32)).astype(np.int32))

    out_loop = transformer_forward(loop_params, toks, _CFG, mesh=None)
    out_scan = transformer_forward(scan_params, toks, cfg_scan, mesh=None)
    np.testing.assert_allclose(np.asarray(out_scan), np.asarray(out_loop),
                               atol=1e-5)

    g_loop = jax.grad(lm_loss)(loop_params, toks, _CFG)
    g_scan = jax.grad(lm_loss)(scan_params, toks, cfg_scan)
    np.testing.assert_allclose(np.asarray(g_scan["head"]),
                               np.asarray(g_loop["head"]), atol=1e-5)
    g_scan_l0 = jax.tree_util.tree_map(lambda a: np.asarray(a[0]),
                                       g_scan["layers"])
    for key in ("wq", "w2", "attn_norm"):
        np.testing.assert_allclose(
            g_scan_l0[key], np.asarray(g_loop["layers"][0][key]), atol=1e-5)


def test_selective_remat_matches_full():
    """remat_policy='dots' (save matmul outputs, recompute attention) is a
    pure re-scheduling too: logits and grads must match full remat."""
    from dataclasses import replace

    from multiverso_tpu.models.transformer import stack_layer_params

    cfg_full = replace(_CFG, scan_layers=True, remat=True)
    cfg_sel = replace(cfg_full, remat_policy="dots")
    loop_params = jax.tree_util.tree_map(jnp.asarray,
                                         init_params(_CFG, seed=7))
    params = dict(loop_params,
                  layers=stack_layer_params(loop_params["layers"]))
    toks = jnp.asarray(np.random.RandomState(7).randint(
        128, size=(2, 32)).astype(np.int32))

    out_full = transformer_forward(params, toks, cfg_full, mesh=None)
    out_sel = transformer_forward(params, toks, cfg_sel, mesh=None)
    np.testing.assert_allclose(np.asarray(out_sel), np.asarray(out_full),
                               atol=1e-5)
    g_full = jax.grad(lm_loss)(params, toks, cfg_full)
    g_sel = jax.grad(lm_loss)(params, toks, cfg_sel)
    for key in ("wq", "w2", "attn_norm"):
        np.testing.assert_allclose(
            np.asarray(g_sel["layers"][key]),
            np.asarray(g_full["layers"][key]), atol=1e-5)

    with pytest.raises(ValueError, match="remat_policy"):
        transformer_forward(params, toks,
                            replace(cfg_full, remat_policy="bogus"),
                            mesh=None)


def test_scan_remat_trainer_sharded():
    """Full trainer on a (dp, sp, tp) mesh with scan+remat params: the
    stacked layout shards, trains, and the loss falls."""
    from dataclasses import replace

    cfg = replace(_CFG, scan_layers=True, remat=True)
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 2, 2),
                ("dp", "sp", "tp"))
    tr = TransformerTrainer(cfg, mesh, updater_type="sgd")
    assert isinstance(tr.params["layers"], dict)       # stacked format
    assert tr.params["layers"]["wq"].shape[0] == cfg.n_layers
    toks = np.random.RandomState(4).randint(
        128, size=(4, 32)).astype(np.int32)
    first = tr.train_step(toks)
    for _ in range(15):
        last = tr.train_step(toks)
    assert last < first * 0.7, (first, last)


def test_scan_remat_moe():
    """MoE layers stack and scan too (nested dict leaves)."""
    from dataclasses import replace

    cfg = replace(_CFG, scan_layers=True, remat=True, num_experts=4,
                  top_k=2)
    params = jax.tree_util.tree_map(jnp.asarray, init_params(cfg, seed=5))
    assert params["layers"]["moe"]["w1"].shape[0] == cfg.n_layers
    toks = jnp.asarray(np.random.RandomState(5).randint(
        128, size=(2, 16)).astype(np.int32))
    loss = lm_loss(params, toks, cfg)
    assert np.isfinite(float(loss))


def test_ring_flash_grad_matches_dense(monkeypatch):
    """Gradients through the ring with kernel pieces (the lse-cotangent
    path through the custom_vjp) match dense-attention gradients."""
    monkeypatch.setenv("MVTPU_FORCE_FLASH", "interpret")
    rng = np.random.RandomState(12)
    q = jnp.asarray(rng.randn(1, 2, 256, 32).astype(np.float32)) * 0.4
    k = jnp.asarray(rng.randn(1, 2, 256, 32).astype(np.float32)) * 0.4
    v = jnp.asarray(rng.randn(1, 2, 256, 32).astype(np.float32)) * 0.4
    mesh = _mesh2()

    def ring_loss(q, k, v):
        o = ring_attention(q, k, v, mesh, axis_name="sp", causal=True,
                           batch_axis="dp", head_axis=None)
        return jnp.sum(jnp.square(o))

    def dense_loss(q, k, v):
        return jnp.sum(jnp.square(dense_attention_ref(q, k, v, True)))

    got = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=4e-4)


def test_trainer_checkpoint_roundtrip_cross_mesh(mv, tmp_path):
    """TransformerTrainer.save/restore: exact state round trip, including
    restoring onto a DIFFERENT mesh layout (1-axis dp -> 3-axis
    dp/sp/tp), with stateful updater slots preserved."""
    from jax.sharding import PartitionSpec as P

    mv.init()
    toks = np.random.RandomState(6).randint(
        128, size=(4, 32)).astype(np.int32)

    mesh1 = Mesh(np.asarray(jax.devices()), ("dp",))
    tr = TransformerTrainer(_CFG, mesh1, updater_type="momentum")
    for _ in range(3):
        tr.train_step(toks)
    path = str(tmp_path / "trainer.ckpt")
    tr.save(path)
    want = jax.tree_util.tree_map(np.asarray, tr.params)
    tr.train_step(toks)                       # diverge past the snapshot
    tr.restore(path)
    got = jax.tree_util.tree_map(np.asarray, tr.params)
    jax.tree_util.tree_map(np.testing.assert_array_equal, got, want)

    mesh3 = Mesh(np.asarray(jax.devices()).reshape(2, 2, 2),
                 ("dp", "sp", "tp"))
    tr3 = TransformerTrainer(_CFG, mesh3, updater_type="momentum")
    tr3.restore(path)                         # cross-mesh re-placement
    got3 = jax.tree_util.tree_map(np.asarray, tr3.params)
    jax.tree_util.tree_map(np.testing.assert_array_equal, got3, want)
    assert tr3.params["head"].sharding.spec == P(None, "tp")
    # momentum slots restored too (non-zero after 3 steps)
    assert float(jnp.abs(tr3.state["head"][0]).max()) > 0
    # and training continues from the restored point
    loss = tr3.train_step(toks)
    assert np.isfinite(loss)



def test_ce_custom_vjp_matches_autodiff():
    """The CE custom_vjp (bf16 cotangent so the head backward runs MXU
    bf16 matmuls) must produce the same dlogits as plain autodiff of
    the f32 loss math — exactly in f32 mode (the cast is the identity,
    keeping the fp32 parity gates honest), and to bf16 rounding in bf16
    mode."""
    from multiverso_tpu.models.transformer import _ce, _ce_value

    rng = np.random.RandomState(0)
    for dt, tol in ((jnp.float32, 1e-6), (jnp.bfloat16, 2e-3)):
        logits = jnp.asarray(rng.randn(2, 8, 32), dt)
        tgt = jnp.asarray(rng.randint(32, size=(2, 8)), jnp.int32)
        g1 = jax.grad(lambda l: _ce(l, tgt))(logits)
        g2 = jax.grad(lambda l: _ce_value(l, tgt))(logits)
        assert g1.dtype == dt
        err = float(jnp.max(jnp.abs(g1.astype(jnp.float32)
                                    - g2.astype(jnp.float32))))
        assert err < tol, (dt, err)


def test_grad_accumulation_matches_full_batch(mv):
    """accum=2 (two microbatches, one update) must produce the same
    post-step params as the plain full-batch step in f32 — the CE is a
    mean over equal chunks, so summed-then-halved microbatch grads ARE
    the full-batch grads."""
    from jax.sharding import Mesh

    cfg = TransformerConfig(vocab_size=64, dim=32, n_layers=2, n_heads=2,
                            hidden=64, max_seq=16,
                            compute_dtype=jnp.float32)
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("dp",))
    toks = np.random.RandomState(3).randint(64, size=(8, 16)).astype(np.int32)

    tr_a = TransformerTrainer(cfg, mesh, updater_type="sgd")
    tr_b = TransformerTrainer(cfg, mesh, updater_type="sgd")
    loss_a = float(tr_a.train_step_async(toks))
    loss_b = float(tr_b.train_step_async(toks, accum=2))
    assert abs(loss_a - loss_b) < 1e-5, (loss_a, loss_b)
    for a, b in zip(jax.tree_util.tree_leaves(tr_a.params),
                    jax.tree_util.tree_leaves(tr_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    # bad split and dp-indivisible microbatch both fail loudly; MoE is
    # rejected (its aux loss is batch-nonlinear, accumulation would
    # silently change the objective)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="divisible"):
        tr_b.train_step_async(toks[:6], accum=4)
    with _pytest.raises(ValueError, match="dp axis"):
        tr_b.train_step_async(toks, accum=8)   # microbatch 1 vs dp=2
    cfg_moe = TransformerConfig(vocab_size=64, dim=32, n_layers=2,
                                n_heads=2, hidden=64, max_seq=16,
                                num_experts=4, top_k=2)
    tr_moe = TransformerTrainer(cfg_moe, mesh, updater_type="sgd")
    with _pytest.raises(ValueError, match="MoE"):
        tr_moe.train_step_async(toks, accum=2)
