"""Core runtime tests: init/shutdown/barrier/ids, flags, log, dashboard.

Models the reference's Configure/Log/lifecycle unit tests (SURVEY.md §4).
"""

import jax
import pytest


def test_devices_virtualized():
    assert len(jax.devices()) == 8


def test_init_shutdown_lifecycle(mv):
    ctx = mv.init()
    assert mv.initialized()
    assert mv.workers_num() == 1          # one controller process
    assert mv.worker_id() == 0
    assert mv.server_id() == 0            # Role.ALL co-hosts server shards
    assert mv.is_master_worker()
    assert mv.num_replicas() == 8         # device-level dp width
    c0 = mv.clock()
    mv.barrier()
    assert mv.clock() == c0 + 1
    mv.shutdown()
    assert not mv.initialized()


def test_init_idempotent(mv):
    ctx1 = mv.init()
    ctx2 = mv.init()
    assert ctx1 is ctx2


def test_flag_parsing(mv):
    rest = mv.config.parse_cmd_flags(
        ["-sync=true", "--updater_type=adagrad", "-port=1234", "positional"])
    assert rest == ["positional"]
    assert mv.config.get("sync") is True
    assert mv.config.get("updater_type") == "adagrad"
    assert mv.config.get("port") == 1234


def test_init_applies_flags(mv):
    ctx = mv.init(args=["-sync=true", "-updater_type=momentum"])
    assert ctx.sync is True
    assert ctx.updater_type == "momentum"


def test_init_kwargs_override_flags(mv):
    ctx = mv.init(args=["-sync=true"], sync=False, updater_type="sgd")
    assert ctx.sync is False
    assert ctx.updater_type == "sgd"


def test_unknown_flag_left_in_remainder(mv):
    rest = mv.config.parse_cmd_flags(["-no_such_flag=1"])
    assert rest == ["-no_such_flag=1"]


def test_log_fatal_raises(mv):
    from multiverso_tpu.log import FatalError

    with pytest.raises(FatalError):
        mv.Log.fatal("boom %d", 42)


def test_dashboard_monitor(mv):
    mv.dashboard.reset()
    with mv.dashboard.monitor("UnitTest::Op"):
        pass
    with mv.dashboard.monitor("UnitTest::Op"):
        pass
    mons = mv.dashboard.report(log=False)
    assert mons["UnitTest::Op"].count == 2
    assert mons["UnitTest::Op"].total_s >= 0


def test_table_registry(mv):
    mv.init()
    t1 = mv.ArrayTable(16)
    t2 = mv.ArrayTable(32)
    ctx = mv.get_context()
    assert t1.table_id != t2.table_id
    assert ctx.table(t1.table_id) is t1
    assert len(ctx.tables()) == 2


def test_init_kwargs_do_not_leak_across_lifecycles(mv):
    """sync/updater kwargs are per-lifecycle; only CLI args persist."""
    ctx1 = mv.init(sync=True, updater_type="momentum")
    assert ctx1.sync is True
    mv.shutdown()
    ctx2 = mv.init()
    assert ctx2.sync is False
    assert ctx2.updater_type == "default"
