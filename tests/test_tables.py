"""Table layer tests: Array/Matrix/SparseMatrix/KV get-add round trips,
BSP vs ASP semantics, sharding, checkpoint snapshots.

Models the reference's in-process table round-trip tests plus the Python
binding tests (SURVEY.md §4), run on the virtual 8-device mesh.
"""

import numpy as np
import pytest


# --------------------------------------------------------------- ArrayTable

def test_array_get_initial(mv):
    mv.init()
    t = mv.ArrayTable(10)
    np.testing.assert_allclose(t.get(), 0.0)


def test_array_init_value(mv):
    mv.init()
    init = np.arange(10, dtype=np.float32)
    t = mv.ArrayTable(10, init=init)
    np.testing.assert_allclose(t.get(), init)


def test_array_add_get_roundtrip(mv):
    mv.init()
    t = mv.ArrayTable(100)
    d = np.random.RandomState(0).randn(100).astype(np.float32)
    t.add(d)
    t.add(d)
    np.testing.assert_allclose(t.get(), 2 * d, rtol=1e-5)


def test_array_add_stacked_workers(mv):
    """[k, size] delta = k workers' contributions summed before update."""
    mv.init()
    t = mv.ArrayTable(16)
    deltas = np.ones((4, 16), np.float32)
    t.add(deltas)
    np.testing.assert_allclose(t.get(), 4.0)


def test_array_sgd_updater(mv):
    mv.init(updater_type="sgd")
    t = mv.ArrayTable(8, init=np.ones(8, np.float32))
    g = np.full(8, 2.0, np.float32)
    t.add(g, option=mv.AddOption(learning_rate=0.5))
    np.testing.assert_allclose(t.get(), 0.0, atol=1e-6)


def test_array_adagrad_state_persists(mv):
    mv.init(updater_type="adagrad")
    t = mv.ArrayTable(8)
    g = np.ones(8, np.float32)
    opt = mv.AddOption(learning_rate=0.1)
    t.add(g, option=opt)
    t.add(g, option=opt)
    exp = -0.1 - 0.1 / np.sqrt(2.0)
    np.testing.assert_allclose(t.get(), exp, rtol=1e-4)


def test_array_bsp_sync_buffering(mv):
    """sync=True: adds invisible until the clock boundary (barrier)."""
    mv.init(sync=True)
    t = mv.ArrayTable(4)
    t.add(np.ones(4, np.float32))
    t.add(np.ones(4, np.float32))
    np.testing.assert_allclose(t.get(), 0.0)      # still clock t
    mv.barrier()                                   # clock closes
    np.testing.assert_allclose(t.get(), 2.0)


def test_array_sharded_over_mesh(mv):
    mv.init()
    t = mv.ArrayTable(64)
    data, _ = t.raw_value()
    assert len(data.sharding.device_set) == 8


def test_array_odd_size_padding(mv):
    mv.init()
    t = mv.ArrayTable(13)           # not divisible by 8
    d = np.arange(13, dtype=np.float32)
    t.add(d)
    np.testing.assert_allclose(t.get(), d)
    assert t.get().shape == (13,)


def test_array_checkpoint_roundtrip(mv):
    mv.init(updater_type="adagrad")
    t = mv.ArrayTable(8)
    t.add(np.ones(8, np.float32))
    snap = t.store_state()
    t.add(np.ones(8, np.float32))
    t.load_state(snap)
    t2 = mv.ArrayTable(8, updater_type="adagrad")
    t2.add(np.ones(8, np.float32))
    np.testing.assert_allclose(t.get(), t2.get(), rtol=1e-6)


# -------------------------------------------------------------- MatrixTable

def test_matrix_get_add_all(mv):
    mv.init()
    t = mv.MatrixTable(10, 4)
    d = np.random.RandomState(1).randn(10, 4).astype(np.float32)
    t.add(d)
    np.testing.assert_allclose(t.get(), d, rtol=1e-5)


def test_matrix_get_rows(mv):
    mv.init()
    init = np.arange(40, dtype=np.float32).reshape(10, 4)
    t = mv.MatrixTable(10, 4, init=init)
    out = t.get_rows([3, 7, 0])
    np.testing.assert_allclose(out, init[[3, 7, 0]])


def test_matrix_add_rows(mv):
    mv.init()
    t = mv.MatrixTable(10, 4)
    rows = np.array([2, 5])
    d = np.ones((2, 4), np.float32)
    t.add_rows(rows, d)
    full = t.get()
    np.testing.assert_allclose(full[[2, 5]], 1.0)
    untouched = np.delete(full, [2, 5], axis=0)
    np.testing.assert_allclose(untouched, 0.0)


def test_matrix_add_rows_duplicates_aggregate(mv):
    mv.init()
    t = mv.MatrixTable(6, 2)
    rows = np.array([1, 1, 3])
    d = np.ones((3, 2), np.float32)
    t.add_rows(rows, d)
    full = t.get()
    np.testing.assert_allclose(full[1], 2.0)
    np.testing.assert_allclose(full[3], 1.0)


def test_matrix_rows_with_adagrad(mv):
    mv.init(updater_type="adagrad")
    t = mv.MatrixTable(6, 2)
    opt = mv.AddOption(learning_rate=0.1)
    t.add_rows([1], np.ones((1, 2), np.float32), option=opt)
    t.add_rows([1], np.ones((1, 2), np.float32), option=opt)
    exp = -0.1 - 0.1 / np.sqrt(2.0)
    full = t.get()
    np.testing.assert_allclose(full[1], exp, rtol=1e-4)
    np.testing.assert_allclose(full[0], 0.0)


def test_matrix_bsp_sparse_flush(mv):
    mv.init(sync=True)
    t = mv.MatrixTable(6, 2)
    t.add_rows([0], np.ones((1, 2), np.float32))
    t.add_rows([0, 2], np.ones((2, 2), np.float32))
    np.testing.assert_allclose(t.get(), 0.0)
    mv.barrier()
    full = t.get()
    np.testing.assert_allclose(full[0], 2.0)
    np.testing.assert_allclose(full[2], 1.0)


def test_matrix_handler_parity_api(mv):
    mv.init()
    t = mv.MatrixTableHandler(5, 3)
    t.add_all(np.ones((5, 3), np.float32))
    np.testing.assert_allclose(t.get_all(), 1.0)
    t.add_by_rows(np.ones((2, 3), np.float32), [0, 4])
    np.testing.assert_allclose(t.get_by_rows([0, 4]), 2.0)


def test_matrix_large_row_bucket(mv):
    """Row batch > default bucket exercises bucketing/padding."""
    mv.init()
    t = mv.MatrixTable(100, 3)
    rows = np.arange(37)
    t.add_rows(rows, np.ones((37, 3), np.float32))
    np.testing.assert_allclose(t.get()[:37], 1.0)
    np.testing.assert_allclose(t.get()[37:], 0.0)


# ------------------------------------------------------- SparseMatrixTable

def test_sparse_matrix_cache_and_invalidate(mv):
    mv.init()
    t = mv.SparseMatrixTable(8, 2)
    out0 = t.get_rows([1, 2])
    np.testing.assert_allclose(out0, 0.0)
    t.add_rows([1], np.ones((1, 2), np.float32))
    out1 = t.get_rows([1, 2])
    np.testing.assert_allclose(out1[0], 1.0)     # cache invalidated on add
    np.testing.assert_allclose(out1[1], 0.0)


def test_sparse_matrix_same_math_as_dense(mv):
    mv.init(updater_type="sgd")
    t = mv.SparseMatrixTable(8, 2)
    opt = mv.AddOption(learning_rate=1.0)
    t.add_rows([3], np.ones((1, 2), np.float32), option=opt)
    np.testing.assert_allclose(t.get()[3], -1.0)


# ------------------------------------------------------------------ KVTable

def test_kv_basic(mv):
    mv.init()
    t = mv.KVTable(value_shape=(3,))
    t.add({"a": np.ones(3, np.float32)})
    t.add({"a": np.ones(3, np.float32), "b": 2 * np.ones(3, np.float32)})
    out = t.get(["a", "b", "missing"])
    np.testing.assert_allclose(out["a"], 2.0)
    np.testing.assert_allclose(out["b"], 2.0)
    np.testing.assert_allclose(out["missing"], 0.0)
    assert "a" in t.raw


def test_kv_sync_flush(mv):
    mv.init(sync=True)
    t = mv.KVTable(value_shape=())
    t.add({"x": np.float32(1.0)})
    t.add({"x": np.float32(2.0)})
    np.testing.assert_allclose(t.get(["x"])["x"], 0.0)
    mv.barrier()
    np.testing.assert_allclose(t.get(["x"])["x"], 3.0)


def test_kv_sgd_updater(mv):
    mv.init(updater_type="sgd")
    t = mv.KVTable(value_shape=(2,))
    t.add({"w": np.ones(2, np.float32)},
          option=mv.AddOption(learning_rate=0.5))
    np.testing.assert_allclose(t.get(["w"])["w"], -0.5)


# ------------------------------------------------------------------ factory

def test_factory(mv):
    mv.init()
    a = mv.create_table("array", 8)
    m = mv.create_table("matrix", 4, 2)
    s = mv.create_table("sparse_matrix", 4, 2)
    k = mv.create_table("kv", value_shape=(1,))
    assert a.kind == "array" and m.kind == "matrix"
    assert s.kind == "sparse_matrix" and k.kind == "kv"
    with pytest.raises(ValueError):
        mv.create_table("nope")


# ------------------------------------------------- code-review regressions

def test_array_bsp_respects_add_option(mv):
    """BSP flush must apply each buffered add's own AddOption."""
    mv.init(sync=True, updater_type="sgd")
    t = mv.ArrayTable(4)
    t.add(np.ones(4, np.float32), option=mv.AddOption(learning_rate=0.5))
    mv.barrier()
    np.testing.assert_allclose(t.get(), -0.5)


def test_matrix_bsp_respects_add_option(mv):
    mv.init(sync=True, updater_type="sgd")
    t = mv.MatrixTable(4, 2)
    t.add_rows([1], np.ones((1, 2), np.float32),
               option=mv.AddOption(learning_rate=2.0))
    mv.barrier()
    np.testing.assert_allclose(t.get()[1], -2.0)


def test_kv_scalar_momentum(mv):
    """0-d values must work with stateful updaters."""
    mv.init(updater_type="momentum")
    t = mv.KVTable(value_shape=())
    t.add({"x": np.float32(1.0)},
          option=mv.AddOption(learning_rate=0.1, momentum=0.9))
    np.testing.assert_allclose(t.get(["x"])["x"], -0.1, rtol=1e-6)


def test_sparse_empty_get_rows(mv):
    mv.init()
    t = mv.SparseMatrixTable(8, 2)
    out = t.get_rows([])
    assert out.shape == (0, 2)
    out2 = mv.MatrixTable(8, 2).get_rows([])
    assert out2.shape == (0, 2)


def test_sparse_cache_invalidated_on_load_state(mv):
    mv.init()
    t = mv.SparseMatrixTable(4, 2)
    snap = t.store_state()          # all zeros
    t.add_rows([1], np.ones((1, 2), np.float32))
    _ = t.get_rows([1])             # warm cache with 1.0
    t.load_state(snap)
    np.testing.assert_allclose(t.get_rows([1]), 0.0)


def test_array_concurrent_adds_threadsafe(mv):
    """Donating jit under concurrency must not lose adds or crash."""
    import threading

    mv.init()
    t = mv.ArrayTable(16)
    d = np.ones(16, np.float32)

    def work():
        for _ in range(10):
            t.add(d)

    threads = [threading.Thread(target=work) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    np.testing.assert_allclose(t.get(), 40.0)


# ---------------------------------------------------- device-resident eager

def test_array_device_add_and_get(mv):
    """Device-resident fast path: jax.Array delta in, device array out,
    same math as the host parity path (no wire hop in between)."""
    import jax
    import jax.numpy as jnp

    mv.init()
    t = mv.ArrayTable(100)
    d = np.random.RandomState(1).randn(100).astype(np.float32)
    t.add(jnp.asarray(d))                 # device delta
    t.add(d)                              # host delta, same table
    np.testing.assert_allclose(t.get(), 2 * d, rtol=1e-5)
    dev = t.get(device=True)
    assert isinstance(dev, jax.Array) and dev.shape == (100,)
    np.testing.assert_allclose(np.asarray(dev), 2 * d, rtol=1e-5)
    # the returned buffer is a snapshot: later adds must not mutate it
    t.add(d)
    np.testing.assert_allclose(np.asarray(dev), 2 * d, rtol=1e-5)


def test_array_device_add_respects_updater(mv):
    import jax.numpy as jnp

    mv.init(updater_type="sgd")
    t = mv.ArrayTable(8)
    g = np.ones(8, np.float32)
    t.add(jnp.asarray(g), option=mv.AddOption(learning_rate=0.5))
    np.testing.assert_allclose(t.get(), -0.5 * g, rtol=1e-6)


def test_array_device_add_stacked(mv):
    import jax.numpy as jnp

    mv.init()
    t = mv.ArrayTable(16)
    d = np.random.RandomState(2).randn(4, 16).astype(np.float32)
    t.add(jnp.asarray(d))                 # [k, size] worker stack, device
    np.testing.assert_allclose(t.get(), d.sum(0), rtol=1e-5)


def test_array_device_add_bsp_falls_back(mv):
    """sync=True tables buffer device deltas like host ones (BSP clock)."""
    import jax.numpy as jnp

    mv.init()
    t = mv.ArrayTable(8, sync=True)
    t.add(jnp.ones(8, dtype=jnp.float32))
    np.testing.assert_allclose(t.get(), 0.0)     # invisible pre-barrier
    mv.barrier()
    np.testing.assert_allclose(t.get(), 1.0)


def test_matrix_device_add_and_get(mv):
    import jax
    import jax.numpy as jnp

    mv.init()
    t = mv.MatrixTable(10, 4)
    d = np.random.RandomState(3).randn(10, 4).astype(np.float32)
    t.add(jnp.asarray(d))
    dev = t.get(device=True)
    assert isinstance(dev, jax.Array) and dev.shape == (10, 4)
    np.testing.assert_allclose(np.asarray(dev), d, rtol=1e-5)
    np.testing.assert_allclose(t.get(), d, rtol=1e-5)


def test_array_device_add_shape_error(mv):
    import jax.numpy as jnp

    mv.init()
    t = mv.ArrayTable(8)
    with pytest.raises(ValueError, match="delta shape"):
        t.add(jnp.ones(9, dtype=jnp.float32))


# ------------------------------------------------------- SSP (staleness)

def test_ssp_staleness_defers_one_clock(mv):
    """staleness=1: a clock's adds stay invisible through ONE barrier and
    land at the next — the SSP reader bound t-1-s (SURVEY.md §2.9-bis)."""
    mv.init()
    t = mv.ArrayTable(4, sync=True, staleness=1, name="ssp_a",
                      updater_type="default")
    t.add(np.ones(4, np.float32))
    np.testing.assert_allclose(t.get(), 0.0)   # buffered (BSP-like)
    mv.barrier()
    np.testing.assert_allclose(t.get(), 0.0)   # deferred: still stale
    mv.barrier()
    np.testing.assert_allclose(t.get(), 1.0)   # matured after s+1 clocks


def test_ssp_zero_equals_bsp(mv):
    """staleness=0 must be bit-identical to plain BSP."""
    mv.init()
    t = mv.ArrayTable(4, sync=True, staleness=0, name="ssp_b",
                      updater_type="default")
    t.add(np.full(4, 2.0, np.float32))
    mv.barrier()
    np.testing.assert_allclose(t.get(), 2.0)


def test_ssp_matrix_and_kv_defer(mv):
    mv.init()
    m = mv.MatrixTable(4, 2, sync=True, staleness=1, name="ssp_m",
                       updater_type="default")
    kv = mv.KVTable(sync=True, staleness=1, name="ssp_kv",
                    updater_type="default")
    m.add_rows(np.array([1]), np.ones((1, 2), np.float32))
    kv.add({"k": 5.0})
    mv.barrier()
    np.testing.assert_allclose(m.get()[1], 0.0)
    assert kv.get(["k"])["k"] == 0.0
    mv.barrier()
    np.testing.assert_allclose(m.get()[1], 1.0)
    assert kv.get(["k"])["k"] == 5.0


def test_ssp_idle_clock_releases_backlog(mv):
    """A barrier with no new adds must still mature the queue."""
    mv.init()
    t = mv.ArrayTable(2, sync=True, staleness=2, name="ssp_idle",
                      updater_type="default")
    t.add(np.ones(2, np.float32))
    mv.barrier()   # clock+1 (held)
    mv.barrier()   # clock+2 (held)
    np.testing.assert_allclose(t.get(), 0.0)
    mv.barrier()   # idle clock: matures and applies
    np.testing.assert_allclose(t.get(), 1.0)


def test_ssp_requires_sync(mv):
    mv.init()
    with pytest.raises(ValueError, match="sync=True"):
        mv.ArrayTable(4, sync=False, staleness=1, name="ssp_bad")
    with pytest.raises(ValueError, match=">= 0"):
        mv.ArrayTable(4, sync=True, staleness=-1, name="ssp_bad2")


def test_ssp_discard_pending_drops_queue(mv):
    """Checkpoint-restore discards BOTH pending buffers and the matured
    SSP backlog (deltas of an abandoned timeline)."""
    mv.init()
    t = mv.ArrayTable(2, sync=True, staleness=1, name="ssp_disc",
                      updater_type="default")
    t.add(np.ones(2, np.float32))
    mv.barrier()                    # now queued in _stale_queue
    t.discard_pending()
    mv.barrier()
    np.testing.assert_allclose(t.get(), 0.0)


# ------------------------------------------------------ KV coalesce/batch

def test_kv_coalesce_buffers_until_barrier(mv):
    mv.init()
    kv = mv.KVTable(coalesce=True, name="kv_co", updater_type="default")
    kv.add({"a": 1.0})
    kv.add({"a": 2.0, "b": 1.0})
    assert kv.get(["a"])["a"] == 0.0       # buffered, not applied
    mv.barrier()
    g = kv.get(["a", "b"])
    assert g["a"] == 3.0 and g["b"] == 1.0


def test_kv_add_many_single_apply(mv):
    mv.init()
    kv = mv.KVTable(name="kv_many", updater_type="default")
    kv.add_many([{"x": 1.0}, {"x": 2.0, "y": 3.0}, {}])
    g = kv.get(["x", "y"])
    assert g["x"] == 3.0 and g["y"] == 3.0
    kv.add_many([])                        # empty batch: no-op
