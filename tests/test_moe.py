"""Mixture-of-Experts tests: routing exactness against a per-token
reference, load-balancing aux-loss behavior, transformer integration,
and an 8-device (dp, sp, tp, ep) expert-parallel training run."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from multiverso_tpu.models.moe import init_moe_params, moe_ffn, moe_shardings
from multiverso_tpu.models import (TransformerConfig, TransformerTrainer,
                                   init_params)
from multiverso_tpu.models.transformer import lm_loss, transformer_forward


def _moe_reference(params, x, top_k):
    """Per-token loop over experts: the semantics moe_ffn must match."""
    B, T, dim = x.shape
    E = params["router"].shape[1]
    logits = x @ params["router"]
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    out = np.zeros_like(x)
    for b in range(B):
        for t in range(T):
            idx = np.argsort(-probs[b, t])[:top_k]
            w = probs[b, t, idx]
            w = w / w.sum()
            for j, e in zip(range(top_k), idx):
                h = x[b, t] @ params["w1"][e]
                g = h / (1 + np.exp(-h))          # silu
                up = x[b, t] @ params["w3"][e]
                out[b, t] += w[j] * ((g * up) @ params["w2"][e])
    return out


@pytest.mark.parametrize("top_k", [1, 2])
def test_moe_matches_per_token_reference(top_k):
    rng = np.random.RandomState(0)
    params = init_moe_params(dim=16, hidden=32, num_experts=4, seed=1)
    x = rng.randn(2, 8, 16).astype(np.float32) * 0.5
    got, _ = moe_ffn(params, jnp.asarray(x), top_k=top_k)
    want = _moe_reference(params, x, top_k)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-4)


def test_moe_topk_equals_experts_is_full_softmax_mix():
    """top_k == E degenerates to a softmax-weighted mixture of all
    experts (no routing sparsity)."""
    rng = np.random.RandomState(1)
    E = 4
    params = init_moe_params(dim=16, hidden=32, num_experts=E, seed=2)
    x = jnp.asarray(rng.randn(1, 6, 16).astype(np.float32) * 0.5)
    got, _ = moe_ffn(params, x, top_k=E)
    probs = jax.nn.softmax(x @ params["router"], axis=-1)
    gate = jax.nn.silu(jnp.einsum("btd,edh->beth", x, params["w1"]))
    up = jnp.einsum("btd,edh->beth", x, params["w3"])
    eo = jnp.einsum("beth,ehd->betd", gate * up, params["w2"])
    want = jnp.einsum("betd,bte->btd", eo, probs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_moe_aux_loss_balanced_vs_skewed():
    """Uniform routing gives aux ≈ top_k (its minimum); routing every
    token to one expert drives aux toward E."""
    rng = np.random.RandomState(2)
    E, k = 4, 1
    params = init_moe_params(dim=16, hidden=32, num_experts=E, seed=3)
    x = jnp.asarray(rng.randn(2, 32, 16).astype(np.float32))

    balanced = dict(params, router=jnp.zeros((16, E)))
    _, aux_bal = moe_ffn(balanced, x, top_k=k)
    assert abs(float(aux_bal) - k) < 0.05, float(aux_bal)

    skew = np.zeros((16, E), np.float32)
    skew[:, 0] = 100.0   # every token -> expert 0 (positive x => +logit)
    _, aux_skew = moe_ffn(dict(params, router=jnp.asarray(skew)),
                          jnp.abs(x), top_k=k)
    assert float(aux_skew) > 0.9 * E, float(aux_skew)


_MOE_CFG = TransformerConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4,
                             hidden=64, max_seq=32, num_experts=4, top_k=2,
                             compute_dtype=jnp.float32)


def test_transformer_moe_forward_and_aux():
    params = jax.tree_util.tree_map(jnp.asarray,
                                    init_params(_MOE_CFG, seed=0))
    assert "moe" in params["layers"][0] and "w1" not in params["layers"][0]
    toks = jnp.asarray(np.random.RandomState(0).randint(
        64, size=(2, 16)).astype(np.int32))
    logits, aux = transformer_forward(params, toks, _MOE_CFG,
                                      return_aux=True)
    assert logits.shape == (2, 16, 64)
    # aux is the sum over layers; each layer's aux >= top_k (its minimum)
    assert float(aux) >= _MOE_CFG.n_layers * _MOE_CFG.top_k * 0.99
    loss_with_aux = lm_loss(params, toks, _MOE_CFG)
    assert np.isfinite(float(loss_with_aux))


def test_transformer_moe_trains_on_ep_mesh():
    """Full 4-axis parallelism: dp x sp x tp x ep on the 8-device mesh,
    experts sharded over ep, loss decreases through the updater step."""
    mesh = Mesh(np.asarray(jax.devices()).reshape(1, 2, 2, 2),
                ("dp", "sp", "tp", "ep"))
    shard = moe_shardings(mesh)
    assert shard["w1"].spec == jax.sharding.PartitionSpec("ep", None, None)
    tr = TransformerTrainer(_MOE_CFG, mesh, updater_type="sgd")
    # expert weights really live sharded over ep
    w1 = tr.params["layers"][0]["moe"]["w1"]
    assert w1.sharding.spec[0] == "ep"
    toks = np.random.RandomState(3).randint(
        64, size=(2, 32)).astype(np.int32)
    first = tr.train_step(toks)
    for _ in range(10):
        last = tr.train_step(toks)
    assert last < first, (first, last)


def test_moe_grad_flows_to_all_routed_experts():
    params = init_moe_params(dim=16, hidden=32, num_experts=4, seed=4)
    x = jnp.asarray(np.random.RandomState(5).randn(2, 16, 16)
                    .astype(np.float32))

    def loss(p):
        out, aux = moe_ffn(p, x, top_k=2)
        return jnp.sum(jnp.square(out)) + 0.01 * aux

    g = jax.grad(loss)(params)
    # router always gets gradient (via combine weights + aux loss)
    assert float(jnp.abs(g["router"]).max()) > 0
    # with 32 tokens and top-2 of 4 experts, every expert is hit w.h.p.
    per_expert = jnp.max(jnp.abs(g["w2"]), axis=(1, 2))
    assert float(per_expert.min()) > 0


# ------------------------------------------------------ capacity dispatch

def test_moe_capacity_matches_dense_with_ample_capacity():
    """With capacity_factor = E/top_k the buckets can never overflow, so
    the capacity schedule must reproduce the dense oracle exactly."""
    rng = np.random.RandomState(7)
    E, k = 4, 2
    params = init_moe_params(dim=16, hidden=32, num_experts=E, seed=6)
    x = jnp.asarray(rng.randn(2, 16, 16).astype(np.float32) * 0.5)
    want, aux_d = moe_ffn(params, x, top_k=k, dispatch="dense")
    got, aux_c = moe_ffn(params, x, top_k=k, dispatch="capacity",
                         capacity_factor=E / k)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)
    np.testing.assert_allclose(float(aux_c), float(aux_d), rtol=1e-6)


def test_moe_capacity_drops_overflow_tokens():
    """Routing everything to one expert with a tight capacity drops the
    overflow routes: late tokens lose that expert's contribution."""
    rng = np.random.RandomState(8)
    E = 4
    params = init_moe_params(dim=16, hidden=32, num_experts=E, seed=9)
    skew = np.zeros((16, E), np.float32)
    skew[:, 0] = 100.0
    params = dict(params, router=skew)
    x = jnp.abs(jnp.asarray(rng.randn(1, 64, 16).astype(np.float32)))
    out, _ = moe_ffn(params, x, top_k=1, dispatch="capacity",
                     capacity_factor=0.5)
    from multiverso_tpu.models.moe import moe_capacity

    C = moe_capacity(64, E, 1, 0.5)
    flat = np.asarray(out).reshape(64, 16)
    # first C tokens got expert 0; the rest overflowed -> exactly zero
    assert np.abs(flat[:C]).max() > 0
    np.testing.assert_allclose(flat[C:], 0.0)


def test_moe_capacity_grads_flow():
    params = init_moe_params(dim=16, hidden=32, num_experts=4, seed=10)
    x = jnp.asarray(np.random.RandomState(11).randn(2, 16, 16)
                    .astype(np.float32))

    def loss(p):
        out, aux = moe_ffn(p, x, top_k=2, dispatch="capacity")
        return jnp.sum(jnp.square(out)) + 0.01 * aux

    g = jax.grad(loss)(params)
    assert float(jnp.abs(g["router"]).max()) > 0
    assert float(jnp.max(jnp.abs(g["w2"]), axis=(1, 2)).min()) > 0


def test_transformer_moe_capacity_trains_on_ep_mesh():
    """Capacity dispatch through the full 4-axis sharded trainer (with
    scan+remat — the production MoE configuration)."""
    from dataclasses import replace

    cfg = replace(_MOE_CFG, moe_dispatch="capacity", capacity_factor=2.0,
                  scan_layers=True, remat=True)
    mesh = Mesh(np.asarray(jax.devices()).reshape(1, 2, 2, 2),
                ("dp", "sp", "tp", "ep"))
    tr = TransformerTrainer(cfg, mesh, updater_type="sgd")
    toks = np.random.RandomState(12).randint(
        64, size=(2, 32)).astype(np.int32)
    first = tr.train_step(toks)
    for _ in range(10):
        last = tr.train_step(toks)
    assert last < first, (first, last)
