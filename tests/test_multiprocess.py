"""Multi-process (2 host) integration test — the TPU-native analog of the
reference's mpirun scenarios (SURVEY.md §4, ``Test/main.cpp``).

Spawns two real OS processes that join one ``jax.distributed`` job on
CPU; each contributes 2 virtual devices to a 4-device global mesh.  The
worker body (``mp_worker.py``) exercises registration, barriers,
collective table Add/Get, BSP flush, rank-0 checkpointing, and the
jax_ext delta-sync — all the ``process_count() > 1`` paths that are dead
code under a single controller.
"""

import os
import socket
import subprocess
import sys

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_distributed_runtime(tmp_path):
    port = _free_port()
    nprocs = 2
    env = dict(os.environ)
    # The workers set their own JAX_PLATFORMS/XLA_FLAGS before importing
    # jax; scrub this (conftest-polluted) process's values out.
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_HERE, "mp_worker.py"),
             str(port), str(i), str(nprocs), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for i in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"worker {i} failed (rc={p.returncode}):\n{out[-4000:]}")
        assert f"WORKER_OK {i}" in out, out[-2000:]
