"""Multi-process (2 host) integration test — the TPU-native analog of the
reference's mpirun scenarios (SURVEY.md §4, ``Test/main.cpp``).

Spawns two real OS processes that join one ``jax.distributed`` job on
CPU; each contributes 2 virtual devices to a 4-device global mesh.  The
worker body (``mp_worker.py``) exercises registration, barriers,
collective table Add/Get, BSP flush, rank-0 checkpointing, and the
jax_ext delta-sync — all the ``process_count() > 1`` paths that are dead
code under a single controller.
"""

import os
import socket
import subprocess
import sys
import time

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))

# Signatures of losing the _free_port TOCTOU race (the socket closes
# before the coordinator binds it — another test/process can steal the
# port in between under parallel CI): retry the whole bring-up on a
# fresh port instead of failing the test.
_BIND_RACE_MARKERS = ("Address already in use", "Failed to bind",
                      "bind failed", "EADDRINUSE")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# Environment capability gate.  Some jaxlib builds cannot run collectives
# across OS processes on the CPU backend at all — jax.distributed
# registration succeeds, then the FIRST cross-process collective dies with
# "Multiprocess computations aren't implemented on the CPU backend".
# That is an environment limit (it needs a jaxlib whose CPU client speaks
# cross-host collectives), not an in-repo bug: probe it ONCE with a
# minimal 2-process sync job and skip the suite with an explicit reason
# instead of failing tier-1 on an impossible prerequisite.
# ---------------------------------------------------------------------------
_MP_CAP: dict = {}


def _require_mp_collectives():
    if "ok" not in _MP_CAP:
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        probe = os.path.join(_HERE, "mp_probe.py")
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, probe, str(port), str(i), "2"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env)
            for i in range(2)
        ]
        outs = []
        try:
            for p in procs:
                outs.append(p.communicate(timeout=120)[0])
        except subprocess.TimeoutExpired:
            outs.append("(probe timed out)")
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        ok = all(p.returncode == 0 for p in procs) and all(
            f"MP_PROBE_OK {i}" in outs[i] for i in range(len(outs)))
        blob = "\n".join(outs)
        if "Multiprocess computations aren't implemented" in blob:
            reason = ("env capability: this jaxlib's CPU backend cannot "
                      "run cross-process collectives ('Multiprocess "
                      "computations aren't implemented on the CPU "
                      "backend') — multi-process tests need a jaxlib "
                      "with CPU cross-host collective support")
        else:
            reason = ("env capability: 2-process jax.distributed probe "
                      "failed:\n" + blob[-800:])
        _MP_CAP["ok"] = ok
        _MP_CAP["reason"] = reason
    if not _MP_CAP["ok"]:
        pytest.skip(_MP_CAP["reason"])


def _deadline(total_s: float = 300.0):
    """Shared wait budget: each communicate() gets what REMAINS of the
    job's window, so one slow worker cannot stack N full timeouts."""
    t0 = time.monotonic()

    def left() -> float:
        return max(10.0, total_s - (time.monotonic() - t0))

    return left


def _spawn_workers(tmp_path, nprocs, port):
    env = dict(os.environ)
    # The workers set their own JAX_PLATFORMS/XLA_FLAGS before importing
    # jax; scrub this (conftest-polluted) process's values out.
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_HERE, "mp_worker.py"),
             str(port), str(i), str(nprocs), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for i in range(nprocs)
    ]
    left = _deadline()
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=left())
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def _run_workers(tmp_path, nprocs, attempts: int = 3):
    for attempt in range(attempts):
        procs, outs = _spawn_workers(tmp_path, nprocs, _free_port())
        failed = [i for i, p in enumerate(procs) if p.returncode != 0]
        raced = failed and all(
            any(m in outs[i] for m in _BIND_RACE_MARKERS) for i in failed)
        if raced and attempt < attempts - 1:
            continue  # fresh port, full retry of the distributed job
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, (
                f"worker {i} failed (rc={p.returncode}):\n{out[-4000:]}")
            assert f"WORKER_OK {i}" in out, out[-2000:]
        return


@pytest.fixture(scope="module")
def two_proc_scratch(tmp_path_factory):
    """Run the n=2 worker job ONCE; its scratch (with mp.ckpt) serves both
    the runtime test and the cross-process-count restore test."""
    _require_mp_collectives()
    scratch = tmp_path_factory.mktemp("mp2")
    _run_workers(scratch, 2)
    return scratch


def test_multi_process_distributed_runtime_n2(two_proc_scratch):
    pass  # the fixture already asserted WORKER_OK for both ranks


def test_multi_process_distributed_runtime_n4(tmp_path):
    _require_mp_collectives()
    _run_workers(tmp_path, 4)


def test_restore_multiprocess_checkpoint_into_single_process(
        two_proc_scratch, mv):
    """A checkpoint saved by the n=2 job restores into an n=1 session:
    the snapshot is process-count-independent (global table state, not
    per-shard files — unlike the reference's per-server dump model)."""
    import numpy as np

    path = os.path.join(str(two_proc_scratch), "mp.ckpt")
    assert os.path.exists(path)

    import multiverso_tpu as m
    from multiverso_tpu import checkpoint

    mv.init()
    total = 3.0                              # sum of (r+1) over 2 ranks
    t = m.ArrayTable(10, name="mp_a")
    mat = m.MatrixTable(8, 4, name="mp_m")
    kv = m.KVTable(value_shape=(2,), name="mp_kv")
    sp = m.SparseMatrixTable(8, 4, name="mp_sp")
    ts = m.ArrayTable(4, name="mp_sync", sync=True)
    tq = m.ArrayTable(64, name="mp_q")
    extra = checkpoint.restore(path)
    assert extra == {"step": 7}
    np.testing.assert_allclose(t.get(), total)
    np.testing.assert_allclose(tq.get(), total)   # 1-bit adds, exact here
    np.testing.assert_allclose(ts.get(), total)
    got = mat.get()
    for r in range(2):
        np.testing.assert_allclose(got[r], r + 1.0)
        np.testing.assert_allclose(got[4 + r], r + 1.0)
    np.testing.assert_allclose(kv.get(["shared"])["shared"], 2.0)
    np.testing.assert_allclose(sp.get_rows(np.array([0]))[0], 1.0)
