"""Test harness: every distributed test runs on a virtual 8-device CPU mesh.

This is the proper version of the reference's single-process degenerate
mode (SURVEY.md §4): instead of one process holding both roles, we get a
real 8-way mesh on one host via XLA's forced host platform device count.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's sitecustomize may import jax before this file runs, in
# which case the env vars above were read too late — force via jax.config.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    # Tier-1 runs `-m 'not slow'` (ROADMAP.md): heavyweight sanitizer
    # sweeps opt out of the runtime budget with this marker, everything
    # else (mvlint, make analyze gate, TSan unit run) stays tier-1.
    config.addinivalue_line(
        "markers",
        "slow: heavyweight sweep (e.g. the ASan/UBSan multi-process "
        "scenario rebuild+run) excluded from tier-1 via -m 'not slow'")


@pytest.fixture()
def mv():
    """Fresh multiverso_tpu runtime per test."""
    import multiverso_tpu as mv

    mv.config.reset()
    if mv.initialized():
        mv.shutdown()
    yield mv
    if mv.initialized():
        mv.shutdown()
    mv.config.reset()


def dense_attention_ref(q, k, v, causal=True):
    """Shared dense attention reference for kernel/ring tests."""
    import jax
    import jax.numpy as jnp

    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhtd,bhsd->bhts", q, k) * scale
    T = q.shape[2]
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    return jnp.einsum("bhts,bhsd->bhtd", jax.nn.softmax(s, -1), v)
