"""Test harness: every distributed test runs on a virtual 8-device CPU mesh.

This is the proper version of the reference's single-process degenerate
mode (SURVEY.md §4): instead of one process holding both roles, we get a
real 8-way mesh on one host via XLA's forced host platform device count.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's sitecustomize may import jax before this file runs, in
# which case the env vars above were read too late — force via jax.config.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def mv():
    """Fresh multiverso_tpu runtime per test."""
    import multiverso_tpu as mv

    mv.config.reset()
    if mv.initialized():
        mv.shutdown()
    yield mv
    if mv.initialized():
        mv.shutdown()
    mv.config.reset()
