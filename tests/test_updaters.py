"""Updater math tests — dense and row-sparse paths, all five updaters.

Models the reference's updater unit tests; the math is checked against
closed-form numpy (reference src/updater/*.cpp semantics, SURVEY.md §2.16).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from multiverso_tpu.updaters import AddOption, get_updater, updater_names


OPT = AddOption(learning_rate=0.1, momentum=0.9, rho=0.5, eps=1e-8)


def test_registry_names():
    names = updater_names()
    for n in ("default", "add", "sgd", "adagrad", "momentum",
              "smooth_gradient"):
        assert n in names
    with pytest.raises(ValueError):
        get_updater("nope")


def _dense(name, w, d, steps=1):
    u = get_updater(name)
    s = u.init_state(w.shape, w.dtype)
    w = jnp.asarray(w)
    for _ in range(steps):
        w, s = u.apply_dense(w, s, jnp.asarray(d), OPT)
    return np.asarray(w), [np.asarray(x) for x in s]


def test_default_add():
    w = np.ones(4, np.float32)
    d = np.full(4, 2.0, np.float32)
    out, _ = _dense("default", w, d)
    np.testing.assert_allclose(out, 3.0)


def test_sgd():
    w = np.ones(4, np.float32)
    g = np.full(4, 2.0, np.float32)
    out, _ = _dense("sgd", w, g)
    np.testing.assert_allclose(out, 1.0 - 0.1 * 2.0, rtol=1e-6)


def test_adagrad_two_steps():
    w = np.zeros(3, np.float32)
    g = np.ones(3, np.float32)
    out, (h,) = _dense("adagrad", w, g, steps=2)
    # step1: h=1, w=-0.1/1 ; step2: h=2, w-=0.1/sqrt(2)
    exp = -0.1 - 0.1 / np.sqrt(2.0)
    np.testing.assert_allclose(out, exp, rtol=1e-5)
    np.testing.assert_allclose(h, 2.0, rtol=1e-6)


def test_momentum_two_steps():
    w = np.zeros(3, np.float32)
    g = np.ones(3, np.float32)
    out, (v,) = _dense("momentum", w, g, steps=2)
    # v1=0.1, w1=-0.1; v2=0.9*0.1+0.1=0.19, w2=-0.29
    np.testing.assert_allclose(v, 0.19, rtol=1e-6)
    np.testing.assert_allclose(out, -0.29, rtol=1e-6)


def test_smooth_gradient_two_steps():
    w = np.zeros(3, np.float32)
    g = np.ones(3, np.float32)
    out, (s,) = _dense("smooth_gradient", w, g, steps=2)
    # s1=0.5, w1=-0.05; s2=0.5*0.5+0.5=0.75, w2=-0.05-0.075=-0.125
    np.testing.assert_allclose(s, 0.75, rtol=1e-6)
    np.testing.assert_allclose(out, -0.125, rtol=1e-6)


@pytest.mark.parametrize("name", ["sgd", "adagrad", "momentum",
                                  "smooth_gradient", "default"])
def test_rows_matches_dense_on_unique_rows(name):
    """Scatter path == dense path when every row is touched exactly once."""
    rng = np.random.RandomState(0)
    w0 = rng.randn(6, 4).astype(np.float32)
    g = rng.randn(6, 4).astype(np.float32)

    u = get_updater(name)
    s0 = u.init_state(w0.shape, jnp.float32)
    wd, sd = u.apply_dense(jnp.asarray(w0), s0, jnp.asarray(g), OPT)

    rows = jnp.arange(6, dtype=jnp.int32)
    ws, ss = u.apply_rows(jnp.asarray(w0), s0, rows, jnp.asarray(g), OPT)

    np.testing.assert_allclose(np.asarray(wd), np.asarray(ws), rtol=1e-5)
    for a, b in zip(sd, ss):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


@pytest.mark.parametrize("name", ["sgd", "adagrad", "momentum",
                                  "smooth_gradient", "default"])
def test_rows_padding_dropped(name):
    """Padding entries (OOB row or mask=False) must not touch any row."""
    w0 = np.ones((4, 2), np.float32)
    u = get_updater(name)
    s0 = u.init_state(w0.shape, jnp.float32)

    rows = jnp.asarray([1, 4, 0], dtype=jnp.int32)   # 4 = OOB pad
    delta = jnp.ones((3, 2), dtype=jnp.float32) * 5.0
    mask = jnp.asarray([True, False, False])          # entry 2 masked off

    w1, s1 = u.apply_rows(jnp.asarray(w0), s0, rows, delta, OPT, mask=mask)
    w1 = np.asarray(w1)
    # row 0 masked off → unchanged; rows 2,3 untouched
    np.testing.assert_allclose(w1[0], w0[0])
    np.testing.assert_allclose(w1[2:], w0[2:])
    # row 1 changed
    assert not np.allclose(w1[1], w0[1])
    for st in s1:
        st = np.asarray(st)
        np.testing.assert_allclose(st[0], 0.0)
        np.testing.assert_allclose(st[2:], 0.0)
