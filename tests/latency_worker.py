"""Fleet holder for the latency-attribution tests (not a pytest module).

Run as ``python latency_worker.py <machine_file> <rank>``: joins a
2-rank native epoll fleet (heartbeat armed — the lease echo is one of
the clock-offset channels), does cross-rank table traffic so every
stage histogram and the per-peer offset estimator have data, prints
``LAT_READY`` — then serves stdin commands until ``quit``:

- ``report``  — print ``LAT_REPORT <one-line JSON>`` (this rank's
  ``MV_OpsReport("latency")``) and ``LAT_OFFSET <json|null>`` (the
  rank-0 clock-offset estimate).
- ``fault``   — arm a 100%% 25 ms ``apply_delay`` fault on THIS rank's
  server apply path, print ``LAT_FAULT_ARMED``.
- ``traffic`` — 20 more cross-rank gets (their replies land in this
  rank's stage histograms), print ``LAT_TRAFFIC_DONE``.
- ``quit``    — clean shutdown, print ``LAT_OK <rank>``.

tests/test_latency.py drives the command protocol; the seeded-fault
scenario arms ``fault`` on rank 0 and ``traffic`` on rank 1, then
asserts latdoctor names ``apply`` (not the wire) as the dominant p99
stage of rank 1's breakdown.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from multiverso_tpu import native as nat  # noqa: E402

SIZE = 64


def main() -> int:
    mf, rank = sys.argv[1], int(sys.argv[2])
    rt = nat.NativeRuntime(args=[
        f"-machine_file={mf}", f"-rank={rank}", "-log_level=error",
        "-trace=true",
        "-heartbeat_ms=100", "-heartbeat_timeout_ms=2000",
        "-rpc_timeout_ms=10000", "-barrier_timeout_ms=30000",
        "-connect_retry_ms=2000"])
    assert rt.net_engine() == "epoll", rt.net_engine()
    h = rt.new_array_table(SIZE)
    rt.barrier()
    for _ in range(8):
        rt.array_add(h, np.ones(SIZE, np.float32))
        rt.array_get(h, SIZE)
    rt.barrier()
    print("LAT_READY", flush=True)

    for line in sys.stdin:
        cmd = line.strip()
        if cmd == "report":
            print("LAT_REPORT " + rt.ops_report("latency"), flush=True)
            print("LAT_OFFSET " + json.dumps(rt.clock_offset(1 - rank)),
                  flush=True)
        elif cmd == "fault":
            rt.set_fault("delay_ms", 25)
            rt.set_fault("apply_delay", 1.0)
            print("LAT_FAULT_ARMED", flush=True)
        elif cmd == "traffic":
            for _ in range(20):
                rt.array_get(h, SIZE)
            print("LAT_TRAFFIC_DONE", flush=True)
        elif cmd == "quit":
            break
    rt.clear_faults()
    rt.barrier()
    rt.shutdown()
    print(f"LAT_OK {rank}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
