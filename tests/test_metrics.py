"""Tier-1 gate for the observability layer (docs/observability.md):
metrics registry math, label cardinality, Prometheus rendering, the
dashboard.monitor shim, Chrome-trace export, the one-call native
bridge (MV_DumpMonitors), and span-id propagation worker -> server —
in the in-process zoo and across a real 2-process wire session
(tools/metrics_demo.py).
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def registry():
    """Fresh metrics + tracing state on both sides of a test."""
    from multiverso_tpu import dashboard, metrics, tracing

    dashboard.reset()
    metrics.reset()
    tracing.disable()
    tracing.clear()
    yield metrics
    dashboard.reset()
    metrics.reset()
    tracing.disable()
    tracing.clear()


# ------------------------------------------------------------ histogram math

def test_histogram_bucket_and_quantile_math(registry):
    """Known distribution, unit-wide buckets: interpolated quantiles are
    exact to within one bucket, min/max clamp, count/sum/mean hold."""
    h = registry.histogram("t.uniform",
                           bounds=[float(i) for i in range(1, 101)])
    for v in range(1, 101):
        h.observe(float(v))
    assert h.count == 100
    assert h.sum == pytest.approx(5050.0)
    assert h.mean == pytest.approx(50.5)
    assert h.max == 100.0
    for q, want in ((0.50, 50.0), (0.95, 95.0), (0.99, 99.0)):
        assert h.quantile(q) == pytest.approx(want, abs=1.0), q
    assert h.quantile(0.0) <= 1.0
    assert h.quantile(1.0) == 100.0


def test_histogram_overflow_bucket_and_skew(registry):
    """Values beyond the last bound land in +inf and quantiles clamp to
    the observed max instead of inventing an upper bound."""
    h = registry.histogram("t.skew", bounds=[1.0, 2.0, 4.0])
    for _ in range(99):
        h.observe(0.5)
    h.observe(1000.0)                       # overflow bucket
    assert h.quantile(0.5) <= 1.0
    # The tail quantile lands in the +inf bucket, whose upper edge is
    # the observed max (interpolated, clamped — never an invented bound).
    assert 4.0 < h.quantile(0.999) <= 1000.0
    assert h.quantile(1.0) == pytest.approx(1000.0)


def test_histogram_rejects_unsorted_bounds(registry):
    with pytest.raises(ValueError):
        registry.histogram("t.bad", bounds=[2.0, 1.0])


def test_counter_and_gauge(registry):
    c = registry.counter("t.count")
    c.inc()
    c.inc(2.5)
    assert c.value == pytest.approx(3.5)
    g = registry.gauge("t.gauge")
    g.set(7)
    g.dec(2)
    assert g.value == pytest.approx(5.0)
    snap = registry.snapshot()
    assert snap["t.count"] == {"type": "counter", "value": 3.5}
    assert snap["t.gauge"]["value"] == 5.0


# ------------------------------------------------------------------- labels

def test_labels_mint_distinct_series(registry):
    a = registry.counter("t.lbl", labels={"table": "a"})
    b = registry.counter("t.lbl", labels={"table": "b"})
    assert a is not b
    a.inc(1)
    b.inc(2)
    # Same labels -> same series, key order irrelevant.
    assert registry.counter("t.lbl", {"table": "a"}) is a
    snap = registry.snapshot()
    assert snap['t.lbl{table="a"}']["value"] == 1
    assert snap['t.lbl{table="b"}']["value"] == 2


def test_label_cardinality_cap_collapses_to_overflow(registry):
    for i in range(registry.MAX_SERIES_PER_NAME + 50):
        registry.counter("t.card", labels={"k": str(i)}).inc()
    snap = registry.snapshot()
    series = [k for k in snap if k.startswith("t.card")]
    # Capped: the explosion collapsed into one overflow series.
    assert len(series) <= registry.MAX_SERIES_PER_NAME + 1
    assert snap['t.card{overflow="true"}']["value"] >= 50


def test_type_collision_raises(registry):
    registry.counter("t.kind")
    with pytest.raises(TypeError):
        registry.gauge("t.kind")


# -------------------------------------------------------------- prometheus

def _parse_prom(text):
    """Tiny exposition parser: {series_line_name: float} + type map."""
    values, types = {}, {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE"):
            _, _, name, kind = line.split()
            types[name] = kind
            continue
        name, value = line.rsplit(" ", 1)
        values[name] = float(value)
    return values, types


def test_prometheus_rendering_round_trip(registry):
    registry.counter("req_total", {"table": "emb"}).inc(5)
    registry.gauge("depth").set(2)
    h = registry.histogram("lat", bounds=[0.1, 1.0])
    for v in (0.05, 0.5, 2.0):
        h.observe(v)
    values, types = _parse_prom(registry.render_prometheus())
    assert types == {"req_total": "counter", "depth": "gauge",
                     "lat": "histogram"}
    assert values['req_total{table="emb"}'] == 5.0
    assert values["depth"] == 2.0
    # Cumulative buckets + sum/count.
    assert values['lat_bucket{le="0.1"}'] == 1.0
    assert values['lat_bucket{le="1.0"}'] == 2.0
    assert values['lat_bucket{le="+Inf"}'] == 3.0
    assert values["lat_count"] == 3.0
    assert values["lat_sum"] == pytest.approx(2.55)


def test_prometheus_name_sanitization(registry):
    registry.histogram("ArrayTable::Get")         # valid (colons legal)
    registry.counter("io.bytes", {"dir": "read"})  # dots -> underscores
    text = registry.render_prometheus()
    assert "ArrayTable::Get_count" in text
    assert 'io_bytes{dir="read"}' in text


# ------------------------------------------------------- dashboard.monitor shim

def test_dashboard_monitor_shim_parity(registry):
    """The legacy monitor()/report() surface holds (count/total_s/max_s)
    AND every monitor shows up in metrics.snapshot() with percentiles."""
    from multiverso_tpu import dashboard

    with dashboard.monitor("Shim::Op"):
        pass
    with dashboard.monitor("Shim::Op"):
        pass
    mons = dashboard.report(log=False)
    m = mons["Shim::Op"]
    assert m.count == 2
    assert m.total_s >= 0.0
    assert m.max_s >= 0.0
    assert m.mean_ms >= 0.0
    assert m.p50_ms <= m.p99_ms <= m.max_s * 1e3 + 1e-6
    assert "p50" in str(m) and "p99" in str(m)
    snap = registry.snapshot()
    assert snap["Shim::Op"]["count"] == 2
    assert {"p50", "p95", "p99"} <= set(snap["Shim::Op"])
    # reset() drops the registry series too (no ghost accumulation).
    dashboard.reset()
    assert "Shim::Op" not in registry.snapshot()


def test_table_ops_report_percentiles(registry, mv):
    """Acceptance: every table op exposes p50/p95/p99 via snapshot()."""
    import numpy as np

    mv.init()
    t = mv.ArrayTable(16, name="t_metrics")
    t.add(np.ones(16, np.float32), sync=True)
    t.get()
    snap = registry.snapshot()
    for op in ("ArrayTable::Add", "ArrayTable::Get"):
        assert op in snap, sorted(snap)
        assert {"p50", "p95", "p99"} <= set(snap[op])
        assert snap[op]["count"] >= 1


def test_fault_and_io_counters_land_in_snapshot(registry, tmp_path):
    from multiverso_tpu import fault
    from multiverso_tpu.io.stream import LocalStream

    fault.reset()
    fault.configure(sites={"io.write": {"times": 1}})
    with pytest.raises(fault.FaultError):
        fault.inject("io.write")
    p = str(tmp_path / "f.bin")
    with LocalStream(p, "wb") as s:
        s.write(b"x" * 100)
    with LocalStream(p, "rb") as s:
        s.read()
    snap = registry.snapshot()
    assert snap["fault.io.write"]["value"] == 1
    assert snap['io.bytes{dir="write"}']["value"] >= 100
    assert snap['io.bytes{dir="read"}']["value"] >= 100
    fault.reset()
    assert "fault.io.write" not in registry.snapshot()


# ------------------------------------------------------------ flush thread

def test_flush_thread_writes_prometheus_file(registry, tmp_path):
    from multiverso_tpu import metrics

    registry.counter("flush.me").inc(3)
    path = str(tmp_path / "metrics.prom")
    metrics.start_flush(10, path=path)
    try:
        import time

        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not os.path.exists(path):
            time.sleep(0.02)
    finally:
        metrics.stop_flush()
    assert os.path.exists(path)
    assert "flush_me 3.0" in open(path).read()


def test_flush_retarget_joins_previous_flusher(registry, tmp_path):
    """start_flush must JOIN the previous flusher before starting a new
    one: two live flushers interleave atomic-replace writes of the same
    metrics_rank<r>.prom (the PR 3 teardown race, retarget flavor)."""
    import threading
    import time

    from multiverso_tpu import metrics

    registry.counter("retarget.me").inc()
    slow_gate = threading.Event()
    orig = metrics._Flusher.flush_once

    def slow(self):
        slow_gate.set()
        time.sleep(0.25)
        orig(self)

    metrics._Flusher.flush_once = slow
    try:
        metrics.start_flush(5, path=str(tmp_path / "a.prom"))
        first = metrics._FLUSHER
        assert slow_gate.wait(5.0)           # first flusher is MID-FLUSH
        metrics.start_flush(5, path=str(tmp_path / "b.prom"))
        # The old thread must be dead BEFORE the retarget returned.
        assert not first.is_alive()
        assert metrics._FLUSHER is not first
    finally:
        metrics._Flusher.flush_once = orig
        metrics.stop_flush()


def test_stop_flush_final_flush_never_interleaves(registry, tmp_path):
    """stop_flush joins the thread BEFORE running the final flush on the
    caller — the shutdown-time file write can never overlap a flusher
    mid-write (the `-metrics_flush_ms` teardown race)."""
    import threading
    import time

    from multiverso_tpu import metrics

    registry.counter("shutdown.me").inc(2)
    path = str(tmp_path / "final.prom")
    windows = []
    orig = metrics._Flusher.flush_once

    def traced(self):
        t0 = time.monotonic()
        time.sleep(0.2)                      # hold the write window open
        orig(self)
        windows.append((t0, time.monotonic(), threading.get_ident()))

    metrics._Flusher.flush_once = traced
    try:
        metrics.start_flush(5, path=path)
        deadline = time.monotonic() + 5.0
        while not windows and time.monotonic() < deadline:
            time.sleep(0.01)
        metrics.stop_flush()                 # join THEN final flush
    finally:
        metrics._Flusher.flush_once = orig
    assert windows, "flusher never ran"
    # The final flush ran on the caller thread, and no two flush windows
    # overlap — the interleaving the fix forbids.
    assert windows[-1][2] == threading.get_ident()
    ordered = sorted(windows)
    for (_, end, _), (start, _, _) in zip(ordered, ordered[1:]):
        assert start >= end, windows
    assert "shutdown_me 2.0" in open(path).read()


# ------------------------------------------------------------- chrome trace

def test_chrome_trace_schema_and_merge(registry, tmp_path):
    from multiverso_tpu import tracing

    tracing.enable(rank=1)
    with tracing.span("Test::outer", detail="x") as tid:
        with tracing.span("Test::inner"):
            pass
    assert tid != 0 and (tid >> 40) == 2        # rank salt
    evts = tracing.events()
    assert {e.name for e in evts} == {"Test::outer", "Test::inner"}
    assert len({e.trace_id for e in evts}) == 1  # nested spans share ids

    p1 = str(tmp_path / "trace_rank1.json")
    tracing.save(p1)
    doc = json.load(open(p1))
    assert doc["displayTimeUnit"] == "ms"
    for e in doc["traceEvents"]:
        assert {"name", "ph", "ts", "dur", "pid", "tid", "args"} <= set(e)
        assert e["ph"] == "X"
        assert e["pid"] == 1
        assert e["args"]["trace_id"].startswith("0x")
    # A second rank's file merges onto one timeline.
    other = {"traceEvents": [{"name": "Remote::op", "ph": "X", "ts": 1,
                              "dur": 2, "pid": 0, "tid": 7, "args": {}}],
             "displayTimeUnit": "ms"}
    with open(tmp_path / "trace_rank0.json", "w") as f:
        json.dump(other, f)
    merged = tracing.merge_dir(str(tmp_path))
    mdoc = json.load(open(merged))
    names = [e["name"] for e in mdoc["traceEvents"]]
    assert "Remote::op" in names and "Test::outer" in names
    # Re-merging skips the previous merge file (no event doubling).
    n = len(json.load(open(tracing.merge_dir(str(tmp_path))))["traceEvents"])
    assert n == len(names)


def test_span_disabled_is_free(registry):
    from multiverso_tpu import tracing

    with tracing.span("Never::recorded") as tid:
        assert tid == 0
    assert tracing.events() == []


# ------------------------------------------------------------- native plane

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")


@pytest.fixture(scope="module")
def native_rt():
    from multiverso_tpu import native as nat

    nat.ensure_built()
    rt = nat.NativeRuntime(args=["-updater_type=default",
                                 "-log_level=error"])
    yield rt
    rt.shutdown()


@needs_gxx
def test_native_bridge_one_call_enumeration(registry, native_rt):
    """MV_DumpMonitors: every native Dashboard monitor arrives in one
    call, with bucket detail enough for host-side percentiles."""
    import numpy as np

    h = native_rt.new_array_table(32)
    native_rt.array_add(h, np.ones(32, np.float32))
    native_rt.array_get(h, 32)
    dump = native_rt.dump_monitors()
    for op in ("ArrayWorker::Get", "ArrayWorker::Add",
               "ArrayServer::ProcessGet", "ArrayServer::ProcessAdd"):
        assert op in dump, sorted(dump)
        count, total, vmax, buckets = dump[op][:4]
        assert count >= 1 and total >= 0.0 and vmax >= 0.0
        assert len(buckets) == 28 and sum(buckets) == count
        # Trailing per-bucket exemplar field (docs/observability.md):
        # present in current dumps, all-zero here (tracing off).
        assert len(dump[op]) == 5 and len(dump[op][4]) == 28
    n = registry.bridge_native(native_rt)
    assert n >= len(dump) - 1            # dead_peers gauge not counted
    snap = registry.snapshot()
    assert {"p50", "p95", "p99"} <= set(snap["native.ArrayWorker::Get"])
    assert snap["native.dead_peers"]["value"] == 0.0
    # Legacy name-by-name query agrees with the enumeration.
    assert native_rt.query_monitor("ArrayWorker::Get") == \
        dump["ArrayWorker::Get"][0]


@needs_gxx
def test_native_span_propagation_in_process_zoo(registry, native_rt):
    """Worker op and server-side apply share one trace id through the
    in-process zoo (message-header propagation, the same mechanism the
    wire uses)."""
    import numpy as np

    from multiverso_tpu import tracing

    native_rt.clear_spans()
    native_rt.set_trace_enabled(True)
    try:
        h = native_rt.new_matrix_table(8, 4)
        native_rt.matrix_add_rows(h, [1, 3], np.ones((2, 4), np.float32))
        native_rt.matrix_get_rows(h, [1, 3], 4)
    finally:
        native_rt.set_trace_enabled(False)
    evts = tracing.parse_native_spans(native_rt.dump_spans())
    by_name = {}
    for e in evts:
        by_name.setdefault(e.name, []).append(e)
    assert "MatrixWorker::GetRows" in by_name, sorted(by_name)
    assert "MatrixServer::ProcessGet" in by_name, sorted(by_name)
    get_ids = {e.trace_id for e in by_name["MatrixWorker::GetRows"]}
    assert any(e.trace_id in get_ids
               for e in by_name["MatrixServer::ProcessGet"])
    add_ids = {e.trace_id for e in by_name["MatrixWorker::AddRows"]}
    assert any(e.trace_id in add_ids
               for e in by_name["MatrixServer::ProcessAdd"])
    assert get_ids.isdisjoint(add_ids)   # per-op ids, not one blob
    native_rt.clear_spans()
    assert native_rt.dump_spans() == ""


@needs_gxx
def test_native_pinned_trace_id_nests_under_host_span(registry, native_rt):
    """NativeRuntime.set_trace_id stitches native spans under a Python
    tracing span's id (the cross-plane correlation path)."""
    import numpy as np

    from multiverso_tpu import tracing

    tracing.enable(rank=0)
    native_rt.clear_spans()
    native_rt.set_trace_enabled(True)
    try:
        h = native_rt.new_array_table(8)
        with tracing.span("host.step") as tid:
            native_rt.set_trace_id(tid)
            try:
                native_rt.array_get(h, 8)
            finally:
                native_rt.set_trace_id(0)
    finally:
        native_rt.set_trace_enabled(False)
    evts = tracing.parse_native_spans(native_rt.dump_spans())
    assert any(e.name == "ArrayWorker::Get" and e.trace_id == tid
               for e in evts), evts
    native_rt.clear_spans()


@needs_gxx
def test_metrics_demo_two_process_trace(tmp_path):
    """The acceptance smoke end-to-end: a 2-process wire session emits a
    merged Chrome trace where a worker Get and the remote server apply
    share a trace id (tools/metrics_demo.py, `make metrics-demo`)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "metrics_demo.py")],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=REPO)
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-2000:]
    assert "METRICS_DEMO_OK" in out.stdout
