"""Smooth-gradient updater — reference ``updater/smooth_gradient_updater.h``
(SURVEY.md §2.16): exponential smoothing of incoming gradients before the
descent step."""

from __future__ import annotations

from typing import Optional

import jax

from .base import AddOption, Updater, effective_rows, masked, register_updater


@register_updater
class SmoothGradientUpdater(Updater):
    """s = rho*s + (1-rho)*g ; w -= lr*s."""

    name = "smooth_gradient"
    num_slots = 1
    linear = False  # duplicate rows must be segment-summed before apply

    def apply_dense(self, w, state, delta, opt: AddOption):
        (s,) = state
        s = opt.rho * s + (1.0 - opt.rho) * delta
        return w - opt.learning_rate * s, (s,)

    def apply_rows(self, w, state, rows, delta, opt: AddOption,
                   mask: Optional[jax.Array] = None):
        (s,) = state
        rows = effective_rows(rows, mask, w.shape[0])
        d = masked(delta, mask)
        s_rows = opt.rho * s[rows] + (1.0 - opt.rho) * d
        s = s.at[rows].set(s_rows, mode="drop")
        w = w.at[rows].add(-opt.learning_rate * s_rows, mode="drop")
        return w, (s,)
