"""Updater base + registry + the default (plain add) updater.

Reference: ``include/multiverso/updater/updater.h`` — base ``Update``/
``Access`` virtuals and the ``GetUpdater`` factory switch (SURVEY.md §2.16).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp

__all__ = ["AddOption", "GetOption", "Updater", "register_updater",
           "get_updater", "updater_names", "aggregate_rows",
           "scatter_apply"]


@dataclass(frozen=True)
class AddOption:
    """Per-Add hyper-parameters (reference ``AddOption``; SURVEY.md §2.10).

    The reference packs these into the message header; here they are static
    jit arguments (python floats hash into the compilation cache).
    """

    learning_rate: float = 0.1
    momentum: float = 0.9
    rho: float = 0.9          # smoothing coefficient (smooth_gradient)
    eps: float = 1e-8         # adagrad denominator floor
    worker_id: int = -1       # carried for parity; unused by math


@dataclass(frozen=True)
class GetOption:
    """Per-Get options (reference ``GetOption``); reserved for parity."""

    worker_id: int = -1


State = Tuple[jax.Array, ...]


class Updater:
    """Pure-functional updater. Subclasses override the three hooks.

    All hooks are shape-polymorphic and jittable; tables call them inside
    their compiled push path (dense) or scatter path (rows).
    """

    name = "default"
    num_slots = 0  # state arrays, each shaped like the table
    # True iff apply is linear in the delta, i.e. scatter-adding duplicate
    # rows equals applying their pre-aggregated sum.  Non-linear updaters
    # (stateful or normalized) require duplicate rows to be segment-summed
    # first — eager tables do it host-side; fused steps via aggregate_rows.
    linear = True

    # -- state --------------------------------------------------------------
    def init_state(self, shape, dtype) -> State:
        return tuple(jnp.zeros(shape, dtype) for _ in range(self.num_slots))

    # -- dense path ---------------------------------------------------------
    def apply_dense(self, w: jax.Array, state: State, delta: jax.Array,
                    opt: AddOption) -> Tuple[jax.Array, State]:
        return w + delta, state

    # -- sparse (row) path --------------------------------------------------
    def apply_rows(self, w: jax.Array, state: State, rows: jax.Array,
                   delta: jax.Array, opt: AddOption,
                   mask: Optional[jax.Array] = None
                   ) -> Tuple[jax.Array, State]:
        """Scatter-apply to ``w[rows]``.

        ``rows``: int32 [k]; ``delta``: [k, cols]; ``mask``: bool [k] marks
        valid entries (padding rows carry mask=False and must not touch
        state). Default: plain scatter-add, duplicate rows accumulate.
        """
        rows = effective_rows(rows, mask, w.shape[0])
        return w.at[rows].add(masked(delta, mask), mode="drop"), state


_REGISTRY: Dict[str, Type[Updater]] = {}


def register_updater(cls: Type[Updater]) -> Type[Updater]:
    _REGISTRY[cls.name] = cls
    return cls


register_updater(Updater)  # "default"
_REGISTRY["add"] = Updater  # alias


def get_updater(name: str) -> Updater:
    """Factory — reference ``Updater<T>::GetUpdater`` switch."""
    try:
        return _REGISTRY[name]()
    except KeyError:
        raise ValueError(
            f"unknown updater_type '{name}'; known: {sorted(_REGISTRY)}")


def updater_names():
    return sorted(_REGISTRY)


def aggregate_rows(rows: jax.Array, delta: jax.Array
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Jittable static-shape segment-sum of duplicate row ids.

    Sorts the batch, sums each duplicate group into its first slot, and
    returns ``(uniq_rows [k], agg_delta [k, ...], mask [k])`` where surplus
    slots carry ``mask=False`` (feed all three to ``Updater.apply_rows`` —
    ``effective_rows`` turns masked slots into dropped scatters).  This is
    the in-jit equivalent of the host-side ``np.unique`` + segment-sum the
    eager tables do, required before any non-``linear`` updater.
    """
    order = jnp.argsort(rows)
    r = rows[order]
    d = delta[order]
    is_new = jnp.concatenate(
        [jnp.ones((1,), bool), r[1:] != r[:-1]])
    seg = jnp.cumsum(is_new) - 1
    agg = jnp.zeros_like(d).at[seg].add(d)
    uniq = jnp.zeros_like(r).at[seg].set(r)
    mask = jnp.zeros(r.shape, bool).at[seg].set(True)
    return uniq, agg, mask


def scatter_apply(upd: "Updater", data, state, rows, delta, opt: AddOption):
    """In-jit row scatter with the linear/non-linear dispatch.

    THE one spelling of "apply a row batch through an updater inside a
    fused step": linear updaters scatter duplicates directly (adds
    commute); non-linear ones get duplicates segment-summed first via
    ``aggregate_rows`` — matching the eager path's host-side np.unique
    aggregation.  Used by every app's fused step.
    """
    if upd.linear:
        return upd.apply_rows(data, state, rows, delta, opt)
    uniq, agg, mask = aggregate_rows(rows, delta)
    return upd.apply_rows(data, state, uniq, agg, opt, mask=mask)


def masked(delta: jax.Array, mask: Optional[jax.Array]) -> jax.Array:
    """Zero out padding rows so they cannot perturb weights or state."""
    if mask is None:
        return delta
    return jnp.where(mask[:, None], delta, 0)


def effective_rows(rows: jax.Array, mask: Optional[jax.Array],
                   num_rows: int) -> jax.Array:
    """Redirect padding entries to an out-of-bounds index.

    With ``mode="drop"`` scatters, an out-of-bounds row is silently skipped,
    so padding can never clobber real rows — regardless of whether the caller
    padded with in-bounds indices. Callers must pre-aggregate duplicate rows
    (tables do, via segment-sum) before stateful ``.set`` updaters.
    """
    if mask is None:
        return rows
    return jnp.where(mask, rows, num_rows)
