"""Server-side updaters, TPU-native.

Reference (SURVEY.md §2.16): ``Updater<T>::GetUpdater`` returns one of
default/add, SGD, AdaGrad, Momentum, SmoothGradient based on the
``-updater_type`` flag; the server applies it element-wise to its shard on
every ``Add``, with per-call hyper-parameters carried by ``AddOption``.

Here the "server" is wherever the table shard lives, so updaters are pure
jittable functions ``(weights, state, delta, option) -> (weights', state')``
that XLA fuses straight into the collective step — the hot arithmetic loop of
reference ``src/updater/*.cpp`` becomes a fused vector op on the MXU/VPU.

Delta convention (documented, reference-compatible in spirit):
- ``default``: delta IS the increment — ``w += delta``.
- ``sgd|adagrad|momentum|smooth_gradient``: delta is a *gradient*; the
  updater performs the descent step with ``AddOption`` hyper-params.

Sparse (row) application keeps per-row state sharded with its rows
(SURVEY.md §7 hard-parts: "per-row server-side updaters").
"""

from __future__ import annotations

from .base import AddOption, GetOption, Updater, register_updater, get_updater, updater_names
from . import sgd as _sgd            # noqa: F401  (registration side effect)
from . import adagrad as _adagrad    # noqa: F401
from . import momentum as _momentum  # noqa: F401
from . import smooth_gradient as _sg # noqa: F401
from . import assign as _assign      # noqa: F401

__all__ = [
    "AddOption",
    "GetOption",
    "Updater",
    "get_updater",
    "register_updater",
    "updater_names",
]
