"""Assign updater — ``w = delta`` (last-write-wins).

The "put" of the host-bridge offload protocol (docs/host_bridge.md):
a table under this updater is a bit-exact remote STORE, not an
accumulator — the server keeps the pushed float32 bits verbatim, so
state that round-trips through it (``parallel/offload.py``) reads back
bitwise identical.  Mirrors the native ``UpdaterType::kAssign``.

Semantics notes: duplicates in one row batch resolve last-write-wins
(order within the batch), and ``apply_rows`` is NOT linear — padding
must go through the masked scatter so it cannot clobber real rows.
"""

from __future__ import annotations

from .base import (AddOption, Updater, effective_rows, register_updater)

__all__ = ["AssignUpdater"]


@register_updater
class AssignUpdater(Updater):
    name = "assign"
    num_slots = 0
    # Not linear: assign(sum of duplicates) != last duplicate assigned.
    linear = False

    def apply_dense(self, w, state, delta, opt: AddOption):
        return delta.astype(w.dtype), state

    def apply_rows(self, w, state, rows, delta, opt: AddOption,
                   mask=None):
        rows = effective_rows(rows, mask, w.shape[0])
        return w.at[rows].set(delta.astype(w.dtype), mode="drop"), state
