"""SGD updater — reference ``updater/sgd_updater.h`` (SURVEY.md §2.16)."""

from __future__ import annotations

from typing import Optional

import jax

from .base import AddOption, Updater, effective_rows, masked, register_updater


@register_updater
class SGDUpdater(Updater):
    """w -= lr * g (delta is a gradient)."""

    name = "sgd"
    num_slots = 0

    def apply_dense(self, w, state, delta, opt: AddOption):
        return w - opt.learning_rate * delta, state

    def apply_rows(self, w, state, rows, delta, opt: AddOption,
                   mask: Optional[jax.Array] = None):
        rows = effective_rows(rows, mask, w.shape[0])
        d = masked(delta, mask)
        return w.at[rows].add(-opt.learning_rate * d, mode="drop"), state
