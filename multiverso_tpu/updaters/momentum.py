"""Momentum updater — reference ``updater/momentum_updater.h`` (SURVEY.md §2.16)."""

from __future__ import annotations

from typing import Optional

import jax

from .base import AddOption, Updater, effective_rows, masked, register_updater


@register_updater
class MomentumUpdater(Updater):
    """v = mu*v + lr*g ; w -= v."""

    name = "momentum"
    num_slots = 1
    linear = False  # duplicate rows must be segment-summed before apply

    def apply_dense(self, w, state, delta, opt: AddOption):
        (v,) = state
        v = opt.momentum * v + opt.learning_rate * delta
        return w - v, (v,)

    def apply_rows(self, w, state, rows, delta, opt: AddOption,
                   mask: Optional[jax.Array] = None):
        (v,) = state
        rows = effective_rows(rows, mask, w.shape[0])
        d = masked(delta, mask)
        v_rows = opt.momentum * v[rows] + opt.learning_rate * d
        v = v.at[rows].set(v_rows, mode="drop")
        w = w.at[rows].add(-v_rows, mode="drop")
        return w, (v,)
