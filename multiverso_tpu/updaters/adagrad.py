"""AdaGrad updater — reference ``updater/adagrad_updater.h`` (SURVEY.md §2.16).

Per-row accumulator state is sharded identically to its rows, so the sparse
path updates state with the same scatter as the weights (SURVEY.md §7
hard-parts: per-row server-side updaters).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .base import AddOption, Updater, effective_rows, masked, register_updater


@register_updater
class AdaGradUpdater(Updater):
    """h += g^2 ; w -= lr * g / (sqrt(h) + eps)."""

    name = "adagrad"
    num_slots = 1
    linear = False  # duplicate rows must be segment-summed before apply

    def apply_dense(self, w, state, delta, opt: AddOption):
        (h,) = state
        h = h + delta * delta
        w = w - opt.learning_rate * delta / (jnp.sqrt(h) + opt.eps)
        return w, (h,)

    def apply_rows(self, w, state, rows, delta, opt: AddOption,
                   mask: Optional[jax.Array] = None):
        (h,) = state
        rows = effective_rows(rows, mask, w.shape[0])
        d = masked(delta, mask)
        # Gather-updated-scatter keeps duplicate-row semantics sane for the
        # weight step; state accumulates by scatter-add (exact for uniques,
        # accumulate-then-read for duplicates).
        h = h.at[rows].add(d * d, mode="drop")
        h_rows = h[rows]
        step = opt.learning_rate * d / (jnp.sqrt(h_rows) + opt.eps)
        w = w.at[rows].add(-step, mode="drop")
        return w, (h,)
