"""Workload sketches — the Python mirror of ``mvtpu/sketch.h``
(docs/observability.md, "workload plane").

Bounded-memory hot-key accounting for skewed sparse-table access:

- :class:`SpaceSavingSketch` (Metwally et al. 2005): top-K heavy
  hitters in K counters.  An unmonitored key evicts the minimum counter
  and inherits its count as ``error``; every key with true frequency
  > total/K is guaranteed monitored and
  ``count - error <= true <= count``.
- :class:`CountMinSketch` (Cormode & Muthukrishnan 2005): depth×width
  counters, per-row hashes, estimate = min over rows.  Never
  underestimates; overestimates by at most ``eps * total`` with
  probability 1-delta for ``width = e/eps``, ``depth = ln(1/delta)``.
- :class:`WorkloadTracker` combines both per table, reporting the same
  JSON shape the native ``"hotkeys"`` OpsQuery kind serves — so the
  pure-JAX plane and the native server plane read identically in mvtop.

Hashing is FNV-1a 64 (``key_hash``), byte-identical with the native
``workload::KeyHash`` / ``KVHash``, so per-rank sketches ``merge()``
coherently across planes (fleet scope folds per-rank top-Ks and
count-min grids cell-by-cell).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = ["key_hash", "SpaceSavingSketch", "CountMinSketch",
           "WorkloadTracker"]

_FNV_OFFSET = 1469598103934665603
_FNV_PRIME = 1099511628211
_MASK64 = (1 << 64) - 1


def key_hash(key: Any) -> int:
    """Stable 64-bit FNV-1a of a key (str/bytes hash their bytes; ints
    hash their little-endian int64 form, matching the native
    ``KeyHash(int64_t)``).  NOT Python ``hash()`` — PYTHONHASHSEED
    randomizes that per process, which would break cross-rank merges."""
    if isinstance(key, bytes):
        data = key
    elif isinstance(key, str):
        data = key.encode()
    else:
        data = int(key).to_bytes(8, "little", signed=True)
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h


def _splitmix(row: int, h: int) -> int:
    """Per-row hash family: splitmix64 finalize of ``h ^ row-salt``
    (identical to the native ``CountMin::RowHash``)."""
    x = (h ^ ((0x9E3779B97F4A7C15 * (row + 1)) & _MASK64)) & _MASK64
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & _MASK64
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & _MASK64
    x ^= x >> 31
    return x


class SpaceSavingSketch:
    """Top-K heavy hitters in K counters (not thread-safe; the owning
    :class:`WorkloadTracker` serializes access)."""

    def __init__(self, k: int = 16):
        self.k = max(1, int(k))
        self.total = 0
        # hash -> [label, count, error]
        self._entries: Dict[int, List[Any]] = {}

    def offer(self, key: Any, n: int = 1,
              _hash: Optional[int] = None) -> None:
        h = key_hash(key) if _hash is None else _hash
        self.total += n
        e = self._entries.get(h)
        if e is not None:
            e[1] += n
            return
        if len(self._entries) < self.k:
            self._entries[h] = [str(key), n, 0]
            return
        # Evict the minimum counter; the newcomer inherits its count as
        # `error` — the space-saving guarantee.
        min_h = min(self._entries, key=lambda x: self._entries[x][1])
        _, min_count, _ = self._entries.pop(min_h)
        self._entries[h] = [str(key), min_count + n, min_count]

    def topk(self) -> List[Tuple[str, int, int]]:
        """``[(label, count, error)]`` descending by count."""
        return sorted(((label, count, err)
                       for label, count, err in self._entries.values()),
                      key=lambda t: -t[1])

    def merge(self, other: "SpaceSavingSketch") -> None:
        """Fold another rank's sketch in (errors add conservatively)."""
        for h, (label, count, err) in list(other._entries.items()):
            e = self._entries.get(h)
            if e is not None:
                e[1] += count
                e[2] += err
                self.total += count
                continue
            self.offer(label, count, _hash=h)
            if h in self._entries:
                self._entries[h][2] += err


class CountMinSketch:
    """Depth×width counter grid; ``estimate()`` = min over rows."""

    def __init__(self, width: int = 1024, depth: int = 4):
        self.width = max(8, int(width))
        self.depth = max(1, int(depth))
        self.total = 0
        self._cells = [[0] * self.width for _ in range(self.depth)]

    def add(self, key: Any, n: int = 1,
            _hash: Optional[int] = None) -> None:
        h = key_hash(key) if _hash is None else _hash
        for r in range(self.depth):
            self._cells[r][_splitmix(r, h) % self.width] += n
        self.total += n

    def estimate(self, key: Any = None,
                 _hash: Optional[int] = None) -> int:
        h = key_hash(key) if _hash is None else _hash
        return min(self._cells[r][_splitmix(r, h) % self.width]
                   for r in range(self.depth))

    def merge(self, other: "CountMinSketch") -> None:
        if (other.width, other.depth) != (self.width, self.depth):
            raise ValueError(
                f"count-min shape mismatch: {self.width}x{self.depth} vs "
                f"{other.width}x{other.depth}")
        for r in range(self.depth):
            mine, theirs = self._cells[r], other._cells[r]
            for c in range(self.width):
                mine[c] += theirs[c]
        self.total += other.total


class WorkloadTracker:
    """Per-table tracker: one space-saving top-K + one count-min +
    per-bucket get/add load counters — the JAX-plane twin of the native
    ``ServerTable`` workload accounting, reporting the same shape as
    the ``"hotkeys"`` OpsQuery kind."""

    def __init__(self, topk: int = 16, buckets: int = 64):
        self._lock = threading.Lock()
        self.buckets = int(buckets)
        self._ss = SpaceSavingSketch(topk)
        self._cm = CountMinSketch()
        self._bucket_gets = [0] * self.buckets
        self._bucket_adds = [0] * self.buckets
        self.gets = 0
        self.adds = 0

    def note_get(self, keys: Optional[Iterable[Any]] = None) -> None:
        self._note(keys, is_add=False)

    def note_add(self, keys: Optional[Iterable[Any]] = None) -> None:
        self._note(keys, is_add=True)

    def _note(self, keys: Optional[Iterable[Any]], is_add: bool) -> None:
        with self._lock:
            if is_add:
                self.adds += 1
            else:
                self.gets += 1
            if keys is None:        # whole-table op: totals only
                return
            loads = self._bucket_adds if is_add else self._bucket_gets
            for key in keys:
                h = key_hash(key)
                self._ss.offer(key, _hash=h)
                self._cm.add(key, _hash=h)
                loads[h % self.buckets] += 1

    def estimate(self, key: Any) -> int:
        with self._lock:
            return self._cm.estimate(key)

    def merge(self, other: "WorkloadTracker") -> None:
        """Fold another rank's tracker (the fleet-scope reduction)."""
        with self._lock, other._lock:
            self._ss.merge(other._ss)
            self._cm.merge(other._cm)
            for b in range(min(self.buckets, other.buckets)):
                self._bucket_gets[b] += other._bucket_gets[b]
                self._bucket_adds[b] += other._bucket_adds[b]
            self.gets += other.gets
            self.adds += other.adds

    def report(self) -> Dict[str, Any]:
        """Same shape as one native ``"hotkeys"`` report entry."""
        with self._lock:
            loads = [g + a for g, a in zip(self._bucket_gets,
                                           self._bucket_adds)]
            mean = sum(loads) / float(self.buckets)
            # Estimate by the STORED hash, not the label string — the
            # key was offered as its raw form (int row ids hash their
            # int64 bytes, matching the native plane), and re-hashing
            # the stringified label would land in different cells.
            top = sorted(
                ({"key": label, "count": count, "error": err,
                  "estimate": self._cm.estimate(_hash=h)}
                 for h, (label, count, err) in self._ss._entries.items()),
                key=lambda e: -e["count"])
            return {
                "gets": self.gets,
                "adds": self.adds,
                "skew_ratio": (max(loads) / mean) if mean > 0 else 0.0,
                "bucket_load_max": max(loads) if loads else 0,
                "bucket_load_mean": mean,
                "hotkeys": {"total": self._cm.total, "topk": top},
            }
