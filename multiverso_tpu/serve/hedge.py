"""Hedged reads for the anonymous serve tier (docs/serving.md "tail").

The classic tail-at-scale remedy: issue the read on the PRIMARY
connection, and if no answer arrives within a delay derived from the
live latency histogram (observed p95, floored at ``-hedge_min_us``),
re-issue it against the hot-key replica — answered AT THE REACTOR
(``-replica_serve_reactor``), so it bypasses the actor mailbox a
straggling apply is clogging — or, when the replica does not hold the
rows, against a second connection.  The first answer wins; the loser is
cancelled with a fire-and-forget ``RequestCancel`` token that overtakes
the mailbox FIFO, so a still-queued loser is dropped at dequeue instead
of burning an apply slot (``serve.hedge.cancelled`` server-side).

Reads only, ever — hedging an add would duplicate its side effect; the
PR 12 audit plane's zero-dup invariant is part of this module's
acceptance test.

Counters (client-side, mirrored into the metrics registry when one is
importable): ``serve.hedge.issued`` / ``won`` / ``wasted`` — the win
rate ``won / issued`` is the benchable health signal (``bench_tail``).
"""

from __future__ import annotations

import selectors
import socket
import time
from typing import Optional

import numpy as np

from .wire import (MSG, AnonServeClient, QOS_CLASSES, pack_frame,
                   unpack_frame)

__all__ = ["HedgedReader", "LatencyTracker"]


def _flag_us(value, name, fallback):
    """Config-flag lookup that stays importable without the package."""
    if value is not None:
        return float(value)
    try:
        from .. import config
        return float(config.get(name))
    except Exception:
        return float(fallback)


class LatencyTracker:
    """Bounded ring of observed read latencies; the hedge delay is the
    observed p95 floored at ``hedge_min_s`` — hedging earlier than the
    tail starts re-issues the bulk of healthy traffic for nothing."""

    def __init__(self, capacity: int = 256):
        self._ring = []
        self._cap = max(8, int(capacity))
        self.samples = 0

    def observe(self, seconds: float) -> None:
        self._ring.append(float(seconds))
        del self._ring[:-self._cap]
        self.samples += 1

    def quantile(self, q: float) -> Optional[float]:
        if not self._ring:
            return None
        vals = sorted(self._ring)
        idx = min(len(vals) - 1, int(q * len(vals)))
        return vals[idx]

    def hedge_delay(self, floor_s: float) -> float:
        p95 = self.quantile(0.95)
        return max(floor_s, p95) if p95 is not None else floor_s


class HedgedReader:
    """Hedged row reads against one server shard over two anonymous
    connections (gold tenant class by default — a hedger re-issuing
    bulk traffic would amplify exactly the herd QoS exists to shed).

    ``get_rows(ids)`` is the hedged entry point; ``enabled=False`` is
    the control arm (identical wire traffic, no hedge ever issued).
    Single-shard scope: the reader targets ONE endpoint, so callers
    aim it at the shard that owns their rows (the DLRM serve shape).
    """

    def __init__(self, endpoint: str, table_id: int, cols: int, *,
                 qos_class="gold", qos_classes=QOS_CLASSES,
                 hedge_min_us: Optional[float] = None,
                 enabled: bool = True,
                 timeout: Optional[float] = None,
                 backup_endpoint: Optional[str] = None,
                 backup_shard: int = -1):
        self.table_id = int(table_id)
        self.cols = int(cols)
        self.enabled = bool(enabled)
        self.hedge_min_s = _flag_us(hedge_min_us, "hedge_min_us",
                                    1000.0) * 1e-6
        self.primary = AnonServeClient(endpoint, timeout=timeout,
                                       timing=False, qos_class=qos_class,
                                       qos_classes=qos_classes)
        self.secondary = AnonServeClient(endpoint, timeout=timeout,
                                         timing=False, qos_class=qos_class,
                                         qos_classes=qos_classes)
        # True-backup hedge (docs/replication.md): with replication
        # armed, the shard has a REAL second copy — the backup rank's
        # serve port answers reads of `backup_shard` from its backed
        # instance (bounded behind the primary only by the forward
        # stream; exact under -repl_sync).  Unlike the hot-key replica
        # it holds EVERY row, so a hedge against it never falls back
        # to re-asking the straggling primary.  The shard hint routes
        # the read at a rank that serves two shards of the table.
        self.backup = None
        self.backup_shard = int(backup_shard)
        self.backup_wins = 0
        if backup_endpoint:
            self.backup = AnonServeClient(backup_endpoint, timeout=timeout,
                                          timing=False,
                                          qos_class=qos_class,
                                          qos_classes=qos_classes)
        self.tracker = LatencyTracker()
        # epoll-backed readiness (NOT select.select: at 10k-connection
        # scale this process's fds exceed FD_SETSIZE and select raises).
        self._psel = selectors.DefaultSelector()
        self._psel.register(self.primary.sock, selectors.EVENT_READ)
        self.issued = 0      # hedges fired
        self.won = 0         # hedge answered first
        self.wasted = 0      # hedge fired but the primary won anyway
        self.cancelled = 0   # cancel tokens sent
        # msg ids whose (late) primary replies must be discarded.
        self._stale = set()

    # ------------------------------------------------------------ plumbing
    def _send_get(self, client: AnonServeClient, ids: np.ndarray) -> int:
        mid = client._next_id()
        client.send_raw(pack_frame(MSG["RequestGet"], self.table_id, mid,
                                   blobs=[ids.tobytes()],
                                   qos=client._qos()))
        return mid

    def _poll_reply(self, client: AnonServeClient, want_mid: int,
                    wait_s: float) -> Optional[dict]:
        """Wait up to ``wait_s`` for ``want_mid``'s reply on ``client``;
        stale replies (cancelled losers) are discarded along the way.
        None on timeout — the socket stays healthy for later frames."""
        deadline = time.monotonic() + max(wait_s, 0.0)
        sock = client.sock
        while True:
            frame = client._decoder.next_frame()
            if frame is not None:
                reply = unpack_frame(frame)
                if reply["msg_id"] in self._stale:
                    self._stale.discard(reply["msg_id"])
                    continue
                if reply["msg_id"] == want_mid:
                    return reply
                continue  # unrelated (shouldn't happen): drop
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            if not self._psel.select(timeout=remaining):
                return None
            try:
                chunk = sock.recv(65536, socket.MSG_DONTWAIT)
            except (BlockingIOError, InterruptedError):
                continue
            if not chunk:
                raise ConnectionError("server closed the connection")
            client._decoder.feed(chunk)

    def _rows_from_reply(self, reply: dict, ids: np.ndarray) -> np.ndarray:
        out = np.frombuffer(reply["blobs"][0], dtype=np.float32)
        return out.reshape(ids.size, self.cols)

    def _note(self, name: str) -> None:
        try:
            from .. import metrics
            metrics.counter(name).inc()
        except Exception:
            pass

    # -------------------------------------------------------------- reads
    def get_rows(self, row_ids) -> np.ndarray:
        """Hedged read of ``row_ids`` (global ids owned by this shard).

        Primary RequestGet first; past the hedge delay, the hot-key
        replica is pulled on the second connection (reactor-served) and
        wins if it holds every requested row at least as fresh as the
        snapshot bound; otherwise a second full get races the primary.
        The loser is cancelled."""
        ids = np.ascontiguousarray(row_ids, dtype=np.int32)
        t0 = time.monotonic()
        mid = self._send_get(self.primary, ids)
        budget = self.primary.timeout or 30.0
        if not self.enabled:
            reply = self._poll_reply(self.primary, mid, budget)
            if reply is None:
                raise TimeoutError(f"primary read {mid} timed out")
            self.tracker.observe(time.monotonic() - t0)
            return self._rows_from_reply(reply, ids)

        delay = self.tracker.hedge_delay(self.hedge_min_s)
        reply = self._poll_reply(self.primary, mid, delay)
        if reply is not None:
            self.tracker.observe(time.monotonic() - t0)
            return self._rows_from_reply(reply, ids)

        # --- hedge: true backup shard first (docs/replication.md),
        # else the hot-key replica (reactor-served, mailbox-free) ------
        self.issued += 1
        self._note("serve.hedge.issued")
        hedge_rows = None
        hit_backup = False
        if self.backup is not None and ids.size:
            # The backup holds the WHOLE shard — a complete answer
            # regardless of key temperature, and a straggling primary's
            # clogged mailbox is not in its path at all.
            hedge_rows = self.backup.get_rows(self.table_id, ids,
                                              self.cols,
                                              shard=self.backup_shard)
            hit_backup = True
            self._note("serve.hedge.backup")
        if hedge_rows is None:
            replica = self.secondary.get_replica(self.table_id)
            if all(int(i) in replica for i in ids):
                hedge_rows = np.stack([replica[int(i)][1] for i in ids])
            elif ids.size:
                # Replica cold for these rows: second-connection hedge.
                hedge_rows = self.secondary.get_rows(self.table_id, ids,
                                                     self.cols)
        # First answer wins: one nonblocking look at the primary.
        late = self._poll_reply(self.primary, mid, 0.0)
        if late is not None:
            self.wasted += 1
            self._note("serve.hedge.wasted")
            self.tracker.observe(time.monotonic() - t0)
            return self._rows_from_reply(late, ids)
        self.won += 1
        self._note("serve.hedge.won")
        if hit_backup:
            self.backup_wins += 1
            self._note("serve.hedge.backup.won")
        # Cancel the loser: a fire-and-forget token that overtakes the
        # mailbox FIFO; its late reply (if the apply already ran) is
        # discarded via the stale set.
        self.primary.cancel(self.table_id, mid)
        self.cancelled += 1
        self._stale.add(mid)
        self.tracker.observe(time.monotonic() - t0)
        return hedge_rows

    def stats(self) -> dict:
        return {"issued": self.issued, "won": self.won,
                "wasted": self.wasted, "cancelled": self.cancelled,
                "backup_wins": self.backup_wins,
                "win_rate": self.won / self.issued if self.issued else 0.0,
                "samples": self.tracker.samples}

    def close(self) -> None:
        try:
            self._psel.unregister(self.primary.sock)
        except (KeyError, ValueError):
            pass
        self._psel.close()
        self.primary.close()
        self.secondary.close()
        if self.backup is not None:
            self.backup.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
