"""Versioned client cache — the read side of the serve layer
(docs/serving.md).

A bounded LRU keyed by arbitrary tuples, where every entry carries the
SERVER VERSION it was fetched at.  A lookup names the freshest version
the caller may not be behind (``min_version`` — typically
``server_version - max_staleness``); entries older than that miss, in
the SSPTable tradition of bounded-staleness reads (PAPERS.md: Cui et
al. ATC'14) — except the bound here is a VERSION distance (number of
server-side applies), not the SSP clock distance the training plane's
``-staleness`` flag speaks (see docs/serving.md for the mapping).

Thread-safe; every operation is O(1).  Counters land in the metrics
registry: ``serve.cache.hit`` / ``serve.cache.miss`` /
``serve.cache.evict`` / ``serve.cache.stale`` (a miss specifically
caused by the version bound).
"""

from __future__ import annotations

import itertools
import threading
import weakref
from collections import OrderedDict
from typing import Any, Hashable, Optional, Tuple

from .. import capacity, metrics

__all__ = ["VersionedLRUCache"]

# Distinguishes same-named caches in the capacity gauge registry (two
# ServeClients both name theirs "serve").
_GAUGE_SEQ = itertools.count()


class VersionedLRUCache:
    """Bounded LRU of (key -> value, version) with staleness-gated reads.

    ``max_entries`` is a hard bound: inserting into a full cache evicts
    the least-recently-used entry (mvlint MV007 — client-side caches in
    library code must be bounded).
    """

    def __init__(self, max_entries: int, name: str = "serve"):
        if max_entries <= 0:
            raise ValueError(f"max_entries must be > 0, got {max_entries}")
        self.max_entries = int(max_entries)
        self._name = name
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, Tuple[Any, int]]" = \
            OrderedDict()  # bounded: see store()'s popitem eviction
        # Capacity plane (docs/observability.md): every serve-plane
        # cache registers a byte gauge — MV018's contract.  Weakly
        # bound: a dead cache prunes its own gauge at the next
        # snapshot, so short-lived ServeClients never leak registry
        # entries (that would be untracked growth in the tracker).
        self._gauge_name = f"{name}.cache.{next(_GAUGE_SEQ)}"
        ref = weakref.ref(self)

        def _gauge(ref=ref, gname=self._gauge_name) -> int:
            obj = ref()
            if obj is None:
                capacity.unregister_gauge(gname)
                return 0
            return obj.bytes()

        capacity.register_gauge(self._gauge_name, _gauge)

    def bytes(self) -> int:
        """Resident bytes of the cached values (+ per-entry overhead,
        the shared capacity unit)."""
        with self._lock:
            return capacity.container_bytes(self._entries)

    def _tick(self, what: str) -> None:
        metrics.counter(f"{self._name}.cache.{what}").inc()

    def lookup(self, key: Hashable,
               min_version: Optional[int] = None) -> Optional[Tuple[Any, int]]:
        """Return ``(value, version)`` when present AND fresh enough,
        else None.  ``min_version=None`` accepts any cached version
        (version gating disabled); otherwise an entry whose version is
        below ``min_version`` misses (and counts ``cache.stale``)."""
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
        if hit is None:
            self._tick("miss")
            return None
        if min_version is not None and hit[1] < min_version:
            self._tick("stale")
            self._tick("miss")
            return None
        self._tick("hit")
        return hit

    def lookup_many(self, keys, min_versions) -> list:
        """Batched row-granular lookup (docs/embedding.md): one lock
        acquisition and one counter update for the whole id set — the
        per-key ``lookup`` loop's lock/metrics cost is what kept the
        row cache from clearing the 10x serving bar.  ``min_versions``
        aligns with ``keys`` (or is a scalar applied to all); returns
        one value-or-None per key (None = absent or stale)."""
        scalar = not hasattr(min_versions, "__len__")
        out = []
        hits = misses = stale = 0
        with self._lock:
            for i, key in enumerate(keys):
                entry = self._entries.get(key)
                if entry is None:
                    out.append(None)
                    misses += 1
                    continue
                mv = min_versions if scalar else min_versions[i]
                if mv is not None and entry[1] < mv:
                    out.append(None)
                    stale += 1
                    misses += 1
                    continue
                self._entries.move_to_end(key)
                out.append(entry[0])
                hits += 1
        if hits:
            metrics.counter(f"{self._name}.cache.hit").inc(hits)
        if misses:
            metrics.counter(f"{self._name}.cache.miss").inc(misses)
        if stale:
            metrics.counter(f"{self._name}.cache.stale").inc(stale)
        return out

    def store(self, key: Hashable, value: Any, version: int) -> None:
        """Insert/refresh an entry; never lowers a cached version (a
        racing slow fetch must not roll a fresher entry back)."""
        with self._lock:
            old = self._entries.get(key)
            if old is not None and old[1] > version:
                return
            self._entries[key] = (value, int(version))
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)   # LRU eviction bound
                self._tick("evict")

    def invalidate(self, prefix: Optional[Hashable] = None) -> int:
        """Drop entries (write-through invalidation on a local add).

        ``prefix=None`` clears everything; otherwise drops every tuple
        key whose FIRST element equals ``prefix`` (the serve client keys
        entries as ``(handle, ...)`` / the tables as ``(kind, ...)``).
        Returns the number dropped."""
        with self._lock:
            if prefix is None:
                n = len(self._entries)
                self._entries.clear()
                return n
            doomed = [k for k in self._entries
                      if isinstance(k, tuple) and k and k[0] == prefix]
            for k in doomed:
                del self._entries[k]
            return len(doomed)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        return {
            "entries": len(self),
            "max_entries": self.max_entries,
            "hits": int(metrics.counter(f"{self._name}.cache.hit").value),
            "misses": int(metrics.counter(f"{self._name}.cache.miss").value),
            "evictions": int(
                metrics.counter(f"{self._name}.cache.evict").value),
        }
