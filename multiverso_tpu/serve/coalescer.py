"""Request coalescer — the batching side of the serve layer
(docs/serving.md).

Merges concurrent (and window-adjacent) requests against the same
logical target into ONE execution, PS-Lite-style: the first caller to
open a batch becomes its LEADER, waits out ``-coalesce_window_us`` (so
near-simultaneous callers can pile on), seals the batch, runs the
merged operation once, and fans the per-item results back to every
waiter.  A size cap seals early so a hot key cannot grow an unbounded
batch (mvlint MV007).

The merge semantics live entirely in the caller's ``execute`` function
— ``execute(items) -> results`` receives every queued item (in arrival
order) and returns one result per item — so the same engine serves:

- identical whole-table gets   (broadcast one fetch to N waiters),
- row-range gets               (union the ids, scatter the rows),
- adds                         (sum the deltas, push once, ack all).

Observability: each sealed batch records its size in the
``serve.coalesce.batch`` histogram and runs under a
``serve::coalesced`` span whose ``n`` arg shows N logical ops
collapsing into one execution.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Hashable, List

from .. import metrics, tracing

__all__ = ["Coalescer"]


class _Batch:
    __slots__ = ("items", "done", "full", "results", "error", "sealed")

    def __init__(self) -> None:
        self.items: List[Any] = []
        self.done = threading.Event()
        self.full = threading.Event()   # size cap hit — leader wakes early
        self.results: List[Any] = []
        self.error: BaseException | None = None
        self.sealed = False


class Coalescer:
    """Leader/follower batcher over keyed windows.

    One instance guards any number of keys (one open batch per key).
    ``submit`` blocks until the batch containing the caller's item
    executed and returns the caller's own result; an ``execute`` failure
    propagates to EVERY waiter of that batch (each may retry, landing in
    a fresh batch).
    """

    def __init__(self, window_s: float = 200e-6, max_batch: int = 64):
        self.window_s = max(0.0, float(window_s))
        self.max_batch = max(1, int(max_batch))
        self._lock = threading.Lock()
        # At most one OPEN batch per key; sealed batches leave the dict
        # before executing, so this cannot grow past the live key set.
        self._open: dict = {}  # mvlint: MV007-exempt(one entry per in-flight key, removed on seal)

    def submit(self, key: Hashable, item: Any,
               execute: Callable[[List[Any]], List[Any]]) -> Any:
        """Queue ``item`` under ``key``; return its result.

        The leader (batch opener) sleeps the window, seals, and calls
        ``execute(items)`` ONCE; followers just wait.  ``execute`` must
        return one result per item, in item order.
        """
        with self._lock:
            batch = self._open.get(key)
            if batch is not None and not batch.sealed:
                leader = False
                batch.items.append(item)
                slot = len(batch.items) - 1
                if len(batch.items) >= self.max_batch:
                    # Size cap: seal now and wake the leader out of the
                    # remainder of its window.
                    batch.sealed = True
                    self._open.pop(key, None)
                    batch.full.set()
            else:
                leader = True
                batch = _Batch()
                batch.items.append(item)
                slot = 0
                self._open[key] = batch
        if leader:
            if self.window_s > 0:
                # Let adjacent callers pile on; a full batch ends the
                # window early.
                batch.full.wait(self.window_s)
            with self._lock:
                if not batch.sealed:
                    batch.sealed = True
                    self._open.pop(key, None)
                items = list(batch.items)
            metrics.histogram("serve.coalesce.batch").observe(
                float(len(items)))
            try:
                with tracing.span("serve::coalesced", n=len(items),
                                  key=str(key)):
                    results = execute(items)
                if len(results) != len(items):
                    raise RuntimeError(
                        f"coalesced execute returned {len(results)} "
                        f"results for {len(items)} items")
                batch.results = list(results)
            except BaseException as exc:  # fan the failure to all waiters
                batch.error = exc
            finally:
                batch.done.set()
        else:
            batch.done.wait()
        if batch.error is not None:
            raise batch.error
        return batch.results[slot]
