"""Anonymous serve-tier wire client (docs/transport.md).

Speaks the native frame protocol directly over a TCP socket — no rank,
no machine file, no native library.  The epoll engine (`-net_engine=
epoll`, the default) accepts such connections on any server rank's
listen port: fleet peers open with a ``Hello`` identify frame, so any
connection whose first frame is an ordinary request (``src = -1``, as
packed here) is treated as anonymous — the reactor assigns it a
pseudo-rank, and replies route back over the same socket.  The blocking ``tcp`` engine does NOT serve
anonymous clients (its readers deliver inbound frames, but replies to a
non-rank ``src`` have no route back).

Frame layout (one ``Message``, little-endian, matching
``mvtpu/message.h``)::

    int64  frame_len                  # bytes after this field
    WireHeader {                      # 56 bytes
        int32 src, dst, type, table_id
        int64 msg_id, trace_id, version
        int32 codec, flags, num_blobs, shard_hint
    }
    num_blobs x { int64 len; bytes payload }

Supported requests are the serve protocol: ``RequestVersion`` (header
only, ``version=-1`` for the whole table), ``RequestGet`` (the server
replies with ITS SHARD of the table — an anonymous client reading a
sharded table contacts each server rank it cares about), the
server-side shed path answers either with ``ReplyBusy`` — plus the
introspection scrape ``OpsQuery``/``OpsReply``
(docs/observability.md): :meth:`AnonServeClient.ops_report` fetches
Prometheus metrics / health / table stats / hot-key workload reports,
local- or fleet-scope.

This module is pure stdlib + numpy so external tooling can vendor it.

Contract-checked: tools/mvcontract.py (``make contract``) statically
diffs the struct formats, ``FLAG_*`` constants, and ``MSG`` numbers
below against ``mvtpu/message.h`` — change them together or tier-1
fails.
"""

from __future__ import annotations

import socket
import struct
import time
from typing import Optional

import numpy as np

__all__ = ["AnonServeClient", "MSG", "pack_frame", "unpack_frame",
           "HEADER", "TIMING", "FLAG_TIMING", "AUDIT", "FLAG_AUDIT",
           "QOS", "FLAG_QOS", "QOS_CLASSES", "qos_id",
           "STAGES", "default_timeout_ms",
           "stage_durations", "ntp_sample", "OffsetEstimator",
           "OPS_SCOPE_LOCAL", "OPS_SCOPE_FLEET", "OPS_KINDS"]

# WireHeader (mvtpu/message.h): 4 x int32, 3 x int64, 4 x int32.
HEADER = struct.Struct("<4i3q4i")
# TimingTrail (mvtpu/message.h): six int64 monotonic-ns stage stamps
# following the header when FLAG_TIMING is set — enqueue, send, recv,
# dequeue, apply_done, reply_send (docs/observability.md).
TIMING = struct.Struct("<6q")
FLAG_TIMING = 1 << 3  # msgflag::kHasTiming
# AuditStamp (mvtpu/message.h): the inclusive per-(worker, table,
# shard) Add seq range this message covers, following the header (after
# the timing trail when both flags are set) when FLAG_AUDIT is set —
# the delivery-audit identity (docs/observability.md "audit plane").
AUDIT = struct.Struct("<2q")
FLAG_AUDIT = 1 << 4  # msgflag::kHasAudit
# QosStamp (mvtpu/message.h): tenant class (a POSITIONAL index into the
# server's -qos_classes list) + remaining deadline budget in ns,
# following the header (after the audit stamp when both flags are set)
# when FLAG_QOS is set — the tail-at-scale stamp (docs/serving.md
# "tail").  The reactor budgets inflight reads per class and drops a
# read already past its deadline at dequeue.
QOS = struct.Struct("<2iq")
FLAG_QOS = 1 << 5  # msgflag::kHasQos
_LEN = struct.Struct("<q")

# The default -qos_classes list (positional ids — both sides must agree
# on the list, the same contract as codec negotiation).
QOS_CLASSES = ("bulk", "gold")

# AnonServeClient's default connect/read timeout when the caller passes
# none.  Mirrors the -serve_timeout_ms flag (multiverso_tpu/config.py);
# kept as a module constant so this file stays vendorable stdlib.
DEFAULT_TIMEOUT_MS = 30000


def default_timeout_ms() -> float:
    """The -serve_timeout_ms flag when multiverso_tpu.config is
    importable, else :data:`DEFAULT_TIMEOUT_MS` — one source of truth
    for the serve tier's deadline budget (docs/serving.md "tail")."""
    try:  # pragma: no cover - import guard keeps the module vendorable
        from multiverso_tpu import config
        return float(config.get("serve_timeout_ms"))
    except Exception:
        return float(DEFAULT_TIMEOUT_MS)


def qos_id(klass, classes=QOS_CLASSES) -> int:
    """Class name (or already-an-id) -> positional wire id."""
    if isinstance(klass, int):
        return klass
    try:
        return classes.index(klass)
    except ValueError:
        raise ValueError(f"unknown QoS class {klass!r} "
                         f"(declared classes: {classes})") from None

# MsgType values used by the serve protocol (mvtpu/message.h).
MSG = {
    "RequestGet": 1,
    "ReplyGet": 3,
    "ReplyError": 5,
    "RequestVersion": 8,
    "ReplyVersion": 9,
    "ReplyBusy": 10,
    # Hot-key replica pull (docs/embedding.md): the server pushes its
    # SpaceSaving top-K rows + bucket versions; anonymous clients keep
    # them as a local hot-row side table consulted before RequestGet.
    "RequestReplica": 11,
    "ReplyReplica": 12,
    # Hedge-cancel token (docs/serving.md "tail"): fire-and-forget
    # notice that the sender no longer wants (this connection, msg_id)'s
    # answer — the LOSER of a hedged read.  Consumed at the reactor (it
    # overtakes the mailbox FIFO); the actor drops the cancelled read at
    # dequeue.  No reply.
    "RequestCancel": 13,
    # Introspection plane (docs/observability.md): in-band scrape.  The
    # request's first blob names the report kind; `version` carries the
    # scope (OPS_SCOPE_LOCAL / OPS_SCOPE_FLEET).  Local-scope queries
    # are answered AT THE REACTOR, never through the actor mailbox.
    "OpsQuery": 23,
    "OpsReply": 24,
}

OPS_SCOPE_LOCAL = 0
OPS_SCOPE_FLEET = 1
# Every report kind the native ops plane dispatches (ops.cc LocalReport)
# — the wire-level catalogue.  tools/mvcontract.py diffs this tuple
# against the C++ dispatch strings, and tests assert every kind has an
# mvtop view and a docs/observability.md section, so adding a kind in
# only one place fails fast.
OPS_KINDS = ("metrics", "health", "tables", "hotkeys", "latency",
             "audit", "replication", "capacity", "alerts")
_TYPE_NAME = {v: k for k, v in MSG.items()}

_ACCEPT_RAW = 1  # msgflag::kAcceptRaw


def pack_frame(msg_type: int, table_id: int, msg_id: int, *,
               version: int = -1, blobs=(), timing: bool = False,
               audit=None, qos=None, shard: int = -1) -> bytes:
    """One wire frame.  ``src=-1`` is what makes the connection
    anonymous: the reactor sees no valid rank in the first frame and
    assigns a pseudo-rank instead.  ``timing=True`` stamps a latency
    trail (enqueue+send = now, monotonic ns) after the header — the
    server echoes and extends it, and the reply's trail attributes the
    round trip per stage (docs/observability.md "latency plane").
    ``audit=(seq_lo, seq_hi)`` stamps a delivery-audit seq range after
    the trail (docs/observability.md "audit plane").
    ``qos=(class_id, budget_ns)`` stamps the tenant class + remaining
    deadline budget after the audit stamp (docs/serving.md "tail") —
    the reactor budgets reads per class and drops a read already past
    its deadline at dequeue instead of burning an apply slot.
    ``shard`` stamps the target shard index (docs/replication.md): a
    post-failover rank serves TWO shards of a table, so the shard hint
    — not the connected rank — names which one this read wants; it
    rides the old header pad slot biased by one (-1 = no hint, the
    pre-replication wire, byte-identical)."""
    flags = (_ACCEPT_RAW | (FLAG_TIMING if timing else 0)
             | (FLAG_AUDIT if audit is not None else 0)
             | (FLAG_QOS if qos is not None else 0))
    body = HEADER.pack(-1, -1, msg_type, table_id, msg_id, 0, version,
                       0, flags, len(blobs), int(shard) + 1)
    if timing:
        now = time.monotonic_ns()
        body += TIMING.pack(now, now, 0, 0, 0, 0)
    if audit is not None:
        body += AUDIT.pack(int(audit[0]), int(audit[1]))
    if qos is not None:
        body += QOS.pack(int(qos[0]), 0, int(qos[1]))
    for b in blobs:
        body += _LEN.pack(len(b)) + bytes(b)
    return _LEN.pack(len(body)) + body


def unpack_frame(body: bytes) -> dict:
    """Decode one frame body (the bytes after the length prefix)."""
    (src, dst, mtype, table_id, msg_id, trace_id, version, codec, flags,
     num_blobs, shard_hint) = HEADER.unpack_from(body, 0)
    blobs = []
    pos = HEADER.size
    timing = None
    if flags & FLAG_TIMING:
        timing = TIMING.unpack_from(body, pos)
        pos += TIMING.size
    audit = None
    if flags & FLAG_AUDIT:
        audit = AUDIT.unpack_from(body, pos)
        pos += AUDIT.size
    qos = None
    if flags & FLAG_QOS:
        klass, _pad2, budget_ns = QOS.unpack_from(body, pos)
        qos = (klass, budget_ns)
        pos += QOS.size
    for _ in range(num_blobs):
        (blen,) = _LEN.unpack_from(body, pos)
        pos += _LEN.size
        blobs.append(body[pos:pos + blen])
        pos += blen
    return {"src": src, "dst": dst, "type": mtype,
            "type_name": _TYPE_NAME.get(mtype, str(mtype)),
            "table_id": table_id, "msg_id": msg_id, "trace_id": trace_id,
            "version": version, "codec": codec, "flags": flags,
            "shard": shard_hint - 1,
            "timing": timing, "audit": audit, "qos": qos, "blobs": blobs}


# Stage names, in trail order (docs/observability.md "latency plane").
STAGES = ("queue", "wire_out", "mailbox", "apply", "reactor", "wire_back")


def ntp_sample(trail, now_ns: int):
    """One NTP offset sample from a reply's timing trail: ``(offset_ns,
    rtt_ns)`` where offset is how far the SERVER's monotonic clock runs
    ahead of ours, rtt the round trip minus the server hold time.
    ``None`` when the trail never crossed the wire (local serve)."""
    t_send, t_recv, t_reply = trail[1], trail[2], trail[5]
    if not (t_send and t_recv and t_reply):
        return None
    offset = ((t_recv - t_send) + (t_reply - now_ns)) // 2
    rtt = (now_ns - t_send) - (t_reply - t_recv)
    return (offset, rtt) if rtt >= 0 else None


def stage_durations(trail, now_ns: int, offset_ns: int = 0) -> dict:
    """Per-stage durations (SECONDS, clamped at 0) from a reply's
    timing trail — the Python mirror of the native latency plane's
    attribution math.  Cross-clock stages (wire_out / wire_back) are
    corrected by ``offset_ns``; with a good estimate the stage sum
    telescopes back to ``total`` exactly."""
    t_enq, t_send, t_recv, t_deq, t_apply, t_reply = trail
    out = {}

    def put(name, ns):
        out[name] = max(ns, 0) * 1e-9

    if t_enq and t_send:
        put("queue", t_send - t_enq)
    remote = t_send and t_recv and t_reply
    if remote:
        put("wire_out", (t_recv - offset_ns) - t_send)
        if t_deq:
            put("mailbox", t_deq - t_recv)
    elif t_send and t_deq:
        put("mailbox", t_deq - t_send)
    if t_deq and t_apply:
        put("apply", t_apply - t_deq)
    if t_apply and t_reply:
        put("reactor", t_reply - t_apply)
    if t_reply:
        put("wire_back",
            now_ns - (t_reply - offset_ns) if remote else now_ns - t_reply)
    if t_enq:
        put("total", now_ns - t_enq)
    return out


class OffsetEstimator:
    """Bounded-window NTP clock filter (the native latency.cc mirror):
    feed every ``(offset, rtt)`` sample; the minimum-RTT sample of the
    last ``window`` wins — queueing delay inflates RTT and,
    asymmetrically, offset error."""

    def __init__(self, window: int = 8):
        self._ring = []          # [(rtt, offset)]
        self._window = max(1, int(window))
        self.samples = 0

    def update(self, offset_ns: int, rtt_ns: int) -> None:
        self._ring.append((int(rtt_ns), int(offset_ns)))
        del self._ring[:-self._window]
        self.samples += 1

    @property
    def offset_ns(self) -> int:
        return min(self._ring)[1] if self._ring else 0

    @property
    def rtt_ns(self) -> Optional[int]:
        return min(self._ring)[0] if self._ring else None


class AnonServeClient:
    """One anonymous connection to a server rank's listen endpoint.

    Blocking convenience wrapper; the fan-in bench/demo drive hundreds
    of these sockets through ``selectors`` instead (send ``request()``
    bytes, feed received bytes to a :class:`FrameDecoder`).

    With ``timing=True`` (the default) every request carries a latency
    trail; each reply then refreshes :attr:`offset` (the NTP-style
    server clock-offset estimate) and :attr:`last_stages` — the
    per-stage breakdown of that round trip, in seconds
    (docs/observability.md "latency plane").  A pre-trail server (or
    ``timing=False``) simply leaves both untouched: the old header
    round-trips exactly as before.

    ``timeout=None`` (the new default) reads ``-serve_timeout_ms`` —
    one source of truth for the serve deadline, because the SAME budget
    is propagated on the wire (docs/serving.md "tail"): every request
    carries a QoS stamp with this client's tenant class (``qos_class``,
    a name from the default class list or a raw positional id) and its
    remaining deadline budget, so a server drops a read whose caller
    already gave up instead of burning an apply slot.  ``qos_class=
    None`` stamps nothing — the pre-13 frame, byte-identical.
    """

    def __init__(self, endpoint: str, timeout: Optional[float] = None,
                 timing: bool = True, qos_class=None,
                 qos_classes=QOS_CLASSES):
        # Satellite discipline (docs/serving.md "tail"): the old
        # hard-coded 30 s default is now the -serve_timeout_ms flag.
        if timeout is None:
            timeout = default_timeout_ms() * 1e-3
        host, port = endpoint.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)),
                                             timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._decoder = FrameDecoder()
        self._msg_id = 0
        self.timing = timing
        self.timeout = timeout
        self.qos_class = (None if qos_class is None
                          else qos_id(qos_class, qos_classes))
        self.offset = OffsetEstimator()
        self.last_stages: Optional[dict] = None
        # Optional observer fn(stages_dict) — multiverso_tpu.latency
        # wires this to the metrics registry (lat.stage.* histograms);
        # kept as a plain callable so this module stays stdlib-only.
        self.stage_hook = None

    def _qos(self):
        """Per-request QoS stamp: (class id, remaining budget ns) from
        this client's declared class + socket timeout; None when no
        class was declared (the pre-13 frame)."""
        if self.qos_class is None:
            return None
        budget = self.timeout if self.timeout else 0.0
        return (self.qos_class, int(budget * 1e9))

    # ------------------------------------------------------------- low level
    def send_raw(self, data: bytes) -> None:
        self.sock.sendall(data)

    def recv_reply(self) -> dict:
        """Block until one full reply frame arrives."""
        while True:
            frame = self._decoder.next_frame()
            if frame is not None:
                reply = unpack_frame(frame)
                if reply["timing"]:
                    self._attribute(reply["timing"])
                return reply
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("server closed the connection")
            self._decoder.feed(chunk)

    def _attribute(self, trail) -> None:
        now = time.monotonic_ns()
        sample = ntp_sample(trail, now)
        if sample is not None:
            self.offset.update(*sample)
        self.last_stages = stage_durations(trail, now,
                                           self.offset.offset_ns)
        hook = self.stage_hook
        if hook is not None:
            hook(self.last_stages)

    # ------------------------------------------------------------ serve ops
    def table_version(self, table_id: int) -> int:
        """Header-only version probe (RequestVersion): returns the
        contacted shard's current table version; a shed raises
        :class:`ServeBusy`."""
        mid = self._next_id()
        self.send_raw(pack_frame(MSG["RequestVersion"], table_id, mid,
                                 timing=self.timing, qos=self._qos()))
        reply = self.recv_reply()
        _check(reply, mid, "ReplyVersion")
        return reply["version"]

    def ops_report(self, kind: str = "health", scope: int = 0) -> str:
        """In-band introspection scrape (OpsQuery): returns the report
        text — Prometheus exposition for ``kind="metrics"`` (exemplar
        trace ids included), JSON for ``health``/``tables``.  With
        ``scope=OPS_SCOPE_FLEET`` the contacted rank fans out to every
        peer under a bounded deadline and merges, labeling series per
        rank and explicitly marking silent ranks."""
        mid = self._next_id()
        self.send_raw(pack_frame(MSG["OpsQuery"], -1, mid, version=scope,
                                 blobs=[kind.encode()],
                                 timing=self.timing, qos=self._qos()))
        reply = self.recv_reply()
        _check(reply, mid, "OpsReply")
        return reply["blobs"][0].decode() if reply["blobs"] else ""

    def get_shard(self, table_id: int) -> np.ndarray:
        """Fetch the contacted rank's shard of an array table as
        float32 (RequestGet; the payload is the shard, not the whole
        table — shards partition contiguously across server ranks).

        Returns a READ-ONLY zero-copy view over the reply bytes
        (``frombuffer`` of immutable ``bytes`` is non-writeable by
        construction) — the old trailing ``.copy()`` paid a full
        payload copy per fetch that cache layers then re-copied
        (docs/host_bridge.md).  Callers that need to mutate copy at
        their own boundary."""
        mid = self._next_id()
        self.send_raw(pack_frame(MSG["RequestGet"], table_id, mid,
                                 timing=self.timing, qos=self._qos()))
        reply = self.recv_reply()
        _check(reply, mid, "ReplyGet")
        return np.frombuffer(reply["blobs"][0], dtype=np.float32)

    def get_rows(self, table_id: int, row_ids, cols: int,
                 shard: int = -1) -> np.ndarray:
        """Row-subset read of a matrix table (RequestGet with an int32
        GLOBAL-row-id blob, the same request shape rank workers send):
        the contacted shard answers its rows in request order —
        mis-routed/out-of-range ids read as zeros, so callers aim at
        the shard that owns their rows.  ``shard`` stamps the shard
        hint (docs/replication.md): required when reading a BACKUP or
        promoted shard, whose host rank serves two shards of the
        table.  Returns a read-only ``(k, cols)`` float32 view over
        the reply bytes."""
        ids = np.ascontiguousarray(row_ids, dtype=np.int32)
        mid = self._next_id()
        self.send_raw(pack_frame(MSG["RequestGet"], table_id, mid,
                                 blobs=[ids.tobytes()],
                                 timing=self.timing, qos=self._qos(),
                                 shard=shard))
        reply = self.recv_reply()
        _check(reply, mid, "ReplyGet")
        out = np.frombuffer(reply["blobs"][0], dtype=np.float32)
        return out.reshape(ids.size, cols) if ids.size else out

    def cancel(self, table_id: int, msg_id: int) -> None:
        """Fire-and-forget hedge-cancel token (docs/serving.md "tail"):
        tell the server this connection no longer wants ``msg_id``'s
        answer.  Consumed at the reactor — if the read is still parked
        in the actor mailbox it is dropped at dequeue
        (serve.hedge.cancelled) instead of burning an apply slot.  No
        reply ever comes back (the caller must NOT wait for one)."""
        self.send_raw(pack_frame(MSG["RequestCancel"], table_id, msg_id))

    def get_replica(self, table_id: int) -> dict:
        """Hot-key replica pull (RequestReplica, docs/embedding.md):
        the contacted shard pushes its current SpaceSaving top-K rows.
        Returns ``{row_id: (version, row)}`` with read-only float32
        rows plus the shard version under key ``"_version"`` — the
        client-side hot-row side table to consult before paying a
        ``RequestGet``.  Empty when the shard's tracker is cold or
        ``-hotkey_enabled=false``."""
        mid = self._next_id()
        self.send_raw(pack_frame(MSG["RequestReplica"], table_id, mid,
                                 timing=self.timing, qos=self._qos()))
        reply = self.recv_reply()
        _check(reply, mid, "ReplyReplica")
        out: dict = {"_version": reply["version"]}
        if len(reply["blobs"]) < 3:
            return out
        ids = np.frombuffer(reply["blobs"][0], dtype=np.int32)
        vers = np.frombuffer(reply["blobs"][1], dtype=np.int64)
        rows = np.frombuffer(reply["blobs"][2], dtype=np.float32)
        if ids.size == 0 or rows.size % ids.size != 0:
            return out
        cols = rows.size // ids.size
        rows = rows.reshape(ids.size, cols)
        for i, rid in enumerate(ids.tolist()):
            out[rid] = (int(vers[i]), rows[i])
        return out

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _next_id(self) -> int:
        self._msg_id += 1
        return self._msg_id


class ServeBusy(RuntimeError):
    """The server (or the reactor's per-client admission gate) shed the
    request with ReplyBusy — retryable after backoff."""


def _check(reply: dict, msg_id: int, want: str) -> None:
    if reply["type"] == MSG["ReplyBusy"]:
        raise ServeBusy(f"request {msg_id} shed (ReplyBusy)")
    if reply["type_name"] != want or reply["msg_id"] != msg_id:
        raise ConnectionError(
            f"unexpected reply {reply['type_name']} (msg_id "
            f"{reply['msg_id']}, wanted {want}/{msg_id})")


# A length prefix outside (0, _MAX_FRAME_BYTES] is stream desync or
# corruption, never a legitimate reply — the bound mirrors the server's
# own rank frame cap (mvtpu's bad-frame-length close), far above any
# reply a serve client can receive.
_MAX_FRAME_BYTES = 1 << 40


class FrameDecoder:
    """Incremental frame reassembly for nonblocking herds: ``feed()``
    received bytes, ``next_frame()`` yields complete frame bodies.

    A corrupt length prefix raises :class:`ConnectionError` — treating
    it as "need more bytes" would buffer a desynced stream forever and
    hang the caller silently."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> None:
        self._buf += data

    def next_frame(self) -> Optional[bytes]:
        if len(self._buf) < _LEN.size:
            return None
        (flen,) = _LEN.unpack_from(self._buf, 0)
        if flen <= 0 or flen > _MAX_FRAME_BYTES:
            raise ConnectionError(
                f"bad frame length {flen}: stream desynced or corrupt")
        end = _LEN.size + flen
        if len(self._buf) < end:
            return None
        frame = bytes(self._buf[_LEN.size:end])
        del self._buf[:end]
        return frame
