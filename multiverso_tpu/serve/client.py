"""ServeClient — the hot-path read client over the native wire plane
(docs/serving.md).

Wraps a :class:`~multiverso_tpu.native.NativeRuntime` with the three
serve-layer mechanisms so concurrent readers stop paying one full wire
round trip per ``get()``:

1. **Coalescing** — concurrent/window-adjacent gets on the same table
   merge into one wire round trip (``-coalesce_window_us``, size-capped
   by ``-serve_max_batch``); row gets union their ids; adds aggregate
   into one delta per AddOption.
2. **Versioned cache** — a bounded LRU serves repeat reads locally while
   ``cached_version >= server_version - max_staleness``.  Knowledge of
   the server version comes free from reply stamps
   (``NativeRuntime.last_version``), stays trusted for
   ``-version_lease_ms``, and is refreshed past the lease by a cheap
   header-only probe (``MV_TableVersion``) instead of a full fetch.
   ``max_staleness=0`` + ``lease_ms=0`` never serves a stale read —
   every cached read pays one probe (still far cheaper than the fetch).
3. **Busy retry** — a server shedding under ``-server_inflight_max``
   raises :class:`~multiverso_tpu.native.BusyError`; the client's
   :class:`~multiverso_tpu.fault.RetryPolicy` backs off and retries
   (PR 2's schedule; ``retry.attempts`` counts in the registry).

Chaos seams (tests/test_serve.py): ``fault.inject("serve.busy")`` fires
inside the wire path — configure it with ``error=BusyError`` to script
shed storms; ``fault.inject("serve.stale")`` fires at the hit decision
and forces that read to miss.
"""

from __future__ import annotations

import time
from typing import Any, Optional, Sequence

import numpy as np

from .. import config, fault, metrics, tracing
from ..native import BusyError
from .cache import VersionedLRUCache
from .coalescer import Coalescer

__all__ = ["ServeClient"]


def _flag(value, name):
    return config.get(name) if value is None else value


class ServeClient:
    """Read-optimized facade over a NativeRuntime (one per process).

    All knobs default to the config flags so launch scripts tune the
    serve layer the same way they tune the wire (``-coalesce_window_us``
    etc.).  ``max_staleness`` is a VERSION distance: how many server-side
    applies a served read may be behind (0 = reads are never stale).
    """

    def __init__(self, rt: Any, *,
                 max_staleness: Optional[int] = None,
                 cache_entries: Optional[int] = None,
                 window_us: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 lease_ms: Optional[float] = None,
                 row_cache: Optional[bool] = None,
                 retry: Optional[fault.RetryPolicy] = None,
                 hedge=None):
        self.rt = rt
        # Tail-at-scale hedging (docs/serving.md "tail"): an optional
        # serve.hedge.HedgedReader the row-cache MISS path fetches
        # through instead of the runtime — past the p95-derived delay
        # the read re-issues against the reactor-served hot-key replica
        # and the loser is cancelled.  Single-shard scope: the reader
        # targets one endpoint, so arm it only when that shard owns the
        # rows this client reads (the DLRM serve shape).
        self.hedge = hedge
        self.max_staleness = int(_flag(max_staleness, "max_staleness"))
        entries = int(_flag(cache_entries, "serve_cache_entries"))
        self.cache = VersionedLRUCache(max(entries, 1))
        self._cache_on = entries > 0
        # Row-granular entries for matrix row / KV key reads
        # (docs/embedding.md): each id is its own versioned entry, so a
        # hot row hits across different requested id sets and a miss
        # wire-fetches only the missing ids.  -serve_row_cache=false
        # reverts to the PR 4 whole-id-set entries.
        self._row_cache = bool(_flag(row_cache, "serve_row_cache"))
        self.coalescer = Coalescer(
            window_s=float(_flag(window_us, "coalesce_window_us")) * 1e-6,
            max_batch=int(_flag(max_batch, "serve_max_batch")))
        self.lease_s = float(_flag(lease_ms, "version_lease_ms")) * 1e-3
        self.retry = retry or fault.RetryPolicy(
            attempts=6, backoff_s=0.01, max_backoff_s=0.5,
            retry_on=(BusyError,))
        # Version-knowledge lease per handle: (version, monotonic ts).
        # Bounded by the process's table-handle count, not by data.
        self._known: dict = {}  # mvlint: MV007-exempt(one entry per table handle)
        # Fleet routing epoch last observed (docs/replication.md):
        # re-checked before every cached read — a promotion/join flip
        # voids cached entries and version leases, whose stamps came
        # from a shard owner that may no longer serve.
        self._route_epoch = 0

    def _check_routing_epoch(self) -> None:
        """Re-check the fleet routing epoch before serving from cache
        (docs/replication.md): cached values and version leases were
        stamped under the PREVIOUS shard→rank map; after a promotion
        or join flip they must be dropped and re-resolved against the
        new owner, never served on the stale route."""
        try:
            epoch = int(self.rt.routing_epoch())
        except Exception:
            return  # pre-replication runtime / stub: epoch-less
        if epoch == self._route_epoch:
            return
        self._route_epoch = epoch
        self.cache.invalidate()
        self._known.clear()
        metrics.counter("serve.route_flip").inc()

    # ------------------------------------------------ version knowledge
    def _note(self, handle: int) -> None:
        """Fold the latest reply stamp into the lease (free, no wire)."""
        v = self.rt.last_version(handle)
        old = self._known.get(handle)
        if old is None or v > old[0]:
            self._known[handle] = (v, time.monotonic())

    def _server_version(self, handle: int) -> int:
        """Best-known server version, probing past the lease.

        Within ``-version_lease_ms`` of the last observation the cached
        knowledge is trusted (zero wire traffic — the demo's repeat-read
        path); beyond it, one header-only RequestVersion round trip
        refreshes it (``serve.probe`` counts them).
        """
        known = self._known.get(handle)
        if known is not None and self.lease_s > 0 and \
                time.monotonic() - known[1] <= self.lease_s:
            return known[0]
        metrics.counter("serve.probe").inc()
        v = self.retry.run(self.rt.table_version, handle)
        self._known[handle] = (v, time.monotonic())
        return v

    def _read_version(self, handle: int) -> Optional[int]:
        """Server-version estimate gating THIS read (None = cache off).

        Doubles as the cache stamp for the value a miss fetches: the
        fetch runs AFTER this estimate, so the data is at least this
        new — stamping with a post-fetch ``last_version`` instead could
        over-stamp (a concurrent add's ack landing between fetch and
        stamp would mark pre-add data post-add fresh)."""
        self._check_routing_epoch()
        if not self._cache_on:
            return None
        return self._server_version(handle)

    @staticmethod
    def _forced_stale() -> bool:
        """``serve.stale`` chaos seam: an injected fault forces this
        read to miss (scriptable staleness storms)."""
        try:
            fault.inject("serve.stale")
        except fault.FaultError:
            return True
        return False

    # ------------------------------------------------------------ reads
    def _cached(self, handle: int, key: tuple, fetch) -> np.ndarray:
        """Shared read path: cache -> coalesced fetch -> store."""
        v0 = self._read_version(handle)
        if v0 is not None:
            # Chaos misses count only with the cache armed — a disabled
            # cache (serve_cache_entries=0) must not accrue miss stats.
            if self._forced_stale():
                metrics.counter("serve.cache.miss").inc()
            else:
                hit = self.cache.lookup(key,
                                        min_version=v0 - self.max_staleness)
                if hit is not None:
                    return hit[0].copy()

        def execute(items):
            def wire():
                fault.inject("serve.busy")
                return fetch()
            out = self.retry.run(wire)
            # One wire value serves every coalesced waiter.
            return [out] * len(items)

        with tracing.span("serve::get", table=str(handle)):
            val = self.coalescer.submit(key, None, execute)
        self._note(handle)
        if v0 is not None:
            # Store the wire value ITSELF, read-only flagged: every
            # consumer (coalesced waiters below, future hits above)
            # copies exactly once at its own boundary, so the old
            # store-a-copy pair cost one redundant full-payload copy
            # per miss (docs/host_bridge.md).  The writeable=False flip
            # turns any aliasing slip into a loud ValueError instead of
            # silent cache corruption.
            val.flags.writeable = False
            self.cache.store(key, val, v0)
        # Per-caller copy: coalesced waiters all hold the SAME wire
        # ndarray — returned uncopied, one caller's in-place mutation
        # would corrupt every other waiter's result (the hit path above
        # already copies).
        return val.copy()

    def array_get(self, handle: int, size: int) -> np.ndarray:
        return self._cached(handle, (handle, "array", size),
                            lambda: self.rt.array_get(handle, size))

    def matrix_get_all(self, handle: int, rows: int, cols: int) -> np.ndarray:
        return self._cached(handle, (handle, "all", rows, cols),
                            lambda: self.rt.matrix_get_all(handle, rows,
                                                           cols))

    def matrix_get_rows(self, handle: int, row_ids: Sequence[int],
                        cols: int) -> np.ndarray:
        """Row-range read: concurrent callers' id sets UNION into one
        wire request; each gets back exactly its rows.

        With the cache armed the entries are ROW-GRANULAR
        (docs/embedding.md): each id caches individually under the same
        versioned staleness bound, so a hot row hits across different
        id sets and a partial miss wire-fetches only the missing rows.
        ``-serve_row_cache=false`` reverts to per-id-set entries."""
        ids = np.ascontiguousarray(row_ids, dtype=np.int32)
        v0 = self._read_version(handle)
        if v0 is not None and self._row_cache and ids.size:
            return self._get_rows_row_granular(handle, ids, cols, v0)
        key = (handle, "rows", tuple(ids.tolist()))
        if v0 is not None:
            if self._forced_stale():
                metrics.counter("serve.cache.miss").inc()
            else:
                hit = self.cache.lookup(key,
                                        min_version=v0 - self.max_staleness)
                if hit is not None:
                    return hit[0].copy()

        def execute(items):
            union = np.unique(np.concatenate(items))

            def wire():
                fault.inject("serve.busy")
                return self.rt.matrix_get_rows(handle, union, cols)
            fetched = self.retry.run(wire)
            # Scatter each waiter its own rows (union is sorted).
            return [fetched[np.searchsorted(union, it)] for it in items]

        with tracing.span("serve::get_rows", table=str(handle),
                          k=int(ids.size)):
            val = self.coalescer.submit((handle, "rows"), ids, execute)
        self._note(handle)
        if v0 is not None:
            self.cache.store(key, val.copy(), v0)
        return val

    def _get_rows_row_granular(self, handle: int, ids: np.ndarray,
                               cols: int, v0: int) -> np.ndarray:
        """Row-granular read tail: per-row lookups, one coalesced union
        wire fetch for the misses, per-row stores stamped with the
        PRE-fetch version estimate (the same conservative discipline as
        ``_cached``)."""
        forced = self._forced_stale()
        if forced:
            metrics.counter("serve.cache.miss").inc()
        id_list = ids.tolist()
        uniq = list(dict.fromkeys(id_list))  # order-preserving dedup
        hits: dict = {}
        missing = []
        if forced:
            missing = uniq
        else:
            # ONE lock + counter update for the whole id set — per-key
            # lookup() calls cost more than the wire fetch they save.
            got = self.cache.lookup_many(
                [(handle, "row", r) for r in uniq],
                v0 - self.max_staleness)
            for r, val in zip(uniq, got):
                if val is not None:
                    hits[r] = val
                else:
                    missing.append(r)
        if missing:
            miss = np.asarray(missing, np.int32)

            def execute(items):
                union = np.unique(np.concatenate(items))

                def wire():
                    fault.inject("serve.busy")
                    if self.hedge is not None:
                        # Hedged miss (docs/serving.md "tail"): the
                        # wire fetch races the hot-key replica past the
                        # hedge delay; serve.hedge.{issued,won,wasted}
                        # count the outcome.
                        return self.hedge.get_rows(union)
                    return self.rt.matrix_get_rows(handle, union, cols)
                fetched = self.retry.run(wire)
                return [fetched[np.searchsorted(union, it)]
                        for it in items]

            with tracing.span("serve::get_rows", table=str(handle),
                              k=int(miss.size)):
                got = self.coalescer.submit((handle, "rows"), miss,
                                            execute)
            self._note(handle)
            for j, r in enumerate(missing):
                row = np.ascontiguousarray(got[j])
                # Read-only in the cache: one copy per consumer at its
                # own boundary (np.stack below), aliasing slips fail
                # loudly.
                row.flags.writeable = False
                self.cache.store((handle, "row", r), row, v0)
                hits[r] = row
        # Fresh caller-owned result assembled row by row out of the
        # read-only cached rows (np.empty + copyto beats np.stack's
        # sequence machinery ~2x on the 8-row hot path).
        out = np.empty((len(id_list), cols), np.float32)
        for j, r in enumerate(id_list):
            out[j] = hits[r]
        return out

    def kv_get(self, handle: int, keys) -> Any:
        """KV read (str or list of str).  Batch reads cache per KEY
        (docs/embedding.md) when the row cache is armed — a hot key
        hits across different key sets, a partial miss wire-fetches
        only the missing keys; ``-serve_row_cache=false`` reverts to
        per-key-set entries."""
        single = isinstance(keys, str)
        v0 = self._read_version(handle)
        if v0 is not None and self._row_cache and not single and keys:
            return self._kv_get_key_granular(handle, list(keys), v0)
        tup = (keys,) if single else tuple(keys)
        key = (handle, "kv", tup)
        if v0 is not None:
            if self._forced_stale():
                metrics.counter("serve.cache.miss").inc()
            else:
                hit = self.cache.lookup(key,
                                        min_version=v0 - self.max_staleness)
                if hit is not None:
                    out = hit[0]
                    return out if single else np.array(out, copy=True)

        def execute(items):
            def wire():
                fault.inject("serve.busy")
                return self.rt.kv_get(handle, keys)
            out = self.retry.run(wire)
            return [out] * len(items)

        with tracing.span("serve::kv_get", table=str(handle)):
            val = self.coalescer.submit(key, None, execute)
        self._note(handle)
        if v0 is not None:
            # Batch values are stored READ-ONLY and uncopied (the same
            # one-copy-per-miss discipline as _cached above); the
            # per-caller copy below is the single copy.
            if not single:
                val.flags.writeable = False
            self.cache.store(key, val, v0)
        # Single-key reads are python floats (immutable); batch reads are
        # one ndarray SHARED by every coalesced waiter — copy per caller.
        return val if single else np.array(val, copy=True)

    def _kv_get_key_granular(self, handle: int, keys: list,
                             v0: int) -> np.ndarray:
        """Per-key cached KV batch read: values are python floats
        (immutable — no copy discipline needed), missing keys fetch in
        one coalesced union wire request."""
        forced = self._forced_stale()
        if forced:
            metrics.counter("serve.cache.miss").inc()
        uniq = list(dict.fromkeys(keys))
        hits: dict = {}
        missing = []
        if forced:
            missing = uniq
        else:
            got = self.cache.lookup_many(
                [(handle, "kvkey", k) for k in uniq],
                v0 - self.max_staleness)
            for k, val in zip(uniq, got):
                if val is not None:
                    hits[k] = val
                else:
                    missing.append(k)
        if missing:
            def execute(items):
                union = []
                seen = set()
                for it in items:
                    for k in it:
                        if k not in seen:
                            seen.add(k)
                            union.append(k)

                def wire():
                    fault.inject("serve.busy")
                    return self.rt.kv_get(handle, union)
                fetched = self.retry.run(wire)
                lut = dict(zip(union, fetched))
                return [[lut[k] for k in it] for it in items]

            with tracing.span("serve::kv_get", table=str(handle),
                              k=len(missing)):
                got = self.coalescer.submit((handle, "kv"), missing,
                                            execute)
            self._note(handle)
            for k, v in zip(missing, got):
                v = float(v)
                self.cache.store((handle, "kvkey", k), v, v0)
                hits[k] = v
        return np.asarray([hits[k] for k in keys], np.float32)

    # ----------------------------------------------------------- writes
    def array_add(self, handle: int, delta, *, coalesce: bool = True,
                  sync: bool = True) -> None:
        """Write path: deltas queued inside one coalescing window merge
        into ONE aggregated wire add (sum — the linear-composition
        contract every BSP flush in this repo already relies on), then
        every cached read of the table is invalidated (write-through).
        """
        # Legitimate copy (MV012 exempt by hoisting): callers hand this
        # façade arbitrary dtypes/layouts, and the coalescer may SUM the
        # buffer with siblings — it must own a normalized copy.  Hot
        # loops that control their buffers use the arena/borrowed path
        # on NativeRuntime directly (docs/host_bridge.md).
        d = np.ascontiguousarray(delta, dtype=np.float32)
        if not coalesce:
            self.retry.run(self.rt.array_add, handle, d, sync=sync)
        else:
            def execute(items):
                agg = items[0] if len(items) == 1 else np.sum(items, axis=0)

                def wire():
                    fault.inject("serve.busy")
                    self.rt.array_add(handle, agg, sync=sync)
                self.retry.run(wire)
                metrics.counter("serve.coalesce.adds").inc(len(items))
                return [None] * len(items)

            with tracing.span("serve::add", table=str(handle)):
                self.coalescer.submit((handle, "add"), d, execute)
        self.invalidate(handle)
        if sync:
            self._note(handle)  # the ack stamped the post-apply version

    def matrix_add_rows(self, handle: int, row_ids, delta, *,
                        sync: bool = True) -> None:
        self.retry.run(self.rt.matrix_add_rows, handle, row_ids, delta,
                       sync=sync)
        self.invalidate(handle)
        if sync:
            self._note(handle)

    def kv_add(self, handle: int, keys, deltas, *, sync: bool = True) -> None:
        self.retry.run(self.rt.kv_add, handle, keys, deltas, sync=sync)
        self.invalidate(handle)
        if sync:
            self._note(handle)

    # ------------------------------------------------------------ admin
    def invalidate(self, handle: Optional[int] = None) -> int:
        """Write-through invalidation: drop this handle's cached reads
        (all handles when None) and void the version lease so the next
        read re-learns the server version."""
        if handle is None:
            self._known.clear()
        else:
            self._known.pop(handle, None)
        return self.cache.invalidate(handle)

    def stats(self) -> dict:
        s = self.cache.stats()
        s["probes"] = int(metrics.counter("serve.probe").value)
        s["retries"] = int(metrics.counter("retry.attempts").value)
        h = metrics.histogram("serve.coalesce.batch")
        s["coalesced_batches"] = h.count
        s["coalesce_batch_p95"] = h.quantile(0.95)
        return s

    def replica_stats(self, handle: int) -> dict:
        """Native hot-key replica ledger for one matrix table
        (docs/embedding.md): rows this process's worker stub served
        from the replica vs sent to the wire, plus the co-located
        shard's push count.  ``{}`` when the runtime has no replica
        surface (stub runtimes in tests)."""
        fn = getattr(self.rt, "replica_stats", None)
        if fn is None:
            return {}
        return fn(handle)
