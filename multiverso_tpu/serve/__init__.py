"""Hot-path serve layer (docs/serving.md).

The reference Multiverso design is a pure push-pull TRAINING fabric:
every worker ``Get()`` is a synchronous whole-table fetch and the server
actor processes one message at a time.  This subsystem converts that
fabric into something that can take READ traffic — three cooperating
parts in the tradition of parameter-server client caches:

- :class:`~multiverso_tpu.serve.coalescer.Coalescer` — a worker-side
  batcher merging concurrent/adjacent reads (and adds) on one table
  into a single wire round trip (PS-Lite-style request batching),
  windowed by ``-coalesce_window_us`` and capped by ``-serve_max_batch``.
- :class:`~multiverso_tpu.serve.cache.VersionedLRUCache` — a bounded
  client cache serving repeat reads locally while
  ``cached_version >= server_version - max_staleness`` (SSPTable-style
  bounded-staleness reads over the wire plane's monotonic per-table /
  per-bucket version stamps).
- :class:`~multiverso_tpu.serve.client.ServeClient` — the facade wiring
  both over a :class:`~multiverso_tpu.native.NativeRuntime`, plus
  busy-retry against ``-server_inflight_max`` backpressure sheds
  (``BusyError`` → ``fault.RetryPolicy`` backoff).
- :class:`~multiverso_tpu.serve.wire.AnonServeClient` — a pure-socket
  ANONYMOUS client speaking the serve protocol (RequestVersion /
  RequestGet / ReplyBusy) straight to a server rank's epoll reactor:
  no rank, no native library — the external-read-tier entry point
  (docs/transport.md).  Declares a tenant QoS class + deadline budget
  per request (docs/serving.md "tail").
- :class:`~multiverso_tpu.serve.hedge.HedgedReader` — tail-at-scale
  hedged row reads over two anonymous connections: past a p95-derived
  delay the read re-issues against the reactor-served hot-key replica
  (or a second connection), first answer wins, the loser is cancelled
  with a RequestCancel token (docs/serving.md "tail").

The JAX-plane tables wear the same cache/coalescer directly (see
``tables/base.py``: ``-serve_cache_entries`` arms it); there the
"server version" is the table's local apply counter, which advances in
lockstep across ranks, so cached reads stay collective-safe.
"""

from __future__ import annotations

from .cache import VersionedLRUCache
from .client import ServeClient
from .coalescer import Coalescer
from .hedge import HedgedReader, LatencyTracker
from .wire import AnonServeClient, FrameDecoder, ServeBusy

__all__ = ["AnonServeClient", "Coalescer", "FrameDecoder", "HedgedReader",
           "LatencyTracker", "ServeBusy", "ServeClient",
           "VersionedLRUCache"]
