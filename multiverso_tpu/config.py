"""Configuration / flag system.

TPU-native re-design of the reference's GFlags-like macro system
(reference: include/multiverso/util/configure.h, src/util/configure.cpp —
``MV_DEFINE_bool/int/string/double`` + ``ParseCMDFlags``; see SURVEY.md §2.20).

Flags keep the reference's names (``sync``, ``updater_type``, ``machine_file``,
``port``, ``backup_worker_ratio``) so launch scripts port unchanged, and the
same ``-name=value`` argv syntax is accepted (plus ``--name=value``).

Instead of C macros registering globals, flags live in a single registry that
both the Python runtime and the native C layer read.  ``machine_file`` is
accepted for CLI compatibility but is a no-op under single-controller SPMD
(documented in SURVEY.md §2.9-bis).  ``backup_worker_ratio`` is likewise a
no-op on the SPMD plane (collectives are lockstep — there is no straggler to
slack), but on the NATIVE wire plane it is real: the sync server releases
clock t once ceil((1-ratio)·workers) ticks arrive (``native/src/zoo.cc``
``HeldBySspLocked``; late adds fold into the open clock).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "define_bool",
    "define_int",
    "define_double",
    "define_string",
    "get",
    "set_flag",
    "parse_cmd_flags",
    "reset",
    "all_flags",
]


@dataclass
class _Flag:
    name: str
    default: Any
    parser: Callable[[str], Any]
    help: str
    value: Any = None

    def __post_init__(self) -> None:
        self.value = self.default


_LOCK = threading.RLock()
_REGISTRY: Dict[str, _Flag] = {}


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


def _define(name: str, default: Any, parser: Callable[[str], Any], help: str) -> None:
    with _LOCK:
        if name in _REGISTRY:
            # Re-definition keeps the first registration (matches the
            # reference's CHECK on duplicate flags but tolerates re-import).
            return
        _REGISTRY[name] = _Flag(name, default, parser, help)


def define_bool(name: str, default: bool, help: str = "") -> None:
    _define(name, default, _parse_bool, help)


def define_int(name: str, default: int, help: str = "") -> None:
    _define(name, default, int, help)


def define_double(name: str, default: float, help: str = "") -> None:
    _define(name, default, float, help)


def define_string(name: str, default: str, help: str = "") -> None:
    _define(name, default, str, help)


def get(name: str) -> Any:
    with _LOCK:
        if name not in _REGISTRY:
            raise KeyError(f"unknown flag: {name}")
        return _REGISTRY[name].value


def set_flag(name: str, value: Any) -> None:
    with _LOCK:
        if name not in _REGISTRY:
            raise KeyError(f"unknown flag: {name}")
        flag = _REGISTRY[name]
        if isinstance(value, str):
            flag.value = flag.parser(value)
        else:
            flag.value = value


def parse_cmd_flags(argv: Optional[List[str]] = None) -> List[str]:
    """Parse ``-name=value`` / ``--name=value`` args; return the leftovers.

    Unknown flags are left in the returned remainder rather than raising,
    mirroring the reference parser which skips unknown argv entries.
    """
    if argv is None:
        argv = []
    rest: List[str] = []
    for arg in argv:
        body = None
        if arg.startswith("--"):
            body = arg[2:]
        elif arg.startswith("-"):
            body = arg[1:]
        if body and "=" in body:
            name, _, val = body.partition("=")
            with _LOCK:
                if name in _REGISTRY:
                    flag = _REGISTRY[name]
                    flag.value = flag.parser(val)
                    continue
        rest.append(arg)
    return rest


def reset() -> None:
    """Reset every flag to its default (test isolation helper)."""
    with _LOCK:
        for flag in _REGISTRY.values():
            flag.value = flag.default


def all_flags() -> Dict[str, Any]:
    with _LOCK:
        return {name: f.value for name, f in _REGISTRY.items()}


# ---------------------------------------------------------------------------
# Core flags — names match the reference CLI (SURVEY.md §2.20).
# Contract-checked: tools/mvcontract.py (`make contract`) diffs these
# registrations against configure.cc and the docs/*.md flag tables —
# a flag shared with the native plane must keep the same default.
# ---------------------------------------------------------------------------

define_bool("sync", False, "BSP (True) vs ASP (False) training semantics")
define_string("updater_type", "default",
              "server-side updater: default|sgd|adagrad|momentum|smooth_gradient")
define_string("machine_file", "", "accepted for CLI parity; unused on TPU mesh")
define_int("port", 55555, "accepted for CLI parity; unused on TPU mesh")
define_double("backup_worker_ratio", 0.0,
              "straggler slack; N/A under SPMD lockstep — real on the "
              "native wire plane (quorum clock release, zoo.cc)")
define_string("log_level", os.environ.get("MVTPU_LOG_LEVEL", "info"),
              "debug|info|error|fatal")
define_string("log_file", "", "optional log file sink")
define_string("checkpoint_dir", "", "directory for table checkpoints")
define_int("checkpoint_interval", 0,
           "clocks between automatic checkpoints (0 = disabled)")
define_int("barrier_timeout_ms", 0,
           "host_sync/barrier deadline: an unresponsive peer raises "
           "BarrierTimeout instead of hanging; <=0 (default) waits "
           "forever (native-flag parity)")
define_int("ckpt_keep", 3,
           "snapshots CheckpointManager retains behind its MANIFEST")
define_int("metrics_flush_ms", 0,
           "periodic metrics export interval: every interval the registry "
           "renders to <trace_dir>/metrics_rank<r>.prom (Prometheus text; "
           "debug log when no trace_dir); 0 (default) disables "
           "(docs/observability.md)")
define_string("trace_dir", "",
              "arm span tracing and write trace_rank<r>.json (Chrome "
              "trace-event JSON, Perfetto-loadable) here at shutdown; "
              "merge ranks with tracing.merge_dir (docs/observability.md)")

# --- latency attribution (docs/observability.md "latency plane") -----------
define_bool("wire_timing", True,
            "stamp a timing trail into request/reply wire headers and "
            "fold replies into lat.stage.* histograms + per-peer clock "
            "offsets (native-flag parity; the Python serve clients "
            "stamp their own trails)")
define_int("profile_hz", 0,
           "arm the always-on sampling profiler at this rate: the "
           "native SIGPROF sampler (native-flag parity) plus the "
           "Python sampler thread (multiverso_tpu/profiler.py), whose "
           "folded stacks land in trace_rank<r>.json beside spans at "
           "shutdown.  0 (default) disarms; 97 is the house rate")

# --- health plane (docs/observability.md "health plane") -------------------
define_int("metrics_history", 64,
           "time-series ring depth: how many flush snapshots each "
           "series keeps for rate()/delta()/alert-window queries.  The "
           "ring spans ~metrics_flush_ms x metrics_history of wall "
           "time; health-rule window_s / for_s beyond that can never "
           "fire (docs/observability.md)")
define_bool("health_rules", True,
            "arm the built-in SLO/alert rule pack (health.py) when the "
            "metrics flusher runs: rules evaluate each flush, firing "
            "alerts land in health.alerts.firing{severity=}, emit "
            "flight-recorder events, and criticals boost the profiler "
            "+ trigger a blackbox dump; the 'alerts' OpsQuery kind "
            "serves the state fleet-wide (tools/mvtop.py --alerts)")
define_double("health_latency_slo_ms", 250.0,
              "end-to-end latency SLO threshold: serve round-trips "
              "slower than this count against the lat.slo.breach "
              "error budget the burn-rate rule watches; <=0 disables "
              "the breach counters")
define_int("watchdog_stall_ms", 0,
           "native stall watchdog: flag a critical loop (epoll "
           "reactor shards, actors, heartbeat/lease scan, Python "
           "metrics flusher) that makes zero progress for this long "
           "while work is queued — dumps profiler folded stacks + a "
           "'stall:' blackbox and bumps watchdog.stalls.  0 (default) "
           "disarms; must exceed the slowest legitimate loop period "
           "(native-flag parity)")

# --- delivery audit (docs/observability.md "audit plane") ------------------
define_bool("audit", True,
            "delivery-audit plane: stamp every native-plane Add with a "
            "per-(worker, table, shard) seq range, keep acked-add "
            "ledgers + applied watermarks, and serve the 'audit' "
            "OpsQuery kind (native-flag parity; tools/mvaudit.py diffs "
            "the books fleet-wide)")
define_int("audit_grace_ms", 2000,
           "delivery-audit gap grace window before the audit_gap "
           "flight-recorder trigger fires (native-flag parity)")
define_int("audit_ring", 64,
           "delivery-audit anomaly ring capacity per server table "
           "(native-flag parity)")

# --- shard replication + failover (docs/replication.md) --------------------
define_int("replication_factor", 0,
           "shard replication: 0 = off (a dead server rank is fatal "
           "for its shard); 1 = every shard gets a backup rank "
           "(chained: shard i's backup is server i+1 mod n) fed by a "
           "primary->backup delta stream, with lease-triggered "
           "promotion and routing-epoch re-pointing "
           "(native-flag parity)")
define_bool("repl_sync", True,
            "sync replication: park the client's add ack until the "
            "backup confirmed the forwarded apply — 'acked' means "
            "applied on BOTH replicas, zero lost acked adds across a "
            "failover by construction (native-flag parity)")
define_int("repl_lag_max", 64,
           "async replication lag bound (-repl_sync=false): stall the "
           "apply path while this many forwards are unacked by the "
           "backup; measured by the repl.lag histogram "
           "(native-flag parity)")
define_bool("promote_auto", True,
            "lease-triggered promotion: a backup whose primary's "
            "heartbeat lease expires promotes automatically; false = "
            "operator-driven only (native-flag parity)")
define_int("blackbox_keep", 4,
           "flight-recorder dump rotation: timestamped "
           "blackbox_rank<r>.<ts>.<n>.json archives retained per rank "
           "beside the canonical latest dump, listed in "
           "blackbox_rank<r>.manifest.json (a second trigger no "
           "longer overwrites the first dump's evidence)")

# --- wire data plane (docs/wire_compression.md) ----------------------------
define_string("wire_codec", "raw",
              "payload codec for table wire traffic: raw|1bit|sparse. "
              "On the JAX plane, 1bit makes sign-bit+scales compression "
              "(error feedback) the default for host dense adds on "
              "float ASP tables (the explicit compress= kwarg still "
              "wins); on the native plane every new table negotiates "
              "this codec at creation (MV_SetTableCodec retargets one)")
define_int("add_agg_ms", 0,
           "native-plane add aggregation window (ms): async dense adds "
           "within the window sum worker-side and ship as ONE "
           "codec-encoded wire message; flushed by Get/Clock/Barrier/"
           "shutdown so BSP/SSP semantics hold (native-flag parity; the "
           "lockstep JAX plane has no per-add wire messages to collapse)")
define_int("add_agg_bytes", 0,
           "native-plane add aggregation size bound: flush once absorbed "
           "payload bytes reach this (native-flag parity)")

# --- serve layer (docs/serving.md) -----------------------------------------
define_int("serve_cache_entries", 0,
           "versioned client cache size (entries) for table reads; 0 "
           "(default) disables the serve cache — tables and ServeClient "
           "read this at construction")
define_int("max_staleness", 0,
           "serve-cache staleness bound in VERSIONS (server-side "
           "applies a served read may be behind); 0 = cached reads are "
           "never stale.  Distinct from the SSP -staleness clock bound "
           "(docs/serving.md maps the two)")
define_double("coalesce_window_us", 200.0,
              "request-coalescing window: concurrent/adjacent reads on "
              "one table arriving within this window merge into one "
              "wire round trip (0 = only truly concurrent calls merge)")
define_int("serve_max_batch", 64,
           "size cap per coalescing window — a full batch seals (and "
           "executes) early")
define_bool("serve_row_cache", True,
            "row-granular serve cache (docs/embedding.md): with the "
            "serve cache armed, Matrix/KV per-id reads cache INDIVIDUAL "
            "rows/keys gated by their bucket versions, so a hot row "
            "keeps hitting across different id sets and adds elsewhere. "
            "False falls back to the PR 4 whole-id-set entries.  "
            "Single-controller only either way — multi-host id reads "
            "bypass the cache (the fetch is a lockstep collective)")
# --- workload observability (docs/observability.md) ------------------------
define_bool("hotkey_enabled", True,
            "per-table workload accounting: hot-key sketches "
            "(space-saving top-K + count-min), per-bucket get/add load "
            "counters and the skew ratio they expose.  Native-flag "
            "parity: the server hot path carries the same switch; False "
            "reduces every hook to one boolean check")
define_int("hotkey_topk", 16,
           "capacity of the space-saving top-K hot-key sketch per table "
           "(memory bound; every key with frequency > total/K is "
           "guaranteed monitored)")
define_bool("hotkey_replica", False,
            "hot-key read replica (docs/embedding.md, native-flag "
            "parity): matrix worker stubs keep a side table of the "
            "servers' pushed SpaceSaving top-K rows and serve row gets "
            "from it before the wire; invalidation rides the "
            "version-stamp protocol")
define_double("replica_lease_ms", 50.0,
              "hot-key replica snapshot lease (native-flag parity): the "
              "pushed row set re-pulls once the snapshot ages past this")
define_int("replica_max_staleness", 0,
           "version distance a replica-served row may be behind the "
           "last observed apply (native-flag parity); 0 = a row older "
           "than any later observed add misses")

# --- capacity plane (docs/observability.md "capacity plane") ---------------
define_bool("capacity_enabled", True,
            "fleet capacity accounting (native-flag parity): per-table "
            "resident bytes per bucket/shard, arena + write-queue + "
            "registered byte gauges, and the bounded load-history ring "
            "behind the 'capacity' OpsQuery kind.  False reduces every "
            "hot-path growth hook to one relaxed atomic check "
            "(MV_SetCapacityTracking toggles live; re-arming resyncs)")
define_int("capacity_history_ms", 250,
           "minimum interval between capacity load-history windows "
           "(native-flag parity): each 'capacity' scrape at least this "
           "far from the last appends one (ts, gets, adds, bytes, "
           "per-bucket load) window to the bounded 64-window ring — "
           "one scrape then yields per-bucket load RATES, the "
           "placement advisor's input.  <= 0 records every scrape")

# --- tail-at-scale serve tier (docs/serving.md "tail") ---------------------
define_int("serve_timeout_ms", 30000,
           "AnonServeClient's default connect/read timeout — ONE source "
           "of truth for the serve deadline: the same budget is stamped "
           "into every request's QoS wire header (deadline propagation), "
           "so a server drops a read whose caller already gave up "
           "(serve.deadline.shed) instead of burning an apply slot")
define_string("qos_classes", "bulk:1,gold:8",
              "tenant classes + weights ('name:weight,...'; wire class "
              "ids are POSITIONAL indices into this list — native-flag "
              "parity).  Weights split -qos_inflight_max into per-class "
              "guaranteed read budgets at the reactor")
define_int("qos_inflight_max", 0,
           "per-class weighted admission over anonymous serve reads at "
           "the reactor (native-flag parity): a class at its share "
           "answers ReplyBusy while others keep flowing; adds are never "
           "shed.  0 (default) disables the gate")
define_string("qos_class", "bulk",
              "the tenant class this process's requests declare "
              "(native-flag parity; a name from -qos_classes)")
define_bool("wire_deadline", True,
            "deadline propagation (native-flag parity): stamp requests "
            "with their remaining timeout budget; receivers drop a read "
            "already past its deadline at dequeue.  Adds never shed")
define_double("hedge_min_us", 1000.0,
              "hedged-read delay floor: HedgedReader re-issues a read "
              "after max(observed p95, this) — hedging earlier than the "
              "tail re-issues healthy traffic for nothing "
              "(docs/serving.md \"tail\")")

define_double("version_lease_ms", 50.0,
              "how long a learned server version stays trusted before "
              "a cached read pays a header-only version probe; 0 = "
              "probe every cached read (never stale even at "
              "max_staleness=0, at one tiny round trip per read)")
