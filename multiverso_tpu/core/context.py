"""Core runtime context — the TPU-native successor of the reference ``Zoo``.

Reference semantics (SURVEY.md §2.2, §3.1): ``Zoo::Start`` parses flags,
initializes the transport (MPI/ZMQ), spawns the Communicator / Worker /
Server / Controller actor threads, registers every node with rank 0, and
barriers.  ``Zoo::Stop`` barriers, joins actors, dumps the Dashboard, and
finalizes the transport.

TPU-native redesign: there are no server processes and no point-to-point
transport.  Model state lives in sharded ``jax.Array``s over a
``jax.sharding.Mesh``; the push-pull message path compiles to XLA
collectives over ICI.  What remains on the host is the control plane:

- ``init()``      → flag parsing, optional ``jax.distributed.initialize``
                    (DCN, multi-host), mesh construction, table registry.
- ``barrier()``   → ``multihost_utils.sync_global_devices`` across hosts
                    (the Controller's Control_Barrier round-trip) + the BSP
                    clock tick that sync-mode tables key on.
- ``shutdown()``  → final barrier, Dashboard dump, registry teardown.

Identity mapping (kept name-compatible with the reference C API):

- a reference *worker process*  ↔ a controller **host process**
  (``worker_id() == jax.process_index()``): the unit that loads a data shard.
- a reference *server process*  ↔ the same host (every device holds table
  shards), so ``server_id() == worker_id()`` under Role.ALL, matching the
  reference's default role assignment.
- device-level data parallelism (the mesh's worker axis) is *inside* the
  compiled step; its width is exposed as ``num_replicas()``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from .. import config, dashboard, metrics, tracing
from ..log import Log

__all__ = [
    "Role", "Context", "BarrierTimeout", "init", "shutdown", "initialized",
    "barrier", "get_context", "worker_id", "workers_num", "server_id",
    "servers_num", "is_master_worker", "num_replicas", "clock",
]


class BarrierTimeout(TimeoutError):
    """A host rendezvous did not complete within its deadline.

    Raised instead of blocking forever when ``barrier()``/``host_sync``
    is given a timeout (kwarg or the ``barrier_timeout_ms`` flag) and a
    peer process never arrives — the SPMD-plane analog of the native
    runtime's ``-barrier_timeout_ms`` (C API rc ``-3``).  NOTE the
    underlying collective cannot be cancelled: the watcher thread stays
    parked in it, so treat this as fatal for the job (checkpoint and
    exit), not as something to retry.
    """


class Role:
    """Role bitmask — parity with reference ``node.h`` (SURVEY.md §2.5)."""

    NONE = 0
    WORKER = 1
    SERVER = 2
    ALL = 3


@dataclass
class Node:
    """Per-process node info (reference ``Node``; SURVEY.md §2.5)."""

    rank: int
    size: int
    role: int = Role.ALL

    @property
    def is_worker(self) -> bool:
        return bool(self.role & Role.WORKER)

    @property
    def is_server(self) -> bool:
        return bool(self.role & Role.SERVER)


class Context:
    """Singleton runtime registry (reference ``Zoo``; SURVEY.md §2.2)."""

    def __init__(self, mesh: jax.sharding.Mesh, node: Node, sync: bool,
                 updater_type: str):
        self.mesh = mesh
        self.node = node
        self.sync = sync
        self.updater_type = updater_type
        self.clock = 0
        self._tables: Dict[int, Any] = {}
        self._next_table_id = 0
        self._lock = threading.Lock()

    # -- table registry (Zoo::RegisterTable) --------------------------------
    def register_table(self, table: Any) -> int:
        with self._lock:
            tid = self._next_table_id
            self._next_table_id += 1
            self._tables[tid] = table
            return tid

    def unregister_table(self, table_id: int) -> None:
        with self._lock:
            self._tables.pop(table_id, None)

    def table(self, table_id: int) -> Any:
        return self._tables[table_id]

    def tables(self) -> List[Any]:
        return list(self._tables.values())

    # -- barrier / clock ----------------------------------------------------
    def host_sync(self, name: str,
                  timeout_s: Optional[float] = None) -> None:
        """Cross-host rendezvous WITHOUT the BSP clock tick / flush.

        For control-plane sync points (checkpointing) that must not apply
        pending sync-mode adds or advance the training clock.

        ``timeout_s`` (default: the ``barrier_timeout_ms`` flag; 0 =
        wait forever) bounds the wait: a peer that never arrives raises
        :class:`BarrierTimeout` naming the sync point instead of hanging
        the job.  The wait runs on a watcher thread because the
        underlying collective has no cancellation — on timeout that
        thread is abandoned (daemon) and the error documents the job as
        unrecoverable-but-diagnosable.
        """
        from .. import fault

        if timeout_s is None:
            ms = int(config.get("barrier_timeout_ms"))
            timeout_s = ms / 1e3 if ms > 0 else None

        def wait() -> None:
            # Chaos seam: the injector can delay (simulating a straggler
            # peer) or fail this rendezvous (tests/test_fault.py).
            fault.inject("barrier")
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices(name)

        if timeout_s is None:
            wait()
            return
        done = threading.Event()
        err: list = []

        def body() -> None:
            try:
                wait()
            except BaseException as exc:  # re-raised on the caller
                err.append(exc)
            finally:
                done.set()

        t = threading.Thread(target=body, name="mvtpu-host-sync",
                             daemon=True)
        t.start()
        if not done.wait(timeout_s):
            # Flight-recorder trigger (docs/observability.md): the
            # moment the job becomes unrecoverable is the moment the
            # black box must hit disk — before the raise unwinds.
            from ..ops.flight_recorder import recorder

            recorder.trigger(f"barrier_timeout: host_sync '{name}' "
                             f"after {timeout_s:.3f}s")
            raise BarrierTimeout(
                f"host_sync '{name}' timed out after {timeout_s:.3f}s "
                f"waiting for {jax.process_count()} process(es) — an "
                f"unresponsive peer; treat as fatal (the collective "
                f"cannot be cancelled)")
        if err:
            raise err[0]

    def barrier(self, name: Optional[str] = None,
                timeout_s: Optional[float] = None) -> None:
        with dashboard.monitor("Zoo::Barrier"):
            self.host_sync(name or f"mvtpu_barrier_{self.clock}",
                           timeout_s=timeout_s)
            self.clock += 1
            for t in self.tables():
                flush = getattr(t, "flush", None)
                if flush is not None:
                    flush()


_LOCK = threading.Lock()
_CONTEXT: Optional[Context] = None


def _default_mesh(axis_name: str = "worker") -> jax.sharding.Mesh:
    devices = np.asarray(jax.devices())
    return jax.sharding.Mesh(devices, (axis_name,))


def init(args: Optional[List[str]] = None,
         sync: Optional[bool] = None,
         updater_type: Optional[str] = None,
         mesh: Optional[jax.sharding.Mesh] = None,
         role: int = Role.ALL,
         distributed: bool = False,
         **distributed_kwargs) -> Context:
    """Start the runtime (reference ``MV_Init`` → ``Zoo::Start``; §3.1).

    ``args`` takes reference-style ``-flag=value`` argv.  Keyword arguments
    override parsed flags.  ``distributed=True`` calls
    ``jax.distributed.initialize`` for multi-host (DCN) jobs before building
    the mesh — the analog of the transport Init + rank-0 registration.
    """
    global _CONTEXT
    with _LOCK:
        if _CONTEXT is not None:
            Log.info("multiverso_tpu.init: already initialized; reusing context")
            return _CONTEXT

        # CLI args mutate the process-global flag registry (reference
        # semantics); keyword overrides are per-lifecycle only, so a
        # sync=True passed to one init() cannot leak into the next.
        config.parse_cmd_flags(args)
        sync_val = bool(config.get("sync")) if sync is None else bool(sync)
        updater_val = (str(config.get("updater_type"))
                       if updater_type is None else str(updater_type))

        from ..log import configure as log_configure

        log_configure(config.get("log_level"), config.get("log_file"))

        if distributed:
            # Multi-host bring-up (DCN): the reference's NetInterface::Init +
            # Control_Register handshake collapses into this one call. Must
            # run before anything touches the backend (so no process_count()
            # guard here); tolerate an environment that already initialized.
            try:
                jax.distributed.initialize(**distributed_kwargs)
            except RuntimeError as e:
                Log.info("jax.distributed.initialize skipped: %s", e)

        if mesh is None:
            mesh = _default_mesh()

        node = Node(rank=jax.process_index(), size=jax.process_count(),
                    role=role)

        # Observability (docs/observability.md): -trace_dir arms span
        # recording (shutdown writes trace_rank<r>.json there);
        # -metrics_flush_ms starts the periodic Prometheus exporter.
        # After the distributed bring-up so process_index() is final.
        trace_dir = str(config.get("trace_dir"))
        if trace_dir:
            tracing.enable(rank=node.rank)
        # Flight recorder (docs/observability.md): always-on bounded
        # ring; the rank pin names the blackbox_rank<r>.json dump a
        # failure trigger (BarrierTimeout, CheckpointCorrupt) writes.
        from ..ops.flight_recorder import recorder as _recorder

        _recorder.attach(rank=node.rank)
        _recorder.record("lifecycle",
                         f"init rank {node.rank}/{node.size}")
        # Latency plane (docs/observability.md): -profile_hz arms the
        # Python sampler thread; its folded stacks land in the trace
        # export at shutdown beside the spans.
        profile_hz = int(config.get("profile_hz"))
        if profile_hz > 0:
            from .. import profiler as _profiler

            _profiler.start(profile_hz)
        flush_ms = int(config.get("metrics_flush_ms"))
        metrics.set_history_depth(int(config.get("metrics_history")))
        if flush_ms > 0:
            import os

            metrics.start_flush(
                flush_ms,
                path=os.path.join(trace_dir,
                                  f"metrics_rank{node.rank}.prom")
                if trace_dir else None)
            # Health plane (docs/observability.md "health plane"):
            # -health_rules arms the default SLO/alert pack on the
            # flush cadence — rules can only evaluate when flushes
            # actually happen, so the gate rides flush_ms.
            if bool(config.get("health_rules")):
                from .. import health as _health

                _health.arm()

        _CONTEXT = Context(mesh=mesh, node=node,
                           sync=sync_val,
                           updater_type=updater_val)
        Log.info(
            "multiverso_tpu initialized: %d process(es), %d device(s), "
            "mesh axes %s, sync=%s, updater=%s",
            node.size, len(jax.devices()), dict(mesh.shape),
            _CONTEXT.sync, _CONTEXT.updater_type,
        )
        _CONTEXT.barrier("mvtpu_init")
        return _CONTEXT


def shutdown(finalize: bool = True) -> None:
    """Stop the runtime (reference ``MV_ShutDown`` → ``Zoo::Stop``; §3.5)."""
    global _CONTEXT
    with _LOCK:
        if _CONTEXT is None:
            return
        from ..ops.flight_recorder import recorder as _recorder

        _recorder.record("lifecycle",
                         f"shutdown rank {_CONTEXT.node.rank}")
        _CONTEXT.barrier("mvtpu_shutdown")
        # Observability teardown: health evaluator off BEFORE the final
        # flush (an alert must not fire against a half-torn-down rank),
        # then the last flush, then the span export (-trace_dir), then
        # the classic Dashboard dump — which now prints percentiles
        # from the same registry.
        from .. import health as _health

        _health.disarm()
        metrics.stop_flush()
        # Profiler down BEFORE the trace export so its folded stacks
        # ride trace_rank<r>.json (stop() folds them into the buffer).
        from .. import profiler as _profiler

        _profiler.stop(to_trace=True)
        trace_dir = str(config.get("trace_dir"))
        if trace_dir and tracing.enabled():
            import os

            os.makedirs(trace_dir, exist_ok=True)
            tracing.save(tracing.default_trace_path(trace_dir))
        dashboard.report(log=True)
        if finalize:
            dashboard.reset()
            tracing.clear()
        _CONTEXT = None


def initialized() -> bool:
    return _CONTEXT is not None


def get_context() -> Context:
    if _CONTEXT is None:
        raise RuntimeError(
            "multiverso_tpu is not initialized; call multiverso_tpu.init()")
    return _CONTEXT


def barrier(timeout_s: Optional[float] = None) -> None:
    get_context().barrier(timeout_s=timeout_s)


def clock() -> int:
    return get_context().clock


def worker_id() -> int:
    """Rank of this host's worker role (reference ``MV_WorkerId``)."""
    return get_context().node.rank


def workers_num() -> int:
    """Number of worker hosts (reference ``MV_NumWorkers``)."""
    return get_context().node.size


def server_id() -> int:
    """Under Role.ALL every host co-hosts server shards (``MV_ServerId``)."""
    node = get_context().node
    return node.rank if node.is_server else -1


def servers_num() -> int:
    return get_context().node.size


def is_master_worker() -> bool:
    return worker_id() == 0


def num_replicas() -> int:
    """Device-level data-parallel width inside the compiled step.

    The size of the mesh's data-parallel axis (named ``worker``, ``dp`` or
    ``data``); for a mesh with no such axis, the full device count (a pure
    model-parallel mesh has one replica per full model, but tables still
    shard over every device).
    """
    ctx = get_context()
    for axis in ("worker", "dp", "data"):
        if axis in ctx.mesh.shape:
            return int(ctx.mesh.shape[axis])
    return int(np.prod(list(ctx.mesh.shape.values())))
