"""Fault injection + retry/backoff — the Python half of the resilience
layer (docs/fault_tolerance.md; the native half is ``mvtpu/fault.h``).

Two pieces:

- :class:`RetryPolicy` — a reusable bounded-retry schedule
  (attempts / exponential backoff / jitter / deadline) for transient
  failures.  ``checkpoint.py`` wears it on every stream read/write; any
  caller can ``RetryPolicy(...).run(fn)``.
- The **fault injector** — a process-global seam the chaos suite
  (``tests/test_fault.py``) uses to script failures at named sites:
  ``io.read`` / ``io.write`` (Streams), ``table.<Op>`` (every eager
  table op), ``barrier`` (``context.host_sync``), and the serve layer
  (docs/serving.md): ``serve.busy`` fires inside the wire fetch
  (configure it with ``error=native.BusyError`` to script shed storms
  the RetryPolicy must absorb) and ``serve.stale`` fires at the
  cache-hit decision, forcing that read to miss.  Disabled (the
  default) :func:`inject` is a single bool check — zero behavior
  change, zero counters.  Deterministic under :func:`configure`'s seed
  (env: ``MVTPU_FAULT_SEED``).

Every injected event counts a metrics-registry counter
``fault.<site>``; every retry counts ``retry.attempts`` — the
observable ledger the acceptance tests (and ``metrics.snapshot()``)
read.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple, Type

from . import metrics
from .log import Log

__all__ = ["FaultError", "RetryPolicy", "configure", "inject", "reset",
           "is_enabled", "count"]


class FaultError(RuntimeError):
    """Raised by an injected failure; carries the site name."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at '{site}'")
        self.site = site


def _tick(name: str) -> None:
    """Count one hit on the named registry counter (the observable
    ledger: fault.<site> / retry.attempts in metrics.snapshot())."""
    metrics.counter(name).inc()


def count(name: str) -> int:
    """Current hit count of a fault/retry counter (0 if it never fired)."""
    return int(metrics.counter(name).value)


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

@dataclass
class RetryPolicy:
    """Bounded retry with exponential backoff and optional deadline.

    ``run(fn)`` calls ``fn`` up to ``attempts`` times, sleeping between
    failures per :meth:`delays`; exceptions outside ``retry_on`` (and
    the last failure) propagate.  A ``deadline_s`` caps the TOTAL wall
    time: a retry whose backoff would cross it re-raises immediately —
    bounded recovery, never a disguised hang.
    """

    attempts: int = 3
    backoff_s: float = 0.05
    multiplier: float = 2.0
    max_backoff_s: float = 2.0
    jitter: float = 0.1          # ± fraction of each delay
    deadline_s: Optional[float] = None
    retry_on: Tuple[Type[BaseException], ...] = (OSError,)
    seed: Optional[int] = None   # deterministic jitter for tests

    def delays(self):
        """The backoff schedule (``attempts - 1`` sleep durations)."""
        rng = random.Random(self.seed)
        d = self.backoff_s
        for _ in range(max(0, self.attempts - 1)):
            j = 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
            yield min(d, self.max_backoff_s) * j
            d *= self.multiplier

    def run(self, fn: Callable[..., Any], *args: Any,
            on_retry: Optional[Callable[[int, BaseException], None]] = None,
            **kwargs: Any) -> Any:
        start = time.monotonic()
        delays = list(self.delays())
        for i in range(self.attempts):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as exc:
                if i == self.attempts - 1:
                    raise
                delay = delays[i]
                if (self.deadline_s is not None
                        and time.monotonic() + delay - start
                        > self.deadline_s):
                    raise
                _tick("retry.attempts")
                Log.info("retry %d/%d after %s: %s (backoff %.0f ms)",
                         i + 1, self.attempts - 1, type(exc).__name__, exc,
                         delay * 1e3)
                if on_retry is not None:
                    on_retry(i, exc)
                time.sleep(delay)
        raise AssertionError("unreachable")  # pragma: no cover


# ---------------------------------------------------------------------------
# Fault injector
# ---------------------------------------------------------------------------

@dataclass
class _Site:
    rate: float = 0.0            # probability per op
    times: int = 0               # deterministic: fire on the next n ops
    delay_s: float = 0.0         # sleep instead of raising when > 0
    error: Type[BaseException] = FaultError


_LOCK = threading.Lock()
_SITES: Dict[str, _Site] = {}
_RNG = random.Random(0)
# Module-level fast-path gate — inject() must cost one attribute load +
# bool check on every hot-path call when chaos is off.
_ENABLED = False


def is_enabled() -> bool:
    return _ENABLED


def configure(seed: Optional[int] = None,
              sites: Optional[Dict[str, Any]] = None) -> None:
    """Arm the injector.  ``sites`` maps a site name to either a float
    (probability per op) or a dict with any of ``rate`` / ``times`` /
    ``delay_s`` / ``error``::

        fault.configure(seed=1234, sites={
            "io.write": {"times": 2},          # next two writes fail
            "table.Add": 0.1,                  # 10% of adds fail
            "barrier": {"delay_s": 5.0, "times": 1},  # one hung barrier
        })

    A site fires by consuming ``times`` first, then by ``rate``.
    Matching is exact name, then the prefix before the last dot
    (``io.write`` falls back to a configured ``io``).
    """
    global _ENABLED
    with _LOCK:
        if seed is not None:
            _RNG.seed(seed)
        for name, spec in (sites or {}).items():
            if isinstance(spec, (int, float)):
                _SITES[name] = _Site(rate=float(spec))
            else:
                _SITES[name] = _Site(**spec)
        _ENABLED = any(s.rate > 0 or s.times > 0 for s in _SITES.values())


def reset() -> None:
    """Disarm completely and zero the counter ledger (test isolation)."""
    global _ENABLED
    with _LOCK:
        _SITES.clear()
        _ENABLED = False
    for s in metrics.REGISTRY.series():
        if isinstance(s, metrics.Counter) and (
                s.name.startswith("fault.")
                or s.name.startswith("retry.")):
            metrics.REGISTRY.remove(s.name, s.labels or None)


def _lookup(site: str) -> Optional[_Site]:
    s = _SITES.get(site)
    if s is None and "." in site:
        s = _SITES.get(site.rsplit(".", 1)[0])
    return s


def inject(site: str) -> None:
    """Chaos seam: no-op unless armed; otherwise maybe delay or raise.

    Call sites name WHERE they are (``io.write``, ``table.Get``,
    ``barrier``); the configuration decides IF and HOW they fail.
    """
    if not _ENABLED:
        return
    with _LOCK:
        s = _lookup(site)
        if s is None:
            return
        if s.times > 0:
            s.times -= 1
        elif not (s.rate > 0 and _RNG.random() < s.rate):
            return
        delay_s, error = s.delay_s, s.error
    _tick(f"fault.{site}")
    if delay_s > 0:
        Log.info("fault: injected %.1f s delay at '%s'", delay_s, site)
        time.sleep(delay_s)
        return
    Log.info("fault: injected failure at '%s'", site)
    if error is FaultError:
        raise FaultError(site)
    raise error(f"injected fault at '{site}'")


def _init_from_env() -> None:
    import os

    seed = os.environ.get("MVTPU_FAULT_SEED")
    if seed is not None:
        configure(seed=int(seed))


_init_from_env()
