"""Leveled logger.

Parity with the reference's ``util/log.h`` / ``src/util/log.cpp`` logger
(``Log::{Debug,Info,Error,Fatal}``, optional file sink; SURVEY.md §2.21),
implemented over Python ``logging`` so it composes with absl/jax logging.

``fatal`` logs and raises (the reference aborts the process; raising is the
single-controller equivalent that tests can assert on).
"""

from __future__ import annotations

import logging
import sys

__all__ = ["Log", "LogLevel", "configure"]


class LogLevel:
    DEBUG = logging.DEBUG
    INFO = logging.INFO
    ERROR = logging.ERROR
    FATAL = logging.CRITICAL


_LEVELS = {
    "debug": LogLevel.DEBUG,
    "info": LogLevel.INFO,
    "error": LogLevel.ERROR,
    "fatal": LogLevel.FATAL,
}

_logger = logging.getLogger("multiverso_tpu")
_configured = False


class FatalError(RuntimeError):
    """Raised by Log.fatal (reference behavior: abort)."""


def configure(level: str = "info", log_file: str = "") -> None:
    """(Re)configure sinks; mirrors the reference's ResetLogFile."""
    global _configured
    for h in list(_logger.handlers):
        _logger.removeHandler(h)
    fmt = logging.Formatter(
        "[%(levelname).1s %(asctime)s multiverso_tpu] %(message)s",
        datefmt="%H:%M:%S",
    )
    sh = logging.StreamHandler(sys.stderr)
    sh.setFormatter(fmt)
    _logger.addHandler(sh)
    if log_file:
        fh = logging.FileHandler(log_file)
        fh.setFormatter(fmt)
        _logger.addHandler(fh)
    _logger.setLevel(_LEVELS.get(level.lower(), LogLevel.INFO))
    _logger.propagate = False
    _configured = True


def _ensure() -> None:
    if not _configured:
        configure()


class Log:
    """Static facade matching the reference's Log class."""

    @staticmethod
    def debug(msg: str, *args) -> None:
        _ensure()
        _logger.debug(msg, *args)

    @staticmethod
    def info(msg: str, *args) -> None:
        _ensure()
        _logger.info(msg, *args)

    @staticmethod
    def error(msg: str, *args) -> None:
        _ensure()
        _logger.error(msg, *args)

    @staticmethod
    def fatal(msg: str, *args) -> None:
        _ensure()
        _logger.critical(msg, *args)
        raise FatalError(msg % args if args else msg)
