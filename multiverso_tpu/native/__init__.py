"""Python binding to the native (C++) host runtime.

The reference's Python binding loads ``libmultiverso.so`` via ctypes
(SURVEY.md §2.28); this package does the same over the TPU framework's
native control plane (``native/src``) — a real actor/message runtime
serving the flat ``MV_*`` C API (SURVEY.md §2.19).

Role in the TPU framework: the JAX tables are the accelerator data path;
the native runtime is the host control plane + FFI surface, letting non-
Python frontends (C, C++, Lua-style FFI) keep the Multiverso API.  The
math (updaters) matches the JAX updaters in float32 so either plane can
serve a table.

Build on demand with ``ensure_built()`` (g++ + make, few seconds) or
``make -C multiverso_tpu/native``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Sequence

import numpy as np

__all__ = ["ensure_built", "load", "NativeRuntime", "HostArena",
           "lib_path", "BusyError", "ArenaError"]


class BusyError(RuntimeError):
    """A server SHED the request under ``-server_inflight_max``
    backpressure (C API rc -6; docs/serving.md).

    Retryable — and unlike the indeterminate rc -3, the server did NO
    work, so a retry cannot double-apply.  ``fault.RetryPolicy`` with
    ``retry_on=(BusyError,)`` is the house backoff (the serve client
    wires this up by default)."""

class ArenaError(RuntimeError):
    """A ``*Borrowed`` call's buffer is not (entirely) inside a live
    :class:`HostArena` buffer (C API rc -7; docs/host_bridge.md).

    Borrowed calls fail loudly instead of silently copying — allocate
    the buffer with ``NativeRuntime.arena().alloc(...)`` (or drop the
    ``borrowed``/``arena`` argument to take the copying path)."""


_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB = os.path.join(_DIR, "build", "libmvtpu.so")
_lib: Optional[ctypes.CDLL] = None


def lib_path() -> str:
    return _LIB


def ensure_built(quiet: bool = True) -> str:
    """Build libmvtpu.so if missing; returns its path."""
    if not os.path.exists(_LIB):
        subprocess.run(
            ["make", "-C", _DIR, "-j", str(os.cpu_count() or 2),
             f"{os.path.join('build', 'libmvtpu.so')}"],
            check=True,
            stdout=subprocess.DEVNULL if quiet else None,
            stderr=subprocess.STDOUT if quiet else None)
    return _LIB


def load(build: bool = True) -> ctypes.CDLL:
    """Load (and memoize) the shared library with typed signatures."""
    global _lib
    if _lib is not None:
        return _lib
    if build:
        ensure_built()
    lib = ctypes.CDLL(_LIB)

    c_float_p = ctypes.POINTER(ctypes.c_float)
    c_int32_p = ctypes.POINTER(ctypes.c_int32)

    lib.MV_Init.argtypes = [ctypes.c_int,
                            ctypes.POINTER(ctypes.c_char_p)]
    lib.MV_Init.restype = ctypes.c_int
    for name in ("MV_ShutDown", "MV_Barrier", "MV_Clock", "MV_NumWorkers",
                 "MV_WorkerId", "MV_ServerId"):
        getattr(lib, name).argtypes = []
        getattr(lib, name).restype = ctypes.c_int
    lib.MV_SetFlag.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.MV_SetFlag.restype = ctypes.c_int
    lib.MV_NewArrayTable.argtypes = [ctypes.c_int64,
                                     ctypes.POINTER(ctypes.c_int32)]
    lib.MV_NewArrayTable.restype = ctypes.c_int
    for name in ("MV_GetArrayTable", "MV_AddArrayTable",
                 "MV_AddAsyncArrayTable"):
        getattr(lib, name).argtypes = [ctypes.c_int32, c_float_p,
                                       ctypes.c_int64]
        getattr(lib, name).restype = ctypes.c_int
    lib.MV_NewSparseMatrixTable.argtypes = [ctypes.c_int64, ctypes.c_int64,
                                            c_int32_p]
    lib.MV_NewSparseMatrixTable.restype = ctypes.c_int
    lib.MV_NewMatrixTable.argtypes = [ctypes.c_int64, ctypes.c_int64,
                                      ctypes.POINTER(ctypes.c_int32)]
    lib.MV_NewMatrixTable.restype = ctypes.c_int
    for name in ("MV_GetMatrixTableAll", "MV_AddMatrixTableAll",
                 "MV_AddAsyncMatrixTableAll"):
        getattr(lib, name).argtypes = [ctypes.c_int32, c_float_p,
                                       ctypes.c_int64]
        getattr(lib, name).restype = ctypes.c_int
    lib.MV_GetMatrixTableByRows.argtypes = [
        ctypes.c_int32, c_float_p, c_int32_p, ctypes.c_int64, ctypes.c_int64]
    lib.MV_GetMatrixTableByRows.restype = ctypes.c_int
    for name in ("MV_AddMatrixTableByRows", "MV_AddAsyncMatrixTableByRows"):
        getattr(lib, name).argtypes = [
            ctypes.c_int32, c_float_p, c_int32_p, ctypes.c_int64,
            ctypes.c_int64]
        getattr(lib, name).restype = ctypes.c_int
    lib.MV_GetAsyncArrayTable.argtypes = [ctypes.c_int32, c_float_p,
                                          ctypes.c_int64, c_int32_p]
    lib.MV_GetAsyncArrayTable.restype = ctypes.c_int
    # ---- host-bridge fast path (docs/host_bridge.md) -----------------
    lib.MV_ArenaAcquire.argtypes = [ctypes.c_int64,
                                    ctypes.POINTER(ctypes.c_void_p)]
    lib.MV_ArenaAcquire.restype = ctypes.c_int
    lib.MV_ArenaRelease.argtypes = [ctypes.c_void_p]
    lib.MV_ArenaRelease.restype = ctypes.c_int
    lib.MV_ArenaStats.argtypes = [ctypes.POINTER(ctypes.c_longlong)] * 7
    lib.MV_ArenaStats.restype = ctypes.c_int
    for name in ("MV_AddArrayTableBorrowed", "MV_AddAsyncArrayTableBorrowed",
                 "MV_GetArrayTableBorrowed",
                 "MV_AddMatrixTableAllBorrowed",
                 "MV_AddAsyncMatrixTableAllBorrowed"):
        getattr(lib, name).argtypes = [ctypes.c_int32, c_float_p,
                                       ctypes.c_int64]
        getattr(lib, name).restype = ctypes.c_int
    lib.MV_GetAsyncArrayTableBorrowed.argtypes = [
        ctypes.c_int32, c_float_p, ctypes.c_int64, c_int32_p]
    lib.MV_GetAsyncArrayTableBorrowed.restype = ctypes.c_int
    for name in ("MV_AddMatrixTableByRowsBorrowed",
                 "MV_AddAsyncMatrixTableByRowsBorrowed"):
        getattr(lib, name).argtypes = [
            ctypes.c_int32, c_float_p, c_int32_p, ctypes.c_int64,
            ctypes.c_int64]
        getattr(lib, name).restype = ctypes.c_int
    lib.MV_GetAsyncMatrixTableByRowsBorrowed.argtypes = [
        ctypes.c_int32, c_float_p, c_int32_p, ctypes.c_int64,
        ctypes.c_int64, c_int32_p]
    lib.MV_GetAsyncMatrixTableByRowsBorrowed.restype = ctypes.c_int
    lib.MV_GetAsyncMatrixTableByRows.argtypes = [
        ctypes.c_int32, c_float_p, c_int32_p, ctypes.c_int64,
        ctypes.c_int64, c_int32_p]
    lib.MV_GetAsyncMatrixTableByRows.restype = ctypes.c_int
    lib.MV_WaitGet.argtypes = [ctypes.c_int32]
    lib.MV_WaitGet.restype = ctypes.c_int
    lib.MV_CancelGet.argtypes = [ctypes.c_int32]
    lib.MV_CancelGet.restype = ctypes.c_int
    lib.MV_NewKVTable.argtypes = [ctypes.POINTER(ctypes.c_int32)]
    lib.MV_NewKVTable.restype = ctypes.c_int
    lib.MV_GetKV.argtypes = [ctypes.c_int32, ctypes.c_char_p, c_float_p]
    lib.MV_GetKV.restype = ctypes.c_int
    for name in ("MV_AddKV", "MV_AddAsyncKV"):
        getattr(lib, name).argtypes = [ctypes.c_int32, ctypes.c_char_p,
                                       ctypes.c_float]
        getattr(lib, name).restype = ctypes.c_int
    lib.MV_GetKVBatch.argtypes = [ctypes.c_int32, ctypes.c_char_p,
                                  ctypes.POINTER(ctypes.c_int32),
                                  ctypes.c_int64, c_float_p]
    lib.MV_GetKVBatch.restype = ctypes.c_int
    lib.MV_AddKVBatch.argtypes = [ctypes.c_int32, ctypes.c_char_p,
                                  ctypes.POINTER(ctypes.c_int32),
                                  ctypes.c_int64, c_float_p]
    lib.MV_AddKVBatch.restype = ctypes.c_int
    lib.MV_SetAddOption.argtypes = [ctypes.c_float] * 4
    lib.MV_SetAddOption.restype = ctypes.c_int
    lib.MV_StoreTable.argtypes = [ctypes.c_int32, ctypes.c_char_p]
    lib.MV_StoreTable.restype = ctypes.c_int
    lib.MV_LoadTable.argtypes = [ctypes.c_int32, ctypes.c_char_p]
    lib.MV_LoadTable.restype = ctypes.c_int
    lib.MV_DashboardReport.argtypes = []
    lib.MV_DashboardReport.restype = ctypes.c_void_p
    lib.MV_FreeString.argtypes = [ctypes.c_void_p]
    lib.MV_FreeString.restype = None
    lib.MV_QueryMonitor.argtypes = [ctypes.c_char_p,
                                    ctypes.POINTER(ctypes.c_longlong)]
    lib.MV_QueryMonitor.restype = ctypes.c_int
    lib.MV_DumpMonitors.argtypes = []
    lib.MV_DumpMonitors.restype = ctypes.c_void_p
    lib.MV_SetTraceEnabled.argtypes = [ctypes.c_int]
    lib.MV_SetTraceEnabled.restype = ctypes.c_int
    lib.MV_SetTraceId.argtypes = [ctypes.c_longlong]
    lib.MV_SetTraceId.restype = ctypes.c_int
    lib.MV_DumpSpans.argtypes = []
    lib.MV_DumpSpans.restype = ctypes.c_void_p
    lib.MV_ClearSpans.argtypes = []
    lib.MV_ClearSpans.restype = ctypes.c_int
    lib.MV_OpsReport.argtypes = [ctypes.c_char_p]
    lib.MV_OpsReport.restype = ctypes.c_void_p
    lib.MV_SetOpsHostMetrics.argtypes = [ctypes.c_char_p]
    lib.MV_SetOpsHostMetrics.restype = ctypes.c_int
    lib.MV_SetOpsHostAlerts.argtypes = [ctypes.c_char_p]
    lib.MV_SetOpsHostAlerts.restype = ctypes.c_int
    lib.MV_SetWatchdog.argtypes = [ctypes.c_int]
    lib.MV_SetWatchdog.restype = ctypes.c_int
    lib.MV_WatchdogBump.argtypes = [ctypes.c_char_p]
    lib.MV_WatchdogBump.restype = ctypes.c_int
    lib.MV_WatchdogBusy.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
    lib.MV_WatchdogBusy.restype = ctypes.c_int
    lib.MV_WatchdogStats.argtypes = []
    lib.MV_WatchdogStats.restype = ctypes.c_void_p
    lib.MV_BlackboxEvent.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.MV_BlackboxEvent.restype = ctypes.c_int
    lib.MV_BlackboxTrigger.argtypes = [ctypes.c_char_p]
    lib.MV_BlackboxTrigger.restype = ctypes.c_int
    lib.MV_HotKeys.argtypes = [ctypes.c_int32]
    lib.MV_HotKeys.restype = ctypes.c_void_p
    lib.MV_TableLoadStats.argtypes = [
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong)]
    lib.MV_TableLoadStats.restype = ctypes.c_int
    lib.MV_SetHotKeyTracking.argtypes = [ctypes.c_int]
    lib.MV_SetHotKeyTracking.restype = ctypes.c_int
    lib.MV_CapacityReport.argtypes = []
    lib.MV_CapacityReport.restype = ctypes.c_void_p
    lib.MV_SetCapacityTracking.argtypes = [ctypes.c_int]
    lib.MV_SetCapacityTracking.restype = ctypes.c_int
    lib.MV_SetWireTiming.argtypes = [ctypes.c_int]
    lib.MV_SetWireTiming.restype = ctypes.c_int
    lib.MV_SetAudit.argtypes = [ctypes.c_int]
    lib.MV_SetAudit.restype = ctypes.c_int
    lib.MV_ClockOffset.argtypes = [ctypes.c_int,
                                   ctypes.POINTER(ctypes.c_longlong),
                                   ctypes.POINTER(ctypes.c_longlong)]
    lib.MV_ClockOffset.restype = ctypes.c_int
    lib.MV_SetProfiler.argtypes = [ctypes.c_int]
    lib.MV_SetProfiler.restype = ctypes.c_int
    lib.MV_ProfilerDump.argtypes = []
    lib.MV_ProfilerDump.restype = ctypes.c_void_p
    lib.MV_ProfilerClear.argtypes = []
    lib.MV_ProfilerClear.restype = ctypes.c_int
    lib.MV_SetHotKeyReplica.argtypes = [ctypes.c_int]
    lib.MV_SetHotKeyReplica.restype = ctypes.c_int
    lib.MV_ReplicaRefresh.argtypes = [ctypes.c_int32]
    lib.MV_ReplicaRefresh.restype = ctypes.c_int
    lib.MV_ReplicaStats.argtypes = [
        ctypes.c_int32] + [ctypes.POINTER(ctypes.c_longlong)] * 5
    lib.MV_ReplicaStats.restype = ctypes.c_int
    lib.MV_OpsFleetReport.argtypes = [ctypes.c_char_p]
    lib.MV_OpsFleetReport.restype = ctypes.c_void_p
    lib.MV_SetFault.argtypes = [ctypes.c_char_p, ctypes.c_double]
    lib.MV_SetFault.restype = ctypes.c_int
    lib.MV_SetFaultN.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
    lib.MV_SetFaultN.restype = ctypes.c_int
    lib.MV_SetFaultSeed.argtypes = [ctypes.c_longlong]
    lib.MV_SetFaultSeed.restype = ctypes.c_int
    lib.MV_ClearFaults.argtypes = []
    lib.MV_ClearFaults.restype = ctypes.c_int
    lib.MV_DeadPeerCount.argtypes = []
    lib.MV_DeadPeerCount.restype = ctypes.c_int
    lib.MV_SetReplication.argtypes = [ctypes.c_int]
    lib.MV_SetReplication.restype = ctypes.c_int
    lib.MV_RoutingEpoch.argtypes = []
    lib.MV_RoutingEpoch.restype = ctypes.c_longlong
    lib.MV_ShardOwner.argtypes = [ctypes.c_int]
    lib.MV_ShardOwner.restype = ctypes.c_int
    lib.MV_BackupShard.argtypes = []
    lib.MV_BackupShard.restype = ctypes.c_int
    lib.MV_PromoteBackup.argtypes = [ctypes.c_int]
    lib.MV_PromoteBackup.restype = ctypes.c_int
    lib.MV_ReplJoin.argtypes = [ctypes.c_int]
    lib.MV_ReplJoin.restype = ctypes.c_int
    lib.MV_ReplicationStats.argtypes = \
        [ctypes.POINTER(ctypes.c_longlong)] * 8
    lib.MV_ReplicationStats.restype = ctypes.c_int
    lib.MV_NetEngine.argtypes = []
    lib.MV_NetEngine.restype = ctypes.c_void_p
    lib.MV_UringSupported.argtypes = []
    lib.MV_UringSupported.restype = ctypes.c_int
    lib.MV_FanInStats.argtypes = [ctypes.POINTER(ctypes.c_longlong)] * 3
    lib.MV_FanInStats.restype = ctypes.c_int
    lib.MV_SetTableCodec.argtypes = [ctypes.c_int32, ctypes.c_char_p]
    lib.MV_SetTableCodec.restype = ctypes.c_int
    lib.MV_FlushAdds.argtypes = [ctypes.c_int32]
    lib.MV_FlushAdds.restype = ctypes.c_int
    lib.MV_WireStats.argtypes = [ctypes.POINTER(ctypes.c_longlong)] * 4
    lib.MV_WireStats.restype = ctypes.c_int
    for name in ("MV_TableVersion", "MV_LastVersion"):
        getattr(lib, name).argtypes = [ctypes.c_int32,
                                       ctypes.POINTER(ctypes.c_longlong)]
        getattr(lib, name).restype = ctypes.c_int
    lib.MV_CacheStats.argtypes = [ctypes.POINTER(ctypes.c_longlong),
                                  ctypes.POINTER(ctypes.c_longlong)]
    lib.MV_CacheStats.restype = ctypes.c_int
    lib.MV_ServeQueueDepth.argtypes = []
    lib.MV_ServeQueueDepth.restype = ctypes.c_int
    _lib = lib
    return lib


def _f32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.float32)


def _contig_f32(a: np.ndarray, size: int, what: str) -> np.ndarray:
    """Validate (never copy) a caller buffer for the borrow/out=
    protocol (docs/host_bridge.md): float32, C-contiguous, exactly
    ``size`` elements — raising beats a silent astype/copy, which is
    the very churn the fast path exists to kill (mvlint MV012)."""
    if not isinstance(a, np.ndarray):
        raise TypeError(f"{what}: expected an ndarray, got {type(a)!r}")
    if a.dtype != np.float32:
        raise ValueError(f"{what}: dtype {a.dtype} != float32 — the "
                         f"borrow/out= protocol never converts")
    if not a.flags["C_CONTIGUOUS"]:
        raise ValueError(f"{what}: buffer is not C-contiguous — the "
                         f"borrow/out= protocol never copies")
    if a.size != size:
        raise ValueError(f"{what}: buffer has {a.size} elements, "
                         f"expected {size}")
    return a


def _fp(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _ip(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


class AsyncGet:
    """In-flight ``MV_GetAsync*`` pull (reference ``GetAsync``+``Wait``,
    SURVEY.md §2.10): the request is already on the wire; ``wait()``
    blocks until every contacted shard replied and returns the filled
    array, raising on dead shard / ``-rpc_timeout_ms`` expiry (the C
    API's indeterminate ``-3``).  The handle keeps the output buffer
    alive for ctypes; ``wait()`` is idempotent (a failure replays on
    retry).  Dropping the handle un-waited cancels the ticket
    (``MV_CancelGet``) so a late reply cannot write freed memory."""

    def __init__(self, rt: "NativeRuntime", ticket: int, out: np.ndarray,
                 shape: tuple):
        self._rt = rt
        self._ticket = ticket
        self._out = out
        self._shape = shape
        self._done = False
        self._err: "Exception | None" = None

    def wait(self) -> np.ndarray:
        if not self._done:
            self._done = True   # MV_WaitGet consumes the ticket either way
            try:
                self._rt._check(self._rt.lib.MV_WaitGet(self._ticket),
                                "MV_WaitGet")
            except Exception as exc:
                self._err = exc  # replayed on retry, not a bogus rc=-2
                raise
        if self._err is not None:
            raise self._err
        return self._out.reshape(self._shape)

    def __del__(self):
        # This object holds the ONLY reference to the output buffer a
        # late shard reply would scatter into — an un-waited drop must
        # withdraw the in-flight request before numpy frees it.
        if getattr(self, "_done", True):
            return
        try:
            self._rt.lib.MV_CancelGet(self._ticket)
        except Exception:  # mvlint: MV015-exempt(__del__ at teardown)
            # interpreter teardown: the lib may already be reclaimed,
            # and raising from a finalizer only aborts the teardown.
            pass


class HostArena:
    """Numpy facade over the native pinned buffer arena
    (docs/host_bridge.md, ``mvtpu/host_arena.h``).

    ``alloc()`` hands out numpy arrays BACKED BY arena buffers —
    recycled, 64-byte-aligned, best-effort mlock'd, and C-contiguous
    float32 by construction (MV008 holds without an
    ``ascontiguousarray`` in sight).  Arrays allocated here are what
    the ``borrowed=``/``out=``/``arena=`` arguments of
    :class:`NativeRuntime` accept: adds ship the bytes zero-copy into
    the scatter-gather send path, async gets land replies straight
    into them.

    Ownership: an array is yours from ``alloc()`` until ``release()``.
    Releasing while a borrowed send is still in flight is safe — the
    native arena defers recycling until the wire is done — but the
    ndarray must not be READ OR WRITTEN after ``release()`` returns
    (a recycled buffer may be handed to the next ``alloc``).
    """

    def __init__(self, rt: "NativeRuntime"):
        self._rt = rt
        self._bases: dict = {}  # mvlint: MV007-exempt(one entry per live buffer, freed by release)

    def alloc(self, shape, dtype=np.float32) -> np.ndarray:
        shape = (int(shape),) if np.isscalar(shape) else tuple(shape)
        dt = np.dtype(dtype)
        nbytes = max(int(np.prod(shape)) * dt.itemsize, 1)
        p = ctypes.c_void_p()
        self._rt._check(
            self._rt.lib.MV_ArenaAcquire(nbytes, ctypes.byref(p)),
            "MV_ArenaAcquire")
        raw = (ctypes.c_char * nbytes).from_address(p.value)
        arr = np.frombuffer(raw, dtype=dt).reshape(shape)
        self._bases[p.value] = True
        return arr

    def owns(self, arr: np.ndarray) -> bool:
        """True when ``arr``'s base address is a live arena buffer this
        facade handed out (offset-0 views included)."""
        try:
            addr = arr.__array_interface__["data"][0]
        except (AttributeError, TypeError):
            return False
        return addr in self._bases

    def release(self, arr: np.ndarray) -> None:
        """Return ``arr``'s buffer to the arena.  The array (and every
        view of it) is dead to the caller afterwards; in-flight
        borrowed sends keep the memory alive natively until they
        drain."""
        addr = arr.__array_interface__["data"][0]
        if addr not in self._bases:
            raise ArenaError(
                "release(): not an arena-allocated array (or already "
                "released)")
        del self._bases[addr]
        self._rt._check(self._rt.lib.MV_ArenaRelease(
            ctypes.c_void_p(addr)), "MV_ArenaRelease")

    def stats(self) -> dict:
        """Native arena counters: ``buffers``/``free_buffers``/``bytes``
        /``in_flight``/``deferred``/``recycled``/``pinned`` —
        ``deferred`` counts releases parked behind in-flight borrows,
        the observable proof of the lifetime contract."""
        vals = [ctypes.c_longlong(0) for _ in range(7)]
        self._rt._check(
            self._rt.lib.MV_ArenaStats(*(ctypes.byref(v) for v in vals)),
            "MV_ArenaStats")
        keys = ("buffers", "free_buffers", "bytes", "in_flight",
                "deferred", "recycled", "pinned")
        return dict(zip(keys, (v.value for v in vals)))


class NativeRuntime:
    """Numpy-facing wrapper over the MV_* C API."""

    def __init__(self, args: Optional[Sequence[str]] = None,
                 build: bool = True):
        self.lib = load(build=build)
        argv = [a.encode() for a in (args or [])]
        arr = (ctypes.c_char_p * len(argv))(*argv)
        if self.lib.MV_Init(len(argv), arr) != 0:
            raise RuntimeError("MV_Init failed (bad flags?)")

    def shutdown(self) -> None:
        self.lib.MV_ShutDown()

    def barrier(self) -> None:
        self._check(self.lib.MV_Barrier(), "MV_Barrier")

    def clock(self) -> None:
        """SSP tick (see MV_Clock / the -staleness flag)."""
        self._check(self.lib.MV_Clock(), "MV_Clock")

    def workers_num(self) -> int:
        return self.lib.MV_NumWorkers()

    def worker_id(self) -> int:
        return self.lib.MV_WorkerId()

    def server_id(self) -> int:
        return self.lib.MV_ServerId()

    def set_add_option(self, learning_rate=0.1, momentum=0.9, rho=0.9,
                       eps=1e-8) -> None:
        self.lib.MV_SetAddOption(learning_rate, momentum, rho, eps)

    # ------------------------------------------------- host bridge
    def arena(self) -> HostArena:
        """The process's pinned buffer arena (docs/host_bridge.md):
        allocate numpy arrays here and pass them to the ``borrowed=``/
        ``out=``/``arena=`` arguments below for the zero-copy path."""
        a = getattr(self, "_arena", None)
        if a is None:
            a = self._arena = HostArena(self)
        return a

    # ------------------------------------------------------------- arrays
    def new_array_table(self, size: int) -> int:
        h = ctypes.c_int32(-1)
        self._check(self.lib.MV_NewArrayTable(size, ctypes.byref(h)),
                    "MV_NewArrayTable")
        return h.value

    def array_get(self, handle: int, size: int,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
        """Pull the array; ``out=`` fills a preallocated float32 buffer
        (no per-call allocation+zeroing — the host-bridge out=
        protocol, docs/host_bridge.md) and returns it."""
        if out is None:
            out = np.zeros(size, np.float32)
        else:
            out = _contig_f32(out, size, "array_get(out=)")
        self._check(self.lib.MV_GetArrayTable(handle, _fp(out), size),
                    "MV_GetArrayTable")
        return out

    def array_get_async(self, handle: int, size: int,
                        out: Optional[np.ndarray] = None,
                        arena: Optional[HostArena] = None) -> AsyncGet:
        """Start a non-blocking Get; overlap compute, then ``wait()``.

        ``out=`` lands the reply in a preallocated buffer.  With
        ``arena=`` (and ``out`` allocated from it) the native side
        holds the buffer until the ticket is consumed, so an early
        ``arena.release(out)`` cannot recycle memory a late shard
        reply could still scatter into."""
        if out is None:
            out = np.zeros(size, np.float32)
        else:
            out = _contig_f32(out, size, "array_get_async(out=)")
        t = ctypes.c_int32(-1)
        if arena is not None:
            if not arena.owns(out):
                raise ArenaError("array_get_async: out= is not an "
                                 "arena-allocated buffer")
            self._check(
                self.lib.MV_GetAsyncArrayTableBorrowed(
                    handle, _fp(out), size, ctypes.byref(t)),
                "MV_GetAsyncArrayTableBorrowed")
        else:
            self._check(
                self.lib.MV_GetAsyncArrayTable(handle, _fp(out), size,
                                               ctypes.byref(t)),
                "MV_GetAsyncArrayTable")
        return AsyncGet(self, t.value, out, (size,))

    def array_add(self, handle: int, delta, sync: bool = True,
                  borrowed: bool = False) -> None:
        """Push a delta.  ``borrowed=True``: ``delta`` is an arena
        array (``arena().alloc``) shipped ZERO-COPY into the send path
        — do not mutate it until the add is known drained (a blocking
        add returning, or any later get/barrier on the table)."""
        if borrowed:
            d = _contig_f32(delta, int(delta.size), "array_add(borrowed)")
            fn = (self.lib.MV_AddArrayTableBorrowed if sync
                  else self.lib.MV_AddAsyncArrayTableBorrowed)
            self._check(fn(handle, _fp(d), d.size),
                        "MV_AddArrayTableBorrowed")
            return
        d = _f32(delta)
        fn = (self.lib.MV_AddArrayTable if sync
              else self.lib.MV_AddAsyncArrayTable)
        self._check(fn(handle, _fp(d), d.size), "MV_AddArrayTable")

    # ------------------------------------------------------------ matrices
    def new_matrix_table(self, rows: int, cols: int) -> int:
        h = ctypes.c_int32(-1)
        self._check(self.lib.MV_NewMatrixTable(rows, cols, ctypes.byref(h)),
                    "MV_NewMatrixTable")
        return h.value

    def new_sparse_matrix_table(self, rows: int, cols: int) -> int:
        """Worker-side row cache variant (MV_NewSparseMatrixTable); same
        get/add calls as the plain matrix table."""
        h = ctypes.c_int32(-1)
        self._check(
            self.lib.MV_NewSparseMatrixTable(rows, cols, ctypes.byref(h)),
            "MV_NewSparseMatrixTable")
        return h.value

    def matrix_get_all(self, handle: int, rows: int, cols: int,
                       out: Optional[np.ndarray] = None) -> np.ndarray:
        if out is None:
            out = np.zeros(rows * cols, np.float32)
        else:
            # Validate BEFORE reshaping: reshape(-1) of a strided array
            # would copy and the caller's buffer would never fill.
            out = _contig_f32(out, rows * cols,
                              "matrix_get_all(out=)").ravel()
        self._check(
            self.lib.MV_GetMatrixTableAll(handle, _fp(out), out.size),
            "MV_GetMatrixTableAll")
        return out.reshape(rows, cols)

    def matrix_add_all(self, handle: int, delta, sync: bool = True,
                       borrowed: bool = False) -> None:
        if borrowed:
            d = _contig_f32(delta, int(delta.size),
                            "matrix_add_all(borrowed)").ravel()
            fn = (self.lib.MV_AddMatrixTableAllBorrowed if sync
                  else self.lib.MV_AddAsyncMatrixTableAllBorrowed)
            self._check(fn(handle, _fp(d), d.size),
                        "MV_AddMatrixTableAllBorrowed")
            return
        d = _f32(delta).ravel()
        fn = (self.lib.MV_AddMatrixTableAll if sync
              else self.lib.MV_AddAsyncMatrixTableAll)
        self._check(fn(handle, _fp(d), d.size), "MV_AddMatrixTableAll")

    def matrix_get_rows(self, handle: int, row_ids, cols: int,
                        out: Optional[np.ndarray] = None) -> np.ndarray:
        ids = np.ascontiguousarray(row_ids, dtype=np.int32)
        if out is None:
            out = np.zeros(ids.size * cols, np.float32)
        else:
            out = _contig_f32(out, ids.size * cols,
                              "matrix_get_rows(out=)").ravel()
        self._check(
            self.lib.MV_GetMatrixTableByRows(handle, _fp(out), _ip(ids),
                                             ids.size, cols),
            "MV_GetMatrixTableByRows")
        return out.reshape(ids.size, cols)

    def matrix_get_rows_async(self, handle: int, row_ids, cols: int,
                              out: Optional[np.ndarray] = None,
                              arena: Optional[HostArena] = None
                              ) -> AsyncGet:
        """Start a non-blocking row pull (``MV_GetAsyncMatrixTableByRows``);
        the ids are consumed before this returns.  On a sparse table the
        async path bypasses the worker row cache entirely.  ``out=``/
        ``arena=`` follow :meth:`array_get_async`'s borrow protocol."""
        ids = np.ascontiguousarray(row_ids, dtype=np.int32)
        if out is None:
            out = np.zeros(ids.size * cols, np.float32)
        else:
            out = _contig_f32(out, ids.size * cols,
                              "matrix_get_rows_async(out=)").ravel()
        t = ctypes.c_int32(-1)
        if arena is not None:
            if not arena.owns(out):
                raise ArenaError("matrix_get_rows_async: out= is not an "
                                 "arena-allocated buffer")
            self._check(
                self.lib.MV_GetAsyncMatrixTableByRowsBorrowed(
                    handle, _fp(out), _ip(ids), ids.size, cols,
                    ctypes.byref(t)),
                "MV_GetAsyncMatrixTableByRowsBorrowed")
        else:
            self._check(
                self.lib.MV_GetAsyncMatrixTableByRows(
                    handle, _fp(out), _ip(ids), ids.size, cols,
                    ctypes.byref(t)),
                "MV_GetAsyncMatrixTableByRows")
        return AsyncGet(self, t.value, out, (ids.size, cols))

    def matrix_add_rows(self, handle: int, row_ids, delta,
                        sync: bool = True,
                        borrowed: bool = False) -> None:
        ids = np.ascontiguousarray(row_ids, dtype=np.int32)
        if borrowed:
            # Zero-copy row push (docs/host_bridge.md): with one server
            # shard the packed delta ships straight from this buffer
            # (no per-rank staging); multi-shard fleets stage per rank
            # but still skip the binding-side astype/copy.
            d = _contig_f32(delta, int(delta.size),
                            "matrix_add_rows(borrowed)")
            if d.ndim != 2 or d.shape[0] != ids.size:
                raise ValueError("rows/delta shape mismatch")
            flat = d.ravel()
            fn = (self.lib.MV_AddMatrixTableByRowsBorrowed if sync
                  else self.lib.MV_AddAsyncMatrixTableByRowsBorrowed)
            self._check(fn(handle, _fp(flat), _ip(ids), ids.size,
                           d.shape[1]),
                        "MV_AddMatrixTableByRowsBorrowed")
            return
        d = _f32(delta)
        if d.shape[0] != ids.size:
            raise ValueError("rows/delta shape mismatch")
        fn = (self.lib.MV_AddMatrixTableByRows if sync
              else self.lib.MV_AddAsyncMatrixTableByRows)
        # Named reference (not `_fp(d.ravel())`): the async add returns
        # before the native side is done with the buffer, so a Python
        # name must keep it alive across the call (mvlint MV001).
        flat = d.ravel()
        self._check(fn(handle, _fp(flat), _ip(ids), ids.size,
                       d.shape[1]),
                    "MV_AddMatrixTableByRows")

    # ------------------------------------------------------------------ KV
    def new_kv_table(self) -> int:
        h = ctypes.c_int32(-1)
        self._check(self.lib.MV_NewKVTable(ctypes.byref(h)),
                    "MV_NewKVTable")
        return h.value

    def kv_get(self, handle: int, keys):
        """str -> float, or list[str] -> np.ndarray (absent keys read 0)."""
        if isinstance(keys, str):
            v = ctypes.c_float(0.0)
            self._check(self.lib.MV_GetKV(handle, keys.encode(),
                                          ctypes.byref(v)), "MV_GetKV")
            return v.value
        enc = [k.encode() for k in keys]
        lens = np.asarray([len(e) for e in enc], np.int32)
        out = np.zeros(len(enc), np.float32)
        self._check(self.lib.MV_GetKVBatch(handle, b"".join(enc),
                                           _ip(lens), len(enc), _fp(out)),
                    "MV_GetKVBatch")
        return out

    def kv_add(self, handle: int, keys, deltas, sync: bool = True) -> None:
        """str+float, or list[str]+array (batch adds are blocking)."""
        if isinstance(keys, str):
            fn = self.lib.MV_AddKV if sync else self.lib.MV_AddAsyncKV
            self._check(fn(handle, keys.encode(), float(deltas)),
                        "MV_AddKV")
            return
        enc = [k.encode() for k in keys]
        lens = np.asarray([len(e) for e in enc], np.int32)
        d = _f32(deltas)
        if d.size != len(enc):
            raise ValueError("keys/deltas length mismatch")
        self._check(self.lib.MV_AddKVBatch(handle, b"".join(enc),
                                           _ip(lens), len(enc), _fp(d)),
                    "MV_AddKVBatch")

    # ----------------------------------------------------------- checkpoint
    def store_table(self, handle: int, path: str) -> None:
        self._check(self.lib.MV_StoreTable(handle, path.encode()),
                    "MV_StoreTable")

    def load_table(self, handle: int, path: str) -> None:
        self._check(self.lib.MV_LoadTable(handle, path.encode()),
                    "MV_LoadTable")

    def dashboard_report(self) -> str:
        ptr = self.lib.MV_DashboardReport()
        try:
            return ctypes.cast(ptr, ctypes.c_char_p).value.decode()
        finally:
            self.lib.MV_FreeString(ptr)

    def query_monitor(self, name: str) -> int:
        """Hit count of one Dashboard monitor (0 if it never fired) —
        e.g. ``net.retries`` / ``net.dropped`` / ``hb.missed``."""
        c = ctypes.c_longlong(0)
        self._check(self.lib.MV_QueryMonitor(name.encode(),
                                             ctypes.byref(c)),
                    "MV_QueryMonitor")
        return c.value

    def _dump_string(self, fn, what: str) -> str:
        ptr = fn()
        if not ptr:
            raise RuntimeError(f"{what} returned NULL")
        try:
            return ctypes.cast(ptr, ctypes.c_char_p).value.decode()
        finally:
            self.lib.MV_FreeString(ptr)

    # ------------------------------------------------- observability
    def dump_monitors(self) -> dict:
        """EVERY Dashboard monitor in one MV_DumpMonitors call:
        {name: (count, total_s, max_s, bucket_counts)} — the enumeration
        ``metrics.bridge_native`` imports (docs/observability.md)."""
        from .. import metrics as _metrics

        return _metrics.parse_native_dump(
            self._dump_string(self.lib.MV_DumpMonitors,
                              "MV_DumpMonitors"))

    def set_trace_enabled(self, on: bool = True) -> None:
        """Arm native span recording (also via the ``-trace`` flag)."""
        self._check(self.lib.MV_SetTraceEnabled(1 if on else 0),
                    "MV_SetTraceEnabled")

    def set_trace_id(self, trace_id: int) -> None:
        """Pin this thread's native trace id (0 = auto per-op ids) so
        native spans nest under a host-side ``tracing.span``."""
        self._check(self.lib.MV_SetTraceId(trace_id), "MV_SetTraceId")

    def dump_spans(self) -> str:
        """Raw MV_DumpSpans text (``tracing.parse_native_spans`` /
        ``tracing.add_native_spans`` turn it into events)."""
        return self._dump_string(self.lib.MV_DumpSpans, "MV_DumpSpans")

    def ops_report(self, kind: str = "health") -> str:
        """This rank's live introspection report — the same text the
        in-band wire scrape (MsgType::OpsQuery) serves: ``metrics``
        (Prometheus exposition with per-bucket exemplar trace ids),
        ``health`` (JSON verdict), or ``tables`` (JSON per-table
        version/spread/codec/agg stats).  docs/observability.md."""
        return self._dump_string(lambda: self.lib.MV_OpsReport(
            kind.encode()), "MV_OpsReport")

    def set_ops_host_metrics(self, prom_text: str) -> None:
        """Push this process's Python metrics-registry rendering so
        in-band scrapes serve the full superset (the flush thread calls
        this each interval via ``metrics.set_ops_push``)."""
        self._check(self.lib.MV_SetOpsHostMetrics(prom_text.encode()),
                    "MV_SetOpsHostMetrics")

    def set_ops_host_alerts(self, alerts_json: str) -> None:
        """Push the Python health evaluator's alert state (JSON object
        text) so the in-band ``"alerts"`` OpsQuery kind serves it under
        its ``"host"`` key beside the native watchdog table (the health
        flush hook calls this each metrics flush).  Empty clears."""
        self._check(self.lib.MV_SetOpsHostAlerts(alerts_json.encode()),
                    "MV_SetOpsHostAlerts")

    def set_watchdog(self, stall_ms: int) -> None:
        """Arm the native stall watchdog at ``stall_ms`` (<= 0 disarms;
        boot value: the ``-watchdog_stall_ms`` flag).  A watched loop
        with queued work and zero progress past the deadline dumps a
        'stall:' blackbox + profiler folded stacks and bumps
        ``watchdog.stalls`` (docs/observability.md "health plane")."""
        self._check(self.lib.MV_SetWatchdog(int(stall_ms)),
                    "MV_SetWatchdog")

    def watchdog_bump(self, loop: str) -> None:
        """One unit of progress on a host-side watched loop (e.g.
        ``py.flush``); registers the loop on first use, no-op when the
        watchdog is disarmed."""
        self._check(self.lib.MV_WatchdogBump(loop.encode()),
                    "MV_WatchdogBump")

    def watchdog_busy(self, loop: str, queued: int) -> None:
        """Declare a host loop's queued work (0 = idle; an idle loop
        cannot stall)."""
        self._check(self.lib.MV_WatchdogBusy(loop.encode(), int(queued)),
                    "MV_WatchdogBusy")

    def watchdog_stats(self) -> list:
        """The per-loop watchdog table (loop, progress, queued, stalls,
        stalled, age_s) — the ``"watchdog"`` section of the ``alerts``
        ops report."""
        import json

        return json.loads(self._dump_string(self.lib.MV_WatchdogStats,
                                            "MV_WatchdogStats"))

    def blackbox_event(self, kind: str, detail: str = "") -> None:
        """Record one lifecycle event into the native flight-recorder
        ring (bounded by ``-blackbox_events``)."""
        self._check(self.lib.MV_BlackboxEvent(kind.encode(),
                                              detail.encode()),
                    "MV_BlackboxEvent")

    def blackbox_trigger(self, reason: str) -> None:
        """Dump the flight recorder (ring + recent spans + monitor
        totals) to ``<trace_dir>/blackbox_rank<r>.json``.  Native
        failure paths (barrier timeout, dead peer, shed storm) trigger
        automatically; this is the host-side trigger (e.g.
        CheckpointCorrupt)."""
        self._check(self.lib.MV_BlackboxTrigger(reason.encode()),
                    "MV_BlackboxTrigger")

    def clear_spans(self) -> None:
        self._check(self.lib.MV_ClearSpans(), "MV_ClearSpans")

    # --------------------------------------------- workload observability
    def hot_keys(self, handle: int = -1) -> list:
        """Per-table hot-key / shard-load report (docs/observability.md,
        the ``"hotkeys"`` OpsQuery kind): for each server table, get/add
        totals, bucket-load skew ratio, space-saving top-K hot keys with
        count-min estimates, observed-staleness stats, and the add
        L2/Linf + NaN/Inf health sentinels.  ``handle >= 0`` restricts
        to one table."""
        import json

        return json.loads(self._dump_string(
            lambda: self.lib.MV_HotKeys(handle), "MV_HotKeys"))

    def table_load_stats(self, handle: int) -> dict:
        """Numeric workload slice for one table: ``{"gets", "adds",
        "skew_ratio", "add_l2", "add_linf", "nan_count", "inf_count"}``
        (MV_TableLoadStats)."""
        gets = ctypes.c_longlong(0)
        adds = ctypes.c_longlong(0)
        skew = ctypes.c_double(0.0)
        l2 = ctypes.c_double(0.0)
        linf = ctypes.c_double(0.0)
        nans = ctypes.c_longlong(0)
        infs = ctypes.c_longlong(0)
        self._check(self.lib.MV_TableLoadStats(
            handle, ctypes.byref(gets), ctypes.byref(adds),
            ctypes.byref(skew), ctypes.byref(l2), ctypes.byref(linf),
            ctypes.byref(nans), ctypes.byref(infs)), "MV_TableLoadStats")
        return {"gets": gets.value, "adds": adds.value,
                "skew_ratio": skew.value, "add_l2": l2.value,
                "add_linf": linf.value, "nan_count": nans.value,
                "inf_count": infs.value}

    def set_hotkey_tracking(self, on: bool = True) -> None:
        """Toggle the workload accounting live (boot value: the
        ``-hotkey_enabled`` flag).  Disarmed, every server hot-path hook
        is a single relaxed atomic check — the A/B behind the
        ``hotkey_track_overhead_pct`` bench bar."""
        self._check(self.lib.MV_SetHotKeyTracking(1 if on else 0),
                    "MV_SetHotKeyTracking")

    # ------------------------------------------------- capacity plane
    def capacity_report(self) -> dict:
        """This rank's capacity report (docs/observability.md
        "capacity plane"), parsed: ``proc`` (RSS/VmHWM/open fds/
        uptime), ``arena``/``net``/``gauges`` byte holders, and per
        table the shard's ``resident_bytes``/``rows`` with per-bucket
        byte + load arrays, the bounded load-history ring, and the
        worker side tables (replica/agg/cache bytes) as their own
        fields.  The same payload the in-band ``"capacity"`` OpsQuery
        kind serves; ``tools/mvplan.py`` bin-packs placement proposals
        over the fleet scrape."""
        import json

        return json.loads(self._dump_string(
            lambda: self.lib.MV_CapacityReport(), "MV_CapacityReport"))

    def set_capacity_tracking(self, on: bool = True) -> None:
        """Toggle the byte accounting live (boot value: the
        ``-capacity_enabled`` flag).  Disarmed, every hot-path growth
        hook is one relaxed atomic check — the ``capacity_overhead_pct``
        A/B; re-arming resyncs every shard's counters exactly."""
        self._check(self.lib.MV_SetCapacityTracking(1 if on else 0),
                    "MV_SetCapacityTracking")

    # ------------------------------------------- latency attribution
    def set_wire_timing(self, on: bool = True) -> None:
        """Toggle wire-header timing trails live (boot value: the
        ``-wire_timing`` flag, default ON).  Armed, every request
        carries six monotonic stage stamps and replies fold into the
        ``lat.stage.*`` histograms + per-peer clock offsets
        (docs/observability.md "latency plane")."""
        self._check(self.lib.MV_SetWireTiming(1 if on else 0),
                    "MV_SetWireTiming")

    def set_audit(self, on: bool = True) -> None:
        """Toggle the delivery-audit plane live (boot value: the
        ``-audit`` flag, default ON; docs/observability.md "audit
        plane").  Armed, every Add carries a per-(worker, table,
        shard) seq range, acks advance the client acked-add ledger,
        and server tables keep per-origin applied watermarks with
        dup/reorder/gap anomaly rings — the ``audit_overhead_pct``
        A/B toggle."""
        self._check(self.lib.MV_SetAudit(1 if on else 0), "MV_SetAudit")

    def audit_report(self) -> dict:
        """This rank's delivery-audit books (the ``"audit"`` OpsQuery
        kind, parsed): per table, the worker acked-add ledger
        (sent/acked per shard stream), the server delivery book
        (per-origin watermark, dups, reorders, pending out-of-order
        ranges, anomaly ring) and per-bucket content checksums.
        ``tools/mvaudit.py`` diffs these fleet-wide."""
        import json

        return json.loads(self.ops_report("audit"))

    def clock_offset(self, rank: int):
        """Best NTP-style clock-offset estimate for a peer rank, as
        ``{"offset_ns", "rtt_ns"}`` — how far the peer's monotonic
        clock runs ahead of this process's, and the minimum round trip
        backing the sample.  ``None`` when no timed round trip to that
        rank completed yet."""
        off = ctypes.c_longlong(0)
        rtt = ctypes.c_longlong(0)
        rc = self.lib.MV_ClockOffset(rank, ctypes.byref(off),
                                     ctypes.byref(rtt))
        if rc == -2:
            return None
        self._check(rc, "MV_ClockOffset")
        return {"offset_ns": off.value, "rtt_ns": rtt.value}

    def set_profiler(self, hz: int) -> None:
        """(Re)arm the SIGPROF sampling profiler at ``hz`` (CPU-time
        sampling; 97 is the house rate), or stop it with ``hz <= 0``.
        Boot value: the ``-profile_hz`` flag."""
        self._check(self.lib.MV_SetProfiler(hz), "MV_SetProfiler")

    def profiler_dump(self) -> str:
        """Folded-stack aggregation of everything sampled so far (one
        ``outer;...;leaf count`` line per distinct stack) —
        ``multiverso_tpu.profiler.add_native_profile`` lands it in the
        Chrome trace beside the spans."""
        return self._dump_string(self.lib.MV_ProfilerDump,
                                 "MV_ProfilerDump")

    def profiler_clear(self) -> None:
        """Drop recorded profiler samples (per-phase A/B runs)."""
        self._check(self.lib.MV_ProfilerClear(), "MV_ProfilerClear")

    def set_hotkey_replica(self, on: bool = True) -> None:
        """Toggle the hot-key read replica live (docs/embedding.md;
        boot value: the ``-hotkey_replica`` flag).  Armed, matrix row
        gets consult the servers' pushed top-K rows before the wire;
        invalidation rides the version-stamp protocol."""
        self._check(self.lib.MV_SetHotKeyReplica(1 if on else 0),
                    "MV_SetHotKeyReplica")

    def replica_refresh(self, handle: int) -> None:
        """Force one replica refresh round trip (RequestReplica to
        every shard) for a matrix table — GetRows otherwise refreshes
        lazily past ``-replica_lease_ms``."""
        self._check(self.lib.MV_ReplicaRefresh(handle),
                    "MV_ReplicaRefresh")

    def replica_stats(self, handle: int) -> dict:
        """Replica ledger for a matrix table: ``{"hits", "misses",
        "rows", "refreshes", "pushes"}`` — rows served locally vs sent
        to the wire, rows currently held, refresh round trips, and this
        rank's server-side push count."""
        vals = [ctypes.c_longlong(0) for _ in range(5)]
        self._check(self.lib.MV_ReplicaStats(
            handle, *(ctypes.byref(v) for v in vals)),
            "MV_ReplicaStats")
        keys = ("hits", "misses", "rows", "refreshes", "pushes")
        return dict(zip(keys, (v.value for v in vals)))

    def ops_fleet_report(self, kind: str = "health") -> str:
        """Fleet-scope ops report assembled BY THIS RANK over the rank
        wire (bounded fan-out + merge) — works on every engine,
        including the blocking tcp engine that refuses anonymous
        scraper connections."""
        return self._dump_string(
            lambda: self.lib.MV_OpsFleetReport(kind.encode()),
            "MV_OpsFleetReport")

    # ------------------------------------------------- fault injection
    def set_fault(self, kind: str, rate: float) -> None:
        """Arm a wire fault (docs/fault_tolerance.md): kind in
        drop|delay|dup|fail_send, probability per op; ``delay_ms`` sets
        the injected delay length."""
        self._check(self.lib.MV_SetFault(kind.encode(), rate),
                    "MV_SetFault")

    def set_fault_n(self, kind: str, n: int) -> None:
        """Deterministic variant: fire on exactly the next ``n`` ops."""
        self._check(self.lib.MV_SetFaultN(kind.encode(), n),
                    "MV_SetFaultN")

    def set_fault_seed(self, seed: int) -> None:
        self._check(self.lib.MV_SetFaultSeed(seed), "MV_SetFaultSeed")

    def clear_faults(self) -> None:
        self._check(self.lib.MV_ClearFaults(), "MV_ClearFaults")

    def dead_peer_count(self) -> int:
        """Peers with expired heartbeat leases on THIS rank
        (-heartbeat_ms; lease watching is symmetric — every rank
        tracks every peer, docs/replication.md)."""
        return self.lib.MV_DeadPeerCount()

    # ---------------------------------- replication (docs/replication.md)
    def set_replication(self, on: bool = True) -> None:
        """Live toggle for the primary->backup forward stream (the
        armed-vs-disarmed overhead A/B); the chained backup assignment
        is latched from ``-replication_factor`` at init."""
        self._check(self.lib.MV_SetReplication(1 if on else 0),
                    "MV_SetReplication")

    def routing_epoch(self) -> int:
        """Current fleet routing epoch (0 = registration-time map;
        every promotion/join bumps and broadcasts it)."""
        return int(self.lib.MV_RoutingEpoch())

    def shard_owner(self, shard_idx: int) -> int:
        """Rank currently serving ``shard_idx`` per the routed map."""
        return self.lib.MV_ShardOwner(shard_idx)

    def backup_shard(self) -> int:
        """Shard index this rank backs (chained or joined), -1 none."""
        return self.lib.MV_BackupShard()

    def promote_backup(self, dead_rank: int) -> int:
        """Operator-driven promotion of this rank's backup shard(s)
        for ``dead_rank``; returns the number of shards promoted (the
        lease-expiry path minus the corpse)."""
        return self.lib.MV_PromoteBackup(dead_rank)

    def repl_join(self, shard_idx: int) -> None:
        """Elastic join: become ``shard_idx``'s backup — announce via
        a routing-epoch flip, then pull whole-shard catch-up snapshots
        (blocking; idempotent, chaos re-runs re-pull)."""
        self._check(self.lib.MV_ReplJoin(shard_idx), "MV_ReplJoin")

    def replication_stats(self) -> dict:
        """Replication ledger: forwards/acks (primary), applied
        (backup), outstanding forwards, promotions, epoch flips,
        post-failover dup-skipped replays, catch-up installs."""
        vals = [ctypes.c_longlong(0) for _ in range(8)]
        self._check(
            self.lib.MV_ReplicationStats(*[ctypes.byref(v) for v in vals]),
            "MV_ReplicationStats")
        keys = ("forwards", "acks", "applied", "outstanding",
                "promotions", "epoch_flips", "dup_skips", "catchups")
        return {k: v.value for k, v in zip(keys, vals)}

    # ------------------------------------------------- transport
    def net_engine(self) -> str:
        """Active (effective) wire engine (docs/transport.md): ``tcp``
        | ``epoll`` | ``mpi`` | ``uring``, or ``local`` for a single
        process with no wire.  A ``-net_engine=uring`` request on a
        kernel without io_uring degrades to epoll and reports
        ``epoll`` here (the health report records the downgrade)."""
        return self._dump_string(self.lib.MV_NetEngine, "MV_NetEngine")

    def uring_supported(self) -> bool:
        """True when this kernel can run the io_uring engine.  Probes
        the kernel, not the session — callable before ``init`` (the
        uring test suites gate on it)."""
        return bool(self.lib.MV_UringSupported())

    def fanin_stats(self) -> dict:
        """Anonymous serve-tier fan-in counters (epoll engine only):
        ``{"accepted_total", "active_clients", "client_shed"}`` —
        non-rank client connections accepted, currently connected, and
        requests shed by the per-client admission gate
        (``-client_inflight_max``)."""
        vals = [ctypes.c_longlong(0) for _ in range(3)]
        self._check(
            self.lib.MV_FanInStats(*(ctypes.byref(v) for v in vals)),
            "MV_FanInStats")
        return {"accepted_total": vals[0].value,
                "active_clients": vals[1].value,
                "client_shed": vals[2].value}

    # ------------------------------------------------- wire data plane
    def set_table_codec(self, handle: int, codec: str) -> None:
        """Retarget one table's wire codec (docs/wire_compression.md):
        ``raw`` | ``1bit`` (sign bits + scales, worker-side error
        feedback) | ``sparse`` (lossless nonzero pairs with raw
        fallback).  Tables start on the ``-wire_codec`` flag."""
        self._check(self.lib.MV_SetTableCodec(handle, codec.encode()),
                    "MV_SetTableCodec")

    def flush_adds(self, handle: int = -1) -> None:
        """Drain the add-aggregation buffer (``-add_agg_ms`` /
        ``-add_agg_bytes``) of one table — or every table when
        ``handle < 0`` — onto the wire.  Get/Clock/Barrier/shutdown
        flush implicitly; this is the explicit trigger."""
        from .. import fault

        fault.inject("agg.flush")
        self._check(self.lib.MV_FlushAdds(handle), "MV_FlushAdds")

    def wire_stats(self) -> dict:
        """Transport byte/frame ledger: ``{"sent_bytes", "recv_bytes",
        "sent_msgs", "recv_msgs"}`` over the native wire (headers
        included) — the numbers behind ``net.bytes{dir=...}`` /
        ``net.msgs`` in the metrics registry."""
        vals = [ctypes.c_longlong(0) for _ in range(4)]
        self._check(self.lib.MV_WireStats(*(ctypes.byref(v) for v in vals)),
                    "MV_WireStats")
        return {"sent_bytes": vals[0].value, "recv_bytes": vals[1].value,
                "sent_msgs": vals[2].value, "recv_msgs": vals[3].value}

    # ------------------------------------------------- serve layer
    def table_version(self, handle: int) -> int:
        """Current max server-side version of the table (docs/serving.md)
        — ONE header-only wire round trip (the cheap cache-validation
        probe), not a full fetch.  Raises :class:`BusyError` when a
        server shed it under ``-server_inflight_max``."""
        v = ctypes.c_longlong(0)
        self._check(self.lib.MV_TableVersion(handle, ctypes.byref(v)),
                    "MV_TableVersion")
        return v.value

    def last_version(self, handle: int) -> int:
        """Highest version stamp observed in any reply to this process
        (free local lower bound on the server version — no wire)."""
        v = ctypes.c_longlong(0)
        self._check(self.lib.MV_LastVersion(handle, ctypes.byref(v)),
                    "MV_LastVersion")
        return v.value

    def cache_stats(self) -> tuple:
        """(hits, misses) of the native worker-side row cache (the
        sparse matrix table); the Python serve cache counts separately
        in the metrics registry (serve.cache.*)."""
        h = ctypes.c_longlong(0)
        m = ctypes.c_longlong(0)
        self._check(self.lib.MV_CacheStats(ctypes.byref(h),
                                           ctypes.byref(m)),
                    "MV_CacheStats")
        return h.value, m.value

    def serve_queue_depth(self) -> int:
        """Server-actor mailbox backlog (the -server_inflight_max
        gauge)."""
        d = self.lib.MV_ServeQueueDepth()
        self._check(min(d, 0), "MV_ServeQueueDepth")
        return d

    @staticmethod
    def _check(rc: int, what: str) -> None:
        if rc == -6:
            raise BusyError(
                f"{what} shed by server backpressure "
                f"(-server_inflight_max) — retry after backoff")
        if rc == -7:
            raise ArenaError(
                f"{what}: buffer is not inside a live HostArena buffer "
                f"— allocate it with NativeRuntime.arena().alloc(...) "
                f"(docs/host_bridge.md)")
        if rc != 0:
            raise RuntimeError(f"{what} failed with rc={rc}")
