// Native test driver — reference Test/ parity (SURVEY.md §2.35, §4):
// named scenarios + unit checks in one binary. Run all: ./mvtpu_test
// Run one: ./mvtpu_test blob|queue|configure|message|array|matrix|
//                        updater|checkpoint|threads
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cassert>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <limits>
#include <string>
#include <thread>
#include <vector>

#include "mvtpu/audit.h"
#include "mvtpu/blob.h"
#include "mvtpu/c_api.h"
#include "mvtpu/capacity.h"
#include "mvtpu/codec.h"
#include "mvtpu/configure.h"
#include "mvtpu/dashboard.h"
#include "mvtpu/host_arena.h"
#include "mvtpu/latency.h"
#include "mvtpu/message.h"
#include "mvtpu/mpi_net.h"
#include "mvtpu/mt_queue.h"
#include "mvtpu/net.h"
#include "mvtpu/ops.h"
#include "mvtpu/qos.h"
#include "mvtpu/repl.h"
#include "mvtpu/sketch.h"
#include "mvtpu/table.h"
#include "mvtpu/updater.h"
#include "mvtpu/waiter.h"
#include "mvtpu/watchdog.h"

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__, __LINE__,   \
              #cond);                                                      \
      return 1;                                                            \
    }                                                                      \
  } while (0)

static int TestBlob() {
  mvtpu::Blob b(16);
  CHECK(b.size() == 16);
  for (int i = 0; i < 4; ++i) b.As<float>()[i] = static_cast<float>(i) * 1.5f;
  mvtpu::Blob shared = b;  // shallow
  shared.As<float>()[0] = 42.0f;
  CHECK(b.As<float>()[0] == 42.0f);
  mvtpu::Blob deep;
  deep.CopyFrom(b);
  deep.As<float>()[0] = 0.0f;
  CHECK(b.As<float>()[0] == 42.0f);
  CHECK(b.count<float>() == 4);
  return 0;
}

static int TestBlobBorrow() {
  // Borrowed external memory (docs/host_bridge.md): Blob::Borrow wraps
  // caller bytes without copying; the keepalive's deleter fires when
  // the LAST shallow copy dies — the arena's "wire is done" signal.
  float ext[4] = {1.0f, 2.0f, 3.0f, 4.0f};
  int released = 0;
  {
    mvtpu::Blob outer;
    {
      auto keep = std::shared_ptr<void>(
          static_cast<void*>(ext), [&released](void*) { ++released; });
      mvtpu::Blob b = mvtpu::Blob::Borrow(ext, sizeof(ext), keep);
      CHECK(b.borrowed());
      CHECK(b.size() == sizeof(ext));
      CHECK(b.As<float>() == ext);  // zero copy: the caller's bytes
      outer = b;                    // shallow copy shares the keepalive
    }
    CHECK(released == 0);  // a live copy still pins the buffer
    CHECK(outer.As<float>()[2] == 3.0f);
    // CopyFrom flattens a borrow into an owning blob and drops the hook.
    mvtpu::Blob deep;
    deep.CopyFrom(outer);
    CHECK(!deep.borrowed());
    CHECK(deep.As<float>() != ext);
    CHECK(deep.As<float>()[3] == 4.0f);
  }
  CHECK(released == 1);  // last copy died -> exactly one release
  return 0;
}

static int TestArena() {
  auto* arena = mvtpu::HostArena::Get();
  // 64-byte alignment by construction (the MV008 contiguity guarantee).
  void* a = arena->Acquire(6144);
  void* b = arena->Acquire(6144);
  CHECK(a && b && a != b);
  CHECK(reinterpret_cast<uintptr_t>(a) % 64 == 0);
  CHECK(reinterpret_cast<uintptr_t>(b) % 64 == 0);
  // BufferOf: containment gate of the *Borrowed C API.
  char* ca = static_cast<char*>(a);
  CHECK(arena->BufferOf(ca, 6144) == a);
  CHECK(arena->BufferOf(ca + 100, 6044) == a);
  CHECK(arena->BufferOf(ca + 100, 6144) == nullptr);  // overruns
  int unknown[1];
  CHECK(arena->BufferOf(unknown, 4) == nullptr);
  // Release/recycle: same capacity comes back off the free list.
  CHECK(arena->Release(b) == 0);
  CHECK(arena->Release(b) == -2);       // double release
  CHECK(arena->Release(unknown) == -1);  // not arena memory
  void* b2 = arena->Acquire(6144);
  CHECK(b2 == b);  // recycled
  // DEFERRED recycle (the borrowed-lifetime regression, red on a naive
  // arena that recycles on caller release alone): while a native borrow
  // is in flight, Release must NOT put the buffer back in rotation —
  // an Acquire of the same size gets fresh memory, not the borrowed
  // bytes a late wire write could still read.
  void* c = nullptr;
  {
    auto hold = arena->BorrowHold(a);
    CHECK(hold);
    CHECK(arena->Release(a) == 0);          // safe mid-flight
    c = arena->Acquire(6144);
    CHECK(c != a);                          // NOT handed back while held
    CHECK(arena->BufferOf(ca, 64) == nullptr);  // released: not borrowable
  }                                         // hold drops -> recycle fires
  void* a2 = arena->Acquire(6144);          // c is still caller-held, so
  CHECK(a2 == a);                           // this must be the recycle
  auto st = arena->GetStats();
  CHECK(st.deferred >= 1);
  CHECK(st.recycled >= 2);
  CHECK(arena->Release(c) == 0);
  CHECK(arena->Release(a2) == 0);
  CHECK(arena->Release(b2) == 0);
  return 0;
}

static int TestQueue() {
  mvtpu::MtQueue<int> q;
  const int kN = 1000;
  std::thread producer([&] {
    for (int i = 0; i < kN; ++i) q.Push(i);
  });
  long long sum = 0;
  int got = 0, v;
  while (got < kN && q.Pop(&v)) {
    sum += v;
    ++got;
  }
  producer.join();
  CHECK(got == kN);
  CHECK(sum == (long long)kN * (kN - 1) / 2);
  q.Exit();
  CHECK(!q.Pop(&v));
  return 0;
}

static int TestConfigure() {
  namespace cfg = mvtpu::configure;
  cfg::RegisterDefaults();
  cfg::Reset();
  CHECK(cfg::GetBool("sync") == false);
  const char* argv[] = {"-sync=true", "-updater_type=sgd", "notaflag",
                        "-port=1234"};
  CHECK(cfg::ParseCmdFlags(4, argv) == 3);
  CHECK(cfg::GetBool("sync") == true);
  CHECK(cfg::GetString("updater_type") == "sgd");
  CHECK(cfg::GetInt("port") == 1234);
  const char* bad[] = {"-port=notanint"};
  CHECK(cfg::ParseCmdFlags(1, bad) == -1);
  const char* unknown[] = {"-no_such_flag=1"};
  CHECK(cfg::ParseCmdFlags(1, unknown) == -1);
  cfg::Reset();
  CHECK(cfg::GetBool("sync") == false);
  return 0;
}

static int TestMessage() {
  mvtpu::Message m;
  m.src = 1;
  m.dst = 2;
  m.type = mvtpu::MsgType::RequestAdd;
  m.table_id = 7;
  m.msg_id = 99;
  m.trace_id = 0x5551234;
  float payload[3] = {1.0f, 2.0f, 3.0f};
  int32_t rows[2] = {4, 5};
  m.data.emplace_back(payload, sizeof(payload));
  m.data.emplace_back(rows, sizeof(rows));
  mvtpu::Blob wire = m.Serialize();
  mvtpu::Message back = mvtpu::Message::Deserialize(wire);
  CHECK(back.src == 1 && back.dst == 2 && back.table_id == 7 &&
        back.msg_id == 99);
  CHECK(back.trace_id == 0x5551234);
  CHECK(back.type == mvtpu::MsgType::RequestAdd);
  CHECK(back.data.size() == 2);
  CHECK(back.data[0].count<float>() == 3);
  CHECK(back.data[0].As<float>()[2] == 3.0f);
  CHECK(back.data[1].As<int32_t>()[1] == 5);
  return 0;
}

static int TestLatencyTrail() {
  using mvtpu::latency::NowNs;
  mvtpu::latency::Reset();
  mvtpu::latency::Arm(true);

  // ---- trail rides the wire only when flagged (version tolerance) ---
  mvtpu::Message plain;
  plain.type = mvtpu::MsgType::RequestGet;
  float payload[2] = {1.0f, 2.0f};
  plain.data.emplace_back(payload, sizeof(payload));
  int64_t plain_bytes = plain.WireBytes();
  mvtpu::Message req = plain;
  mvtpu::latency::StampEnqueue(&req);
  CHECK(req.has_timing());
  CHECK(req.WireBytes() == plain_bytes +
        static_cast<int64_t>(sizeof(mvtpu::TimingTrail)));
  mvtpu::latency::StampSend(&req);
  mvtpu::Message back = mvtpu::Message::Deserialize(req.Serialize());
  CHECK(back.has_timing());
  CHECK(back.timing.t[mvtpu::TimingTrail::kEnqueue] ==
        req.timing.t[mvtpu::TimingTrail::kEnqueue]);
  CHECK(back.timing.t[mvtpu::TimingTrail::kSend] ==
        req.timing.t[mvtpu::TimingTrail::kSend]);
  // Old-header frame (no flag): parses exactly as before, no trail.
  mvtpu::Message old_back = mvtpu::Message::Deserialize(plain.Serialize());
  CHECK(!old_back.has_timing());
  CHECK(old_back.data.size() == 1 && old_back.data[0].count<float>() == 2);
  // Zero-copy path agrees.
  mvtpu::Blob w = req.Serialize();
  auto slab = std::make_shared<std::vector<char>>(w.data(),
                                                  w.data() + w.size());
  mvtpu::Message view;
  CHECK(mvtpu::Message::DeserializeView(slab, 0, slab->size(), &view));
  CHECK(view.has_timing());
  CHECK(view.timing.t[mvtpu::TimingTrail::kSend] ==
        req.timing.t[mvtpu::TimingTrail::kSend]);
  // A flagged frame too short for the trail is malformed, not misread.
  auto runt = std::make_shared<std::vector<char>>(
      slab->begin(), slab->begin() + sizeof(mvtpu::WireHeader));
  mvtpu::Message bad;
  CHECK(!mvtpu::Message::DeserializeView(runt, 0, runt->size(), &bad));

  // ---- stamp-once / reply-slot discipline ---------------------------
  mvtpu::latency::StampRecv(&back);
  int64_t recv1 = back.timing.t[mvtpu::TimingTrail::kRecv];
  CHECK(recv1 != 0);
  mvtpu::latency::StampRecv(&back);  // duplicate keeps the first
  CHECK(back.timing.t[mvtpu::TimingTrail::kRecv] == recv1);
  mvtpu::latency::StampDequeue(&back);
  mvtpu::Message reply;
  reply.type = mvtpu::MsgType::ReplyGet;
  mvtpu::latency::StampReply(back, &reply);
  CHECK(reply.has_timing());
  CHECK(reply.timing.t[mvtpu::TimingTrail::kApplyDone] != 0);
  mvtpu::latency::StampSend(&reply);  // reply type -> reply-send slot
  CHECK(reply.timing.t[mvtpu::TimingTrail::kReplySend] != 0);
  CHECK(reply.timing.t[mvtpu::TimingTrail::kSend] ==
        req.timing.t[mvtpu::TimingTrail::kSend]);

  // ---- OnReply: stages recorded + an offset estimate materializes ---
  // Simulate a peer clock running exactly 5 ms ahead by shifting the
  // server-side stamps; the NTP sample must recover ~that offset.
  const int64_t kShift = 5'000'000;
  reply.timing.t[mvtpu::TimingTrail::kRecv] += kShift;
  reply.timing.t[mvtpu::TimingTrail::kDequeue] += kShift;
  reply.timing.t[mvtpu::TimingTrail::kApplyDone] += kShift;
  reply.timing.t[mvtpu::TimingTrail::kReplySend] += kShift;
  mvtpu::Dashboard::Reset();
  mvtpu::latency::OnReply(reply, 3);
  long long n = 0;
  CHECK(mvtpu::Dashboard::Query("lat.total", &n, nullptr) && n == 1);
  CHECK(mvtpu::Dashboard::Query("lat.stage.apply", &n, nullptr) && n == 1);
  int64_t off = 0, rtt = 0;
  CHECK(mvtpu::latency::PeerOffset(3, &off, &rtt));
  // The estimate absorbs the handler wall time between the stamps, so
  // only bound it loosely around the injected shift.
  CHECK(off > kShift / 2 && off < kShift * 2);
  CHECK(rtt >= 0);
  CHECK(!mvtpu::latency::PeerOffset(99, &off, &rtt));

  // Disarmed: StampEnqueue mints nothing.
  mvtpu::latency::Arm(false);
  mvtpu::Message dis;
  mvtpu::latency::StampEnqueue(&dis);
  CHECK(!dis.has_timing());
  mvtpu::latency::Arm(true);
  mvtpu::latency::Reset();
  return 0;
}

static int TestAudit() {
  mvtpu::audit::Arm(true);

  // ---- stamp rides the wire only when flagged (version tolerance) ---
  mvtpu::Message plain;
  plain.type = mvtpu::MsgType::RequestAdd;
  float payload[2] = {1.0f, 2.0f};
  plain.data.emplace_back(payload, sizeof(payload));
  int64_t plain_bytes = plain.WireBytes();
  mvtpu::Message req = plain;
  req.flags |= mvtpu::msgflag::kHasAudit;
  req.audit = {7, 12};
  CHECK(req.WireBytes() == plain_bytes +
        static_cast<int64_t>(sizeof(mvtpu::AuditStamp)));
  mvtpu::Message back = mvtpu::Message::Deserialize(req.Serialize());
  CHECK(back.has_audit());
  CHECK(back.audit.seq_lo == 7 && back.audit.seq_hi == 12);
  // Old-header frame (no flag) parses exactly as before, no stamp.
  mvtpu::Message old_back = mvtpu::Message::Deserialize(plain.Serialize());
  CHECK(!old_back.has_audit());
  CHECK(old_back.data.size() == 1 && old_back.data[0].count<float>() == 2);
  // Timing trail + audit stamp compose (trail first, Serialize order).
  mvtpu::latency::Arm(true);
  mvtpu::latency::StampEnqueue(&req);
  mvtpu::Blob w = req.Serialize();
  auto slab = std::make_shared<std::vector<char>>(w.data(),
                                                  w.data() + w.size());
  mvtpu::Message view;
  CHECK(mvtpu::Message::DeserializeView(slab, 0, slab->size(), &view));
  CHECK(view.has_timing() && view.has_audit());
  CHECK(view.audit.seq_lo == 7 && view.audit.seq_hi == 12);
  CHECK(view.data[0].count<float>() == 2);
  // A flagged frame too short for the stamp is malformed, not misread.
  auto runt = std::make_shared<std::vector<char>>(
      slab->begin(), slab->begin() + sizeof(mvtpu::WireHeader));
  mvtpu::Message bad;
  CHECK(!mvtpu::Message::DeserializeView(runt, 0, runt->size(), &bad));

  // ---- AckLedger: dense per-shard streams + agg range accounting ----
  mvtpu::audit::AckLedger led;
  int64_t lo = 0, hi = 0;
  led.NextRange(0, 1, &lo, &hi);
  CHECK(lo == 1 && hi == 1);
  led.NextRange(0, 6, &lo, &hi);       // a 6-add agg flush window
  CHECK(lo == 2 && hi == 7);
  led.NextRange(1, 1, &lo, &hi);       // shard 1 is its own stream
  CHECK(lo == 1 && hi == 1);
  led.Ack(0, 7);
  led.Ack(0, 3);                       // stale ack never rolls back
  auto snap = led.Snapshot();
  CHECK(snap.size() == 2);
  CHECK(snap[0].sent == 7 && snap[0].acked == 7);
  CHECK(snap[1].sent == 1 && snap[1].acked == 0);

  // ---- DeliveryBook: advance / dup / reorder / drain ----------------
  mvtpu::audit::DeliveryBook book;
  book.NoteApply(2, 1, 1, 0);
  book.NoteApply(2, 2, 7, 0);          // agg range advances to 7
  book.NoteApply(2, 2, 7, 0);          // retry dup: visible, no advance
  book.NoteApply(2, 9, 9, 0);          // hole at 8: parked
  book.NoteApply(2, 10, 10, 0);        // still parked
  book.NoteApply(2, 8, 8, 0);          // hole filled: drains to 10
  std::string j = book.Json();
  CHECK(j.find("\"watermark\":10") != std::string::npos);
  CHECK(j.find("\"dups\":1") != std::string::npos);
  CHECK(j.find("\"reorders\":2") != std::string::npos);
  CHECK(j.find("\"pending\":[]") != std::string::npos);
  CHECK(j.find("\"kind\":\"dup\"") != std::string::npos);

  // ---- seq wraparound safety near INT64_MAX -------------------------
  // The books compare, never add, beyond +1 — a stream living at the
  // top of the seq space must not overflow into a phantom gap.
  mvtpu::audit::DeliveryBook top;
  const int64_t big = std::numeric_limits<int64_t>::max() - 1;
  top.NoteApply(0, 1, big, 0);
  top.NoteApply(0, big + 1, big + 1, 0);   // contiguous at the top
  std::string tj = top.Json();
  CHECK(tj.find("\"reorders\":0") != std::string::npos);
  CHECK(tj.find("\"dups\":0") != std::string::npos);

  // ---- anomaly ring wraps (bounded), total keeps counting -----------
  mvtpu::audit::DeliveryBook ringy;
  ringy.NoteApply(5, 1, 1, 0);
  for (int i = 0; i < 200; ++i) ringy.NoteApply(5, 1, 1, 0);  // 200 dups
  std::string rj = ringy.Json();
  CHECK(rj.find("\"anomaly_total\":200") != std::string::npos);
  CHECK(rj.find("\"dups\":200") != std::string::npos);

  // ---- checksum primitive -------------------------------------------
  const char* msg = "123456789";
  CHECK(mvtpu::audit::Crc32(msg, 9) == 0xcbf43926u);  // IEEE vector
  // Chaining: Crc32(b, seed=Crc32(a)) == Crc32(a+b).
  CHECK(mvtpu::audit::Crc32(msg + 4, 5, mvtpu::audit::Crc32(msg, 4)) ==
        mvtpu::audit::Crc32(msg, 9));

  // Bit-exact assign stores leave bit-identical bucket checksums; a
  // single changed element changes exactly its bucket's beacon.
  mvtpu::MatrixServerTable a(8, 4, mvtpu::UpdaterType::kAssign);
  mvtpu::MatrixServerTable b(8, 4, mvtpu::UpdaterType::kAssign);
  std::vector<float> rows(2 * 4, 1.5f);
  int32_t ids[2] = {1, 6};
  for (mvtpu::MatrixServerTable* t : {&a, &b}) {
    mvtpu::Message add;
    add.src = 3;
    mvtpu::AddOption opt;
    add.data.emplace_back(&opt, sizeof(opt));
    add.data.emplace_back(ids, sizeof(ids));
    add.data.emplace_back(rows.data(), rows.size() * sizeof(float));
    t->ProcessAdd(add);
  }
  auto ca = a.BucketChecksums();
  auto cb = b.BucketChecksums();
  CHECK(ca.size() == cb.size() && ca == cb);
  {
    mvtpu::Message add;
    add.src = 3;
    mvtpu::AddOption opt;
    int32_t one = 6;
    float bump[4] = {0.25f, 0, 0, 0};
    add.data.emplace_back(&opt, sizeof(opt));
    add.data.emplace_back(&one, sizeof(one));
    add.data.emplace_back(bump, sizeof(bump));
    b.ProcessAdd(add);
  }
  cb = b.BucketChecksums();
  int diffs = 0;
  for (size_t i = 0; i < ca.size(); ++i) diffs += ca[i] != cb[i];
  CHECK(diffs == 1);
  CHECK(ca[6 % mvtpu::ServerTable::kVersionBuckets] !=
        cb[6 % mvtpu::ServerTable::kVersionBuckets]);

  // ---- server-side booking via the table hook -----------------------
  mvtpu::Message stamped;
  stamped.src = 4;
  stamped.flags |= mvtpu::msgflag::kHasAudit;
  stamped.audit = {1, 3};
  a.NoteAuditApply(stamped);
  CHECK(a.audit_book().Json().find("\"watermark\":3") !=
        std::string::npos);

  // ---- disarmed: stamps nothing, books nothing ----------------------
  mvtpu::audit::Arm(false);
  mvtpu::Message dis;
  dis.src = 4;
  dis.flags |= mvtpu::msgflag::kHasAudit;
  dis.audit = {4, 4};
  a.NoteAuditApply(dis);
  CHECK(a.audit_book().Json().find("\"watermark\":3") !=
        std::string::npos);
  mvtpu::audit::Arm(true);
  return 0;
}

static int TestCodec() {
  using mvtpu::Blob;
  using mvtpu::codec::DecodeOneBit;
  using mvtpu::codec::DecodeSparse;
  using mvtpu::codec::EncodeOneBit;
  using mvtpu::codec::EncodeSparse;

  // ---- sparse: lossless round trips across the edge cases -----------
  {
    // Mostly-zero ODD-length payload with NaN/Inf nonzeros: bit-exact
    // round trip (sparse pays off once nonzeros < n/2 - 2).
    float d[33] = {0};
    d[1] = 1.5f;
    d[4] = -2.25f;
    d[31] = std::numeric_limits<float>::quiet_NaN();
    d[32] = std::numeric_limits<float>::infinity();
    Blob enc = EncodeSparse(d, 33);
    CHECK(enc.size() > 0 && enc.size() < 33 * sizeof(float));
    std::vector<float> out;
    CHECK(DecodeSparse(enc, &out));
    CHECK(out.size() == 33);
    CHECK(memcmp(out.data(), d, sizeof(d)) == 0);  // NaN survives memcmp
  }
  {
    // Empty payload: the sparse form (16 bytes) is never smaller than
    // 0 raw bytes — the encoder must fall back to raw.
    Blob enc = EncodeSparse(nullptr, 0);
    CHECK(enc.size() == 0);
  }
  {
    // Dense payload: no benefit, raw fallback signalled by empty blob.
    float d[4] = {1, 2, 3, 4};
    CHECK(EncodeSparse(d, 4).size() == 0);
  }
  {
    // Malformed payloads must decode false, not overread.
    std::vector<float> out;
    CHECK(!DecodeSparse(Blob("xy", 2), &out));
    int64_t bad[2] = {8, 9};  // k > n
    CHECK(!DecodeSparse(Blob(bad, sizeof(bad)), &out));
  }

  // ---- 1bit: shapes, signs, error-feedback drain --------------------
  {
    // Odd length, mixed signs, no residual.
    float d[5] = {1.0f, -3.0f, 2.0f, -1.0f, 0.0f};
    Blob enc = EncodeOneBit(d, 5, nullptr);
    CHECK(enc.size() == 16 + 1);  // header + one bit byte
    std::vector<float> out;
    CHECK(DecodeOneBit(enc, &out));
    CHECK(out.size() == 5);
    CHECK(fabsf(out[0] - 1.0f) < 1e-6f);   // pos mean = (1+2+0)/3
    CHECK(fabsf(out[1] + 2.0f) < 1e-6f);   // neg mean = (-3-1)/2
    CHECK(out[0] == out[2] && out[1] == out[3] && out[0] == out[4]);
  }
  {
    // All-negative payload: pos bucket empty -> pos_scale 0, decode ok.
    float d[3] = {-1.0f, -2.0f, -3.0f};
    std::vector<float> out;
    CHECK(DecodeOneBit(EncodeOneBit(d, 3, nullptr), &out));
    CHECK(fabsf(out[0] + 2.0f) < 1e-6f && out[0] == out[1]);
  }
  {
    // Empty payload round-trips to an empty vector.
    std::vector<float> out{1.0f};
    CHECK(DecodeOneBit(EncodeOneBit(nullptr, 0, nullptr), &out));
    CHECK(out.empty());
  }
  {
    // Non-finite inputs are sanitized: finite scales, zeroed residual.
    float d[4] = {std::numeric_limits<float>::quiet_NaN(),
                  -std::numeric_limits<float>::infinity(), 2.0f, -2.0f};
    float res[4] = {0, 0, 0, 0};
    std::vector<float> out;
    CHECK(DecodeOneBit(EncodeOneBit(d, 4, res), &out));
    for (float v : out) CHECK(std::isfinite(v));
    CHECK(res[0] == 0.0f && res[1] == 0.0f);
    for (float v : res) CHECK(std::isfinite(v));
  }
  {
    // Error feedback: repeated compress/apply with a ROTATING deviation
    // pattern (real gradients fluctuate; a constant per-element
    // deviation is the known two-global-scale pathology where the
    // residual grows linearly).  Over full rotation cycles every
    // element's true sum is kSteps * 0.7 exactly; the applied sum must
    // track it with the residual bounded by one cycle's spread —
    // i.e. the error DRAINS into later messages instead of
    // accumulating.
    const int kN = 16, kSteps = 60;  // 12 full cycles of 5
    float delta[kN], res[kN] = {0};
    std::vector<float> applied(kN, 0.0f);
    for (int s = 0; s < kSteps; ++s) {
      for (int i = 0; i < kN; ++i)
        delta[i] = 0.5f + 0.1f * static_cast<float>((i + s) % 5);
      std::vector<float> out;
      CHECK(DecodeOneBit(EncodeOneBit(delta, kN, res), &out));
      for (int i = 0; i < kN; ++i) applied[i] += out[i];
    }
    const float want = 0.7f * kSteps;
    for (int i = 0; i < kN; ++i) {
      CHECK(fabsf(applied[i] - want) < 1.0f);
      CHECK(fabsf(applied[i] - want) / want < 0.02f);
      CHECK(fabsf(res[i]) < 1.0f);  // drained, not accumulated
    }
  }

  // ---- header stamp + in-place decode (the server's path) -----------
  {
    mvtpu::Message m;
    m.type = mvtpu::MsgType::RequestAdd;
    float d[16] = {0};
    d[2] = 4.0f;
    d[15] = -1.0f;
    Blob enc = EncodeSparse(d, 16);
    CHECK(enc.size() > 0);
    m.codec = mvtpu::Codec::kSparse;
    m.flags = mvtpu::msgflag::kAcceptRaw | mvtpu::msgflag::kAcceptSparse;
    m.data.push_back(enc);
    // Codec + flags survive the wire header round trip.
    mvtpu::Message back = mvtpu::Message::Deserialize(m.Serialize());
    CHECK(back.codec == mvtpu::Codec::kSparse);
    CHECK(back.flags == m.flags);
    CHECK(mvtpu::codec::DecodeInPlace(&back));
    CHECK(back.codec == mvtpu::Codec::kRaw);
    CHECK(back.data[0].count<float>() == 16);
    CHECK(back.data[0].As<float>()[2] == 4.0f);
    CHECK(back.data[0].As<float>()[15] == -1.0f);
    // Reply encoding honors the accept list: raw-only stays raw.
    mvtpu::Message reply;
    reply.data.emplace_back(d, sizeof(d));
    mvtpu::codec::MaybeEncodeReply(&reply, mvtpu::msgflag::kAcceptRaw);
    CHECK(reply.codec == mvtpu::Codec::kRaw);
    mvtpu::codec::MaybeEncodeReply(
        &reply, mvtpu::msgflag::kAcceptRaw | mvtpu::msgflag::kAcceptSparse);
    CHECK(reply.codec == mvtpu::Codec::kSparse);
    CHECK(reply.data[0].size() < sizeof(d));
  }
  return 0;
}

static int TestDashboard() {
  using mvtpu::Dashboard;
  Dashboard::Reset();
  Dashboard::Record("Unit::fast", 2e-6);   // bucket 1 (<= 2 µs)
  Dashboard::Record("Unit::fast", 2e-6);
  Dashboard::Record("Unit::slow", 1e-3);
  long long c = 0;
  double t = 0.0;
  CHECK(Dashboard::Query("Unit::fast", &c, &t) && c == 2);
  // One-call enumeration: both monitors, with bucket columns.
  std::string dump = Dashboard::Dump();
  CHECK(dump.find("Unit::fast\t2\t") != std::string::npos);
  CHECK(dump.find("Unit::slow\t1\t") != std::string::npos);
  CHECK(std::count(dump.begin(), dump.end(), '\n') == 2);
  // Spans: a Monitor under tracing records one span; nested monitors on
  // the same thread share the generated trace id.
  Dashboard::SetTraceRank(3);
  Dashboard::SetTraceEnabled(true);
  {
    mvtpu::Monitor outer("Unit::outer");
    mvtpu::Monitor inner("Unit::inner");
  }
  Dashboard::SetTraceEnabled(false);
  std::string spans = Dashboard::DumpSpans();
  CHECK(spans.find("Unit::outer\t") != std::string::npos);
  CHECK(spans.find("Unit::inner\t") != std::string::npos);
  // Same trace id on both lines (field 2), carrying the rank-3 salt.
  long long id_outer = 0, id_inner = 0;
  CHECK(sscanf(spans.c_str() + spans.find("Unit::inner\t") + 12, "%lld",
               &id_inner) == 1);
  CHECK(sscanf(spans.c_str() + spans.find("Unit::outer\t") + 12, "%lld",
               &id_outer) == 1);
  CHECK(id_outer == id_inner);
  CHECK((id_outer >> 40) == 4);  // rank + 1
  // Thread-local cleaned up: next monitor outside tracing stays span-free.
  CHECK(Dashboard::ThreadTraceId() == 0);
  Dashboard::ClearSpans();
  CHECK(Dashboard::DumpSpans().empty());
  Dashboard::SetTraceRank(0);
  Dashboard::Reset();
  return 0;
}

static int TestUpdater() {
  using mvtpu::AddOption;
  using mvtpu::UpdaterType;
  AddOption opt;
  opt.learning_rate = 0.5f;
  float w[2] = {1.0f, 1.0f}, d[2] = {2.0f, 2.0f};
  mvtpu::ApplyUpdate(UpdaterType::kSGD, opt, w, nullptr, d, 2);
  CHECK(w[0] == 0.0f);
  // adagrad twice matches the JAX test: -0.1 - 0.1/sqrt(2)
  opt.learning_rate = 0.1f;
  opt.eps = 1e-8f;
  float w2[1] = {0.0f}, h[1] = {0.0f}, g[1] = {1.0f};
  mvtpu::ApplyUpdate(UpdaterType::kAdaGrad, opt, w2, h, g, 1);
  mvtpu::ApplyUpdate(UpdaterType::kAdaGrad, opt, w2, h, g, 1);
  float expect = -0.1f - 0.1f / sqrtf(2.0f);
  CHECK(fabsf(w2[0] - expect) < 1e-5f);
  // assign: stored bits == pushed bits (the offload bridge's bit-exact
  // remote store, docs/host_bridge.md); repeated assigns do not
  // accumulate, and NumSlots is 0 (no optimizer state of its own).
  CHECK(mvtpu::NumSlots(UpdaterType::kAssign) == 0);
  CHECK(mvtpu::UpdaterFromName("assign") == UpdaterType::kAssign);
  CHECK(mvtpu::IsUpdaterName("assign"));
  float w3[2] = {7.0f, -7.0f}, d3[2] = {0.25f, -1.5f};
  mvtpu::ApplyUpdate(UpdaterType::kAssign, opt, w3, nullptr, d3, 2);
  mvtpu::ApplyUpdate(UpdaterType::kAssign, opt, w3, nullptr, d3, 2);
  CHECK(w3[0] == 0.25f && w3[1] == -1.5f);
  return 0;
}

static int TestArray() {
  const char* argv[] = {"-updater_type=default", "-log_level=error"};
  CHECK(MV_Init(2, argv) == 0);
  int32_t h;
  CHECK(MV_NewArrayTable(64, &h) == 0);
  std::vector<float> delta(64, 1.0f), out(64, -1.0f);
  CHECK(MV_AddArrayTable(h, delta.data(), 64) == 0);
  CHECK(MV_AddAsyncArrayTable(h, delta.data(), 64) == 0);
  CHECK(MV_Barrier() == 0);  // flushes the async add
  CHECK(MV_GetArrayTable(h, out.data(), 64) == 0);
  for (float v : out) CHECK(v == 2.0f);
  CHECK(MV_NumWorkers() == 1 && MV_WorkerId() == 0 && MV_ServerId() == 0);
  return 0;
}

static int TestMatrix() {
  int32_t h;
  CHECK(MV_NewMatrixTable(8, 4, &h) == 0);
  std::vector<float> all(32, 0.5f), out(32, 0.0f);
  CHECK(MV_AddMatrixTableAll(h, all.data(), 32) == 0);
  int32_t rows[3] = {1, 3, 1};  // duplicate row composes sequentially
  std::vector<float> rd(12, 1.0f), rout(8, 0.0f);
  CHECK(MV_AddMatrixTableByRows(h, rd.data(), rows, 3, 4) == 0);
  int32_t qrows[2] = {1, 3};
  CHECK(MV_GetMatrixTableByRows(h, rout.data(), qrows, 2, 4) == 0);
  for (int c = 0; c < 4; ++c) {
    CHECK(rout[c] == 2.5f);       // row 1: 0.5 + 1 + 1
    CHECK(rout[4 + c] == 1.5f);   // row 3: 0.5 + 1
  }
  CHECK(MV_GetMatrixTableAll(h, out.data(), 32) == 0);
  CHECK(out[0] == 0.5f);
  return 0;
}

static int TestBridge() {
  // Host-bridge fast path over the C API (docs/host_bridge.md); runs
  // after `array` armed the single-process runtime.  Every payload here
  // lives in a HostArena buffer and ships borrowed — zero payload copy
  // on the send side.
  int32_t h;
  CHECK(MV_NewArrayTable(48, &h) == 0);
  void* p = nullptr;
  CHECK(MV_ArenaAcquire(48 * sizeof(float), &p) == 0);
  float* buf = static_cast<float*>(p);
  for (int i = 0; i < 48; ++i) buf[i] = static_cast<float>(i);
  // Borrowed calls FAIL LOUDLY on non-arena memory (rc -7, nothing
  // sent) — the contract mvlint MV012 polices from the Python side.
  std::vector<float> heap(48, 1.0f);
  CHECK(MV_AddArrayTableBorrowed(h, heap.data(), 48) == -7);
  CHECK(MV_GetArrayTableBorrowed(h, heap.data(), 48) == -7);
  // Blocking borrowed add + borrowed get into a second arena buffer.
  CHECK(MV_AddArrayTableBorrowed(h, buf, 48) == 0);
  void* po = nullptr;
  CHECK(MV_ArenaAcquire(48 * sizeof(float), &po) == 0);
  float* out = static_cast<float*>(po);
  CHECK(MV_GetArrayTableBorrowed(h, out, 48) == 0);
  for (int i = 0; i < 48; ++i) CHECK(out[i] == static_cast<float>(i));
  // Async borrowed add: the arena defers the buffer past the in-flight
  // send; the barrier flushes, then values must read back doubled.
  CHECK(MV_AddAsyncArrayTableBorrowed(h, buf, 48) == 0);
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetArrayTableBorrowed(h, out, 48) == 0);
  for (int i = 0; i < 48; ++i) CHECK(out[i] == 2.0f * i);
  // Async borrowed get + EARLY caller release: the ticket's arena hold
  // keeps the destination un-recycled until MV_WaitGet consumes it — an
  // Acquire of the same size mid-flight must get different memory.
  int32_t ticket = -1;
  CHECK(MV_GetAsyncArrayTableBorrowed(h, out, 48, &ticket) == 0);
  CHECK(MV_ArenaRelease(po) == 0);  // safe: recycle deferred past Wait
  void* other = nullptr;
  CHECK(MV_ArenaAcquire(48 * sizeof(float), &other) == 0);
  CHECK(other != po);
  CHECK(MV_WaitGet(ticket) == 0);
  for (int i = 0; i < 48; ++i) CHECK(out[i] == 2.0f * i);
  CHECK(MV_ArenaRelease(other) == 0);
  // Matrix plane: whole-table + by-rows borrowed (single shard -> the
  // no-staging fast path) + async borrowed row get.
  int32_t hm;
  CHECK(MV_NewMatrixTable(6, 4, &hm) == 0);
  void* pm = nullptr;
  CHECK(MV_ArenaAcquire(24 * sizeof(float), &pm) == 0);
  float* md = static_cast<float*>(pm);
  for (int i = 0; i < 24; ++i) md[i] = 0.5f;
  CHECK(MV_AddMatrixTableAllBorrowed(hm, md, 24) == 0);
  int32_t rows[2] = {1, 4};
  CHECK(MV_AddMatrixTableByRowsBorrowed(hm, md, rows, 2, 4) == 0);
  int32_t bad_rows[2] = {1, 99};  // out of range: staging path handles
  CHECK(MV_AddMatrixTableByRowsBorrowed(hm, md, bad_rows, 2, 4) == 0);
  void* pr = nullptr;
  CHECK(MV_ArenaAcquire(8 * sizeof(float), &pr) == 0);
  float* rout = static_cast<float*>(pr);
  int32_t t2 = -1;
  CHECK(MV_GetAsyncMatrixTableByRowsBorrowed(hm, rout, rows, 2, 4, &t2)
        == 0);
  CHECK(MV_WaitGet(t2) == 0);
  for (int c = 0; c < 4; ++c) {
    CHECK(rout[c] == 1.5f);      // row 1: 0.5 + 0.5 + 0.5
    CHECK(rout[4 + c] == 1.0f);  // row 4: 0.5 + 0.5
  }
  CHECK(MV_ArenaRelease(pr) == 0);
  CHECK(MV_ArenaRelease(pm) == 0);
  CHECK(MV_ArenaRelease(p) == 0);
  long long buffers = 0, in_flight = 0, deferred = 0;
  CHECK(MV_ArenaStats(&buffers, nullptr, nullptr, &in_flight, &deferred,
                      nullptr, nullptr) == 0);
  CHECK(in_flight == 0);   // every borrowed send drained
  CHECK(deferred >= 1);    // the early release above was deferred
  return 0;
}

static int TestCheckpoint() {
  int32_t h;
  CHECK(MV_NewArrayTable(16, &h) == 0);
  std::vector<float> delta(16, 3.0f), out(16, 0.0f);
  CHECK(MV_AddArrayTable(h, delta.data(), 16) == 0);
  const char* path = "/tmp/mvtpu_native_ck.bin";
  CHECK(MV_StoreTable(h, path) == 0);
  CHECK(MV_AddArrayTable(h, delta.data(), 16) == 0);
  CHECK(MV_LoadTable(h, path) == 0);
  CHECK(MV_GetArrayTable(h, out.data(), 16) == 0);
  for (float v : out) CHECK(v == 3.0f);
  return 0;
}

static int TestSparseMatrix() {
  // Worker row cache: own adds invalidate their rows; a barrier (clock)
  // invalidates everything; reads serve correct values throughout.
  int32_t h;
  CHECK(MV_NewSparseMatrixTable(6, 4, &h) == 0);
  int32_t rows[2] = {1, 4};
  std::vector<float> d(8, 2.0f), out(8, -1.0f);
  CHECK(MV_AddMatrixTableByRows(h, d.data(), rows, 2, 4) == 0);
  CHECK(MV_GetMatrixTableByRows(h, out.data(), rows, 2, 4) == 0);
  for (float v : out) CHECK(v == 2.0f);          // cache filled
  CHECK(MV_GetMatrixTableByRows(h, out.data(), rows, 2, 4) == 0);
  for (float v : out) CHECK(v == 2.0f);          // cache hit, same value
  CHECK(MV_AddMatrixTableByRows(h, d.data(), rows, 2, 4) == 0);
  CHECK(MV_GetMatrixTableByRows(h, out.data(), rows, 2, 4) == 0);
  for (float v : out) CHECK(v == 4.0f);          // own add invalidated
  CHECK(MV_Barrier() == 0);                      // clock invalidate
  CHECK(MV_GetMatrixTableByRows(h, out.data(), rows, 2, 4) == 0);
  for (float v : out) CHECK(v == 4.0f);
  // An SSP tick (MV_Clock) must invalidate the cache like a barrier —
  // a cache hit would bypass the server's -staleness enforcement.
  // Observable via the base table's wire-fetch monitor: warm reads
  // don't touch it, the post-tick read must.
  long long wire0 = 0, wire1 = 0, wire2 = 0;
  double tot = 0.0;
  mvtpu::Dashboard::Query("MatrixWorker::GetRows", &wire0, &tot);
  CHECK(MV_GetMatrixTableByRows(h, out.data(), rows, 2, 4) == 0);
  mvtpu::Dashboard::Query("MatrixWorker::GetRows", &wire1, &tot);
  CHECK(wire1 == wire0);                         // warm: pure cache hit
  CHECK(MV_Clock() == 0);
  CHECK(MV_GetMatrixTableByRows(h, out.data(), rows, 2, 4) == 0);
  mvtpu::Dashboard::Query("MatrixWorker::GetRows", &wire2, &tot);
  CHECK(wire2 == wire1 + 1);                     // tick forced a re-fetch
  for (float v : out) CHECK(v == 4.0f);
  int32_t oob[1] = {99};
  std::vector<float> zout(4, -1.0f);
  CHECK(MV_GetMatrixTableByRows(h, zout.data(), oob, 1, 4) == 0);
  for (float v : zout) CHECK(v == 0.0f);         // out-of-range zeros
  return 0;
}

static int TestKV() {
  // Single-process KV round trips: singles, batch (with a duplicate key
  // summing), absent-key zero reads, and a checkpoint round trip.
  int32_t h;
  CHECK(MV_NewKVTable(&h) == 0);
  float v = -1.0f;
  CHECK(MV_GetKV(h, "absent", &v) == 0);
  CHECK(v == 0.0f);
  CHECK(MV_AddKV(h, "alpha", 2.5f) == 0);
  CHECK(MV_AddAsyncKV(h, "alpha", 0.5f) == 0);
  CHECK(MV_Barrier() == 0);  // flush the async add
  CHECK(MV_GetKV(h, "alpha", &v) == 0);
  CHECK(v == 3.0f);
  // Batch: "bee"+"bee" duplicate must compose to the sum, "sea" lands.
  const char keys[] = "beebeesea";
  int32_t lens[3] = {3, 3, 3};
  float deltas[3] = {1.0f, 2.0f, 4.0f};
  CHECK(MV_AddKVBatch(h, keys, lens, 3, deltas) == 0);
  float vals[3] = {-1, -1, -1};
  CHECK(MV_GetKVBatch(h, keys, lens, 3, vals) == 0);
  CHECK(vals[0] == 3.0f && vals[1] == 3.0f && vals[2] == 4.0f);
  // Checkpoint: mutate after store, load must restore the snapshot.
  const char* path = "/tmp/mvtpu_native_kv_ck.bin";
  CHECK(MV_StoreTable(h, path) == 0);
  CHECK(MV_AddKV(h, "alpha", 10.0f) == 0);
  CHECK(MV_LoadTable(h, path) == 0);
  CHECK(MV_GetKV(h, "alpha", &v) == 0);
  CHECK(v == 3.0f);
  CHECK(MV_GetKV(h, "sea", &v) == 0);
  CHECK(v == 4.0f);
  return 0;
}

static int TestServeVersions() {
  // Serve-layer version protocol (docs/serving.md), single process:
  // fresh tables read version 0; every apply bumps monotonically; the
  // header-only probe (MV_TableVersion) and the free local bound
  // (MV_LastVersion, refreshed by reply stamps) agree; bucket stamps
  // let reads of untouched rows/keys report an older version.
  int32_t h;
  CHECK(MV_NewArrayTable(8, &h) == 0);
  long long v = -1;
  CHECK(MV_TableVersion(h, &v) == 0);
  CHECK(v == 0);
  std::vector<float> ones(8, 1.0f), out(8);
  CHECK(MV_AddArrayTable(h, ones.data(), 8) == 0);
  CHECK(MV_TableVersion(h, &v) == 0);
  CHECK(v == 1);
  // The blocking-add ack stamped the post-apply version locally.
  long long lv = -1;
  CHECK(MV_LastVersion(h, &lv) == 0);
  CHECK(lv == 1);
  CHECK(MV_AddArrayTable(h, ones.data(), 8) == 0);
  CHECK(MV_GetArrayTable(h, out.data(), 8) == 0);
  CHECK(MV_LastVersion(h, &lv) == 0);
  CHECK(lv == 2);
  // KV: adds to one key leave OTHER buckets' read stamps behind the
  // table version (bucket-granular staleness).  Async adds (no ack →
  // no local stamp) so the READ stamps are what last_version observes.
  int32_t kv;
  CHECK(MV_NewKVTable(&kv) == 0);
  CHECK(MV_AddAsyncKV(kv, "hot", 1.0f) == 0);
  CHECK(MV_AddAsyncKV(kv, "hot", 1.0f) == 0);
  CHECK(MV_Barrier() == 0);                 // flush the async adds
  float val = -1.0f;
  CHECK(MV_GetKV(kv, "cold", &val) == 0);   // untouched bucket
  CHECK(MV_LastVersion(kv, &lv) == 0);
  CHECK(lv == 0);  // cold bucket never bumped — read stamped 0
  CHECK(MV_GetKV(kv, "hot", &val) == 0);
  CHECK(val == 2.0f);
  CHECK(MV_LastVersion(kv, &lv) == 0);
  CHECK(lv == 2);  // hot bucket carries both applies
  long long kvv = -1;
  CHECK(MV_TableVersion(kv, &kvv) == 0);
  CHECK(kvv == 2);
  CHECK(MV_ServeQueueDepth() >= 0);
  long long hits = -1, misses = -1;
  CHECK(MV_CacheStats(&hits, &misses) == 0);
  CHECK(hits >= 0 && misses >= 0);
  return 0;
}

static int TestWorkload() {
  using mvtpu::workload::CountMin;
  using mvtpu::workload::KeyHash;
  using mvtpu::workload::SpaceSaving;

  // --- SpaceSaving: planted heavy hitters always surface -------------
  SpaceSaving ss(4);
  for (int round = 0; round < 200; ++round) {
    ss.Offer(KeyHash((int64_t)1), "1", 1);        // 2 in 3 offers: hot
    ss.Offer(KeyHash((int64_t)1), "1", 1);
    ss.Offer(KeyHash((int64_t)(100 + round)), std::to_string(100 + round));
  }
  auto top = ss.TopK();
  CHECK(!top.empty());
  CHECK(top[0].label == "1");
  CHECK(top[0].count - top[0].error <= 400);      // lower bound honest
  CHECK(top[0].count >= 400);                     // upper bound covers
  CHECK(ss.total() == 600);

  // --- CountMin: never underestimates; eps-bounded overestimate ------
  CountMin cm(1024, 4);
  for (int i = 0; i < 5000; ++i) cm.Add(KeyHash((int64_t)(i % 50)));
  for (int i = 0; i < 50; ++i) {
    int64_t est = cm.Estimate(KeyHash((int64_t)i));
    CHECK(est >= 100);                            // true count = 100
    CHECK(est <= 100 + 2 * 5000 * 4 / 1024);      // ~eps*N slack
  }
  CHECK(cm.Estimate(KeyHash((int64_t)999999)) <= 2 * 5000 * 4 / 1024);

  // --- merge across ranks: the fleet-scope fold -----------------------
  SpaceSaving a(4), b(4);
  for (int i = 0; i < 30; ++i) a.Offer(KeyHash((int64_t)7), "7");
  for (int i = 0; i < 20; ++i) b.Offer(KeyHash((int64_t)7), "7");
  b.Offer(KeyHash((int64_t)8), "8");
  a.Merge(b);
  CHECK(a.TopK()[0].label == "7");
  CHECK(a.TopK()[0].count == 50);
  CHECK(a.total() == 51);

  // --- server hot path: skewed row gets -> top-K + skew ratio ---------
  int32_t h;
  CHECK(MV_NewMatrixTable(256, 4, &h) == 0);
  std::vector<float> row(4, 0.5f), got(4);
  std::vector<int32_t> hot_id = {3};
  for (int i = 0; i < 64; ++i) {
    CHECK(MV_AddMatrixTableByRows(h, row.data(), hot_id.data(), 1, 4) == 0);
    CHECK(MV_GetMatrixTableByRows(h, got.data(), hot_id.data(), 1, 4) == 0);
    int32_t cold = 10 + i;                        // one touch each
    CHECK(MV_GetMatrixTableByRows(h, got.data(), &cold, 1, 4) == 0);
  }
  long long gets = 0, adds = 0, nans = 0, infs = 0;
  double skew = 0, l2 = 0, linf = 0;
  CHECK(MV_TableLoadStats(h, &gets, &adds, &skew, &l2, &linf, &nans,
                          &infs) == 0);
  CHECK(gets == 128 && adds == 64);
  CHECK(skew > 2.0);                              // row 3's bucket is hot
  CHECK(l2 > 0.0 && linf == 0.5);
  CHECK(nans == 0 && infs == 0);
  char* json = MV_HotKeys(h);
  CHECK(json && strstr(json, "\"key\":\"3\"") != nullptr);
  CHECK(strstr(json, "\"skew_ratio\"") != nullptr);
  MV_FreeString(json);
  json = MV_OpsReport("hotkeys");
  CHECK(json && strstr(json, "\"topk\"") != nullptr);
  MV_FreeString(json);

  // --- NaN sentinel: first poisoned add trips the black box -----------
  long long triggers0 = 0;
  CHECK(MV_QueryMonitor("blackbox.trigger", &triggers0) == 0);
  int32_t hn;
  CHECK(MV_NewArrayTable(8, &hn) == 0);
  std::vector<float> poison(8, 1.0f);
  poison[3] = std::numeric_limits<float>::quiet_NaN();
  poison[5] = std::numeric_limits<float>::infinity();
  CHECK(MV_AddArrayTable(hn, poison.data(), 8) == 0);
  CHECK(MV_TableLoadStats(hn, nullptr, nullptr, nullptr, nullptr,
                          nullptr, &nans, &infs) == 0);
  CHECK(nans == 1 && infs == 1);
  long long triggers1 = 0;
  CHECK(MV_QueryMonitor("blackbox.trigger", &triggers1) == 0);
  CHECK(triggers1 == triggers0 + 1);
  // Second poisoned add: counted, but the trigger fired once per table.
  CHECK(MV_AddArrayTable(hn, poison.data(), 8) == 0);
  CHECK(MV_QueryMonitor("blackbox.trigger", &triggers1) == 0);
  CHECK(triggers1 == triggers0 + 1);

  // --- disarmed: accounting freezes at one atomic check ---------------
  CHECK(MV_SetHotKeyTracking(0) == 0);
  CHECK(MV_GetMatrixTableByRows(h, got.data(), hot_id.data(), 1, 4) == 0);
  long long gets2 = 0;
  CHECK(MV_TableLoadStats(h, &gets2, nullptr, nullptr, nullptr, nullptr,
                          nullptr, nullptr) == 0);
  CHECK(gets2 == gets);
  CHECK(MV_SetHotKeyTracking(1) == 0);
  return 0;
}

static int TestReplica() {
  // Hot-key read replica (docs/embedding.md), single process: the
  // server's SpaceSaving top-K pushes into the worker-side table,
  // GetRows serves hits with zero additional server applies, and the
  // version gate IS the invalidation — at -replica_max_staleness=0 an
  // acked add stales every entry from before it (the regression the
  // acceptance bar names: RED on a replica that serves without
  // invalidation).
  int32_t h;
  CHECK(MV_NewMatrixTable(64, 4, &h) == 0);
  std::vector<float> ones(2 * 4, 1.0f), out(3 * 4, -1.0f);
  int32_t hot[2] = {1, 2};
  CHECK(MV_AddMatrixTableByRows(h, ones.data(), hot, 2, 4) == 0);
  int32_t ids[3] = {1, 2, 3};
  for (int i = 0; i < 10; ++i)
    CHECK(MV_GetMatrixTableByRows(h, out.data(), ids, 3, 4) == 0);
  CHECK(MV_SetHotKeyReplica(1) == 0);
  CHECK(MV_ReplicaRefresh(h) == 0);
  long long hits = 0, misses = 0, rows = 0, refreshes = 0, pushes = 0;
  CHECK(MV_ReplicaStats(h, &hits, &misses, &rows, &refreshes,
                        &pushes) == 0);
  CHECK(rows >= 2);      // the hot rows were pushed
  CHECK(pushes >= 1);
  long long hits0 = hits;
  CHECK(MV_GetMatrixTableByRows(h, out.data(), ids, 3, 4) == 0);
  CHECK(out[0] == 1.0f && out[4] == 1.0f);
  CHECK(MV_ReplicaStats(h, &hits, &misses, nullptr, nullptr,
                        nullptr) == 0);
  CHECK(hits > hits0);   // served from the replica, not the wire
  // Invalidation, own-add shape: a blocking add to row 1 (ack bumps
  // last_version) — the next read of row 1 MUST return the new value.
  std::vector<float> bump(4, 5.0f);
  int32_t one[1] = {1};
  CHECK(MV_AddMatrixTableByRows(h, bump.data(), one, 1, 4) == 0);
  CHECK(MV_GetMatrixTableByRows(h, out.data(), one, 1, 4) == 0);
  CHECK(out[0] == 6.0f);
  // Version gate specifically: row 2 is still IN the replica (the add
  // touched only row 1's entry) but its stamp predates the acked add —
  // at staleness 0 it must MISS to the wire, not serve the old stamp.
  long long miss0 = 0;
  CHECK(MV_ReplicaStats(h, nullptr, &miss0, nullptr, nullptr,
                        nullptr) == 0);
  int32_t two[1] = {2};
  CHECK(MV_GetMatrixTableByRows(h, out.data(), two, 1, 4) == 0);
  CHECK(out[0] == 1.0f);
  CHECK(MV_ReplicaStats(h, nullptr, &misses, nullptr, nullptr,
                        nullptr) == 0);
  CHECK(misses > miss0);
  // A fresh refresh re-covers the hot set at the NEW version: reads
  // hit again and serve the post-add value.
  CHECK(MV_ReplicaRefresh(h) == 0);
  CHECK(MV_ReplicaStats(h, &hits0, nullptr, nullptr, nullptr,
                        nullptr) == 0);
  CHECK(MV_GetMatrixTableByRows(h, out.data(), one, 1, 4) == 0);
  CHECK(out[0] == 6.0f);
  CHECK(MV_ReplicaStats(h, &hits, nullptr, nullptr, nullptr,
                        nullptr) == 0);
  CHECK(hits > hits0);
  CHECK(MV_SetHotKeyReplica(0) == 0);
  return 0;
}

// First integer after "\"key\":" in a JSON doc, or `dflt` when absent
// (strstr-grade parsing, the house style for report assertions).
static long long JsonIntAfter(const std::string& doc, const std::string& key,
                              long long dflt = -1) {
  size_t at = doc.find("\"" + key + "\":");
  if (at == std::string::npos) return dflt;
  return std::strtoll(doc.c_str() + at + key.size() + 3, nullptr, 10);
}

static int TestCapacity() {
  using mvtpu::capacity::kKVEntryOverhead;

  // ---- matrix shard bytes: exact at construction ---------------------
  int32_t h;
  CHECK(MV_NewMatrixTable(128, 4, &h) == 0);
  char* rep = MV_CapacityReport();
  CHECK(rep != nullptr);
  std::string doc(rep);
  MV_FreeString(rep);
  // Single process: the shard is the whole table — 128 rows x 4 cols
  // x 4 bytes (default updater: no slot plane).
  size_t at = doc.find("\"id\":" + std::to_string(h) + ",");
  CHECK(at != std::string::npos);
  std::string entry = doc.substr(at);
  CHECK(JsonIntAfter(entry, "resident_bytes") == 128 * 4 * 4);
  CHECK(JsonIntAfter(entry, "rows") == 128);
  // Per-bucket bytes sum back to the shard total (the 64-bucket map).
  {
    size_t bb = entry.find("\"bucket_bytes\":[");
    CHECK(bb != std::string::npos);
    const char* p = entry.c_str() + bb + 16;
    long long sum = 0;
    for (int i = 0; i < 64; ++i) {
      char* end = nullptr;
      sum += std::strtoll(p, &end, 10);
      p = end + 1;
    }
    CHECK(sum == 128 * 4 * 4);
  }
  // Proc stats ride the health report (RSS / fds present).
  rep = MV_OpsReport("health");
  std::string health(rep);
  MV_FreeString(rep);
  CHECK(health.find("\"rss_bytes\":") != std::string::npos);
  CHECK(health.find("\"open_fds\":") != std::string::npos);
  CHECK(JsonIntAfter(health, "rss_bytes") > 0);
  CHECK(JsonIntAfter(health, "open_fds") > 0);

  // ---- KV incremental accounting vs the ground-truth walk ------------
  int32_t hk;
  CHECK(MV_NewKVTable(&hk) == 0);
  long long expect = 0;
  for (int i = 0; i < 20; ++i) {
    std::string key = "cap-key-" + std::to_string(i);
    CHECK(MV_AddKV(hk, key.c_str(), 1.0f) == 0);
    expect += static_cast<long long>(key.size()) + 4 + kKVEntryOverhead;
  }
  rep = MV_CapacityReport();
  doc.assign(rep);
  MV_FreeString(rep);
  at = doc.find("\"id\":" + std::to_string(hk) + ",");
  CHECK(at != std::string::npos);
  entry = doc.substr(at);
  CHECK(JsonIntAfter(entry, "resident_bytes") == expect);
  CHECK(JsonIntAfter(entry, "rows") == 20);
  // Duplicate adds do not grow the books.
  CHECK(MV_AddKV(hk, "cap-key-0", 1.0f) == 0);
  rep = MV_CapacityReport();
  doc.assign(rep);
  MV_FreeString(rep);
  entry = doc.substr(doc.find("\"id\":" + std::to_string(hk) + ","));
  CHECK(JsonIntAfter(entry, "rows") == 20);

  // ---- disarm: growth hooks freeze; re-arm resyncs exactly -----------
  CHECK(MV_SetCapacityTracking(0) == 0);
  CHECK(MV_AddKV(hk, "while-disarmed", 2.0f) == 0);
  rep = MV_CapacityReport();
  doc.assign(rep);
  MV_FreeString(rep);
  CHECK(doc.find("\"armed\":false") != std::string::npos);
  entry = doc.substr(doc.find("\"id\":" + std::to_string(hk) + ","));
  CHECK(JsonIntAfter(entry, "rows") == 20);  // stale while disarmed
  CHECK(MV_SetCapacityTracking(1) == 0);     // re-arm RESYNCS
  expect += static_cast<long long>(strlen("while-disarmed")) + 4 +
            kKVEntryOverhead;
  rep = MV_CapacityReport();
  doc.assign(rep);
  MV_FreeString(rep);
  entry = doc.substr(doc.find("\"id\":" + std::to_string(hk) + ","));
  CHECK(JsonIntAfter(entry, "rows") == 21);
  CHECK(JsonIntAfter(entry, "resident_bytes") == expect);

  // ---- history ring: bounded at 64 windows ---------------------------
  CHECK(MV_SetFlag("capacity_history_ms", "0") == 0);
  for (int i = 0; i < 70; ++i) {
    rep = MV_CapacityReport();
    MV_FreeString(rep);
  }
  rep = MV_CapacityReport();
  doc.assign(rep);
  MV_FreeString(rep);
  long long windows = JsonIntAfter(doc, "windows");
  CHECK(windows >= 2 && windows <= 64);
  CHECK(doc.find("\"curve\":[") != std::string::npos);
  CHECK(doc.find("\"bucket_rate\":[") != std::string::npos);
  CHECK(MV_SetFlag("capacity_history_ms", "250") == 0);

  // ---- replica rows are their OWN field (double-count regression) ----
  // With an armed replica install, the "tables" report must keep the
  // shard row count pure and report replica entries separately — a
  // capacity sum over rows+replica_rows is the caller's CHOICE, never
  // a baked-in double count.
  std::vector<float> ones(2 * 4, 1.0f), out(2 * 4, 0.0f);
  int32_t hot[2] = {1, 2};
  CHECK(MV_AddMatrixTableByRows(h, ones.data(), hot, 2, 4) == 0);
  for (int i = 0; i < 8; ++i)
    CHECK(MV_GetMatrixTableByRows(h, out.data(), hot, 2, 4) == 0);
  CHECK(MV_SetHotKeyReplica(1) == 0);
  CHECK(MV_ReplicaRefresh(h) == 0);
  rep = MV_OpsReport("tables");
  doc.assign(rep);
  MV_FreeString(rep);
  entry = doc.substr(doc.find("\"id\":" + std::to_string(h) + ","));
  CHECK(JsonIntAfter(entry, "rows") == 128);          // shard rows only
  CHECK(JsonIntAfter(entry, "replica_rows") >= 2);    // own field
  // The capacity report agrees: worker.replica_bytes > 0, and the
  // shard's resident bytes did NOT absorb the replica copies.
  rep = MV_CapacityReport();
  doc.assign(rep);
  MV_FreeString(rep);
  entry = doc.substr(doc.find("\"id\":" + std::to_string(h) + ","));
  CHECK(JsonIntAfter(entry, "resident_bytes") == 128 * 4 * 4);
  CHECK(JsonIntAfter(entry, "replica_bytes") > 0);
  CHECK(MV_SetHotKeyReplica(0) == 0);

  // ---- gauges object carries the registered native gauges ------------
  CHECK(doc.find("\"host_arena.bytes\":") != std::string::npos);
  CHECK(doc.find("\"net.writeq_bytes\":") != std::string::npos);
  return 0;
}

static int TestQos() {
  // ---- wire format: stamp rides only when flagged -------------------
  mvtpu::Message plain;
  plain.type = mvtpu::MsgType::RequestGet;
  float payload[2] = {1.0f, 2.0f};
  plain.data.emplace_back(payload, sizeof(payload));
  int64_t plain_bytes = plain.WireBytes();
  mvtpu::Message req = plain;
  req.flags |= mvtpu::msgflag::kHasQos;
  req.qos.klass = 1;
  req.qos.budget_ns = 5'000'000'000ll;
  CHECK(req.WireBytes() ==
        plain_bytes + static_cast<int64_t>(sizeof(mvtpu::QosStamp)));
  mvtpu::Message back = mvtpu::Message::Deserialize(req.Serialize());
  CHECK(back.has_qos());
  CHECK(back.qos.klass == 1 && back.qos.budget_ns == 5'000'000'000ll);
  // Old-header frame (no flag) parses byte-identically, no stamp.
  mvtpu::Message old_back = mvtpu::Message::Deserialize(plain.Serialize());
  CHECK(!old_back.has_qos());
  CHECK(old_back.data.size() == 1 && old_back.data[0].count<float>() == 2);
  // Trail + audit + qos compose in Serialize order.
  mvtpu::latency::Arm(true);
  mvtpu::latency::StampEnqueue(&req);
  req.flags |= mvtpu::msgflag::kHasAudit;
  req.audit = {3, 4};
  mvtpu::Blob w = req.Serialize();
  auto slab = std::make_shared<std::vector<char>>(w.data(),
                                                  w.data() + w.size());
  mvtpu::Message view;
  CHECK(mvtpu::Message::DeserializeView(slab, 0, slab->size(), &view));
  CHECK(view.has_timing() && view.has_audit() && view.has_qos());
  CHECK(view.qos.klass == 1 && view.qos.budget_ns == 5'000'000'000ll);
  CHECK(view.audit.seq_lo == 3 && view.data[0].count<float>() == 2);
  // A flagged frame too short for the stamp is malformed, not misread.
  auto runt = std::make_shared<std::vector<char>>(
      slab->begin(), slab->begin() + sizeof(mvtpu::WireHeader));
  mvtpu::Message bad;
  CHECK(!mvtpu::Message::DeserializeView(runt, 0, runt->size(), &bad));

  // ---- weighted deficit admission -----------------------------------
  mvtpu::configure::RegisterDefaults();
  mvtpu::configure::Set("qos_classes", "gold:8,bulk:1");
  mvtpu::configure::Set("qos_inflight_max", "9");
  mvtpu::qos::Configure();
  mvtpu::qos::Reset();
  CHECK(mvtpu::qos::NumClasses() == 2);
  CHECK(mvtpu::qos::ClassId("gold") == 0);
  CHECK(mvtpu::qos::ClassId("bulk") == 1);
  CHECK(mvtpu::qos::ClassId("nope") == -1);
  CHECK(mvtpu::qos::ClassName(1) == "bulk");
  // Guaranteed shares: gold 8 slots, bulk 1 (cap * w / sum).
  CHECK(mvtpu::qos::TryAdmit(1));            // bulk's guaranteed slot
  for (int i = 0; i < 8; ++i) CHECK(mvtpu::qos::TryAdmit(0));  // gold
  CHECK(!mvtpu::qos::TryAdmit(1));           // at cap: bulk sheds
  CHECK(!mvtpu::qos::TryAdmit(0));           // at cap: even gold sheds
  mvtpu::qos::Release(0);
  // One spare slot: bulk borrows only after deficit credit accrues in
  // weight proportion (one admit per max-weight failed passes).
  int admitted = 0;
  for (int i = 0; i < 8; ++i) admitted += mvtpu::qos::TryAdmit(1) ? 1 : 0;
  CHECK(admitted == 1);
  // Gold borrows the next spare immediately (weight == quantum).
  mvtpu::qos::Release(1);
  CHECK(mvtpu::qos::TryAdmit(0));
  std::string j = mvtpu::qos::Json();
  CHECK(j.find("\"name\":\"gold\"") != std::string::npos);
  CHECK(j.find("\"inflight_max\":9") != std::string::npos);

  // ---- deadline adoption + dequeue shed -----------------------------
  mvtpu::Message dm;
  dm.flags |= mvtpu::msgflag::kHasQos;
  dm.qos.budget_ns = 1;                      // expires immediately
  mvtpu::qos::AdoptDeadline(&dm);
  CHECK(dm.qos_deadline_ns != 0);
  std::this_thread::sleep_for(std::chrono::milliseconds(2));
  CHECK(mvtpu::qos::ShedExpired(dm));
  CHECK(mvtpu::qos::DeadlineSheds() >= 1);
  mvtpu::Message fresh;
  fresh.flags |= mvtpu::msgflag::kHasQos;
  fresh.qos.budget_ns = 60'000'000'000ll;    // a minute: never expires here
  mvtpu::qos::AdoptDeadline(&fresh);
  CHECK(!mvtpu::qos::ShedExpired(fresh));
  mvtpu::Message unstamped;                  // no budget: never shed
  mvtpu::qos::AdoptDeadline(&unstamped);
  CHECK(unstamped.qos_deadline_ns == 0);
  CHECK(!mvtpu::qos::ShedExpired(unstamped));

  // ---- request stamping follows -wire_deadline / -qos_class ---------
  mvtpu::configure::Set("qos_class", "bulk");
  mvtpu::configure::Set("rpc_timeout_ms", "250");
  mvtpu::qos::Configure();
  mvtpu::Message stamped;
  mvtpu::qos::StampRequest(&stamped);
  CHECK(stamped.has_qos());
  CHECK(stamped.qos.klass == 1);             // bulk's positional id
  CHECK(stamped.qos.budget_ns == 250'000'000ll);
  mvtpu::configure::Set("wire_deadline", "false");
  mvtpu::qos::Configure();
  mvtpu::Message unflagged;
  mvtpu::qos::StampRequest(&unflagged);
  CHECK(!unflagged.has_qos());

  // ---- hedge-cancel registry: consume-once --------------------------
  mvtpu::qos::NoteCancel(5, 42);
  CHECK(mvtpu::qos::Cancelled(5, 42));
  CHECK(!mvtpu::qos::Cancelled(5, 42));      // consumed
  CHECK(!mvtpu::qos::Cancelled(5, 43));      // never noted

  // Restore defaults so later cases see a clean slate.
  mvtpu::configure::Set("qos_classes", "bulk:1,gold:8");
  mvtpu::configure::Set("qos_inflight_max", "0");
  mvtpu::configure::Set("wire_deadline", "true");
  mvtpu::configure::Set("qos_class", "bulk");
  mvtpu::configure::Set("rpc_timeout_ms", "30000");
  mvtpu::qos::Configure();
  mvtpu::qos::Reset();
  return 0;
}

static int TestMultiBlobAdd() {
  // Multi-shard borrowed AddRows wire shape (docs/embedding.md): the
  // delta may arrive split across SEVERAL row-aligned blobs (one per
  // contiguous caller run); the server walks rows across the sequence
  // (RowBlobCursor) and a cross-blob size mismatch drops cleanly.
  mvtpu::MatrixServerTable t(8, 2, mvtpu::UpdaterType::kDefault);
  mvtpu::AddOption opt;
  mvtpu::Message req;
  req.data.emplace_back(&opt, sizeof(opt));
  int32_t ids[3] = {1, 2, 5};
  req.data.emplace_back(ids, sizeof(ids));
  float run1[4] = {1.0f, 1.0f, 2.0f, 2.0f};  // rows 1, 2
  float run2[2] = {5.0f, 5.0f};              // row 5
  req.data.emplace_back(run1, sizeof(run1));
  req.data.emplace_back(run2, sizeof(run2));
  t.ProcessAdd(req);
  mvtpu::Message get, reply;
  get.data.emplace_back(ids, sizeof(ids));
  t.ProcessGet(get, &reply);
  const float* vals = reply.data[0].As<float>();
  CHECK(vals[0] == 1.0f && vals[1] == 1.0f);
  CHECK(vals[2] == 2.0f && vals[3] == 2.0f);
  CHECK(vals[4] == 5.0f && vals[5] == 5.0f);
  // 3 ids but only 2 rows of delta across the blobs: dropped whole.
  mvtpu::Message bad;
  bad.data.emplace_back(&opt, sizeof(opt));
  bad.data.emplace_back(ids, sizeof(ids));
  bad.data.emplace_back(run1, sizeof(run1));
  t.ProcessAdd(bad);
  mvtpu::Message reply2;
  t.ProcessGet(get, &reply2);
  const float* vals2 = reply2.data[0].As<float>();
  for (int i = 0; i < 6; ++i) CHECK(vals2[i] == vals[i]);
  return 0;
}

static int TestWatchdog() {
  namespace wd = mvtpu::watchdog;
  wd::Reset();
  // Disarmed (the default): Bump/Busy are no-ops, nothing registers.
  wd::Bump("t.noop");
  CHECK(!wd::Armed());
  CHECK(wd::StatsJson() == "[]");
  long long triggers0 = mvtpu::ops::BlackboxTriggerCount();
  wd::Arm(50);
  CHECK(wd::Armed());
  // A busy loop that never progresses must be flagged within
  // stall_ms + one checker period; a progressing loop never is.
  wd::Busy("t.stuck", 3);
  bool stalled = false;
  for (int i = 0; i < 200 && !stalled; ++i) {
    wd::Bump("t.live");
    wd::Busy("t.live", 1);
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
    stalled = wd::StallCount() > 0;
  }
  CHECK(stalled);
  CHECK(wd::StallCount() == 1);  // flagged once, not once per period
  std::string js = wd::StatsJson();
  CHECK(js.find("\"loop\":\"t.stuck\"") != std::string::npos);
  CHECK(js.find("\"stalled\":true") != std::string::npos);
  CHECK(js.find("\"loop\":\"t.live\"") != std::string::npos);
  // The stall dumped a blackbox (stall message + folded stacks).
  CHECK(mvtpu::ops::BlackboxTriggerCount() > triggers0);
  // Recovery: one unit of progress clears the flag.
  wd::Bump("t.stuck");
  bool cleared = false;
  for (int i = 0; i < 50 && !cleared; ++i) {
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
    cleared = wd::StatsJson().find("\"stalled\":true") ==
              std::string::npos;
  }
  CHECK(cleared);
  wd::Busy("t.stuck", 0);  // idle: cannot re-stall
  // C API surface.
  CHECK(MV_WatchdogBump("t.capi") == 0);
  CHECK(MV_WatchdogBusy("t.capi", 1) == 0);
  char* stats = MV_WatchdogStats();
  CHECK(stats != nullptr);
  CHECK(std::string(stats).find("t.capi") != std::string::npos);
  MV_FreeString(stats);
  CHECK(MV_WatchdogBump(nullptr) == -1);
  CHECK(MV_WatchdogBusy(nullptr, 1) == -1);
  CHECK(MV_SetWatchdog(0) == 0);
  CHECK(!wd::Armed());
  // The "alerts" ops report carries the watchdog table + host push.
  CHECK(MV_SetOpsHostAlerts("{\"armed\":true,\"alerts\":[]}") == 0);
  char* rep = MV_OpsReport("alerts");
  CHECK(rep != nullptr);
  std::string alerts(rep);
  MV_FreeString(rep);
  CHECK(alerts.find("\"watchdog\":[") != std::string::npos);
  CHECK(alerts.find("\"host\":{\"armed\":true") != std::string::npos);
  CHECK(MV_SetOpsHostAlerts(nullptr) == 0);  // clears → null
  rep = MV_OpsReport("alerts");
  CHECK(std::string(rep).find("\"host\":null") != std::string::npos);
  MV_FreeString(rep);
  wd::Reset();
  CHECK(wd::StatsJson() == "[]");
  return 0;
}

static int TestThreads() {
  // Concurrent blocking adds from many app threads — the actor pipeline
  // must serialize them without loss (reference MtQueue/actor guarantee).
  int32_t h;
  CHECK(MV_NewArrayTable(32, &h) == 0);
  const int kThreads = 8, kAdds = 50;
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; ++t)
    ts.emplace_back([h] {
      std::vector<float> d(32, 1.0f);
      for (int i = 0; i < kAdds; ++i) MV_AddArrayTable(h, d.data(), 32);
    });
  for (auto& t : ts) t.join();
  std::vector<float> out(32, 0.0f);
  CHECK(MV_GetArrayTable(h, out.data(), 32) == 0);
  for (float v : out) CHECK(v == (float)(kThreads * kAdds));
  return 0;
}

static int NetChild(const char* machine_file, const char* rank,
                    const char* engine) {
  // N-process scenario (spawned N times by tests/test_native.py): sharded
  // tables over the TCP transport — Add/Get round-trips cross the process
  // boundary, MV_Barrier rendezvouses through rank 0's controller.
  // N comes from the machine file (2 and 4 in CI); N <= 4.  `engine`
  // picks the readiness model (tcp|epoll; tests run both).
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  std::string eng = std::string("-net_engine=") + engine;
  // Bounded deadlines: an infra failure (stolen port, dead sibling)
  // must fail a CHECK quickly, not hang the rank past pytest's timeout.
  const char* argv2[] = {mf.c_str(), rk.c_str(), eng.c_str(),
                         "-updater_type=default",
                         "-log_level=error", "-rpc_timeout_ms=60000",
                         "-barrier_timeout_ms=60000"};
  CHECK(MV_Init(7, argv2) == 0);
  int me = MV_WorkerId();
  int n = MV_NumWorkers();
  CHECK(n >= 2 && n <= 4);
  float total = (float)(n * (n + 1) / 2);  // sum over ranks of (r+1)

  int32_t h;
  CHECK(MV_NewArrayTable(10, &h) == 0);
  int32_t hm;
  CHECK(MV_NewMatrixTable(8, 4, &hm) == 0);
  CHECK(MV_Barrier() == 0);  // every rank registered both tables

  // Each rank pushes its own delta; shards live on EVERY rank, so every
  // Add crosses the wire for the remote shards. After the barrier all
  // ranks must read the sum.
  std::vector<float> delta(10, (float)(me + 1)), out(10, -1.0f);
  CHECK(MV_AddArrayTable(h, delta.data(), 10) == 0);
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetArrayTable(h, out.data(), 10) == 0);
  for (float v : out) CHECK(v == total);
  // Rendezvous between rounds: without it, a slow rank's verify-Get
  // races the fast ranks' next-round async adds (observed at n=4).
  CHECK(MV_Barrier() == 0);

  // Async add flushes through the pipeline before the barrier completes.
  CHECK(MV_AddAsyncArrayTable(h, delta.data(), 10) == 0);
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetArrayTable(h, out.data(), 10) == 0);
  for (float v : out) CHECK(v == 2 * total);
  CHECK(MV_Barrier() == 0);  // same read-vs-next-round fence as above

  // Matrix rows: rank r touches rows {r, 4+r}, so row blocks from every
  // shard see both local and remote writes.
  int32_t rows[2] = {me, 4 + me};
  std::vector<float> rd(8, (float)(me + 1));
  CHECK(MV_AddMatrixTableByRows(hm, rd.data(), rows, 2, 4) == 0);
  CHECK(MV_Barrier() == 0);
  for (int r = 0; r < n; ++r) {
    int32_t qrows[2] = {r, 4 + r};
    std::vector<float> rout(8, -1.0f);
    CHECK(MV_GetMatrixTableByRows(hm, rout.data(), qrows, 2, 4) == 0);
    for (float v : rout) CHECK(v == (float)(r + 1));
  }

  // Sparse matrix cross-rank: the worker row cache serves CACHED values
  // while peers add (AD-LDA staleness), and a barrier makes peers' adds
  // visible.  A KV counter synchronizes "all +10 adds applied" without
  // touching the sparse cache, so the staleness assert is deterministic.
  int32_t hs;
  CHECK(MV_NewSparseMatrixTable(4, 4, &hs) == 0);
  int32_t hsync;
  CHECK(MV_NewKVTable(&hsync) == 0);
  CHECK(MV_Barrier() == 0);
  int32_t my_row[1] = {me};
  std::vector<float> mine(4, (float)(me + 1));
  CHECK(MV_AddMatrixTableByRows(hs, mine.data(), my_row, 1, 4) == 0);
  CHECK(MV_Barrier() == 0);
  // Fill the cache with every rank's row, then RENDEZVOUS THROUGH KV
  // (not a barrier — that would invalidate the cache) before anyone
  // bumps: a fast rank's bump must not land before a slow rank's
  // snapshot read, or the snapshot values race.
  std::vector<int32_t> all_rows(n);
  for (int r = 0; r < n; ++r) all_rows[r] = r;
  std::vector<float> snap(n * 4, -1.0f);
  CHECK(MV_GetMatrixTableByRows(hs, snap.data(), all_rows.data(), n, 4) == 0);
  for (int r = 0; r < n; ++r)
    for (int c = 0; c < 4; ++c) CHECK(snap[r * 4 + c] == (float)(r + 1));
  CHECK(MV_AddKV(hsync, "cached", 1.0f) == 0);
  float cached = 0.0f;
  for (int tries = 0; tries < 500 && cached < (float)n; ++tries) {
    CHECK(MV_GetKV(hsync, "cached", &cached) == 0);
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }
  CHECK(cached == (float)n);
  // Everyone bumps their own row by 10 (blocking), then announces via KV.
  std::vector<float> bump(4, 10.0f);
  CHECK(MV_AddMatrixTableByRows(hs, bump.data(), my_row, 1, 4) == 0);
  CHECK(MV_AddKV(hsync, "adds_done", 1.0f) == 0);
  float done = 0.0f;
  for (int tries = 0; tries < 500 && done < (float)n; ++tries) {
    CHECK(MV_GetKV(hsync, "adds_done", &done) == 0);
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }
  CHECK(done == (float)n);
  // Peer rows: served from the cache — the PRE-bump snapshot — even
  // though every +10 is provably applied server-side by now.  Own row:
  // our add invalidated it, so it re-fetches fresh.
  int peer = (me + 1) % n;
  int32_t prow[1] = {(int32_t)peer};
  std::vector<float> pv(4, -1.0f);
  CHECK(MV_GetMatrixTableByRows(hs, pv.data(), prow, 1, 4) == 0);
  for (float v : pv) CHECK(v == (float)(peer + 1));       // stale (cached)
  std::vector<float> ov(4, -1.0f);
  CHECK(MV_GetMatrixTableByRows(hs, ov.data(), my_row, 1, 4) == 0);
  for (float v : ov) CHECK(v == (float)(me + 11));        // fresh (own add)
  CHECK(MV_Barrier() == 0);                               // clock closes
  CHECK(MV_GetMatrixTableByRows(hs, pv.data(), prow, 1, 4) == 0);
  for (float v : pv) CHECK(v == (float)(peer + 11));      // now visible

  // KV cross-rank: every rank adds (rank+1) under a SHARED key (entries
  // hash-shard, so whichever rank owns it sees remote adds) plus its own
  // key; after the barrier every rank reads the merged map.
  int32_t hk;
  CHECK(MV_NewKVTable(&hk) == 0);
  CHECK(MV_Barrier() == 0);  // every rank registered the table
  char own_key[16];
  snprintf(own_key, sizeof(own_key), "rank_%d", me);
  CHECK(MV_AddKV(hk, "shared", (float)(me + 1)) == 0);
  CHECK(MV_AddAsyncKV(hk, own_key, 100.0f + static_cast<float>(me)) == 0);
  CHECK(MV_Barrier() == 0);  // async adds flushed, all ranks landed
  float kv = -1.0f;
  CHECK(MV_GetKV(hk, "shared", &kv) == 0);
  CHECK(kv == total);
  for (int r = 0; r < n; ++r) {
    char qk[16];
    snprintf(qk, sizeof(qk), "rank_%d", r);
    CHECK(MV_GetKV(hk, qk, &kv) == 0);
    CHECK(kv == 100.0f + static_cast<float>(r));
  }

  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("NET_CHILD_OK %d\n", me);
  return 0;
}

static int NetUpdaterChild(const char* machine_file, const char* rank,
                           const char* updater) {
  // Stateful-updater cross-rank scenario: every rank pushes identical
  // blocking deltas, the server shards apply them SEQUENTIALLY through
  // the stateful updater (slot state lives with the shard), and every
  // rank must read the same deterministic result.
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  std::string up = std::string("-updater_type=") + updater;
  const char* argv2[] = {mf.c_str(), rk.c_str(), up.c_str(),
                         "-log_level=error", "-rpc_timeout_ms=60000",
                         "-barrier_timeout_ms=60000"};
  CHECK(MV_Init(6, argv2) == 0);
  int me = MV_WorkerId();
  int n = MV_NumWorkers();
  CHECK(MV_SetAddOption(0.1f, 0.9f, 0.9f, 1e-8f) == 0);

  int32_t h;
  CHECK(MV_NewArrayTable(6, &h) == 0);
  CHECK(MV_Barrier() == 0);
  std::vector<float> ones(6, 1.0f), out(6, -1.0f);
  CHECK(MV_AddArrayTable(h, ones.data(), 6) == 0);  // blocking
  CHECK(MV_Barrier() == 0);                         // all n adds applied
  CHECK(MV_GetArrayTable(h, out.data(), 6) == 0);

  float want = 0.0f;
  if (std::string(updater) == "sgd") {
    want = -0.1f * static_cast<float>(n);                       // linear: order-free
  } else if (std::string(updater) == "adagrad") {
    // n sequential g=1 applies: w -= lr * g / sqrt(h_i), h_i = i
    for (int i = 1; i <= n; ++i) want -= 0.1f / sqrtf((float)i);
  } else if (std::string(updater) == "momentum") {
    // v_i = mu*v_{i-1} + lr;  w -= v_i  (identical g=1 deltas)
    float v = 0.0f;
    for (int i = 0; i < n; ++i) {
      v = 0.9f * v + 0.1f;
      want -= v;
    }
  } else if (std::string(updater) == "smooth_gradient") {
    // s_i = rho*s_{i-1} + (1-rho);  w -= lr*s_i
    float sgd_s = 0.0f;
    for (int i = 0; i < n; ++i) {
      sgd_s = 0.9f * sgd_s + 0.1f;
      want -= 0.1f * sgd_s;
    }
  } else {
    CHECK(false);
  }
  for (float v : out) CHECK(fabsf(v - want) < 1e-4f);

  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("NET_UPDATER_OK %d\n", me);
  return 0;
}

static int DeadPeerChild(const char* machine_file, const char* rank) {
  // One live rank; the OTHER endpoint has nothing listening.  Every
  // blocking call that needs the dead rank must ERROR within its
  // deadline — the round-2 behavior was an infinite hang.  Rank 0
  // exercises the quorum-timeout path (it is its own barrier
  // authority); rank 1 exercises the unreachable-authority path
  // (Deliver latches barrier_failed_ — a false "success" here would
  // silently break BSP).
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  const char* argv2[] = {mf.c_str(),       rk.c_str(),
                         "-updater_type=default", "-log_level=error",
                         "-connect_retry_ms=300", "-rpc_timeout_ms=3000",
                         "-barrier_timeout_ms=1000"};
  CHECK(MV_Init(7, argv2) == 0);
  int32_t h;
  CHECK(MV_NewArrayTable(10, &h) == 0);

  auto t0 = std::chrono::steady_clock::now();
  std::vector<float> out(10, 0.0f);
  CHECK(MV_GetArrayTable(h, out.data(), 10) == -3);  // peer unreachable
  std::vector<float> d(10, 1.0f);
  CHECK(MV_AddArrayTable(h, d.data(), 10) == -3);
  CHECK(MV_Barrier() == -3);
  CHECK(MV_Barrier() == -3);  // a retry must not fake a quorum either
  auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                std::chrono::steady_clock::now() - t0)
                .count();
  CHECK(ms < 20000);  // fail-fast, not rpc_timeout*calls hang
  CHECK(MV_ShutDown() == 0);  // barrier inside times out and proceeds
  printf("DEAD_PEER_OK\n");
  return 0;
}

static int DeadServerChild(const char* machine_file, const char* rank) {
  // Both ranks start and rendezvous; rank 1 then dies WITHOUT shutdown
  // (a crash).  Rank 0's next blocking Get must error within the
  // deadline instead of waiting forever on the never-coming reply.
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  const char* argv2[] = {mf.c_str(),       rk.c_str(),
                         "-updater_type=default", "-log_level=error",
                         "-connect_retry_ms=500", "-rpc_timeout_ms=2500",
                         "-barrier_timeout_ms=2000"};
  CHECK(MV_Init(7, argv2) == 0);
  int me = MV_WorkerId();
  int32_t h;
  CHECK(MV_NewArrayTable(10, &h) == 0);
  CHECK(MV_Barrier() == 0);
  if (me == 1) _exit(0);  // simulated crash: no shutdown, no goodbye

  std::this_thread::sleep_for(std::chrono::milliseconds(800));
  auto t0 = std::chrono::steady_clock::now();
  std::vector<float> out(10, 0.0f);
  CHECK(MV_GetArrayTable(h, out.data(), 10) == -3);
  auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                std::chrono::steady_clock::now() - t0)
                .count();
  CHECK(ms < 10000);
  CHECK(MV_ShutDown() == 0);
  printf("DEAD_SERVER_OK\n");
  return 0;
}

static int RegisterChild(const char* ctrl, const char* port,
                         const char* role, const char* num,
                         const char* is_ctrl) {
  // Dynamic registration scenario (reference Control_Register): three
  // processes — controller (role all), a worker-only node, a
  // server-only node — find each other through the controller alone (no
  // machine file, no -rank).  Tables shard across the TWO server-role
  // ranks; only the TWO worker-role ranks push/pull.
  std::string a_ctrl = std::string("-controller_endpoint=") + ctrl;
  std::string a_port = std::string("-port=") + port;
  std::string a_role = std::string("-role=") + role;
  std::string a_num = std::string("-num_nodes=") + num;
  std::string a_isc = std::string("-is_controller=") + is_ctrl;
  const char* argv2[] = {a_ctrl.c_str(), a_port.c_str(), a_role.c_str(),
                         a_num.c_str(),  a_isc.c_str(),
                         "-updater_type=default", "-log_level=error",
                         "-rpc_timeout_ms=60000",
                         "-barrier_timeout_ms=60000"};
  CHECK(MV_Init(9, argv2) == 0);
  int wid = MV_WorkerId(), sid = MV_ServerId();
  if (std::string(role) == "worker") CHECK(sid == -1 && wid >= 0);
  if (std::string(role) == "server") CHECK(wid == -1 && sid >= 0);
  if (std::string(role) == "all") CHECK(wid == 0 && sid == 0);
  CHECK(MV_NumWorkers() == 2);

  int32_t h;
  CHECK(MV_NewArrayTable(12, &h) == 0);
  int32_t hm;
  CHECK(MV_NewMatrixTable(6, 2, &hm) == 0);
  CHECK(MV_Barrier() == 0);

  if (wid >= 0) {
    std::vector<float> d(12, (float)(wid + 1));
    CHECK(MV_AddArrayTable(h, d.data(), 12) == 0);
    int32_t row = wid;
    std::vector<float> rd(2, (float)(wid + 1));
    CHECK(MV_AddMatrixTableByRows(hm, rd.data(), &row, 1, 2) == 0);
  }
  CHECK(MV_Barrier() == 0);
  if (wid >= 0) {
    std::vector<float> out(12, -1.0f);
    CHECK(MV_GetArrayTable(h, out.data(), 12) == 0);
    for (float v : out) CHECK(v == 3.0f);   // worker ids 0,1 → 1+2
    int32_t qrows[2] = {0, 1};
    std::vector<float> rout(4, -1.0f);
    CHECK(MV_GetMatrixTableByRows(hm, rout.data(), qrows, 2, 2) == 0);
    CHECK(rout[0] == 1.0f && rout[1] == 1.0f);
    CHECK(rout[2] == 2.0f && rout[3] == 2.0f);
  }
  // Store/Load are collective (internal barrier): EVERY rank calls them,
  // the worker-only rank contributes no shard but must not deadlock the
  // server ranks (each rank stores its own shard file, reference model).
  std::string ck = std::string("/tmp/mvtpu_register_ck_") + port + ".bin";
  CHECK(MV_StoreTable(h, ck.c_str()) == 0);
  if (wid >= 0) {
    std::vector<float> d(12, 100.0f);
    CHECK(MV_AddArrayTable(h, d.data(), 12) == 0);
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_LoadTable(h, ck.c_str()) == 0);
  CHECK(MV_Barrier() == 0);
  if (wid >= 0) {
    std::vector<float> out(12, -1.0f);
    CHECK(MV_GetArrayTable(h, out.data(), 12) == 0);
    for (float v : out) CHECK(v == 3.0f);  // post-store adds rolled back
  }

  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("REGISTER_OK %s\n", role);
  return 0;
}

static int SspChild(const char* machine_file, const char* rank,
                    const char* staleness) {
  // SSP scenario (SURVEY.md §2.9-bis, -staleness + MV_Clock): rank 0
  // races ahead while rank 1 lags ~1.5 s.  With s=1 the first fast-rank
  // Get OVERLAPS the straggler (admitted, no wait); one more clock and
  // the bound binds (held until the straggler's tick).  With s=0 every
  // ahead-Get is held — and the released read must include the
  // straggler's clock adds (ticks ride the connection BEHIND the adds),
  // which is exactly the BSP read guarantee.
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  std::string st = std::string("-staleness=") + staleness;
  const char* argv2[] = {mf.c_str(), rk.c_str(), st.c_str(),
                         "-updater_type=default", "-log_level=error",
                         "-rpc_timeout_ms=20000",
                         "-barrier_timeout_ms=20000"};
  CHECK(MV_Init(7, argv2) == 0);
  int me = MV_WorkerId();
  int s = atoi(staleness);
  int32_t h;
  CHECK(MV_NewArrayTable(4, &h) == 0);
  CHECK(MV_Barrier() == 0);

  if (me == 1) {
    // The straggler: adds for its clock 1, then ticks, 1.5 s late.
    std::this_thread::sleep_for(std::chrono::milliseconds(1500));
    std::vector<float> twos(4, 2.0f);
    CHECK(MV_AddAsyncArrayTable(h, twos.data(), 4) == 0);
    CHECK(MV_Clock() == 0);
  } else {
    auto t0 = std::chrono::steady_clock::now();
    std::vector<float> ones(4, 1.0f), out(4, -1.0f);
    CHECK(MV_AddArrayTable(h, ones.data(), 4) == 0);
    CHECK(MV_Clock() == 0);  // clock 1
    CHECK(MV_GetArrayTable(h, out.data(), 4) == 0);
    auto ms1 = std::chrono::duration_cast<std::chrono::milliseconds>(
                   std::chrono::steady_clock::now() - t0)
                   .count();
    if (s >= 1) {
      // Overlap: admitted while 1 - 0 <= s, no straggler wait.
      CHECK(ms1 < 1000);
      CHECK(MV_Clock() == 0);  // clock 2: now 2 - 0 > s — must hold
      CHECK(MV_GetArrayTable(h, out.data(), 4) == 0);
    }
    // (s=0: the first Get itself was the held one.)
    auto ms2 = std::chrono::duration_cast<std::chrono::milliseconds>(
                   std::chrono::steady_clock::now() - t0)
                   .count();
    CHECK(ms2 >= 1200);  // held until the straggler's tick
    // Released read includes the straggler's clock-1 adds (BSP read).
    for (float v : out) CHECK(v == 3.0f);
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("SSP_OK %d s=%d\n", me, s);
  return 0;
}

static int BackupChild(const char* machine_file, const char* rank,
                       const char* ratio) {
  // backup_worker_ratio scenario (reference server.h sync variant,
  // SURVEY §2.9; VERDICT r4 action 3): 3 workers, staleness 0.  Ranks
  // 0/1 add + tick clock 1 immediately; rank 2 is a deliberate ~1.5 s
  // straggler.  With -backup_worker_ratio=0.34 the quorum is
  // ceil(0.66*3)=2, so the fast ranks' clock-1 reads admit as soon as
  // BOTH fast ranks ticked — no straggler wait (asserted < 1000 ms).
  // With ratio=0 (control) the same reads park until the straggler's
  // tick (asserted >= 1200 ms) — the quorum releases only because of
  // the ratio.  Either way the straggler's adds are never dropped:
  // after the final barrier every rank reads the full sum.
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  std::string rt = std::string("-backup_worker_ratio=") + ratio;
  const char* argv2[] = {mf.c_str(), rk.c_str(), rt.c_str(),
                         "-staleness=0",  "-updater_type=default",
                         "-log_level=error", "-rpc_timeout_ms=20000",
                         "-barrier_timeout_ms=20000"};
  CHECK(MV_Init(8, argv2) == 0);
  int me = MV_WorkerId();
  bool slack = atof(ratio) > 0.0;
  int32_t h;
  CHECK(MV_NewArrayTable(4, &h) == 0);
  CHECK(MV_Barrier() == 0);

  if (me == 2) {
    // The straggler: its clock-1 work lands ~1.5 s late.
    std::this_thread::sleep_for(std::chrono::milliseconds(1500));
    std::vector<float> twos(4, 2.0f);
    CHECK(MV_AddAsyncArrayTable(h, twos.data(), 4) == 0);
    CHECK(MV_Clock() == 0);
  } else {
    auto t0 = std::chrono::steady_clock::now();
    std::vector<float> ones(4, 1.0f), out(4, -1.0f);
    CHECK(MV_AddArrayTable(h, ones.data(), 4) == 0);
    CHECK(MV_Clock() == 0);  // clock 1
    CHECK(MV_GetArrayTable(h, out.data(), 4) == 0);
    auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                  std::chrono::steady_clock::now() - t0)
                  .count();
    if (slack) {
      CHECK(ms < 1000);    // quorum of 2 released without the straggler
      // Quorum-released read carries at least both fast ranks' adds
      // (the straggler's may or may not have landed — ASP fold).
      for (float v : out) CHECK(v >= 2.0f);
    } else {
      CHECK(ms >= 1200);   // control: parked until the straggler's tick
      for (float v : out) CHECK(v == 4.0f);  // BSP read: all adds
    }
  }
  // Straggler catch-up fence, then the consistency check: no add was
  // dropped by the quorum release.
  CHECK(MV_Barrier() == 0);
  std::vector<float> fin(4, -1.0f);
  CHECK(MV_GetArrayTable(h, fin.data(), 4) == 0);
  for (float v : fin) CHECK(v == 4.0f);
  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("BACKUP_OK %d ratio=%s\n", me, ratio);
  return 0;
}

static int SspThroughputChild(const char* machine_file, const char* rank,
                              const char* staleness) {
  // SSP-earns-its-keep scenario (VERDICT r4 action 7): 2 workers, 10
  // clocks.  Rank 0 computes a steady 40 ms per clock; rank 1 is a
  // JITTERY straggler — alternating 0 / 160 ms (same 80 ms average).
  // With -staleness=0 every rank-0 read rendezvouses with the
  // straggler's CURRENT clock, so rank 0 pays the straggler's
  // worst-case path.  With -staleness=3 the window absorbs the
  // alternation — rank 0 only ever waits for clock c-3, which the
  // straggler's average pace has long passed.  Rank 0 prints its timed
  // window; the pytest side runs both modes and asserts the SSP run is
  // meaningfully faster on the SAME straggler profile.
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  std::string st = std::string("-staleness=") + staleness;
  const char* argv2[] = {mf.c_str(), rk.c_str(), st.c_str(),
                         "-updater_type=default", "-log_level=error",
                         "-rpc_timeout_ms=30000",
                         "-barrier_timeout_ms=30000"};
  CHECK(MV_Init(7, argv2) == 0);
  int me = MV_WorkerId();
  const int kClocks = 10;
  int32_t h;
  CHECK(MV_NewArrayTable(8, &h) == 0);
  CHECK(MV_Barrier() == 0);

  std::vector<float> delta(8, 1.0f), out(8, 0.0f);
  auto t0 = std::chrono::steady_clock::now();
  for (int c = 0; c < kClocks; ++c) {
    int ms = (me == 0) ? 40 : ((c % 2) ? 160 : 0);   // the "compute"
    std::this_thread::sleep_for(std::chrono::milliseconds(ms));
    CHECK(MV_AddAsyncArrayTable(h, delta.data(), 8) == 0);
    CHECK(MV_Clock() == 0);
    CHECK(MV_GetArrayTable(h, out.data(), 8) == 0);  // SSP-gated read
  }
  auto dt_ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                   std::chrono::steady_clock::now() - t0)
                   .count();
  if (me == 0)
    printf("SSP_TPUT ms=%lld staleness=%s\n",
           static_cast<long long>(dt_ms), staleness);
  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("SSP_TPUT_OK %d\n", me);
  return 0;
}

static int SspDeadChild(const char* machine_file, const char* rank) {
  // SSP + dead straggler: rank 1 rendezvouses then crashes without ever
  // ticking.  Rank 0 races ahead; its held Gets must fail fast (rc=-3,
  // bounded by -rpc_timeout_ms) and repeated attempts must keep failing
  // fast — each park purges the previous expired one (no unbounded
  // held_gets_ growth, no hang).
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  const char* argv2[] = {mf.c_str(), rk.c_str(), "-staleness=0",
                         "-updater_type=default", "-log_level=error",
                         "-connect_retry_ms=500", "-rpc_timeout_ms=2000",
                         "-barrier_timeout_ms=2000"};
  CHECK(MV_Init(8, argv2) == 0);
  int me = MV_WorkerId();
  int32_t h;
  CHECK(MV_NewArrayTable(4, &h) == 0);
  CHECK(MV_Barrier() == 0);
  if (me == 1) _exit(0);  // crash before any MV_Clock

  std::this_thread::sleep_for(std::chrono::milliseconds(300));
  CHECK(MV_Clock() == 0);  // now ahead of the dead rank 1 forever
  auto t0 = std::chrono::steady_clock::now();
  std::vector<float> out(4, 0.0f);
  CHECK(MV_GetArrayTable(h, out.data(), 4) == -3);
  CHECK(MV_GetArrayTable(h, out.data(), 4) == -3);  // retry also bounded
  auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                std::chrono::steady_clock::now() - t0)
                .count();
  CHECK(ms < 15000);
  CHECK(MV_ShutDown() == 0);
  printf("SSP_DEAD_OK\n");
  return 0;
}

// Scenario children: a CHECK failure returns without MV_ShutDown, and
// live runtime threads then crash during normal process exit (rc=-11),
// MPI scenarios (SURVEY §2.17, reference net/mpi_net.h).  MPI allows one
// init/finalize cycle per process, so each scenario is its own argv[1]
// dispatch (own subprocess from pytest).  When no usable libmpi resolves
// they print MPI_UNAVAILABLE and exit 0 — the pytest side skips.

// Direct wire exercise: a Message with real float payload rides MPI to
// this rank (self-send traverses the actual transport — MpiNet::Send →
// MPI_Send → probe thread → inbound callback; the Zoo's local-dst
// shortcut is deliberately not in the path).
static int MpiSelfScenario() {
  if (!mvtpu::MpiNet::Available()) {
    printf("MPI_UNAVAILABLE\n");
    return 0;
  }
  mvtpu::MpiNet net;
  mvtpu::MtQueue<mvtpu::Message> inbox;
  CHECK(net.Init([&](mvtpu::Message&& m) { inbox.Push(std::move(m)); }));
  CHECK(net.size() >= 1);

  mvtpu::Message msg;
  msg.src = net.rank();
  msg.dst = net.rank();
  msg.type = mvtpu::MsgType::RequestAdd;
  msg.table_id = 7;
  msg.msg_id = 1234;
  mvtpu::Blob payload(4 * sizeof(float));
  for (int i = 0; i < 4; ++i) payload.As<float>()[i] = 0.5f * static_cast<float>(i);
  msg.data.push_back(payload);
  CHECK(net.Send(net.rank(), msg));

  mvtpu::Message got;
  CHECK(inbox.Pop(&got));
  CHECK(got.src == net.rank() && got.dst == net.rank());
  CHECK(got.type == mvtpu::MsgType::RequestAdd);
  CHECK(got.table_id == 7 && got.msg_id == 1234);
  CHECK(got.data.size() == 1 && got.data[0].count<float>() == 4);
  for (int i = 0; i < 4; ++i)
    CHECK(std::fabs(got.data[0].As<float>()[i] - 0.5f * static_cast<float>(i)) < 1e-6f);

  // Unknown rank → clean false, not an MPI abort.
  CHECK(!net.Send(net.size() + 3, msg));

  // Concurrent senders: 4 threads x 50 sends through the serial-mode
  // lock (Isend + Test polling) while the probe thread drains — the
  // exact interleaving a worker/server pair generates under load.
  std::atomic<int> sent{0};
  std::vector<std::thread> senders;
  for (int s = 0; s < 4; ++s)
    senders.emplace_back([&net, &sent, &msg] {
      for (int i = 0; i < 50; ++i)
        if (net.Send(net.rank(), msg)) ++sent;
    });
  for (auto& t : senders) t.join();
  CHECK(sent.load() == 200);
  for (int i = 0; i < 200; ++i) {
    mvtpu::Message m;
    CHECK(inbox.Pop(&m));
    CHECK(m.table_id == 7 && m.data.size() == 1);
  }
  // Every send above completed or failed before Isend (unknown rank):
  // no payload may be parked in the orphan list — an increment here
  // would mean the error/timeout path fired on a healthy transport.
  CHECK(mvtpu::MpiNet::OrphanedSendBufCount() == 0);
  net.Stop();
  printf("MPI_SELF_OK rank=%d size=%d\n", net.rank(), net.size());
  return 0;
}

// Full runtime lifecycle over the MPI transport: MV_Init with
// -net_type=mpi (isolated singleton under a plain launch; the same path
// serves mpirun-launched jobs), table round trips, clean shutdown.
static int MpiZooScenario() {
  if (!mvtpu::MpiNet::Available()) {
    printf("MPI_UNAVAILABLE\n");
    return 0;
  }
  const char* argv[] = {"-net_type=mpi", "-updater_type=default",
                        "-log_level=error"};
  CHECK(MV_Init(3, argv) == 0);
  CHECK(MV_NumWorkers() >= 1);
  int32_t h = -1;
  CHECK(MV_NewArrayTable(16, &h) == 0);
  std::vector<float> delta(16, 2.0f), out(16, 0.0f);
  CHECK(MV_AddArrayTable(h, delta.data(), 16) == 0);
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetArrayTable(h, out.data(), 16) == 0);
  for (float v : out) CHECK(std::fabs(v - 2.0f) < 1e-6f);
  CHECK(MV_ShutDown() == 0);
  printf("MPI_ZOO_OK\n");
  return 0;
}

static int WireBenchChild(const char* machine_file, const char* rank,
                          const char* net_type) {
  // Direct transport microbench (VERDICT r4 action 6): message-size
  // sweep at the Net layer itself — no tables, no updaters — so a
  // transport regression is visible independent of the LR/w2v
  // aggregates.  Protocol per size S in 4 KiB → 16 MiB:
  //   put: rank 0 fires K S-byte messages at rank 1; rank 1 acks once
  //        after the K-th (time ≈ K·S / one-way bandwidth).
  //   get: rank 0 sends one tiny request; rank 1 answers K S-byte
  //        messages (the reply-payload direction).
  //   rtt: median of 64 empty round trips.
  // Output: one "WIRE <size> <put_gbps> <get_gbps> <rtt_ms>" line per
  // size on rank 0, parsed by bench.py into wire_{tcp,mpi}_* keys.
  using mvtpu::Blob;
  using mvtpu::Message;
  using mvtpu::MsgType;
  // net_type: "tcp" | "epoll" (rank transports via the -net_engine
  // factory seam) | "mpi" (the literal MPI wire).
  const bool mpi = std::string(net_type) == "mpi";
  int me = atoi(rank);

  // Payload sizes; K scaled so each probe moves ~32 MiB.
  const size_t kSizes[] = {4 << 10, 64 << 10, 1 << 20, 16 << 20};
  const int kNumSizes = 4, kPings = 64;
  auto burst_len = [](size_t s) {
    return std::max(2, (int)((32u << 20) / s));
  };

  // Directional protocol (each counter only ever counts the peer's
  // sends): rank 0 receives ReplyFlush (ping echo), ReplyAdd (burst
  // ack), RequestAdd (get payloads); rank 1 receives RequestFlush
  // (ping), RequestAdd (put payloads), RequestGet (serve request),
  // ControlRegister (done sentinel).
  std::atomic<int> pings{0}, payloads{0}, get_reqs{0}, echoes{0},
      burst_acks{0}, done{0};

  std::unique_ptr<mvtpu::RankTransport> rank_net;
  mvtpu::MpiNet mpin;
  mvtpu::Net* net = nullptr;
  auto inbound = [&](Message&& m) {
    switch (m.type) {
      case MsgType::RequestFlush: pings.fetch_add(1); break;
      case MsgType::ReplyFlush: echoes.fetch_add(1); break;
      case MsgType::RequestAdd: payloads.fetch_add(1); break;
      case MsgType::ReplyAdd: burst_acks.fetch_add(1); break;
      case MsgType::RequestGet: get_reqs.fetch_add(1); break;
      case MsgType::ControlRegister: done.store(1); break;
      default: break;
    }
  };
  if (mpi) {
    if (!mvtpu::MpiNet::Available()) {
      printf("MPI_UNAVAILABLE\n");
      return 0;
    }
    CHECK(mpin.Init(inbound));
    if (mpin.size() < 2) {
      // No mpirun in the image: singleton mode gives size 1 — report
      // and succeed so the bench can skip the MPI sweep cleanly.
      printf("WIRE_MPI_SINGLETON\n");
      mpin.Stop();
      return 0;
    }
    net = &mpin;
    me = mpin.rank();
  } else {
    auto eps = mvtpu::TcpNet::ParseMachineFile(machine_file);
    CHECK(eps.size() == 2);
    rank_net = mvtpu::MakeRankTransport(net_type);
    CHECK(rank_net != nullptr);
    CHECK(rank_net->Init(eps, me, inbound, 15000));
    net = rank_net.get();
  }

  auto mk = [&](MsgType t, size_t bytes) {
    Message m;
    m.type = t;
    m.src = me;
    m.dst = 1 - me;
    m.msg_id = 0;
    m.table_id = 0;
    if (bytes) {
      Blob b(bytes);
      memset(b.data(), 7, bytes);
      m.data.push_back(std::move(b));
    }
    return m;
  };
  auto wait_until = [&](std::atomic<int>& ctr, int target) {
    while (ctr.load() < target)
      std::this_thread::sleep_for(std::chrono::microseconds(50));
  };
  auto now = [] { return std::chrono::steady_clock::now(); };
  auto secs = [](auto d) {
    return std::chrono::duration<double>(d).count();
  };

  if (me == 0) {
    // Ping 0 is the startup rendezvous; 1..kPings time the RTT.
    std::vector<double> rtts;
    for (int i = 0; i <= kPings; ++i) {
      auto t0 = now();
      CHECK(net->Send(1, mk(MsgType::RequestFlush, 0)));
      wait_until(echoes, i + 1);
      if (i > 0) rtts.push_back(secs(now() - t0));
    }
    std::sort(rtts.begin(), rtts.end());
    double rtt_ms = rtts[rtts.size() / 2] * 1e3;

    int acks_seen = 0, payloads_seen = 0;
    for (size_t S : kSizes) {
      int K = burst_len(S);
      // put: K payloads, then the peer's counted ack.
      auto t0 = now();
      for (int i = 0; i < K; ++i)
        CHECK(net->Send(1, mk(MsgType::RequestAdd, S)));
      wait_until(burst_acks, ++acks_seen);
      double put_gbps = (double)K * (double)S / secs(now() - t0) / 1e9;
      // get: one request, K payloads back.
      t0 = now();
      CHECK(net->Send(1, mk(MsgType::RequestGet, 0)));
      payloads_seen += K;
      wait_until(payloads, payloads_seen);
      double get_gbps = (double)K * (double)S / secs(now() - t0) / 1e9;
      printf("WIRE %zu %.4f %.4f %.4f\n", S, put_gbps, get_gbps, rtt_ms);
    }
    CHECK(net->Send(1, mk(MsgType::ControlRegister, 0)));  // done
  } else {
    // Peer state machine: echo pings, ack completed put bursts (sizes
    // arrive in order), serve get requests, exit on the sentinel.
    int echoed = 0, served = 0, acked = 0, burst_base = 0;
    while (!done.load()) {
      while (echoed < pings.load()) {
        ++echoed;
        CHECK(net->Send(0, mk(MsgType::ReplyFlush, 0)));
      }
      if (acked < kNumSizes) {
        int K = burst_len(kSizes[acked]);
        if (payloads.load() - burst_base >= K) {
          burst_base += K;
          ++acked;
          CHECK(net->Send(0, mk(MsgType::ReplyAdd, 0)));
        }
      }
      if (served < get_reqs.load() && served < kNumSizes) {
        size_t S = kSizes[served];
        int K = burst_len(S);
        for (int i = 0; i < K; ++i)
          CHECK(net->Send(0, mk(MsgType::RequestAdd, S)));
        ++served;
      }
      std::this_thread::sleep_for(std::chrono::microseconds(50));
    }
  }
  net->Stop();
  printf("WIRE_BENCH_OK %d\n", me);
  return 0;
}

static int CodecWireChild(const char* machine_file, const char* rank) {
  // Compressed data plane acceptance (docs/wire_compression.md): the
  // SAME dense-add workload over the 2-process wire, once on the raw
  // codec and once on 1bit, measured via the net.bytes.sent ledger
  // (MV_WireStats).  1bit must ship >= 3x fewer bytes (it actually
  // ships ~30x fewer; the bar leaves room for framing/control traffic)
  // and the served values must stay within tolerance thanks to the
  // worker-side error feedback.  Rank 0 prints one
  //   CODEC <name> bytes=<b> msgs=<m> secs=<s>
  // line per phase (bench.py's wire_{raw,1bit}_* keys) plus the
  // headline ratio.
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  const char* argv2[] = {mf.c_str(), rk.c_str(), "-updater_type=default",
                         "-log_level=error", "-rpc_timeout_ms=60000",
                         "-barrier_timeout_ms=60000"};
  CHECK(MV_Init(6, argv2) == 0);
  int me = MV_WorkerId();
  const int64_t kN = 1 << 16;  // 256 KiB of payload per full add
  const int kAdds = 8;
  std::vector<float> delta(kN), out(kN, -1.0f);
  // Per-add rotation of the deviation pattern (delta depends on i + a):
  // over kAdds (two full cycles of 4) every element's true sum is
  // kAdds * 1.375 EXACTLY, and the 1-bit error-feedback residual stays
  // bounded (a constant per-element deviation would instead grow it
  // linearly — the known two-scale-quantizer pathology real gradients
  // don't exhibit).
  auto fill_delta = [&](int a) {
    for (int64_t i = 0; i < kN; ++i)
      delta[i] = 1.0f + 0.25f * static_cast<float>((i + a) % 4);
  };
  double mean = 1.0 + 0.25 * (0 + 1 + 2 + 3) / 4.0;  // 1.375

  auto sent_bytes = []() -> long long {
    long long sb = 0, rb = 0, sm = 0, rm = 0;
    if (MV_WireStats(&sb, &rb, &sm, &rm) != 0) return -1;
    return sb;
  };
  auto sent_msgs = []() -> long long {
    long long sb = 0, rb = 0, sm = 0, rm = 0;
    if (MV_WireStats(&sb, &rb, &sm, &rm) != 0) return -1;
    return sm;
  };
  auto now = [] { return std::chrono::steady_clock::now(); };
  auto secs = [](auto d) {
    return std::chrono::duration<double>(d).count();
  };

  long long phase_bytes[2] = {0, 0}, phase_msgs[2] = {0, 0};
  double phase_secs[2] = {0, 0};
  const char* names[2] = {"raw", "1bit"};
  for (int phase = 0; phase < 2; ++phase) {
    int32_t h;
    CHECK(MV_NewArrayTable(kN, &h) == 0);
    if (phase == 1) CHECK(MV_SetTableCodec(h, "1bit") == 0);
    CHECK(MV_Barrier() == 0);
    long long b0 = sent_bytes(), m0 = sent_msgs();
    auto t0 = now();
    if (me == 0)
      for (int a = 0; a < kAdds; ++a) {
        fill_delta(a);
        CHECK(MV_AddArrayTable(h, delta.data(), kN) == 0);
      }
    CHECK(MV_Barrier() == 0);
    phase_secs[phase] = secs(now() - t0);
    phase_bytes[phase] = sent_bytes() - b0;
    phase_msgs[phase] = sent_msgs() - m0;
    CHECK(MV_GetArrayTable(h, out.data(), kN) == 0);
    const double want = kAdds * mean;  // exact per element (full cycles)
    if (phase == 0) {
      for (int64_t i = 0; i < kN; ++i)
        CHECK(fabs(out[i] - want) < 1e-3);
    } else {
      // 1bit + error feedback: per-element error bounded by the
      // un-flushed residual (~one deviation cycle's spread); the MEAN
      // is preserved tightly — comfortably inside the 5% loss bar.
      double sum = 0.0;
      for (int64_t i = 0; i < kN; ++i) {
        sum += out[i];
        CHECK(fabs(out[i] - want) < 1.5);
      }
      double got_mean = sum / static_cast<double>(kN);
      CHECK(fabs(got_mean - want) / want < 0.02);
    }
    CHECK(MV_Barrier() == 0);
  }
  if (me == 0) {
    CHECK(phase_bytes[0] > 0 && phase_bytes[1] > 0);
    double ratio = static_cast<double>(phase_bytes[0]) /
                   static_cast<double>(phase_bytes[1]);
    for (int p = 0; p < 2; ++p)
      printf("CODEC %s bytes=%lld msgs=%lld secs=%.4f\n", names[p],
             phase_bytes[p], phase_msgs[p], phase_secs[p]);
    printf("CODEC_RATIO %.2f\n", ratio);
    CHECK(ratio >= 3.0);  // acceptance bar (measured ~20-30x)
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("CODEC_WIRE_OK %d\n", me);
  return 0;
}

static int AggChild(const char* machine_file, const char* rank,
                    const char* engine) {
  // Worker-side add aggregation (docs/wire_compression.md): async dense
  // adds sum into a local buffer and ship as ONE wire message per flush
  // window; Get, Clock, and Barrier all force the flush, so read and
  // BSP/SSP visibility semantics are unchanged.  Counters: agg.adds
  // (absorbed adds), agg.flush (windows shipped).
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  std::string eng = std::string("-net_engine=") + engine;
  const char* argv2[] = {mf.c_str(), rk.c_str(), eng.c_str(),
                         "-updater_type=default",
                         "-log_level=error", "-rpc_timeout_ms=60000",
                         "-barrier_timeout_ms=60000",
                         "-add_agg_bytes=16777216"};
  CHECK(MV_Init(8, argv2) == 0);
  int me = MV_WorkerId();
  int32_t h;
  CHECK(MV_NewArrayTable(16, &h) == 0);
  CHECK(MV_Barrier() == 0);
  std::vector<float> ones(16, 1.0f), out(16, -1.0f);
  long long adds = 0, flushes = 0;

  // Phase 1 — flush-on-Get: 6 tiny async adds collapse into one wire
  // message; the Get that follows must still read its own writes.
  if (me == 0) {
    for (int i = 0; i < 6; ++i)
      CHECK(MV_AddAsyncArrayTable(h, ones.data(), 16) == 0);
    CHECK(MV_QueryMonitor("agg.flush", &flushes) == 0);
    CHECK(flushes == 0);  // still buffered — nothing on the wire yet
    CHECK(MV_GetArrayTable(h, out.data(), 16) == 0);
    for (float v : out) CHECK(v == 6.0f);  // read-your-writes held
    CHECK(MV_QueryMonitor("agg.adds", &adds) == 0);
    CHECK(MV_QueryMonitor("agg.flush", &flushes) == 0);
    CHECK(adds == 6);
    CHECK(flushes == 1);  // >= 4 adds collapsed into ONE message
  }
  CHECK(MV_Barrier() == 0);

  // Phase 2 — flush-on-Clock: the SSP tick must ride BEHIND the
  // aggregated adds it announces.
  if (me == 0) {
    for (int i = 0; i < 4; ++i)
      CHECK(MV_AddAsyncArrayTable(h, ones.data(), 16) == 0);
    CHECK(MV_Clock() == 0);
    CHECK(MV_QueryMonitor("agg.flush", &flushes) == 0);
    CHECK(flushes == 2);
  } else {
    CHECK(MV_Clock() == 0);  // keep the worker clocks aligned
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetArrayTable(h, out.data(), 16) == 0);
  for (float v : out) CHECK(v == 10.0f);  // both ranks see 6 + 4
  // Rendezvous between rounds (the NetChild race note): without this,
  // a slow rank's verify-Get races the fast rank's next-phase async
  // adds — the blocking engine's synchronous Send masked the window,
  // the reactor's enqueue-and-return Send opens it.
  CHECK(MV_Barrier() == 0);

  // Phase 3 — flush-on-Barrier: BSP visibility for aggregated adds.
  if (me == 0) {
    for (int i = 0; i < 5; ++i)
      CHECK(MV_AddAsyncArrayTable(h, ones.data(), 16) == 0);
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetArrayTable(h, out.data(), 16) == 0);
  for (float v : out) CHECK(v == 15.0f);
  if (me == 0) {
    CHECK(MV_QueryMonitor("agg.adds", &adds) == 0);
    CHECK(MV_QueryMonitor("agg.flush", &flushes) == 0);
    CHECK(adds == 15);
    CHECK(flushes == 3);
  }
  CHECK(MV_Barrier() == 0);  // same verify-vs-next-round fence as above

  // Phase 4 — explicit flush (MV_FlushAdds) + blocking-add ordering:
  // a blocking add flushes the buffer first, so its ack covers both.
  if (me == 0) {
    CHECK(MV_AddAsyncArrayTable(h, ones.data(), 16) == 0);
    CHECK(MV_FlushAdds(h) == 0);
    CHECK(MV_QueryMonitor("agg.flush", &flushes) == 0);
    CHECK(flushes == 4);
    CHECK(MV_AddAsyncArrayTable(h, ones.data(), 16) == 0);
    CHECK(MV_AddArrayTable(h, ones.data(), 16) == 0);  // blocking
    CHECK(MV_GetArrayTable(h, out.data(), 16) == 0);
    for (float v : out) CHECK(v == 18.0f);
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("AGG_OK %d\n", me);
  return 0;
}

static int AggBenchChild(const char* machine_file, const char* rank) {
  // Aggregation throughput probe (bench.py add_agg keys): rank 0 fires
  // bursts of small async adds under an armed aggregation window and
  // reports the adds-per-wire-message collapse ratio from the
  // agg.adds/agg.flush counters.  Correctness is asserted (the final
  // read must equal the add count) so the numbers can't be "fast but
  // wrong".
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  const char* argv2[] = {mf.c_str(), rk.c_str(), "-updater_type=default",
                         "-log_level=error", "-rpc_timeout_ms=60000",
                         "-barrier_timeout_ms=60000",
                         "-add_agg_bytes=262144"};
  CHECK(MV_Init(7, argv2) == 0);
  int me = MV_WorkerId();
  const int64_t kN = 1024;     // 4 KiB per add
  const int kBursts = 16, kPerBurst = 16;
  int32_t h;
  CHECK(MV_NewArrayTable(kN, &h) == 0);
  CHECK(MV_Barrier() == 0);
  std::vector<float> ones(kN, 1.0f), out(kN, -1.0f);
  auto t0 = std::chrono::steady_clock::now();
  if (me == 0) {
    for (int b = 0; b < kBursts; ++b) {
      for (int i = 0; i < kPerBurst; ++i)
        CHECK(MV_AddAsyncArrayTable(h, ones.data(), kN) == 0);
      CHECK(MV_FlushAdds(h) == 0);
    }
  }
  CHECK(MV_Barrier() == 0);
  double secs = std::chrono::duration<double>(
                    std::chrono::steady_clock::now() - t0)
                    .count();
  CHECK(MV_GetArrayTable(h, out.data(), kN) == 0);
  for (float v : out) CHECK(v == (float)(kBursts * kPerBurst));
  if (me == 0) {
    long long adds = 0, flushes = 0;
    CHECK(MV_QueryMonitor("agg.adds", &adds) == 0);
    CHECK(MV_QueryMonitor("agg.flush", &flushes) == 0);
    CHECK(adds == (long long)kBursts * kPerBurst);
    CHECK(flushes >= 1 && adds / flushes >= 4);
    printf("AGG_BENCH adds=%lld flushes=%lld secs=%.4f\n", adds, flushes,
           secs);
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("AGG_BENCH_OK %d\n", me);
  return 0;
}

static int AsyncOverlapChild(const char* machine_file, const char* rank) {
  // Async Get overlap scenario (reference WorkerTable::GetAsync + Wait,
  // SURVEY.md §2.10 / the AsyncBuffer idiom §2.24): the pull must make
  // wire progress WHILE the caller computes.  Protocol on rank 0: time
  // a blocking GetRows of a wire-heavy row set; start the identical
  // pull async; spend ~3x the blocking time "computing" (sleep); then
  // Wait() — which must return in well under the blocking time, since
  // the shards answered during the compute.  Bounds are generous (half
  // the blocking time plus 50 ms absolute slack) so a loaded CI host
  // cannot flake the assertion; the w2v native bench carries the
  // quantitative overlap claim.
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  const char* argv2[] = {mf.c_str(), rk.c_str(), "-updater_type=default",
                         "-log_level=error", "-rpc_timeout_ms=60000",
                         "-barrier_timeout_ms=60000"};
  CHECK(MV_Init(6, argv2) == 0);
  int me = MV_WorkerId();
  const int64_t R = 20000, C = 128, K = 16000;   // pull ~8 MB of rows
  int32_t hm;
  CHECK(MV_NewMatrixTable(R, C, &hm) == 0);
  CHECK(MV_Barrier() == 0);
  if (me == 0) {
    std::vector<float> ones(R * C, 1.0f);
    CHECK(MV_AddMatrixTableAll(hm, ones.data(), R * C) == 0);
  }
  CHECK(MV_Barrier() == 0);  // the add is visible everywhere

  if (me == 0) {
    std::vector<int32_t> ids(K);
    for (int64_t i = 0; i < K; ++i)
      ids[i] = static_cast<int32_t>((i * 2654435761ull) % R);
    std::vector<float> out1(K * C, -1.0f), out2(K * C, -1.0f);
    auto now = [] { return std::chrono::steady_clock::now(); };
    auto secs = [](auto d) {
      return std::chrono::duration<double>(d).count();
    };

    auto t0 = now();
    CHECK(MV_GetMatrixTableByRows(hm, out1.data(), ids.data(), K, C) == 0);
    double t_sync = secs(now() - t0);

    int32_t ticket = -1;
    t0 = now();
    CHECK(MV_GetAsyncMatrixTableByRows(hm, out2.data(), ids.data(), K, C,
                                       &ticket) == 0);
    double t_start = secs(now() - t0);
    // The start call must not secretly block for the round trip.
    CHECK(t_start < t_sync * 0.5 + 0.05);
    std::this_thread::sleep_for(std::chrono::duration<double>(
        t_sync * 3.0 + 0.05));                     // the "compute"
    t0 = now();
    CHECK(MV_WaitGet(ticket) == 0);
    double t_wait = secs(now() - t0);
    CHECK(t_wait < t_sync * 0.5 + 0.05);           // overlapped, not serial
    CHECK(MV_WaitGet(ticket) == -2);               // ticket consumed
    for (int64_t i = 0; i < K * C; i += 997)
      CHECK(out2[i] == 1.0f);
    printf("overlap: sync=%.3fs start=%.4fs wait=%.4fs\n", t_sync,
           t_start, t_wait);
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("ASYNC_OVERLAP_OK %d\n", me);
  return 0;
}

// ---------------------------------------------------------------- chaos
// Scripted-failure scenarios (docs/fault_tolerance.md): the injection
// hooks in mvtpu/fault.h let these DRIVE the failure modes the dead_*
// scenarios can only approximate with real process death.  All run with
// a fixed seed so CI is deterministic.

static int ChaosRetryChild(const char* machine_file, const char* rank,
                           const char* engine) {
  // Send retry-then-succeed: the first two write attempts of rank 0's
  // blocking Add are injected failures; the bounded-backoff retry loop
  // reconnects and lands the delta.  Proves retries are counted and the
  // payload survives the faulty wire — on EITHER engine (the fault seam
  // consumes an attempt the same way on the reactor path).
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  std::string eng = std::string("-net_engine=") + engine;
  const char* argv2[] = {mf.c_str(), rk.c_str(), eng.c_str(),
                         "-updater_type=default",
                         "-log_level=error", "-rpc_timeout_ms=30000",
                         "-barrier_timeout_ms=30000", "-send_retries=3",
                         "-send_backoff_ms=20", "-connect_retry_ms=2000"};
  CHECK(MV_Init(10, argv2) == 0);
  CHECK(MV_SetFaultSeed(1234) == 0);
  int me = MV_WorkerId();
  int32_t h;
  CHECK(MV_NewArrayTable(10, &h) == 0);
  CHECK(MV_Barrier() == 0);
  if (me == 0) {
    CHECK(MV_SetFaultN("fail_send", 2) == 0);
    std::vector<float> ones(10, 1.0f);
    CHECK(MV_AddArrayTable(h, ones.data(), 10) == 0);  // survives the faults
    long long retries = 0, injected = 0;
    CHECK(MV_QueryMonitor("net.retries", &retries) == 0);
    CHECK(MV_QueryMonitor("fault.fail_send", &injected) == 0);
    CHECK(retries >= 2);
    CHECK(injected == 2);
    CHECK(MV_ClearFaults() == 0);
  }
  CHECK(MV_Barrier() == 0);
  std::vector<float> out(10, -1.0f);
  CHECK(MV_GetArrayTable(h, out.data(), 10) == 0);
  for (float v : out) CHECK(v == 1.0f);
  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("CHAOS_RETRY_OK %d\n", me);
  return 0;
}

static int ChaosDropDupChild(const char* machine_file, const char* rank) {
  // Lossy/duplicating wire: rank 0 drops exactly one async-add message
  // (the remote shard misses the delta; the local shard applies), then
  // duplicates exactly one (the remote shard double-applies) — counters
  // and values both assert the injected behavior.  Shards split 5/5
  // (balanced contiguous partition): elements 0-4 live on rank 0,
  // 5-9 on rank 1; only the remote partition rides the faulty wire.
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  const char* argv2[] = {mf.c_str(), rk.c_str(), "-updater_type=default",
                         "-log_level=error", "-rpc_timeout_ms=30000",
                         "-barrier_timeout_ms=30000"};
  CHECK(MV_Init(6, argv2) == 0);
  CHECK(MV_SetFaultSeed(1234) == 0);
  int me = MV_WorkerId();
  int32_t h;
  CHECK(MV_NewArrayTable(10, &h) == 0);
  CHECK(MV_Barrier() == 0);
  std::vector<float> ones(10, 1.0f), out(10, -1.0f);
  // Rank 1 STAGGERS its entry into the barrier that follows each armed
  // add: its own barrier-flush request would otherwise race rank 0's
  // add for the injected budget (rank 0's ReplyFlush to it is also a
  // wire send), and the budget must deterministically hit the add.
  if (me == 0) {
    CHECK(MV_SetFaultN("drop", 1) == 0);
    CHECK(MV_AddAsyncArrayTable(h, ones.data(), 10) == 0);  // remote lost
  } else {
    std::this_thread::sleep_for(std::chrono::milliseconds(500));
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetArrayTable(h, out.data(), 10) == 0);
  for (int i = 0; i < 5; ++i) CHECK(out[i] == 1.0f);   // local applied
  for (int i = 5; i < 10; ++i) CHECK(out[i] == 0.0f);  // dropped on wire
  CHECK(MV_Barrier() == 0);
  if (me == 0) {
    CHECK(MV_SetFaultN("dup", 1) == 0);
    CHECK(MV_AddAsyncArrayTable(h, ones.data(), 10) == 0);  // remote 2x
  } else {
    std::this_thread::sleep_for(std::chrono::milliseconds(500));
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetArrayTable(h, out.data(), 10) == 0);
  for (int i = 0; i < 10; ++i) CHECK(out[i] == 2.0f);  // 1+1 local, 0+2 remote
  if (me == 0) {
    long long dropped = 0, duped = 0;
    CHECK(MV_QueryMonitor("net.dropped", &dropped) == 0);
    CHECK(MV_QueryMonitor("net.duplicated", &duped) == 0);
    CHECK(dropped == 1);
    CHECK(duped == 1);
    CHECK(MV_ClearFaults() == 0);
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("CHAOS_DROPDUP_OK %d\n", me);
  return 0;
}

static int BridgeChild(const char* machine_file, const char* rank,
                       const char* engine) {
  // Borrowed sends UNDER CHAOS (docs/host_bridge.md): 2 ranks, arena
  // buffers shipped zero-copy over the wire with drop/dup/delay faults
  // armed on rank 0's sends.  The point is lifetime, not arithmetic:
  // a dropped frame's message dies on the retry path, a duplicated one
  // extends the borrow, a delayed one parks it — in every case the
  // arena must defer recycling until the LAST in-flight borrow drops,
  // and the sanitizer sweeps (tests/test_native.py) run this scenario
  // under TSan and ASan to prove no borrowed byte is read after reuse.
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  std::string eng = std::string("-net_engine=") + engine;
  const char* argv2[] = {mf.c_str(), rk.c_str(), eng.c_str(),
                         "-updater_type=default", "-log_level=error",
                         "-rpc_timeout_ms=60000",
                         "-barrier_timeout_ms=60000"};
  CHECK(MV_Init(7, argv2) == 0);
  CHECK(MV_SetFaultSeed(4242) == 0);
  int me = MV_WorkerId();
  int32_t h;
  CHECK(MV_NewArrayTable(10, &h) == 0);
  CHECK(MV_Barrier() == 0);

  void* p = nullptr;
  CHECK(MV_ArenaAcquire(10 * sizeof(float), &p) == 0);
  float* buf = static_cast<float*>(p);
  for (int i = 0; i < 10; ++i) buf[i] = 1.0f;

  // Round 1: rank 0 drops exactly one borrowed async add's remote frame
  // (same stagger discipline as ChaosDropDupChild so the budget
  // deterministically hits the add, not rank 1's barrier flush).
  if (me == 0) {
    CHECK(MV_SetFaultN("drop", 1) == 0);
    CHECK(MV_AddAsyncArrayTableBorrowed(h, buf, 10) == 0);
  } else {
    std::this_thread::sleep_for(std::chrono::milliseconds(500));
  }
  CHECK(MV_Barrier() == 0);
  std::vector<float> out(10, -1.0f);
  CHECK(MV_GetArrayTable(h, out.data(), 10) == 0);
  if (me == 0) {
    for (int i = 0; i < 5; ++i) CHECK(out[i] == 1.0f);   // local applied
    for (int i = 5; i < 10; ++i) CHECK(out[i] == 0.0f);  // dropped
  }
  CHECK(MV_Barrier() == 0);

  // Round 2: duplicate a borrowed async add's remote frame — the dup's
  // shallow message copy EXTENDS the borrow (two frames gather-read the
  // same arena bytes).
  if (me == 0) {
    CHECK(MV_SetFaultN("dup", 1) == 0);
    CHECK(MV_AddAsyncArrayTableBorrowed(h, buf, 10) == 0);
  } else {
    std::this_thread::sleep_for(std::chrono::milliseconds(500));
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetArrayTable(h, out.data(), 10) == 0);
  if (me == 0) {
    for (int i = 0; i < 5; ++i) CHECK(out[i] == 2.0f);   // 2 local adds
    for (int i = 5; i < 10; ++i) CHECK(out[i] == 2.0f);  // 0 + dup(2)
  }
  CHECK(MV_Barrier() == 0);

  // Round 3: DELAY the remote frame and release the buffer mid-flight —
  // the worker-actor send sleeps 50 ms while the caller's Release lands,
  // so the recycle MUST defer behind the parked borrow (a naive arena
  // frees here and the delayed sendmsg reads freed memory — ASan red).
  if (me == 0) {
    CHECK(MV_SetFault("delay_ms", 50) == 0);
    CHECK(MV_SetFaultN("delay", 1) == 0);
    CHECK(MV_AddAsyncArrayTableBorrowed(h, buf, 10) == 0);
    CHECK(MV_ArenaRelease(p) == 0);  // mid-flight: defer, no use-after-free
  } else {
    std::this_thread::sleep_for(std::chrono::milliseconds(500));
    CHECK(MV_ArenaRelease(p) == 0);
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetArrayTable(h, out.data(), 10) == 0);
  if (me == 0) {
    // Local shard: 3 clean applies; remote shard: drop(-1) + dup(+1)
    // cancel — both read 3.
    for (int i = 0; i < 10; ++i) CHECK(out[i] == 3.0f);
    long long duped = 0, delayed = 0;
    CHECK(MV_QueryMonitor("net.duplicated", &duped) == 0);
    CHECK(MV_QueryMonitor("net.delayed", &delayed) == 0);
    CHECK(duped == 1);
    CHECK(delayed == 1);
    CHECK(MV_ClearFaults() == 0);
  }
  CHECK(MV_Barrier() == 0);
  // Every borrow must drain: no buffer may stay parked in flight once
  // the fleet quiesced (spin briefly — the dup's extra frame finishes
  // asynchronously of the barrier).
  long long in_flight = 1, deferred = 0;
  for (int spin = 0; spin < 100 && in_flight != 0; ++spin) {
    CHECK(MV_ArenaStats(nullptr, nullptr, nullptr, &in_flight, &deferred,
                        nullptr, nullptr) == 0);
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }
  CHECK(in_flight == 0);
  if (me == 0) CHECK(deferred >= 1);  // the mid-flight release deferred
  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("BRIDGE_CHAOS_OK %d\n", me);
  return 0;
}

static int EmbedChild(const char* machine_file, const char* rank,
                      const char* engine) {
  // Sparse-embedding data plane UNDER CHAOS (docs/embedding.md): 2
  // ranks, multi-shard borrowed AddRows shipping run-iovecs out of one
  // arena buffer, and hot-key replica pushes — with drop/dup/delay
  // armed on rank 1's sends.  Like BridgeChild the point is lifetime
  // and semantics, not arithmetic luck: a dropped run frame loses
  // exactly the remote shard's rows, a duplicated one doubles them, a
  // delayed one parks the borrow past a mid-flight release (deferred
  // recycle), and a dropped/duplicated/delayed replica push can never
  // make the version gate serve a stale row.  The sanitizer sweeps
  // (tests/test_native.py) run this under TSan and ASan.
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  std::string eng = std::string("-net_engine=") + engine;
  const char* argv2[] = {mf.c_str(), rk.c_str(), eng.c_str(),
                         "-updater_type=default", "-log_level=error",
                         "-rpc_timeout_ms=60000",
                         "-barrier_timeout_ms=60000",
                         "-hotkey_topk=8", "-replica_lease_ms=50"};
  CHECK(MV_Init(9, argv2) == 0);
  CHECK(MV_SetFaultSeed(2424) == 0);
  int me = MV_WorkerId();
  int32_t h;
  CHECK(MV_NewMatrixTable(16, 4, &h) == 0);  // 8 rows per shard
  CHECK(MV_Barrier() == 0);

  // Rank 1 drives: SORTED ids {1, 9} span both shards — row 1 is
  // REMOTE (rank 0's shard), row 9 local — so the borrowed
  // multi-shard run path (one iovec per shard) is what every round
  // exercises.
  void* p = nullptr;
  CHECK(MV_ArenaAcquire(2 * 4 * sizeof(float), &p) == 0);
  float* buf = static_cast<float*>(p);
  for (int i = 0; i < 8; ++i) buf[i] = 1.0f;
  int32_t ids[2] = {1, 9};
  std::vector<float> out(16 * 4, -1.0f);
  int32_t all[16];
  for (int i = 0; i < 16; ++i) all[i] = i;

  // Round 1: drop exactly the remote run frame — row 1's add dies,
  // row 9's local apply lands.
  if (me == 1) {
    CHECK(MV_SetFaultN("drop", 1) == 0);
    // No ClearFaults here: the async send happens on the worker-actor
    // thread, so the N=1 budget must stay armed until IT fires (the
    // BridgeChild discipline) — budgets self-consume.
    CHECK(MV_AddAsyncMatrixTableByRowsBorrowed(h, buf, ids, 2, 4) == 0);
  } else {
    std::this_thread::sleep_for(std::chrono::milliseconds(500));
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetMatrixTableByRows(h, out.data(), all, 16, 4) == 0);
  if (me == 1) {
    CHECK(out[1 * 4] == 0.0f);   // dropped remote run
    CHECK(out[9 * 4] == 1.0f);   // local run applied
  }
  CHECK(MV_Barrier() == 0);

  // Round 2: duplicate the remote run frame — the dup's shallow copy
  // EXTENDS the borrow; row 1 applies twice.
  if (me == 1) {
    CHECK(MV_SetFaultN("dup", 1) == 0);
    CHECK(MV_AddAsyncMatrixTableByRowsBorrowed(h, buf, ids, 2, 4) == 0);
  } else {
    std::this_thread::sleep_for(std::chrono::milliseconds(500));
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetMatrixTableByRows(h, out.data(), all, 16, 4) == 0);
  if (me == 1) {
    CHECK(out[1 * 4] == 2.0f);   // 0 + dup(2)
    CHECK(out[9 * 4] == 2.0f);   // 1 + 1
  }
  CHECK(MV_Barrier() == 0);

  // Round 3: DELAY the remote run frame and release the arena buffer
  // mid-flight — the recycle must defer behind the parked borrow (a
  // naive arena frees and the delayed sendmsg reads freed memory:
  // ASan red).
  if (me == 1) {
    CHECK(MV_SetFault("delay_ms", 50) == 0);
    CHECK(MV_SetFaultN("delay", 1) == 0);
    CHECK(MV_AddAsyncMatrixTableByRowsBorrowed(h, buf, ids, 2, 4) == 0);
    CHECK(MV_ArenaRelease(p) == 0);  // mid-flight: defer, no UAF
  } else {
    std::this_thread::sleep_for(std::chrono::milliseconds(500));
    CHECK(MV_ArenaRelease(p) == 0);
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetMatrixTableByRows(h, out.data(), all, 16, 4) == 0);
  if (me == 1) {
    CHECK(out[1 * 4] == 3.0f);
    CHECK(out[9 * 4] == 3.0f);
  }
  CHECK(MV_Barrier() == 0);

  // Replica plane under chaos.  Rank 1 warms rank 0's tracker on rows
  // 1/2 (remote gets), then refreshes with faults armed:
  //  - DROPPED push: the refresh round-trip times out (bounded by a
  //    lowered rpc deadline) and the replica simply stays cold — no
  //    torn install;
  //  - DUPLICATED push: OnReplicaPush is idempotent (never rolls a
  //    fresher entry back);
  //  - after a fresh add, a replica read must serve the NEW value
  //    (version gate, cross-chaos).
  CHECK(MV_SetHotKeyReplica(1) == 0);
  if (me == 1) {
    int32_t warm[2] = {1, 2};
    std::vector<float> w(2 * 4);
    for (int i = 0; i < 6; ++i)
      CHECK(MV_GetMatrixTableByRows(h, w.data(), warm, 2, 4) == 0);
    CHECK(MV_SetFlag("rpc_timeout_ms", "500") == 0);
    CHECK(MV_SetFaultN("drop", 1) == 0);
    CHECK(MV_ReplicaRefresh(h) != 0);  // dropped push: bounded failure
    CHECK(MV_ClearFaults() == 0);
    CHECK(MV_SetFlag("rpc_timeout_ms", "60000") == 0);
    CHECK(MV_SetFaultN("dup", 1) == 0);
    CHECK(MV_ReplicaRefresh(h) == 0);  // duplicated push: idempotent
    CHECK(MV_ClearFaults() == 0);
    long long rows = 0;
    CHECK(MV_ReplicaStats(h, nullptr, nullptr, &rows, nullptr,
                          nullptr) == 0);
    CHECK(rows >= 1);
    // Fresh blocking add to replicated row 1, then read: the version
    // gate must refetch — never the pre-add replica value.
    float bump[4] = {10.0f, 10.0f, 10.0f, 10.0f};
    int32_t one[1] = {1};
    CHECK(MV_AddMatrixTableByRows(h, bump, one, 1, 4) == 0);
    std::vector<float> fresh(4, -1.0f);
    CHECK(MV_GetMatrixTableByRows(h, fresh.data(), one, 1, 4) == 0);
    CHECK(fresh[0] == 13.0f);
  } else {
    std::this_thread::sleep_for(std::chrono::milliseconds(200));
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_SetHotKeyReplica(0) == 0);

  // Every borrow must drain (the dup's extra frame finishes async of
  // the barrier).
  long long in_flight = 1, deferred = 0;
  for (int spin = 0; spin < 100 && in_flight != 0; ++spin) {
    CHECK(MV_ArenaStats(nullptr, nullptr, nullptr, &in_flight, &deferred,
                        nullptr, nullptr) == 0);
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }
  CHECK(in_flight == 0);
  if (me == 1) CHECK(deferred >= 1);
  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("EMBED_CHAOS_OK %d\n", me);
  return 0;
}

static int ChaosBarrierTimeoutChild(const char* machine_file,
                                    const char* rank) {
  // Deadline-bounded barrier: rank 1 simply never arrives (busy for 4 s)
  // — rank 0's barrier must return -3 within the configured deadline
  // with an error NAMING rank 1 (asserted by the pytest side on this
  // process's stderr), never hang.
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  const char* argv2[] = {mf.c_str(), rk.c_str(), "-updater_type=default",
                         "-log_level=error", "-rpc_timeout_ms=3000",
                         "-barrier_timeout_ms=1500",
                         "-connect_retry_ms=300"};
  CHECK(MV_Init(7, argv2) == 0);
  int me = MV_WorkerId();
  int32_t h;
  CHECK(MV_NewArrayTable(4, &h) == 0);
  if (me == 1) {
    // The straggler: never joins this barrier round, then leaves
    // without a goodbye (its own shutdown barrier would also time out).
    std::this_thread::sleep_for(std::chrono::milliseconds(4000));
    fflush(stdout);
    printf("CHAOS_BARRIER_OK 1\n");
    fflush(stdout);
    _exit(0);
  }
  auto t0 = std::chrono::steady_clock::now();
  CHECK(MV_Barrier() == -3);
  auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                std::chrono::steady_clock::now() - t0)
                .count();
  CHECK(ms >= 1400 && ms < 10000);  // deadline honored, not a hang
  CHECK(MV_ShutDown() == 0);        // its barrier times out and proceeds
  printf("CHAOS_BARRIER_OK %d\n", me);
  return 0;
}

static int ChaosHeartbeatChild(const char* machine_file, const char* rank) {
  // Dropped-peer heartbeat report: leases on (-heartbeat_ms=100), rank 1
  // crashes after the rendezvous; within a few intervals rank 0 reports
  // the dead peer (MV_DeadPeerCount, Dashboard hb.missed) WITHOUT any
  // blocking call having to discover it the hard way.
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  const char* argv2[] = {mf.c_str(), rk.c_str(), "-updater_type=default",
                         "-log_level=error", "-rpc_timeout_ms=3000",
                         "-barrier_timeout_ms=1500", "-heartbeat_ms=100",
                         "-heartbeat_timeout_ms=400",
                         "-connect_retry_ms=300"};
  CHECK(MV_Init(9, argv2) == 0);
  int me = MV_WorkerId();
  CHECK(MV_Barrier() == 0);
  if (me == 1) _exit(0);  // crash: no shutdown, no goodbye

  CHECK(MV_DeadPeerCount() == 0);  // lease still fresh at the crash
  // Lease expiry is 400 ms of silence; poll up to 3 s for the report.
  int dead = 0;
  for (int tries = 0; tries < 150 && dead == 0; ++tries) {
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
    dead = MV_DeadPeerCount();
  }
  CHECK(dead == 1);
  long long missed = 0;
  CHECK(MV_QueryMonitor("hb.missed", &missed) == 0);
  CHECK(missed >= 1);
  CHECK(MV_ShutDown() == 0);  // shutdown barrier times out and proceeds
  printf("CHAOS_HB_OK %d\n", me);
  return 0;
}

static int ChaosQuietChild(const char* machine_file, const char* rank) {
  // Injection disabled ⇒ zero observable difference: a normal 2-rank
  // round trip leaves every injected-path counter at exactly zero.
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  const char* argv2[] = {mf.c_str(), rk.c_str(), "-updater_type=default",
                         "-log_level=error", "-rpc_timeout_ms=30000",
                         "-barrier_timeout_ms=30000"};
  CHECK(MV_Init(6, argv2) == 0);
  int me = MV_WorkerId();
  int32_t h;
  CHECK(MV_NewArrayTable(10, &h) == 0);
  CHECK(MV_Barrier() == 0);
  std::vector<float> ones(10, 1.0f), out(10, -1.0f);
  CHECK(MV_AddArrayTable(h, ones.data(), 10) == 0);
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetArrayTable(h, out.data(), 10) == 0);
  for (float v : out) CHECK(v == 2.0f);
  for (const char* counter :
       {"net.retries", "net.dropped", "net.delayed", "net.duplicated",
        "fault.fail_send", "hb.missed"}) {
    long long c = -1;
    CHECK(MV_QueryMonitor(counter, &c) == 0);
    CHECK(c == 0);
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("CHAOS_QUIET_OK %d\n", me);
  return 0;
}

static int TestRepl() {
  using mvtpu::Message;
  using mvtpu::MsgType;
  // ---- shard-hint wire round trip (version-tolerant bias) -----------
  {
    Message m;
    m.type = MsgType::RequestGet;
    m.table_id = 2;
    m.msg_id = 9;
    m.shard = 3;
    Message back = Message::Deserialize(m.Serialize());
    CHECK(back.shard == 3);
    Message unhinted;
    unhinted.type = MsgType::RequestGet;
    Message back2 = Message::Deserialize(unhinted.Serialize());
    CHECK(back2.shard == -1);  // old wire value 0 = no hint
    // Zero-copy parse adopts the hint too.
    mvtpu::Blob frame = m.Serialize();
    auto slab = std::make_shared<std::vector<char>>(
        frame.data(), frame.data() + frame.size());
    Message viewed;
    CHECK(Message::DeserializeView(slab, 0, slab->size(), &viewed));
    CHECK(viewed.shard == 3);
  }
  // ---- MemStream: the snapshot wire form ----------------------------
  {
    mvtpu::repl::MemStream ms;
    int64_t vals[3] = {7, -1, 42};
    CHECK(ms.Write(vals, sizeof(vals)) == sizeof(vals));
    mvtpu::repl::MemStream in(ms.bytes());
    int64_t got[3] = {0, 0, 0};
    CHECK(in.Read(got, sizeof(got)) == sizeof(got));
    CHECK(got[0] == 7 && got[1] == -1 && got[2] == 42);
    char extra;
    CHECK(in.Read(&extra, 1) == 0);  // drained
  }
  // ---- whole-shard catch-up: Store -> Load, beacons converge --------
  {
    mvtpu::MatrixServerTable primary(8, 4, mvtpu::UpdaterType::kDefault,
                                     /*rank=*/0, /*size=*/2);
    mvtpu::MatrixServerTable backup(8, 4, mvtpu::UpdaterType::kDefault,
                                    /*rank=*/0, /*size=*/2);
    Message add;
    add.type = MsgType::RequestAdd;
    mvtpu::AddOption opt;
    std::vector<int32_t> ids = {0, 2, 3};
    std::vector<float> delta(3 * 4, 1.5f);
    add.data.emplace_back(&opt, sizeof(opt));
    add.data.emplace_back(ids.data(), ids.size() * sizeof(int32_t));
    add.data.emplace_back(delta.data(), delta.size() * sizeof(float));
    primary.ProcessAdd(add);
    CHECK(primary.BucketChecksums() != backup.BucketChecksums());
    mvtpu::repl::MemStream snap;
    CHECK(primary.Store(&snap));
    mvtpu::repl::MemStream in(snap.bytes());
    CHECK(backup.Load(&in));
    CHECK(primary.BucketChecksums() == backup.BucketChecksums());
    // Version adoption: the installed backup must never stamp BEHIND
    // what clients already saw from the primary.
    backup.AdvanceVersionTo(primary.version());
    CHECK(backup.version() >= primary.version());
    // Delta forwarding after the snapshot keeps them converged.
    primary.ProcessAdd(add);
    backup.ProcessAdd(add);
    CHECK(primary.BucketChecksums() == backup.BucketChecksums());
  }
  // ---- idempotent stamped replay: Covers + NoteDupSkipped -----------
  {
    mvtpu::audit::DeliveryBook book;
    mvtpu::audit::Arm(true);
    book.NoteApply(/*origin=*/1, 1, 3, /*table_id=*/0);
    CHECK(book.Covers(1, 1, 3));
    CHECK(book.Covers(1, 2, 2));
    CHECK(!book.Covers(1, 3, 4));   // hi past the watermark
    CHECK(!book.Covers(2, 1, 1));   // unseen origin
    book.NoteApply(1, 6, 6, 0);     // parked ahead of the 4..5 hole
    CHECK(book.Covers(1, 6, 6));    // pending ranges count as seen
    CHECK(!book.Covers(1, 4, 5));
    book.NoteDupSkipped(1, 1, 3);
    CHECK(book.Json().find("\"dups\":1") != std::string::npos);
    // Watermark export/import: the catch-up payload's book half.
    mvtpu::audit::DeliveryBook joined;
    joined.ImportWatermarks(book.ExportWatermarks());
    CHECK(joined.Covers(1, 1, 3));
  }
  return 0;
}

static int FailoverChild(const char* machine_file, const char* rank,
                         const char* engine) {
  // Replication + lease-triggered failover chaos (docs/replication.md):
  // a 3-rank fleet with -replication_factor=1 (shard i backed by
  // server i+1 mod 3).  After a converged warm phase rank 1 is
  // CRASHED (no goodbye); rank 2 — shard 1's backup — detects the
  // expired lease on its own (symmetric watching), promotes, and
  // broadcasts the routing-epoch flip; rank 0's retried adds re-route
  // and the fleet converges to the exact expected values with zero
  // lost acked adds (sync replication: an acked add is on both
  // replicas by construction).
  std::string mf = std::string("-machine_file=") + machine_file;
  std::string rk = std::string("-rank=") + rank;
  std::string eng = std::string("-net_engine=") + engine;
  const char* argv2[] = {mf.c_str(), rk.c_str(), eng.c_str(),
                         "-updater_type=default", "-log_level=error",
                         "-rpc_timeout_ms=2000",
                         "-barrier_timeout_ms=8000",
                         "-heartbeat_ms=100", "-heartbeat_timeout_ms=400",
                         "-replication_factor=1", "-repl_sync=true",
                         "-promote_auto=true", "-send_retries=2",
                         "-send_backoff_ms=20", "-connect_retry_ms=500"};
  CHECK(MV_Init(15, argv2) == 0);
  int me = MV_WorkerId();
  constexpr int64_t kN = 12;  // 3 shards of 4
  int32_t h;
  CHECK(MV_NewArrayTable(kN, &h) == 0);
  CHECK(MV_Barrier() == 0);

  std::vector<float> ones(kN, 1.0f), out(kN, -1.0f);
  // Warm phase: every rank lands one acked add — with sync replication
  // the ack certifies BOTH replicas applied it.
  CHECK(MV_AddArrayTable(h, ones.data(), kN) == 0);
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetArrayTable(h, out.data(), kN) == 0);
  for (float v : out) CHECK(v == 3.0f);
  long long fwd = 0, acks = 0;
  CHECK(MV_ReplicationStats(&fwd, &acks, nullptr, nullptr, nullptr,
                            nullptr, nullptr, nullptr) == 0);
  CHECK(fwd >= 1);  // this rank forwarded its shard's applies
  CHECK(MV_Barrier() == 0);

  // Dup-idempotence probe: with replication armed, a re-delivered
  // stamped frame (injected dup — the same wire-retry shape) must be
  // SKIPPED, not re-applied, so post-failover replays cannot double
  // count.  Rank 0 dups exactly one of its three shard sends; the
  // exact value proves the second delivery was dropped by the
  // Covers() gate (without it, one shard's slice would read +2).
  if (me == 0) {
    CHECK(MV_SetFaultSeed(17) == 0);
    CHECK(MV_SetFaultN("dup", 1) == 0);
    CHECK(MV_AddArrayTable(h, ones.data(), kN) == 0);
    CHECK(MV_ClearFaults() == 0);
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetArrayTable(h, out.data(), kN) == 0);
  for (float v : out) CHECK(v == 4.0f);
  CHECK(MV_Barrier() == 0);

  if (me == 1) _exit(0);  // SIGKILL stand-in: no shutdown, no goodbye

  // Lease expiry detected by each SURVIVOR on its own (symmetric
  // watching — rank 0 is not special; the same path covers rank 0
  // itself being the corpse).
  int dead = 0;
  for (int tries = 0; tries < 300 && dead == 0; ++tries) {
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
    dead = MV_DeadPeerCount();
  }
  CHECK(dead >= 1);
  // Promotion within the lease window: shard 1's routed owner
  // converges on global rank 2 (the promoted backup broadcasts the
  // epoch flip; rank 0 adopts it without restarting).
  int owner = -1;
  for (int tries = 0; tries < 300; ++tries) {
    owner = MV_ShardOwner(1);
    if (owner == 2) break;
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }
  CHECK(owner == 2);
  CHECK(MV_RoutingEpoch() >= 1);
  if (me == 2) {
    long long promos = 0;
    CHECK(MV_ReplicationStats(nullptr, nullptr, nullptr, nullptr,
                              &promos, nullptr, nullptr, nullptr) == 0);
    CHECK(promos >= 1);
    CHECK(MV_BackupShard() == 1);
  }
  // Post-promotion traffic: blocking adds through the flipped route —
  // the promoted shard takes rank 1's slice without a fleet restart.
  // (The retry loop guards the adoption race; a whole-array add is
  // only exactness-safe once every shard routes to a live rank.)
  int failures = 0;
  for (int i = 0; i < 2; ++i) {
    int rc = -1;
    for (int tries = 0; tries < 100 && rc != 0; ++tries) {
      rc = MV_AddArrayTable(h, ones.data(), kN);
      if (rc != 0) {
        ++failures;
        std::this_thread::sleep_for(std::chrono::milliseconds(50));
      }
    }
    CHECK(rc == 0);
  }
  // Survivor rendezvous: the dead-leased rank is EXCUSED from the
  // barrier quorum (elastic membership) — then prove exact
  // convergence: 4 (warm + dup probe) + 2 rounds from each of the 2
  // survivors = 8 everywhere, the promoted shard included.
  CHECK(MV_Barrier() == 0);
  CHECK(MV_GetArrayTable(h, out.data(), kN) == 0);
  for (float v : out) CHECK(v == 8.0f);
  CHECK(MV_ShutDown() == 0);
  printf("FAILOVER_OK %d failures=%d\n", me, failures);
  return 0;
}

static int JoinChild(const char* ctrl, const char* port, const char* role,
                     const char* num, const char* is_ctrl) {
  // Elastic-join scenario (docs/replication.md): three dynamically
  // registered processes — controller (role all, rank 0), a
  // server-only node, and a WORKER-ONLY node that joins the
  // replication set live: MV_ReplJoin(0) creates backup instances,
  // announces via a routing-epoch flip (the primary starts
  // forwarding), and pulls a whole-shard catch-up snapshot.  The
  // joiner then takes shard 0 over through an operator-driven
  // promotion (MV_PromoteBackup) — traffic re-routes with no fleet
  // restart, and exact values prove the snapshot + delta stream
  // delivered the full shard (a join is replication + an epoch flip).
  std::string a_ctrl = std::string("-controller_endpoint=") + ctrl;
  std::string a_port = std::string("-port=") + port;
  std::string a_role = std::string("-role=") + role;
  std::string a_num = std::string("-num_nodes=") + num;
  std::string a_isc = std::string("-is_controller=") + is_ctrl;
  const char* argv2[] = {a_ctrl.c_str(), a_port.c_str(), a_role.c_str(),
                         a_num.c_str(),  a_isc.c_str(),
                         "-updater_type=default", "-log_level=error",
                         "-rpc_timeout_ms=20000",
                         "-barrier_timeout_ms=30000",
                         "-replication_factor=1", "-repl_sync=true",
                         "-promote_auto=false"};
  CHECK(MV_Init(12, argv2) == 0);
  int wid = MV_WorkerId(), sid = MV_ServerId();
  bool joiner = std::string(role) == "worker";
  constexpr int64_t kN = 8;  // 2 server shards of 4
  int32_t h;
  CHECK(MV_NewArrayTable(kN, &h) == 0);
  CHECK(MV_Barrier() == 0);

  std::vector<float> ones(kN, 1.0f), out(kN, -1.0f);
  if (wid >= 0) CHECK(MV_AddArrayTable(h, ones.data(), kN) == 0);
  CHECK(MV_Barrier() == 0);
  if (wid >= 0) {
    CHECK(MV_GetArrayTable(h, out.data(), kN) == 0);
    for (float v : out) CHECK(v == 2.0f);  // two worker-role ranks
  }
  CHECK(MV_Barrier() == 0);

  if (joiner) {
    CHECK(MV_BackupShard() == -1);  // worker-only: backs nothing yet
    CHECK(MV_ReplJoin(0) == 0);     // live join: announce + catch-up
    // Chaos re-run (the kill-mid-catch-up recovery path): the second
    // pull re-installs the snapshot idempotently.
    CHECK(MV_ReplJoin(0) == 0);
    CHECK(MV_BackupShard() == 0);
    long long catchups = 0;
    CHECK(MV_ReplicationStats(nullptr, nullptr, nullptr, nullptr,
                              nullptr, nullptr, nullptr,
                              &catchups) == 0);
    CHECK(catchups >= 1);
  }
  CHECK(MV_Barrier() == 0);
  // Post-join writes stream to the joiner as forwards.
  if (wid >= 0) CHECK(MV_AddArrayTable(h, ones.data(), kN) == 0);
  CHECK(MV_Barrier() == 0);

  if (joiner) {
    // Operator-driven handover: promote the joined backup into
    // serving shard 0 (the lease-expiry path minus the corpse).
    CHECK(MV_PromoteBackup(0) == 1);
    CHECK(MV_ShardOwner(0) != 0);
  }
  // Every rank adopts the epoch flip: shard 0's owner leaves rank 0.
  int owner = 0;
  for (int tries = 0; tries < 300; ++tries) {
    owner = MV_ShardOwner(0);
    if (owner != 0) break;
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }
  CHECK(owner != 0);
  CHECK(MV_RoutingEpoch() >= 1);
  CHECK(MV_Barrier() == 0);
  // Traffic lands on the promoted joiner; exact values prove the
  // catch-up snapshot + forwarded deltas delivered the whole shard
  // (no torn read: 2 warm + 2 post-join + 2 post-promotion).
  if (wid >= 0) {
    CHECK(MV_AddArrayTable(h, ones.data(), kN) == 0);
  }
  CHECK(MV_Barrier() == 0);
  if (wid >= 0) {
    CHECK(MV_GetArrayTable(h, out.data(), kN) == 0);
    for (float v : out) CHECK(v == 6.0f);
  }
  CHECK(MV_Barrier() == 0);
  CHECK(MV_ShutDown() == 0);
  printf("JOIN_OK %s wid=%d sid=%d\n", role, wid, sid);
  return 0;
}

// masking the CHECK diagnostic — _exit skips teardown and keeps rc=1.
static int ScenarioExit(int rc) {
  fflush(stdout);
  fflush(stderr);
  if (rc) _exit(rc);
  return 0;
}

int main(int argc, char** argv) {
  if ((argc == 4 || argc == 5) && std::string(argv[1]) == "net_child")
    return ScenarioExit(
        NetChild(argv[2], argv[3], argc == 5 ? argv[4] : "epoll"));
  if (argc == 5 && std::string(argv[1]) == "net_updater")
    return ScenarioExit(NetUpdaterChild(argv[2], argv[3], argv[4]));
  if (argc == 7 && std::string(argv[1]) == "register")
    return ScenarioExit(
        RegisterChild(argv[2], argv[3], argv[4], argv[5], argv[6]));
  if (argc == 5 && std::string(argv[1]) == "ssp_child")
    return ScenarioExit(SspChild(argv[2], argv[3], argv[4]));
  if (argc == 5 && std::string(argv[1]) == "ssp_tput")
    return ScenarioExit(SspThroughputChild(argv[2], argv[3], argv[4]));
  if (argc == 5 && std::string(argv[1]) == "backup_child")
    return ScenarioExit(BackupChild(argv[2], argv[3], argv[4]));
  if (argc == 4 && std::string(argv[1]) == "ssp_dead")
    return ScenarioExit(SspDeadChild(argv[2], argv[3]));
  if (argc == 5 && std::string(argv[1]) == "wire_bench")
    return ScenarioExit(WireBenchChild(argv[2], argv[3], argv[4]));
  if (argc == 4 && std::string(argv[1]) == "async_overlap")
    return ScenarioExit(AsyncOverlapChild(argv[2], argv[3]));
  if (argc == 4 && std::string(argv[1]) == "codec_wire")
    return ScenarioExit(CodecWireChild(argv[2], argv[3]));
  if ((argc == 4 || argc == 5) && std::string(argv[1]) == "embed_child")
    return ScenarioExit(EmbedChild(argv[2], argv[3],
                                   argc == 5 ? argv[4] : "epoll"));
  if ((argc == 4 || argc == 5) && std::string(argv[1]) == "bridge_child")
    return ScenarioExit(BridgeChild(argv[2], argv[3],
                                    argc == 5 ? argv[4] : "epoll"));
  if ((argc == 4 || argc == 5) && std::string(argv[1]) == "agg_child")
    return ScenarioExit(AggChild(argv[2], argv[3],
                                 argc == 5 ? argv[4] : "epoll"));
  if (argc == 4 && std::string(argv[1]) == "agg_bench")
    return ScenarioExit(AggBenchChild(argv[2], argv[3]));
  if ((argc == 4 || argc == 5) && std::string(argv[1]) == "chaos_retry")
    return ScenarioExit(
        ChaosRetryChild(argv[2], argv[3], argc == 5 ? argv[4] : "epoll"));
  if (argc == 4 && std::string(argv[1]) == "chaos_dropdup")
    return ScenarioExit(ChaosDropDupChild(argv[2], argv[3]));
  if (argc == 4 && std::string(argv[1]) == "chaos_barrier")
    return ScenarioExit(ChaosBarrierTimeoutChild(argv[2], argv[3]));
  if (argc == 4 && std::string(argv[1]) == "chaos_heartbeat")
    return ScenarioExit(ChaosHeartbeatChild(argv[2], argv[3]));
  if (argc == 4 && std::string(argv[1]) == "chaos_quiet")
    return ScenarioExit(ChaosQuietChild(argv[2], argv[3]));
  if (argc == 4 && std::string(argv[1]) == "dead_peer")
    return ScenarioExit(DeadPeerChild(argv[2], argv[3]));
  if (argc == 4 && std::string(argv[1]) == "dead_server")
    return ScenarioExit(DeadServerChild(argv[2], argv[3]));
  if ((argc == 4 || argc == 5) && std::string(argv[1]) == "failover_child")
    return ScenarioExit(FailoverChild(argv[2], argv[3],
                                      argc == 5 ? argv[4] : "epoll"));
  if (argc == 7 && std::string(argv[1]) == "join_child")
    return ScenarioExit(
        JoinChild(argv[2], argv[3], argv[4], argv[5], argv[6]));
  if (argc == 2 && std::string(argv[1]) == "mpi_self")
    return ScenarioExit(MpiSelfScenario());
  if (argc == 2 && std::string(argv[1]) == "mpi_zoo")
    return ScenarioExit(MpiZooScenario());
  struct Case {
    const char* name;
    int (*fn)();
  };
  // array must run before the other C-API scenarios (it calls MV_Init).
  Case cases[] = {
      {"blob", TestBlob},         {"blob_borrow", TestBlobBorrow},
      {"arena", TestArena},       {"queue", TestQueue},
      {"configure", TestConfigure}, {"message", TestMessage},
      {"latency", TestLatencyTrail},
      {"audit", TestAudit},
      {"qos", TestQos},
      {"codec", TestCodec},
      {"dashboard", TestDashboard},
      {"updater", TestUpdater},   {"array", TestArray},
      {"matrix", TestMatrix},     {"bridge", TestBridge},
      {"sparse", TestSparseMatrix},
      {"checkpoint", TestCheckpoint},
      {"kv", TestKV},             {"threads", TestThreads},
      {"serve", TestServeVersions},
      {"workload", TestWorkload},
      {"capacity", TestCapacity},
      {"replica", TestReplica},
      {"repl", TestRepl},
      {"multiblob_add", TestMultiBlobAdd},
      {"watchdog", TestWatchdog},
  };
  int failures = 0;
  std::string only = argc > 1 ? argv[1] : "";
  for (const Case& c : cases) {
    if (!only.empty() && only != c.name) continue;
    int rc = c.fn();
    printf("%-12s %s\n", c.name, rc == 0 ? "OK" : "FAILED");
    failures += rc != 0;
  }
  MV_ShutDown();
  printf(failures ? "FAILURES: %d\n" : "ALL NATIVE TESTS PASSED\n", failures);
  return failures ? 1 : 0;
}
