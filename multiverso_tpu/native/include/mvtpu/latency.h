// Latency attribution plane (docs/observability.md "latency plane").
//
// Answers *where* a slow request spent its time: the wire header's
// TimingTrail (mvtpu/message.h) carries six monotonic-clock stamps —
// client enqueue, client send, server frame-complete, server actor
// dequeue, apply done, reply send — and the client, on reply receipt,
// folds the trail into per-stage Dashboard histograms:
//
//   lat.stage.queue      enqueue -> transport (client mailbox + handler)
//   lat.stage.wire_out   client send -> server frame-complete (*)
//   lat.stage.mailbox    frame-complete -> actor dequeue (incl. SSP park)
//   lat.stage.apply      dequeue -> table work done
//   lat.stage.reactor    apply done -> reply handed to the transport
//   lat.stage.wire_back  reply send -> client receipt (*)
//   lat.total            enqueue -> client receipt (end to end)
//
// (*) cross-rank stages span two clocks; they are corrected by the
// per-peer clock offset this module estimates NTP-style from every
// timed round trip (request/reply AND the PR 2 heartbeat, whose echo
// carries a trail): offset = ((t_recv - t_send) + (t_reply - t_now))/2,
// with the minimum-RTT sample of a bounded window winning (the classic
// clock filter — congested samples carry the most offset error).
// Offset-corrected stage sums telescope back to lat.total exactly, so
// "stages sum to the end-to-end latency" is a checkable invariant.
//
// Stamping costs one steady_clock read per boundary and 48 wire bytes
// per message; `-wire_timing=false` (or MV_SetWireTiming) compiles the
// whole plane down to one relaxed atomic load per site.
#pragma once

#include <cstdint>
#include <string>

#include "mvtpu/message.h"

namespace mvtpu {
namespace latency {

// Monotonic nanoseconds (std::chrono::steady_clock) — NEVER wall clock:
// the offset estimator assumes each rank's stamps share one monotonic
// timebase (mvlint MV014 polices the Python mirror).
int64_t NowNs();

// Arm switch: latched from -wire_timing at Zoo::Start, toggled live by
// MV_SetWireTiming.  Disarmed, every stamp below is one relaxed load.
void Arm(bool on);
bool Armed();

// ---- stamping (no-op when disarmed / the message has no trail) -------
// Mint the trail on a fresh request: sets msgflag::kHasTiming + the
// enqueue stamp.  Called by the worker-side request builders.
void StampEnqueue(Message* m);
// Transport hand-off stamp: requests fill kSend, replies (and any
// message whose apply stamp is already set — the heartbeat echo)
// fill kReplySend.  Stamp-once: a retry does not refresh it.
void StampSend(Message* m);
// Receiver-side stamps, stamp-if-zero so a duplicated or re-delivered
// message keeps its FIRST boundary crossing (SSP re-delivery folds the
// park time into lat.stage.mailbox, where it belongs).
void StampRecv(Message* m);     // frame complete (reactor / reader)
void StampDequeue(Message* m);  // actor handler entry
// Server reply hand-off: copy the request's trail into the reply, set
// its timing flag, and stamp kApplyDone — a reply only ever carries a
// trail when the request did (old clients are never handed one).
void StampReply(const Message& req, Message* reply);

// ---- client-side attribution ----------------------------------------
// Fold a timed reply into the stage histograms and feed the peer's
// clock-offset estimator.  `peer_rank` is the server rank whose clock
// stamped the middle of the trail.  Safe on trail-less replies (no-op).
void OnReply(const Message& reply, int peer_rank);

// Best current offset estimate for a peer: *offset_ns is how far the
// PEER's monotonic clock sits ahead of ours; false when no timed round
// trip to that peer completed yet.
bool PeerOffset(int rank, int64_t* offset_ns, int64_t* rtt_ns,
                long long* samples = nullptr);

// JSON array of every estimated peer offset — the "offsets" section of
// the "latency" OpsQuery report.
std::string OffsetsJson();

// Test isolation: drop every offset estimate (histograms live in the
// Dashboard and reset with it).
void Reset();

}  // namespace latency
}  // namespace mvtpu
