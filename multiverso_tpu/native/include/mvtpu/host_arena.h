// HostArena — pinned, recycled, 64-byte-aligned host buffers for the
// zero-copy numpy<->Blob handoff (docs/host_bridge.md).
//
// The arena is the ownership authority of the host-bridge fast path: a
// buffer handed out by Acquire() has TWO kinds of holds —
//
//   - the CALLER hold (Acquire -> Release): the binding / application
//     owns the bytes and may read or write them;
//   - NATIVE borrows (BorrowHold copies): in-flight messages whose
//     Blobs borrow the bytes straight into the scatter-gather send path
//     instead of copying (Blob::Borrow).
//
// A buffer returns to the free list only when BOTH are gone.  A caller
// releasing a buffer while a borrowed send is still in flight does not
// free or recycle anything — the recycle is DEFERRED until the last
// borrow drops (the release hook fires when the last shallow Blob copy
// dies), so a late wire write can never read recycled memory.  This is
// the "mutate/free mid-flight" contract: Release() is always safe;
// actually MUTATING a borrowed buffer before its borrows drop is the
// caller's bug (the Python HostArena only re-hands out recycled
// buffers, so respecting Acquire/Release makes mutation safe too).
//
// Buffers are 64-byte aligned (cache-line / AVX-512 friendly, and MV008
// contiguity holds by construction for arrays built over them) and
// best-effort pinned with mlock(2) under `-arena_pin` — pinning failure
// (RLIMIT_MEMLOCK) is counted, not fatal.  Freed buffers are retained
// for reuse: the arena's footprint is the high-water mark of
// simultaneously live buffers, never traffic.
#pragma once

#include <cstddef>
#include <map>
#include <memory>

#include "mvtpu/mutex.h"

namespace mvtpu {

class HostArena {
 public:
  static HostArena* Get();

  // A recycled (or fresh) buffer of capacity >= bytes, 64-byte aligned,
  // caller-held until Release().  nullptr only on allocation failure.
  void* Acquire(size_t bytes);

  // Drop the caller hold.  rc 0 ok (recycled now, or deferred behind
  // in-flight borrows); -1 unknown pointer; -2 already released.
  int Release(void* ptr);

  // Base pointer of the LIVE (caller-held) arena buffer fully
  // containing [p, p+len); nullptr when p is not arena memory, the
  // window overruns its buffer, or the buffer was already released —
  // the validity gate of every *Borrowed C API call.
  void* BufferOf(const void* p, size_t len);

  // A shared native hold on `base` (an Acquire'd buffer's base
  // pointer).  Copies keep the buffer off the free list; the last drop
  // recycles it iff the caller hold is gone.  This is the keepalive a
  // borrowed Blob carries (Blob::Borrow).
  std::shared_ptr<void> BorrowHold(void* base);

  struct Stats {
    long long buffers = 0;       // live buffers (caller-held or borrowed)
    long long free_buffers = 0;  // recycled, ready for Acquire
    long long bytes = 0;         // total arena bytes (live + free)
    long long in_flight = 0;     // buffers with active native borrows
    long long deferred = 0;      // releases deferred behind a borrow (total)
    long long recycled = 0;      // Acquires served from the free list
    long long pinned = 0;        // buffers mlock'd (best-effort)
  };
  Stats GetStats();

 private:
  struct Buf {
    size_t cap = 0;
    bool caller_held = false;
    int borrows = 0;
    bool pinned = false;
  };

  void DropBorrow(void* base);
  void Recycle(char* base, Buf* b) REQUIRES(mu_);

  Mutex mu_;
  std::map<char*, Buf> bufs_ GUARDED_BY(mu_);        // by base address
  std::multimap<size_t, char*> free_ GUARDED_BY(mu_);  // by capacity
  Stats stats_ GUARDED_BY(mu_);
};

}  // namespace mvtpu
