// Delivery-audit plane (docs/observability.md "audit plane").
//
// Proves the asynchronous push-pull contract held: every Add a worker
// sends is stamped with a durable identity — (origin_rank, table, seq)
// where seq is a per-(worker, table, server-shard) monotonic counter
// carried behind msgflag::kHasAudit — and both ends keep books:
//
//   client  AckLedger     per shard: last seq SENT and last seq ACKED
//                         (a blocking add's ReplyAdd echoes its stamp;
//                         per-connection FIFO means an ack of seq n
//                         covers every earlier seq on that stream)
//   server  DeliveryBook  per origin: applied watermark w (all seqs
//                         <= w applied), a bounded out-of-order pending
//                         set, dup/reorder counters, and a bounded
//                         anomaly ring naming each event's seq range
//
// The invariant the auditor checks fleet-wide (tools/mvaudit.py):
//   acked(origin, table, shard) <= watermark(server shard, table, origin)
// An acked seq the server never applied is a LOST ACKED ADD — the
// failure class ROADMAP item 1's replication gate must prove absent.
// A pending out-of-order range that survives `-audit_grace_ms` fires
// the PR 7 flight recorder (`audit_gap`), capturing evidence at
// detection time rather than postmortem.
//
// Periodic per-bucket content checksums (Crc32 over table state,
// bucket mapping shared with the PR 4 version stamps) give replica-
// divergence detection its primitive: two shards holding the same rows
// must report identical bucket checksums, and the XOR-of-row-CRCs
// construction makes the value independent of iteration order.
//
// `-audit=false` (or MV_SetAudit) compiles the whole plane down to one
// relaxed atomic load per site.
#pragma once

#include <cstdint>
#include <map>
#include <string>
#include <utility>
#include <vector>

#include "mvtpu/mutex.h"

namespace mvtpu {
namespace audit {

// Arm switch: latched from -audit at Zoo::Start, toggled live by
// MV_SetAudit.  Disarmed, workers stamp nothing and servers book
// nothing (frames already in flight still parse — the flag bit is
// per message).
void Arm(bool on);
bool Armed();

// CRC-32 (IEEE 802.3, reflected) — the checksum beacon primitive.
// `seed` chains: Crc32(b, n, Crc32(a, m)) == Crc32(a+b).
uint32_t Crc32(const void* data, size_t n, uint32_t seed = 0);

// One recorded delivery anomaly (the bounded ring's unit).
struct Anomaly {
  enum Kind { kDup = 0, kReorder = 1, kGap = 2 };
  Kind kind;
  int origin;
  int64_t seq_lo, seq_hi;
  int64_t ts_ms;  // steady-clock ms at detection
};

// Server-side per-(table, origin) delivery book.  One instance per
// ServerTable; every stamped RequestAdd lands in NoteApply right after
// the table applied it.  Thread-safe (the server actor is single-
// threaded today, but ops scrapes read concurrently).
class DeliveryBook {
 public:
  struct OriginState {
    int64_t watermark = 0;   // all seqs <= watermark applied
    int64_t applied = 0;     // stamped messages applied
    int64_t covered = 0;     // logical adds covered (sum of range widths)
    int64_t dups = 0;        // re-delivered ranges (retry/injected dup)
    int64_t reorders = 0;    // ranges that arrived ahead of a gap
    int64_t pending_dropped = 0;  // ranges evicted from a full pending set
    int64_t pending_since_ms = -1;  // first out-of-order observed (-1 none)
    bool gap_fired = false;  // audit_gap blackbox latched this episode
    // mvlint: MV018-exempt(bounded at kMaxPending ranges — the
    // highest range evicts + counts pending_dropped when full)
    std::map<int64_t, int64_t> pending;  // lo -> hi, disjoint, sorted
  };

  // Book one applied stamped message.  `table_id` only names the table
  // in anomaly records / the audit_gap trigger reason.
  void NoteApply(int origin, int64_t seq_lo, int64_t seq_hi,
                 int32_t table_id);

  // ---- replication / failover support (docs/replication.md) ---------
  // True when [seq_lo, seq_hi] was already applied here (entirely
  // below the watermark or inside a parked out-of-order range).  With
  // replication armed the server consults this BEFORE ProcessAdd: a
  // post-failover replay of an already-forwarded add must ack without
  // re-applying — stamped adds become idempotent end-to-end, which is
  // what lets workers retry through a promotion without double-counts.
  bool Covers(int origin, int64_t seq_lo, int64_t seq_hi) const;
  // Book a dup that was SKIPPED (not re-applied): counts the anomaly
  // so the auditor still names it, but applied/covered stay honest.
  void NoteDupSkipped(int origin, int64_t seq_lo, int64_t seq_hi);
  // Current applied watermark for one origin (0 = none booked) — the
  // value an add ack echoes as its acked bound (docs/replication.md):
  // under the per-connection FIFO this equals the request's seq_hi,
  // but across a failover a hole (an attempt that died with the old
  // primary) must never be covered by a later ack — the book's
  // watermark is the truth, the FIFO rule was only its proxy.
  int64_t Watermark(int origin) const;
  // Snapshot/restore the per-origin applied watermarks — rides the
  // ShardSnapshot catch-up payload so a joining backup's book agrees
  // with the primary's at the snapshot version (mvaudit's diff then
  // holds across primary AND backup).
  std::vector<std::pair<int, int64_t>> ExportWatermarks() const;
  void ImportWatermarks(const std::vector<std::pair<int, int64_t>>& w);

  // Grace sweep: fire the audit_gap flight-recorder trigger for any
  // origin whose pending set outlived `-audit_grace_ms` (also run
  // opportunistically by NoteApply).  Called by the audit report build
  // so a gap with no follow-up traffic still surfaces.
  void CheckGaps(int32_t table_id);

  // {"origins":[{...}],"anomalies":[{...}]} — the server half of one
  // table's entry in the "audit" OpsQuery report.
  std::string Json() const;

  // Test / bench isolation.
  void Reset();

 private:
  void RecordAnomaly(Anomaly::Kind kind, int origin, int64_t lo,
                     int64_t hi) REQUIRES(mu_);
  void CheckGapsLocked(int32_t table_id, int64_t now_ms) REQUIRES(mu_);

  mutable Mutex mu_;
  std::map<int, OriginState> origins_ GUARDED_BY(mu_);
  std::vector<Anomaly> ring_ GUARDED_BY(mu_);  // bounded by -audit_ring
  size_t ring_next_ GUARDED_BY(mu_) = 0;
  long long ring_total_ GUARDED_BY(mu_) = 0;
};

// Client-side per-(table, shard) acked-add ledger.  Seq assignment and
// ack watermarks live together because both are keyed by the shard
// stream.  Thread-safe: table ops may run on any caller thread while
// the worker actor thread lands acks.
class AckLedger {
 public:
  // Allocate the seq range a new Add message to `shard` covers:
  // `span` logical adds (1 for a plain add; the collapsed window size
  // for a PR 5 aggregation flush).  Returns [lo, hi] inclusive.
  void NextRange(int shard, int64_t span, int64_t* lo, int64_t* hi);
  // A ReplyAdd ack echoing [lo, hi] landed from `shard`: advance the
  // acked watermark (per-connection FIFO: an ack covers every earlier
  // seq on the stream, so max-merge of hi is exact).
  void Ack(int shard, int64_t seq_hi);

  struct ShardState {
    int64_t sent = 0;   // last seq assigned (0 = none)
    int64_t acked = 0;  // acked watermark (all seqs <= acked applied)
  };
  std::vector<ShardState> Snapshot() const;
  std::string Json() const;  // {"shards":[{"shard","sent","acked"}]}
  void Reset();

 private:
  mutable Mutex mu_;
  std::vector<ShardState> shards_ GUARDED_BY(mu_);  // grown on demand
};

}  // namespace audit
}  // namespace mvtpu
