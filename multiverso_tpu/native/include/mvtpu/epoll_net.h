// EpollNet — the event-driven transport engine (docs/transport.md).
//
// One epoll loop (plus optional `-net_threads` shards) drives every
// socket non-blocking through per-connection read/write state machines:
//
//  - READ: frames reassemble incrementally (a peer may deliver one byte
//    per readiness event) into a reusable receive ARENA; a completed
//    frame is decoded ZERO-COPY — Message blobs are views into the
//    arena slab (Blob::View), and the slab is recycled once no view is
//    left alive.  A connection dropping mid-frame discards the partial.
//  - WRITE: sends enqueue scatter-gather frames (header scratch + blob
//    refs, no payload copy) on a bounded per-connection write queue
//    drained by the reactor under EPOLLOUT — a short write just waits
//    for the next readiness instead of tearing the connection down
//    (TcpNet's retry-by-reconnect).  A full queue backpressures the
//    sender (bounded by `-io_timeout_ms`).
//  - ACCEPT: rank peers identify themselves with a tiny Hello first
//    frame (sent by ConnectToRank pre-reactor; only a valid Hello
//    grants rank identity and the large rank frame bound).  Besides
//    them, the reactor accepts ANONYMOUS serve clients (connections
//    opening with anything other than a rank Hello).  Each is
//    assigned a pseudo-rank >= transport::kClientRankBase; replies
//    route back over the accepted socket, and a per-client admission
//    gate (`-client_inflight_max`) sheds Gets/probes with ReplyBusy on
//    top of the server-wide `-server_inflight_max`.
//
// Selected by `-net_engine=epoll` (the default for TCP fleets).
#pragma once

#include <atomic>
#include <cstdint>
#include <deque>
#include <memory>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "mvtpu/message.h"
#include "mvtpu/mutex.h"
#include "mvtpu/transport.h"

namespace mvtpu {

class EpollNet : public RankTransport {
 public:
  ~EpollNet() override { Stop(); }

  bool Init(const std::vector<std::string>& endpoints, int rank,
            InboundFn fn, int64_t connect_retry_ms = 15000) override;

  // Fault-injection + bounded-retry semantics match TcpNet::Send
  // (drop/delay/dup per logical message, fail_send per attempt,
  // net.retries/net.dropped/... counters); delivery itself is a queue
  // append + reactor wake, so the caller never blocks on the socket —
  // only on the write-queue backpressure bound.
  bool Send(int dst_rank, const Message& msg) override;

  void Stop() override;

  int rank() const override { return rank_; }
  int size() const override { return static_cast<int>(endpoints_.size()); }
  const char* engine() const override { return "epoll"; }
  FanInStats FanIn() const override;
  void SettleClient(int client_rank) override;
  // Capacity plane (docs/observability.md): bytes currently parked
  // across every connection's bounded write queue — the
  // `net.writeq_bytes` gauge of the "capacity" ops report.
  long long QueuedBytes() const override {
    return wq_bytes_total_.load(std::memory_order_relaxed);
  }
  // Receive-arena footprint: sum of every connection's live slab —
  // the `net.rx_arena_bytes` gauge (transport memory that was invisible
  // to mvtop --capacity / mvplan before it).
  long long RxArenaBytes() const override {
    return rx_arena_total_.load(std::memory_order_relaxed);
  }

 private:
  struct PendingFrame;
  struct Conn;
  struct Shard;

  void ReactorLoop(Shard* s);
  // Adopt pending connection registrations + write-queue arms.  Called
  // at the top of every reactor cycle AND whenever the wake eventfd is
  // drained mid-batch — consuming a wake without re-adopting would
  // strand the sender's hand-off for a full epoll_wait cycle (the
  // lost-wakeup tail spike the latency plane attributed to wire_back).
  void AdoptHandoffs(Shard* s);
  void HandleAccept(Shard* s);
  void HandleReadable(Shard* s, const std::shared_ptr<Conn>& c);
  // Drain the write queue as far as the socket accepts.  Returns false
  // on a hard write error (the caller closes the connection).
  bool DrainWrites(const std::shared_ptr<Conn>& c, bool* empty);
  void CloseConn(Shard* s, const std::shared_ptr<Conn>& c,
                 const char* why);
  // Decode + route one completed arena frame; false on a malformed
  // frame or a shed whose busy-reply could not be queued.
  bool FinishFrame(Shard* s, const std::shared_ptr<Conn>& c);

  bool SendAttempt(int dst_rank, const Message& msg);
  std::shared_ptr<Conn> ResolveConn(int dst_rank);
  std::shared_ptr<Conn> ConnectToRank(int dst_rank);
  // may_block=false for reactor-originated sends (synthesized busy
  // replies): the reactor drains the queues, so it must never wait on
  // one — a full queue drops the reply instead of deadlocking the
  // shard.
  bool Enqueue(const std::shared_ptr<Conn>& c, const Message& msg,
               bool may_block = true);
  void WakeShard(Shard* s);
  void ArmWrite(const std::shared_ptr<Conn>& c);

  std::vector<std::string> endpoints_;
  int rank_ = 0;
  InboundFn inbound_;
  int64_t connect_retry_ms_ = 15000;

  std::atomic<bool> running_{false};
  std::atomic<bool> stopping_{false};
  std::atomic<int> listen_fd_{-1};
  std::atomic<int> next_shard_{0};
  std::atomic<int> next_client_{0};

  // Fan-in counters (FanIn()).
  std::atomic<long long> accepted_total_{0};
  std::atomic<long long> active_clients_{0};
  std::atomic<long long> client_shed_{0};
  // Engine-wide write-queue depth in bytes (sum of per-conn wq_bytes,
  // maintained beside every wq mutation — QueuedBytes()).
  std::atomic<long long> wq_bytes_total_{0};
  // Engine-wide receive-arena bytes (sum of per-conn slab sizes,
  // maintained beside every slab allocation/close — RxArenaBytes()).
  std::atomic<long long> rx_arena_total_{0};

  std::vector<std::unique_ptr<Shard>> shards_;

  // Connection registry.  rank_conns_ holds the lazy outbound
  // connection per peer rank; client_conns_ maps pseudo-rank ->
  // accepted anonymous connection; all_conns_ is the teardown roster.
  Mutex conns_mu_;
  std::vector<std::shared_ptr<Conn>> rank_conns_ GUARDED_BY(conns_mu_);
  std::unordered_map<int, std::shared_ptr<Conn>> client_conns_
      GUARDED_BY(conns_mu_);
  std::vector<std::shared_ptr<Conn>> all_conns_ GUARDED_BY(conns_mu_);

  Mutex stop_mu_;  // serializes Stop vs Stop
};

}  // namespace mvtpu
