// UringNet — the io_uring completion-driven transport engine
// (docs/transport.md "io_uring data plane").
//
// Where EpollNet asks the kernel "which sockets are READY" and then
// issues the read/write itself, UringNet hands the kernel the whole
// operation up front and consumes COMPLETIONS: per shard one io_uring
// (SQ/CQ rings mmap'd, driven with raw syscalls — the container has no
// liburing) on which every recv, send, accept and timer is an in-flight
// SQE.  The message semantics are exactly EpollNet's — same Hello
// identify, same anonymous serve tier with pseudo-ranks and per-client
// admission, same fault/retry Send contract, same frame caps and
// 8-aligned arena packing — only the readiness model changed:
//
//  - RECEIVE: each shard registers a pool of `-uring_reg_bufs` fixed
//    buffers (IORING_REGISTER_BUFFERS) carved from HostArena slabs.
//    Frame bodies land via IORING_OP_READ_FIXED straight into a
//    registered slab and decode ZERO-COPY through Blob::Borrow — the
//    borrow's keepalive is the RegSlab handle, so the buffer index
//    returns to the pool only when the last consumer view dies (the
//    PR 9 two-hold recycle discipline, with the kernel as one of the
//    holders).  When the pool runs dry or a frame outgrows a slab the
//    conn falls back to plain IORING_OP_RECV into a heap slab decoded
//    with Blob::View — correctness never depends on registration.
//  - SEND: frames queue on the same bounded per-conn write queue; the
//    reactor submits one gather IORING_OP_SENDMSG at a time per conn
//    over the frame's scatter segments.  Payloads at/above
//    `-uring_zc_bytes` use IORING_OP_SENDMSG_ZC when the kernel has it:
//    the frame's buffers stay pinned (a zc_holds ref per in-flight
//    zero-copy send) until the kernel's F_NOTIF completion says the
//    pages are no longer referenced.
//  - ACCEPT: one multishot IORING_OP_ACCEPT services the listen socket
//    (downgrading to re-armed single-shot on old kernels); the wake
//    eventfd is watched by a multishot POLL_ADD; a periodic
//    IORING_OP_TIMEOUT gives the loop the 200 ms heartbeat the epoll
//    engine gets from its epoll_wait timeout (running_ checks +
//    watchdog cadence).
//
// Selected by `-net_engine=uring`.  zoo.cc calls uring::Probe() first
// and degrades to epoll with a logged reason (and an `effective_engine`
// health field) when the kernel cannot run this engine.
#pragma once

#include <atomic>
#include <cstdint>
#include <deque>
#include <memory>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "mvtpu/message.h"
#include "mvtpu/mutex.h"
#include "mvtpu/transport.h"

namespace mvtpu {

namespace uring {

// Can this kernel run the uring engine?  Checks io_uring_setup plus
// IORING_REGISTER_PROBE support for every opcode the data plane needs
// (READ_FIXED, RECV, SENDMSG, ACCEPT, POLL_ADD, TIMEOUT).  On false,
// `reason` (if non-null) says why — the zoo logs it and degrades to
// epoll.  MVTPU_URING_FORCE_UNSUPPORTED=1 in the environment forces a
// false (the fallback regression test's hook; an env var, not a flag,
// so the knob stays off the wire/flag-parity surface).
bool Probe(std::string* reason);

}  // namespace uring

class UringNet : public RankTransport {
 public:
  // Out of line: members hold unique_ptr<Shard> with Shard defined in
  // the .cc only.
  ~UringNet() override;

  bool Init(const std::vector<std::string>& endpoints, int rank,
            InboundFn fn, int64_t connect_retry_ms = 15000) override;

  // Fault-injection + bounded-retry semantics match EpollNet::Send
  // exactly (drop/delay/dup/fail_send, net.retries/net.dropped/...);
  // delivery is a queue append + eventfd wake — the caller blocks only
  // on the write-queue backpressure bound, never the socket.
  bool Send(int dst_rank, const Message& msg) override;

  void Stop() override;

  int rank() const override { return rank_; }
  int size() const override { return static_cast<int>(endpoints_.size()); }
  const char* engine() const override { return "uring"; }
  FanInStats FanIn() const override;
  void SettleClient(int client_rank) override;
  long long QueuedBytes() const override {
    return wq_bytes_total_.load(std::memory_order_relaxed);
  }
  // Receive-arena footprint (`net.rx_arena_bytes`): the registered
  // buffer pools (counted whole — the engine holds them for its
  // lifetime) plus every conn's live heap-fallback slab.
  long long RxArenaBytes() const override {
    return rx_arena_total_.load(std::memory_order_relaxed);
  }

 private:
  struct PendingFrame;
  struct RegPool;
  struct RegSlab;
  struct Conn;
  struct Shard;

  // ---- ring plumbing (all reactor-thread-only per shard)
  bool SetupRing(Shard* s, unsigned depth, bool sqpoll);
  void TeardownRing(Shard* s);
  void* GetSqe(Shard* s);  // io_uring_sqe*, null if SQ full past a flush
  int SubmitPending(Shard* s, bool wait);
  unsigned DrainCqes(Shard* s);
  void ProcessCqe(Shard* s, uint64_t user_data, int32_t res,
                  uint32_t flags);

  void ReactorLoop(Shard* s);
  void AdoptHandoffs(Shard* s);
  void ArmWake(Shard* s);
  void ArmAccept(Shard* s);
  void ArmTimeout(Shard* s);
  void ArmRecv(Shard* s, const std::shared_ptr<Conn>& c);
  // Submit (or re-submit after a partial) the head-of-queue frame.
  void PumpSend(Shard* s, const std::shared_ptr<Conn>& c);
  void OnAccepted(Shard* s, int fd);
  void OnRecv(Shard* s, const std::shared_ptr<Conn>& c, int32_t res);
  void OnSent(Shard* s, const std::shared_ptr<Conn>& c, int32_t res,
              uint32_t cqe_flags, uint32_t zc_seq, bool zc);
  // Choose where the announced frame assembles (registered slab vs
  // heap fallback) honoring the 8-aligned rewind/append/alloc rules.
  void PlaceFrame(Shard* s, const std::shared_ptr<Conn>& c, size_t need);
  bool FinishFrame(Shard* s, const std::shared_ptr<Conn>& c);
  // Two-phase teardown: Retire() stops new I/O and shuts the socket
  // down; the conn finalizes (close + erase) once its in-flight SQEs
  // have all completed (pending_ops == 0).
  void RetireConn(Shard* s, const std::shared_ptr<Conn>& c,
                  const char* why);
  void FinalizeConn(Shard* s, const std::shared_ptr<Conn>& c);

  bool SendAttempt(int dst_rank, const Message& msg);
  std::shared_ptr<Conn> ResolveConn(int dst_rank);
  std::shared_ptr<Conn> ConnectToRank(int dst_rank);
  bool Enqueue(const std::shared_ptr<Conn>& c, const Message& msg,
               bool may_block = true);
  void WakeShard(Shard* s);

  std::vector<std::string> endpoints_;
  int rank_ = 0;
  InboundFn inbound_;
  int64_t connect_retry_ms_ = 15000;

  std::atomic<bool> running_{false};
  std::atomic<bool> stopping_{false};
  std::atomic<int> listen_fd_{-1};
  std::atomic<int> next_shard_{0};
  std::atomic<int> next_client_{0};
  std::atomic<uint32_t> next_conn_id_{1};
  // SENDMSG_ZC support, probed at Init; cleared engine-wide the first
  // time the kernel answers EINVAL/EOPNOTSUPP (the frame resubmits as
  // a plain SENDMSG — degradation, never data loss).
  std::atomic<bool> zc_ok_{false};
  // `-uring_zc_bytes`: frames at/above this many remaining bytes send
  // zero-copy (negative disables).  Read once at Init.
  int64_t zc_bytes_ = 65536;

  std::atomic<long long> accepted_total_{0};
  std::atomic<long long> active_clients_{0};
  std::atomic<long long> client_shed_{0};
  std::atomic<long long> wq_bytes_total_{0};
  std::atomic<long long> rx_arena_total_{0};

  std::vector<std::unique_ptr<Shard>> shards_;

  Mutex conns_mu_;
  std::vector<std::shared_ptr<Conn>> rank_conns_ GUARDED_BY(conns_mu_);
  std::unordered_map<int, std::shared_ptr<Conn>> client_conns_
      GUARDED_BY(conns_mu_);
  std::vector<std::shared_ptr<Conn>> all_conns_ GUARDED_BY(conns_mu_);

  Mutex stop_mu_;  // serializes Stop vs Stop
};

// Factory for the `-net_engine=uring` arm of MakeRankTransport.
std::unique_ptr<RankTransport> MakeUringTransport();

}  // namespace mvtpu
