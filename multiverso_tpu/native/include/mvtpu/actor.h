// Actor — named mailbox + own thread + per-message-type handlers.
// Capability parity with include/multiverso/actor.h (SURVEY.md §2.3).
#pragma once

#include <functional>
#include <map>
#include <string>
#include <thread>

#include "mvtpu/message.h"
#include "mvtpu/mt_queue.h"

namespace mvtpu {

namespace actor {
inline constexpr const char* kWorker = "worker";
inline constexpr const char* kServer = "server";
inline constexpr const char* kCommunicator = "communicator";
inline constexpr const char* kController = "controller";
}  // namespace actor

class Actor {
 public:
  explicit Actor(std::string name) : name_(std::move(name)) {}
  virtual ~Actor();

  const std::string& name() const { return name_; }

  void Start();          // spawn the mailbox-drain thread
  void Stop();           // push Exit, join
  void Receive(MessagePtr msg) { mailbox_.Push(std::move(msg)); }
  // Mailbox backlog (messages queued behind the one being processed) —
  // the serve layer's inflight measure (-server_inflight_max).
  size_t QueueSize() const { return mailbox_.Size(); }

 protected:
  using Handler = std::function<void(MessagePtr&)>;
  void RegisterHandler(MsgType type, Handler h) { handlers_[type] = std::move(h); }

 private:
  void Main();

  std::string name_;
  MtQueue<MessagePtr> mailbox_;
  std::map<MsgType, Handler> handlers_;
  std::thread thread_;
  bool running_ = false;
};

}  // namespace mvtpu
