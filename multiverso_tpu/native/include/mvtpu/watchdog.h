// Stall watchdog — mechanical detection of a wedged critical loop
// (docs/observability.md "health plane").
//
// Every critical loop (epoll reactor shards, actor mailboxes, the
// heartbeat/lease scan, the Python metrics flusher via the C API)
// Bump()s a per-loop progress counter each iteration and declares its
// queued work with Busy().  A low-rate checker thread flags any loop
// that made ZERO progress for -watchdog_stall_ms while work was
// queued: it records `watchdog.stalls`, lands a
// "stall: <loop> no progress for Nms, queue=D" blackbox event plus the
// sampling profiler's folded stacks (so the dump names WHERE the loop
// is stuck, not just THAT it is stuck), and fires a blackbox trigger.
// This is the class of bug the reactor lost-wakeup was — an alive
// process whose event loop silently stopped draining — caught by a
// counter instead of a human.
//
// Idle is innocent: a loop with nothing queued never stalls, so a
// quiet fleet costs nothing and alerts nothing.  Disarmed (the
// default, -watchdog_stall_ms=0) every call is one relaxed atomic
// load.  -watchdog_stall_ms must exceed the slowest legitimate loop
// period (the heartbeat scan ticks at -hb_interval_ms) or steady-state
// cadence reads as a stall.
#pragma once

#include <string>

namespace mvtpu {
namespace watchdog {

// Arm the checker at `stall_ms` (<= 0 disarms and joins the checker).
// The checker period is stall_ms/4 clamped to [10ms, 1s], so detection
// lands within stall_ms + one checker period.  Idempotent.
void Arm(int stall_ms);
bool Armed();

// One unit of progress on `loop` (registers the loop on first use).
void Bump(const std::string& loop);

// Declare `loop`'s queued work; 0 = idle (an idle loop cannot stall).
void Busy(const std::string& loop, long long queued);

// JSON array, one object per registered loop:
//   {"loop":..,"progress":n,"queued":n,"stalls":n,"stalled":bool,
//    "age_s":s,"stalled_s":s}
// — the "watchdog" section of the "alerts" OpsQuery report.
std::string StatsJson();

// Total stalls flagged since Arm/Reset (testing, ops).
long long StallCount();

// Test isolation: disarm and drop every registered loop.
void Reset();

}  // namespace watchdog
}  // namespace mvtpu
