// Leveled logger with optional file sink.
// Capability parity with include/multiverso/util/log.h (SURVEY.md §2.21).
#pragma once

#include <string>

namespace mvtpu {

enum class LogLevel : int { kDebug = 0, kInfo = 1, kError = 2, kFatal = 3 };

class Log {
 public:
  static void SetLevel(LogLevel level);
  static void ResetLogFile(const std::string& path);  // "" = stderr only
  static void Debug(const char* fmt, ...);
  static void Info(const char* fmt, ...);
  static void Error(const char* fmt, ...);
  // Logs and aborts (reference Fatal semantics).
  [[noreturn]] static void Fatal(const char* fmt, ...);
};

}  // namespace mvtpu
