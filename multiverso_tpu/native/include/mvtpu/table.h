// Table layer: worker-side stubs + server-side shards.
// Capability parity with include/multiverso/table_interface.h and
// include/multiverso/table/ (SURVEY.md §2.10–2.12): ArrayTable (dense 1-D)
// and MatrixTable (2-D, row-addressable) in float32. The worker stub turns
// Get/Add into request messages answered by the Server actor; a Waiter
// blocks the caller until the reply lands — the reference's §3.2/§3.3 hot
// path, in-process.
#pragma once

#include <cstdint>
#include <memory>
#include <mutex>
#include <unordered_map>
#include <vector>

#include "mvtpu/message.h"
#include "mvtpu/stream.h"
#include "mvtpu/updater.h"
#include "mvtpu/waiter.h"

namespace mvtpu {

// ---------------------------------------------------------------- server
class ServerTable {
 public:
  virtual ~ServerTable() = default;
  // Fill reply blobs for a get request.
  virtual void ProcessGet(const Message& req, Message* reply) = 0;
  virtual void ProcessAdd(const Message& req) = 0;
  virtual bool Store(Stream* out) const = 0;
  virtual bool Load(Stream* in) = 0;
};

class ArrayServerTable : public ServerTable {
 public:
  ArrayServerTable(int64_t size, UpdaterType updater);
  void ProcessGet(const Message& req, Message* reply) override;
  void ProcessAdd(const Message& req) override;
  bool Store(Stream* out) const override;
  bool Load(Stream* in) override;
  int64_t size() const { return static_cast<int64_t>(data_.size()); }

 private:
  std::vector<float> data_;
  std::vector<float> slot0_;
  UpdaterType updater_;
  std::mutex mu_;
};

class MatrixServerTable : public ServerTable {
 public:
  MatrixServerTable(int64_t rows, int64_t cols, UpdaterType updater);
  void ProcessGet(const Message& req, Message* reply) override;
  void ProcessAdd(const Message& req) override;
  bool Store(Stream* out) const override;
  bool Load(Stream* in) override;
  int64_t rows() const { return rows_; }
  int64_t cols() const { return cols_; }

 private:
  int64_t rows_, cols_;
  std::vector<float> data_;   // rows*cols, row-major
  std::vector<float> slot0_;
  UpdaterType updater_;
  std::mutex mu_;
};

// ---------------------------------------------------------------- worker
// Blocking stub; one instance per table per process.
class WorkerTable {
 public:
  explicit WorkerTable(int32_t table_id) : table_id_(table_id) {}
  virtual ~WorkerTable() = default;
  int32_t table_id() const { return table_id_; }

  // Called by the Worker actor when a reply for msg_id arrives.
  void Notify(int64_t msg_id, const Message& reply);

 protected:
  // Send req via the Zoo, block until the reply is consumed by `consume`.
  void RoundTrip(MessagePtr req,
                 void (*consume)(void*, const Message&), void* arg);

  int32_t table_id_;

 private:
  std::mutex mu_;
  struct Pending {
    Waiter* waiter;
    void (*consume)(void*, const Message&);
    void* arg;
  };
  std::unordered_map<int64_t, Pending> pending_;
};

class ArrayWorkerTable : public WorkerTable {
 public:
  using WorkerTable::WorkerTable;
  void Get(float* data, int64_t size);
  void Add(const float* delta, int64_t size, const AddOption& opt,
           bool blocking);
};

class MatrixWorkerTable : public WorkerTable {
 public:
  MatrixWorkerTable(int32_t table_id, int64_t rows, int64_t cols)
      : WorkerTable(table_id), rows_(rows), cols_(cols) {}
  void GetAll(float* data);                       // [rows*cols]
  void GetRows(const int32_t* row_ids, int64_t k, float* data);  // [k*cols]
  void AddAll(const float* delta, const AddOption& opt, bool blocking);
  void AddRows(const int32_t* row_ids, int64_t k, const float* delta,
               const AddOption& opt, bool blocking);

 private:
  int64_t rows_, cols_;
};

}  // namespace mvtpu
