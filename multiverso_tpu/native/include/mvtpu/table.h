// Table layer: worker-side stubs + server-side shards.
// Capability parity with include/multiverso/table_interface.h and
// include/multiverso/table/ (SURVEY.md §2.10–2.12): ArrayTable (dense 1-D)
// and MatrixTable (2-D, row-addressable) in float32.  The worker stub
// turns Get/Add into request messages answered by Server actors; a Waiter
// blocks the caller until every contacted shard replied — the reference's
// §3.2/§3.3 hot path.  Sharding matches the reference: server rank r owns
// a contiguous array chunk / matrix row block computed by ShardRange, the
// worker partitions each request across owners (WorkerTable::Partition
// semantics) and reassembles replies by the reply's src rank.
#pragma once

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

#include "mvtpu/audit.h"
#include "mvtpu/capacity.h"
#include "mvtpu/codec.h"
#include "mvtpu/message.h"
#include "mvtpu/mutex.h"
#include "mvtpu/sketch.h"
#include "mvtpu/stream.h"
#include "mvtpu/updater.h"
#include "mvtpu/waiter.h"

namespace mvtpu {

// Contiguous balanced partition of n elements over `size` shards; the
// same formula on worker and server sides is the partition contract.
struct ShardRange {
  int64_t begin = 0;
  int64_t end = 0;
  int64_t len() const { return end - begin; }
};

inline ShardRange ShardOf(int64_t n, int rank, int size) {
  int64_t base = n / size;
  int64_t rem = n % size;
  int64_t b = rank * base + std::min<int64_t>(rank, rem);
  return {b, b + base + (rank < rem ? 1 : 0)};
}

inline int OwnerOf(int64_t index, int64_t n, int size) {
  // Inverse of ShardOf: first `rem` shards have base+1 elements.
  int64_t base = n / size;
  int64_t rem = n % size;
  int64_t big = (base + 1) * rem;  // elements held by the larger shards
  if (base == 0) return static_cast<int>(index);  // n < size degenerate
  if (index < big) return static_cast<int>(index / (base + 1));
  return static_cast<int>(rem + (index - big) / base);
}

// ---- host-bridge borrow window (docs/host_bridge.md) -----------------
// RAII thread-local borrow scope for the *Borrowed C API: while a scope
// is active on this thread, raw float payloads whose bytes fall inside
// [base, base+len) ship as Blob::Borrow sharing `hold` (the HostArena
// keepalive) instead of being copied into owning blobs.  Encode paths
// (1bit/sparse) and the aggregation buffer ignore the scope — they must
// mutate or outlive the payload, so they take ownership by copying
// (copy-on-conflict).  Scopes do not nest.
class BorrowScope {
 public:
  BorrowScope(const void* base, size_t len, std::shared_ptr<void> hold);
  ~BorrowScope();
  BorrowScope(const BorrowScope&) = delete;
  BorrowScope& operator=(const BorrowScope&) = delete;
};

// Payload blob for [p, p+bytes): borrowed when the active scope covers
// the window, an owning copy otherwise — THE one spelling every raw
// send-path payload goes through.
Blob WrapPayload(const void* p, size_t bytes);

// ---------------------------------------------------------------- server
class ServerTable {
 public:
  ServerTable() {
    for (auto& b : bucket_versions_) b.store(0, std::memory_order_relaxed);
    for (auto& b : bucket_gets_) b.store(0, std::memory_order_relaxed);
    for (auto& b : bucket_adds_) b.store(0, std::memory_order_relaxed);
    for (auto& b : bucket_bytes_) b.store(0, std::memory_order_relaxed);
  }
  virtual ~ServerTable() = default;
  // Fill reply blobs for a get request.
  virtual void ProcessGet(const Message& req, Message* reply) = 0;
  virtual void ProcessAdd(const Message& req) = 0;
  // Store/Load operate on the LOCAL shard (multi-process callers keep
  // one file per rank, the reference's per-server dump model).
  virtual bool Store(Stream* out) const = 0;
  virtual bool Load(Stream* in) = 0;

  // ---- serve-layer versions (docs/serving.md) ------------------------
  // Every ProcessAdd bumps a per-shard monotonic counter; row/key adds
  // additionally stamp the touched BUCKETS, so a read of untouched
  // buckets can report an older (still-valid) version and client caches
  // miss less.  Replies stamp the version covering the data they serve.
  static constexpr int kVersionBuckets = 64;
  int64_t version() const {
    return version_.load(std::memory_order_acquire);
  }
  int64_t bucket_version(int b) const {
    if (b < 0 || b >= kVersionBuckets) return version();
    return bucket_versions_[b].load(std::memory_order_acquire);
  }

  // ---- workload observability (docs/observability.md) ----------------
  // Data-plane accounting beside the version plumbing: per-bucket
  // get/add load counters (skew = max bucket / mean bucket), a top-K /
  // count-min hot-key tracker, an observed-staleness histogram, and
  // update-health sentinels (add L2/Linf accumulators, NaN/Inf counts
  // with a flight-recorder trigger on the first NaN).  Every hook
  // no-ops on one relaxed atomic load when `-hotkey_enabled=false`.
  void set_table_id(int32_t id) { obs_table_id_ = id; }
  int32_t observed_table_id() const { return obs_table_id_; }

  struct LoadStats {
    int64_t gets = 0;        // ProcessGet calls served
    int64_t adds = 0;        // ProcessAdd calls applied
    double skew_ratio = 0;   // max bucket load / mean bucket load
    int64_t bucket_load_max = 0;
    double bucket_load_mean = 0;
    double add_l2 = 0;       // sqrt of accumulated delta L2^2
    double add_linf = 0;     // max |delta element| ever applied
    long long nan_count = 0;
    long long inf_count = 0;
    long long staleness_count = 0;  // stamped reads observed
    double staleness_mean = 0;      // mean version distance at serve time
  };
  LoadStats Load() const;
  std::string HotKeysJson() const { return tracker_.Json(); }

  // ---- capacity accounting (docs/observability.md "capacity plane") --
  // Resident bytes/rows of THIS shard, per bucket and in total —
  // migration's placement unit, measured.  Construction and snapshot
  // Load recompute exactly (RecomputeCapacity: a full walk under the
  // shard lock); growth on the hot path (KV key inserts — matrix/array
  // shards are fixed-size) bumps the counters incrementally behind one
  // relaxed capacity::Armed() load.  Re-arming via
  // MV_SetCapacityTracking resyncs every table, so counters disarmed
  // adds left stale heal the moment tracking turns back on.
  struct CapacityUsage {
    int64_t bytes = 0;  // resident payload + per-entry overhead
    int64_t rows = 0;   // matrix rows / KV entries / array elements
  };
  CapacityUsage Capacity() const {
    CapacityUsage u;
    u.bytes = resident_bytes_.load(std::memory_order_relaxed);
    u.rows = resident_rows_.load(std::memory_order_relaxed);
    return u;
  }
  std::vector<int64_t> BucketBytes() const {
    std::vector<int64_t> out(kVersionBuckets, 0);
    for (int b = 0; b < kVersionBuckets; ++b)
      out[b] = bucket_bytes_[b].load(std::memory_order_relaxed);
    return out;
  }
  // Per-bucket get/add load counters (the rate-curve substrate the
  // capacity history ring snapshots); both arrays kVersionBuckets long.
  void BucketLoads(int64_t* gets, int64_t* adds) const {
    for (int b = 0; b < kVersionBuckets; ++b) {
      if (gets) gets[b] = bucket_gets_[b].load(std::memory_order_relaxed);
      if (adds) adds[b] = bucket_adds_[b].load(std::memory_order_relaxed);
    }
  }
  int64_t total_gets() const {
    return total_gets_.load(std::memory_order_relaxed);
  }
  int64_t total_adds() const {
    return total_adds_.load(std::memory_order_relaxed);
  }
  // Exact full walk under the shard lock; called at construction,
  // after a successful snapshot Load, and on re-arm.
  virtual void RecomputeCapacity() {}

 protected:
  // Zero + set the whole-shard counters (the Recompute entry).
  void ResetCapacity(int64_t bytes, int64_t rows) {
    resident_bytes_.store(bytes, std::memory_order_relaxed);
    resident_rows_.store(rows, std::memory_order_relaxed);
    for (auto& b : bucket_bytes_) b.store(0, std::memory_order_relaxed);
  }
  void ChargeBucketBytes(int bucket, int64_t bytes) {
    if (bucket >= 0)
      bucket_bytes_[bucket % kVersionBuckets].fetch_add(
          bytes, std::memory_order_relaxed);
  }
  // Hot-path increment for one NEW resident entry (KV insert): one
  // relaxed load disarmed, three relaxed bumps armed.  rows=0 for
  // side-slot growth that adds bytes but no logical entry.
  void NoteEntryBytes(int bucket, int64_t bytes, int64_t rows = 1) {
    if (!capacity::Armed()) return;
    resident_bytes_.fetch_add(bytes, std::memory_order_relaxed);
    if (rows) resident_rows_.fetch_add(rows, std::memory_order_relaxed);
    ChargeBucketBytes(bucket, bytes);
  }

 public:
  std::vector<workload::HotKeyTracker::Item> HotTopK() const {
    return tracker_.TopK();
  }

  // ---- hot-key replica push (docs/embedding.md) ----------------------
  // Fill a ReplyReplica with this shard's current SpaceSaving top-K
  // rows: [int32 row ids][int64 bucket versions][float row data], rows
  // and versions snapshotted atomically against concurrent adds.  The
  // default is an empty push (table kinds with no row-replica form);
  // MatrixServerTable overrides.  Answered by the server actor for
  // MsgType::RequestReplica — sheddable like a Get, never blocks adds.
  virtual void BuildReplica(Message* reply) { (void)reply; }
  int64_t replica_pushes() const {
    return replica_pushes_.load(std::memory_order_relaxed);
  }

  // ---- delivery audit (docs/observability.md "audit plane") ----------
  // Book one applied stamped add: the server actor calls this right
  // after ProcessAdd for every RequestAdd carrying an AuditStamp, so
  // the per-(table, origin) applied watermark tracks exactly what the
  // updaters saw.  No-op when the message is unstamped or -audit=false.
  void NoteAuditApply(const Message& req) {
    if (!req.has_audit() || !audit::Armed()) return;
    audit_book_.NoteApply(req.src, req.audit.seq_lo, req.audit.seq_hi,
                          obs_table_id_);
  }
  audit::DeliveryBook& audit_book() { return audit_book_; }
  const audit::DeliveryBook& audit_book() const { return audit_book_; }
  // Per-bucket content checksums (CRC32 over table state, bucket
  // mapping shared with the PR 4 version stamps): the replica-
  // divergence primitive — two shards holding the same rows report
  // identical values, independent of iteration order (XOR of per-entry
  // CRCs seeded by the entry's identity).  The base reports a single
  // whole-shard checksum; bucket-granular kinds override.
  virtual std::vector<uint32_t> BucketChecksums() const { return {}; }

 protected:
  void NoteReplicaPush() {
    replica_pushes_.fetch_add(1, std::memory_order_relaxed);
  }

 public:

 protected:
  // One call per ProcessGet/ProcessAdd; bucket < 0 = whole-table op
  // (counts toward totals only — charging all 64 buckets would fake a
  // flat profile over the skew the per-key ops reveal).
  void NoteGet(int bucket) {
    if (!workload::Armed()) return;
    total_gets_.fetch_add(1, std::memory_order_relaxed);
    if (bucket >= 0)
      bucket_gets_[bucket % kVersionBuckets].fetch_add(
          1, std::memory_order_relaxed);
  }
  void NoteAdd(int bucket) {
    if (!workload::Armed()) return;
    total_adds_.fetch_add(1, std::memory_order_relaxed);
    if (bucket >= 0)
      bucket_adds_[bucket % kVersionBuckets].fetch_add(
          1, std::memory_order_relaxed);
  }
  // One touched key (matrix row / KV key): sketch offer + bucket load.
  void NoteKey(uint64_t hash, const std::string& label, int bucket,
               bool is_add) {
    if (!workload::Armed()) return;
    tracker_.Note(hash, label);
    auto& loads = is_add ? bucket_adds_ : bucket_gets_;
    if (bucket >= 0)
      loads[bucket % kVersionBuckets].fetch_add(
          1, std::memory_order_relaxed);
  }
  // Observed staleness at serve time: server version minus the version
  // the requester stamped into the Get (its last-seen stamp).  Recorded
  // into the per-table Dashboard histogram `workload.staleness.t<id>`
  // (1 unit = 1 version, via the µs-bucket ladder) — the measured
  // distribution to hold against `-max_staleness`.
  void NoteStaleness(int64_t request_version);
  // Update-health scan over a decoded add payload: L2^2 / Linf
  // accumulators + NaN/Inf counts; the FIRST NaN trips a flight-
  // recorder dump naming this table (a diverging model is a failure
  // whose post-mortem needs the recent ring, not a silent poisoning).
  void NoteAddHealth(const float* delta, size_t n);

 public:
  // Replication catch-up (docs/replication.md): adopt a primary's
  // snapshot version (max-merge, every bucket) so a freshly installed
  // backup's reply stamps never run BEHIND versions clients already
  // observed from the old primary.
  void AdvanceVersionTo(int64_t v) {
    int64_t cur = version_.load(std::memory_order_acquire);
    while (cur < v &&
           !version_.compare_exchange_weak(cur, v,
                                           std::memory_order_acq_rel)) {
    }
    for (auto& b : bucket_versions_) {
      int64_t bv = b.load(std::memory_order_acquire);
      while (bv < v &&
             !b.compare_exchange_weak(bv, v, std::memory_order_acq_rel)) {
      }
    }
  }

 protected:
  // bucket < 0 stamps EVERY bucket (whole-table adds).
  void BumpVersion(int64_t bucket = -1) {
    int64_t v = version_.fetch_add(1, std::memory_order_acq_rel) + 1;
    if (bucket < 0) {
      for (auto& b : bucket_versions_) b.store(v, std::memory_order_release);
    } else {
      bucket_versions_[bucket % kVersionBuckets].store(
          v, std::memory_order_release);
    }
  }
  static int RowBucket(int64_t row) {
    return static_cast<int>(((row % kVersionBuckets) + kVersionBuckets) %
                            kVersionBuckets);
  }

 private:
  std::atomic<int64_t> version_{0};
  std::atomic<int64_t> bucket_versions_[kVersionBuckets];

  // ---- workload accounting state (docs/observability.md) -------------
  int32_t obs_table_id_ = -1;
  std::atomic<int64_t> bucket_gets_[kVersionBuckets];
  std::atomic<int64_t> bucket_adds_[kVersionBuckets];
  std::atomic<int64_t> total_gets_{0};
  std::atomic<int64_t> total_adds_{0};
  workload::HotKeyTracker tracker_;
  std::atomic<int64_t> replica_pushes_{0};
  audit::DeliveryBook audit_book_;

  // ---- capacity accounting state (docs/observability.md) -------------
  std::atomic<int64_t> resident_bytes_{0};
  std::atomic<int64_t> resident_rows_{0};
  std::atomic<int64_t> bucket_bytes_[kVersionBuckets];
  mutable Mutex health_mu_;
  double add_l2sq_ GUARDED_BY(health_mu_) = 0.0;
  double add_linf_ GUARDED_BY(health_mu_) = 0.0;
  long long nan_count_ GUARDED_BY(health_mu_) = 0;
  long long inf_count_ GUARDED_BY(health_mu_) = 0;
  std::atomic<bool> nan_triggered_{false};
};

class ArrayServerTable : public ServerTable {
 public:
  ArrayServerTable(int64_t global_size, UpdaterType updater, int rank = 0,
                   int size = 1);
  void ProcessGet(const Message& req, Message* reply) override;
  void ProcessAdd(const Message& req) override;
  bool Store(Stream* out) const override;
  bool Load(Stream* in) override;
  std::vector<uint32_t> BucketChecksums() const override;
  void RecomputeCapacity() override;
  int64_t size() const {
    MutexLock lk(mu_);
    return static_cast<int64_t>(data_.size());
  }

 private:
  ShardRange range_;
  mutable Mutex mu_;
  std::vector<float> data_ GUARDED_BY(mu_);    // the local shard
  std::vector<float> slot0_ GUARDED_BY(mu_);
  UpdaterType updater_;
};

class MatrixServerTable : public ServerTable {
 public:
  MatrixServerTable(int64_t rows, int64_t cols, UpdaterType updater,
                    int rank = 0, int size = 1);
  void ProcessGet(const Message& req, Message* reply) override;
  void ProcessAdd(const Message& req) override;
  // Hot-key replica push (docs/embedding.md): this shard's current
  // top-K rows with their bucket versions, snapshotted under mu_ so a
  // concurrent add can neither tear a row nor out-date a stamp.
  void BuildReplica(Message* reply) override;
  bool Store(Stream* out) const override;
  bool Load(Stream* in) override;
  std::vector<uint32_t> BucketChecksums() const override;
  void RecomputeCapacity() override;
  int64_t rows() const { return range_.len(); }
  int64_t cols() const { return cols_; }

 private:
  int64_t global_rows_, cols_;
  ShardRange range_;           // the row block this rank owns
  mutable Mutex mu_;
  std::vector<float> data_ GUARDED_BY(mu_);  // range_.len()*cols, row-major
  std::vector<float> slot0_ GUARDED_BY(mu_);
  UpdaterType updater_;
};

// ---------------------------------------------------------------- worker
class WorkerTable;

// Handle for an in-flight async Get (reference WorkerTable::GetAsync +
// Waiter-handle Wait, SURVEY.md §2.10): the request is on the wire when
// the starting call returns, so the caller overlaps the round trip with
// compute — the AsyncBuffer double-buffer idiom (§2.24) expressed over
// the wire.  The caller's output buffer must stay alive and untouched
// until Wait() returns.  Wait() is RoundTrip's back half: true when
// every contacted shard replied, false on dead-shard ReplyError or
// `-rpc_timeout_ms` expiry — with the same INDETERMINATE contract (the
// buffer may be partially filled).  Idempotent.  Destroying an
// un-Wait()ed handle withdraws the request safely: late replies are
// dropped at the door, never touching the dead waiter or the buffer.
// The owning table must outlive the handle.
class AsyncGetHandle {
 public:
  ~AsyncGetHandle();
  bool Wait();

 private:
  friend class WorkerTable;
  AsyncGetHandle(WorkerTable* t, int64_t msg_id, int nreq,
                 std::shared_ptr<void> state)
      : table_(t), msg_id_(msg_id),
        waiter_(std::make_shared<Waiter>(nreq)), state_(std::move(state)) {}
  WorkerTable* table_;
  int64_t msg_id_;          // -1: empty request, trivially complete
  std::shared_ptr<Waiter> waiter_;  // shared with pending_ (see Notify)
  bool failed_ GUARDED_BY(table_->mu_) = false;  // written by Notify
  bool busy_ GUARDED_BY(table_->mu_) = false;    // ReplyBusy shed
  // Owner-thread state (only the thread driving Wait()/~ touches these;
  // no lock, so they carry no capability annotation).
  bool waited_ = false;
  bool ok_ = false;
  std::shared_ptr<void> state_;  // owns the consume plan (scatter map)
};
using AsyncGetPtr = std::unique_ptr<AsyncGetHandle>;

// Blocking stub; one instance per table per process.
class WorkerTable {
 public:
  explicit WorkerTable(int32_t table_id) : table_id_(table_id) {}
  virtual ~WorkerTable() = default;
  int32_t table_id() const { return table_id_; }

  // Called by the Worker actor when a reply for msg_id arrives.
  void Notify(int64_t msg_id, const Message& reply);

  // Clock boundary hook (Zoo::Barrier success): worker-side caches drop
  // entries here — peers' adds from the closed clock are now visible.
  virtual void OnClockInvalidate() {}

  // ---- serve layer (docs/serving.md) ---------------------------------
  // Highest server-side version stamp observed in ANY reply to this
  // worker stub — a free (no wire) lower bound on the server version,
  // refreshed by every Get/Add ack.
  int64_t last_version() const {
    return last_version_.load(std::memory_order_acquire);
  }
  // Cheap wire probe: fills *version with the max CURRENT version over
  // every server shard (`bucket >= 0` asks one bucket of a KV/matrix
  // table).  One tiny header-only round trip instead of a full fetch.
  // False on dead shard / deadline / busy-shed (see last_call_busy).
  bool QueryVersion(int64_t* version, int bucket = -1);
  // True when THIS THREAD's most recent blocking round trip (Get/Add/
  // QueryVersion/Wait) failed because a server SHED it under
  // `-server_inflight_max` backpressure (ReplyBusy) rather than dying
  // or timing out — the retryable case (C API rc -6 vs -3).
  static bool last_call_busy();

  // ---- wire codec (docs/wire_compression.md) -------------------------
  // Negotiated at table creation from `-wire_codec` (overridable per
  // table via MV_SetTableCodec) and stamped per message: dense Add
  // payloads ship 1-bit (sign + two scales, worker-side error feedback)
  // or sparse (nonzero index/value pairs, lossless, with per-message
  // raw fallback when not smaller); Get requests advertise the accept
  // set so large mostly-zero replies can come back sparse.
  void set_codec(Codec c) {
    codec_.store(static_cast<int32_t>(c), std::memory_order_release);
  }
  Codec wire_codec() const {
    return static_cast<Codec>(codec_.load(std::memory_order_acquire));
  }
  // msgflag:: bits for requests: raw always; non-raw tables also accept
  // the lossless sparse reply form (1-bit replies never happen — error
  // feedback needs a per-receiver residual the server does not hold).
  int32_t accept_flags() const {
    Codec c = wire_codec();
    int32_t f = msgflag::kAcceptRaw;
    if (c != Codec::kRaw) f |= msgflag::kAcceptSparse;
    if (c == Codec::kOneBit) f |= msgflag::kAccept1Bit;
    return f;
  }

  // ---- delivery audit (docs/observability.md "audit plane") ----------
  // Stamp an outbound RequestAdd headed for server shard `shard` with
  // the next seq range of that shard's stream (msgflag::kHasAudit).
  // Inside a FlushAdds window the range covers every collapsed logical
  // add (the PR 5 agg accounting); otherwise one.  No-op disarmed.
  void StampAuditAdd(Message* req, int shard);
  // The acked-add ledger: per shard, last seq sent and last seq acked
  // (advanced by ReplyAdd acks in Notify — per-connection FIFO makes
  // an ack cover every earlier seq on the stream).
  audit::AckLedger& ack_ledger() { return ack_ledger_; }
  std::string AuditLedgerJson() const { return ack_ledger_.Json(); }

  // ---- add aggregation (docs/wire_compression.md) --------------------
  // With `-add_agg_ms`/`-add_agg_bytes` armed, ASYNC dense adds are
  // summed into a local per-table buffer and shipped as ONE
  // codec-encoded wire message per flush window.  Flush triggers: the
  // size/time bound, any Get/QueryVersion, any blocking or
  // differently-shaped add, Clock (the tick must ride BEHIND the adds
  // it announces), Barrier (via FlushPipelines) and shutdown — so
  // BSP/SSP visibility semantics are unchanged.  The time window is
  // checked lazily at the next table op (no flusher thread).
  void FlushAdds();

 protected:
  // Absorb an async dense add of n elements into the aggregation
  // buffer.  True = absorbed (nothing on the wire yet); false = the
  // aggregation feature is off and the caller sends normally.  An
  // incompatible buffered aggregate (different length or AddOption) is
  // flushed first; a full/expired buffer is flushed right after.
  bool MaybeAggregate(const float* delta, int64_t n, const AddOption& opt);

 public:
  // Introspection (mvtpu/ops.h): async adds absorbed into the
  // aggregation buffer but not yet shipped — the "agg buffer depth" of
  // an ops table report.
  int64_t agg_pending() {
    MutexLock lk(agg_mu_);
    return agg_count_;
  }
  // Capacity plane (docs/observability.md): bytes currently held by
  // the add-aggregation buffer (one delta-shaped float sum).
  int64_t agg_bytes() {
    MutexLock lk(agg_mu_);
    return static_cast<int64_t>(agg_sum_.size() * sizeof(float));
  }

 protected:
  // Subclass hook: ship `sum` (n elements) as one async add.
  virtual void SendAggregate(const float* sum, int64_t n,
                             const AddOption& opt) {
    (void)sum;
    (void)n;
    (void)opt;
  }
  // Append the delta payload blob to `req`, encoded per this table's
  // codec, stamping req->codec.  `elem_offset` locates the slice inside
  // the table's flat element space (the 1-bit error-feedback residual
  // is per element and spans the whole table, `table_elems` long).
  void AppendEncodedDelta(Message* req, const float* delta, int64_t n,
                          int64_t elem_offset, int64_t table_elems);

 protected:
  // Send all reqs (same msg_id) via the Zoo, block until each got its
  // reply; `consume` runs once per reply (serialized — one worker-actor
  // thread drains replies).  Returns false when a shard was unreachable
  // (a synthesized ReplyError arrived) or the `-rpc_timeout_ms` deadline
  // passed — the caller fails fast instead of hanging on a dead peer.
  bool RoundTrip(std::vector<MessagePtr> reqs,
                 void (*consume)(void*, const Message&), void* arg);

  // RoundTrip's front half: register the pending entry, put every req
  // on the wire, return the handle whose Wait() is the back half.
  // `state` keeps `arg` (the consume destination plan) alive for the
  // handle's lifetime.
  AsyncGetPtr StartRoundTrip(std::vector<MessagePtr> reqs,
                             void (*consume)(void*, const Message&),
                             void* arg, std::shared_ptr<void> state);

  int32_t table_id_;

 private:
  friend class AsyncGetHandle;
  Mutex mu_;
  struct Pending {
    // shared_ptr, not a raw pointer to the caller's frame: the waiter
    // must stay a live heap object for as long as a reply could touch
    // it (and TSan only tracks mutex death through free()).
    std::shared_ptr<Waiter> waiter;
    void (*consume)(void*, const Message&);
    void* arg;
    int remaining;
    bool* failed;
    bool* busy = nullptr;  // set when a shard answered ReplyBusy
  };
  // mvlint: MV018-exempt(one entry per in-flight round trip, drained
  // by Notify/Wait — bounded by caller concurrency, never by traffic)
  std::unordered_map<int64_t, Pending> pending_ GUARDED_BY(mu_);
  std::atomic<int64_t> last_version_{0};
  audit::AckLedger ack_ledger_;

  // Wire codec (set at registration; MV_SetTableCodec may retarget).
  std::atomic<int32_t> codec_{static_cast<int32_t>(Codec::kRaw)};

  // 1-bit error-feedback residual: per element over the WHOLE table's
  // flat space, lazily sized on first encode.  Worker-side state (the
  // reference keeps it with the sender), never on the wire.
  Mutex residual_mu_;
  std::vector<float> residual_ GUARDED_BY(residual_mu_);

  // Add-aggregation buffer: one delta-shaped sum + the option it rides
  // under.  Bounded by construction (one payload) and drained by the
  // flush triggers documented at FlushAdds().
  Mutex agg_mu_;
  std::vector<float> agg_sum_ GUARDED_BY(agg_mu_);
  AddOption agg_opt_ GUARDED_BY(agg_mu_);
  int64_t agg_count_ GUARDED_BY(agg_mu_) = 0;
  int64_t agg_first_ms_ GUARDED_BY(agg_mu_) = 0;
};

class ArrayWorkerTable : public WorkerTable {
 public:
  ArrayWorkerTable(int32_t table_id, int64_t global_size, int num_servers)
      : WorkerTable(table_id), global_(global_size),
        servers_(num_servers) {}
  bool Get(float* data, int64_t size);
  // Non-blocking Get: data fills in the background; see AsyncGetHandle.
  AsyncGetPtr GetAsync(float* data, int64_t size);
  bool Add(const float* delta, int64_t size, const AddOption& opt,
           bool blocking);

 protected:
  void SendAggregate(const float* sum, int64_t n,
                     const AddOption& opt) override;

 private:
  // The one sharded-send plan for Add and the aggregation flush.
  bool SendAdd(const float* delta, int64_t size, const AddOption& opt,
               bool blocking);
  int64_t global_;
  int servers_;
};

class MatrixWorkerTable : public WorkerTable {
 public:
  MatrixWorkerTable(int32_t table_id, int64_t rows, int64_t cols,
                    int num_servers = 1)
      : WorkerTable(table_id), rows_(rows), cols_(cols),
        servers_(num_servers) {}
  virtual bool GetAll(float* data);               // [rows*cols]
  virtual bool GetRows(const int32_t* row_ids, int64_t k,
                       float* data);              // [k*cols]
  // Non-blocking GetRows (see AsyncGetHandle).  row_ids are consumed
  // before this returns; `data` must live until Wait().  Deliberately
  // non-virtual: on a SparseMatrixWorkerTable this goes straight to the
  // wire — it neither reads nor installs into the row cache (an async
  // fill racing a clock invalidation could resurrect stale rows).
  AsyncGetPtr GetRowsAsync(const int32_t* row_ids, int64_t k, float* data);

  virtual bool AddAll(const float* delta, const AddOption& opt,
                      bool blocking);
  virtual bool AddRows(const int32_t* row_ids, int64_t k,
                       const float* delta, const AddOption& opt,
                       bool blocking);

  // ---- hot-key read replica (docs/embedding.md) ----------------------
  // With `-hotkey_replica` armed, GetRows consults a worker-local side
  // table of the servers' pushed top-K rows BEFORE the wire: a row is a
  // hit when the snapshot is inside `-replica_lease_ms` AND its pushed
  // bucket version satisfies last_version() - `-replica_max_staleness`
  // (version gating IS the invalidation: this worker's own add acks
  // advance last_version, staling every older entry at staleness 0).
  // Refresh = one RequestReplica round trip per shard ("push-on-pull":
  // the SERVER chooses what to replicate — its SpaceSaving top-K).
  bool RefreshReplica();
  void OnReplicaPush(const Message& reply);  // install one shard's push
  struct ReplicaStats {
    long long hits = 0;       // rows served from the replica
    long long misses = 0;     // rows that had to go to the wire
    long long rows = 0;       // rows currently held
    long long refreshes = 0;  // RequestReplica round trips
  };
  ReplicaStats replica_stats() const;
  void OnClockInvalidate() override;  // clock boundary: replica is void
  // Capacity plane (docs/observability.md): resident bytes of the
  // replica side table (rows x cols floats + per-entry overhead) —
  // reported as its OWN field so fleet capacity math never counts a
  // replicated row into the table's shard bytes.
  int64_t replica_bytes() const;

 protected:
  void SendAggregate(const float* sum, int64_t n,
                     const AddOption& opt) override;
  int64_t rows_, cols_;
  int servers_;

 private:
  // The one sharded-send plan for AddAll and the aggregation flush.
  bool SendAddAll(const float* delta, const AddOption& opt, bool blocking);
  // AddRows' send plan: the single-shard borrowed fast path, the
  // multi-shard borrowed run-iovec path (docs/embedding.md), the
  // sparse-codec staging path, and the plain staging fallback.
  bool SendAddRows(const int32_t* row_ids, int64_t k, const float* delta,
                   const AddOption& opt, bool blocking);
  // THE one owner-partitioning plan for GetRows/GetRowsAsync: fills
  // `positions` (caller slots per shard), zero-fills the output (the
  // out-of-range-id contract), returns the per-shard requests.  Both
  // paths must stay in lockstep — a divergence here silently breaks
  // one of them.
  std::vector<MessagePtr> PlanRowsGet(
      const int32_t* row_ids, int64_t k, float* data,
      std::vector<std::vector<int64_t>>* positions);
  // GetRows' wire body (the pre-replica fetch path); GetRows itself now
  // serves replica hits first and routes only the remainder here.
  bool FetchRowsWire(const int32_t* row_ids, int64_t k, float* data);
  // Refresh the replica when the snapshot aged past -replica_lease_ms.
  void MaybeRefreshReplica();
  // Drop replica entries for rows this worker just added (belt to the
  // version gate's braces — the ack that would stale them may race a
  // concurrent read).
  void InvalidateReplicaRows(const int32_t* row_ids, int64_t k);

  struct ReplicaRow {
    int64_t version = 0;        // pushed bucket version at snapshot
    std::vector<float> data;    // cols_ floats
  };
  mutable Mutex replica_mu_;
  // capacity: replica_bytes() gauge — the "capacity" report's
  // worker.replica_bytes field (rows bounded at 4x topk x shards)
  std::unordered_map<int32_t, ReplicaRow> replica_ GUARDED_BY(replica_mu_);
  int64_t replica_ts_ms_ GUARDED_BY(replica_mu_) = -1;  // -1: never
  std::atomic<long long> replica_hits_{0};
  std::atomic<long long> replica_misses_{0};
  std::atomic<long long> replica_refreshes_{0};
};

// Sparse variant (SURVEY.md §2.13, table/sparse_matrix_table.h): the
// worker keeps a row cache — repeated GetRows of hot rows (LightLDA's
// access pattern) skip the wire until the row is invalidated by this
// worker's own Add or by a clock boundary (Zoo::Barrier), when peers'
// adds become visible.  Mirrors tables/sparse_matrix_table.py: a dense
// [rows, cols] mirror + validity bitmap, lazily allocated.
class SparseMatrixWorkerTable : public MatrixWorkerTable {
 public:
  using MatrixWorkerTable::MatrixWorkerTable;
  bool GetRows(const int32_t* row_ids, int64_t k, float* data) override;
  bool AddAll(const float* delta, const AddOption& opt,
              bool blocking) override;
  bool AddRows(const int32_t* row_ids, int64_t k, const float* delta,
               const AddOption& opt, bool blocking) override;
  void OnClockInvalidate() override;

 private:
  Mutex cache_mu_;
  std::vector<uint8_t> valid_ GUARDED_BY(cache_mu_);   // lazily rows_
  std::vector<float> mirror_ GUARDED_BY(cache_mu_);    // lazily rows_*cols_
  // Bumped by every invalidation (own add, clock).  GetRows releases
  // cache_mu_ for the wire fetch and installs the result only if the
  // epoch is unchanged — a fetch that raced an invalidation must not
  // resurrect pre-add values into the cache.
  uint64_t cache_epoch_ GUARDED_BY(cache_mu_) = 0;
};

// ------------------------------------------------------------------- KV
// Hash-map table, string key -> float value (SURVEY.md §2.14,
// table/kv_table.h: KVWorkerTable::{Get,Add,raw} / KVServerTable).
// Keys shard by a FIXED hash (FNV-1a — std::hash is implementation-
// defined and the partition contract must agree across processes).
// Wire: keys blob = concatenated (u32 len, bytes) entries;
//   Get  req: [keys]                 reply: [float vals, request order,
//                                            missing keys read 0]
//   Add  req: [AddOption][keys][float vals]
inline uint64_t KVHash(const char* s, size_t n) {
  uint64_t h = 1469598103934665603ull;          // FNV-1a 64
  for (size_t i = 0; i < n; ++i) {
    h ^= static_cast<unsigned char>(s[i]);
    h *= 1099511628211ull;
  }
  return h;
}

Blob PackKeys(const std::vector<std::string>& keys);
std::vector<std::string> UnpackKeys(const Blob& b);

class KVServerTable : public ServerTable {
 public:
  explicit KVServerTable(UpdaterType updater) : updater_(updater) {}
  void ProcessGet(const Message& req, Message* reply) override;
  void ProcessAdd(const Message& req) override;
  bool Store(Stream* out) const override;
  bool Load(Stream* in) override;
  std::vector<uint32_t> BucketChecksums() const override;
  void RecomputeCapacity() override;
  size_t size() const;

 private:
  void RecomputeCapacityLocked() REQUIRES(mu_);
  mutable Mutex mu_;
  std::unordered_map<std::string, float> data_ GUARDED_BY(mu_);
  std::unordered_map<std::string, float> slot0_ GUARDED_BY(mu_);  // slots
  UpdaterType updater_;
};

class KVWorkerTable : public WorkerTable {
 public:
  KVWorkerTable(int32_t table_id, int num_servers)
      : WorkerTable(table_id), servers_(num_servers) {}
  // vals[i] receives the value of keys[i] (0 when absent); refreshes
  // the local cache — the reference worker's `raw` dict.
  bool Get(const std::vector<std::string>& keys, float* vals);
  bool Add(const std::vector<std::string>& keys, const float* deltas,
           const AddOption& opt, bool blocking);
  // Worker-side cache of the last Get'd values (reference `raw()`).
  // By value, under the lock: the old by-reference accessor handed out
  // an unsynchronized view a concurrent Get could rehash under the
  // reader (the first hole `make analyze` flagged in this layer).
  std::unordered_map<std::string, float> raw() const {
    MutexLock lk(cache_mu_);
    return cache_;
  }
  // Capacity plane: resident bytes of the raw() mirror (keys + values
  // + the KV entry-overhead constant the server books use).
  int64_t cache_bytes() const {
    MutexLock lk(cache_mu_);
    int64_t bytes = 0;
    for (const auto& kv : cache_)
      bytes += static_cast<int64_t>(kv.first.size()) +
               static_cast<int64_t>(sizeof(float)) +
               capacity::kKVEntryOverhead;
    return bytes;
  }

 private:
  int servers_;
  mutable Mutex cache_mu_;
  // capacity: cache_bytes() rides the "capacity" report's worker object
  std::unordered_map<std::string, float> cache_ GUARDED_BY(cache_mu_);
};

}  // namespace mvtpu
