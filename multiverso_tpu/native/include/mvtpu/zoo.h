// Zoo — the runtime registry/singleton: owns the actors and the
// transport, routes messages, registers tables, answers barrier.
// Capability parity with include/multiverso/zoo.h (SURVEY.md §2.2, §3.1).
//
// Placement note (TPU-native design): the TPU data plane is XLA
// collectives over ICI/DCN (the Python/JAX layer); this native runtime is
// the HOST control/parity plane — a real actor pipeline with a real TCP
// transport (net.h).  With no machine file it runs the reference's
// Role::ALL single-process degenerate mode; with `-machine_file=F
// -rank=N` it becomes N cooperating processes: tables shard across the
// server roles (arrays by contiguous chunk, matrices by row block), the
// worker stubs partition requests per shard owner, and rank 0's
// controller answers the barrier — the reference's §3.1–§3.3 call stacks
// across OS processes.
#pragma once

#include <atomic>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "mvtpu/actor.h"
#include "mvtpu/net.h"
#include "mvtpu/table.h"

namespace mvtpu {

class Waiter;

class Zoo {
 public:
  static Zoo* Get();

  // argc/argv parsed through configure; spawns actors (+ transport when a
  // machine file names more than one process); idempotent.
  bool Start(int argc, const char* const* argv);
  void Stop();
  bool started() const { return started_; }

  int rank() const { return rank_; }
  int size() const { return size_; }
  int num_workers() const { return size_; }
  int worker_id() const { return rank_; }
  int server_id() const { return rank_; }

  // Blocks until every rank arrived; false when `-barrier_timeout_ms`
  // (default: infinite) expired or the barrier authority is unreachable.
  bool Barrier();

  // Deliver to a LOCAL actor's mailbox.
  void SendTo(const std::string& actor_name, MessagePtr msg);

  // Deliver to msg->dst's `actor_name` actor — local mailbox when dst is
  // this rank (or unset), the TCP transport otherwise (the Communicator
  // routing of SURVEY.md §2.6; inbound routing is RouteInbound).
  void Deliver(const std::string& actor_name, MessagePtr msg);

  int64_t NextMsgId() { return next_msg_id_.fetch_add(1); }

  // ---- table registry -------------------------------------------------
  int32_t RegisterArrayTable(int64_t size);
  int32_t RegisterMatrixTable(int64_t rows, int64_t cols);
  ServerTable* server_table(int32_t id);
  WorkerTable* worker_table(int32_t id);
  ArrayWorkerTable* array_worker(int32_t id);
  MatrixWorkerTable* matrix_worker(int32_t id);

  UpdaterType updater_type() const { return updater_type_; }

  // ---- barrier plumbing (internal) ------------------------------------
  void OnBarrierArrive(int src_rank);   // rank-0 controller counting
  void OnBarrierRelease();              // local waiter release

 private:
  Zoo() = default;

  void RouteInbound(Message&& m);       // transport reader threads

  bool started_ = false;
  std::mutex mu_;         // lifecycle (Start/Stop) + actor pointers
  std::mutex tables_mu_;  // table registry — actors query it mid-Stop, so
                          // it must never be held across a thread join
  std::atomic<int64_t> next_msg_id_{0};
  UpdaterType updater_type_ = UpdaterType::kDefault;

  int rank_ = 0;
  int size_ = 1;
  std::unique_ptr<TcpNet> net_;

  std::unique_ptr<Actor> worker_actor_;
  std::unique_ptr<Actor> server_actor_;
  std::unique_ptr<Actor> controller_actor_;

  std::vector<std::unique_ptr<ServerTable>> server_tables_;
  std::vector<std::unique_ptr<WorkerTable>> worker_tables_;

  // Barrier state: one outstanding barrier per rank; rank 0 tracks
  // arrivals PER RANK (a retry after an abandoned round must not double
  // count toward the quorum).  barrier_failed_ latches transport
  // failures so Barrier() reports them instead of a false release.
  std::mutex barrier_mu_;
  Waiter* barrier_waiter_ = nullptr;
  std::vector<bool> barrier_arrived_;
  bool barrier_failed_ = false;
};

}  // namespace mvtpu
