// Zoo — the runtime registry/singleton: owns the actors, routes messages,
// registers tables, answers barrier.
// Capability parity with include/multiverso/zoo.h (SURVEY.md §2.2, §3.1).
//
// Placement note (TPU-native design): the reference's Zoo also owns the
// MPI/ZMQ transport between processes. In this framework cross-host data
// movement is XLA collectives over ICI/DCN (the Python/JAX layer); the
// native Zoo is the HOST control plane — a real actor runtime running the
// worker/server/controller message path in-process (the reference's
// Role::ALL degenerate mode, which is also its test mode), serving the C
// API for FFI parity.
#pragma once

#include <atomic>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "mvtpu/actor.h"
#include "mvtpu/table.h"

namespace mvtpu {

class Zoo {
 public:
  static Zoo* Get();

  // argc/argv parsed through configure; spawns actors; idempotent.
  bool Start(int argc, const char* const* argv);
  void Stop();
  bool started() const { return started_; }

  int rank() const { return 0; }   // single-process control plane
  int size() const { return 1; }
  int num_workers() const { return 1; }
  int worker_id() const { return 0; }
  int server_id() const { return 0; }

  void Barrier();

  // Deliver to a local actor's mailbox (the communicator's routing).
  void SendTo(const std::string& actor_name, MessagePtr msg);

  int64_t NextMsgId() { return next_msg_id_.fetch_add(1); }

  // ---- table registry -------------------------------------------------
  int32_t RegisterArrayTable(int64_t size);
  int32_t RegisterMatrixTable(int64_t rows, int64_t cols);
  ServerTable* server_table(int32_t id);
  WorkerTable* worker_table(int32_t id);
  ArrayWorkerTable* array_worker(int32_t id);
  MatrixWorkerTable* matrix_worker(int32_t id);

  UpdaterType updater_type() const { return updater_type_; }

 private:
  Zoo() = default;

  bool started_ = false;
  std::mutex mu_;         // lifecycle (Start/Stop)
  std::mutex tables_mu_;  // table registry — actors query it mid-Stop, so
                          // it must never be held across a thread join
  std::atomic<int64_t> next_msg_id_{0};
  UpdaterType updater_type_ = UpdaterType::kDefault;

  std::unique_ptr<Actor> worker_actor_;
  std::unique_ptr<Actor> server_actor_;
  std::unique_ptr<Actor> controller_actor_;

  std::vector<std::unique_ptr<ServerTable>> server_tables_;
  std::vector<std::unique_ptr<WorkerTable>> worker_tables_;
};

}  // namespace mvtpu
